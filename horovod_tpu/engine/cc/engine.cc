#include "engine.h"

#include <string.h>

#include <algorithm>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <limits>
#include <map>
#include <set>

#include "net.h"

namespace hvdtpu {

namespace {

// ---------------------------------------------------------------------------
// Dtype arithmetic helpers (reduction + half-precision staging).
// The reference delegated these to MPI_SUM / ncclSum; a TCP data plane has to
// do its own math.  f16/bf16 are staged through f32 (better numerics than
// reducing in half precision, and the MXU-friendly layout for any future
// on-device path).
// ---------------------------------------------------------------------------

template <typename T>
void AddInto(T* dst, const T* src, int64_t n) {
  for (int64_t i = 0; i < n; ++i) dst[i] += src[i];
}

void AccumulateSum(void* dst, const void* src, int64_t n, uint8_t dtype) {
  switch (dtype) {
    case HVD_UINT8:
      AddInto(static_cast<uint8_t*>(dst), static_cast<const uint8_t*>(src), n);
      break;
    case HVD_INT8:
      AddInto(static_cast<int8_t*>(dst), static_cast<const int8_t*>(src), n);
      break;
    case HVD_UINT16:
      AddInto(static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(src),
              n);
      break;
    case HVD_INT32:
      AddInto(static_cast<int32_t*>(dst), static_cast<const int32_t*>(src), n);
      break;
    case HVD_INT64:
      AddInto(static_cast<int64_t*>(dst), static_cast<const int64_t*>(src), n);
      break;
    case HVD_FLOAT32:
      AddInto(static_cast<float*>(dst), static_cast<const float*>(src), n);
      break;
    case HVD_FLOAT64:
      AddInto(static_cast<double*>(dst), static_cast<const double*>(src), n);
      break;
    case HVD_BOOL: {
      // Sum on bool saturates to logical OR (what MPI_SUM on C bool gives).
      uint8_t* d = static_cast<uint8_t*>(dst);
      const uint8_t* s = static_cast<const uint8_t*>(src);
      for (int64_t i = 0; i < n; ++i) d[i] = (d[i] || s[i]) ? 1 : 0;
      break;
    }
    default:
      break;  // f16/bf16 never reach the wire: staged through f32
  }
}

template <typename T>
void DivideBy(T* dst, int64_t n, double divisor) {
  for (int64_t i = 0; i < n; ++i)
    dst[i] = static_cast<T>(dst[i] / divisor);
}

void DivideBuffer(void* buf, int64_t n, uint8_t dtype, double divisor) {
  switch (dtype) {
    case HVD_FLOAT32:
      DivideBy(static_cast<float*>(buf), n, divisor);
      break;
    case HVD_FLOAT64:
      DivideBy(static_cast<double*>(buf), n, divisor);
      break;
    case HVD_INT32:
      DivideBy(static_cast<int32_t*>(buf), n, divisor);
      break;
    case HVD_INT64:
      DivideBy(static_cast<int64_t*>(buf), n, divisor);
      break;
    case HVD_UINT8:
      DivideBy(static_cast<uint8_t*>(buf), n, divisor);
      break;
    case HVD_INT8:
      DivideBy(static_cast<int8_t*>(buf), n, divisor);
      break;
    case HVD_UINT16:
      DivideBy(static_cast<uint16_t*>(buf), n, divisor);
      break;
    default:
      break;  // bool: averaging is meaningless; result is the OR
  }
}

template <typename T>
void ScaleBy(T* dst, int64_t n, double scale) {
  for (int64_t i = 0; i < n; ++i) dst[i] = static_cast<T>(dst[i] * scale);
}

float HalfToFloat(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t man = h & 0x3ffu;
  uint32_t bits;
  if (exp == 0) {
    if (man == 0) {
      bits = sign;
    } else {  // subnormal
      exp = 127 - 15 + 1;
      while (!(man & 0x400u)) {
        man <<= 1;
        --exp;
      }
      man &= 0x3ffu;
      bits = sign | (exp << 23) | (man << 13);
    }
  } else if (exp == 31) {
    bits = sign | 0x7f800000u | (man << 13);
  } else {
    bits = sign | ((exp + 127 - 15) << 23) | (man << 13);
  }
  float f;
  memcpy(&f, &bits, 4);
  return f;
}

uint16_t FloatToHalf(float f) {
  uint32_t bits;
  memcpy(&bits, &f, 4);
  uint16_t sign = static_cast<uint16_t>((bits >> 16) & 0x8000u);
  int32_t exp = static_cast<int32_t>((bits >> 23) & 0xff) - 127 + 15;
  uint32_t man = bits & 0x7fffffu;
  if (((bits >> 23) & 0xff) == 0xff) {  // inf/nan
    return sign | 0x7c00u | (man ? 0x200u : 0);
  }
  if (exp >= 31) return sign | 0x7c00u;  // overflow -> inf
  if (exp <= 0) {                        // subnormal / underflow
    if (exp < -10) return sign;
    man |= 0x800000u;
    int shift = 14 - exp;
    uint32_t half_man = man >> shift;
    uint32_t rem = man & ((1u << shift) - 1);
    uint32_t halfway = 1u << (shift - 1);
    if (rem > halfway || (rem == halfway && (half_man & 1))) ++half_man;
    return static_cast<uint16_t>(sign | half_man);
  }
  uint32_t half_man = man >> 13;
  uint32_t rem = man & 0x1fffu;
  if (rem > 0x1000u || (rem == 0x1000u && (half_man & 1))) {
    ++half_man;
    if (half_man == 0x400u) {
      half_man = 0;
      ++exp;
      if (exp >= 31) return sign | 0x7c00u;
    }
  }
  return static_cast<uint16_t>(sign | (exp << 10) | half_man);
}

float Bf16ToFloat(uint16_t b) {
  uint32_t bits = static_cast<uint32_t>(b) << 16;
  float f;
  memcpy(&f, &bits, 4);
  return f;
}

uint16_t FloatToBf16(float f) {
  uint32_t bits;
  memcpy(&bits, &f, 4);
  if ((bits & 0x7f800000u) == 0x7f800000u && (bits & 0x7fffffu))
    return static_cast<uint16_t>((bits >> 16) | 0x40);  // quiet nan
  uint32_t rounded = bits + 0x7fffu + ((bits >> 16) & 1);
  return static_cast<uint16_t>(rounded >> 16);
}

// ---------------------------------------------------------------------------
// fp8-e4m3fn (4 exponent bits, bias 7, 3 mantissa bits; no inf, 0x7f/0xff
// = nan) — the ml_dtypes.float8_e4m3fn layout the XLA plane mirrors with
// jnp casts.  Encoding SATURATES at ±448 instead of producing nan (the
// gradient-compression convention: one clipped outlier must not poison a
// whole fused bucket); the Python plane clips before casting for the same
// reason, so both planes quantize identically.
// ---------------------------------------------------------------------------

constexpr float kFp8Max = 448.0f;

uint8_t FloatToFp8(float f) {
  // Branchy bit-twiddled round-to-nearest-even (the hot loop of the fp8
  // wire path runs this per element; the frexp/nearbyint formulation it
  // replaced was 5x slower end to end).
  uint32_t bits;
  memcpy(&bits, &f, 4);
  uint8_t sign = static_cast<uint8_t>((bits >> 24) & 0x80u);
  uint32_t a = bits & 0x7fffffffu;
  if (a > 0x7f800000u) return sign | 0x7f;   // nan
  if (a >= 0x43e00000u) return sign | 0x7e;  // >= 448: saturate (no inf)
  if (a < 0x3c800000u) {
    // < 2^-6: subnormal grid, quantum 2^-9.  lrintf under the default
    // FE_TONEAREST mode is round-to-nearest-even, matching ml_dtypes;
    // a result of 8 lands exactly on the smallest normal (0x08).
    float av;
    memcpy(&av, &a, 4);
    return sign | static_cast<uint8_t>(lrintf(av * 512.0f));
  }
  // Normal: RNE the 23-bit mantissa down to 3 bits in the integer
  // domain, then rebias the exponent (127 -> 7).  Mantissa carry-out
  // propagates into the exponent arithmetically; a carry past 448
  // saturates.
  uint32_t rounded = a + 0x7ffffu + ((a >> 20) & 1u);
  if (rounded >= 0x43e00000u) return sign | 0x7e;
  uint32_t exp8 = ((rounded >> 23) & 0xffu) - 120u;
  return sign | static_cast<uint8_t>((exp8 << 3) | ((rounded >> 20) & 7u));
}

float Fp8ToFloat(uint8_t b) {
  static const std::vector<float> table = [] {
    std::vector<float> t(256);
    for (int i = 0; i < 256; ++i) {
      int exp = (i >> 3) & 0xf;
      int man = i & 7;
      float v;
      if (exp == 15 && man == 7)
        v = std::numeric_limits<float>::quiet_NaN();
      else if (exp == 0)
        v = std::ldexp(static_cast<float>(man), -9);
      else
        v = std::ldexp(1.0f + man / 8.0f, exp - 7);
      t[i] = (i & 0x80) ? -v : v;
    }
    return t;
  }();
  return table[b];
}

// ---------------------------------------------------------------------------
// Wire formats for the compressed ring (docs/performance.md
// #wire-compression): the reduction buffer stays f32 end to end, these
// helpers narrow segments at the send boundary and widen them back at the
// receive boundary.  COMP_* codes double as wire codes for f32 payloads;
// WIRE_F16 serves native-width f16 payload shipping.
// ---------------------------------------------------------------------------

constexpr uint8_t WIRE_BF16 = COMP_BF16;
constexpr uint8_t WIRE_FP8 = COMP_FP8;
constexpr uint8_t WIRE_F16 = 3;

size_t WireFormatSize(uint8_t wire) { return wire == WIRE_FP8 ? 1 : 2; }

void CompressBuf(const float* src, void* dst, int64_t n, uint8_t wire) {
  if (wire == WIRE_FP8) {
    uint8_t* d = static_cast<uint8_t*>(dst);
    for (int64_t i = 0; i < n; ++i) d[i] = FloatToFp8(src[i]);
  } else {
    uint16_t* d = static_cast<uint16_t*>(dst);
    if (wire == WIRE_F16)
      for (int64_t i = 0; i < n; ++i) d[i] = FloatToHalf(src[i]);
    else
      for (int64_t i = 0; i < n; ++i) d[i] = FloatToBf16(src[i]);
  }
}

void DecompressBuf(const void* src, float* dst, int64_t n, uint8_t wire) {
  if (wire == WIRE_FP8) {
    const uint8_t* s = static_cast<const uint8_t*>(src);
    for (int64_t i = 0; i < n; ++i) dst[i] = Fp8ToFloat(s[i]);
  } else {
    const uint16_t* s = static_cast<const uint16_t*>(src);
    if (wire == WIRE_F16)
      for (int64_t i = 0; i < n; ++i) dst[i] = HalfToFloat(s[i]);
    else
      for (int64_t i = 0; i < n; ++i) dst[i] = Bf16ToFloat(s[i]);
  }
}

void DecompressAccumulate(const void* src, float* dst, int64_t n,
                          uint8_t wire) {
  if (wire == WIRE_FP8) {
    const uint8_t* s = static_cast<const uint8_t*>(src);
    for (int64_t i = 0; i < n; ++i) dst[i] += Fp8ToFloat(s[i]);
  } else {
    const uint16_t* s = static_cast<const uint16_t*>(src);
    if (wire == WIRE_F16)
      for (int64_t i = 0; i < n; ++i) dst[i] += HalfToFloat(s[i]);
    else
      for (int64_t i = 0; i < n; ++i) dst[i] += Bf16ToFloat(s[i]);
  }
}

// One value's quantize -> dequantize round trip: what the wire will
// deliver, and therefore what the error-feedback residual is measured
// against.
float QuantDequant(float v, uint8_t wire) {
  if (wire == WIRE_FP8) {
    if (v > kFp8Max) v = kFp8Max;
    if (v < -kFp8Max) v = -kFp8Max;
    return Fp8ToFloat(FloatToFp8(v));
  }
  if (wire == WIRE_F16) return HalfToFloat(FloatToHalf(v));
  return Bf16ToFloat(FloatToBf16(v));
}

void HalfBufToFloat(const void* src, float* dst, int64_t n, uint8_t dtype) {
  const uint16_t* s = static_cast<const uint16_t*>(src);
  if (dtype == HVD_FLOAT16)
    for (int64_t i = 0; i < n; ++i) dst[i] = HalfToFloat(s[i]);
  else
    for (int64_t i = 0; i < n; ++i) dst[i] = Bf16ToFloat(s[i]);
}

void FloatBufToHalf(const float* src, void* dst, int64_t n, uint8_t dtype) {
  uint16_t* d = static_cast<uint16_t*>(dst);
  if (dtype == HVD_FLOAT16)
    for (int64_t i = 0; i < n; ++i) d[i] = FloatToHalf(src[i]);
  else
    for (int64_t i = 0; i < n; ++i) d[i] = FloatToBf16(src[i]);
}

int64_t NumElements(const std::vector<int64_t>& dims) {
  int64_t n = 1;
  for (int64_t d : dims) n *= d;
  return n;
}

std::string DimsToString(const std::vector<int64_t>& dims) {
  std::string s = "[";
  for (size_t i = 0; i < dims.size(); ++i) {
    if (i) s += ", ";
    s += std::to_string(dims[i]);
  }
  return s + "]";
}

// Fusion predicate shared by fresh negotiations (CoordinatorTick) and
// cached replays (ProcessCacheHits): may `bytes` more of `dtype` merge
// into the current allreduce `group`?  Both paths MUST stay equivalent,
// or replayed steps would get different ring-pass bucket boundaries than
// their first negotiation.
bool FusesInto(const Response& group, int64_t group_bytes,
               uint8_t group_dtype, uint8_t dtype, int64_t bytes,
               int64_t threshold) {
  // Stage-scoped buckets never fuse (docs/pipeline.md): a group op's
  // participant set differs from its neighbours', so a merged bucket
  // would have no single execution membership.  Both callers also check
  // the CANDIDATE's stage_ranks before offering it here.
  return group.type == RESP_ALLREDUCE && group.stage_ranks.empty() &&
         group.names.size() < 1024 && group_dtype == dtype &&
         group_bytes + bytes <= threshold;
}

// Participant count a pending negotiation must reach before its response
// builds: the pair for send/recv, the stage group's membership for a
// stage-scoped collective, the full world otherwise (docs/pipeline.md).
int RequiredCount(const Request& req, int world) {
  if (req.op == OP_SEND || req.op == OP_RECV) return 2;
  if (!req.stage_ranks.empty())
    return static_cast<int>(req.stage_ranks.size());
  return world;
}

// Ranks expected to announce a pending negotiation — the denominator the
// stall / timeout sweeps measure "missing" against.  For a p2p pair the
// expected set is the announcer(s) plus the peer each one named; for a
// stage group, its members; otherwise everyone.
std::vector<bool> ExpectedRanks(const std::vector<Request>& reqs,
                                int world) {
  std::vector<bool> expected(world, false);
  if (reqs.empty() ||
      (reqs[0].op != OP_SEND && reqs[0].op != OP_RECV &&
       reqs[0].stage_ranks.empty())) {
    expected.assign(world, true);
    return expected;
  }
  if (!reqs[0].stage_ranks.empty()) {
    for (int32_t m : reqs[0].stage_ranks)
      if (m >= 0 && m < world) expected[m] = true;
    return expected;
  }
  for (const auto& r : reqs) {
    if (r.rank >= 0 && r.rank < world) expected[r.rank] = true;
    if (r.p2p_peer >= 0 && r.p2p_peer < world) expected[r.p2p_peer] = true;
  }
  return expected;
}

// Expected announcers of a cached slot's agreement (the cache_pending
// analogue of ExpectedRanks): the stored pair for p2p, the stage members
// for a group op, everyone otherwise.
std::vector<bool> SlotExpectedRanks(const CacheSlot* s, int world) {
  std::vector<bool> expected(world, true);
  if (s == nullptr) return expected;
  if (s->response.type == RESP_SENDRECV) {
    expected.assign(world, false);
    if (s->response.p2p_src >= 0 && s->response.p2p_src < world)
      expected[s->response.p2p_src] = true;
    if (s->response.p2p_dst >= 0 && s->response.p2p_dst < world)
      expected[s->response.p2p_dst] = true;
  } else if (!s->response.stage_ranks.empty()) {
    expected.assign(world, false);
    for (int32_t m : s->response.stage_ranks)
      if (m >= 0 && m < world) expected[m] = true;
  }
  return expected;
}

// "1, 3" for the ranks NOT marked in `present`.
std::string MissingRanks(const std::vector<bool>& present) {
  std::string missing;
  for (size_t r = 0; r < present.size(); ++r)
    if (!present[r])
      missing += (missing.empty() ? "" : ", ") + std::to_string(r);
  return missing;
}

}  // namespace

// ---------------------------------------------------------------------------
// Negotiation response cache (docs/performance.md).  All mutation happens
// on the engine thread while processing the broadcast response lists, in
// list order, so every rank's cache evolves in lockstep — the property
// that lets a bare slot index stand in for a full string request.
// ---------------------------------------------------------------------------

int ResponseCache::Lookup(const Request& req) const {
  auto it = by_name_.find(req.name);
  if (it == by_name_.end()) return -1;
  const CacheSlot& s = slots_[it->second];
  // Point-to-point slots are stored from the broadcast response's
  // metadata (identical on every rank, participant or not), so the
  // signature match is role-aware: this rank's request matches when it
  // restates the same pair orientation the agreement recorded.
  if (s.response.type == RESP_SENDRECV) {
    const Response& a = s.response;
    bool as_send = req.op == OP_SEND && req.rank == a.p2p_src &&
                   req.p2p_peer == a.p2p_dst;
    bool as_recv = req.op == OP_RECV && req.rank == a.p2p_dst &&
                   req.p2p_peer == a.p2p_src;
    if (!(as_send || as_recv) || req.p2p_tag != a.p2p_tag ||
        req.dtype != a.p2p_dtype || req.dims != a.p2p_dims)
      return -1;
    return it->second;
  }
  if (s.op != req.op || s.dtype != req.dtype ||
      s.root_rank != req.root_rank || s.dims != req.dims ||
      req.stage_ranks != s.response.stage_ranks)
    return -1;
  return it->second;
}

int ResponseCache::SlotByName(const std::string& name) const {
  auto it = by_name_.find(name);
  return it == by_name_.end() ? -1 : it->second;
}

const CacheSlot* ResponseCache::Get(int slot) const {
  if (slot < 0 || slot >= static_cast<int>(slots_.size()) ||
      !slots_[slot].valid)
    return nullptr;
  return &slots_[slot];
}

int ResponseCache::Put(const std::string& name, uint8_t op, uint8_t dtype,
                       const std::vector<int64_t>& dims, int32_t root_rank,
                       const Response& response, CacheSlot* evicted) {
  evicted->valid = false;
  auto it = by_name_.find(name);
  int slot;
  if (it != by_name_.end()) {
    slot = it->second;
  } else if (static_cast<int64_t>(by_name_.size()) < capacity_) {
    // Lowest free slot (deterministic: slot states evolve in lockstep).
    slot = -1;
    for (int i = 0; i < static_cast<int>(slots_.size()); ++i)
      if (!slots_[i].valid) {
        slot = i;
        break;
      }
    if (slot < 0) {
      slot = static_cast<int>(slots_.size());
      slots_.emplace_back();
    }
  } else {
    // Full: evict the least-recently-touched entry.  The linear scan only
    // runs on fresh-name inserts past capacity — steady state is pure
    // hits, which never reach here.
    slot = 0;
    for (int i = 1; i < static_cast<int>(slots_.size()); ++i)
      if (slots_[i].valid &&
          (!slots_[slot].valid ||
           slots_[i].last_touch < slots_[slot].last_touch))
        slot = i;
    *evicted = slots_[slot];
    by_name_.erase(slots_[slot].name);
  }
  CacheSlot& s = slots_[slot];
  s.valid = true;
  s.last_touch = ++touch_counter_;
  s.name = name;
  s.op = op;
  s.dtype = dtype;
  s.root_rank = root_rank;
  s.dims = dims;
  s.response = response;
  by_name_[name] = slot;
  return slot;
}

void ResponseCache::Touch(int slot) {
  if (slot >= 0 && slot < static_cast<int>(slots_.size()) &&
      slots_[slot].valid)
    slots_[slot].last_touch = ++touch_counter_;
}

void ResponseCache::Erase(const std::string& name) {
  auto it = by_name_.find(name);
  if (it == by_name_.end()) return;
  slots_[it->second] = CacheSlot();
  by_name_.erase(it);
}

void ResponseCache::Clear() {
  slots_.clear();
  by_name_.clear();
  // touch_counter_ keeps rolling: only relative order matters.
}

// ---------------------------------------------------------------------------
// Coordinator state (rank 0).  Analogue of the reference MessageTable +
// IncrementTensorCount/ConstructMPIResponse
// (/root/reference/horovod/common/operations.cc:101,268,301).
// ---------------------------------------------------------------------------

struct Engine::Coordinator {
  struct PendingTensor {
    std::vector<Request> requests;  // one per rank that announced, any order
    std::chrono::steady_clock::time_point first_seen;
    uint64_t order = 0;
    // Announce-time accounting on rank 0's clock (µs since epoch): under
    // the coordinator tree the sub-coordinators forward each rank's TRUE
    // announce timestamp in the aggregate frame, so the last-to-announce
    // straggler verdict names the rank that was actually late, not the
    // sub-coordinator whose aggregate closed the count.
    int64_t first_us = -1;
    int64_t last_us = -1;
    int last_rank = -1;
    // Set when a cross-transport mismatch is detected (one camp announced
    // the bare name over the engine, another the "__xp."-prefixed
    // metadata op for the SAME logical tensor over the XLA plane): the
    // name negotiates straight to a typed error response instead of
    // stalling forever at count < size.
    std::string forced_error;
    // Entries for a recently-poisoned base name get a decision deadline
    // instead of an immediate error: if all ranks announce (a corrected,
    // consistent resubmission) the name negotiates normally and the
    // poison clears; if the count still stalls at the deadline, the
    // announcers are stragglers of the mismatched round and get the
    // poison's typed error.  0 = no deadline.
    int64_t poison_deadline_tick = 0;
  };
  std::unordered_map<std::string, PendingTensor> message_table;
  std::vector<std::string> ready;  // names with all ranks announced, in order
  // Base names that hit a cross-transport mismatch: stragglers of either
  // camp announcing within the poison window after the error response was
  // broadcast re-trigger the same typed error instead of re-pending
  // forever.  Entries EXPIRE (kPoisonWindowSec) so a later, consistent
  // resubmission of the same tensor name works again — the recovery
  // contract docs/tpu.md promises.  Bounded: cleared past 1024 entries.
  static constexpr double kPoisonWindowSec = 5.0;
  static constexpr int64_t kPoisonDeadlineTicks = 40;  // ~200ms @ 5ms cycle
  // A straggler first announcing AFTER the window expired still must not
  // pend forever (its peers consumed their error responses long ago).
  // The expired tombstone grants a stall-warning-length grace deadline:
  // any healthy (if skewed) full-count reuse of the name negotiates
  // normally well within it, and a still-short count at the deadline —
  // the point where the stall sweep would start warning anyway — gets
  // the typed error instead of an indefinite pend.
  std::unordered_map<std::string,
                     std::pair<std::string,
                               std::chrono::steady_clock::time_point>>
      poisoned;
  uint64_t next_order = 0;
  bool shutdown_requested = false;
  // Response-cache intersection: per-slot bit announcements still short of
  // full count (the integer-keyed analogue of message_table — no strings,
  // no per-tensor Request rebuild on the steady-state path).
  struct PendingBits {
    std::vector<bool> ranks;
    int count = 0;
    std::chrono::steady_clock::time_point first_seen;
    // Same per-rank announce-time accounting as PendingTensor.
    int64_t first_us = -1;
    int64_t last_us = -1;
    int last_rank = -1;
  };
  std::unordered_map<uint32_t, PendingBits> cache_pending;
  // Slots every rank announced, in agreement order; broadcast as
  // ResponseList.cache_hits next tick.
  std::vector<uint32_t> cached_ready;
  // Liveness (rank 0): workers whose control socket hit EOF/error.  The
  // first death arms the coordinated abort below; later deaths are noted
  // but the first abort wins.
  std::vector<bool> rank_dead;
  // Postmortem accounting (per rank, engine thread only): the tick each
  // rank's last control frame arrived at, and the tick/name of its last
  // collective announce — the raw material of the cross-rank diagnosis
  // ("rank 2 stopped announcing after tick 1841").  -1 = never.
  std::vector<int64_t> last_frame_tick;
  std::vector<int64_t> last_announce_tick;
  std::vector<std::string> last_announce_name;
  // Armed abort, broadcast in the next response list: ST_RANKS_DOWN or
  // ST_TIMEOUT plus a structured message naming missing ranks / stalled
  // tensors.  0 = not aborting.
  int32_t abort_code = 0;
  std::string abort_message;
  // Elastic membership (docs/fault-tolerance.md#elastic-membership).
  // reshape_pending arms a SHRINK barrier at the next tick (a worker died
  // but >= min_size survive); pending joiners are standbys that connected
  // to the control listen socket and await admission at the next barrier.
  bool reshape_pending = false;
  std::vector<int> pending_join_fds;
  std::vector<std::string> pending_join_endpoints;
  // Accepted control-plane connects whose JOIN handshake bytes have not
  // arrived yet.  The handshake is completed only once the fd is
  // readable, so a connect that never sends anything (health probe, port
  // scanner) costs the negotiation tick nothing and is dropped at its
  // deadline instead of stalling every worker's response wait.
  struct Handshake {
    int fd;
    std::chrono::steady_clock::time_point deadline;
    std::vector<uint8_t> buf;  // handshake bytes assembled so far
  };
  std::vector<Handshake> handshaking;
  // When the FIRST currently-pending joiner registered: a grow barrier
  // prefers a quiesced tick, but a fully pipelined training loop may
  // never quiesce — past a bounded wait the barrier is forced (in-flight
  // collectives get the same retryable ST_RESHAPE a shrink hands out),
  // so standby admission cannot starve behind steady traffic.
  std::chrono::steady_clock::time_point join_wait_since;
  // Decentralized steady state (docs/performance.md
  // #control-plane-scaling): the pattern detector's recent cache-hit
  // slot stream, each entry flagged when it opened a new broadcast list
  // (the per-tick grouping replayed buckets must reproduce).  Reset by
  // any non-hit broadcast (fresh response, tuned params, reshape, abort)
  // so the window only ever contains a pure steady-state hit stream.
  std::deque<std::pair<uint32_t, bool>> slot_history;
  // A STEADY verdict is in force: the coordinator broadcasts nothing
  // (beyond abort/shutdown) until EVERY rank has fallen back — an
  // earlier broadcast would double-execute replays on ranks still
  // self-clocking.
  bool steady = false;
  std::vector<bool> steady_exited;
  // Stamp the first post-steady broadcast with the revoke marker.
  bool steady_revoke_next = false;
};

// Control-plane hello a standby sends instead of a rank number when
// rejoining a live elastic job (rank hellos are < size, so this cannot
// collide).
static const uint32_t kJoinHello = 0xFFFFFFFEu;

Engine* GlobalEngine() {
  // Intentionally leaked: outlives any Python teardown order, mirroring the
  // reference's never-destructed HorovodGlobalState.
  static Engine* engine = new Engine();
  return engine;
}

Engine::Engine() = default;

Engine::~Engine() { Shutdown(); }

int Engine::Init(const EngineOptions& opts, std::string* err) {
  if (initialized_.load()) return 0;
  opts_ = opts;
  if (opts_.hierarchical_allreduce && opts_.size == 1)
    opts_.hierarchical_allreduce = false;
  if (opts_.elastic && opts_.hierarchical_allreduce) {
    // Reshapes rebuild only the flat ring; the two-level topology's
    // node-local stars would go stale at the first membership change.
    fprintf(stderr,
            "[horovod_tpu] WARNING: elastic membership forces the flat "
            "ring (hierarchical allreduce disabled).\n");
    opts_.hierarchical_allreduce = false;
  }
  // The multi-rank layout validation (ranks in contiguous blocks of
  // local_size, the hvdrun layout — analogue of the reference's
  // MPI_Comm_split_type shared-memory split, operations.cc:1364-1373)
  // happens inside SetupSockets over the coordinator star, so that every
  // rank reaches the SAME hierarchical/flat decision before any data-plane
  // topology is built.
  shut_down_.store(false);
  loop_exited_.store(false);
  data_plane_failed_.store(false);
  completions_.store(0);
  ticks_done_.store(0);
  {
    // abort_events_ stays cumulative across re-init (metrics contract,
    // like stall_events_); the latched status resets with the engine.
    std::lock_guard<std::mutex> lk(abort_mu_);
    abort_code_.store(0);
    abort_message_.clear();
    abort_pending_info_.clear();
  }
  epoch_ = std::chrono::steady_clock::now();
  clock_offset_us_.store(0);
  clock_rtt_us_.store(0);
  {
    // Announce counts are process-cumulative (like stall_events_); only
    // grow the per-rank vector if this job is wider than the last.
    std::lock_guard<std::mutex> lk(announce_mu_);
    if (static_cast<int>(last_announce_counts_.size()) < opts_.size)
      last_announce_counts_.resize(opts_.size, 0);
  }
  coord_.reset(new Coordinator());
  coord_->rank_dead.assign(opts_.size, false);
  coord_->last_frame_tick.assign(opts_.size, -1);
  coord_->last_announce_tick.assign(opts_.size, -1);
  coord_->last_announce_name.assign(opts_.size, "");
  // Control-plane tree + steady state start each lifetime cold; the
  // entry/exit/replay/frame counters stay process-cumulative (the
  // metrics contract StallEvents set).
  tree_enabled_ = false;
  is_sub_coord_ = false;
  sub_holding_ = false;
  tree_child_fds_.clear();
  tree_child_ranks_.clear();
  tree_child_dead_.clear();
  coord_children_.clear();
  pending_dead_reports_.clear();
  steady_active_.store(false);
  steady_pattern_.clear();
  steady_groups_.clear();
  steady_pos_ = steady_group_idx_ = 0;
  steady_epoch_ = 0;
  steady_pending_group_.clear();
  steady_pending_reqs_.clear();
  steady_exit_pending_ = false;
  steady_pattern_len_.store(0);
  ctrl_children_.store(0);
  ctrl_hosts_.store(1);
  if (opts_.elastic || opts_.rejoin) {
    // Elastic jobs keep the one-level star: membership reshapes rebuild
    // only the star, never a tree.  Steady state STAYS available
    // (hvdmodel's reshape-mid-steady interleavings pinned the protocol):
    // a barrier arming mid-steady is broadcast as a revocation first —
    // self-clocking ranks poll their parent socket every pass and treat
    // any payload broadcast as a revocation — and the barrier then
    // fires on the next regular tick (MaybeRevokeSteadyForReshape).
    opts_.coord_tree = false;
  }
  {
    std::lock_guard<std::mutex> lk(coord_info_mu_);
    coord_pending_info_.clear();
  }
  // Flight recorder (postmortem plane): always-on unless sized to 0.
  // Env-read here rather than plumbed through the init signature — the
  // ring is pure observability and every rank reads the same launcher
  // environment.
  {
    const char* cap_env = getenv("HVD_TPU_FLIGHT_EVENTS");
    int64_t cap = 512;
    if (cap_env && *cap_env) cap = atoll(cap_env);
    if (cap < 0) cap = 0;
    flight_.Initialize(cap, epoch_);
  }
  // Data-plane heartbeat detector + link-fault injection
  // (docs/fault-tolerance.md#failure-detection).  Env-read here like the
  // flight recorder: every rank reads the same launcher environment, and
  // the knobs must be known BEFORE SetupSockets (which dials the beat
  // sockets only when the detector is on).
  {
    const char* hb_env = getenv("HVD_TPU_HEARTBEAT_MS");
    hb_interval_ms_ = (hb_env && *hb_env) ? atoi(hb_env) : 100;
    if (hb_interval_ms_ < 0) hb_interval_ms_ = 0;
    const char* miss_env = getenv("HVD_TPU_HEARTBEAT_MISS");
    hb_miss_limit_ = (miss_env && *miss_env) ? atoi(miss_env) : 10;
    if (hb_miss_limit_ < 1) hb_miss_limit_ = 1;
    const char* fault_env = getenv("HVD_TPU_NET_FAULT_SPEC");
    std::string fault_err;
    if (!NetFaultInit(fault_env ? fault_env : "", opts_.rank, &fault_err)) {
      *err = "bad HVD_TPU_NET_FAULT_SPEC: " + fault_err;
      return 1;
    }
    // Perf-introspection plane (docs/metrics.md#links / #anomalies):
    // per-link telemetry default-on (counters are process-cumulative and
    // cost one mutex hold per transport call; HVD_TPU_LINK_STATS=0 is
    // the kill switch), anomaly detector default sigma 5 (0 disables).
    const char* ls_env = getenv("HVD_TPU_LINK_STATS");
    NetLinkInit(!(ls_env && *ls_env && atoi(ls_env) == 0));
    const char* as_env = getenv("HVD_TPU_ANOMALY_SIGMA");
    anomaly_sigma_ = (as_env && *as_env) ? atoi(as_env) : 5;
    if (anomaly_sigma_ < 0) anomaly_sigma_ = 0;
    const char* ai_env = getenv("HVD_TPU_ANOMALY_INTERVAL_MS");
    anomaly_interval_ms_ = (ai_env && *ai_env) ? atoi(ai_env) : 500;
    if (anomaly_interval_ms_ < 10) anomaly_interval_ms_ = 10;
    anomaly_stop_.store(false);
    // Transport seam policy (docs/performance.md#transport).  Env-read
    // here like the knobs above, but the MODE becomes part of the init
    // job-wide agreement in SetupSockets — a per-rank divergence (one
    // rank with the kill switch thrown) would otherwise split the job
    // between transports mid-ring.
    shm_mode_ = ParseShmMode(getenv("HVD_TPU_SHM"));
    const char* srb_env = getenv("HVD_TPU_SHM_RING_BYTES");
    shm_ring_bytes_ = (srb_env && *srb_env) ? atoll(srb_env) : (1 << 20);
    if (shm_ring_bytes_ < (64 << 10)) shm_ring_bytes_ = 64 << 10;
    shm_agreed_ = false;
    shm_active_ = false;
    topo_shm_.store(false);
    std::lock_guard<std::mutex> lk(hb_mu_);
    hb_last_seen_us_.clear();
    hb_miss_counts_.clear();
    pending_hb_dead_.clear();
    pending_hb_report_.clear();
    hb_wake_fds_.clear();
    hb_ctrl_wake_fd_ = -1;
    hb_epoch_ = 0;
    hb_local_abort_msg_.clear();
    hb_local_abort_.store(false);
    hb_stop_.store(false);
  }
  fast_ticks_ = 0;
  last_fusion_use_ = epoch_;
  // Every rank writes its own trace; the Python side resolves
  // HOROVOD_TIMELINE's directory / %d forms to a per-rank path (a plain
  // file path stays rank-0-only there, for the legacy single-file mode).
  timeline_.Initialize(opts_.timeline_path, opts_.rank, epoch_);
  // Elastic membership starts each lifetime at epoch 0.  The lost/joined
  // lists and reshapes_total_ stay PROCESS-CUMULATIVE (like
  // stall_events_): their lengths back the hvd_tpu_membership_*_total
  // Prometheus counters, which must never decrease across an in-process
  // re-init.  Only the poison message is per-lifetime.
  membership_epoch_.store(0);
  reshape_ack_pending_.store(false);
  {
    std::lock_guard<std::mutex> lk(membership_mu_);
    reshape_message_.clear();
  }
  std::string setup_err;
  bool setup_ok = opts_.rejoin ? SetupRejoinSockets(&setup_err)
                               : SetupSockets(&setup_err);
  if (!setup_ok) {
    *err = setup_err;
    TeardownSockets();
    return 1;
  }
  // rank/size may have been (re)assigned by the rejoin admission; the
  // atomics below are what Python's hvd.rank()/hvd.size() read.
  cur_rank_.store(opts_.rank);
  cur_size_.store(opts_.size);
  cur_local_rank_.store(opts_.local_rank);
  cur_local_size_.store(opts_.local_size);
  timeline_.WriteClockSync(clock_offset_us_.load(), clock_rtt_us_.load());
  // The response cache starts cold every engine lifetime: restart epochs
  // and in-process re-inits must renegotiate (the peers' caches are
  // gone).  Hit/miss/eviction counters stay process-cumulative, like
  // stalls.  The capacity is the JOB-WIDE agreement SetupSockets just
  // negotiated — per-rank env divergence (one rank with the kill switch
  // thrown, or a smaller HVD_TPU_CACHE_CAPACITY) would otherwise make a
  // slot index mean different things on different ranks.
  cache_.set_capacity(opts_.cache_capacity);
  cache_.Clear();
  cache_size_.store(0);
  // Wire compression (docs/performance.md#wire-compression): per-engine-
  // lifetime state.  SetupSockets just validated the mode/min-bytes
  // agreement job-wide; residuals start empty (a restart epoch must not
  // replay stale error feedback), and the decision log restarts so the
  // lockstep-identical contract is testable per lifetime.
  cur_compression_.store(opts_.compression_mode);
  cur_comp_min_bytes_.store(opts_.compression_min_bytes);
  residuals_.clear();
  residual_bytes_.store(0);
  residual_tensors_.store(0);
  {
    std::lock_guard<std::mutex> lk(comp_mu_);
    comp_log_.clear();
  }
  if (opts_.compression_mode != COMP_NONE && flight_.Enabled())
    flight_.Record(FL_COMPRESS, "", opts_.compression_mode);
  // Online autotuning (docs/performance.md#autotuning): the search runs
  // at the coordinator only; every rank tracks the applied parameters.
  // State is per-engine-lifetime — a restart epoch re-tunes from its env
  // (the winning params are in the previous run's report for pinning).
  // The compression axis is searchable only when the job opted into a
  // lossy wire format: with HVD_TPU_COMPRESSION off the axis pins at
  // "none" so the tuner can never silently make an exact job lossy.
  // (The two-level topology no longer pins it: the DCN hop compresses
  // like the flat ring.)  The cross-algo axis is the dual: it only means
  // anything on the two-level topology, so a flat-ring job pins it at
  // the env value instead of burning windows scoring a dead knob.
  // Both topology-coupled axes go dead on a single-NODE two-level job
  // (no DCN hop): pin them rather than burn windows scoring identical
  // points.  n_nodes_ is known here — SetupSockets already ran.
  bool cross_hop_live = opts_.hierarchical_allreduce && n_nodes_ > 1;
  int64_t tuner_fix_comp = opts_.compression_mode == COMP_NONE
                               ? COMP_NONE
                               : opts_.autotune_fix_compression;
  if (opts_.hierarchical_allreduce && !cross_hop_live)
    tuner_fix_comp = opts_.compression_mode;
  tuner_.Configure(opts_.autotune && (opts_.rank == 0 || opts_.size == 1),
                   opts_.autotune_warmup, opts_.autotune_window,
                   opts_.autotune_fix_fusion, opts_.autotune_fix_cycle_ms,
                   tuner_fix_comp,
                   cross_hop_live ? opts_.autotune_fix_cross_algo
                                  : opts_.cross_algo_threshold,
                   opts_.fusion_threshold, opts_.cycle_time_ms,
                   opts_.compression_mode, opts_.cross_algo_threshold);
  cur_fusion_.store(opts_.fusion_threshold);
  cur_cycle_us_.store(static_cast<int64_t>(opts_.cycle_time_ms * 1000.0));
  cur_cross_algo_.store(opts_.cross_algo_threshold);
  topo_last_algo_.store(-1);
  autotune_frozen_.store(false);
  applied_window_.store(0);
  {
    std::lock_guard<std::mutex> lk(autotune_mu_);
    applied_log_.clear();
    fusion_history_.clear();
    fusion_history_.emplace_back(0, opts_.fusion_threshold);
    compression_history_.clear();
    compression_history_.emplace_back(0, opts_.compression_mode);
  }
  last_stall_check_ = std::chrono::steady_clock::now();
  initialized_.store(true);
  background_ = std::thread([this]() { BackgroundLoop(); });
  // Liveness monitor: off the engine tick by construction, so a busy (or
  // blocked) local ring never starves the beats.  An elastic solo rank
  // starts it too — the first grow's RebuildRing hands it beat sockets.
  if (hb_interval_ms_ > 0 && (opts_.size > 1 || opts_.elastic))
    hb_thread_ = std::thread([this]() { HeartbeatLoop(); });
  // Anomaly detector: same off-the-tick construction.  Single-rank jobs
  // skip it (no links, no announce order, nothing to localize).
  if (anomaly_sigma_ > 0 && opts_.size > 1)
    anomaly_thread_ = std::thread([this]() { AnomalyLoop(); });
  return 0;
}

bool Engine::SetupSockets(std::string* err) {
  if (opts_.size == 1) {
    // A solo ELASTIC coordinator still needs its listen sockets: the
    // control listener is where standbys register (a job launched at or
    // shrunk to one rank must keep accepting joiners) and the data
    // listener is what RebuildRing accepts the first grow's neighbour
    // on.  Endpoints come from the launcher env; a plain single-process
    // init without them simply stays non-growable.
    if (opts_.elastic && !opts_.coord_endpoint.empty() &&
        !opts_.data_endpoints.empty()) {
      std::string host;
      int port;
      if (ParseEndpoint(opts_.coord_endpoint, &host, &port))
        coord_listen_fd_ = Listen("0.0.0.0", port, err);
      if (coord_listen_fd_ >= 0 &&
          ParseEndpoint(opts_.data_endpoints[0], &host, &port))
        data_listen_fd_ = Listen("0.0.0.0", port, err);
      if (coord_listen_fd_ < 0 || data_listen_fd_ < 0) {
        *err = "elastic single-rank listen failed: " + *err;
        return false;
      }
      coord_fds_.assign(1, -1);
    }
    return true;
  }
  std::string host;
  int port;
  const double kTimeout = 120.0;
  // Control plane: rank-0 star.
  if (opts_.rank == 0) {
    if (!ParseEndpoint(opts_.coord_endpoint, &host, &port)) {
      *err = "bad coordinator endpoint " + opts_.coord_endpoint;
      return false;
    }
    coord_listen_fd_ = Listen("0.0.0.0", port, err);
    if (coord_listen_fd_ < 0) return false;
  }
  // Data plane: every rank listens on its endpoint.
  if (!ParseEndpoint(opts_.data_endpoints[opts_.rank], &host, &port)) {
    *err = "bad data endpoint " + opts_.data_endpoints[opts_.rank];
    return false;
  }
  data_listen_fd_ = Listen("0.0.0.0", port, err);
  if (data_listen_fd_ < 0) return false;

  if (opts_.rank == 0) {
    coord_fds_.assign(opts_.size, -1);
    for (int pending = opts_.size - 1; pending > 0;) {
      int fd = AcceptOne(coord_listen_fd_, kTimeout, err);
      if (fd < 0) return false;
      uint32_t peer_rank;
      if (!RecvAll(fd, &peer_rank, 4)) {
        *err = "bad hello from worker";
        CloseFd(fd);
        return false;
      }
      if (opts_.elastic && peer_rank == kJoinHello) {
        // A standby can register while init is still collecting worker
        // hellos: hvdrun backfills toward --max-np from the first tick of
        // the keep-alive loop, so a start-small launch (-np 2 --max-np 6)
        // races its first standby against this loop.  Park it for the
        // first reshape barrier instead of failing the whole job's init.
        if (!RegisterJoiner(fd, 1.0)) CloseFd(fd);
        continue;
      }
      if (peer_rank >= (uint32_t)opts_.size || coord_fds_[peer_rank] >= 0) {
        *err = "bad hello from worker";
        CloseFd(fd);
        return false;
      }
      coord_fds_[peer_rank] = fd;
      --pending;
    }
  } else {
    if (!ParseEndpoint(opts_.coord_endpoint, &host, &port)) {
      *err = "bad coordinator endpoint " + opts_.coord_endpoint;
      return false;
    }
    coord_fd_ = ConnectRetry(host, port, kTimeout, err);
    if (coord_fd_ < 0) return false;
    uint32_t my_rank = static_cast<uint32_t>(opts_.rank);
    if (!SendAll(coord_fd_, &my_rank, 4)) {
      *err = "hello send failed";
      return false;
    }
  }
  // Topology agreement: every rank reports (local_rank, local_size,
  // hierarchical-requested) to rank 0, which validates the contiguous-block
  // layout globally and broadcasts one job-wide hierarchical/flat decision.
  // A per-rank decision could diverge (e.g. interleaved placement passing
  // the modular check on some ranks only) and deadlock the socket setup.
  {
    // The 4th slot agrees on the response-cache capacity job-wide (the
    // minimum across ranks — a thrown kill switch anywhere disables it
    // everywhere): per-rank divergence would make a cache-slot index
    // mean different collectives on different ranks.  Slots 5/6 carry
    // the wire-compression config, which must be IDENTICAL on every rank
    // — a min-reduce would silently weaken a rank's explicit choice, and
    // a split would make ranks pack the same bucket in different wire
    // formats; a mismatch is therefore a typed init error, not a vote.
    uint32_t cap32 = static_cast<uint32_t>(std::min<int64_t>(
        std::max<int64_t>(opts_.cache_capacity, 0), 0x7fffffff));
    uint32_t cmin32 = static_cast<uint32_t>(std::min<int64_t>(
        std::max<int64_t>(opts_.compression_min_bytes, 0), 0x7fffffff));
    // Slot 7 carries the HVD_TPU_SHM transport choice, with the same
    // IDENTICAL-everywhere contract as compression: a split would put
    // some ranks of a node ring on the segment and others on the socket,
    // which deadlocks the first local hop.  Like compression, mismatch
    // is a typed init error, never a vote.
    uint32_t mine[8] = {(uint32_t)opts_.local_rank, (uint32_t)opts_.local_size,
                        opts_.hierarchical_allreduce ? 1u : 0u, cap32,
                        (uint32_t)opts_.compression_mode, cmin32,
                        opts_.coord_tree ? 1u : 0u, (uint32_t)shm_mode_};
    // {hierarchical decision, capacity, compression mismatch flag,
    //  coordinator-tree decision, transport mismatch flag, shm verdict}
    uint32_t reply[6] = {0, cap32, 0, 0, 0, 0};
    if (opts_.rank == 0) {
      std::vector<uint32_t> lr(opts_.size), ls(opts_.size), hr(opts_.size);
      lr[0] = mine[0]; ls[0] = mine[1]; hr[0] = mine[2];
      bool tree_want = mine[6] != 0;
      uint32_t agreed_cap = cap32;
      std::string comp_mismatch;
      std::string shm_mismatch;
      for (int r = 1; r < opts_.size; ++r) {
        uint32_t peer[8];
        if (!RecvAll(coord_fds_[r], peer, sizeof peer)) {
          *err = "topology agreement recv failed";
          return false;
        }
        lr[r] = peer[0]; ls[r] = peer[1]; hr[r] = peer[2];
        tree_want = tree_want && peer[6] != 0;
        agreed_cap = std::min(agreed_cap, peer[3]);
        if (comp_mismatch.empty() &&
            (peer[4] != mine[4] || peer[5] != mine[5]))
          comp_mismatch =
              "HVD_TPU_COMPRESSION mismatch: rank 0 configured mode " +
              std::string(CompressionName(opts_.compression_mode)) +
              " (min bytes " + std::to_string(cmin32) + ") but rank " +
              std::to_string(r) + " configured mode " +
              CompressionName(static_cast<uint8_t>(peer[4])) +
              " (min bytes " + std::to_string(peer[5]) +
              "); wire compression must be configured identically on "
              "every rank.";
        if (shm_mismatch.empty() && peer[7] != mine[7])
          shm_mismatch =
              "HVD_TPU_SHM mismatch: rank 0 configured mode " +
              std::string(ShmModeName(shm_mode_)) + " but rank " +
              std::to_string(r) + " configured mode " +
              ShmModeName(static_cast<ShmMode>(peer[7] <= 2 ? peer[7] : 1)) +
              "; the data-plane transport must be configured identically "
              "on every rank.";
      }
      bool want = true, valid = true;
      for (int r = 0; r < opts_.size; ++r) want = want && hr[r] != 0;
      uint32_t L = ls[0];
      // L == 1 would make the leader ring an exact duplicate of the flat
      // global ring; fall back rather than double the data-plane sockets.
      if (L < 2 || opts_.size % (int)L != 0) valid = false;
      for (int r = 0; valid && r < opts_.size; ++r)
        if (ls[r] != L || lr[r] != (uint32_t)(r % (int)L)) valid = false;
      if (want && !valid && L >= 2)
        fprintf(stderr,
                "[horovod_tpu] WARNING: hierarchical allreduce requires "
                "equal local_size on every rank and ranks grouped in "
                "contiguous blocks of local_size; falling back to the flat "
                "ring.\n");
      reply[0] = (want && valid) ? 1 : 0;
      reply[1] = agreed_cap;
      reply[2] = comp_mismatch.empty() ? 0 : 1;
      // Coordinator-tree verdict (docs/performance.md
      // #control-plane-scaling): same contiguous-block layout contract
      // as the data topology, and only meaningful with >= 2 nodes of
      // >= 2 ranks — otherwise the star IS the degenerate one-level
      // tree.  Job-wide so every rank rewires (or keeps) its control
      // socket identically.
      reply[3] = (tree_want && valid && !opts_.elastic &&
                  opts_.size / (int)L >= 2)
                     ? 1
                     : 0;
      reply[4] = shm_mismatch.empty() ? 0 : 1;
      // Shm verdict: the segment rings carry the NODE-LOCAL hops, so shm
      // can only arm on the agreed two-level topology (want && valid),
      // and never on elastic jobs (reshapes force the flat ring).  The
      // mode itself is identical job-wide when reply[4] == 0.
      reply[5] = (shm_mode_ != ShmMode::kOff && shm_mismatch.empty() &&
                  want && valid && !opts_.elastic)
                     ? 1
                     : 0;
      for (int r = 1; r < opts_.size; ++r) {
        if (!SendAll(coord_fds_[r], reply, sizeof reply)) {
          *err = "topology agreement send failed";
          return false;
        }
      }
      if (!comp_mismatch.empty()) {
        // The verdict was sent (workers fail with the same typed error);
        // fail init on the coordinator with the full who-said-what story.
        *err = comp_mismatch;
        return false;
      }
      if (!shm_mismatch.empty()) {
        *err = shm_mismatch;
        return false;
      }
    } else {
      // A fresh init can race a PREVIOUS engine's teardown on rank 0
      // (shutdown -> re-init, e.g. the compression convergence test's
      // back-to-back jobs): a running non-elastic coordinator never
      // accepts on its control listener, so this worker's connect can
      // land in the OLD listener's kernel backlog — the hello and the
      // agreement report buffer fine — and die with an RST only when
      // rank 0 finally tears down, while rank 0's NEW init waits for a
      // hello that will never arrive and the job deadlocks until the
      // accept timeout.  The handshake therefore retries WHOLE
      // (reconnect + hello + agreement) until the deadline: a reply can
      // only come from a live init, because replying requires accept().
      auto hs_deadline = std::chrono::steady_clock::now() +
                         std::chrono::duration<double>(kTimeout);
      while (!SendAll(coord_fd_, mine, sizeof mine) ||
             !RecvAll(coord_fd_, reply, sizeof reply)) {
        CloseFd(coord_fd_);
        coord_fd_ = -1;
        double left =
            std::chrono::duration<double>(
                hs_deadline - std::chrono::steady_clock::now())
                .count();
        if (left <= 0.0) {
          *err = "topology agreement exchange failed";
          return false;
        }
        coord_fd_ = ConnectRetry(host, port, left, err);
        if (coord_fd_ < 0) return false;
        uint32_t my_rank = static_cast<uint32_t>(opts_.rank);
        if (!SendAll(coord_fd_, &my_rank, 4)) continue;
      }
      if (reply[2] != 0) {
        *err = "HVD_TPU_COMPRESSION mismatch: the ranks disagree on the "
               "wire-compression configuration (mode or min-bytes floor); "
               "set HVD_TPU_COMPRESSION and HVD_TPU_COMPRESSION_MIN_BYTES "
               "identically on every rank.";
        return false;
      }
      if (reply[4] != 0) {
        *err = "HVD_TPU_SHM mismatch: the ranks disagree on the data-plane "
               "transport mode; set HVD_TPU_SHM identically on every rank.";
        return false;
      }
    }
    opts_.hierarchical_allreduce = reply[0] != 0;
    opts_.cache_capacity = static_cast<int64_t>(reply[1]);
    opts_.coord_tree = reply[3] != 0;
    shm_agreed_ = reply[5] != 0;
    if (shm_mode_ == ShmMode::kForce && !shm_agreed_) {
      *err = "HVD_TPU_SHM=force but the shared-memory transport cannot arm: "
             "it requires the two-level topology (hierarchical allreduce "
             "agreed job-wide: equal local_size >= 2, ranks in contiguous "
             "blocks of local_size) on a non-elastic job; use HVD_TPU_SHM="
             "auto to fall back to TCP instead.";
      return false;
    }
  }
  // (Clock alignment runs at the END of socket setup, AFTER the tree
  // restructure and the data-plane accept loop: under the coordinator
  // tree the probes are RELAYED through the sub-coordinators — rank 0
  // probes only its direct children, each sub composes its own verdict
  // with per-child probes over the tree sockets built below — so rank
  // 0's init fan-in stays O(hosts) instead of the old O(ranks) star.)
  node_id_ = opts_.hierarchical_allreduce ? opts_.rank / opts_.local_size : 0;
  n_nodes_ = opts_.hierarchical_allreduce ? opts_.size / opts_.local_size : 1;
  topo_hier_.store(opts_.hierarchical_allreduce);
  topo_nodes_.store(n_nodes_);

  // Control-plane coordinator tree restructure (docs/performance.md
  // #control-plane-scaling).  The init rendezvous above is a transient
  // O(ranks) star (one bounded round — agreement + clock sync); the
  // STEADY-STATE control plane is what scales, so non-lead workers of
  // nodes >= 1 now re-home their control socket to their node's
  // local-rank-0, which accepts them over its DATA listener with a typed
  // hello (no new endpoints).  Rank 0 keeps one socket per
  // sub-coordinator plus its own node's workers: O(hosts + local_size).
  tree_enabled_ = opts_.coord_tree && opts_.size > 1;
  const int Lc = opts_.local_size;
  const int ctrl_nodes = tree_enabled_ ? opts_.size / Lc : 1;
  is_sub_coord_ =
      tree_enabled_ && opts_.local_rank == 0 && opts_.rank >= Lc;
  ctrl_hosts_.store(ctrl_nodes);
  if (opts_.rank == 0) {
    coord_children_.clear();
    for (int r = 1; r < opts_.size; ++r) {
      bool direct = !tree_enabled_ || r < Lc || r % Lc == 0;
      if (direct) {
        coord_children_.push_back(r);
      } else {
        CloseFd(coord_fds_[r]);
        coord_fds_[r] = -1;
      }
    }
    ctrl_children_.store(static_cast<int>(coord_children_.size()));
  } else if (is_sub_coord_) {
    tree_child_fds_.assign(Lc - 1, -1);
    tree_child_ranks_.clear();
    for (int i = 1; i < Lc; ++i)
      tree_child_ranks_.push_back(opts_.rank + i);
    tree_child_dead_.assign(Lc - 1, false);
    ctrl_children_.store(Lc - 1);
  }

  // Data-plane connections.  Every outgoing connection announces itself
  // with a 4-byte hello (kind in the high byte, sender id in the low 24
  // bits) so one listen socket can serve the global ring, the node-local
  // star, and the cross-node leader ring.  Kernel listen backlogs complete
  // handshakes before accept(2), so every rank can finish all its connects
  // before starting its accepts without deadlock.
  const uint32_t kHelloRing = 0u << 24;
  const uint32_t kHelloLocal = 1u << 24;
  const uint32_t kHelloCross = 2u << 24;
  // Control-plane tree: a non-lead worker's hello to its node's
  // sub-coordinator (id = the worker's global rank).
  const uint32_t kHelloCtrl = 5u << 24;
  auto connect_hello = [&](const std::string& ep, uint32_t hello,
                           std::string* err) -> int {
    std::string h;
    int p;
    if (!ParseEndpoint(ep, &h, &p)) {
      *err = "bad data endpoint " + ep;
      return -1;
    }
    int fd = ConnectRetry(h, p, kTimeout, err);
    if (fd < 0) return -1;
    if (!SendAll(fd, &hello, 4)) {
      *err = "data-plane hello send failed";
      CloseFd(fd);
      return -1;
    }
    return fd;
  };

  bool hier = opts_.hierarchical_allreduce;
  const int L = opts_.local_size;
  // Recursive-doubling tree partners exist only for power-of-two node
  // counts; otherwise the tree path falls back to the ring and no fds
  // are built.
  int tree_levels = 0;
  if (hier && n_nodes_ > 1 && (n_nodes_ & (n_nodes_ - 1)) == 0)
    for (int m = n_nodes_; m > 1; m >>= 1) ++tree_levels;
  const uint32_t kHelloTree = 4u << 24;
  // Control-tree re-home: a non-lead worker of a node >= 1 drops its
  // init-star socket to rank 0 and connects to its sub-coordinator's
  // data listener instead (rank 0 closed its end above symmetrically).
  if (tree_enabled_ && opts_.rank >= Lc && opts_.local_rank != 0) {
    CloseFd(coord_fd_);
    int lead = opts_.rank - opts_.local_rank;
    coord_fd_ = connect_hello(opts_.data_endpoints[lead],
                              kHelloCtrl | (uint32_t)opts_.rank, err);
    if (coord_fd_ < 0) {
      *err = "control-tree connect to the sub-coordinator failed: " + *err;
      return false;
    }
  }
  // Connect to the right global-ring neighbour.
  int right = (opts_.rank + 1) % opts_.size;
  right_fd_ = connect_hello(opts_.data_endpoints[right],
                            kHelloRing | (uint32_t)opts_.rank, err);
  if (right_fd_ < 0) return false;
  // Heartbeat beacon sockets (docs/fault-tolerance.md#failure-detection):
  // rank r dials (r+1)%size and accepts (r-1+size)%size over the same
  // data listener, typed hello kind 6 with the membership epoch in bits
  // 16-23 (init epoch 0) and the sender rank in the low 16.  Dedicated
  // fds, full-duplex, owned by the monitor thread — never the ring's.
  const uint32_t kHelloBeat = 6u << 24;
  const bool want_beats = hb_interval_ms_ > 0;
  if (want_beats) {
    int bfd = connect_hello(
        opts_.data_endpoints[right],
        kHelloBeat | ((uint32_t)opts_.rank & 0xffffu), err);
    if (bfd < 0) {
      *err = "heartbeat beacon connect failed: " + *err;
      return false;
    }
    std::lock_guard<std::mutex> lk(hb_mu_);
    beat_out_fd_ = bfd;
    beat_out_peer_ = right;
  }
  if (hier) {
    // Node-local ring: every rank connects to its right local neighbour
    // (same node, local_rank+1 mod L) — the hop the local reduce-scatter
    // and allgather phases run over.
    int node_base = opts_.rank - opts_.local_rank;
    int local_right = node_base + (opts_.local_rank + 1) % L;
    local_right_fd_ = connect_hello(
        opts_.data_endpoints[local_right],
        kHelloLocal | (uint32_t)opts_.local_rank, err);
    if (local_right_fd_ < 0) return false;
  }
  if (hier && n_nodes_ > 1) {
    // Sharded cross-node ring: EVERY local rank connects to its
    // same-local-rank peer on the next node, so each of the local_size
    // shards crosses the DCN on its own stream (the single-leader-NIC
    // bottleneck this topology replaces).
    int peer = ((node_id_ + 1) % n_nodes_) * L + opts_.local_rank;
    cross_right_fd_ = connect_hello(opts_.data_endpoints[peer],
                                    kHelloCross | (uint32_t)node_id_, err);
    if (cross_right_fd_ < 0) return false;
    // Tree partners: for each XOR level the side with the level bit
    // CLEAR connects, the side with it SET accepts — exactly one
    // connection per partner pair per level.
    cross_tree_fds_.assign(tree_levels, -1);
    for (int k = 0; k < tree_levels; ++k) {
      if (node_id_ & (1 << k)) continue;  // this side accepts
      int p = (node_id_ ^ (1 << k)) * L + opts_.local_rank;
      cross_tree_fds_[k] = connect_hello(
          opts_.data_endpoints[p],
          kHelloTree | ((uint32_t)k << 16) | (uint32_t)node_id_, err);
      if (cross_tree_fds_[k] < 0) return false;
    }
  }

  int expected = 1;  // left global-ring neighbour
  if (hier) {
    expected += 1;  // left local-ring neighbour
    if (n_nodes_ > 1) {
      expected += 1;  // cross-ring left neighbour
      for (int k = 0; k < tree_levels; ++k)
        if (node_id_ & (1 << k)) expected += 1;  // tree partner connects
    }
  }
  if (is_sub_coord_) expected += Lc - 1;  // this node's control sockets
  if (want_beats) expected += 1;          // left neighbour's beat socket
  const int beat_left = (opts_.rank + opts_.size - 1) % opts_.size;
  for (int i = 0; i < expected; ++i) {
    int fd = AcceptOne(data_listen_fd_, kTimeout, err);
    if (fd < 0) return false;
    uint32_t hello;
    if (!RecvAll(fd, &hello, 4)) {
      *err = "data-plane hello recv failed";
      CloseFd(fd);
      return false;
    }
    uint32_t kind = hello & 0xff000000u;
    uint32_t id = hello & 0x00ffffffu;
    if (kind == kHelloRing && left_fd_ < 0) {
      left_fd_ = fd;
    } else if (kind == kHelloLocal && hier && local_left_fd_ < 0 &&
               id == (uint32_t)((opts_.local_rank + L - 1) % L)) {
      local_left_fd_ = fd;
    } else if (kind == kHelloCross && hier && n_nodes_ > 1 &&
               cross_left_fd_ < 0) {
      cross_left_fd_ = fd;
    } else if (kind == kHelloTree && hier && n_nodes_ > 1) {
      int k = (int)((id >> 16) & 0xff);
      if (k >= tree_levels || !(node_id_ & (1 << k)) ||
          cross_tree_fds_[k] >= 0) {
        *err = "unexpected tree-partner hello " + std::to_string(hello);
        CloseFd(fd);
        return false;
      }
      cross_tree_fds_[k] = fd;
    } else if (kind == kHelloCtrl && is_sub_coord_) {
      int child = static_cast<int>(id) - opts_.rank - 1;
      if (child < 0 || child >= Lc - 1 || tree_child_fds_[child] >= 0) {
        *err = "unexpected control-tree hello " + std::to_string(hello);
        CloseFd(fd);
        return false;
      }
      tree_child_fds_[child] = fd;
    } else if (kind == kHelloBeat && want_beats &&
               (id & 0xffffu) == (uint32_t)beat_left &&
               ((id >> 16) & 0xff) == 0) {
      std::lock_guard<std::mutex> lk(hb_mu_);
      if (beat_in_fd_ >= 0) {
        *err = "duplicate heartbeat hello " + std::to_string(hello);
        CloseFd(fd);
        return false;
      }
      beat_in_fd_ = fd;
      beat_in_peer_ = beat_left;
    } else {
      *err = "unexpected data-plane hello " + std::to_string(hello);
      CloseFd(fd);
      return false;
    }
  }
  if (left_fd_ < 0) {
    *err = "global ring left neighbour never connected";
    return false;
  }
  if (hier && local_left_fd_ < 0) {
    *err = "node-local ring left neighbour never connected";
    return false;
  }
  if (is_sub_coord_)
    for (int i = 0; i < Lc - 1; ++i)
      if (tree_child_fds_[i] < 0) {
        *err = "control-tree worker rank " +
               std::to_string(tree_child_ranks_[i]) + " never connected";
        return false;
      }
  if (want_beats && beat_in_fd_ < 0) {
    *err = "heartbeat beacon left neighbour never connected";
    return false;
  }
  // fd -> peer-rank registry (net.h): every data/control/beat fd maps to
  // the rank at its far end.  HVD_TPU_NET_FAULT_SPEC clauses naming
  // ranks resolve to sockets through it, and the per-link telemetry
  // (NetLinkInfo) attributes bytes/latency through the SAME map — so
  // registration is unconditional now (the fault hot path still costs
  // one relaxed atomic when no spec is armed).  The beat fds register
  // too — a partitioned link MUST also silence its beacons, or the
  // detector could never see the partition it exists to detect.
  {
    NetFaultRegister(right_fd_, right);
    NetFaultRegister(left_fd_, beat_left);
    if (hier) {
      int node_base = opts_.rank - opts_.local_rank;
      NetFaultRegister(local_right_fd_,
                       node_base + (opts_.local_rank + 1) % L);
      NetFaultRegister(local_left_fd_,
                       node_base + (opts_.local_rank + L - 1) % L);
      if (n_nodes_ > 1) {
        NetFaultRegister(cross_right_fd_,
                         ((node_id_ + 1) % n_nodes_) * L + opts_.local_rank);
        NetFaultRegister(cross_left_fd_, ((node_id_ + n_nodes_ - 1) %
                                          n_nodes_) * L + opts_.local_rank);
        for (int k = 0; k < tree_levels; ++k)
          if (cross_tree_fds_[k] >= 0)
            NetFaultRegister(cross_tree_fds_[k],
                             (node_id_ ^ (1 << k)) * L + opts_.local_rank);
      }
    }
    if (opts_.rank == 0) {
      for (int r : coord_children_) NetFaultRegister(coord_fds_[r], r);
    } else {
      NetFaultRegister(coord_fd_, (tree_enabled_ && opts_.rank >= Lc &&
                                   opts_.local_rank != 0)
                                      ? opts_.rank - opts_.local_rank
                                      : 0);
      for (size_t i = 0; i < tree_child_fds_.size(); ++i)
        NetFaultRegister(tree_child_fds_[i], tree_child_ranks_[i]);
    }
    std::lock_guard<std::mutex> lk(hb_mu_);
    NetFaultRegister(beat_out_fd_, beat_out_peer_);
    NetFaultRegister(beat_in_fd_, beat_in_peer_);
  }
  // Transport seam: wrap the topology fds in channels and run the shm
  // segment rendezvous when the job-wide agreement armed it.  Before the
  // monitor wake registry (the segment joins it) and before ClockSync
  // (a force-mode failure must surface as the init verdict).
  if (!SetupShmTransport(err)) return false;
  // Arm the monitor's wake registry: the data-plane fds the engine thread
  // can block in (ring exchanges), shut down by the monitor when it
  // flags a silent peer so a survivor wakes in O(heartbeat) instead of
  // stalling transitively behind the frozen rank.  NEVER the beat fds
  // (the gossip must keep flowing) and never the control fds (only
  // hb_ctrl_wake_fd_, at the local-abort escalation).
  {
    std::lock_guard<std::mutex> lk(hb_mu_);
    hb_wake_fds_.clear();
    hb_wake_fds_.push_back(left_fd_);
    hb_wake_fds_.push_back(right_fd_);
    if (local_left_fd_ >= 0) hb_wake_fds_.push_back(local_left_fd_);
    if (local_right_fd_ >= 0) hb_wake_fds_.push_back(local_right_fd_);
    if (cross_left_fd_ >= 0) hb_wake_fds_.push_back(cross_left_fd_);
    if (cross_right_fd_ >= 0) hb_wake_fds_.push_back(cross_right_fd_);
    for (int fd : cross_tree_fds_)
      if (fd >= 0) hb_wake_fds_.push_back(fd);
    hb_wake_shm_ = shm_active_ ? &shm_seg_ : nullptr;
    hb_ctrl_wake_fd_ = opts_.rank == 0 ? -1 : coord_fd_;
    // Monitored peers start "just seen": the first miss window opens at
    // init, not at the epoch of the clock.
    int64_t now_us = EpochNowUs();
    if (beat_in_peer_ >= 0) hb_last_seen_us_[beat_in_peer_] = now_us;
    if (beat_out_peer_ >= 0) hb_last_seen_us_[beat_out_peer_] = now_us;
  }
  // Clock alignment for the per-rank timelines (docs/timeline.md),
  // relayed through the coordinator tree when one was just built.
  if (!ClockSync(err)) return false;
  return true;
}

namespace {
// Attach-token relay words (ASCII-tagged for strace readability).
constexpr uint32_t kShmRound1Ok = 0x53484d31;   // "SHM1"
constexpr uint32_t kShmRound1Bad = 0x53484d30;  // "SHM0"
constexpr uint32_t kShmRound2Arm = 0x53484d41;  // "SHMA"
constexpr uint32_t kShmRound2Tcp = 0x53484d54;  // "SHMT"

bool ShmTokenSend(int fd, uint32_t tok) { return SendAll(fd, &tok, 4); }
bool ShmTokenRecv(int fd, uint32_t* tok) {
  return WaitReadable(fd, 30.0) && RecvAll(fd, tok, 4);
}
}  // namespace

bool Engine::SetupShmTransport(std::string* err) {
  const int L = opts_.hierarchical_allreduce ? opts_.local_size : 1;
  const int node_base = node_id_ * L;
  const int lr = opts_.local_rank;
  // The channels wrap every topology fd unconditionally — the TCP path
  // is simply a channel with no rings — so the data-plane code has ONE
  // seam instead of an fd path and a ring path.
  left_ch_ = Channel{left_fd_, nullptr, nullptr,
                     (opts_.rank + opts_.size - 1) % opts_.size};
  right_ch_ = Channel{right_fd_, nullptr, nullptr,
                      (opts_.rank + 1) % opts_.size};
  local_left_ch_ = Channel{local_left_fd_, nullptr, nullptr,
                           node_base + (lr + L - 1) % L};
  local_right_ch_ = Channel{local_right_fd_, nullptr, nullptr,
                            node_base + (lr + 1) % L};
  cross_left_ch_ = Channel{
      cross_left_fd_, nullptr, nullptr,
      ((node_id_ + n_nodes_ - 1) % n_nodes_) * L + lr};
  cross_right_ch_ = Channel{cross_right_fd_, nullptr, nullptr,
                            ((node_id_ + 1) % n_nodes_) * L + lr};
  if (!shm_agreed_) return true;
  // Chaos interop (the ISSUE's never-silently-ignored contract): a
  // fault clause naming ANY in-node ring link decides the node's
  // transport before the segment exists.  delay/jitter clauses apply at
  // the shm seam (NetFaultDelayPeer per handoff); drop/flaky/partition
  // shapes cannot be expressed by a memory fence, so they demote the
  // node to TCP (auto) or fail init typed (force).  Every local rank
  // scans ALL in-node links, so the whole node reaches one verdict with
  // no extra rendezvous round.
  bool chaos_tcp = false;
  for (int i = 0; i < L && !chaos_tcp; ++i) {
    std::string clause;
    int verdict = NetFaultQueryLink(node_base + i, node_base + (i + 1) % L,
                                    &clause);
    if (verdict == 2) {
      if (shm_mode_ == ShmMode::kForce) {
        *err = "HVD_TPU_SHM=force but HVD_TPU_NET_FAULT_SPEC clause '" +
               clause + "' injects a drop/flaky/partition fault on the "
               "same-host link " + std::to_string(node_base + i) + "-" +
               std::to_string(node_base + (i + 1) % L) +
               ", which the shared-memory transport cannot express; "
               "drop the clause or use HVD_TPU_SHM=auto (TCP fallback).";
        return false;
      }
      if (lr == 0)
        fprintf(stderr,
                "[horovod_tpu] WARNING: HVD_TPU_NET_FAULT_SPEC clause "
                "'%s' injects a drop/flaky fault on a same-host link; "
                "node %d keeps the TCP transport (HVD_TPU_SHM=auto "
                "demotes, it never silently ignores a clause).\n",
                clause.c_str(), node_id_);
      chaos_tcp = true;
    }
  }
  if (chaos_tcp) return true;
  // Segment name: job tag (coordinator endpoint — unique per job on a
  // host) + node + epoch (launcher restart epoch composed with the
  // elastic membership epoch), so restarts and reshapes can never
  // attach a stale generation's segment.
  const char* re_env = getenv("HVD_TPU_RESTART_EPOCH");
  long long restart_epoch = (re_env && *re_env) ? atoll(re_env) : 0;
  long long epoch = restart_epoch * 1000000 + membership_epoch_.load();
  std::string name = ShmSegmentName(opts_.coord_endpoint, node_id_, epoch);
  // Two-round token relay over the node-local ring sockets (already
  // connected, already chaos-registered).  Round 1 (attach): local rank
  // 0 creates, then an Ok token circulates rightward with every rank
  // attaching before forwarding (any failure flips it to Bad).  Round 2
  // (verdict): the creator UNLINKS THE NAME FIRST — every rank is
  // attached or the node is abandoning shm, so from here no abort,
  // typed death, or SIGKILL can leak a /dev/shm entry — then circulates
  // Arm/Tcp so every rank flips its channels in the same tick.
  uint32_t tok = 0;
  std::string seg_err;
  bool attached = false;
  if (lr == 0) {
    attached = shm_seg_.Create(name, L, (size_t)shm_ring_bytes_, &seg_err);
    if (!ShmTokenSend(local_right_fd_, attached ? kShmRound1Ok
                                                : kShmRound1Bad) ||
        !ShmTokenRecv(local_left_fd_, &tok)) {
      *err = "shm attach-token relay failed on the node-local ring";
      return false;
    }
    shm_seg_.Unlink();
    bool arm = attached && tok == kShmRound1Ok;
    uint32_t verdict = arm ? kShmRound2Arm : kShmRound2Tcp;
    if (!ShmTokenSend(local_right_fd_, verdict) ||
        !ShmTokenRecv(local_left_fd_, &tok) || tok != verdict) {
      *err = "shm verdict-token relay failed on the node-local ring";
      return false;
    }
  } else {
    if (!ShmTokenRecv(local_left_fd_, &tok)) {
      *err = "shm attach-token relay failed on the node-local ring";
      return false;
    }
    if (tok == kShmRound1Ok) {
      attached = shm_seg_.Attach(name, L, (size_t)shm_ring_bytes_, &seg_err);
      if (!attached) tok = kShmRound1Bad;
    }
    if (!ShmTokenSend(local_right_fd_, tok) ||
        !ShmTokenRecv(local_left_fd_, &tok) ||
        !ShmTokenSend(local_right_fd_, tok)) {
      *err = "shm verdict-token relay failed on the node-local ring";
      return false;
    }
  }
  if (tok != kShmRound2Arm) {
    shm_seg_.Unmap();
    if (shm_mode_ == ShmMode::kForce) {
      *err = "HVD_TPU_SHM=force but the node " + std::to_string(node_id_) +
             " segment could not arm" +
             (seg_err.empty() ? std::string(" (a peer failed to attach)")
                              : ": " + seg_err) +
             "; use HVD_TPU_SHM=auto to fall back to TCP instead.";
      return false;
    }
    if (lr == 0)
      fprintf(stderr,
              "[horovod_tpu] WARNING: shared-memory transport could not "
              "arm on node %d (%s); falling back to TCP.\n",
              node_id_, seg_err.empty() ? "a peer failed to attach"
                                        : seg_err.c_str());
    return true;
  }
  // Armed: point the node-local channels at the segment rings.  Ring
  // (r, 0) flows rightward (r writes, (r+1)%L reads), ring (r, 1)
  // leftward — so this rank SENDS right on (lr, 0) and left on (lr, 1),
  // RECEIVES from the left neighbour's rightward ring and the right
  // neighbour's leftward ring.
  local_right_ch_.tx = shm_seg_.Ring(lr, 0);
  local_right_ch_.rx = shm_seg_.Ring((lr + 1) % L, 1);
  local_left_ch_.tx = shm_seg_.Ring(lr, 1);
  local_left_ch_.rx = shm_seg_.Ring((lr + L - 1) % L, 0);
  shm_active_ = true;
  topo_shm_.store(true);
  if (flight_.Enabled())
    flight_.Record(FL_TRANSPORT, "shm", (int64_t)shm_ring_bytes_);
  if (lr == 0)
    fprintf(stderr,
            "[horovod_tpu] node %d local ring on shared-memory transport "
            "(%d ranks, %lld-byte rings, segment unlinked).\n",
            node_id_, L, (long long)shm_ring_bytes_);
  return true;
}

void Engine::TeardownSockets() {
  {
    // The monitor is already joined (Shutdown) or was never started
    // (init failure); clear its wake registry BEFORE any CloseFd below
    // so no path can ever shut down a recycled fd number, and reap any
    // beat fds it never got to.
    std::lock_guard<std::mutex> lk(hb_mu_);
    hb_wake_fds_.clear();
    hb_wake_shm_ = nullptr;
    hb_ctrl_wake_fd_ = -1;
    CloseFd(beat_in_fd_);
    CloseFd(beat_out_fd_);
    beat_in_fd_ = beat_out_fd_ = -1;
    beat_in_peer_ = beat_out_peer_ = -1;
    for (int fd : hb_graveyard_) CloseFd(fd);
    hb_graveyard_.clear();
  }
  CloseFd(coord_listen_fd_);
  CloseFd(coord_fd_);
  for (int fd : coord_fds_) CloseFd(fd);
  coord_fds_.clear();
  if (coord_) {
    // Standbys parked for an admission that will never come (plus any
    // half-done handshakes): their processes see EOF and exit instead of
    // blocking on a closed coordinator.
    for (int fd : coord_->pending_join_fds) CloseFd(fd);
    coord_->pending_join_fds.clear();
    coord_->pending_join_endpoints.clear();
    for (const auto& hs : coord_->handshaking) CloseFd(hs.fd);
    coord_->handshaking.clear();
  }
  for (int fd : tree_child_fds_) CloseFd(fd);
  tree_child_fds_.clear();
  tree_child_ranks_.clear();
  tree_child_dead_.clear();
  coord_children_.clear();
  CloseFd(data_listen_fd_);
  CloseFd(left_fd_);
  CloseFd(right_fd_);
  CloseTopologyFds();
  CloseP2pChannels();
  coord_listen_fd_ = coord_fd_ = data_listen_fd_ = left_fd_ = right_fd_ = -1;
  left_ch_ = Channel{};
  right_ch_ = Channel{};
}

void Engine::ShutdownTopologyFds() {
  ShutdownFd(local_left_fd_);
  ShutdownFd(local_right_fd_);
  ShutdownFd(cross_left_fd_);
  ShutdownFd(cross_right_fd_);
  for (int fd : cross_tree_fds_) ShutdownFd(fd);
  // Dedicated p2p channels: a peer blocked mid-transfer wakes too.  The
  // fds close with CloseP2pChannels (teardown / ring rebuild).
  for (auto& kv : p2p_chans_) ShutdownFd(kv.second.fd);
  // Shm analogue of ShutdownFd: a helper (or peer) blocked in a ring
  // drive loop wakes within one poll iteration.  Unmap stays with
  // CloseTopologyFds, after the helpers joined.
  shm_seg_.CloseRings();
}

void Engine::CloseTopologyFds() {
  CloseFd(local_left_fd_);
  CloseFd(local_right_fd_);
  CloseFd(cross_left_fd_);
  CloseFd(cross_right_fd_);
  for (int fd : cross_tree_fds_) CloseFd(fd);
  cross_tree_fds_.clear();
  local_left_fd_ = local_right_fd_ = -1;
  cross_left_fd_ = cross_right_fd_ = -1;
  // Segment teardown.  The name was already unlinked the moment the
  // attach token round-tripped; the extra Unlink here covers the
  // create-to-attach window on an init failure, so no typed death path
  // can leak a /dev/shm entry.  De-register from the monitor BEFORE
  // unmapping (it may be mid-CloseRings on the mapping).
  {
    std::lock_guard<std::mutex> lk(hb_mu_);
    hb_wake_shm_ = nullptr;
  }
  shm_seg_.Unlink();
  shm_seg_.Unmap();
  shm_active_ = false;
  topo_shm_.store(false);
  // Only the TOPOLOGY channels: the flat-ring pair (left_ch_/right_ch_)
  // tracks left_fd_/right_fd_, which outlive a two-level teardown (the
  // flat ring keeps serving broadcast/allgather after a failed
  // hierarchical collective latched the topology closed).
  local_left_ch_ = Channel{};
  local_right_ch_ = Channel{};
  cross_left_ch_ = Channel{};
  cross_right_ch_ = Channel{};
}

int64_t Engine::EpochNowUs() const {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now() - epoch_)
      .count();
}

bool Engine::ClockSync(std::string* err) {
  // K round trips per probed peer; the minimum-RTT sample gives the best
  // offset estimate (symmetric-path assumption: the peer's timestamp was
  // taken at the probe's midpoint), its RTT the error bound.  The
  // verdict is sent back so each rank knows its own offset — each rank's
  // timeline records it for tools/timeline_merge.py.
  //
  // Under the coordinator tree the sync RELAYS: rank 0 probes only its
  // direct children — O(hosts + local_size), not the O(ranks) star this
  // replaced — and each sub-coordinator, once it holds its own verdict
  // (offset o_s, error r_s), probes its leaves against ITS clock
  // (offset o_c) and hands them the composed verdict {o_s + o_c,
  // r_s + r_c}: leaf_clock = rank0_clock + o_s + o_c, with the error
  // bounds summing along the relay path.
  const int kProbes = 8;
  if (opts_.size == 1) return true;
  auto probe_peer = [&](int fd, int64_t* best_off,
                        int64_t* best_rtt) -> bool {
    *best_rtt = -1;
    *best_off = 0;
    for (int k = 0; k < kProbes; ++k) {
      uint8_t probe = 1;
      int64_t t0 = EpochNowUs();
      if (!SendAll(fd, &probe, 1)) return false;
      int64_t peer_ts;
      if (!RecvAll(fd, &peer_ts, 8)) return false;
      int64_t t1 = EpochNowUs();
      int64_t rtt = t1 - t0;
      if (*best_rtt < 0 || rtt < *best_rtt) {
        *best_rtt = rtt;
        *best_off = peer_ts - (t0 + t1) / 2;
      }
    }
    return true;
  };
  auto serve_probes = [&](int fd) -> bool {
    for (int k = 0; k < kProbes; ++k) {
      uint8_t probe;
      if (!RecvAll(fd, &probe, 1)) return false;
      int64_t now = EpochNowUs();
      if (!SendAll(fd, &now, 8)) return false;
    }
    return true;
  };
  if (opts_.rank == 0) {
    int probed = 0;
    for (int r : coord_children_) {
      int64_t off, rtt;
      if (!probe_peer(coord_fds_[r], &off, &rtt)) {
        *err = "clock sync probe failed (rank " + std::to_string(r) + ")";
        return false;
      }
      ++probed;
      int64_t verdict[2] = {off, rtt};
      if (!SendAll(coord_fds_[r], verdict, sizeof verdict)) {
        *err = "clock sync verdict send failed (rank " + std::to_string(r) +
               ")";
        return false;
      }
    }
    clock_fanin_.store(probed);
  } else {
    if (!serve_probes(coord_fd_)) {
      *err = "clock sync probe recv failed";
      return false;
    }
    int64_t verdict[2];
    if (!RecvAll(coord_fd_, verdict, sizeof verdict)) {
      *err = "clock sync verdict recv failed";
      return false;
    }
    clock_offset_us_.store(verdict[0]);
    clock_rtt_us_.store(verdict[1]);
    // Sub-coordinator relay leg (tree_child_fds_ is empty off the tree).
    int probed = 0;
    for (size_t i = 0; i < tree_child_fds_.size(); ++i) {
      int64_t off, rtt;
      if (!probe_peer(tree_child_fds_[i], &off, &rtt)) {
        *err = "clock sync relay probe failed (rank " +
               std::to_string(tree_child_ranks_[i]) + ")";
        return false;
      }
      ++probed;
      int64_t composed[2] = {verdict[0] + off, verdict[1] + rtt};
      if (!SendAll(tree_child_fds_[i], composed, sizeof composed)) {
        *err = "clock sync relay verdict send failed (rank " +
               std::to_string(tree_child_ranks_[i]) + ")";
        return false;
      }
    }
    if (probed > 0) clock_fanin_.store(probed);
  }
  return true;
}

void Engine::RecordAnnounce(int last_rank, int64_t skew_us) {
  if (skew_us < 0) skew_us = 0;
  std::lock_guard<std::mutex> lk(announce_mu_);
  ++announce_events_;
  if (last_rank >= 0 &&
      last_rank < static_cast<int>(last_announce_counts_.size()))
    ++last_announce_counts_[last_rank];
  announce_log_.emplace_back(last_rank, skew_us);
  while (announce_log_.size() > 1024) announce_log_.pop_front();
}

int64_t Engine::AnnounceEvents() {
  std::lock_guard<std::mutex> lk(announce_mu_);
  return announce_events_;
}

std::string Engine::AnnounceLog() {
  // The cumulative event count is PREFIXED ("count:entries") under the
  // same lock hold as the log serialization: a reader pairing a separate
  // AnnounceEvents() call with this log could race concurrent
  // negotiations and mis-window the entries (dropping or double-counting
  // skew samples).
  std::lock_guard<std::mutex> lk(announce_mu_);
  std::string out = std::to_string(announce_events_) + ":";
  bool first = true;
  for (const auto& rec : announce_log_) {
    if (!first) out += ';';
    first = false;
    out += std::to_string(rec.first) + "|" + std::to_string(rec.second);
  }
  return out;
}

std::string Engine::LastAnnounceCounts() {
  std::lock_guard<std::mutex> lk(announce_mu_);
  std::string out;
  for (size_t i = 0; i < last_announce_counts_.size(); ++i) {
    if (i) out += ',';
    out += std::to_string(last_announce_counts_[i]);
  }
  return out;
}

void Engine::Shutdown() {
  if (!initialized_.load()) return;
  shut_down_.store(true);
  // BackgroundLoop's exit path drains the table and fails pending entries;
  // after join there is nothing left to complete (new Enqueues are rejected
  // once loop_exited_ flips under mu_).
  if (background_.joinable()) background_.join();
  StopHeartbeatMonitor();
  StopAnomalyMonitor();
  timeline_.Shutdown();
  TeardownSockets();
  initialized_.store(false);
}

void Engine::StopAnomalyMonitor() {
  anomaly_stop_.store(true);
  if (anomaly_thread_.joinable()) anomaly_thread_.join();
}

void Engine::StopHeartbeatMonitor() {
  hb_stop_.store(true);
  {
    // Wake the monitor out of any beat-socket poll; the fds stay
    // allocated (shutdown, not close) until after the join.
    std::lock_guard<std::mutex> lk(hb_mu_);
    ShutdownFd(beat_in_fd_);
    ShutdownFd(beat_out_fd_);
  }
  if (hb_thread_.joinable()) hb_thread_.join();
  // TeardownSockets reaps the beat fds and the graveyard.
}

void Engine::HeartbeatLoop() {
  // Monitor thread contract (docs/fault-tolerance.md#failure-detection):
  // beacons out and liveness in over the two dedicated beat fds, NEVER a
  // control or ring socket, and no engine state beyond the hb_mu_-guarded
  // block — escalation is queued for the engine thread (MarkRankDead and
  // AbortLocal clear coordinator tables and the response cache, which
  // only that thread may touch).  The one cross-thread action it takes
  // is ShutdownFd on registered fds, which is the wake primitive.
  const int64_t interval_us = static_cast<int64_t>(hb_interval_ms_) * 1000;
  const int64_t window_us = interval_us * hb_miss_limit_;
  std::vector<uint8_t> bufs[2];
  bool eofs[2] = {false, false};
  int cached_fds[2] = {-2, -2};
  int cached_peers[2] = {-1, -1};
  int64_t cached_epoch = -1;
  std::vector<int> suspects;       // flagged this epoch (local + gossip)
  int64_t grace_deadline_us = -1;  // -1 unarmed, -2 fired
  int64_t last_beat_us = 0;
  uint32_t seq = 0;
  // Echo-RTT send-stamp ring (per-link RTT telemetry, net.h
  // NetLinkRecordRtt): beacon seq -> send time, 256 deep — ~25s of
  // beacons at the default cadence, far past any echo's plausible
  // return, so a match is never a wrapped stale seq.
  uint32_t echo_seq[256];
  int64_t echo_ts[256];
  for (int i = 0; i < 256; ++i) {
    echo_seq[i] = 0xffffffffu;
    echo_ts[i] = 0;
  }

  auto flagged = [&](int peer) {
    for (int s : suspects)
      if (s == peer) return true;
    return false;
  };
  auto flag = [&](int peer) {
    if (flagged(peer)) return;
    suspects.push_back(peer);
    hb_miss_events_.fetch_add(1);
    if (flight_.Enabled()) flight_.Record(FL_HEARTBEAT_MISS, "flag", peer);
    {
      std::lock_guard<std::mutex> lk(hb_mu_);
      if (cur_rank_.load() == 0)
        pending_hb_dead_.push_back(peer);
      else
        pending_hb_report_.push_back(peer);
      // Wake the engine thread out of any ring exchange: with a frozen
      // participant the whole ring stalls transitively, so the job is
      // headed for a reshape (elastic) or an abort either way — breaking
      // the data links now converts an O(collective-timeout) hang into
      // an O(heartbeat) typed verdict.  The registry is cleared by the
      // engine (under this same mutex) before any of these fds is
      // closed, so a recycled fd number can never be hit.
      for (int fd : hb_wake_fds_) ShutdownFd(fd);
      // Same wake for the shm transport: closing the segment's rings
      // breaks any drive loop blocked on a full/empty ring within one
      // poll iteration.  The registry entry is cleared (under this
      // mutex) before the engine unmaps, so no use-after-unmap.
      if (hb_wake_shm_) hb_wake_shm_->CloseRings();
    }
    if (grace_deadline_us == -1) {
      // One more miss window for the coordinated path (reports up, typed
      // abort or reshape broadcast back) before concluding the
      // coordinator itself is unreachable (partition) and escalating
      // locally.  Elastic jobs get extra slack: a reshape needs a full
      // revoke + barrier round trip.
      int64_t extra = opts_.elastic ? 2000000 : 0;
      grace_deadline_us = EpochNowUs() + window_us + extra;
    }
    queue_cv_.notify_all();
  };

  while (!hb_stop_.load()) {
    int fds[2], peers[2];
    int64_t ep;
    {
      std::lock_guard<std::mutex> lk(hb_mu_);
      for (int fd : hb_graveyard_) CloseFd(fd);
      hb_graveyard_.clear();
      fds[0] = beat_in_fd_;
      fds[1] = beat_out_fd_;
      peers[0] = beat_in_peer_;
      peers[1] = beat_out_peer_;
      ep = hb_epoch_;
    }
    if (ep != cached_epoch) {
      cached_epoch = ep;
      suspects.clear();
      grace_deadline_us = -1;
    }
    for (int i = 0; i < 2; ++i)
      if (fds[i] != cached_fds[i]) {
        cached_fds[i] = fds[i];
        cached_peers[i] = peers[i];
        bufs[i].clear();
        eofs[i] = false;
      }
    if (fds[0] < 0 && fds[1] < 0) {
      // Solo (or between reshapes): nothing to monitor yet.
      std::this_thread::sleep_for(
          std::chrono::milliseconds(hb_interval_ms_));
      continue;
    }
    int64_t now = EpochNowUs();
    if (now - last_beat_us >= interval_us) {
      last_beat_us = now;
      HeartbeatFrame hb;
      hb.sender_rank = static_cast<uint32_t>(cur_rank_.load());
      hb.epoch = static_cast<uint32_t>(ep);
      hb.seq = seq++;
      echo_seq[hb.seq & 0xffu] = hb.seq;
      echo_ts[hb.seq & 0xffu] = now;
      uint8_t frame[kHeartbeatFrameBytes];
      SerializeHeartbeat(hb, frame);
      for (int i = 0; i < 2; ++i)
        if (fds[i] >= 0 && SendAll(fds[i], frame, sizeof frame))
          hb_sent_.fetch_add(1);
      // Suspect gossip: repeat every accusation each interval so it hops
      // rank to rank even when the frozen rank sits between the accuser
      // and rank 0 (the mid-steady partition story).
      for (int s : suspects) {
        HeartbeatFrame g;
        g.magic = kSuspectMagic;
        g.sender_rank = hb.sender_rank;
        g.epoch = hb.epoch;
        g.seq = static_cast<uint32_t>(s);
        SerializeHeartbeat(g, frame);
        for (int i = 0; i < 2; ++i)
          if (fds[i] >= 0) SendAll(fds[i], frame, sizeof frame);
      }
      // Miss accounting for the (up to two) directly monitored peers.
      std::vector<int> to_flag;
      {
        std::lock_guard<std::mutex> lk(hb_mu_);
        for (int i = 0; i < 2; ++i) {
          int peer = cached_peers[i];
          if (peer < 0 || (i == 1 && peer == cached_peers[0])) continue;
          auto it = hb_last_seen_us_.find(peer);
          if (it == hb_last_seen_us_.end())
            it = hb_last_seen_us_.emplace(peer, now).first;
          int misses = static_cast<int>((now - it->second) / interval_us);
          hb_miss_counts_[peer] = misses;
          if (misses >= hb_miss_limit_) to_flag.push_back(peer);
        }
      }
      for (int peer : to_flag) flag(peer);
    }
    if (grace_deadline_us >= 0 && now > grace_deadline_us) {
      grace_deadline_us = -2;
      if (abort_code_.load() == 0 && !suspects.empty()) {
        // The coordinated escalation never came back: the path to rank 0
        // is itself dead (network partition).  Latch the typed local
        // verdict for the engine thread and break its parent wait.
        std::vector<int> sorted = suspects;
        std::sort(sorted.begin(), sorted.end());
        std::string csv;
        for (int s : sorted)
          csv += (csv.empty() ? "" : ", ") + std::to_string(s);
        {
          std::lock_guard<std::mutex> lk(hb_mu_);
          hb_local_abort_msg_ =
              "ranks down: " + csv +
              " (no data-plane heartbeats within the detection window; "
              "process(es) frozen or network partitioned, and the "
              "coordinator is unreachable). The job was aborted; restart "
              "it (e.g. hvdrun --max-restarts) to resume from the latest "
              "checkpoint.";
          hb_local_abort_.store(true);
          ShutdownFd(hb_ctrl_wake_fd_);
        }
        if (flight_.Enabled())
          flight_.Record(FL_HEARTBEAT_MISS, "local-abort", sorted[0]);
        queue_cv_.notify_all();
      }
    }
    // Nap, then drain whatever beacons arrived.  The nap paces the loop
    // well under the beat interval so send jitter never costs a miss.
    int nap_ms = hb_interval_ms_ / 4;
    if (nap_ms < 1) nap_ms = 1;
    if (nap_ms > 25) nap_ms = 25;
    std::this_thread::sleep_for(std::chrono::milliseconds(nap_ms));
    for (int i = 0; i < 2; ++i) {
      if (cached_fds[i] < 0 || eofs[i]) continue;
      if (!RecvAvailable(cached_fds[i], &bufs[i])) {
        // EOF/error: a crashed peer.  Stop reading; its silence ages out
        // through the same miss path a freeze takes.
        eofs[i] = true;
        continue;
      }
      size_t off = 0;
      while (bufs[i].size() - off >= kHeartbeatFrameBytes) {
        HeartbeatFrame in;
        if (ParseHeartbeat(bufs[i].data() + off, &in) &&
            static_cast<int64_t>(in.epoch) == ep) {
          if (in.magic == kSuspectMagic) {
            int s = static_cast<int>(in.seq);
            if (s >= 0 && s < cur_size_.load() && s != cur_rank_.load())
              flag(s);
          } else if (in.magic == kEchoMagic) {
            // Our own beacon, bounced back by the neighbour: one RTT
            // sample for the link this echo arrived on.
            int idx = static_cast<int>(in.seq & 0xffu);
            if (static_cast<int>(in.sender_rank) == cur_rank_.load() &&
                echo_seq[idx] == in.seq)
              NetLinkRecordRtt(cached_peers[i],
                               EpochNowUs() - echo_ts[idx]);
          } else {
            hb_recv_.fetch_add(1);
            int sender = static_cast<int>(in.sender_rank);
            {
              std::lock_guard<std::mutex> lk(hb_mu_);
              hb_last_seen_us_[sender] = EpochNowUs();
              hb_miss_counts_[sender] = 0;
            }
            // Bounce the beacon straight back with the magic swapped
            // (sender_rank/epoch/seq preserved) on the same full-duplex
            // socket — the sender turns it into the link's RTT estimate.
            HeartbeatFrame echo = in;
            echo.magic = kEchoMagic;
            uint8_t ef[kHeartbeatFrameBytes];
            SerializeHeartbeat(echo, ef);
            SendAll(cached_fds[i], ef, sizeof ef);
          }
        }
        off += kHeartbeatFrameBytes;
      }
      if (off > 0)
        bufs[i].erase(bufs[i].begin(),
                      bufs[i].begin() + static_cast<long>(off));
    }
  }
}

bool Engine::CheckHeartbeatLocalAbort() {
  if (!hb_local_abort_.load()) return false;
  std::string msg;
  {
    std::lock_guard<std::mutex> lk(hb_mu_);
    msg = hb_local_abort_msg_;
  }
  AbortLocal(ST_RANKS_DOWN, msg);
  return true;
}

void Engine::CoordinatorDrainHeartbeatDeaths() {
  if (!coord_) return;
  std::vector<int> dead;
  {
    std::lock_guard<std::mutex> lk(hb_mu_);
    if (pending_hb_dead_.empty()) return;
    dead.swap(pending_hb_dead_);
  }
  for (int r : dead) {
    if (r <= 0 || r >= opts_.size || coord_->rank_dead[r]) continue;
    hb_evictions_.fetch_add(1);
    MarkRankDead(r,
                 "no data-plane heartbeats at rank 0 for the miss window; "
                 "process frozen or link partitioned");
  }
}

bool Engine::SendHeartbeatReports(int fd) {
  std::vector<int> reports;
  {
    std::lock_guard<std::mutex> lk(hb_mu_);
    if (pending_hb_report_.empty()) return true;
    reports.swap(pending_hb_report_);
  }
  if (fd < 0) return true;
  RequestList rl;
  rl.membership_epoch = membership_epoch_.load();
  rl.hb_report = true;
  for (int r : reports) {
    rl.dead_ranks.push_back(r);
    if (flight_.Enabled()) flight_.Record(FL_HEARTBEAT_MISS, "report", r);
  }
  if (!SendFrame(fd, SerializeRequestList(rl))) return false;
  ctrl_frames_sent_.fetch_add(1);
  return true;
}

bool Engine::WaitParentSliced(int fd, double total_sec) {
  // total_sec < 0 means "no deadline" (collective timeout disabled).
  if (hb_interval_ms_ <= 0) {
    if (total_sec < 0) {
      while (!WaitReadable(fd, 3600.0)) {
      }
      return true;
    }
    return WaitReadable(fd, total_sec);
  }
  // Slice the blocking parent wait so the heartbeat escalation stays
  // live inside it: pending reports go up (the coordinator handles
  // out-of-band hb_report frames at any point in the alternation) and a
  // monitor-latched local abort breaks the wait immediately.
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(total_sec);
  for (;;) {
    if (hb_local_abort_.load()) return false;
    SendHeartbeatReports(fd);
    double left =
        total_sec < 0 ? 0.05
                      : std::chrono::duration<double>(
                            deadline - std::chrono::steady_clock::now())
                            .count();
    if (left <= 0.0) return false;
    if (WaitReadable(fd, std::min(0.05, left))) return true;
  }
}

std::string Engine::LivenessInfo() {
  std::string out = std::to_string(hb_interval_ms_) + "|" +
                    std::to_string(hb_miss_limit_) + "|" +
                    std::to_string(hb_sent_.load()) + "|" +
                    std::to_string(hb_recv_.load()) + "|" +
                    std::to_string(hb_miss_events_.load()) + "|" +
                    std::to_string(hb_evictions_.load()) + "|" +
                    std::to_string(clock_fanin_.load()) + "|";
  std::lock_guard<std::mutex> lk(hb_mu_);
  std::vector<int> peers;
  for (const auto& kv : hb_last_seen_us_) peers.push_back(kv.first);
  std::sort(peers.begin(), peers.end());
  int64_t now = EpochNowUs();
  bool first = true;
  for (int p : peers) {
    if (!first) out += ' ';
    first = false;
    auto mit = hb_miss_counts_.find(p);
    out += std::to_string(p) + ":" +
           std::to_string(now - hb_last_seen_us_[p]) + ":" +
           std::to_string(mit == hb_miss_counts_.end() ? 0 : mit->second);
  }
  return out;
}

namespace {
// Verdict-kind names; index = the `kind` stored in AnomalyVerdict and
// the arg carried by the FL_ANOMALY flight event.
const char* const kAnomalyKinds[] = {"slow_link", "straggler",
                                     "cache_degraded", "slow_phase"};

double RobustMedian(std::vector<double> v) {
  if (v.empty()) return 0.0;
  std::sort(v.begin(), v.end());
  size_t n = v.size();
  return n % 2 ? v[n / 2] : 0.5 * (v[n / 2 - 1] + v[n / 2]);
}

double RobustMad(const std::vector<double>& v, double med) {
  std::vector<double> devs;
  devs.reserve(v.size());
  for (double x : v) devs.push_back(std::fabs(x - med));
  return RobustMedian(std::move(devs));
}
}  // namespace

std::string Engine::LinkInfo() { return NetLinkInfo(); }

void Engine::EmitAnomaly(int kind, const std::string& subject,
                         const std::string& detail) {
  std::string label = kAnomalyKinds[kind];
  if (!subject.empty()) label += "(" + subject + ")";
  {
    std::lock_guard<std::mutex> lk(anomaly_mu_);
    ++anomaly_counts_[kind];
    anomaly_log_.push_back({EpochNowUs(), kind, subject, detail});
    while (anomaly_log_.size() > 64) anomaly_log_.pop_front();
  }
  if (flight_.Enabled()) flight_.Record(FL_ANOMALY, label, kind);
  timeline_.Instant("hvd_anomaly", label);
}

std::string Engine::AnomalyInfo() {
  std::lock_guard<std::mutex> lk(anomaly_mu_);
  std::string out = std::to_string(anomaly_sigma_) + "|" +
                    std::to_string(anomaly_interval_ms_);
  for (int i = 0; i < 4; ++i)
    out += "|" + std::to_string(anomaly_counts_[i]);
  return out;
}

std::string Engine::AnomalyLog() {
  int64_t now = EpochNowUs();
  std::lock_guard<std::mutex> lk(anomaly_mu_);
  std::string out;
  for (const auto& v : anomaly_log_) {
    if (!out.empty()) out += ';';
    std::string subj, det;
    for (char c : v.subject) subj += (c == ';' || c == '|') ? '_' : c;
    for (char c : v.detail) det += (c == ';' || c == '|') ? '_' : c;
    out += std::string(kAnomalyKinds[v.kind]) + "|" + subj + "|" + det +
           "|" + std::to_string(now - v.ts_us);
  }
  return out;
}

void Engine::AnomalyLoop() {
  // Detector thread contract: all sweep state (windows, baselines,
  // episode flags) is thread-local to this function; the only shared
  // surfaces are atomics, the net.h link accessor, announce_mu_, and the
  // verdict sink (EmitAnomaly).  One verdict per episode: a flagged
  // subject re-arms only after a clean sweep.
  const int kSustain = 3;  // consecutive excursion sweeps before a verdict
  const double sigma = anomaly_sigma_;
  std::map<int, std::deque<double>> link_win;
  std::map<int, long long> link_sum, link_cnt;
  std::map<int, int> link_hot;
  std::set<int> link_flagged;
  std::vector<int64_t> ann_last;
  std::vector<int> ann_hot;
  std::set<int> ann_flagged;
  int64_t cache_hits_last = cache_hits_.load();
  int64_t cache_misses_last = cache_misses_.load();
  std::deque<double> cache_win;
  int cache_hot = 0;
  bool cache_flagged = false;
  const char* phase_names[3] = {"local_rs", "cross", "local_ag"};
  std::atomic<int64_t>* phase_src[3] = {&topo_rs_us_, &topo_cross_us_,
                                        &topo_ag_us_};
  int64_t phase_last[3] = {phase_src[0]->load(), phase_src[1]->load(),
                           phase_src[2]->load()};
  int64_t phase_ops_last = topo_timed_ops_.load();
  std::deque<double> phase_win[3];
  int phase_hot[3] = {0, 0, 0};
  bool phase_flagged[3] = {false, false, false};

  while (!anomaly_stop_.load()) {
    // Sliced nap: shutdown joins within ~10ms regardless of interval.
    for (int slept = 0;
         slept < anomaly_interval_ms_ && !anomaly_stop_.load(); slept += 10)
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
    if (anomaly_stop_.load()) break;
    if (!initialized_.load()) continue;
    const int me = cur_rank_.load();

    // --- slow_link: CROSS-SECTIONAL robust baseline.  Each link's level
    // (median of its per-sweep delta-mean timed-send latencies) is
    // compared against the median + MAD across ALL this rank's links —
    // never against its own history — so a link that has been slow since
    // init (a chaos delay clause with no @after, or a genuinely bad DCN
    // route) still stands out.  Needs >= 3 links for the median to pin
    // the healthy level; a 2-link rank cannot localize anyway.
    for (const auto& lt : NetLinkLatencyTotals()) {
      long long dsum = lt.sum_us - link_sum[lt.peer];
      long long dcnt = lt.count - link_cnt[lt.peer];
      link_sum[lt.peer] = lt.sum_us;
      link_cnt[lt.peer] = lt.count;
      if (dcnt <= 0) continue;  // idle sweep: window keeps its level
      auto& w = link_win[lt.peer];
      w.push_back(static_cast<double>(dsum) / static_cast<double>(dcnt));
      while (w.size() > 16) w.pop_front();
    }
    std::vector<std::pair<int, double>> levels;
    for (const auto& kv : link_win)
      if (kv.second.size() >= 3)
        levels.emplace_back(
            kv.first, RobustMedian(std::vector<double>(kv.second.begin(),
                                                       kv.second.end())));
    if (levels.size() >= 3) {
      std::vector<double> ls;
      ls.reserve(levels.size());
      for (const auto& p : levels) ls.push_back(p.second);
      double med = RobustMedian(ls);
      // 200µs floor under the MAD: loopback/veth sends jitter by tens of
      // µs, and a near-zero MAD would turn that into false verdicts.
      double scale = std::max(RobustMad(ls, med), 200.0);
      for (const auto& p : levels) {
        bool hot = (p.second - med) / scale > sigma;
        int& streak = link_hot[p.first];
        streak = hot ? streak + 1 : 0;
        if (!hot) link_flagged.erase(p.first);
        if (streak >= kSustain && !link_flagged.count(p.first)) {
          link_flagged.insert(p.first);
          char det[128];
          snprintf(det, sizeof det,
                   "timed-send level %.0fus vs cross-link median %.0fus",
                   p.second, med);
          int lo = std::min(me, p.first), hi = std::max(me, p.first);
          EmitAnomaly(0, std::to_string(lo) + "-" + std::to_string(hi),
                      det);
        }
      }
    }

    // --- straggler (rank 0): a rank closing >= 75% of a sweep's
    // negotiations (the coordinator's exact last-to-announce counts)
    // across kSustain busy sweeps is the straggler — share-based rather
    // than sigma-based because with one bad rank the "population" of
    // closers is degenerate (median = the straggler).
    if (me == 0) {
      std::vector<int64_t> counts;
      {
        std::lock_guard<std::mutex> lk(announce_mu_);
        counts = last_announce_counts_;
      }
      if (ann_last.size() != counts.size()) {
        ann_last.assign(counts.size(), 0);
        ann_hot.assign(counts.size(), 0);
        ann_flagged.clear();
      }
      int64_t total = 0;
      std::vector<int64_t> delta(counts.size(), 0);
      for (size_t r = 0; r < counts.size(); ++r) {
        delta[r] = counts[r] - ann_last[r];
        total += delta[r];
        ann_last[r] = counts[r];
      }
      if (total >= 16) {
        for (size_t r = 0; r < counts.size(); ++r) {
          bool hot = delta[r] * 4 >= total * 3;
          ann_hot[r] = hot ? ann_hot[r] + 1 : 0;
          if (!hot) ann_flagged.erase(static_cast<int>(r));
          if (ann_hot[r] >= kSustain &&
              !ann_flagged.count(static_cast<int>(r))) {
            ann_flagged.insert(static_cast<int>(r));
            char det[96];
            snprintf(det, sizeof det,
                     "last to announce in %lld of %lld negotiations",
                     static_cast<long long>(delta[r]),
                     static_cast<long long>(total));
            EmitAnomaly(1, std::to_string(r), det);
          }
        }
      }
    }

    // --- cache_degraded: TEMPORAL baseline on the per-sweep hit rate
    // (degradation over time is the failure mode; the cold-start climb
    // can never fire it — early sweeps sit below no baseline).
    {
      int64_t h = cache_hits_.load(), m = cache_misses_.load();
      int64_t dh = h - cache_hits_last, dm = m - cache_misses_last;
      cache_hits_last = h;
      cache_misses_last = m;
      if (dh + dm >= 16) {
        double rate =
            static_cast<double>(dh) / static_cast<double>(dh + dm);
        if (cache_win.size() >= 6) {
          std::vector<double> v(cache_win.begin(), cache_win.end());
          double med = RobustMedian(v);
          bool hot =
              (med - rate) / std::max(RobustMad(v, med), 0.02) > sigma;
          cache_hot = hot ? cache_hot + 1 : 0;
          if (!hot) cache_flagged = false;
          if (cache_hot >= kSustain && !cache_flagged) {
            cache_flagged = true;
            char det[96];
            snprintf(det, sizeof det, "hit rate %.2f vs baseline %.2f",
                     rate, med);
            EmitAnomaly(2, "", det);
          }
        }
        cache_win.push_back(rate);
        while (cache_win.size() > 32) cache_win.pop_front();
      }
    }

    // --- slow_phase: temporal baselines on the two-level topology's
    // per-phase mean times (local reduce-scatter / cross-node / local
    // allgather) — localizes "the DCN hop got slow" separately from any
    // single link verdict.
    {
      int64_t ops = topo_timed_ops_.load();
      int64_t dops = ops - phase_ops_last;
      phase_ops_last = ops;
      for (int p = 0; p < 3; ++p) {
        int64_t s = phase_src[p]->load();
        int64_t ds = s - phase_last[p];
        phase_last[p] = s;
        if (dops <= 0) continue;
        double mean = static_cast<double>(ds) / static_cast<double>(dops);
        if (phase_win[p].size() >= 6) {
          std::vector<double> v(phase_win[p].begin(), phase_win[p].end());
          double med = RobustMedian(v);
          bool hot =
              (mean - med) / std::max(RobustMad(v, med), 100.0) > sigma;
          phase_hot[p] = hot ? phase_hot[p] + 1 : 0;
          if (!hot) phase_flagged[p] = false;
          if (phase_hot[p] >= kSustain && !phase_flagged[p]) {
            phase_flagged[p] = true;
            char det[96];
            snprintf(det, sizeof det,
                     "phase mean %.0fus vs baseline %.0fus", mean, med);
            EmitAnomaly(3, phase_names[p], det);
          }
        }
        phase_win[p].push_back(mean);
        while (phase_win[p].size() > 32) phase_win[p].pop_front();
      }
    }
  }
}

void Engine::BackgroundLoop() {
  while (RunLoopOnce()) {
  }
  // Drain: fail everything still pending so blocked Wait() calls return
  // (the reference's SHUT_DOWN_ERROR drain on loop exit,
  // operations.cc:1446-1461).  loop_exited_ flips under mu_ so a racing
  // Enqueue either lands before the drain (and is failed here) or observes
  // the flag and is rejected.
  std::vector<TableEntry> leftovers;
  {
    std::lock_guard<std::mutex> lk(mu_);
    loop_exited_.store(true);
    for (auto& kv : table_) leftovers.push_back(kv.second);
    table_.clear();
    queue_.clear();
  }
  // A coordinated abort poisons the drain with its structured status
  // (ST_RANKS_DOWN / ST_TIMEOUT naming the missing ranks or stalled
  // tensors); a clean shutdown keeps the generic ST_ABORTED message.
  int32_t code = abort_code_.load();
  std::string msg;
  if (code != 0) {
    std::lock_guard<std::mutex> lk(abort_mu_);
    msg = abort_message_;
  } else {
    code = ST_ABORTED;
    msg = "Horovod-TPU has been shut down. This was caused by an "
          "exception on one of the ranks or an earlier shutdown.";
  }
  for (auto& e : leftovers) CompleteEntry(e, code, msg);
  // Post-mortem traces must survive even if the process exits without
  // reaching Shutdown() (docs/timeline.md): the loop exit — abort paths
  // included — leaves the file parseable on disk.
  timeline_.Flush();
}

int64_t Engine::Enqueue(uint8_t op, const std::string& name, const void* in,
                        void* out, const std::vector<int64_t>& dims,
                        uint8_t dtype, int root_rank, bool average, int peer,
                        int tag, const std::vector<int32_t>& stage_ranks) {
  if (!initialized_.load()) return -1;
  auto status = std::make_shared<HandleStatus>();
  int64_t handle = next_handle_.fetch_add(1);
  {
    std::lock_guard<std::mutex> lk(handles_mu_);
    handles_[handle] = status;
  }
  // Preconditions the coordinator could only report a tick later: a p2p
  // op needs a real counterpart, and stage groups only scope allreduce.
  if (op == OP_SEND || op == OP_RECV) {
    if (peer < 0 || peer >= size() || peer == rank()) {
      status->error = std::string(OpName(op)) + " '" + name +
                      "' names peer rank " + std::to_string(peer) +
                      ", which is not another rank of this " +
                      std::to_string(size()) + "-rank job.";
      status->code.store(ST_PRECONDITION);
      return handle;
    }
  } else if (!stage_ranks.empty()) {
    bool member = false;
    bool in_range = true;
    for (int32_t m : stage_ranks) {
      if (m == rank()) member = true;
      if (m < 0 || m >= size()) in_range = false;
    }
    if (op != OP_ALLREDUCE || !member || !in_range ||
        stage_ranks.size() < 2) {
      status->error =
          "stage-group collectives support allreduce among >= 2 valid "
          "member ranks including the caller; '" +
          name + "' violates that (op " + OpName(op) + ", " +
          std::to_string(stage_ranks.size()) + " members).";
      status->code.store(ST_PRECONDITION);
      return handle;
    }
  }
  TableEntry e;
  e.name = name;
  e.op = op;
  e.dtype = dtype;
  e.dims = dims;
  e.in = in;
  e.out = out;
  e.root_rank = root_rank;
  e.average = average;
  e.p2p_peer = peer;
  e.p2p_tag = tag;
  e.stage_ranks = stage_ranks;
  e.handle = handle;
  e.enqueued_at = std::chrono::steady_clock::now();
  {
    std::lock_guard<std::mutex> lk(mu_);
    // Failure paths need no notify: the handle has not been returned to
    // the caller yet, so no waiter can exist; Wait's predicate check sees
    // the already-flipped (atomic) code.
    if (loop_exited_.load()) {
      int32_t code = abort_code_.load();
      if (code != 0) {
        std::lock_guard<std::mutex> alk(abort_mu_);
        status->error = abort_message_;
      } else {
        code = ST_ABORTED;
        status->error =
            "Horovod-TPU has been shut down; no further collectives can run.";
      }
      status->code.store(code);
      return handle;
    }
    if (reshape_ack_pending_.load()) {
      // Elastic reshape not yet acknowledged: fail fast with the
      // retryable status instead of letting this op stall a negotiation
      // its peers are not running (they are resyncing state).  Checked
      // under mu_ so it pairs exactly with ApplyReshape's drain.
      std::lock_guard<std::mutex> mlk(membership_mu_);
      status->error = reshape_message_;
      status->code.store(ST_RESHAPE);
      return handle;
    }
    if (table_.count(name)) {
      // Same duplicate-name precondition as the reference enqueue
      // (operations.cc:1827-1833).
      status->error = "A collective with name '" + name +
                      "' is already in progress; names must be unique per "
                      "outstanding operation.";
      status->code.store(ST_PRECONDITION);
      return handle;
    }
    table_.emplace(name, std::move(e));
    if (flight_.Enabled()) flight_.Record(FL_ENQUEUE, name, handle);
    Request req;
    req.rank = opts_.rank;
    req.op = op;
    req.dtype = dtype;
    req.root_rank = root_rank;
    req.name = name;
    req.dims = dims;
    req.p2p_peer = peer;
    req.p2p_tag = tag;
    req.stage_ranks = stage_ranks;
    queue_.push_back(std::move(req));
  }
  // Wake a steady-state idle wait (no-op otherwise: nothing waits on
  // this cv while the per-tick frame protocol paces the loop).
  queue_cv_.notify_one();
  return handle;
}

// ---------------------------------------------------------------------------
// Negotiation tick.
// ---------------------------------------------------------------------------

namespace {

// Per-aggregate slot -> bit_groups index, threaded through the merge so
// the per-tick fold stays linear in announced bits (a plain scan made
// the sub-coordinator's fold quadratic in distinct slots — wasted work
// on exactly the path this tree exists to flatten).
using SlotIndex = std::unordered_map<uint32_t, size_t>;

// Fold one rank's cache-bit announcement into an aggregate's per-slot
// groups (docs/performance.md#control-plane-scaling).
void AddBitToGroups(RequestList* agg, SlotIndex* idx, uint32_t slot,
                    int rank, int64_t ts) {
  auto it = idx->find(slot);
  if (it == idx->end())
    it = idx->emplace(slot, agg->bit_groups.size()).first;
  if (it->second == agg->bit_groups.size()) {
    BitGroup g;
    g.slot = slot;
    agg->bit_groups.push_back(std::move(g));
  }
  BitGroup& g = agg->bit_groups[it->second];
  g.ranks.push_back(rank);
  g.announce_us.push_back(ts);
}

// Fold one per-rank frame (the sub-coordinator's own, or a leaf child's)
// into the aggregate forwarded to rank 0.  `ts` is the announce time on
// rank 0's clock for entries that carry none of their own.
void MergeFrameIntoAggregate(const RequestList& frame, int rank, int64_t ts,
                             RequestList* agg, SlotIndex* idx) {
  agg->shutdown = agg->shutdown || frame.shutdown;
  for (size_t i = 0; i < frame.requests.size(); ++i) {
    agg->requests.push_back(frame.requests[i]);
    agg->announce_us.push_back(
        i < frame.announce_us.size() && frame.announce_us[i] >= 0
            ? frame.announce_us[i]
            : ts);
  }
  for (uint32_t bit : frame.cache_bits)
    AddBitToGroups(agg, idx, bit, rank, ts);
  for (const auto& g : frame.bit_groups)
    for (size_t j = 0; j < g.ranks.size(); ++j)
      AddBitToGroups(agg, idx, g.slot, g.ranks[j],
                     j < g.announce_us.size() ? g.announce_us[j] : ts);
  agg->frames_from.push_back(rank);
  for (int32_t r : frame.frames_from) agg->frames_from.push_back(r);
  for (int32_t r : frame.dead_ranks) agg->dead_ranks.push_back(r);
  if (frame.steady_exit) agg->steady_exits.push_back(rank);
  for (int32_t r : frame.steady_exits) agg->steady_exits.push_back(r);
}

}  // namespace

bool Engine::RunLoopOnce() {
  auto tick_start = std::chrono::steady_clock::now();

  // Monitor-latched partition verdict: surface it before anything else
  // touches a socket this pass.
  if (CheckHeartbeatLocalAbort()) return false;

  // Reclaim the fusion buffer after a sustained idle stretch (it
  // previously only ever grew, pinning its high-water mark for the life
  // of the process): a burst of big fused allreduces no longer holds tens
  // of MB through hours of, say, evaluation-only phases.
  if (!fusion_buffer_.empty() &&
      tick_start - last_fusion_use_ > std::chrono::seconds(10)) {
    std::vector<char>().swap(fusion_buffer_);
  }

  // Decentralized steady state (docs/performance.md
  // #control-plane-scaling): the control plane is dark; replay the
  // broadcast pattern self-clocked with zero frames per cycle.
  if (steady_active_.load()) return SteadyLoopOnce();

  RequestList my_requests;
  my_requests.shutdown = shut_down_.load();
  // Frames are epoch-stamped so the coordinator can reject one built
  // against a previous membership (wire.h RequestList.membership_epoch).
  my_requests.membership_epoch = membership_epoch_.load();
  if (steady_exit_pending_) {
    // First frame after a steady exit carries the fallback marker (and
    // the miss position, for postmortem dumps): rank 0 resumes
    // broadcasting only once every rank has sent one of these.
    my_requests.steady_exit = 1;
    my_requests.steady_epoch = steady_exit_epoch_;
    my_requests.steady_pos = steady_exit_pos_;
    // hvdlint: lockstep-ok(one-shot send latch set by ExitSteadyLocal)
    steady_exit_pending_ = false;
  }
  {
    std::lock_guard<std::mutex> lk(mu_);
    while (!queue_.empty()) {
      Request req = std::move(queue_.front());
      queue_.pop_front();
      // Response-cache fast path: a signature-identical repeat announces
      // its slot index; everything else (first occurrence, or a changed
      // shape/dtype/root — the fallback that keeps the PR-2 mismatch
      // validation live) goes out as a full string request.
      int slot = cache_.enabled() ? cache_.Lookup(req) : -1;
      if (slot >= 0) {
        my_requests.cache_bits.push_back(static_cast<uint32_t>(slot));
        cache_hits_.fetch_add(1);
        if (flight_.Enabled()) flight_.Record(FL_CACHE_HIT, req.name, slot);
      } else {
        if (cache_.enabled()) cache_misses_.fetch_add(1);
        if (flight_.Enabled()) flight_.Record(FL_ANNOUNCE, req.name, 0);
        my_requests.requests.push_back(std::move(req));
      }
    }
  }

  ResponseList responses;
  if (opts_.rank == 0) {
    // Coordinator (covers the single-process case too: the worker loop
    // and broadcast below are empty at size 1, but joiner admission and
    // reshape barriers must still run — a job shrunk to one rank keeps
    // accepting standbys).
    CoordinatorAcceptJoiners();
    CoordinatorDrainHeartbeatDeaths();
    coord_->shutdown_requested |= my_requests.shutdown;
    if (my_requests.steady_exit) NoteSteadyExit(0);
    CoordinatorHandle(my_requests, 0);
    if (coord_->steady) {
      // Post-steady holding pattern: some ranks may still be
      // self-clocking with their control sockets dark, so (a) expect no
      // per-tick frames — poll instead of the blocking liveness recv,
      // and (b) broadcast NOTHING (beyond abort/shutdown, which the poll
      // handles) until every rank has fallen back, or ranks still
      // replaying would double-execute the ops a broadcast list carries.
      if (!CoordinatorSteadyPoll()) return false;
      {
        int rv = MaybeRevokeSteadyForReshape();
        if (rv < 0) return false;
        if (rv > 0) return true;  // revoked: next pass is a normal tick
      }
      if (!AllSteadyExited()) {
        UpdateCoordPendingInfo();
        std::this_thread::sleep_for(std::chrono::microseconds(200));
        return true;
      }
      coord_->steady = false;
      coord_->steady_revoke_next = true;
      coord_->slot_history.clear();
      // Fall through: THIS pass builds and broadcasts the resume list —
      // frames already polled above, so skip the per-child recv loop.
    } else {
      // One frame per live child per tick, collected in ARRIVAL order:
      // a frozen child must not head-of-line-block its healthy siblings,
      // whose frames (and heartbeat reports) are exactly what lets the
      // sweep mark the frozen one dead in O(heartbeat) rather than its
      // own O(collective-timeout) deadline.
      //
      // Liveness: a healthy child's engine thread sends a frame every
      // cycle (~5ms), so with a hard deadline configured, a deadline of
      // control-plane silence means the child PROCESS is frozen
      // (SIGSTOP, OOM thrash) or partitioned — a state socket EOF never
      // reports.  A healthy sub-coordinator may itself block up to one
      // deadline probing a frozen LEAF before its aggregate (naming the
      // true dead rank) goes out — it gets the same widened bound the
      // workers give the coordinator, or rank 0 would misattribute a
      // leaf freeze to the whole node.
      std::vector<int> waiting;
      for (int r : coord_children_)
        if (!coord_->rank_dead[r]) waiting.push_back(r);
      auto sweep_start = std::chrono::steady_clock::now();
      const double T = opts_.collective_timeout_sec;
      while (!waiting.empty()) {
        CoordinatorDrainHeartbeatDeaths();
        for (size_t i = 0; i < waiting.size();)
          if (coord_->rank_dead[waiting[i]])
            waiting.erase(waiting.begin() + i);
          else
            ++i;
        bool progressed = false;
        for (size_t i = 0; i < waiting.size();) {
          int r = waiting[i];
          int fd = coord_fds_[r];
          bool sub_lead = tree_enabled_ && r >= opts_.local_size;
          bool consumed_tick = false, lost = false;
          while (WaitReadable(fd, 0.0)) {
            std::vector<uint8_t> buf;
            if (!RecvFrame(fd, &buf)) {
              // A child died (control-socket EOF): escalate to a
              // coordinated ABORT naming the missing rank and the
              // tensors it left pending (sharpens the reference's
              // SHUT_DOWN_ERROR path, operations.cc:1579-1605, into a
              // structured status).
              MarkRankDead(r, sub_lead
                                  ? "sub-coordinator connection lost "
                                    "(its node is unreachable)"
                                  : "connection lost at the coordinator");
              lost = true;
              break;
            }
            ctrl_frames_recv_.fetch_add(1);
            RequestList rl;
            if (!ParseRequestList(buf, &rl)) continue;
            coord_->last_frame_tick[r] = ticks_done_.load();
            coord_->shutdown_requested |= rl.shutdown;
            CoordinatorHandle(rl, r);
            // Out-of-band heartbeat reports ride BETWEEN tick frames
            // (wire.h RequestList.hb_report); keep waiting for the
            // child's real frame.
            if (rl.hb_report) continue;
            consumed_tick = true;
            break;
          }
          if (consumed_tick || lost) {
            waiting.erase(waiting.begin() + i);
            progressed = true;
          } else {
            ++i;
          }
        }
        if (waiting.empty() || progressed) continue;
        double waited = std::chrono::duration<double>(
                            std::chrono::steady_clock::now() - sweep_start)
                            .count();
        if (T > 0) {
          bool timed_out = false;
          for (size_t i = 0; i < waiting.size();) {
            int r = waiting[i];
            bool sub_lead = tree_enabled_ && r >= opts_.local_size;
            if (waited > (sub_lead ? 2 * T + 5.0 : T)) {
              char why[112];
              snprintf(why, sizeof(why),
                       "no control-plane traffic for %.0fs; %s frozen or "
                       "network partitioned",
                       T, sub_lead ? "sub-coordinator" : "process");
              MarkRankDead(r, why);
              waiting.erase(waiting.begin() + i);
              timed_out = true;
            } else {
              ++i;
            }
          }
          if (timed_out) continue;
        }
        // Nothing ready and nobody over deadline: block on the first
        // straggler, sliced so heartbeat deaths and other children's
        // frames keep getting service.
        double slice = hb_interval_ms_ > 0 ? 0.05 : 1.0;
        if (T > 0) {
          bool first_sub =
              tree_enabled_ && waiting[0] >= opts_.local_size;
          double left = (first_sub ? 2 * T + 5.0 : T) - waited;
          if (left < slice) slice = std::max(left, 0.001);
        }
        WaitReadable(coord_fds_[waiting[0]], slice);
      }
    }
    CheckCollectiveTimeout();
    responses = CoordinatorTick();
    AttachTunedParams(&responses);
    CoordinatorMaybeReshape(&responses);
    CoordinatorMaybeSteady(&responses);
    if (coord_->steady_revoke_next && responses.abort_code == 0) {
      responses.steady_revoke = true;
      coord_->steady_revoke_next = false;
    }
    UpdateCoordPendingInfo();
    if (opts_.size > 1 || responses.reshape_present) {
      std::vector<uint8_t> out = SerializeResponseList(responses);
      for (int r : coord_children_) {
        if (coord_->rank_dead[r]) continue;
        if (SendFrame(coord_fds_[r], out)) ctrl_frames_sent_.fetch_add(1);
      }
      // Admitted standbys receive the same barrier frame over the control
      // socket they registered on; ApplyReshape below then folds their
      // fds into the coordinator star.
      if (responses.reshape_present)
        for (int fd : coord_->pending_join_fds) SendFrame(fd, out);
    }
  } else if (is_sub_coord_) {
    if (sub_holding_) {
      // Between this sub-coordinator's own steady exit and the next
      // parent broadcast: children may still be self-clocking, so never
      // block on them — forward own announcements upward as they drain,
      // keep relaying children's fallback frames, and let SubRelayPass
      // consume the resume broadcast.
      if (!my_requests.requests.empty() || !my_requests.cache_bits.empty() ||
          my_requests.steady_exit || my_requests.shutdown) {
        RequestList agg;
        SlotIndex idx;
        agg.membership_epoch = membership_epoch_.load();
        MergeFrameIntoAggregate(my_requests, opts_.rank,
                                EpochNowUs() - clock_offset_us_.load(),
                                &agg, &idx);
        if (!SendFrame(coord_fd_, SerializeRequestList(agg))) {
          AbortLocal(ST_RANKS_DOWN,
                     "ranks down: 0 (coordinator connection lost); this "
                     "job cannot continue and should be restarted.");
          return false;
        }
        ctrl_frames_sent_.fetch_add(1);
      }
      if (!SubRelayPass()) return false;
      std::this_thread::sleep_for(std::chrono::microseconds(200));
      return true;
    }
    // Strict per-tick aggregation: one frame from each live child, one
    // aggregate up, one broadcast down (relayed raw before local
    // processing — the sub's own data-plane execution blocks on its
    // children's participation).
    RequestList agg;
    SlotIndex idx;
    agg.membership_epoch = membership_epoch_.load();
    MergeFrameIntoAggregate(my_requests, opts_.rank,
                            EpochNowUs() - clock_offset_us_.load(), &agg,
                            &idx);
    // This sub's own monitor flags ride up inside the aggregate's
    // dead_ranks (the pending_dead_reports_ flush below), exactly like a
    // child EOF it observed.
    {
      std::lock_guard<std::mutex> lk(hb_mu_);
      for (int r : pending_hb_report_) pending_dead_reports_.push_back(r);
      pending_hb_report_.clear();
    }
    for (size_t i = 0; i < tree_child_fds_.size(); ++i) {
      if (tree_child_dead_[i]) continue;
      int fd = tree_child_fds_[i];
      int crank = tree_child_ranks_[i];
      // Sliced child wait: a child's out-of-band heartbeat report must
      // relay upward (and this sub's own local-abort latch must fire)
      // without waiting out a frozen leaf's full deadline.
      auto child_start = std::chrono::steady_clock::now();
      const double T = opts_.collective_timeout_sec;
      for (;;) {
        if (WaitReadable(fd, hb_interval_ms_ > 0 ? 0.05 : (T > 0 ? T : 1.0))) {
          std::vector<uint8_t> buf;
          if (!RecvFrame(fd, &buf)) {
            tree_child_dead_[i] = true;
            agg.dead_ranks.push_back(crank);
            break;
          }
          ctrl_frames_recv_.fetch_add(1);
          RequestList child;
          if (!ParseRequestList(buf, &child)) continue;
          if (child.hb_report) {
            // Relay the report's dead_ranks in this tick's aggregate and
            // keep waiting for the child's real frame.
            for (int32_t r : child.dead_ranks) agg.dead_ranks.push_back(r);
            continue;
          }
          NoteChildSteadyExit(child, crank);
          MergeFrameIntoAggregate(child, crank,
                                  EpochNowUs() - clock_offset_us_.load(),
                                  &agg, &idx);
          break;
        }
        if (hb_local_abort_.load()) break;  // surfaced next pass
        double waited = std::chrono::duration<double>(
                            std::chrono::steady_clock::now() - child_start)
                            .count();
        if (T > 0 && waited > T) {
          tree_child_dead_[i] = true;
          agg.dead_ranks.push_back(crank);
          break;
        }
      }
    }
    for (int32_t r : pending_dead_reports_) agg.dead_ranks.push_back(r);
    pending_dead_reports_.clear();
    if (!SendFrame(coord_fd_, SerializeRequestList(agg))) {
      responses.abort_code = ST_RANKS_DOWN;
      responses.abort_message =
          "ranks down: 0 (coordinator connection lost); this job cannot "
          "continue and should be restarted.";
    } else {
      ctrl_frames_sent_.fetch_add(1);
      bool alive = opts_.collective_timeout_sec <= 0
                       ? WaitParentSliced(coord_fd_, -1.0)
                       : WaitParentSliced(coord_fd_, ParentWaitSec());
      std::vector<uint8_t> buf;
      if (!alive) {
        if (CheckHeartbeatLocalAbort()) return false;
        responses.abort_code = ST_RANKS_DOWN;
        responses.abort_message =
            "ranks down: 0 (coordinator unresponsive: no control-plane "
            "traffic within the deadline; process frozen or network "
            "partitioned); this job cannot continue and should be "
            "restarted.";
      } else if (!RecvFrame(coord_fd_, &buf) ||
                 !ParseResponseList(buf, &responses)) {
        if (CheckHeartbeatLocalAbort()) return false;
        responses.abort_code = ST_RANKS_DOWN;
        responses.abort_message =
            "ranks down: 0 (coordinator connection lost); this job cannot "
            "continue and should be restarted.";
      } else {
        ctrl_frames_recv_.fetch_add(1);
        for (size_t i = 0; i < tree_child_fds_.size(); ++i)
          if (!tree_child_dead_[i] && SendFrame(tree_child_fds_[i], buf))
            ctrl_frames_sent_.fetch_add(1);
      }
    }
  } else {
    // Out-of-band heartbeat reports ride ahead of this tick's frame, so
    // the send-one-wait-one alternation with the coordinator holds.
    SendHeartbeatReports(coord_fd_);
    if (!SendFrame(coord_fd_, SerializeRequestList(my_requests))) {
      if (CheckHeartbeatLocalAbort()) return false;
      responses.abort_code = ST_RANKS_DOWN;
      responses.abort_message =
          "ranks down: 0 (coordinator connection lost); this job cannot "
          "continue and should be restarted.";
    } else {
      ctrl_frames_sent_.fetch_add(1);
      // Bound the response wait too: 2x the deadline plus slack, because
      // a healthy coordinator may itself block up to one deadline probing
      // a frozen THIRD rank before it aborts and responds.
      bool alive = opts_.collective_timeout_sec <= 0
                       ? WaitParentSliced(coord_fd_, -1.0)
                       : WaitParentSliced(coord_fd_, ParentWaitSec());
      std::vector<uint8_t> buf;
      if (!alive) {
        if (CheckHeartbeatLocalAbort()) return false;
        responses.abort_code = ST_RANKS_DOWN;
        responses.abort_message =
            "ranks down: 0 (coordinator unresponsive: no control-plane "
            "traffic within the deadline; process frozen or network "
            "partitioned); this job cannot continue and should be "
            "restarted.";
      } else if (!RecvFrame(coord_fd_, &buf) ||
                 !ParseResponseList(buf, &responses)) {
        if (CheckHeartbeatLocalAbort()) return false;
        responses.abort_code = ST_RANKS_DOWN;
        responses.abort_message =
            "ranks down: 0 (coordinator connection lost); this job cannot "
            "continue and should be restarted.";
      } else {
        ctrl_frames_recv_.fetch_add(1);
      }
    }
  }
  return ProcessResponseList(responses, my_requests, tick_start);
}

bool Engine::ProcessResponseList(
    ResponseList& responses, const RequestList& my_requests,
    std::chrono::steady_clock::time_point tick_start) {
  // Elastic reshape barrier: the list carries no op payload (the
  // coordinator cleared it), so adopting the membership IS this tick's
  // work.  A rebuild failure latched a local abort — exit and drain.
  if (responses.reshape_present && !ApplyReshape(responses)) return false;
  // Tuned parameters apply BEFORE this tick's cache-hit replay: the
  // replay re-fuses under opts_.fusion_threshold, and every rank
  // processes this same list at this same tick index, so fusion-plan
  // changes land at one lockstep boundary instead of splitting the job
  // into old-threshold and new-threshold camps.
  if (responses.tuned_present) ApplyTunedParams(responses);
  ProcessCacheHits(responses.cache_hits);
  for (const auto& resp : responses.responses) PerformOperation(resp);
  // The response list (identical on every rank) is fully processed: close
  // the tick.  Completions stamped with tick t are all visible once
  // ticks_done_ > t, on every rank.
  ticks_done_.fetch_add(1);
  if (!responses.responses.empty() || !responses.cache_hits.empty())
    negotiated_ticks_.fetch_add(1);

  if (opts_.rank == 0) CheckForStalledTensors();

  if (responses.abort_code != 0) {
    // Coordinated abort: latch the structured status, then exit the loop;
    // the BackgroundLoop drain fails everything still pending with it.
    AbortLocal(responses.abort_code, responses.abort_message);
    return false;
  }
  if (responses.shutdown) return false;
  if (responses.steady_present) {
    // Arm self-clocked replay AFTER this list's hits replayed: every
    // rank processed the identical list, so every rank starts the
    // pattern at position 0 of the same cycle boundary.
    ApplySteady(responses);
    return true;
  }

  // Adaptive tick (docs/performance.md): with requests PENDING, the
  // fixed cycle sleep — not the negotiation itself — dominated latency
  // (a bit-vector agreement costs ~µs, the sleep ~5ms per round, and a
  // skewed multi-round negotiation paid it per ROUND on every rank).
  // While work flows — this tick announced requests or carried
  // responses — tick again immediately; the control-plane frame round
  // trip itself paces the loop.  With work outstanding but nothing
  // moving, run a bounded number of fast ticks (a multi-tick negotiation
  // finishing) before falling back to the configured cycle, so a
  // genuinely missing peer cannot spin the control plane at full speed.
  // Fully idle, take ONE cycle-length sleep (no fine-grained polling — an
  // idle fleet must not wake 5000x/s): fresh enqueues deliberately wait
  // for the cycle boundary, because the remainder of the cycle is the
  // CO-ARRIVAL window that lets an enqueue-all-then-wait group land in
  // one negotiation round and fuse into one ring pass (tests pin this).
  // HVD_TPU_CYCLE_TIME_MS therefore trades fusion window against
  // first-announce latency; once announced, rounds run at wire speed.
  const auto kPollSlice = std::chrono::microseconds(200);
  const int kMaxFastTicks = 64;
  bool flowed = !my_requests.requests.empty() ||
                !my_requests.cache_bits.empty() ||
                !responses.responses.empty() || !responses.cache_hits.empty();
  // Flight: stamp ticks that moved work (an idle fleet must not roll the
  // ring with thousands of empty ticks — the interesting final seconds
  // would be overwritten by silence).
  if (flowed && flight_.Enabled())
    flight_.Record(FL_TICK, "", ticks_done_.load());
  bool outstanding;
  {
    std::lock_guard<std::mutex> lk(mu_);
    outstanding = !queue_.empty() || !table_.empty();
  }
  if (coord_ && (opts_.rank == 0 || opts_.size == 1))
    outstanding = outstanding || !coord_->message_table.empty() ||
                  !coord_->cache_pending.empty();
  if (flowed) {
    fast_ticks_ = 0;
    return true;
  }
  if (outstanding && fast_ticks_ < kMaxFastTicks) {
    ++fast_ticks_;
    std::this_thread::sleep_for(kPollSlice);
    return true;
  }
  fast_ticks_ = 0;
  auto cycle = std::chrono::duration<double, std::milli>(opts_.cycle_time_ms);
  auto elapsed = std::chrono::steady_clock::now() - tick_start;
  if (elapsed < cycle)
    std::this_thread::sleep_for(cycle - elapsed);
  return true;
}

// ---------------------------------------------------------------------------
// Decentralized steady state (docs/performance.md#control-plane-scaling).
//
// The PR-4 response cache made repeats cheap (a few bytes per op); this
// makes them FREE: once the coordinator observes the cache-hit slot
// stream repeat an identical cycle HVD_TPU_STEADY_THRESHOLD times at
// quiesced boundaries, it broadcasts the pattern and every rank
// self-clocks on an epoch counter, replaying the stored responses with
// zero control-plane frames per cycle.  Any miss (signature change, new
// tensor, shutdown) falls back to full negotiation; the signature-change
// revocation points all flow through the normal lockstep machinery once
// every rank has fallen back.
// ---------------------------------------------------------------------------

namespace {

// Smallest period P of `w` in the sliding-window sense (w[i] == w[i-P]
// for all i >= P), via the KMP prefix function.  O(|w|).
size_t SmallestPeriod(const std::vector<uint32_t>& w) {
  if (w.empty()) return 0;
  std::vector<size_t> pi(w.size(), 0);
  for (size_t i = 1; i < w.size(); ++i) {
    size_t k = pi[i - 1];
    while (k > 0 && w[i] != w[k]) k = pi[k - 1];
    if (w[i] == w[k]) ++k;
    pi[i] = k;
  }
  return w.size() - pi.back();
}

}  // namespace

void Engine::CoordinatorMaybeSteady(ResponseList* out) {
  if (!coord_ || opts_.steady_threshold <= 0) return;
  // Any non-pure-hit broadcast resets the detector: the window must
  // contain nothing but the steady-state hit stream, so a pattern found
  // in it is a pattern of the WHOLE control plane, not a lull between
  // fresh negotiations.
  if (out->abort_code != 0 || out->shutdown || out->reshape_present ||
      out->tuned_present || !out->responses.empty()) {
    coord_->slot_history.clear();
    return;
  }
  if (out->cache_hits.empty()) return;  // idle ticks are neutral
  for (size_t i = 0; i < out->cache_hits.size(); ++i)
    coord_->slot_history.emplace_back(out->cache_hits[i], i == 0);
  const size_t cap = static_cast<size_t>(opts_.steady_threshold) *
                         static_cast<size_t>(opts_.steady_max_period) +
                     static_cast<size_t>(opts_.steady_max_period);
  while (coord_->slot_history.size() > cap) coord_->slot_history.pop_front();
  // Eligibility: a quiesced cycle boundary with every lockstep mutation
  // source at rest.  The autotune search must be frozen (a tuned-param
  // broadcast cannot reach ranks whose control sockets are dark); an
  // elastic job may arm too — a barrier arming mid-steady goes out as a
  // revocation broadcast first (MaybeRevokeSteadyForReshape), so dark
  // sockets never strand a reshape.
  if (coord_->steady || opts_.size <= 1 || !cache_.enabled() ||
      tuner_.active() || !coord_->message_table.empty() ||
      !coord_->cache_pending.empty() ||
      !coord_->pending_join_fds.empty() || !coord_->handshaking.empty() ||
      reshape_ack_pending_.load())
    return;
  std::vector<uint32_t> w;
  std::vector<bool> starts;
  w.reserve(coord_->slot_history.size());
  for (const auto& e : coord_->slot_history) {
    w.push_back(e.first);
    starts.push_back(e.second);
  }
  size_t P = SmallestPeriod(w);
  if (P == 0 || P > static_cast<size_t>(opts_.steady_max_period)) return;
  if (w.size() < static_cast<size_t>(opts_.steady_threshold) * P) return;
  // The window must END at a cycle boundary by construction (cycles are
  // periodic), and the final cycle must START at a broadcast-list
  // boundary so the observed per-tick grouping cuts cleanly into replay
  // groups.
  size_t base = w.size() - P;
  if (!starts[base]) return;
  std::vector<uint32_t> pattern(w.begin() + base, w.end());
  // Patterns that include the XLA plane's "__xp." metadata agreements
  // never arm: the plane's dispatch-ordering contract waits on tick
  // closure, which self-clocked cycles advance only per wrap — the plane
  // already has its own zero-roundtrip replay (PR-4/PR-7).
  for (uint32_t slot : pattern) {
    const CacheSlot* s = cache_.Get(static_cast<int>(slot));
    if (s == nullptr || s->name.compare(0, 5, "__xp.") == 0) return;
  }
  std::vector<uint32_t> groups;
  for (size_t i = base; i < w.size(); ++i) {
    if (starts[i])
      groups.push_back(1);
    else
      ++groups.back();
  }
  out->steady_present = true;
  out->steady_pattern = std::move(pattern);
  out->steady_groups = std::move(groups);
  coord_->steady = true;
  coord_->steady_exited.assign(opts_.size, false);
  coord_->slot_history.clear();
}

void Engine::ApplySteady(const ResponseList& rl) {
  steady_pattern_ = rl.steady_pattern;
  steady_groups_.assign(rl.steady_groups.begin(), rl.steady_groups.end());
  // Defensive: groups must tile the pattern exactly; fall back to
  // per-slot groups (always safe — every rank received the same list,
  // so every rank falls back identically).
  uint64_t total = 0;
  for (uint32_t g : steady_groups_) total += g;
  if (steady_groups_.empty() || total != steady_pattern_.size())
    steady_groups_.assign(steady_pattern_.size(), 1);
  steady_pos_ = 0;
  steady_group_idx_ = 0;
  steady_epoch_ = 0;
  steady_idle_passes_ = 0;
  steady_last_poll_ = std::chrono::steady_clock::now();
  steady_pending_group_.clear();
  steady_pending_reqs_.clear();
  steady_exit_pending_ = false;
  steady_pattern_len_.store(static_cast<int64_t>(steady_pattern_.size()));
  steady_active_.store(true);
  steady_entries_.fetch_add(1);
  if (flight_.Enabled())
    flight_.Record(FL_STEADY, "enter",
                   static_cast<int64_t>(steady_pattern_.size()));
  timeline_.Instant("steady", "STEADY_ENTER");
}

void Engine::ExitSteadyLocal(const std::string& reason) {
  if (!steady_active_.load()) return;
  steady_active_.store(false);
  steady_exits_.fetch_add(1);
  steady_exit_pending_ = true;
  steady_exit_epoch_ = steady_epoch_;
  steady_exit_pos_ = static_cast<int64_t>(steady_pos_);
  steady_pattern_len_.store(0);
  if (is_sub_coord_) sub_holding_ = true;
  if (opts_.rank == 0 && coord_) NoteSteadyExit(0);
  if (flight_.Enabled()) flight_.Record(FL_STEADY, reason, steady_epoch_);
  timeline_.Instant("steady", "STEADY_EXIT");
}

void Engine::NoteSteadyExit(int r) {
  if (!coord_ || !coord_->steady) return;
  if (r >= 0 && r < static_cast<int>(coord_->steady_exited.size()))
    coord_->steady_exited[r] = true;
}

void Engine::NoteChildSteadyExit(const RequestList& frame, int child_rank) {
  if (!frame.steady_exit || !flight_.Enabled()) return;
  flight_.Record(FL_STEADY,
                 "peer-exit:" + std::to_string(child_rank) + "@" +
                     std::to_string(frame.steady_epoch) + "/" +
                     std::to_string(frame.steady_pos),
                 frame.steady_epoch);
}

double Engine::ParentWaitSec() const {
  if (opts_.collective_timeout_sec <= 0) return 0.0;
  // Star / node-0 worker: the coordinator may block one deadline probing
  // a frozen third rank (2T+5).  Under the tree, rank 0 probes a frozen
  // SUB for 2T+5 before the abort goes out, so a healthy sub waits
  // 3T+10; a leaf sits one relay below its sub and waits 4T+15.
  double T = opts_.collective_timeout_sec;
  if (is_sub_coord_) return 3 * T + 10.0;
  if (tree_enabled_ && opts_.rank >= opts_.local_size) return 4 * T + 15.0;
  return 2 * T + 5.0;
}

bool Engine::AllSteadyExited() const {
  if (!coord_ || !coord_->steady) return true;
  for (size_t r = 0; r < coord_->steady_exited.size(); ++r)
    if (!coord_->steady_exited[r] && !coord_->rank_dead[r]) return false;
  return true;
}

bool Engine::CoordinatorSteadyPoll() {
  // Rank 0 while steady (or holding): frames are exceptional — fallback
  // announcements, steady-exit markers, EOFs.  Drain whatever arrived
  // without blocking; the collective-timeout sweep still covers
  // announced-but-incomplete negotiations (the mid-steady divergence
  // story), and socket EOF still covers crashes.
  // Heartbeat escalation stays live mid-steady: rank 0's own monitor
  // flags drain here (a frozen neighbour is evicted with zero control
  // frames flowing), and workers' out-of-band hb_report frames arrive
  // through the normal drain below — they parse as RequestLists whose
  // dead_ranks CoordinatorHandle consumes.
  CoordinatorDrainHeartbeatDeaths();
  for (int r : coord_children_) {
    if (coord_->rank_dead[r]) continue;
    int fd = coord_fds_[r];
    if (fd < 0) continue;
    bool dead = false;
    while (WaitReadable(fd, 0.0)) {
      std::vector<uint8_t> buf;
      if (!RecvFrame(fd, &buf)) {
        dead = true;
        break;
      }
      ctrl_frames_recv_.fetch_add(1);
      RequestList rl;
      if (ParseRequestList(buf, &rl)) {
        coord_->last_frame_tick[r] = ticks_done_.load();
        coord_->shutdown_requested |= rl.shutdown;
        CoordinatorHandle(rl, r);
      }
    }
    // EOF makes the socket readable, so a dead child always lands in
    // the RecvFrame failure path above — no extra probe per pass.
    if (dead) {
      bool sub_lead = tree_enabled_ && r >= opts_.local_size;
      MarkRankDead(r, sub_lead ? "sub-coordinator connection lost (its "
                                 "node is unreachable)"
                               : "connection lost at the coordinator");
    }
  }
  CheckCollectiveTimeout();
  CheckForStalledTensors();
  if (coord_->abort_code != 0 || coord_->shutdown_requested) {
    // Abort/shutdown broadcasts go out IMMEDIATELY, steady or not: ranks
    // still self-clocking poll their parent socket every pass, and both
    // verdicts drain everything position-independently.  Strip any op
    // payload CoordinatorTick may carry on the shutdown path — ranks at
    // different replay positions must not execute it.
    ResponseList out = CoordinatorTick();
    out.responses.clear();
    out.cache_hits.clear();
    out.shutdown = out.shutdown || coord_->shutdown_requested;
    std::vector<uint8_t> bytes = SerializeResponseList(out);
    for (int r : coord_children_) {
      if (coord_->rank_dead[r] || coord_fds_[r] < 0) continue;
      if (SendFrame(coord_fds_[r], bytes)) ctrl_frames_sent_.fetch_add(1);
    }
    if (steady_active_.load())
      ExitSteadyLocal(out.abort_code != 0 ? "abort" : "shutdown");
    if (out.abort_code != 0) AbortLocal(out.abort_code, out.abort_message);
    return false;
  }
  return true;
}

int Engine::MaybeRevokeSteadyForReshape() {
  if (!opts_.elastic || !coord_ || !coord_->steady) return 0;
  if (coord_->abort_code != 0 || coord_->shutdown_requested) return 0;
  // The normal loop's joiner accept never runs while rank 0 is steady,
  // so drain the listen backlog here — non-blocking — or a standby
  // registering mid-steady would sit unseen until some rank missed.
  CoordinatorAcceptJoiners();
  // Shrink: a death just armed the barrier.  Grow: a standby is waiting
  // and steady state means the control plane is quiesced by construction
  // — no normal tick is coming to host the barrier, so without this the
  // admission would starve until some rank happens to miss.
  if (!coord_->reshape_pending && coord_->pending_join_fds.empty())
    return 0;
  // Broadcast a bare revocation list (no ops, no hits, no reshape):
  // ranks still self-clocking poll their parent socket every pass and
  // treat any payload broadcast as a revocation; ranks that already fell
  // back are blocked on a response and consume it as an empty tick.  The
  // barrier itself then fires on the NEXT regular tick through
  // CoordinatorMaybeReshape, which re-establishes the one-frame-per-
  // child alternation the barrier broadcast relies on (a barrier sent
  // directly from here could cross an in-flight fallback frame; the
  // epoch stamp on RequestList is the backstop for exactly that race).
  ResponseList out;
  out.steady_revoke = true;
  std::vector<uint8_t> bytes = SerializeResponseList(out);
  for (int r : coord_children_) {
    if (coord_->rank_dead[r] || coord_fds_[r] < 0) continue;
    if (SendFrame(coord_fds_[r], bytes)) ctrl_frames_sent_.fetch_add(1);
  }
  coord_->steady = false;
  coord_->steady_revoke_next = false;
  coord_->slot_history.clear();
  if (steady_active_.load()) ExitSteadyLocal("reshape-revoke");
  if (!steady_pending_reqs_.empty()) {
    // Requeue the drained-but-unreplayed partial group: its handles are
    // still in table_, and a dropped announce would strand them forever
    // (hvdmodel's no-deadlock invariant over the bare drop).
    std::lock_guard<std::mutex> lk(mu_);
    for (size_t i = steady_pending_reqs_.size(); i-- > 0;)
      queue_.push_front(std::move(steady_pending_reqs_[i]));
    steady_pending_reqs_.clear();
    steady_pending_group_.clear();
  }
  if (flight_.Enabled())
    flight_.Record(FL_STEADY, "reshape-revoke", steady_epoch_);
  RequestList none;
  return ProcessResponseList(out, none, std::chrono::steady_clock::now())
             ? 1
             : -1;
}

bool Engine::SubRelayPass() {
  // Sub-coordinator while steady (or holding): poll children for
  // fallback frames and forward them upward; poll the parent for
  // broadcasts and relay them down.  Never blocks — children still
  // self-clocking are silent by design.
  RequestList agg;
  SlotIndex idx;
  agg.membership_epoch = membership_epoch_.load();
  // Own monitor flags ride up in this pass's aggregate dead_ranks, same
  // as a child EOF this sub observed.
  {
    std::lock_guard<std::mutex> lk(hb_mu_);
    for (int r : pending_hb_report_) pending_dead_reports_.push_back(r);
    pending_hb_report_.clear();
  }
  for (size_t i = 0; i < tree_child_fds_.size(); ++i) {
    if (tree_child_dead_[i]) continue;
    int fd = tree_child_fds_[i];
    int crank = tree_child_ranks_[i];
    bool dead = false;
    while (WaitReadable(fd, 0.0)) {
      std::vector<uint8_t> buf;
      if (!RecvFrame(fd, &buf)) {
        dead = true;
        break;
      }
      ctrl_frames_recv_.fetch_add(1);
      RequestList child;
      if (ParseRequestList(buf, &child)) {
        NoteChildSteadyExit(child, crank);
        MergeFrameIntoAggregate(child, crank,
                                EpochNowUs() - clock_offset_us_.load(),
                                &agg, &idx);
      }
    }
    if (dead) {
      tree_child_dead_[i] = true;
      pending_dead_reports_.push_back(crank);
    }
  }
  if (!pending_dead_reports_.empty()) {
    for (int32_t r : pending_dead_reports_) agg.dead_ranks.push_back(r);
    pending_dead_reports_.clear();
  }
  if (!agg.requests.empty() || !agg.bit_groups.empty() ||
      !agg.dead_ranks.empty() || !agg.steady_exits.empty() ||
      agg.shutdown) {
    if (!SendFrame(coord_fd_, SerializeRequestList(agg))) {
      ExitSteadyLocal("coordinator-lost");
      AbortLocal(ST_RANKS_DOWN,
                 "ranks down: 0 (coordinator connection lost); this job "
                 "cannot continue and should be restarted.");
      return false;
    }
    ctrl_frames_sent_.fetch_add(1);
  }
  while (coord_fd_ >= 0 && WaitReadable(coord_fd_, 0.0)) {
    std::vector<uint8_t> buf;
    if (!RecvFrame(coord_fd_, &buf)) {
      ExitSteadyLocal("coordinator-lost");
      AbortLocal(ST_RANKS_DOWN,
                 "ranks down: 0 (coordinator connection lost); this job "
                 "cannot continue and should be restarted.");
      return false;
    }
    ctrl_frames_recv_.fetch_add(1);
    // Relay raw bytes down first: whatever this frame is, the children
    // need it too (they are all blocked or polling).
    for (size_t i = 0; i < tree_child_fds_.size(); ++i)
      if (!tree_child_dead_[i] && SendFrame(tree_child_fds_[i], buf))
        ctrl_frames_sent_.fetch_add(1);
    ResponseList rl;
    if (!ParseResponseList(buf, &rl)) continue;
    if (rl.abort_code != 0) {
      ExitSteadyLocal("abort");
      AbortLocal(rl.abort_code, rl.abort_message);
      return false;
    }
    if (rl.shutdown) {
      ExitSteadyLocal("shutdown");
      return false;
    }
    // The resume broadcast (or, defensively, any payload list): leave
    // steady/holding and process it like a normal tick.  Requeue any
    // drained-but-unreplayed partial group first — a mid-steady reshape
    // revocation legitimately lands here with one pending, and the bare
    // drop stranded those handles forever (hvdmodel caught it).
    if (steady_active_.load()) ExitSteadyLocal("broadcast-resumed");
    if (!steady_pending_reqs_.empty()) {
      std::lock_guard<std::mutex> lk(mu_);
      for (size_t i = steady_pending_reqs_.size(); i-- > 0;)
        queue_.push_front(std::move(steady_pending_reqs_[i]));
      steady_pending_reqs_.clear();
      steady_pending_group_.clear();
    }
    sub_holding_ = false;
    RequestList none;
    return ProcessResponseList(rl, none, std::chrono::steady_clock::now());
  }
  return true;
}

bool Engine::SteadyLoopOnce() {
  // 1. Control-socket duty: rank 0 polls its children (fallback frames,
  // EOFs, deadline sweeps); everyone else polls the parent for
  // abort/shutdown frames; sub-coordinators additionally relay.  The
  // duty rides the IDLE cadence: frames are exceptional in steady state
  // (that is the point), so burning O(children) poll syscalls inside
  // every replay burst would put the fan-in term back into the replay
  // path this mode exists to remove — the idle wait (1-10ms) bounds
  // abort/fallback latency instead, with a ~5ms time floor so a
  // pipeline that keeps the queue non-empty on every pass (no idle
  // passes at all) still reads abort/shutdown frames promptly.
  auto duty_now = std::chrono::steady_clock::now();
  if (steady_idle_passes_ > 0 ||
      duty_now - steady_last_poll_ > std::chrono::milliseconds(5)) {
  steady_last_poll_ = duty_now;
  if (opts_.rank == 0) {
    if (!CoordinatorSteadyPoll()) return false;
    int rv = MaybeRevokeSteadyForReshape();
    if (rv < 0) return false;
    if (rv > 0) return true;  // revoked: next pass is a normal tick
  } else {
    // A monitor-latched local abort (grace expired with the coordinator
    // unreachable) surfaces here even with zero frames flowing.
    if (hb_local_abort_.load()) {
      ExitSteadyLocal("heartbeat-abort");
      CheckHeartbeatLocalAbort();
      return false;
    }
    if (is_sub_coord_) {
      if (!SubRelayPass()) return false;
      // SubRelayPass may have exited steady (abort consumed elsewhere);
      // fall through so the normal loop takes over next pass.
      if (!steady_active_.load()) return true;
    } else {
      // Out-of-band heartbeat reports flow mid-steady too — rank 0's
      // steady poll drains them like fallback frames.
      SendHeartbeatReports(coord_fd_);
      while (coord_fd_ >= 0 && WaitReadable(coord_fd_, 0.0)) {
        std::vector<uint8_t> buf;
        if (!RecvFrame(coord_fd_, &buf)) {
          ExitSteadyLocal("coordinator-lost");
          AbortLocal(ST_RANKS_DOWN,
                     "ranks down: 0 (coordinator connection lost); this "
                     "job cannot continue and should be restarted.");
          return false;
        }
        ctrl_frames_recv_.fetch_add(1);
        ResponseList rl;
        if (!ParseResponseList(buf, &rl)) continue;
        if (rl.abort_code != 0) {
          ExitSteadyLocal("abort");
          AbortLocal(rl.abort_code, rl.abort_message);
          return false;
        }
        if (rl.shutdown) {
          ExitSteadyLocal("shutdown");
          return false;
        }
        // Defensively treat any payload broadcast as a revocation.
        // Requeue any drained-but-unreplayed partial group first — a
        // mid-steady reshape revocation legitimately lands here with one
        // pending, and the bare drop stranded those handles forever.
        ExitSteadyLocal("broadcast-resumed");
        if (!steady_pending_reqs_.empty()) {
          std::lock_guard<std::mutex> lk(mu_);
          for (size_t i = steady_pending_reqs_.size(); i-- > 0;)
            queue_.push_front(std::move(steady_pending_reqs_[i]));
          steady_pending_reqs_.clear();
          steady_pending_group_.clear();
        }
        RequestList none;
        return ProcessResponseList(rl, none,
                                   std::chrono::steady_clock::now());
      }
      // (EOF makes the socket readable, so the RecvFrame failure path
      // above already covers a dead parent — no extra probe needed.)
    }
  }
  }
  // 2. A Python-initiated shutdown must reach the coordinator: exit
  // steady so the next (normal) pass sends the shutdown frame.
  if (shut_down_.load()) {
    ExitSteadyLocal("shutdown");
    return true;
  }
  // 3. Drain the queue and replay pattern matches group by group.  A
  // group replays only once COMPLETE (all its slots drained), and a
  // drained request may match ANY not-yet-drained slot of the CURRENT
  // group, not just the next position: a group's slots co-arrived in
  // one negotiation tick, so their per-rank enqueue order carries no
  // cross-rank meaning (async/threaded submitters legitimately differ),
  // and a strict positional match would miss — and fall back — on one
  // rank while its peers replay the fused bucket into the data plane.
  // Replay always executes the group in PATTERN order, so fusion
  // boundaries and execution order stay identical on every rank
  // regardless of local drain order.
  std::deque<Request> drained;
  {
    std::lock_guard<std::mutex> lk(mu_);
    drained.swap(queue_);
  }
  bool replayed = false;
  while (!drained.empty()) {
    Request req = std::move(drained.front());
    drained.pop_front();
    int slot = cache_.Lookup(req);
    // Remaining slots of the current group = pattern[group_base + n]
    // for n in [pending, group_size) where drained slots are tracked in
    // steady_pending_group_ (a multiset of the group's already-drained
    // slots).
    bool match = false;
    if (slot >= 0 && steady_group_idx_ < steady_groups_.size()) {
      size_t group_size = steady_groups_[steady_group_idx_];
      size_t group_base = steady_pos_ - steady_pending_group_.size();
      for (size_t n = 0; n < group_size && !match; ++n) {
        if (steady_pattern_[group_base + n] !=
            static_cast<uint32_t>(slot))
          continue;
        // Slot appears in the group; unmatched iff its multiplicity in
        // the group exceeds its count among already-drained slots.
        size_t in_group = 0, drained_n = 0;
        for (size_t m = 0; m < group_size; ++m)
          if (steady_pattern_[group_base + m] ==
              static_cast<uint32_t>(slot))
            ++in_group;
        for (uint32_t d : steady_pending_group_)
          if (d == static_cast<uint32_t>(slot)) ++drained_n;
        match = drained_n < in_group;
      }
    }
    if (!match) {
      // Miss: fall back to full negotiation for this and everything
      // after it (and everything drained-but-unreplayed before it).
      // Steady state assumes SPMD: under it every rank misses at the
      // same pattern position and the fallback converges (the tests pin
      // this).  A rank whose PROGRAM diverged — it alone misses while
      // peers keep matching — is already a broken job; peers block in
      // the data plane on its missing participation and the failure
      // surfaces through the exchange-silence timeout / EOF cascade as
      // a typed abort, the same quality the star gave mismatched
      // submissions.
      ExitSteadyLocal("miss:" + req.name);
      std::lock_guard<std::mutex> lk(mu_);
      // Requeue in original order AT THE FRONT (entries enqueued after
      // the swap above must stay behind these).
      for (size_t i = drained.size(); i-- > 0;)
        queue_.push_front(std::move(drained[i]));
      queue_.push_front(std::move(req));
      for (size_t i = steady_pending_reqs_.size(); i-- > 0;)
        queue_.push_front(std::move(steady_pending_reqs_[i]));
      steady_pending_reqs_.clear();
      steady_pending_group_.clear();
      return true;
    }
    if (steady_pending_group_.empty())
      steady_group_wait_ = std::chrono::steady_clock::now();
    steady_pending_group_.push_back(static_cast<uint32_t>(slot));
    steady_pending_reqs_.push_back(std::move(req));
    ++steady_pos_;
    cache_hits_.fetch_add(1);
    if (flight_.Enabled())
      flight_.Record(FL_CACHE_HIT, steady_pending_reqs_.back().name, slot);
    if (steady_pending_group_.size() ==
        static_cast<size_t>(steady_groups_[steady_group_idx_])) {
      // Complete replay group: execute exactly like a broadcast list's
      // cache_hits (same fusion walk, same LRU touches — lockstep), in
      // the PATTERN'S canonical slot order — never the local drain
      // order, which may differ per rank within a group.
      std::vector<uint32_t> canonical(
          steady_pattern_.begin() + (steady_pos_ -
                                     steady_pending_group_.size()),
          steady_pattern_.begin() + steady_pos_);
      // Count the group BEFORE executing it: CompleteEntry inside
      // ProcessCacheHits wakes data-plane waiters, and a metrics
      // snapshot taken the instant wait() returns must already include
      // the group (and cycle) that completed it.
      steady_replays_.fetch_add(
          static_cast<int64_t>(steady_pending_group_.size()));
      if (steady_group_idx_ + 1 == steady_groups_.size())
        steady_cycles_.fetch_add(1);
      ProcessCacheHits(canonical);
      steady_pending_group_.clear();
      steady_pending_reqs_.clear();
      ++steady_group_idx_;
      replayed = true;
      if (steady_group_idx_ == steady_groups_.size()) {
        // Pattern wrap = one full self-clocked cycle.  ticks_done_
        // advances HERE (identically on every rank, since the replay
        // stream is identical) so completion stamps and the per-tick
        // lockstep lookups stay cross-rank consistent while the control
        // plane is dark.
        steady_group_idx_ = 0;
        steady_pos_ = 0;
        ++steady_epoch_;
        ticks_done_.fetch_add(1);
        timeline_.Instant("steady", "STEADY_EPOCH");
      }
    }
  }
  // 4. A partial group can starve only if the program's enqueue style
  // changed (the grouping was OBSERVED from real broadcast lists);
  // rather than risk a silent stall, fall back to negotiation.
  if (!steady_pending_group_.empty() &&
      std::chrono::steady_clock::now() - steady_group_wait_ >
          std::chrono::duration<double>(2.0)) {
    ExitSteadyLocal("group-timeout");
    std::lock_guard<std::mutex> lk(mu_);
    for (size_t i = steady_pending_reqs_.size(); i-- > 0;)
      queue_.push_front(std::move(steady_pending_reqs_[i]));
    steady_pending_reqs_.clear();
    steady_pending_group_.clear();
    return true;
  }
  if (!replayed) {
    // Idle: block on the enqueue cv (µs-latency wake when work arrives)
    // with a bounded timeout so the parent-socket poll above still runs
    // for abort/shutdown frames.  The timeout backs off after a few
    // empty passes — enqueues wake the cv directly, so a long timeout
    // costs nothing on the replay path, while hundreds of in-process
    // simulated ranks ticking short timers would entrain the scheduler
    // and show up as milliseconds of wake latency in every cycle.
    int wait_ms = ++steady_idle_passes_ < 4 ? 1 : 10;
    std::unique_lock<std::mutex> lk(mu_);
    if (queue_.empty())
      queue_cv_.wait_for(lk, std::chrono::milliseconds(wait_ms));
  } else {
    steady_idle_passes_ = 0;
  }
  return true;
}

std::string Engine::ControlInfo() {
  return std::string(tree_enabled_ ? "1" : "0") + "|" +
         std::to_string(ctrl_children_.load()) + "|" +
         std::to_string(ctrl_hosts_.load()) + "|" +
         (steady_active_.load() ? "1" : "0") + "|" +
         std::to_string(steady_pattern_len_.load()) + "|" +
         std::to_string(opts_.steady_threshold) + "|" +
         std::to_string(steady_entries_.load()) + "|" +
         std::to_string(steady_exits_.load()) + "|" +
         std::to_string(steady_replays_.load()) + "|" +
         std::to_string(steady_cycles_.load()) + "|" +
         std::to_string(negotiated_ticks_.load()) + "|" +
         std::to_string(ctrl_frames_sent_.load()) + "|" +
         std::to_string(ctrl_frames_recv_.load());
}

// The XLA plane negotiates each collective via a "__xp.<name>" metadata
// allreduce through this engine (jax/eager_mesh.py).  Transport choice is
// dtype-deterministic, so a rank whose dtype is plane-ineligible (f64,
// bool) announces the bare "<name>" while plane ranks announce
// "__xp.<name>" — two pending entries that can never each reach full
// count.  SiblingName maps one to the other so the coordinator can turn
// that split into a typed error (the reference's cross-rank validation
// contract, operations.cc:301-503, extended across transports).
static const char kPlanePrefix[] = "__xp.";

static std::string SiblingName(const std::string& name) {
  const size_t n = sizeof(kPlanePrefix) - 1;
  if (name.compare(0, n, kPlanePrefix) == 0) return name.substr(n);
  return kPlanePrefix + name;
}

static std::string BaseName(const std::string& name) {
  const size_t n = sizeof(kPlanePrefix) - 1;
  if (name.compare(0, n, kPlanePrefix) == 0) return name.substr(n);
  return name;
}

void Engine::CoordinatorHandle(const RequestList& rl, int from_rank) {
  if (rl.membership_epoch < membership_epoch_.load()) {
    // Stale-epoch frame: built against a membership a reshape barrier
    // already replaced (possible only around a mid-steady revocation,
    // which breaks the send-one-wait-one alternation).  Its cache bits
    // name slots the barrier cleared and its announces would pollute the
    // new membership's table, so the whole frame is dropped — the sender
    // re-announces everything after its own ApplyReshape anyway
    // (hvdmodel invariant: no stale-epoch frame is ever accepted).
    if (flight_.Enabled())
      flight_.Record(FL_RESHAPE, "stale-frame:" + std::to_string(from_rank),
                     rl.membership_epoch);
    return;
  }
  int64_t now_us = EpochNowUs();
  bool have_ts = rl.announce_us.size() == rl.requests.size();
  for (size_t i = 0; i < rl.requests.size(); ++i) {
    const Request& req = rl.requests[i];
    // A full string request for a name whose slot (or whose
    // cross-transport sibling's slot) has outstanding cache bits means
    // some rank fell back to full negotiation — a signature change, or a
    // dtype split across transports.  Fold those bits back into their
    // equivalent full requests first, so the validation below sees every
    // rank and the PR-2 mismatch/typed-error contract still fires.
    CoordinatorDrainBitsFor(req.name);
    CoordinatorDrainBitsFor(SiblingName(req.name));
    // Aggregate frames carry requests from several ranks (each Request
    // names its true rank) plus their announce timestamps on rank 0's
    // clock; direct frames stamp on arrival.
    HandleOneRequest(req, req.rank, have_ts ? rl.announce_us[i] : now_us);
  }
  CoordinatorHandleBits(rl.cache_bits, from_rank);
  for (const auto& g : rl.bit_groups)
    for (size_t j = 0; j < g.ranks.size(); ++j)
      HandleOneBit(g.slot, g.ranks[j],
                   j < g.announce_us.size() && g.announce_us[j] >= 0
                       ? g.announce_us[j]
                       : now_us);
  // Liveness/postmortem accounting for ranks whose frames this aggregate
  // folds in (the per-rank last-frame story must survive aggregation).
  for (int32_t r : rl.frames_from)
    if (r >= 0 && r < static_cast<int>(coord_->last_frame_tick.size()))
      coord_->last_frame_tick[r] = ticks_done_.load();
  // Worker deaths observed elsewhere: control-socket EOF at a
  // sub-coordinator, or — when the frame is an out-of-band heartbeat
  // report — a peer's data-plane beacons going silent.
  for (int32_t r : rl.dead_ranks)
    if (r > 0 && r < opts_.size) {
      if (rl.hb_report && !coord_->rank_dead[r])
        hb_evictions_.fetch_add(1);
      MarkRankDead(r, rl.hb_report
                          ? "missed data-plane heartbeats; process frozen "
                            "or link partitioned"
                          : "connection lost at its sub-coordinator");
    }
  if (rl.steady_exit) {
    // The direct-frame exit marker carries the miss coordinates: land
    // them in rank 0's flight ring so the postmortem can say WHERE in
    // the pattern the fallback happened (sub-coordinators do the same
    // for their leaves as the marker passes through).
    NoteChildSteadyExit(rl, from_rank);
    NoteSteadyExit(from_rank);
  }
  for (int32_t r : rl.steady_exits) NoteSteadyExit(r);
}

Request Engine::SynthesizeFromSlot(const CacheSlot& slot, int rank) const {
  Request r;
  r.rank = rank;
  r.op = slot.op;
  r.dtype = slot.dtype;
  r.root_rank = slot.root_rank;
  r.name = slot.name;
  r.dims = slot.dims;
  // The stored dims are THIS rank's; ragged allgather geometry differs
  // per rank — restore `rank`'s dim0 from the agreed response.
  if (slot.op == OP_ALLGATHER && !r.dims.empty() &&
      rank < static_cast<int>(slot.response.rank_dim0.size()))
    r.dims[0] = slot.response.rank_dim0[rank];
  // A p2p slot stores the pair's agreement, not one rank's request:
  // restore `rank`'s role (the sender re-announces OP_SEND naming the
  // receiver, and vice versa) so renegotiation revalidates the pair.
  if (slot.response.type == RESP_SENDRECV) {
    const Response& a = slot.response;
    r.op = rank == a.p2p_src ? OP_SEND : OP_RECV;
    r.p2p_peer = rank == a.p2p_src ? a.p2p_dst : a.p2p_src;
    r.p2p_tag = a.p2p_tag;
    r.dtype = a.p2p_dtype;
    r.dims = a.p2p_dims;
  }
  r.stage_ranks = slot.response.stage_ranks;
  return r;
}

void Engine::CoordinatorDrainBitsFor(const std::string& name) {
  if (coord_->cache_pending.empty()) return;
  int slot = cache_.SlotByName(name);
  if (slot < 0) return;
  const CacheSlot* s = cache_.Get(slot);
  if (s != nullptr) CoordinatorDrainSlot(slot, *s);
}

void Engine::CoordinatorDrainSlot(int slot, const CacheSlot& contents) {
  auto it = coord_->cache_pending.find(static_cast<uint32_t>(slot));
  if (it == coord_->cache_pending.end()) return;
  Coordinator::PendingBits pb = std::move(it->second);
  coord_->cache_pending.erase(it);
  // Close the NEGOTIATE row the first bit opened; the synthesized
  // requests below re-open it on the full-negotiation path.
  timeline_.NegotiateEnd(contents.name);
  for (int r = 0; r < opts_.size; ++r)
    if (pb.ranks[r]) HandleOneRequest(SynthesizeFromSlot(contents, r), r);
}

void Engine::CoordinatorHandleBits(const std::vector<uint32_t>& bits,
                                   int from_rank) {
  int64_t now_us = EpochNowUs();
  for (uint32_t bit : bits) HandleOneBit(bit, from_rank, now_us);
}

void Engine::HandleOneBit(uint32_t bit, int from_rank, int64_t announce_ts) {
  const CacheSlot* s = cache_.Get(static_cast<int>(bit));
  if (s == nullptr) {
    // Unreachable when every rank runs the same cache state — which
    // Init enforces by agreeing on one job-wide capacity over the
    // coordinator star and the lockstep mutation contract maintains.
    // If it happens anyway, DROPPING the bit would leave the
    // announcing rank waiting forever; abort the job with a crisp
    // status instead.
    if (coord_->abort_code == 0) {
      coord_->abort_code = ST_INVALID;
      coord_->abort_message =
          "response-cache protocol error: rank " +
          std::to_string(from_rank) + " announced cache slot " +
          std::to_string(bit) +
          ", unknown to the coordinator (the ranks disagree on the "
          "negotiation response cache state); this job cannot continue "
          "and should be restarted.";
    }
    return;
  }
  if (coord_->message_table.count(s->name)) {
    // A full (re-)negotiation of this name is in flight: fold the bit
    // in as its equivalent full request so validation sees this rank.
    HandleOneRequest(SynthesizeFromSlot(*s, from_rank), from_rank,
                     announce_ts);
    return;
  }
  auto& pb = coord_->cache_pending[bit];
  if (pb.ranks.empty()) {
    pb.ranks.assign(opts_.size, false);
    pb.first_seen = std::chrono::steady_clock::now();
    timeline_.NegotiateStart(s->name, s->op);
  }
  if (!pb.ranks[from_rank]) {
    pb.ranks[from_rank] = true;
    ++pb.count;
    if (announce_ts < 0) announce_ts = EpochNowUs();
    if (pb.first_us < 0 || announce_ts < pb.first_us)
      pb.first_us = announce_ts;
    if (announce_ts >= pb.last_us) {
      pb.last_us = announce_ts;
      pb.last_rank = from_rank;
    }
    timeline_.NegotiateRankReady(s->name, from_rank, announce_ts);
    if (from_rank <
        static_cast<int>(coord_->last_announce_tick.size())) {
      coord_->last_announce_tick[from_rank] = ticks_done_.load();
      coord_->last_announce_name[from_rank] = s->name;
    }
  }
  // Slot-scoped full count (docs/pipeline.md): a cached p2p pair agrees
  // at TWO bits, a cached stage-group collective at its membership.
  int required = opts_.size;
  if (s->response.type == RESP_SENDRECV)
    required = 2;
  else if (!s->response.stage_ranks.empty())
    required = static_cast<int>(s->response.stage_ranks.size());
  if (pb.count == required) {
    // Agreement by pure bit intersection: no strings were parsed, no
    // Requests rebuilt.  Keep the announce/straggler accounting live in
    // steady state, and mark the NEGOTIATE row as a cache hit.
    if (opts_.size > 1)
      RecordAnnounce(pb.last_rank, pb.last_us - pb.first_us);
    timeline_.Instant(s->name, "NEGOTIATE_CACHED");
    timeline_.NegotiateEnd(s->name);
    // Autotune window accounting: a bit agreement is one negotiated
    // collective of the slot's payload size (the steady-state path the
    // tuner mostly scores).  NOOP slots score zero bytes, matching the
    // fresh-negotiation path — their dims are metadata geometry, not
    // payload, and mixed scoring would bias windows by cache-hit mix.
    if (tuner_.active())
      tuner_.Record(
          s->op == OP_NOOP
              ? 0
              : NumElements(s->dims) *
                    static_cast<int64_t>(DataTypeSize(s->dtype)),
          1);
    coord_->cached_ready.push_back(bit);
    coord_->cache_pending.erase(bit);
  }
}

void Engine::HandleOneRequest(const Request& req, int from_rank,
                              int64_t announce_ts) {
  if (announce_ts < 0) announce_ts = EpochNowUs();
  if (from_rank >= 0 &&
      from_rank < static_cast<int>(coord_->last_announce_tick.size())) {
    coord_->last_announce_tick[from_rank] = ticks_done_.load();
    coord_->last_announce_name[from_rank] = req.name;
  }
  {
    auto& pt = coord_->message_table[req.name];
    if (pt.requests.empty()) {
      pt.first_seen = std::chrono::steady_clock::now();
      pt.order = coord_->next_order++;
      timeline_.NegotiateStart(req.name, req.op);
      std::string base = BaseName(req.name);
      auto poisoned = coord_->poisoned.find(base);
      if (poisoned != coord_->poisoned.end()) {
        auto age = std::chrono::steady_clock::now() - poisoned->second.second;
        if (age > std::chrono::duration<double>(
                      Coordinator::kPoisonWindowSec)) {
          // Expired: the name is usable again, but this announcer may be a
          // very late straggler of the mismatched round whose peers
          // already consumed their error responses — give it a
          // stall-warning-length grace deadline instead of letting it
          // re-pend forever.
          coord_->poisoned.erase(poisoned);
          pt.poison_deadline_tick =
              ticks_done_.load() +
              std::max<int64_t>(
                  Coordinator::kPoisonDeadlineTicks,
                  static_cast<int64_t>(opts_.stall_warning_sec * 1000.0 /
                                       std::max(opts_.cycle_time_ms, 0.1)));
        } else {
          // Defer: full count before the deadline = consistent retry
          // (negotiates normally); stalled at the deadline = straggler of
          // the mismatched round (typed error, swept in CoordinatorTick).
          pt.poison_deadline_tick =
              ticks_done_.load() + Coordinator::kPoisonDeadlineTicks;
        }
      }
      auto sib = coord_->message_table.find(SiblingName(req.name));
      // Only a sibling still PENDING (count < size) indicates a split: a
      // full-count sibling is a validly negotiated collective already in
      // `ready` (erroring it would convert a good op into a spurious
      // failure and double-push its name, double-building the response).
      if (sib != coord_->message_table.end() &&
          !sib->second.requests.empty() &&
          static_cast<int>(sib->second.requests.size()) < opts_.size &&
          sib->second.forced_error.empty() && pt.forced_error.empty()) {
        std::string msg =
            "cross-transport mismatch for tensor '" + base +
            "': some ranks submitted it over the XLA data plane while "
            "others fell back to the TCP engine (rank " +
            std::to_string(req.rank) + " vs rank " +
            std::to_string(sib->second.requests[0].rank) +
            ").  The transport is chosen by dtype, so this means the "
            "ranks disagree on the tensor's dtype (e.g. float32 on one "
            "rank, float64/bool on another); every rank must submit the "
            "same collective with the same dtype.";
        pt.forced_error = msg;
        sib->second.forced_error = msg;
        if (coord_->poisoned.size() > 1024) coord_->poisoned.clear();
        coord_->poisoned[base] = {msg, std::chrono::steady_clock::now()};
        coord_->ready.push_back(req.name);
        coord_->ready.push_back(sib->first);
      }
    }
    timeline_.NegotiateRankReady(req.name, from_rank, announce_ts);
    if (pt.first_us < 0 || announce_ts < pt.first_us)
      pt.first_us = announce_ts;
    if (announce_ts >= pt.last_us) {
      pt.last_us = announce_ts;
      pt.last_rank = from_rank;
    }
    pt.requests.push_back(req);
    // forced_error entries were already pushed to ready at detection; a
    // second push here would double-build (and double-erase) the entry.
    // The full count is op-scoped (docs/pipeline.md): a send/recv pair
    // completes at TWO announcements (paired readiness — sender and
    // receiver must both have posted), a stage-scoped collective at its
    // group's membership, everything else at the whole world.
    if (static_cast<int>(pt.requests.size()) ==
            RequiredCount(pt.requests[0], opts_.size) &&
        pt.forced_error.empty()) {
      if (pt.poison_deadline_tick != 0) {
        // Every rank re-announced consistently: the mismatch is resolved;
        // the name negotiates normally and the poison clears.
        coord_->poisoned.erase(BaseName(req.name));
        pt.poison_deadline_tick = 0;
      }
      // Straggler attribution: the rank with the LATEST announce
      // timestamp announced last; skew = first -> last announce.  Tree
      // aggregates forward the true per-rank times, so this names the
      // real straggler rank, not the sub-coordinator.  At size 1 every
      // count completes instantly — pure noise, skip.
      if (opts_.size > 1)
        RecordAnnounce(pt.last_rank, pt.last_us - pt.first_us);
      timeline_.NegotiateEnd(req.name);
      coord_->ready.push_back(req.name);
    }
  }
}

Response Engine::BuildResponse(const std::string& name) {
  // Cross-rank consistency validation, mirroring the checks in the
  // reference's ConstructMPIResponse (operations.cc:301-503): op, dtype,
  // shape (exact for allreduce/broadcast, all-but-dim-0 for allgather) and
  // broadcast root must agree across ranks.
  auto it = coord_->message_table.find(name);
  Response resp;
  resp.names.push_back(name);
  if (!it->second.forced_error.empty()) {
    resp.type = RESP_ERROR;
    resp.error_message = it->second.forced_error;
    // Close the NEGOTIATE row opened at first announce (the normal path
    // closes it at full count, which forced errors never reach).
    timeline_.NegotiateEnd(name);
    coord_->message_table.erase(it);
    return resp;
  }
  auto& reqs = it->second.requests;
  const Request& first = reqs[0];
  std::string error;
  if (first.op == OP_SEND || first.op == OP_RECV) {
    // Point-to-point pair (docs/pipeline.md): exactly two announcements
    // reached the full count — one OP_SEND and one OP_RECV, each naming
    // the other rank as its peer, with equal tag, dtype and shape.  The
    // agreement broadcasts to EVERY rank (caches mutate in lockstep);
    // only the pair executes it.
    const Request& a = reqs[0];
    const Request& b = reqs[1];
    const Request& snd = a.op == OP_SEND ? a : b;
    const Request& rcv = a.op == OP_SEND ? b : a;
    if (a.op == b.op)
      error = std::string("Mismatched point-to-point operations for '") +
              BaseName(name) + "': ranks " + std::to_string(a.rank) +
              " and " + std::to_string(b.rank) + " both posted " +
              OpName(a.op) +
              "; a pair needs one send and one matching recv.";
    else if (snd.p2p_peer != rcv.rank || rcv.p2p_peer != snd.rank)
      error = "Mismatched point-to-point peers for '" + BaseName(name) +
              "': rank " + std::to_string(snd.rank) +
              " sends to rank " + std::to_string(snd.p2p_peer) +
              " but rank " + std::to_string(rcv.rank) +
              " receives from rank " + std::to_string(rcv.p2p_peer) + ".";
    else if (snd.p2p_tag != rcv.p2p_tag)
      error = "Mismatched point-to-point tags for '" + BaseName(name) +
              "': send tag " + std::to_string(snd.p2p_tag) +
              " vs recv tag " + std::to_string(rcv.p2p_tag) + ".";
    else if (snd.dtype != rcv.dtype)
      error = std::string("Mismatched point-to-point data types: the "
                          "sender ships ") +
              DataTypeName(snd.dtype) + ", the receiver expects " +
              DataTypeName(rcv.dtype) + ".";
    else if (snd.dims != rcv.dims)
      error = "Mismatched point-to-point tensor shapes: the sender "
              "ships " + DimsToString(snd.dims) +
              ", the receiver expects " + DimsToString(rcv.dims) + ".";
    if (!error.empty()) {
      resp.type = RESP_ERROR;
      resp.error_message = error;
    } else {
      resp.type = RESP_SENDRECV;
      resp.p2p_src = snd.rank;
      resp.p2p_dst = rcv.rank;
      resp.p2p_tag = snd.p2p_tag;
      resp.p2p_dtype = snd.dtype;
      resp.p2p_dims = snd.dims;
    }
    // The NEGOTIATE row closed in HandleOneRequest at full count (2).
    coord_->message_table.erase(it);
    return resp;
  }
  for (size_t i = 1; i < reqs.size() && error.empty(); ++i) {
    const Request& r = reqs[i];
    if (r.op != first.op) {
      if (r.op == OP_NOOP || first.op == OP_NOOP) {
        // One camp replayed the cached cross-rank agreement (the XLA
        // plane's metadata-cache fast path) while another re-submitted
        // changed metadata: the shape/dtype/root consistency the metadata
        // allreduce would have checked no longer holds across ranks.
        int noop_rank = r.op == OP_NOOP ? r.rank : first.rank;
        int full_rank = r.op == OP_NOOP ? first.rank : r.rank;
        error = "Mismatched collective metadata for tensor '" +
                BaseName(name) + "': rank " + std::to_string(noop_rank) +
                " replayed the cached cross-rank agreement while rank " +
                std::to_string(full_rank) +
                " submitted changed metadata (shape/dtype/root); every "
                "rank must submit the same collective with the same shape "
                "and dtype.";
      } else {
        error = "Mismatched collective operations: rank " +
                std::to_string(r.rank) + " requested " + OpName(r.op) +
                ", rank " + std::to_string(first.rank) + " requested " +
                OpName(first.op) + ".";
      }
    } else if (r.dtype != first.dtype)
      error = std::string("Mismatched data types: one rank sent ") +
              DataTypeName(r.dtype) + ", another sent " +
              DataTypeName(first.dtype) + ".";
    else if (r.stage_ranks != first.stage_ranks)
      error = "Mismatched stage groups for '" + BaseName(name) +
              "': ranks " + std::to_string(r.rank) + " and " +
              std::to_string(first.rank) +
              " scoped the collective to different member lists; every "
              "member must pass the same stage group.";
    else if ((first.op == OP_ALLREDUCE || first.op == OP_NOOP) &&
             r.dims != first.dims)
      error = "Mismatched allreduce tensor shapes: one rank sent " +
              DimsToString(r.dims) + ", another sent " +
              DimsToString(first.dims) + ".";
    else if (first.op == OP_BROADCAST &&
             (r.dims != first.dims || r.root_rank != first.root_rank))
      error = r.root_rank != first.root_rank
                  ? "Mismatched broadcast root ranks: one rank requested root " +
                        std::to_string(r.root_rank) +
                        ", another requested root " +
                        std::to_string(first.root_rank) + "."
                  : "Mismatched broadcast tensor shapes: one rank sent " +
                        DimsToString(r.dims) + ", another sent " +
                        DimsToString(first.dims) + ".";
    else if (first.op == OP_ALLGATHER) {
      if (r.dims.size() != first.dims.size() || r.dims.empty())
        error = "Mismatched allgather tensor ranks (all ranks must send "
                "tensors of the same rank, with rank >= 1).";
      else
        for (size_t d = 1; d < r.dims.size(); ++d)
          if (r.dims[d] != first.dims[d]) {
            error = "Mismatched allgather tensor shapes: dimensions beyond "
                    "the first must agree across ranks (" +
                    DimsToString(r.dims) + " vs " + DimsToString(first.dims) +
                    ").";
            break;
          }
    }
  }
  if (first.op == OP_ALLGATHER && first.dims.empty())
    error = "Allgather requires tensors of rank >= 1.";
  if (first.op == OP_BROADCAST &&
      (first.root_rank < 0 || first.root_rank >= opts_.size))
    error = "Broadcast root rank " + std::to_string(first.root_rank) +
            " out of range [0, " + std::to_string(opts_.size) + ").";
  if (error.empty() && !first.stage_ranks.empty() &&
      first.op != OP_ALLREDUCE)
    error = std::string("Stage groups scope only allreduce; '") +
            BaseName(name) + "' requested " + OpName(first.op) + ".";
  if (!error.empty()) {
    resp.type = RESP_ERROR;
    resp.error_message = error;
  } else if (first.op == OP_ALLREDUCE) {
    resp.type = RESP_ALLREDUCE;
    if (!first.stage_ranks.empty()) {
      // Stage-scoped (docs/pipeline.md): the broadcast carries the
      // membership plus the payload signature, so NON-members can mutate
      // their response caches in lockstep without ever having seen a
      // request for this name.
      resp.stage_ranks = first.stage_ranks;
      resp.p2p_dtype = first.dtype;
      resp.p2p_dims = first.dims;
    }
  } else if (first.op == OP_NOOP) {
    resp.type = RESP_NOOP;
  } else if (first.op == OP_BROADCAST) {
    resp.type = RESP_BROADCAST;
  } else {
    resp.type = RESP_ALLGATHER;
    resp.rank_dim0.assign(opts_.size, 0);
    for (const Request& r : reqs) resp.rank_dim0[r.rank] = r.dims[0];
  }
  coord_->message_table.erase(it);
  return resp;
}

ResponseList Engine::CoordinatorTick() {
  ResponseList out;
  out.shutdown = coord_->shutdown_requested;
  if (coord_->abort_code != 0) {
    // Coordinated abort: carry only the abort verdict.  Deliberately no
    // op responses — a "ready" op would execute over ring sockets the
    // dead rank just broke; draining everything with the abort status is
    // uniform and safe.
    out.abort_code = coord_->abort_code;
    out.abort_message = coord_->abort_message;
    out.shutdown = true;
    return out;
  }
  // Cache hits agreed this tick: broadcast the slot indices; every rank
  // replays its stored response for each, in this order.
  out.cache_hits.swap(coord_->cached_ready);
  // Poison-deadline sweep: entries for a recently-mismatched base name
  // that are STILL short of full count at their deadline are stragglers
  // of the mismatched round — give them the typed error.
  const int64_t now_tick = ticks_done_.load();
  for (auto& kv : coord_->message_table) {
    auto& pt = kv.second;
    if (pt.poison_deadline_tick != 0 && now_tick >= pt.poison_deadline_tick &&
        pt.forced_error.empty() && !pt.requests.empty()) {
      auto poisoned = coord_->poisoned.find(BaseName(kv.first));
      pt.forced_error =
          poisoned != coord_->poisoned.end()
              ? poisoned->second.first
              : "cross-transport mismatch for tensor '" + BaseName(kv.first) +
                    "' (straggler of an earlier mismatched round).";
      coord_->ready.push_back(kv.first);
    }
  }
  if (coord_->ready.empty()) return out;
  std::vector<std::string> ready;
  ready.swap(coord_->ready);
  std::vector<Response> responses;
  std::vector<int64_t> nbytes;   // per response, for fusion accounting
  std::vector<uint8_t> ndtypes;  // per response, for the compression verdict
  for (const auto& name : ready) {
    // Byte size must be computed before BuildResponse erases the table entry.
    auto& pt = coord_->message_table[name];
    const Request& first = pt.requests[0];
    int64_t bytes = NumElements(first.dims) *
                    static_cast<int64_t>(DataTypeSize(first.dtype));
    uint8_t dtype = first.dtype;
    Response r = BuildResponse(name);
    // Autotune window accounting: one fresh negotiation of `bytes`
    // payload (negotiation-only no-ops score as ops moving zero bytes).
    if (tuner_.active() && r.type != RESP_ERROR)
      tuner_.Record(r.type == RESP_NOOP ? 0 : bytes, 1);
    // Tensor fusion: merge consecutive same-dtype allreduces while the fused
    // payload stays under the threshold (operations.cc:1607-1642).
    if (r.type == RESP_ALLREDUCE && r.stage_ranks.empty() &&
        !responses.empty() &&
        FusesInto(responses.back(), nbytes.back(), last_fused_dtype_, dtype,
                  bytes, opts_.fusion_threshold)) {
      responses.back().names.push_back(name);
      nbytes.back() += bytes;
    } else {
      responses.push_back(std::move(r));
      nbytes.push_back(bytes);
      ndtypes.push_back(dtype);
      last_fused_dtype_ = dtype;
    }
  }
  // Wire-compression verdict, per FINAL bucket (the fusion loop above may
  // have grown a bucket past the min-bytes floor, so the decision runs
  // after fusion settles): stamped on the broadcast response so every
  // rank packs/unpacks the same format.  The COMPRESS attr also lands on
  // each tensor's NEGOTIATE timeline row at the coordinator.
  for (size_t i = 0; i < responses.size(); ++i) {
    Response& r = responses[i];
    if (r.type == RESP_SENDRECV) {
      // A p2p transfer compresses only when the pair spans nodes (the
      // DCN hop, where bytes cost money) and the payload is fp32 — the
      // same policy the two-level allreduce applies to its cross hop.
      // The verdict is stored with the cached agreement and replayed
      // verbatim: p2p never re-fuses, so there is no bucket geometry to
      // recompute at replay time.
      bool cross_node =
          opts_.hierarchical_allreduce && opts_.local_size > 0 &&
          r.p2p_src / opts_.local_size != r.p2p_dst / opts_.local_size;
      if (cross_node && r.p2p_dtype == HVD_FLOAT32)
        r.compression = ChooseCompression(r.p2p_dtype, nbytes[i]);
      continue;
    }
    if (r.type != RESP_ALLREDUCE || !r.stage_ranks.empty()) continue;
    r.compression = ChooseCompression(ndtypes[i], nbytes[i]);
    if (r.compression != COMP_NONE && timeline_.Enabled())
      for (const auto& name : r.names)
        timeline_.Instant(
            name, std::string("COMPRESS_") + CompressionName(r.compression));
  }
  out.responses = std::move(responses);
  return out;
}

void Engine::CheckForStalledTensors() {
  auto now = std::chrono::steady_clock::now();
  if (now - last_stall_check_ <
      std::chrono::duration<double>(opts_.stall_warning_sec))
    return;
  last_stall_check_ = now;
  bool preamble = false;
  // One record per stalled negotiation, whether it is pending as full
  // string requests (message_table) or as cache-bit announcements.
  auto warn = [&](const std::string& name, const std::vector<bool>& present,
                  std::chrono::steady_clock::time_point first_seen) {
    {
      // Record for the Python metrics registry (hvd_tpu_stall_count /
      // hvd_tpu_stall_info): one event per (tensor, sweep) warning.
      double stalled_sec =
          std::chrono::duration<double>(now - first_seen).count();
      std::lock_guard<std::mutex> lk(stall_mu_);
      ++stall_events_;
      stall_log_.emplace_back(name, stalled_sec);
      while (stall_log_.size() > 64) stall_log_.pop_front();
    }
    if (flight_.Enabled())
      flight_.Record(
          FL_STALL, name,
          static_cast<int64_t>(
              std::chrono::duration<double>(now - first_seen).count()));
    if (!preamble) {
      fprintf(stderr,
              "[horovod_tpu] WARNING: One or more tensors were submitted to "
              "be reduced, gathered or broadcasted by subset of ranks and are "
              "waiting for remainder of ranks for more than %.0f seconds. "
              "This may indicate that different ranks are trying to submit "
              "different tensors or that only subset of ranks is submitting "
              "tensors, which will cause deadlock.\nStalled ops:\n",
              opts_.stall_warning_sec);
      preamble = true;
    }
    fprintf(stderr, "%s [missing ranks: %s]\n", name.c_str(),
            MissingRanks(present).c_str());
  };
  for (const auto& kv : coord_->message_table) {
    if (now - kv.second.first_seen <
        std::chrono::duration<double>(opts_.stall_warning_sec))
      continue;
    // Ranks outside a partial-participation op's expected set are not
    // "missing" — mask them present so the warning names only the
    // genuinely absent participants (the p2p peer, the stage members).
    std::vector<bool> present(opts_.size, false);
    for (const auto& r : kv.second.requests)
      if (r.rank >= 0 && r.rank < opts_.size) present[r.rank] = true;
    std::vector<bool> expected =
        ExpectedRanks(kv.second.requests, opts_.size);
    for (int r = 0; r < opts_.size; ++r)
      if (!expected[r]) present[r] = true;
    warn(kv.first, present, kv.second.first_seen);
  }
  for (const auto& kv : coord_->cache_pending) {
    if (now - kv.second.first_seen <
        std::chrono::duration<double>(opts_.stall_warning_sec))
      continue;
    const CacheSlot* s = cache_.Get(static_cast<int>(kv.first));
    std::vector<bool> present = kv.second.ranks;
    std::vector<bool> expected = SlotExpectedRanks(s, opts_.size);
    for (int r = 0; r < opts_.size && r < static_cast<int>(present.size());
         ++r)
      if (!expected[r]) present[r] = true;
    warn(s ? s->name : "<cache slot " + std::to_string(kv.first) + ">",
         present, kv.second.first_seen);
  }
}

int64_t Engine::StallEvents() {
  std::lock_guard<std::mutex> lk(stall_mu_);
  return stall_events_;
}

std::string Engine::StallInfo() {
  std::lock_guard<std::mutex> lk(stall_mu_);
  std::string out;
  for (const auto& rec : stall_log_) {
    if (!out.empty()) out += ';';
    for (char c : rec.first) out += (c == ';' || c == '|') ? '_' : c;
    char buf[32];
    snprintf(buf, sizeof(buf), "|%.3f", rec.second);
    out += buf;
  }
  return out;
}

// ---------------------------------------------------------------------------
// Coordinated abort (fault tolerance, docs/fault-tolerance.md).
// ---------------------------------------------------------------------------

namespace {

// "a, b [missing ranks: 1, 3]" for one pending tensor.  An unmatched
// p2p announce names the tensor AND the absent counterpart explicitly —
// the paired-readiness diagnosis docs/pipeline.md#fault-semantics
// promises ("rank 1's send of 'act_s0' waits on rank 2's recv").
std::string DescribePending(const std::string& name,
                            const std::vector<Request>& reqs, int size) {
  if (reqs.size() == 1 &&
      (reqs[0].op == OP_SEND || reqs[0].op == OP_RECV)) {
    const Request& r = reqs[0];
    return "'" + name + "' [" + OpName(r.op) + " announced by rank " +
           std::to_string(r.rank) + "; waiting for the matching " +
           (r.op == OP_SEND ? "recv" : "send") + " from peer rank " +
           std::to_string(r.p2p_peer) + "]";
  }
  std::vector<bool> present(size, false);
  for (const auto& r : reqs)
    if (r.rank >= 0 && r.rank < size) present[r.rank] = true;
  std::vector<bool> expected = ExpectedRanks(reqs, size);
  std::string missing;
  for (int r = 0; r < size; ++r)
    if (expected[r] && !present[r])
      missing += (missing.empty() ? "" : ", ") + std::to_string(r);
  return "'" + name + "' [missing ranks: " + missing + "]";
}

}  // namespace

void Engine::MarkRankDead(int r, const std::string& reason) {
  if (coord_->rank_dead[r]) return;
  coord_->rank_dead[r] = true;
  if (opts_.elastic && coord_->abort_code == 0) {
    // Shrink-and-continue (docs/fault-tolerance.md#elastic-membership):
    // with enough survivors, arm a reshape barrier at the next tick
    // instead of the fatal abort cascade.  Rank 0 hosts the coordinator,
    // so it is alive by construction here; more deaths observed in the
    // same sweep accumulate into the same barrier, and dropping below
    // min_size falls through to the abort (the checkpoint-restart
    // fallback hvdrun --min-np relies on).
    int alive = 0;
    for (int i = 0; i < opts_.size; ++i)
      if (!coord_->rank_dead[i]) ++alive;
    if (alive >= std::max<int64_t>(opts_.min_size, 1)) {
      coord_->reshape_pending = true;
      fprintf(stderr,
              "[horovod_tpu] WARNING: rank %d down (%s); elastic reshape "
              "at the next tick (%d survivor(s), membership epoch %lld -> "
              "%lld).\n",
              r, reason.c_str(), alive,
              static_cast<long long>(membership_epoch_.load()),
              static_cast<long long>(membership_epoch_.load() + 1));
      return;
    }
    coord_->reshape_pending = false;  // below min_size: abort instead
  }
  if (coord_->abort_code != 0) return;  // first abort wins
  std::string down;
  std::vector<int> dead_ranks;
  for (int i = 0; i < opts_.size; ++i)
    if (coord_->rank_dead[i]) {
      down += (down.empty() ? "" : ", ") + std::to_string(i);
      dead_ranks.push_back(i);
    }
  std::string pending;
  int listed = 0;
  for (const auto& kv : coord_->message_table) {
    if (listed == 8) {
      pending += ", ...";
      break;
    }
    pending += (pending.empty() ? "" : "; ") +
               DescribePending(kv.first, kv.second.requests, opts_.size);
    ++listed;
  }
  for (const auto& kv : coord_->cache_pending) {
    if (listed == 8) {
      pending += ", ...";
      break;
    }
    const CacheSlot* s = cache_.Get(static_cast<int>(kv.first));
    pending += (pending.empty() ? "" : "; ") + std::string("'") +
               (s ? s->name : "<cache slot>") +
               "' [missing ranks: " + MissingRanks(kv.second.ranks) + "]";
    ++listed;
  }
  coord_->abort_code = ST_RANKS_DOWN;
  coord_->abort_message =
      "ranks down: " + down + " (" + reason + ")" +
      (pending.empty() ? std::string(".")
                       : "; pending collective(s): " + pending + ".") +
      (opts_.elastic
           ? " Survivors fell below the elastic minimum (--min-np " +
                 std::to_string(static_cast<long long>(opts_.min_size)) +
                 "), so the job cannot shrink further."
           : std::string()) +
      " The job was aborted; restart it (e.g. hvdrun --max-restarts) to "
      "resume from the latest checkpoint." +
      " cross-rank diagnosis: " + BuildDiagnosis(dead_ranks);
}

void Engine::CheckCollectiveTimeout() {
  if (opts_.collective_timeout_sec <= 0 || coord_->abort_code != 0) return;
  // An armed reshape barrier poisons every in-flight collective with the
  // retryable ST_RESHAPE at this very tick and clears the pending
  // tables.  Entries here may already be past the timeout — the liveness
  // WaitReadable that detected the dead rank blocked for the full
  // timeout while they aged — so latching fatal ST_TIMEOUT now would
  // preempt the shrink-and-continue the elastic path just armed (a
  // frozen rank would kill the job where a crashed one would not).
  if (coord_->reshape_pending) return;
  auto now = std::chrono::steady_clock::now();
  std::string stalled;
  double worst = 0.0;
  int n_stalled = 0;
  std::vector<bool> missing_any(opts_.size, false);
  auto note_missing = [&](const std::vector<bool>& present) {
    for (int r = 0; r < opts_.size && r < static_cast<int>(present.size());
         ++r)
      if (!present[r]) missing_any[r] = true;
  };
  for (const auto& kv : coord_->message_table) {
    if (kv.second.requests.empty() || !kv.second.forced_error.empty())
      continue;
    double age =
        std::chrono::duration<double>(now - kv.second.first_seen).count();
    if (age < opts_.collective_timeout_sec) continue;
    worst = std::max(worst, age);
    ++n_stalled;
    // Mask ranks outside the op's expected participant set (p2p pair /
    // stage group): the abort must name the absent counterpart, not the
    // whole uninvolved world.
    std::vector<bool> present(opts_.size, false);
    for (const auto& r : kv.second.requests)
      if (r.rank >= 0 && r.rank < opts_.size) present[r.rank] = true;
    std::vector<bool> expected =
        ExpectedRanks(kv.second.requests, opts_.size);
    for (int r = 0; r < opts_.size; ++r)
      if (!expected[r]) present[r] = true;
    note_missing(present);
    if (n_stalled <= 8)
      stalled += (stalled.empty() ? "" : "; ") +
                 DescribePending(kv.first, kv.second.requests, opts_.size);
  }
  for (const auto& kv : coord_->cache_pending) {
    double age =
        std::chrono::duration<double>(now - kv.second.first_seen).count();
    if (age < opts_.collective_timeout_sec) continue;
    worst = std::max(worst, age);
    ++n_stalled;
    const CacheSlot* s = cache_.Get(static_cast<int>(kv.first));
    std::vector<bool> present = kv.second.ranks;
    std::vector<bool> expected = SlotExpectedRanks(s, opts_.size);
    for (int r = 0; r < opts_.size && r < static_cast<int>(present.size());
         ++r)
      if (!expected[r]) present[r] = true;
    note_missing(present);
    if (n_stalled <= 8) {
      stalled += (stalled.empty() ? "" : "; ") + std::string("'") +
                 (s ? s->name : "<cache slot>") +
                 "' [missing ranks: " + MissingRanks(present) + "]";
    }
  }
  if (n_stalled == 0) return;
  std::vector<int> missing_ranks;
  for (int r = 0; r < opts_.size; ++r)
    if (missing_any[r]) missing_ranks.push_back(r);
  if (n_stalled > 8)
    stalled += "; ... (" + std::to_string(n_stalled - 8) + " more)";
  char worst_buf[32];
  snprintf(worst_buf, sizeof(worst_buf), "%.1f", worst);
  coord_->abort_code = ST_TIMEOUT;
  coord_->abort_message =
      std::string("collective timeout: tensor(s) stalled for ") + worst_buf +
      "s (> HVD_TPU_COLLECTIVE_TIMEOUT_SEC=" +
      std::to_string(static_cast<long long>(opts_.collective_timeout_sec)) +
      "): " + stalled +
      ". One or more ranks never submitted the matching collective; the "
      "job was aborted instead of hanging." +
      " cross-rank diagnosis: " + BuildDiagnosis(missing_ranks);
}

void Engine::AbortLocal(int32_t code, const std::string& message) {
  // Freeze the in-flight table BEFORE the latch: the BackgroundLoop
  // drain clears table_ moments later, and the postmortem dump must
  // still know what was pending at the moment of death.
  std::string pending = LivePendingInfo();
  {
    std::lock_guard<std::mutex> lk(abort_mu_);
    if (abort_code_.load() != 0) return;  // first abort wins
    abort_message_ = message;
    abort_pending_info_ = std::move(pending);
    abort_code_.store(code);
  }
  abort_events_.fetch_add(1);
  if (flight_.Enabled()) flight_.Record(FL_ABORT, "", code);
  // A broken job must fail every subsequent collective uniformly.
  data_plane_failed_.store(true);
  // Invalidate the response cache: the peers' caches die with the job,
  // and a relaunch must renegotiate from scratch (docs/performance.md).
  cache_.Clear();
  cache_size_.store(0);
  if (coord_) coord_->cache_pending.clear();
  // Aborting jobs often die before Python reaches shutdown(): flush now
  // so the trace on disk parses (the BackgroundLoop drain flushes again
  // after the final completions land).
  timeline_.Flush();
  fprintf(stderr, "[horovod_tpu] ERROR: coordinated abort on rank %d: %s\n",
          opts_.rank, message.c_str());
}

std::string Engine::AbortMessage() {
  std::lock_guard<std::mutex> lk(abort_mu_);
  return abort_message_;
}

// ---------------------------------------------------------------------------
// Postmortem plane (flight recorder drains, pending tables, diagnosis).
// ---------------------------------------------------------------------------

namespace {

// The marker Python and Diagnosis() split the broadcast abort message on.
const char kDiagnosisMarker[] = "cross-rank diagnosis: ";

std::string SanitizeInfo(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (char c : s) out += (c == ';' || c == '|') ? '_' : c;
  return out;
}

}  // namespace

std::string Engine::BuildDiagnosis(const std::vector<int>& missing) {
  // Engine thread only (coordinator tables).  One paragraph: where the
  // job is, and — for each rank a stalled collective waits on — the last
  // thing the coordinator ever heard from it.  This is the "rank 2
  // stopped announcing after tick 1841" story the postmortem renders.
  if (!coord_) return "no coordinator state on this rank.";
  int64_t cur = ticks_done_.load();
  std::string out = "the coordinator is at tick " + std::to_string(cur);
  if (missing.empty()) return out + "; no rank is missing.";
  for (int r : missing) {
    out += "; rank " + std::to_string(r);
    bool announced =
        r >= 0 && r < static_cast<int>(coord_->last_announce_tick.size()) &&
        coord_->last_announce_tick[r] >= 0;
    if (announced) {
      out += " last announced '" + coord_->last_announce_name[r] +
             "' at tick " + std::to_string(coord_->last_announce_tick[r]) +
             " and stopped announcing after that";
    } else {
      out += " never announced any collective";
    }
    if (r > 0 && r < static_cast<int>(coord_->last_frame_tick.size())) {
      out += coord_->last_frame_tick[r] >= 0
                 ? " (last control-plane frame at tick " +
                       std::to_string(coord_->last_frame_tick[r]) + ")"
                 : " (no control-plane frame ever received)";
    }
  }
  return out + ".";
}

std::string Engine::Diagnosis() {
  std::lock_guard<std::mutex> lk(abort_mu_);
  size_t pos = abort_message_.find(kDiagnosisMarker);
  if (pos == std::string::npos) return "";
  return abort_message_.substr(pos + sizeof(kDiagnosisMarker) - 1);
}

std::string Engine::LivePendingInfo() {
  auto now = std::chrono::steady_clock::now();
  std::string out;
  std::lock_guard<std::mutex> lk(mu_);
  int listed = 0;
  for (const auto& kv : table_) {
    if (listed++ == 64) break;
    int64_t age_us = std::chrono::duration_cast<std::chrono::microseconds>(
                         now - kv.second.enqueued_at)
                         .count();
    out += (out.empty() ? "" : ";") + SanitizeInfo(kv.first) + "|" +
           OpName(kv.second.op) + "|" + std::to_string(age_us);
  }
  return out;
}

std::string Engine::PendingInfo() {
  std::string live = LivePendingInfo();
  if (!live.empty()) return live;
  // Post-abort the drain has emptied the table; serve the snapshot the
  // abort froze instead.
  std::lock_guard<std::mutex> lk(abort_mu_);
  return abort_code_.load() != 0 ? abort_pending_info_ : live;
}

void Engine::UpdateCoordPendingInfo() {
  // Engine thread, rank 0 (and size-1), once per tick.  Negotiations
  // normally resolve within a tick or two, so the tables are almost
  // always empty and this is a lock + an empty-compare.
  if (!coord_) return;
  std::string info;
  auto now = std::chrono::steady_clock::now();
  int listed = 0;
  for (const auto& kv : coord_->message_table) {
    if (listed++ == 64) break;
    if (kv.second.requests.empty()) continue;
    std::vector<bool> present(opts_.size, false);
    for (const auto& r : kv.second.requests)
      if (r.rank >= 0 && r.rank < opts_.size) present[r.rank] = true;
    int64_t age_us = std::chrono::duration_cast<std::chrono::microseconds>(
                         now - kv.second.first_seen)
                         .count();
    std::string missing;
    for (int r = 0; r < opts_.size; ++r)
      if (!present[r])
        missing += (missing.empty() ? "" : " ") + std::to_string(r);
    info += (info.empty() ? "" : ";") + SanitizeInfo(kv.first) + "|" +
            std::to_string(age_us) + "|" + missing;
  }
  for (const auto& kv : coord_->cache_pending) {
    if (listed++ == 64) break;
    const CacheSlot* s = cache_.Get(static_cast<int>(kv.first));
    int64_t age_us = std::chrono::duration_cast<std::chrono::microseconds>(
                         now - kv.second.first_seen)
                         .count();
    std::string missing;
    for (int r = 0;
         r < opts_.size && r < static_cast<int>(kv.second.ranks.size()); ++r)
      if (!kv.second.ranks[r])
        missing += (missing.empty() ? "" : " ") + std::to_string(r);
    info += (info.empty() ? "" : ";") +
            SanitizeInfo(s ? s->name
                           : "<cache slot " + std::to_string(kv.first) + ">") +
            "|" + std::to_string(age_us) + "|" + missing;
  }
  std::lock_guard<std::mutex> lk(coord_info_mu_);
  if (coord_pending_info_ != info) coord_pending_info_ = std::move(info);
}

std::string Engine::CoordPendingInfo() {
  std::lock_guard<std::mutex> lk(coord_info_mu_);
  return coord_pending_info_;
}

// ---------------------------------------------------------------------------
// Online autotuning (docs/performance.md#autotuning).
// ---------------------------------------------------------------------------

void Engine::AttachTunedParams(ResponseList* out) {
  // No proposals on abort/shutdown ticks: the job is ending, and the
  // drain paths must not race a parameter mutation.
  if (out->abort_code != 0 || out->shutdown) return;
  ParameterManager::Proposal p;
  tuner_.Tick(std::chrono::steady_clock::now(), cur_fusion_.load(),
              static_cast<double>(cur_cycle_us_.load()) / 1000.0,
              cur_compression_.load(), cur_cross_algo_.load(), &p);
  if (!p.present) return;
  out->tuned_present = true;
  out->tuned_frozen = p.frozen;
  out->tuned_fusion_threshold = p.fusion_threshold;
  out->tuned_cycle_time_us = p.cycle_time_us;
  out->tuned_compression = static_cast<uint8_t>(p.compression);
  out->tuned_cross_algo_threshold = p.cross_algo_threshold;
  out->tuned_window = p.window;
}

void Engine::ApplyTunedParams(const ResponseList& rl) {
  // Runs on the engine thread of EVERY rank while processing the same
  // broadcast list, before this tick's cache-hit replay: the tick index
  // below is therefore identical everywhere, which is what makes the
  // applied log comparable across ranks and the fusion history a
  // deterministic function of the tick.
  int64_t tick = ticks_done_.load();
  bool comp_changed =
      cur_compression_.load() != static_cast<int64_t>(rl.tuned_compression);
  opts_.fusion_threshold = rl.tuned_fusion_threshold;
  opts_.cycle_time_ms =
      static_cast<double>(rl.tuned_cycle_time_us) / 1000.0;
  opts_.compression_mode = rl.tuned_compression;
  opts_.cross_algo_threshold = rl.tuned_cross_algo_threshold;
  cur_fusion_.store(rl.tuned_fusion_threshold);
  cur_cycle_us_.store(rl.tuned_cycle_time_us);
  cur_compression_.store(rl.tuned_compression);
  cur_cross_algo_.store(rl.tuned_cross_algo_threshold);
  if (rl.tuned_frozen) autotune_frozen_.store(true);
  applied_window_.store(rl.tuned_window);
  {
    std::lock_guard<std::mutex> lk(autotune_mu_);
    char buf[144];
    snprintf(buf, sizeof(buf), "%lld|%lld|%lld|%d|%lld|%d",
             static_cast<long long>(tick),
             static_cast<long long>(rl.tuned_fusion_threshold),
             static_cast<long long>(rl.tuned_cycle_time_us),
             static_cast<int>(rl.tuned_compression),
             static_cast<long long>(rl.tuned_cross_algo_threshold),
             rl.tuned_frozen ? 1 : 0);
    applied_log_.emplace_back(buf);
    while (applied_log_.size() > 256) applied_log_.pop_front();
    if (fusion_history_.empty() ||
        fusion_history_.back().second != rl.tuned_fusion_threshold)
      fusion_history_.emplace_back(tick, rl.tuned_fusion_threshold);
    if (compression_history_.empty() ||
        compression_history_.back().second !=
            static_cast<int64_t>(rl.tuned_compression))
      compression_history_.emplace_back(
          tick, static_cast<int64_t>(rl.tuned_compression));
    // Bounded: a pathological external policy (hvd.autotune_set per
    // phase, for hours) must not grow this without limit.  Dropping the
    // oldest change point makes the second-oldest the floor for all
    // earlier ticks — safe, because the plane only queries ticks that
    // closed recently.
    while (fusion_history_.size() > 1024) fusion_history_.pop_front();
    while (compression_history_.size() > 1024)
      compression_history_.pop_front();
  }
  timeline_.Instant("autotune",
                    rl.tuned_frozen ? "AUTOTUNE_FREEZE" : "AUTOTUNE_APPLY");
  if (flight_.Enabled()) {
    flight_.Record(FL_TUNE, "", rl.tuned_fusion_threshold);
    // Tune-style compression event (postmortem plane): straggler reports
    // must show WHICH wire format a stalled bucket was using, so mode
    // changes land in the ring next to the tick they applied at.
    if (comp_changed)
      flight_.Record(FL_COMPRESS, "", rl.tuned_compression);
  }
}

int64_t Engine::AutotuneWindows() {
  // API threads call this live; the atomic mirrors, not opts_, are the
  // reshape-safe identity (elastic reassigns opts_.rank/size mid-run on
  // the engine thread — a TSan-confirmed race when this read opts_).
  if (cur_rank_.load() == 0 || cur_size_.load() == 1)
    return tuner_.windows();
  return applied_window_.load();
}

std::string Engine::AutotuneApplied() {
  std::lock_guard<std::mutex> lk(autotune_mu_);
  std::string out;
  for (const auto& e : applied_log_) {
    if (!out.empty()) out += ';';
    out += e;
  }
  return out;
}

int Engine::AutotuneInject(int64_t fusion, double cycle_ms,
                           int64_t compression, int64_t cross_algo) {
  if (!initialized_.load()) return 2;
  if (opts_.rank != 0 && opts_.size > 1) return 1;
  tuner_.Inject(fusion, cycle_ms, compression, cross_algo);
  return 0;
}

int64_t Engine::FusionThresholdAt(int64_t tick) {
  std::lock_guard<std::mutex> lk(autotune_mu_);
  if (fusion_history_.empty()) return cur_fusion_.load();
  // Last change point at or before `tick` (the history is tiny: one
  // entry per applied threshold change).
  int64_t value = fusion_history_.front().second;
  for (const auto& e : fusion_history_) {
    if (e.first > tick) break;
    value = e.second;
  }
  return value;
}

int64_t Engine::CompressionModeAt(int64_t tick) {
  std::lock_guard<std::mutex> lk(autotune_mu_);
  if (compression_history_.empty()) return cur_compression_.load();
  int64_t value = compression_history_.front().second;
  for (const auto& e : compression_history_) {
    if (e.first > tick) break;
    value = e.second;
  }
  return value;
}

// ---------------------------------------------------------------------------
// Elastic membership (docs/fault-tolerance.md#elastic-membership).
//
// The rank-0 coordinator already OWNS membership: liveness, negotiation
// counts, and the broadcast response list all key off it.  A reshape is
// therefore just another lockstep broadcast: the coordinator ships the new
// membership (dense ranks + endpoints + the parameters the new job must
// agree on) in the response list, and every rank adopts it at the same
// tick boundary — cancelling in-flight collectives with the RETRYABLE
// ST_RESHAPE status, clearing the response cache and autotune search (so
// slot numbering and tuned params stay lockstep in the new membership),
// and rebuilding the flat data ring over the still-open listen sockets.
// ---------------------------------------------------------------------------

namespace {

std::string RankCsv(const std::vector<int32_t>& ranks) {
  std::string out;
  for (int32_t r : ranks)
    out += (out.empty() ? "" : ", ") + std::to_string(r);
  return out;
}

}  // namespace

namespace {

// Endpoints are "host:port" strings; anything past this is a corrupt or
// hostile frame length, not a real standby.
const uint32_t kMaxJoinEndpointLen = 1024;

// Incremental parse of a joiner's endpoint frame ([u32 LE length]
// [payload]) out of the bytes assembled so far.  Returns 1 with *ep
// filled when the frame is complete, 0 when more bytes are needed, and
// -1 when the bytes can never become a valid frame (zero/oversize
// length, or trailing junk after the payload).
int ParseJoinEndpointFrame(const std::vector<uint8_t>& buf,
                           std::string* ep) {
  if (buf.size() < 4) return 0;
  uint32_t len = static_cast<uint32_t>(buf[0]) |
                 (static_cast<uint32_t>(buf[1]) << 8) |
                 (static_cast<uint32_t>(buf[2]) << 16) |
                 (static_cast<uint32_t>(buf[3]) << 24);
  if (len == 0 || len > kMaxJoinEndpointLen) return -1;
  if (buf.size() < 4 + static_cast<size_t>(len)) return 0;
  if (buf.size() > 4 + static_cast<size_t>(len)) return -1;
  ep->assign(buf.begin() + 4, buf.end());
  return 1;
}

}  // namespace

bool Engine::RegisterJoiner(int fd, double timeout_sec) {
  // The joiner's hello word has been consumed; assemble its endpoint
  // frame with bounded non-blocking reads (a trickled or truncated frame
  // costs at most timeout_sec, never a blocked engine loop) and park it
  // for the next reshape barrier.
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(timeout_sec);
  std::vector<uint8_t> epbuf;
  std::string ep;
  while (true) {
    if (!RecvAvailable(fd, &epbuf)) return false;
    int rc = ParseJoinEndpointFrame(epbuf, &ep);
    if (rc < 0) return false;
    if (rc > 0) break;
    double remaining = std::chrono::duration<double>(
                           deadline - std::chrono::steady_clock::now())
                           .count();
    if (remaining <= 0 || !WaitReadable(fd, remaining)) return false;
  }
  return RegisterJoinerEndpoint(fd, ep);
}

bool Engine::RegisterJoinerEndpoint(int fd, const std::string& ep) {
  // Duplicate endpoints (a standby retrying, or one colliding with a
  // LIVE member) are refused.  A dead rank's endpoint is fair game: a
  // fixed-endpoint deployment restarts the replacement on the same
  // host:port, and refusing it would crash-loop the standby while the
  // job shrinks instead of backfilling.
  bool dup = false;
  for (const auto& e : coord_->pending_join_endpoints) dup |= (e == ep);
  // rank_dead is sized by the job size; an endpoint list longer than it
  // (env-launched job with a stale HVD_TPU_DATA) counts the extras as
  // live rather than reading past the vector.
  for (size_t r = 0; r < opts_.data_endpoints.size(); ++r)
    if (r >= coord_->rank_dead.size() || !coord_->rank_dead[r])
      dup |= (opts_.data_endpoints[r] == ep);
  if (dup) return false;
  if (coord_->pending_join_fds.empty())
    coord_->join_wait_since = std::chrono::steady_clock::now();
  coord_->pending_join_fds.push_back(fd);
  coord_->pending_join_endpoints.push_back(ep);
  fprintf(stderr,
          "[horovod_tpu] standby %s registered with the coordinator; "
          "admitting at the next reshape barrier.\n",
          ep.c_str());
  return true;
}

void Engine::CoordinatorAcceptJoiners() {
  if (!opts_.elastic || coord_listen_fd_ < 0) return;
  // Drain the listen backlog without blocking (at most a few per tick).
  // The handshake itself is deferred: a standby sends hello+endpoint
  // immediately after connect, so its fd turns readable within a tick,
  // while a non-joiner connect that never sends (port scanner, health
  // check, load-balancer probe) parks in `handshaking` at zero cost to
  // the tick and is dropped at its deadline — it must not be able to
  // stall every worker's negotiation wait behind a blocking read.
  for (int accepted = 0; accepted < 4 && WaitReadable(coord_listen_fd_, 0.0);
       ++accepted) {
    std::string err;
    int fd = AcceptOne(coord_listen_fd_, 0.0, &err);
    if (fd < 0) break;
    coord_->handshaking.push_back(
        {fd, std::chrono::steady_clock::now() + std::chrono::seconds(5)});
  }
  for (size_t i = coord_->handshaking.size(); i-- > 0;) {
    auto& hs = coord_->handshaking[i];
    // Assemble the hello + endpoint frame strictly from bytes already in
    // the kernel buffer: a peer that trickles a partial handshake parks
    // here until its deadline and can never block the tick mid-message.
    bool settled = false;
    if (!RecvAvailable(hs.fd, &hs.buf)) {
      settled = true;  // EOF or socket error before a full handshake
      CloseFd(hs.fd);
    } else {
      uint32_t hello = 0;
      if (hs.buf.size() >= 4) memcpy(&hello, hs.buf.data(), 4);
      if (hs.buf.size() >= 4 && hello != kJoinHello) {
        settled = true;  // not a joiner (probe, scanner, stale connect)
        CloseFd(hs.fd);
      } else if (hs.buf.size() >= 4) {
        std::string ep;
        std::vector<uint8_t> frame(hs.buf.begin() + 4, hs.buf.end());
        int rc = ParseJoinEndpointFrame(frame, &ep);
        if (rc != 0) {
          settled = true;
          if (rc < 0 || !RegisterJoinerEndpoint(hs.fd, ep))
            CloseFd(hs.fd);
        }
      }
    }
    if (!settled && std::chrono::steady_clock::now() >= hs.deadline) {
      settled = true;
      CloseFd(hs.fd);
    }
    if (settled)
      coord_->handshaking.erase(coord_->handshaking.begin() + i);
  }
}

bool Engine::CoordinatorMaybeReshape(ResponseList* out) {
  if (!opts_.elastic || out->abort_code != 0 || out->shutdown) return false;
  // Sweep joiners that died while waiting for admission: broadcasting a
  // dead standby's endpoint would send every survivor's RebuildRing
  // chasing a closed port and turn a healthy elastic job into a fatal
  // abort.  (A joiner has nothing to send after registering, so any
  // readable state here is EOF/error.)
  for (size_t i = coord_->pending_join_fds.size(); i-- > 0;) {
    if (!PeerClosed(coord_->pending_join_fds[i])) continue;
    fprintf(stderr,
            "[horovod_tpu] standby %s died before admission; dropped.\n",
            coord_->pending_join_endpoints[i].c_str());
    CloseFd(coord_->pending_join_fds[i]);
    coord_->pending_join_fds.erase(coord_->pending_join_fds.begin() + i);
    coord_->pending_join_endpoints.erase(
        coord_->pending_join_endpoints.begin() + i);
  }
  bool shrink = coord_->reshape_pending;
  // A grow-only barrier waits for a quiesced tick (nothing pending or
  // broadcast this tick, and the previous reshape acknowledged) so the
  // interruption is limited to the enqueue-poison handshake; a shrink
  // barrier fires immediately — everything in flight is doomed anyway.
  bool grow = !shrink && !coord_->pending_join_fds.empty() &&
              coord_->message_table.empty() &&
              coord_->cache_pending.empty() && out->responses.empty() &&
              out->cache_hits.empty() && !reshape_ack_pending_.load();
  if (!shrink && !grow && !coord_->pending_join_fds.empty() &&
      !reshape_ack_pending_.load()) {
    // A fully pipelined loop (async enqueues keeping every tick busy)
    // may never present a quiesced tick; past a bounded wait, force the
    // barrier so admission cannot starve — the standby's own admission
    // timeout (120s in SetupRejoinSockets) is the backstop this must
    // beat.  In-flight collectives get the retryable ST_RESHAPE exactly
    // as in a shrink.
    constexpr double kForcedGrowSec = 10.0;
    double waited = std::chrono::duration<double>(
                        std::chrono::steady_clock::now() -
                        coord_->join_wait_since)
                        .count();
    if (waited >= kForcedGrowSec) {
      fprintf(stderr,
              "[horovod_tpu] standby waited %.1fs without a quiesced "
              "tick; forcing the grow barrier (in-flight collectives "
              "will retry in the new membership).\n",
              waited);
      grow = true;
    }
  }
  if (!shrink && !grow) return false;
  // The reshape replaces this tick's payload: op responses built against
  // the old membership would execute over a ring a dead rank just broke,
  // and cache hits would replay against caches the barrier is clearing.
  out->responses.clear();
  out->cache_hits.clear();
  out->tuned_present = false;
  out->reshape_present = true;
  out->membership_epoch = membership_epoch_.load() + 1;
  out->reshape_cache_capacity = opts_.cache_capacity;
  out->reshape_fusion_threshold = cur_fusion_.load();
  out->reshape_cycle_time_us = cur_cycle_us_.load();
  out->reshape_compression = static_cast<uint8_t>(cur_compression_.load());
  out->reshape_compression_min_bytes = opts_.compression_min_bytes;
  out->reshape_cross_algo_threshold = cur_cross_algo_.load();
  for (int r = 0; r < opts_.size; ++r) {
    if (coord_->rank_dead[r]) {
      out->reshape_lost.push_back(r);
      continue;
    }
    out->member_old_ranks.push_back(r);
    out->member_endpoints.push_back(opts_.data_endpoints[r]);
  }
  for (const auto& ep : coord_->pending_join_endpoints) {
    out->member_old_ranks.push_back(-1);
    out->member_endpoints.push_back(ep);
  }
  return true;
}

bool Engine::ApplyReshape(const ResponseList& rl) {
  int old_rank = opts_.rank;
  int old_size = opts_.size;
  int new_size = static_cast<int>(rl.member_old_ranks.size());
  int new_rank = -1;
  std::vector<int32_t> joined;
  for (int i = 0; i < new_size; ++i) {
    if (rl.member_old_ranks[i] == old_rank) new_rank = i;
    if (rl.member_old_ranks[i] < 0) joined.push_back(i);
  }
  if (new_rank < 0) {
    // Unreachable for a live rank (the coordinator only reshapes around
    // survivors it is still talking to); fail closed rather than run
    // with a wrong identity.
    AbortLocal(ST_RANKS_DOWN,
               "membership reshape did not include this rank; the job "
               "cannot continue and should be restarted.");
    return false;
  }
  std::string msg =
      "membership changed (epoch " +
      std::to_string(static_cast<long long>(rl.membership_epoch)) + "): " +
      (rl.reshape_lost.empty()
           ? std::string("rank(s) joined")
           : "ranks down: " + RankCsv(rl.reshape_lost)) +
      "; continuing with " + std::to_string(new_size) +
      " rank(s).  In-flight collectives were cancelled; re-enter "
      "agreement and resync state from the root (hvd.run_elastic does "
      "both).";

  // 1. Arm the enqueue poison BEFORE draining: an Enqueue that misses the
  // flag must have entered the table before the drain below (both hold
  // mu_), so every in-flight or racing collective gets the retryable
  // status — none can slip through into the new membership's negotiation
  // before Python acknowledges (hvd.membership_ack / run_elastic resync).
  {
    std::lock_guard<std::mutex> lk(membership_mu_);
    reshape_message_ = msg;
    for (int32_t r : rl.reshape_lost) ranks_lost_.push_back(r);
    for (int32_t r : joined) ranks_joined_.push_back(r);
  }
  reshape_ack_pending_.store(true);
  // 2. Cancel everything in flight with the retryable status.  Entries
  // already failed by a broken ring carry their transport error instead;
  // the elastic driver treats both as retryable once the epoch bumps.
  std::vector<TableEntry> doomed;
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& kv : table_) doomed.push_back(std::move(kv.second));
    table_.clear();
    queue_.clear();
  }
  for (auto& e : doomed) CompleteEntry(e, ST_RESHAPE, msg);
  // 3. Caches and the autotune search reset at the barrier, on every
  // rank at the same tick: slot numbering and tuned parameters must mean
  // the same thing everywhere in the new membership.
  opts_.cache_capacity = rl.reshape_cache_capacity;
  cache_.set_capacity(opts_.cache_capacity);
  cache_.Clear();
  cache_size_.store(0);
  opts_.fusion_threshold = rl.reshape_fusion_threshold;
  opts_.cycle_time_ms =
      static_cast<double>(rl.reshape_cycle_time_us) / 1000.0;
  cur_fusion_.store(rl.reshape_fusion_threshold);
  cur_cycle_us_.store(rl.reshape_cycle_time_us);
  // Wire compression re-agrees across the barrier: every member — the
  // admitted standbys included, whose own env never went through the
  // init-time equality check — adopts the broadcast mode and floor, and
  // the error-feedback residuals reset (the membership, and with it
  // every sum a residual was correcting toward, just changed).
  opts_.compression_mode = rl.reshape_compression;
  opts_.compression_min_bytes = rl.reshape_compression_min_bytes;
  cur_compression_.store(rl.reshape_compression);
  cur_comp_min_bytes_.store(rl.reshape_compression_min_bytes);
  // The ring-vs-tree boundary re-agrees like the other tuned axes, so
  // the applied-parameter view stays identical across the barrier (the
  // knob itself is dormant until a topology is rebuilt hierarchical).
  opts_.cross_algo_threshold = rl.reshape_cross_algo_threshold;
  cur_cross_algo_.store(rl.reshape_cross_algo_threshold);
  residuals_.clear();
  residual_bytes_.store(0);
  residual_tensors_.store(0);
  autotune_frozen_.store(false);
  applied_window_.store(0);
  // A stale steady-exit marker must not cross the barrier: it would
  // report miss coordinates in a pattern whose slots the cache clear
  // above just renumbered.  (Steady replay itself cannot be active here
  // — every path into ApplyReshape exits steady first.)
  steady_exit_pending_ = false;
  {
    std::lock_guard<std::mutex> lk(autotune_mu_);
    applied_log_.clear();
    fusion_history_.clear();
    fusion_history_.emplace_back(ticks_done_.load(),
                                 rl.reshape_fusion_threshold);
    compression_history_.clear();
    compression_history_.emplace_back(
        ticks_done_.load(), static_cast<int64_t>(rl.reshape_compression));
  }
  // 4. Adopt the new identity.  Elastic jobs are single-host (the
  // launcher rejects --hosts), so the local identity tracks the global
  // one — a survivor and an admitted standby must never collide on
  // local_rank() for per-host resources.
  opts_.rank = new_rank;
  opts_.size = new_size;
  opts_.local_rank = new_rank;
  opts_.local_size = new_size;
  opts_.data_endpoints.assign(rl.member_endpoints.begin(),
                              rl.member_endpoints.end());
  cur_rank_.store(new_rank);
  cur_size_.store(new_size);
  cur_local_rank_.store(new_rank);
  cur_local_size_.store(new_size);
  membership_epoch_.store(rl.membership_epoch);
  reshapes_total_.fetch_add(1);
  // 5. Coordinator bookkeeping: compact the control star to the new
  // membership (survivor fds keep their sockets, admitted standbys bring
  // theirs) and restart the per-rank liveness/search state.
  if (old_rank == 0 && coord_) {
    std::vector<int> new_fds(new_size, -1);
    int join_i = 0;
    for (int i = 0; i < new_size; ++i) {
      int prev = rl.member_old_ranks[i];
      if (prev == 0) continue;  // self
      if (prev > 0 && prev < static_cast<int>(coord_fds_.size())) {
        new_fds[i] = coord_fds_[prev];
        coord_fds_[prev] = -1;
      } else if (prev < 0 &&
                 join_i < static_cast<int>(coord_->pending_join_fds.size())) {
        new_fds[i] = coord_->pending_join_fds[join_i++];
      }
    }
    for (int fd : coord_fds_) CloseFd(fd);  // dead ranks' sockets
    coord_fds_ = std::move(new_fds);
    // Elastic jobs run the one-level star: every worker is a direct
    // child of the rebuilt coordinator.
    coord_children_.clear();
    for (int r = 1; r < new_size; ++r) coord_children_.push_back(r);
    ctrl_children_.store(new_size - 1);
    coord_->pending_join_fds.clear();
    coord_->pending_join_endpoints.clear();
    coord_->rank_dead.assign(new_size, false);
    // Post-reshape postmortem accounting restarts: old entries carry the
    // previous membership's rank numbering.
    coord_->last_frame_tick.assign(new_size, -1);
    coord_->last_announce_tick.assign(new_size, -1);
    coord_->last_announce_name.assign(new_size, "");
    coord_->reshape_pending = false;
    coord_->message_table.clear();
    coord_->ready.clear();
    coord_->cache_pending.clear();
    coord_->cached_ready.clear();
    // Steady-state bookkeeping resets with the membership: the old
    // pattern named cache slots the clear above renumbered, and the
    // exit-barrier accounting must match the new size.
    coord_->steady = false;
    coord_->steady_revoke_next = false;
    coord_->steady_exited.assign(new_size, false);
    coord_->slot_history.clear();
    // Reshapes force the flat ring, so the cross-algo axis pins (the
    // knob is dead in the new membership).
    tuner_.Configure(opts_.autotune, opts_.autotune_warmup,
                     opts_.autotune_window, opts_.autotune_fix_fusion,
                     opts_.autotune_fix_cycle_ms,
                     opts_.compression_mode == COMP_NONE
                         ? COMP_NONE
                         : opts_.autotune_fix_compression,
                     opts_.cross_algo_threshold,
                     opts_.fusion_threshold, opts_.cycle_time_ms,
                     opts_.compression_mode, opts_.cross_algo_threshold);
    std::lock_guard<std::mutex> lk(announce_mu_);
    if (static_cast<int>(last_announce_counts_.size()) < new_size)
      last_announce_counts_.resize(new_size, 0);
  }
  // 6. Rebuild the data plane for the new membership.  A clean rebuild
  // also clears the broken-transport latch a mid-collective death set.
  std::string err;
  if (!RebuildRing(&err)) {
    AbortLocal(ST_RANKS_DOWN,
               "membership reshape failed while rebuilding the data ring "
               "(" + err + "); this job cannot continue and should be "
               "restarted.");
    return false;
  }
  data_plane_failed_.store(false);
  if (flight_.Enabled())
    flight_.Record(FL_RESHAPE, "", rl.membership_epoch);
  timeline_.Instant("membership", "MEMBERSHIP_RESHAPE");
  std::string how = rl.reshape_lost.empty()
                        ? std::string(" (grow)")
                        : " (lost rank(s) " + RankCsv(rl.reshape_lost) + ")";
  fprintf(stderr,
          "[horovod_tpu] membership epoch %lld: rank %d/%d -> %d/%d%s.\n",
          static_cast<long long>(rl.membership_epoch), old_rank, old_size,
          new_rank, new_size, how.c_str());
  return true;
}

bool Engine::RebuildRing(std::string* err) {
  // Quiesce the heartbeat monitor for the rebuild: clear the wake
  // registry FIRST (the fds it lists are about to be closed), then move
  // the old beat sockets to the graveyard — ShutdownFd kicks the monitor
  // out of any blocking poll on them, and it closes the fds itself on
  // its next pass after re-reading the swapped state (closing here would
  // race fd reuse against its poll set).
  {
    std::lock_guard<std::mutex> lk(hb_mu_);
    hb_wake_fds_.clear();
    hb_ctrl_wake_fd_ = -1;
    if (beat_in_fd_ >= 0) {
      ShutdownFd(beat_in_fd_);
      hb_graveyard_.push_back(beat_in_fd_);
    }
    if (beat_out_fd_ >= 0) {
      ShutdownFd(beat_out_fd_);
      hb_graveyard_.push_back(beat_out_fd_);
    }
    beat_in_fd_ = beat_out_fd_ = -1;
    beat_in_peer_ = beat_out_peer_ = -1;
    hb_last_seen_us_.clear();
    hb_miss_counts_.clear();
    pending_hb_dead_.clear();
    pending_hb_report_.clear();
  }
  CloseFd(left_fd_);
  CloseFd(right_fd_);
  left_fd_ = right_fd_ = -1;
  left_ch_ = Channel{};
  right_ch_ = Channel{};
  // Elastic jobs run the flat ring only; make sure no stale two-level
  // topology outlives a reshape.
  CloseTopologyFds();
  // Dedicated p2p channels name ranks of the OLD membership; drop them
  // and let the new membership redial lazily.
  CloseP2pChannels();
  node_id_ = 0;
  n_nodes_ = 1;
  topo_hier_.store(false);
  topo_nodes_.store(1);
  if (opts_.size == 1) return true;  // monitor idles on fd==-1
  const double kTimeout = 30.0;
  // Epoch-tagged hellos: a stale connect from a previous membership (or
  // a dying rank's last SYN in the backlog) parses as a mismatch and is
  // dropped instead of being adopted as a neighbour.
  const uint32_t epoch_tag =
      static_cast<uint32_t>(membership_epoch_.load() & 0xff) << 16;
  uint32_t hello = (3u << 24) | epoch_tag |
                   (static_cast<uint32_t>(opts_.rank) & 0xffff);
  int right = (opts_.rank + 1) % opts_.size;
  const int beat_left = (opts_.rank + opts_.size - 1) % opts_.size;
  const bool want_beats = hb_interval_ms_ > 0;
  std::string host;
  int port;
  if (!ParseEndpoint(opts_.data_endpoints[right], &host, &port)) {
    *err = "bad data endpoint " + opts_.data_endpoints[right];
    return false;
  }
  right_fd_ = ConnectRetry(host, port, kTimeout, err);
  if (right_fd_ < 0) return false;
  if (!SendAll(right_fd_, &hello, 4)) {
    *err = "ring-rebuild hello send failed";
    return false;
  }
  // The beacon lane rebuilds with the ring, epoch-tagged the same way.
  int new_beat_out = -1;
  int new_beat_in = -1;
  if (want_beats) {
    uint32_t beat_hello = (6u << 24) | epoch_tag |
                          (static_cast<uint32_t>(opts_.rank) & 0xffff);
    new_beat_out = ConnectRetry(host, port, kTimeout, err);
    if (new_beat_out < 0) return false;
    if (!SendAll(new_beat_out, &beat_hello, 4)) {
      CloseFd(new_beat_out);
      *err = "beacon rebuild hello send failed";
      return false;
    }
  }
  for (int attempts = 0;
       attempts < 32 && (left_fd_ < 0 || (want_beats && new_beat_in < 0));
       ++attempts) {
    int fd = AcceptOne(data_listen_fd_, kTimeout, err);
    if (fd < 0) {
      if (new_beat_out >= 0 && beat_out_fd_ != new_beat_out)
        CloseFd(new_beat_out);
      return false;
    }
    uint32_t peer = 0;
    if (!WaitReadable(fd, 2.0) || !RecvAll(fd, &peer, 4)) {
      CloseFd(fd);
      continue;
    }
    if ((peer & 0x00ff0000u) != epoch_tag) {
      CloseFd(fd);  // stale pre-reshape connect
      continue;
    }
    uint32_t kind = peer >> 24;
    if (kind == 3 && left_fd_ < 0) {
      left_fd_ = fd;
    } else if (kind == 6 && want_beats && new_beat_in < 0 &&
               (peer & 0xffffu) == static_cast<uint32_t>(beat_left)) {
      new_beat_in = fd;
    } else {
      CloseFd(fd);
    }
  }
  if (left_fd_ < 0 || (want_beats && new_beat_in < 0)) {
    if (new_beat_in >= 0) CloseFd(new_beat_in);
    if (new_beat_out >= 0) CloseFd(new_beat_out);
    *err = left_fd_ < 0
               ? "ring left neighbour never connected after the reshape"
               : "heartbeat beacon left neighbour never reconnected "
                 "after the reshape";
    return false;
  }
  // Unconditional (like SetupSockets): the link telemetry rides the same
  // fd -> peer registry as the fault clauses.
  NetFaultRegister(right_fd_, right);
  NetFaultRegister(left_fd_, beat_left);
  if (want_beats) {
    NetFaultRegister(new_beat_out, right);
    NetFaultRegister(new_beat_in, beat_left);
  }
  // Swap the new beacon lane in and re-arm the detector for the new
  // membership in one atomic step (the monitor re-reads everything from
  // hb_mu_-guarded state each pass).
  {
    std::lock_guard<std::mutex> lk(hb_mu_);
    beat_out_fd_ = new_beat_out;
    beat_out_peer_ = want_beats ? right : -1;
    beat_in_fd_ = new_beat_in;
    beat_in_peer_ = want_beats ? beat_left : -1;
    hb_epoch_ = static_cast<int>(membership_epoch_.load() & 0xff);
    int64_t now = EpochNowUs();
    if (want_beats) {
      hb_last_seen_us_[right] = now;
      hb_last_seen_us_[beat_left] = now;
    }
    hb_wake_fds_.push_back(left_fd_);
    hb_wake_fds_.push_back(right_fd_);
    hb_ctrl_wake_fd_ = opts_.rank == 0 ? -1 : coord_fd_;
  }
  // Re-wrap the rebuilt ring in channels (elastic jobs run TCP-only —
  // the shm agreement excludes them — so no rings to re-attach).
  left_ch_ = Channel{left_fd_, nullptr, nullptr, beat_left};
  right_ch_ = Channel{right_fd_, nullptr, nullptr, right};
  return true;
}

bool Engine::SetupRejoinSockets(std::string* err) {
  // Standby bring-up: listen on our own data endpoint, register with the
  // coordinator, and block until the admitting reshape broadcast names
  // our dense rank and the full membership.
  const double kTimeout = 120.0;
  if (opts_.data_endpoints.empty() || opts_.coord_endpoint.empty()) {
    *err = "rejoin requires HVD_TPU_COORD and this rank's HVD_TPU_DATA";
    return false;
  }
  std::string my_ep = opts_.data_endpoints[0];
  std::string host;
  int port;
  if (!ParseEndpoint(my_ep, &host, &port)) {
    *err = "bad data endpoint " + my_ep;
    return false;
  }
  data_listen_fd_ = Listen("0.0.0.0", port, err);
  if (data_listen_fd_ < 0) return false;
  if (!ParseEndpoint(opts_.coord_endpoint, &host, &port)) {
    *err = "bad coordinator endpoint " + opts_.coord_endpoint;
    return false;
  }
  coord_fd_ = ConnectRetry(host, port, kTimeout, err);
  if (coord_fd_ < 0) return false;
  if (!SendAll(coord_fd_, &kJoinHello, 4) ||
      !SendFrame(coord_fd_,
                 std::vector<uint8_t>(my_ep.begin(), my_ep.end()))) {
    *err = "rejoin registration send failed";
    return false;
  }
  if (!WaitReadable(coord_fd_, kTimeout)) {
    *err = "rejoin admission timed out (no reshape barrier within " +
           std::to_string(static_cast<long long>(kTimeout)) + "s)";
    return false;
  }
  std::vector<uint8_t> buf;
  ResponseList rl;
  if (!RecvFrame(coord_fd_, &buf) || !ParseResponseList(buf, &rl) ||
      !rl.reshape_present) {
    *err = "rejoin admission failed (coordinator closed or sent a "
           "non-reshape frame)";
    return false;
  }
  int new_rank = -1;
  for (size_t i = 0; i < rl.member_endpoints.size(); ++i)
    if (rl.member_old_ranks[i] < 0 && rl.member_endpoints[i] == my_ep)
      new_rank = static_cast<int>(i);
  if (new_rank < 0) {
    *err = "rejoin admission did not include this standby's endpoint";
    return false;
  }
  opts_.rank = new_rank;
  opts_.size = static_cast<int>(rl.member_old_ranks.size());
  // Single-host elastic: local identity tracks global (see ApplyReshape).
  opts_.local_rank = new_rank;
  opts_.local_size = opts_.size;
  opts_.data_endpoints.assign(rl.member_endpoints.begin(),
                              rl.member_endpoints.end());
  opts_.cache_capacity = rl.reshape_cache_capacity;
  opts_.fusion_threshold = rl.reshape_fusion_threshold;
  opts_.cycle_time_ms =
      static_cast<double>(rl.reshape_cycle_time_us) / 1000.0;
  // Wire compression comes from the admitting broadcast, not this
  // standby's env: the live job's agreement wins.  Same for the
  // cross-algo boundary (Init stores cur_cross_algo_ from opts_ after
  // this returns, like fusion/cycle).
  opts_.compression_mode = rl.reshape_compression;
  opts_.compression_min_bytes = rl.reshape_compression_min_bytes;
  opts_.cross_algo_threshold = rl.reshape_cross_algo_threshold;
  cur_compression_.store(rl.reshape_compression);
  cur_comp_min_bytes_.store(rl.reshape_compression_min_bytes);
  cur_rank_.store(new_rank);
  cur_size_.store(opts_.size);
  membership_epoch_.store(rl.membership_epoch);
  {
    std::lock_guard<std::mutex> lk(membership_mu_);
    ranks_joined_.push_back(new_rank);
    for (int32_t r : rl.reshape_lost) ranks_lost_.push_back(r);
  }
  fprintf(stderr,
          "[horovod_tpu] standby admitted as rank %d/%d (membership epoch "
          "%lld).\n",
          new_rank, opts_.size,
          static_cast<long long>(rl.membership_epoch));
  // No clock sync for standbys: the admitting barrier cannot stall the
  // live job on probe round-trips; this rank's timeline keeps offset 0.
  return RebuildRing(err);
}

std::string Engine::MembershipInfo() {
  std::lock_guard<std::mutex> lk(membership_mu_);
  return std::to_string(static_cast<long long>(membership_epoch_.load())) +
         "|" + std::to_string(cur_size_.load()) + "|" +
         RankCsv(ranks_lost_) + "|" + RankCsv(ranks_joined_);
}

// ---------------------------------------------------------------------------
// Execution.
// ---------------------------------------------------------------------------

void Engine::ProcessCacheHits(const std::vector<uint32_t>& hits) {
  if (hits.empty()) return;
  // Replay the stored responses in broadcast order, re-fusing consecutive
  // same-dtype allreduces under the threshold exactly like the
  // coordinator fuses fresh negotiations — steady-state repeats keep
  // their one-ring-pass-per-bucket behavior.
  std::vector<Response> merged;
  std::vector<int64_t> merged_bytes;
  std::vector<uint8_t> merged_dtypes;
  uint8_t fused_dtype = 255;
  for (uint32_t hit : hits) {
    const CacheSlot* s = cache_.Get(static_cast<int>(hit));
    if (s == nullptr) continue;  // unreachable with lockstep caches
    // Broadcast-driven LRU touch: identical order on every rank, so
    // eviction decisions stay in lockstep.
    cache_.Touch(static_cast<int>(hit));
    int64_t bytes =
        NumElements(s->dims) * static_cast<int64_t>(DataTypeSize(s->dtype));
    if (s->response.type == RESP_ALLREDUCE &&
        s->response.stage_ranks.empty() && !merged.empty() &&
        FusesInto(merged.back(), merged_bytes.back(), fused_dtype, s->dtype,
                  bytes, opts_.fusion_threshold)) {
      merged.back().names.push_back(s->name);
      merged_bytes.back() += bytes;
    } else {
      merged.push_back(s->response);
      merged_bytes.push_back(bytes);
      merged_dtypes.push_back(s->dtype);
      fused_dtype = s->dtype;
    }
  }
  // Replayed buckets recompute the wire-compression verdict locally from
  // the same inputs the coordinator would use — bucket dtype/bytes (from
  // the broadcast hit order) and the lockstep-mutated (mode, min-bytes)
  // state — so a replayed bucket compresses exactly like its fresh
  // negotiation would, on every rank, without putting the verdict back on
  // the wire.
  // (RESP_SENDRECV slots replay their stored verdict verbatim — p2p
  // never re-fuses — and stage-scoped allreduces never compress.)
  for (size_t i = 0; i < merged.size(); ++i)
    if (merged[i].type == RESP_ALLREDUCE && merged[i].stage_ranks.empty())
      merged[i].compression =
          ChooseCompression(merged_dtypes[i], merged_bytes[i]);
  for (const auto& resp : merged) PerformOperation(resp, /*from_cache=*/true);
}

void Engine::PerformOperation(const Response& resp, bool from_cache) {
  std::vector<TableEntry> entries;
  auto arrived = std::chrono::steady_clock::now();
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (const auto& name : resp.names) {
      auto it = table_.find(name);
      if (it == table_.end()) continue;  // should not happen
      entries.push_back(std::move(it->second));
      table_.erase(it);
    }
  }
  if (resp.type == RESP_ERROR && cache_.enabled()) {
    // A name that negotiated to an error must renegotiate from scratch:
    // drop any stale agreement so later (consistent) reuse is a clean
    // miss, not a replay of dead metadata.  Driven by resp.names alone —
    // NOT the local entries — so even a rank that never submitted this
    // round (a poison-window straggler scenario) evicts in lockstep.
    for (const auto& name : resp.names) cache_.Erase(name);
    cache_size_.store(cache_.size());
  }
  // Partial-participation agreements (p2p pairs, stage-scoped
  // collectives) mutate the cache from the RESPONSE metadata, before the
  // no-local-entry return below: most ranks never enqueued the name, yet
  // every cache must Put the slot at this list position or slot indices
  // diverge and the next cache-bit announce is garbage (docs/pipeline.md
  // #steady-state).  The slot is byte-identical on every rank (canonical
  // op + the broadcast signature); Lookup restores the per-rank role.
  if (cache_.enabled() && !from_cache &&
      (resp.type == RESP_SENDRECV ||
       (resp.type == RESP_ALLREDUCE && !resp.stage_ranks.empty()))) {
    Response single = resp;  // p2p/stage responses are never fused
    CacheSlot evicted;
    uint8_t slot_op = resp.type == RESP_SENDRECV
                          ? static_cast<uint8_t>(OP_SEND)
                          : static_cast<uint8_t>(OP_ALLREDUCE);
    int slot = cache_.Put(resp.names[0], slot_op, resp.p2p_dtype,
                          resp.p2p_dims, -1, single, &evicted);
    if (evicted.valid) {
      cache_evictions_.fetch_add(1);
      CoordinatorDrainSlot(slot, evicted);
    }
    cache_size_.store(cache_.size());
  }
  if (entries.empty()) return;
  // Negotiation latency stamp (negotiation_sec histogram, both planes):
  // enqueue -> the agreed response reaching this rank, before execution.
  for (auto& e : entries)
    e.negotiation_us = std::chrono::duration_cast<std::chrono::microseconds>(
                           arrived - e.enqueued_at)
                           .count();

  if (flight_.Enabled() && !resp.names.empty())
    flight_.Record(resp.type == RESP_ERROR ? FL_ERROR : FL_EXECUTE,
                   resp.names[0], static_cast<int64_t>(resp.names.size()));
  if (resp.type == RESP_ERROR) {
    for (auto& e : entries) CompleteEntry(e, ST_PRECONDITION, resp.error_message);
    return;
  }
  if (cache_.enabled() && !from_cache && resp.type != RESP_SENDRECV &&
      (resp.type != RESP_ALLREDUCE || resp.stage_ranks.empty())) {
    // Freshly negotiated: store each name's agreement so its next
    // signature-identical submission announces a compact cache bit.
    // Slot assignment and LRU order are driven by the broadcast list —
    // lockstep on every rank.  (p2p / stage-scoped responses stored
    // above, from metadata, on every rank.)
    for (auto& e : entries) {
      Response single;
      single.type = resp.type;
      single.names.push_back(e.name);
      single.rank_dim0 = resp.rank_dim0;
      // Deliberately NOT the bucket's compression verdict: replays
      // re-fuse and recompute it from the replayed bucket's size
      // (ProcessCacheHits), so a stale per-name copy would only mislead.
      single.compression = COMP_NONE;
      CacheSlot evicted;
      int slot = cache_.Put(e.name, e.op, e.dtype, e.dims, e.root_rank,
                            single, &evicted);
      if (evicted.valid) {
        cache_evictions_.fetch_add(1);
        // Rank 0: bits still pending against the evicted entry can no
        // longer be matched by index — convert them back to full
        // requests so their negotiation completes by name.
        CoordinatorDrainSlot(slot, evicted);
      }
    }
    cache_size_.store(cache_.size());
  }
  if (data_plane_failed_.load()) {
    for (auto& e : entries)
      CompleteEntry(e, ST_ABORTED,
                    "the data plane failed during an earlier collective "
                    "(a rank died or a transport broke); this job cannot "
                    "make progress and should be restarted.");
    return;
  }
  switch (resp.type) {
    case RESP_ALLREDUCE:
      if (!resp.stage_ranks.empty())
        ExecuteGroupAllreduce(resp, entries);
      else
        ExecuteAllreduce(resp, entries);
      break;
    case RESP_SENDRECV:
      ExecuteSendRecv(resp, entries[0]);
      break;
    case RESP_ALLGATHER:
      ExecuteAllgather(resp, entries[0]);
      break;
    case RESP_BROADCAST:
      ExecuteBroadcast(resp, entries[0]);
      break;
    case RESP_NOOP:
      // Negotiation-only (the XLA plane's cached metadata agreement): the
      // completion stamp IS the payload — no data moves.
      for (auto& e : entries) CompleteEntry(e, ST_OK, "");
      break;
    default:
      for (auto& e : entries)
        CompleteEntry(e, ST_UNKNOWN, "unknown response type");
  }
}

void Engine::ExecuteAllreduce(const Response& resp,
                              std::vector<TableEntry>& entries) {
  uint8_t dtype = entries[0].dtype;
  bool half = (dtype == HVD_FLOAT16 || dtype == HVD_BFLOAT16);
  bool hier = opts_.hierarchical_allreduce && opts_.size > 1;
  // Negotiated wire compression (the Response's per-bucket verdict)
  // applies to fp32 payloads on the flat ring AND on the two-level
  // topology's cross-node (DCN) hop — the hop where bytes cost money.
  uint8_t comp = (dtype == HVD_FLOAT32) ? resp.compression : COMP_NONE;
  // Wire format for the f32-master paths: a lossy compressed format for
  // fp32 buckets, or the payload's OWN width for f16/bf16 (halves ship
  // native-width on the flat ring and on BOTH hops of the two-level
  // topology) — 255 = plain wire in the payload dtype.  Reduction
  // accumulates in f32 at each hop in all wire modes.
  uint8_t wire = 255;
  if (comp == COMP_BF16)
    wire = WIRE_BF16;
  else if (comp == COMP_FP8)
    wire = WIRE_FP8;
  else if (half)
    wire = dtype == HVD_FLOAT16 ? WIRE_F16 : WIRE_BF16;
  size_t esize = DataTypeSize(dtype);

  int64_t total_elems = 0;
  for (auto& e : entries) total_elems += NumElements(e.dims);
  for (auto& e : entries) timeline_.Start(e.name, "ALLREDUCE");

  // Two-level cross-node algorithm selection (per bucket, lockstep: the
  // threshold is broadcast tuned state and the bucket size follows the
  // lockstep fusion plan, so every rank flips ring<->tree at the same
  // bucket).  Latency-bound small buckets take the recursive-doubling
  // tree; bandwidth-bound big ones the ring.
  bool use_tree = false;
  if (hier && n_nodes_ > 1) {
    int64_t bucket_bytes = total_elems * static_cast<int64_t>(esize);
    use_tree = !cross_tree_fds_.empty() &&
               bucket_bytes < cur_cross_algo_.load();
    (use_tree ? topo_ops_tree_ : topo_ops_ring_).fetch_add(1);
    int algo = use_tree ? 1 : 0;
    int last = topo_last_algo_.exchange(algo);
    if (last != -1 && last != algo && flight_.Enabled())
      flight_.Record(FL_TOPOLOGY, entries[0].name, algo);
  }

  // Compression metrics: every executed bucket records its payload width
  // and its wire width (on the two-level topology: the cross/DCN hop's
  // width), so wire_bytes/payload_bytes exposes both the compression win
  // and any residual staging inflation (native halves: wire == payload).
  int64_t wire_unit = wire != 255 ? static_cast<int64_t>(WireFormatSize(wire))
                                  : static_cast<int64_t>(esize);
  RecordCompressedOp(entries[0].name, comp,
                     total_elems * static_cast<int64_t>(esize),
                     total_elems * wire_unit);

  std::string err;
  bool ok = true;
  const char* reduce_activity =
      hier ? "HIERARCHICAL_ALLREDUCE" : "RING_ALLREDUCE";
  auto do_allreduce = [&](void* buf, int64_t n, std::string* e) {
    return hier ? TwoLevelAllreduce(buf, n, dtype, 255, 255, use_tree,
                                    entries[0].name, e)
                : RingAllreduce(buf, n, dtype, e);
  };
  if (wire != 255 || (hier && dtype == HVD_FLOAT32)) {
    // Compressed / native-width wire path: fp32 master copies live in the
    // fusion buffer, segments cross the wire narrowed.  For lossy fp32
    // compression each tensor carries an error-feedback residual: the
    // quantization error of THIS step's (input + residual) is saved and
    // added back in before the next step's compression (1-bit-SGD-style
    // error feedback), so the wire rounding never compounds into drift.
    last_fusion_use_ = std::chrono::steady_clock::now();
    if (fusion_buffer_.size() < static_cast<size_t>(total_elems) * 4)
      fusion_buffer_.resize(static_cast<size_t>(total_elems) * 4);
    float* fb = reinterpret_cast<float*>(fusion_buffer_.data());
    bool ef = comp != COMP_NONE;  // native half payloads are already
                                  // wire-exact; no residual needed
    if (ef) {
      // Residual-map bound: a stream of never-repeating auto-named
      // tensors gains nothing from error feedback but would grow this
      // forever.  Checked ONCE, before this bucket touches the map — a
      // mid-bucket clear would discard residuals just stored for the
      // bucket's earlier tensors in this very step.
      size_t fresh = 0;
      for (auto& e : entries)
        if (!residuals_.count(e.name)) ++fresh;
      if (fresh > 0 && residuals_.size() + fresh > 4096) {
        residuals_.clear();
        residual_bytes_.store(0);
      }
    }
    int64_t off = 0;
    for (auto& e : entries) {
      timeline_.ActivityStart(e.name, "MEMCPY_IN_FUSION_BUFFER");
      int64_t n = NumElements(e.dims);
      float* seg = fb + off;
      if (half)
        HalfBufToFloat(e.in, seg, n, dtype);
      else
        memcpy(seg, e.in, static_cast<size_t>(n) * 4);
      if (ef) {
        auto it = residuals_.find(e.name);
        if (it == residuals_.end())
          it = residuals_.emplace(e.name, std::vector<float>()).first;
        std::vector<float>& r = it->second;
        if (static_cast<int64_t>(r.size()) != n) {
          residual_bytes_.fetch_add(
              (n - static_cast<int64_t>(r.size())) * 4);
          r.assign(static_cast<size_t>(n), 0.0f);
        }
        // Quantize the local contribution NOW: the residual is measured
        // against exactly what the wire will deliver, and the first
        // reduce-scatter hop then sends these values losslessly.
        for (int64_t i = 0; i < n; ++i) {
          float v = seg[i] + r[i];
          float q = QuantDequant(v, wire);
          r[i] = v - q;
          seg[i] = q;
        }
      }
      off += n;
      timeline_.ActivityEnd(e.name);
    }
    if (ef) residual_tensors_.store(
        static_cast<int64_t>(residuals_.size()));
    if (comp != COMP_NONE && timeline_.Enabled())
      for (auto& e : entries)
        timeline_.Instant(
            e.name, std::string("COMPRESS_") + CompressionName(comp));
    timeline_.ActivityStart(entries[0].name, reduce_activity);
    if (hier) {
      // Two-level: the local hop stays full/native width (halves ship
      // their own width, f32 ships f32) while the cross (DCN) hop takes
      // the negotiated compressed format.
      uint8_t local_wire = half ? wire : 255;
      ok = TwoLevelAllreduce(fb, total_elems, HVD_FLOAT32, local_wire,
                             wire, use_tree, entries[0].name, &err);
    } else {
      ok = RingAllreduceWire(fb, total_elems, wire, opts_.size, opts_.rank,
                             left_ch_, right_ch_, &err);
    }
    timeline_.ActivityEnd(entries[0].name);
    if (ok) {
      off = 0;
      for (auto& e : entries) {
        timeline_.ActivityStart(e.name, "MEMCPY_OUT_FUSION_BUFFER");
        int64_t n = NumElements(e.dims);
        float* seg = fb + off;
        // `average` is a per-tensor attribute, so divide per segment:
        // fused neighbours may mix averaged and summed reductions.
        if (e.average) DivideBuffer(seg, n, HVD_FLOAT32, opts_.size);
        if (half)
          FloatBufToHalf(seg, e.out, n, dtype);
        else
          memcpy(e.out, seg, static_cast<size_t>(n) * 4);
        off += n;
        timeline_.ActivityEnd(e.name);
      }
    }
  } else if (entries.size() == 1 && !half) {
    // Single unfused tensor: skip the fusion buffer, reduce in place on the
    // output (the reference's single-entry in-place path,
    // operations.cc:1186).
    TableEntry& e = entries[0];
    if (e.out != e.in)
      memcpy(e.out, e.in, static_cast<size_t>(total_elems) * esize);
    timeline_.ActivityStart(e.name, reduce_activity);
    ok = do_allreduce(e.out, total_elems, &err);
    timeline_.ActivityEnd(e.name);
    if (ok && e.average) DivideBuffer(e.out, total_elems, dtype, opts_.size);
  } else {
    // Fuse into one contiguous buffer, one pass, scatter back out -- the
    // reference's fusion-buffer dance (operations.cc:1109-1186).  Halves
    // never reach here any more (they always take the f32-master wire
    // path above, at native width on every hop); this branch serves
    // int/f64 payloads at their own width — on the two-level topology as
    // an uncompressed native-dtype two-level pass.
    last_fusion_use_ = std::chrono::steady_clock::now();
    if (fusion_buffer_.size() < static_cast<size_t>(total_elems) * esize)
      fusion_buffer_.resize(static_cast<size_t>(total_elems) * esize);
    char* fb = fusion_buffer_.data();
    int64_t off = 0;
    for (auto& e : entries) {
      timeline_.ActivityStart(e.name, "MEMCPY_IN_FUSION_BUFFER");
      int64_t n = NumElements(e.dims);
      memcpy(fb + off * esize, e.in, static_cast<size_t>(n) * esize);
      off += n;
      timeline_.ActivityEnd(e.name);
    }
    timeline_.ActivityStart(entries[0].name, reduce_activity);
    ok = do_allreduce(fb, total_elems, &err);
    timeline_.ActivityEnd(entries[0].name);
    if (ok) {
      off = 0;
      for (auto& e : entries) {
        timeline_.ActivityStart(e.name, "MEMCPY_OUT_FUSION_BUFFER");
        int64_t n = NumElements(e.dims);
        // `average` is a per-tensor attribute, so divide per segment: fused
        // neighbours may mix averaged and summed reductions.
        memcpy(e.out, fb + off * esize, static_cast<size_t>(n) * esize);
        if (e.average) DivideBuffer(e.out, n, dtype, opts_.size);
        off += n;
        timeline_.ActivityEnd(e.name);
      }
    }
  }
  for (auto& e : entries) {
    timeline_.End(e.name, NumElements(e.dims) * static_cast<int64_t>(esize));
    if (ok) {
      CompleteEntry(e, ST_OK, "");
    } else {
      data_plane_failed_.store(true);
      CompleteEntry(e, ST_UNKNOWN, "ring allreduce failed: " + err);
    }
  }
}

void Engine::ExecuteAllgather(const Response& resp, TableEntry& e) {
  timeline_.Start(e.name, "ALLGATHER");
  size_t esize = DataTypeSize(e.dtype);
  int64_t row_elems = 1;
  for (size_t d = 1; d < e.dims.size(); ++d) row_elems *= e.dims[d];
  int64_t row_bytes = row_elems * static_cast<int64_t>(esize);

  std::vector<int64_t> block_bytes(opts_.size);
  int64_t total_dim0 = 0;
  for (int r = 0; r < opts_.size; ++r) {
    block_bytes[r] = resp.rank_dim0[r] * row_bytes;
    total_dim0 += resp.rank_dim0[r];
  }
  int64_t total_bytes = total_dim0 * row_bytes;

  std::shared_ptr<HandleStatus> status;
  {
    std::lock_guard<std::mutex> lk(handles_mu_);
    auto it = handles_.find(e.handle);
    if (it != handles_.end()) status = it->second;
  }
  if (!status) return;
  status->gathered.resize(static_cast<size_t>(total_bytes));
  status->out_dim0 = total_dim0;
  char* buf = status->gathered.data();
  int64_t my_off = 0;
  for (int r = 0; r < opts_.rank; ++r) my_off += block_bytes[r];
  timeline_.ActivityStart(e.name, "MEMCPY_IN_FUSION_BUFFER");
  memcpy(buf + my_off, e.in, static_cast<size_t>(block_bytes[opts_.rank]));
  timeline_.ActivityEnd(e.name);

  std::string err;
  timeline_.ActivityStart(e.name, "RING_ALLGATHER");
  bool ok = RingAllgather(buf, block_bytes, &err);
  timeline_.ActivityEnd(e.name);
  if (ok && e.out != nullptr)
    memcpy(e.out, buf, static_cast<size_t>(total_bytes));
  timeline_.End(e.name, total_bytes);
  if (ok) {
    CompleteEntry(e, ST_OK, "");
  } else {
    data_plane_failed_.store(true);
    CompleteEntry(e, ST_UNKNOWN, "ring allgather failed: " + err);
  }
}

void Engine::ExecuteBroadcast(const Response& resp, TableEntry& e) {
  timeline_.Start(e.name, "BROADCAST");
  int64_t nbytes = NumElements(e.dims) * static_cast<int64_t>(DataTypeSize(e.dtype));
  if (opts_.rank == e.root_rank && e.out != e.in && e.out != nullptr)
    memcpy(e.out, e.in, static_cast<size_t>(nbytes));
  void* buf = e.out != nullptr ? e.out : const_cast<void*>(e.in);
  std::string err;
  timeline_.ActivityStart(e.name, "RING_BROADCAST");
  bool ok = RingBroadcast(buf, nbytes, e.root_rank, &err);
  timeline_.ActivityEnd(e.name);
  timeline_.End(e.name, nbytes);
  if (ok) {
    CompleteEntry(e, ST_OK, "");
  } else {
    data_plane_failed_.store(true);
    CompleteEntry(e, ST_UNKNOWN, "ring broadcast failed: " + err);
  }
}

// ---------------------------------------------------------------------------
// Point-to-point plane (docs/pipeline.md): negotiated pairwise transfers
// for pipeline parallelism, executed over the same Channel seam the
// collectives ride.
// ---------------------------------------------------------------------------

const Channel* Engine::GetP2pChannel(int peer, std::string* err) {
  const int rank = opts_.rank;
  const int size = opts_.size;
  // Fabric reuse first: when the negotiated pair already sits on a
  // topology channel the transfer rides it — the node-local ring is
  // shm-capable, which is the whole point for intra-host activation
  // traffic.  Safe because both ends execute the same broadcast
  // response at the same list position, so the channel is quiet, and
  // both sides pick the matching direction by the SAME symmetric rule
  // (2-cycles, where the peer is both neighbours, tie-break by the
  // lower id owning the rightward pair).
  const bool hier = opts_.hierarchical_allreduce && opts_.local_size > 1;
  if (hier) {
    const int L = opts_.local_size;
    const int node_base = opts_.rank - opts_.local_rank;
    if (peer >= node_base && peer < node_base + L) {
      int plr = peer - node_base;
      int lr = opts_.local_rank;
      bool at_right = plr == (lr + 1) % L;
      bool at_left = plr == (lr + L - 1) % L;
      if (at_right && at_left)
        return rank < peer ? &local_right_ch_ : &local_left_ch_;
      if (at_right) return &local_right_ch_;
      if (at_left) return &local_left_ch_;
    } else if (n_nodes_ > 1 && peer % L == opts_.local_rank) {
      // Same shard on another node: the sharded cross ring when the
      // node is adjacent.
      int pnode = peer / L;
      bool at_right = pnode == (node_id_ + 1) % n_nodes_;
      bool at_left = pnode == (node_id_ + n_nodes_ - 1) % n_nodes_;
      if (at_right && at_left)
        return node_id_ < pnode ? &cross_right_ch_ : &cross_left_ch_;
      if (at_right) return &cross_right_ch_;
      if (at_left) return &cross_left_ch_;
    }
  }
  {
    bool at_right = peer == (rank + 1) % size;
    bool at_left = peer == (rank + size - 1) % size;
    if (at_right && at_left) return rank < peer ? &right_ch_ : &left_ch_;
    if (at_right) return &right_ch_;
    if (at_left) return &left_ch_;
  }

  // Non-neighbour pair: a dedicated TCP connection, dialed lazily at
  // first use and cached for the job's lifetime (pipeline schedules
  // reuse the same stage pairs every micro-batch).  The LOWER rank
  // dials the higher rank's data listener with a typed hello; the
  // higher rank accepts.  Deterministic rendezvous: both ends reach
  // this call executing the same response at the same list position.
  auto it = p2p_chans_.find(peer);
  if (it != p2p_chans_.end()) return &it->second;
  const uint32_t kHelloP2P = 7u << 24;
  const double kDialTimeout = 120.0;
  if (rank < peer) {
    std::string host;
    int port;
    if (peer >= static_cast<int>(opts_.data_endpoints.size()) ||
        !ParseEndpoint(opts_.data_endpoints[peer], &host, &port)) {
      *err = "bad data endpoint for p2p peer " + std::to_string(peer);
      return nullptr;
    }
    int fd = ConnectRetry(host, port, kDialTimeout, err);
    if (fd < 0) {
      *err = "p2p dial to rank " + std::to_string(peer) + " failed: " + *err;
      return nullptr;
    }
    uint32_t hello = kHelloP2P | static_cast<uint32_t>(rank);
    if (!SendAll(fd, &hello, 4)) {
      *err = "p2p hello send to rank " + std::to_string(peer) + " failed";
      CloseFd(fd);
      return nullptr;
    }
    NetFaultRegister(fd, peer);
    const Channel& ch =
        p2p_chans_.emplace(peer, Channel{fd, nullptr, nullptr, peer})
            .first->second;
    p2p_channels_.store(static_cast<int64_t>(p2p_chans_.size()));
    return &ch;
  }
  // Accept side.  A dial for a LATER response in this rank's list can
  // land in the listen backlog first (the dialer's connect+hello does
  // not wait for the accept), so unexpected p2p hellos from lower ranks
  // are parked in the channel map — they are connections this rank will
  // execute against at their own list position anyway.  Anything else
  // (a stale or foreign hello) is dropped and the wait continues.
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(kDialTimeout);
  while (true) {
    double left = std::chrono::duration<double>(
                      deadline - std::chrono::steady_clock::now())
                      .count();
    if (left <= 0.0) {
      *err = "timed out accepting the p2p dial from rank " +
             std::to_string(peer);
      return nullptr;
    }
    int fd = AcceptOne(data_listen_fd_, left, err);
    if (fd < 0) {
      *err = "p2p accept from rank " + std::to_string(peer) +
             " failed: " + *err;
      return nullptr;
    }
    uint32_t hello = 0;
    if (!RecvAll(fd, &hello, 4)) {
      CloseFd(fd);
      continue;
    }
    int from = static_cast<int>(hello & 0x00ffffffu);
    if ((hello & 0xff000000u) != kHelloP2P || from < 0 || from >= rank ||
        p2p_chans_.count(from)) {
      CloseFd(fd);
      continue;
    }
    NetFaultRegister(fd, from);
    p2p_chans_.emplace(from, Channel{fd, nullptr, nullptr, from});
    p2p_channels_.store(static_cast<int64_t>(p2p_chans_.size()));
    if (from == peer) return &p2p_chans_.find(peer)->second;
  }
}

void Engine::CloseP2pChannels() {
  for (auto& kv : p2p_chans_) CloseFd(kv.second.fd);
  p2p_chans_.clear();
  p2p_channels_.store(0);
}

void Engine::ExecuteSendRecv(const Response& resp, TableEntry& e) {
  const bool sender = opts_.rank == resp.p2p_src;
  const int peer = sender ? resp.p2p_dst : resp.p2p_src;
  timeline_.Start(e.name, sender ? "SEND" : "RECV");
  int64_t n = NumElements(e.dims);
  size_t esize = DataTypeSize(e.dtype);
  int64_t nbytes = n * static_cast<int64_t>(esize);

  // The coordinator's negotiated per-transfer compression verdict
  // (fp32 cross-node pairs only; see CoordinatorTick).  Same wire
  // formats and error-feedback residual discipline as the allreduce
  // path, so a compressed activation stream never compounds rounding
  // into drift across micro-batches.
  uint8_t comp = e.dtype == HVD_FLOAT32 ? resp.compression : COMP_NONE;
  uint8_t wire = 255;
  if (comp == COMP_BF16)
    wire = WIRE_BF16;
  else if (comp == COMP_FP8)
    wire = WIRE_FP8;
  int64_t wire_bytes =
      wire == 255 ? nbytes : n * static_cast<int64_t>(WireFormatSize(wire));
  RecordCompressedOp(e.name, comp, nbytes, wire_bytes);

  std::string err;
  const Channel* ch = GetP2pChannel(peer, &err);
  bool ok = ch != nullptr;
  if (ok) {
    timeline_.ActivityStart(e.name, sender ? "P2P_SEND" : "P2P_RECV");
    if (wire == 255) {
      ok = sender
               ? ChannelSendAll(*ch, e.in, static_cast<size_t>(nbytes))
               : ChannelRecvAll(*ch, e.out, static_cast<size_t>(nbytes));
      if (!ok) err = "peer rank " + std::to_string(peer) + " closed";
    } else if (sender) {
      // Residual-map bound: same discipline as ExecuteAllreduce — a
      // never-repeating name stream must not grow the map forever.
      if (!residuals_.count(e.name) && residuals_.size() >= 4096) {
        residuals_.clear();
        residual_bytes_.store(0);
      }
      auto rit = residuals_.emplace(e.name, std::vector<float>()).first;
      std::vector<float>& r = rit->second;
      if (static_cast<int64_t>(r.size()) != n) {
        residual_bytes_.fetch_add((n - static_cast<int64_t>(r.size())) * 4);
        r.assign(static_cast<size_t>(n), 0.0f);
      }
      residual_tensors_.store(static_cast<int64_t>(residuals_.size()));
      const float* src = static_cast<const float*>(e.in);
      std::vector<float> q(static_cast<size_t>(n));
      for (int64_t i = 0; i < n; ++i) {
        float v = src[i] + r[i];
        float w = QuantDequant(v, wire);
        r[i] = v - w;
        q[i] = w;
      }
      std::vector<char> wbuf(static_cast<size_t>(wire_bytes));
      CompressBuf(q.data(), wbuf.data(), n, wire);
      if (timeline_.Enabled())
        timeline_.Instant(e.name,
                          std::string("COMPRESS_") + CompressionName(comp));
      ok = ChannelSendAll(*ch, wbuf.data(), wbuf.size());
      if (!ok) err = "peer rank " + std::to_string(peer) + " closed";
    } else {
      std::vector<char> wbuf(static_cast<size_t>(wire_bytes));
      ok = ChannelRecvAll(*ch, wbuf.data(), wbuf.size());
      if (ok)
        DecompressBuf(wbuf.data(), static_cast<float*>(e.out), n, wire);
      else
        err = "peer rank " + std::to_string(peer) + " closed";
    }
    timeline_.ActivityEnd(e.name);
  }
  timeline_.End(e.name, wire_bytes);
  if (ok) {
    if (sender) {
      p2p_sends_.fetch_add(1);
      p2p_bytes_out_.fetch_add(wire_bytes);
    } else {
      p2p_recvs_.fetch_add(1);
      p2p_bytes_in_.fetch_add(wire_bytes);
    }
    p2p_matched_.fetch_add(1);
    // One ring entry per transfer; a negative arg marks the receive so
    // the postmortem distinguishes direction without a second code.
    if (flight_.Enabled())
      flight_.Record(FL_P2P, e.name, sender ? wire_bytes : -wire_bytes);
    CompleteEntry(e, ST_OK, "");
  } else {
    data_plane_failed_.store(true);
    CompleteEntry(e, ST_UNKNOWN,
                  std::string("p2p ") + (sender ? "send" : "recv") +
                      " failed: " + err);
  }
}

void Engine::ExecuteGroupAllreduce(const Response& resp,
                                   std::vector<TableEntry>& entries) {
  // Stage-scoped allreduce (docs/pipeline.md): the DP reduction inside
  // one pipeline stage.  Never fused (FusesInto), so exactly one entry.
  // Leader-reduce: the first member gathers, accumulates in f32-free
  // native width, and redistributes over p2p channels — stage groups
  // are small (the DP width), so the O(members) star costs less than
  // building a ring per group.
  TableEntry& e = entries[0];
  const std::vector<int32_t>& members = resp.stage_ranks;
  const int leader = members[0];
  timeline_.Start(e.name, "GROUP_ALLREDUCE");
  int64_t n = NumElements(e.dims);
  size_t esize = DataTypeSize(e.dtype);
  int64_t nbytes = n * static_cast<int64_t>(esize);

  std::string err;
  bool ok = true;
  timeline_.ActivityStart(e.name, "GROUP_ALLREDUCE");
  if (opts_.rank == leader) {
    if (e.out != e.in && e.out != nullptr)
      memcpy(e.out, e.in, static_cast<size_t>(nbytes));
    std::vector<char> tmp(static_cast<size_t>(nbytes));
    for (int32_t m : members) {
      if (m == leader) continue;
      const Channel* ch = GetP2pChannel(m, &err);
      if (!ch || !ChannelRecvAll(*ch, tmp.data(), tmp.size())) {
        if (err.empty())
          err = "stage member rank " + std::to_string(m) + " closed";
        ok = false;
        break;
      }
      AccumulateSum(e.out, tmp.data(), n, e.dtype);
    }
    if (ok && e.average)
      DivideBuffer(e.out, n, e.dtype, static_cast<int>(members.size()));
    if (ok) {
      for (int32_t m : members) {
        if (m == leader) continue;
        const Channel* ch = GetP2pChannel(m, &err);
        if (!ch || !ChannelSendAll(*ch, e.out, static_cast<size_t>(nbytes))) {
          if (err.empty())
            err = "stage member rank " + std::to_string(m) + " closed";
          ok = false;
          break;
        }
      }
    }
  } else {
    const Channel* ch = GetP2pChannel(leader, &err);
    ok = ch && ChannelSendAll(*ch, e.in, static_cast<size_t>(nbytes)) &&
         ChannelRecvAll(*ch, e.out, static_cast<size_t>(nbytes));
    if (!ok && err.empty())
      err = "stage leader rank " + std::to_string(leader) + " closed";
  }
  timeline_.ActivityEnd(e.name);
  timeline_.End(e.name, nbytes);
  if (ok) {
    p2p_group_ops_.fetch_add(1);
    CompleteEntry(e, ST_OK, "");
  } else {
    data_plane_failed_.store(true);
    CompleteEntry(e, ST_UNKNOWN, "stage-group allreduce failed: " + err);
  }
}

std::string Engine::P2pInfo() {
  // Unmatched gauge: enqueued send/recv entries still waiting for their
  // counterpart to announce — the number the pipeline stall diagnosis
  // starts from.
  int64_t unmatched = 0;
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (const auto& kv : table_)
      if (kv.second.op == OP_SEND || kv.second.op == OP_RECV) ++unmatched;
  }
  return std::to_string(p2p_sends_.load()) + "|" +
         std::to_string(p2p_recvs_.load()) + "|" +
         std::to_string(p2p_bytes_out_.load()) + "|" +
         std::to_string(p2p_bytes_in_.load()) + "|" +
         std::to_string(p2p_matched_.load()) + "|" +
         std::to_string(unmatched) + "|" +
         std::to_string(p2p_group_ops_.load()) + "|" +
         std::to_string(p2p_channels_.load());
}

void Engine::CompleteEntry(const TableEntry& e, int32_t code,
                           const std::string& error) {
  std::shared_ptr<HandleStatus> status;
  {
    std::lock_guard<std::mutex> lk(handles_mu_);
    auto it = handles_.find(e.handle);
    if (it != handles_.end()) status = it->second;
  }
  if (!status) return;
  // Stamp completion order before `code` flips (readers observe the stamps
  // after seeing a non-pending code).  CompleteEntry only runs on the engine
  // thread, in response-execution order, and response lists are broadcast
  // from rank 0 — so the *relative* order of these stamps is identical
  // across ranks for the same ops.  Waking only THIS handle's cv keeps a
  // group of N outstanding collectives at O(N) wakeups total instead of
  // the O(N^2) a global notify_all per completion costs.
  {
    std::lock_guard<std::mutex> lk(status->mu);
    status->completion_seq = completions_.fetch_add(1);
    status->completion_tick = ticks_done_.load();
    status->negotiation_us = e.negotiation_us;
    status->error = error;
    status->code.store(code);
  }
  status->cv.notify_all();
}

// ---------------------------------------------------------------------------
// Ring data plane.
// ---------------------------------------------------------------------------

bool Engine::RingAllreduce(void* buf, int64_t count, uint8_t dtype,
                           std::string* err) {
  return RingAllreduceOn(buf, count, dtype, opts_.size, opts_.rank, left_ch_,
                         right_ch_, err);
}

namespace {

// Segment bookkeeping for one direction of the bidirectional ring.
// `index` is the rank's position in the (possibly relabeled) ring.
struct HalfRing {
  char* data;
  int64_t count = 0;
  size_t esize;
  int N, index;

  int64_t base() const { return count / N; }
  int64_t rem() const { return count % N; }
  int64_t seg_start(int i) const {
    return i * base() + std::min<int64_t>(i, rem());
  }
  int64_t seg_count(int i) const { return base() + (i < rem() ? 1 : 0); }
  int send_seg(int step, bool gather) const {
    int r = gather ? index + 1 : index;
    return ((r - step) % N + N) % N;
  }
  int recv_seg(int step, bool gather) const {
    int r = gather ? index : index - 1;
    return ((r - step) % N + N) % N;
  }
  char* send_ptr(int step, bool gather) const {
    return data + seg_start(send_seg(step, gather)) * esize;
  }
  size_t send_len(int step, bool gather) const {
    return static_cast<size_t>(seg_count(send_seg(step, gather))) * esize;
  }
  char* recv_ptr(int step, bool gather) const {
    return data + seg_start(recv_seg(step, gather)) * esize;
  }
  size_t recv_len(int step, bool gather) const {
    return static_cast<size_t>(seg_count(recv_seg(step, gather))) * esize;
  }
};

}  // namespace

bool Engine::RingAllreduceOn(void* buf, int64_t count, uint8_t dtype, int N,
                             int index, const Channel& left,
                             const Channel& right, std::string* err) {
  // Bidirectional ring: the buffer splits into two halves that travel in
  // opposite directions simultaneously — half A rightward (send on
  // right_fd, receive on left_fd) and half B leftward on the mirrored ring
  // (relabeling rank r as (N - r) % N turns the physical left neighbour
  // into the logical "right" one, so the same segment schedule applies).
  // Each link is full-duplex TCP, so this doubles usable bandwidth over
  // the unidirectional ring (the role NCCL's multi-channel rings play for
  // the reference, operations.cc:1050).
  if (N == 1 || count == 0) return true;
  size_t esize = DataTypeSize(dtype);
  char* data = static_cast<char*>(buf);
  int64_t cB = count / 2, cA = count - cB;
  HalfRing A{data, cA, esize, N, index};
  HalfRing B{data + cA * esize, cB, esize, N, (N - index) % N};
  int64_t max_seg = cA / N + (cA % N ? 1 : 0);
  int64_t max_seg_b = cB / N + (cB % N ? 1 : 0);
  std::vector<char> tmpA(static_cast<size_t>(max_seg) * esize);
  std::vector<char> tmpB(static_cast<size_t>(max_seg_b) * esize);

  // Phase 1: reduce-scatter both halves.  After N-1 steps this rank owns
  // fully reduced segment (index+1) of A and (mirror+1) of B.
  for (int step = 0; step < N - 1; ++step) {
    if (!ChannelExchangeBi(right, A.send_ptr(step, false),
                           A.send_len(step, false), tmpB.data(),
                           B.recv_len(step, false), left,
                           B.send_ptr(step, false), B.send_len(step, false),
                           tmpA.data(), A.recv_len(step, false))) {
      *err = "neighbour exchange failed (reduce-scatter step " +
             std::to_string(step) + ")";
      return false;
    }
    AccumulateSum(A.recv_ptr(step, false), tmpA.data(),
                  A.seg_count(A.recv_seg(step, false)), dtype);
    AccumulateSum(B.recv_ptr(step, false), tmpB.data(),
                  B.seg_count(B.recv_seg(step, false)), dtype);
  }
  // Phase 2: allgather of reduced segments, both directions.
  for (int step = 0; step < N - 1; ++step) {
    if (!ChannelExchangeBi(right, A.send_ptr(step, true),
                           A.send_len(step, true), B.recv_ptr(step, true),
                           B.recv_len(step, true), left,
                           B.send_ptr(step, true), B.send_len(step, true),
                           A.recv_ptr(step, true), A.recv_len(step, true))) {
      *err = "neighbour exchange failed (allgather step " +
             std::to_string(step) + ")";
      return false;
    }
  }
  return true;
}

bool Engine::RingAllreduceWire(float* buf, int64_t count, uint8_t wire,
                               int N, int index, const Channel& left,
                               const Channel& right, std::string* err) {
  // The bidirectional ring of RingAllreduceOn with the wire narrowed:
  // the local buffer stays f32 (every hop accumulates in f32), segments
  // are compressed at the send boundary and decompressed at the receive
  // boundary.  The allgather phase recompresses the owner's reduced f32
  // segment on every forward hop — exact, because dequantized values are
  // representable in the wire format by construction — so forwarding
  // needs no wire-byte staging.
  if (N == 1 || count == 0) return true;
  const size_t wsz = WireFormatSize(wire);
  int64_t cB = count / 2, cA = count - cB;
  HalfRing A{reinterpret_cast<char*>(buf), cA, sizeof(float), N, index};
  HalfRing B{reinterpret_cast<char*>(buf + cA), cB, sizeof(float), N,
             (N - index) % N};
  int64_t max_a = cA / N + (cA % N ? 1 : 0);
  int64_t max_b = cB / N + (cB % N ? 1 : 0);
  std::vector<uint8_t> send_a(static_cast<size_t>(max_a) * wsz);
  std::vector<uint8_t> send_b(static_cast<size_t>(max_b) * wsz);
  std::vector<uint8_t> recv_a(static_cast<size_t>(max_a) * wsz);
  std::vector<uint8_t> recv_b(static_cast<size_t>(max_b) * wsz);
  float* bufB = buf + cA;

  for (int gather = 0; gather < 2; ++gather) {
    bool g = gather != 0;
    for (int step = 0; step < N - 1; ++step) {
      int64_t sa = A.seg_count(A.send_seg(step, g));
      int64_t sb = B.seg_count(B.send_seg(step, g));
      int64_t ra = A.seg_count(A.recv_seg(step, g));
      int64_t rb = B.seg_count(B.recv_seg(step, g));
      CompressBuf(buf + A.seg_start(A.send_seg(step, g)), send_a.data(), sa,
                  wire);
      CompressBuf(bufB + B.seg_start(B.send_seg(step, g)), send_b.data(), sb,
                  wire);
      if (!ChannelExchangeBi(right, send_a.data(),
                             static_cast<size_t>(sa) * wsz, recv_b.data(),
                             static_cast<size_t>(rb) * wsz, left,
                             send_b.data(), static_cast<size_t>(sb) * wsz,
                             recv_a.data(), static_cast<size_t>(ra) * wsz)) {
        *err = std::string("neighbour exchange failed (compressed ") +
               (g ? "allgather" : "reduce-scatter") + " step " +
               std::to_string(step) + ")";
        return false;
      }
      if (g) {
        // Allgather: adopt the fully reduced segment as broadcast.
        DecompressBuf(recv_a.data(), buf + A.seg_start(A.recv_seg(step, g)),
                      ra, wire);
        DecompressBuf(recv_b.data(), bufB + B.seg_start(B.recv_seg(step, g)),
                      rb, wire);
      } else {
        // Reduce-scatter: accumulate in f32.
        DecompressAccumulate(recv_a.data(),
                             buf + A.seg_start(A.recv_seg(step, g)), ra,
                             wire);
        DecompressAccumulate(recv_b.data(),
                             bufB + B.seg_start(B.recv_seg(step, g)), rb,
                             wire);
      }
    }
    // The owned, fully reduced segments are forwarded quantized during
    // the allgather phase; quantize the local copy too, so every rank
    // ends with IDENTICAL values (the owner must not keep a higher-
    // precision copy than it broadcast).
    if (!g) {
      int64_t oa = A.seg_count(A.send_seg(0, true));
      float* pa = buf + A.seg_start(A.send_seg(0, true));
      for (int64_t i = 0; i < oa; ++i) pa[i] = QuantDequant(pa[i], wire);
      int64_t ob = B.seg_count(B.send_seg(0, true));
      float* pb = bufB + B.seg_start(B.send_seg(0, true));
      for (int64_t i = 0; i < ob; ++i) pb[i] = QuantDequant(pb[i], wire);
    }
  }
  return true;
}

uint8_t Engine::ChooseCompression(uint8_t dtype, int64_t bytes) const {
  uint8_t mode = static_cast<uint8_t>(cur_compression_.load());
  if (mode == COMP_NONE) return COMP_NONE;
  // Lossy wire formats apply to fp32 payloads only: f16/bf16 already
  // ship at native width and integer sums must stay exact.  A
  // single-rank job moves no wire bytes at all.  On the two-level
  // topology the verdict narrows the cross-node (DCN) hop while the
  // intra-node hop stays full width (TwoLevelAllreduce).
  if (dtype != HVD_FLOAT32) return COMP_NONE;
  if (opts_.size <= 1) return COMP_NONE;
  // A single-node two-level job has no DCN hop — the only hop the
  // verdict narrows — so compressing would round gradients for zero
  // wire savings (and report a phantom compression win in the metrics).
  if (opts_.hierarchical_allreduce && n_nodes_ <= 1) return COMP_NONE;
  // The min-bytes floor keeps latency-bound small buckets uncompressed:
  // their cost is negotiation + syscalls, not bandwidth, and the
  // quantize/dequantize passes would be pure overhead.
  if (bytes < opts_.compression_min_bytes) return COMP_NONE;
  return mode;
}

void Engine::RecordCompressedOp(const std::string& name, uint8_t mode,
                                int64_t payload_bytes, int64_t wire_bytes) {
  comp_payload_bytes_.fetch_add(payload_bytes);
  comp_wire_bytes_.fetch_add(wire_bytes);
  switch (mode) {
    case COMP_BF16: comp_ops_bf16_.fetch_add(1); break;
    case COMP_FP8: comp_ops_fp8_.fetch_add(1); break;
    default: comp_ops_none_.fetch_add(1); break;
  }
  std::lock_guard<std::mutex> lk(comp_mu_);
  std::string entry;
  for (char c : name) entry += (c == ';' || c == '|') ? '_' : c;
  entry += std::string("|") + CompressionName(mode);
  comp_log_.push_back(std::move(entry));
  while (comp_log_.size() > 256) comp_log_.pop_front();
}

std::string Engine::CompressionInfo() {
  return std::to_string(comp_wire_bytes_.load()) + "|" +
         std::to_string(comp_payload_bytes_.load()) + "|" +
         std::to_string(comp_ops_none_.load()) + "|" +
         std::to_string(comp_ops_bf16_.load()) + "|" +
         std::to_string(comp_ops_fp8_.load()) + "|" +
         std::to_string(residual_bytes_.load()) + "|" +
         std::to_string(residual_tensors_.load()) + "|" +
         std::to_string(cur_comp_min_bytes_.load());
}

std::string Engine::CompressionLog() {
  std::lock_guard<std::mutex> lk(comp_mu_);
  std::string out;
  for (const auto& e : comp_log_) {
    if (!out.empty()) out += ';';
    out += e;
  }
  return out;
}

// Segment bookkeeping for the node-local reduce-scatter/allgather: `n`
// elements split into `P` near-equal parts (the first `rem` get one
// extra), matching the HalfRing partition convention.
namespace {
struct SegPart {
  int64_t n;
  int P;
  int64_t base() const { return n / P; }
  int64_t rem() const { return n % P; }
  int64_t start(int i) const {
    return i * base() + std::min<int64_t>(i, rem());
  }
  int64_t cnt(int i) const { return base() + (i < rem() ? 1 : 0); }
};
}  // namespace

bool Engine::LocalReduceScatter(char* data, int64_t n, uint8_t dtype,
                                uint8_t wire, int64_t* bytes_moved,
                                std::string* err) {
  const int L = opts_.local_size, r = opts_.local_rank;
  if (L == 1 || n == 0) return true;
  const size_t esize = DataTypeSize(dtype);
  const size_t unit = wire == 255 ? esize : WireFormatSize(wire);
  SegPart part{n, L};
  int64_t max_seg = part.base() + (part.rem() ? 1 : 0);
  std::vector<uint8_t> sendw;
  if (wire != 255) sendw.resize(static_cast<size_t>(max_seg) * unit);
  std::vector<uint8_t> recvw(static_cast<size_t>(max_seg) *
                             std::max(unit, esize));
  // Standard ring reduce-scatter: after L-1 steps local rank r owns the
  // fully reduced segment (r+1) % L.
  for (int step = 0; step < L - 1; ++step) {
    int ss = ((r - step) % L + L) % L;
    int rs = ((r - step - 1) % L + L) % L;
    const void* sp = data + part.start(ss) * esize;
    if (wire != 255) {
      CompressBuf(reinterpret_cast<const float*>(data) + part.start(ss),
                  sendw.data(), part.cnt(ss), wire);
      sp = sendw.data();
    }
    if (!ChannelExchange(local_right_ch_, sp,
                         static_cast<size_t>(part.cnt(ss)) * unit,
                         local_left_ch_, recvw.data(),
                         static_cast<size_t>(part.cnt(rs)) * unit)) {
      *err = "node-local reduce-scatter exchange failed (step " +
             std::to_string(step) + ")";
      return false;
    }
    *bytes_moved += part.cnt(ss) * static_cast<int64_t>(unit);
    if (wire == 255)
      AccumulateSum(data + part.start(rs) * esize, recvw.data(),
                    part.cnt(rs), dtype);
    else
      DecompressAccumulate(recvw.data(),
                           reinterpret_cast<float*>(data) + part.start(rs),
                           part.cnt(rs), wire);
  }
  return true;
}

bool Engine::LocalAllgather(char* data, int64_t n, uint8_t dtype,
                            uint8_t wire, int64_t* bytes_moved,
                            std::string* err) {
  const int L = opts_.local_size, r = opts_.local_rank;
  if (L == 1 || n == 0) return true;
  const size_t esize = DataTypeSize(dtype);
  const size_t unit = wire == 255 ? esize : WireFormatSize(wire);
  SegPart part{n, L};
  int64_t max_seg = part.base() + (part.rem() ? 1 : 0);
  std::vector<uint8_t> sendw, recvw;
  if (wire != 255) {
    sendw.resize(static_cast<size_t>(max_seg) * unit);
    recvw.resize(static_cast<size_t>(max_seg) * unit);
  }
  // Ring allgather from the RS ownership map (rank r owns (r+1) % L):
  // step s forwards segment (r+1-s) % L rightward and adopts
  // (r-s) % L from the left.
  for (int step = 0; step < L - 1; ++step) {
    int ss = ((r + 1 - step) % L + L) % L;
    int rs = ((r - step) % L + L) % L;
    const void* sp = data + part.start(ss) * esize;
    void* rp = data + part.start(rs) * esize;
    if (wire != 255) {
      // Exact: allgather segments are already wire-representable (the
      // owner quantized its reduced segment before the first forward).
      CompressBuf(reinterpret_cast<const float*>(data) + part.start(ss),
                  sendw.data(), part.cnt(ss), wire);
      sp = sendw.data();
      rp = recvw.data();
    }
    if (!ChannelExchange(local_right_ch_, sp,
                         static_cast<size_t>(part.cnt(ss)) * unit,
                         local_left_ch_, rp,
                         static_cast<size_t>(part.cnt(rs)) * unit)) {
      *err = "node-local allgather exchange failed (step " +
             std::to_string(step) + ")";
      return false;
    }
    *bytes_moved += part.cnt(ss) * static_cast<int64_t>(unit);
    if (wire != 255)
      DecompressBuf(recvw.data(),
                    reinterpret_cast<float*>(data) + part.start(rs),
                    part.cnt(rs), wire);
  }
  return true;
}

bool Engine::CrossTreeAllreduce(char* seg, int64_t n, uint8_t dtype,
                                uint8_t wire, std::string* err) {
  const size_t esize = DataTypeSize(dtype);
  const size_t unit = wire == 255 ? esize : WireFormatSize(wire);
  std::vector<uint8_t> sendw;
  if (wire != 255) sendw.resize(static_cast<size_t>(n) * unit);
  std::vector<uint8_t> recvw(static_cast<size_t>(n) * unit);
  float* f = reinterpret_cast<float*>(seg);
  // Recursive doubling: at level k, XOR partners exchange their full
  // running sums and both add — log2(nodes) latency steps, the win for
  // latency-bound small shards.
  for (size_t k = 0; k < cross_tree_fds_.size(); ++k) {
    int fd = cross_tree_fds_[k];
    if (fd < 0) {
      *err = "cross-node tree partner closed after an earlier failure";
      return false;
    }
    const void* sp = seg;
    if (wire != 255) {
      // Quantize the running sum first, so both partners add IDENTICAL
      // dequantized values — float addition is commutative, which keeps
      // every node's shard bit-identical through the whole tree.
      for (int64_t i = 0; i < n; ++i) f[i] = QuantDequant(f[i], wire);
      CompressBuf(f, sendw.data(), n, wire);
      sp = sendw.data();
    }
    // Ad-hoc channel: tree partners are TCP-only (they live on other
    // hosts by construction), but routing through the seam keeps the
    // telemetry and chaos hooks uniform.
    Channel tc{fd, nullptr, nullptr,
               (node_id_ ^ (1 << k)) * opts_.local_size + opts_.local_rank};
    if (!ChannelExchange(tc, sp, static_cast<size_t>(n) * unit, tc,
                         recvw.data(), static_cast<size_t>(n) * unit)) {
      *err = "cross-node tree exchange failed (level " +
             std::to_string(k) + ")";
      return false;
    }
    if (wire == 255)
      AccumulateSum(seg, recvw.data(), n, dtype);
    else
      DecompressAccumulate(recvw.data(), f, n, wire);
  }
  return true;
}

bool Engine::CrossShardAllreduce(char* seg, int64_t n, uint8_t dtype,
                                 uint8_t wire, bool use_tree,
                                 int64_t* bytes_moved, std::string* err) {
  if (n_nodes_ == 1 || n == 0) return true;
  const size_t esize = DataTypeSize(dtype);
  const size_t unit = wire == 255 ? esize : WireFormatSize(wire);
  if (use_tree && !cross_tree_fds_.empty()) {
    if (!CrossTreeAllreduce(seg, n, dtype, wire, err)) return false;
    *bytes_moved += static_cast<int64_t>(cross_tree_fds_.size()) * n *
                    static_cast<int64_t>(unit);
    return true;
  }
  if (cross_left_fd_ < 0 || cross_right_fd_ < 0) {
    *err = "cross-node ring closed after an earlier failure";
    return false;
  }
  bool ok =
      wire == 255
          ? RingAllreduceOn(seg, n, dtype, n_nodes_, node_id_,
                            cross_left_ch_, cross_right_ch_, err)
          : RingAllreduceWire(reinterpret_cast<float*>(seg), n, wire,
                              n_nodes_, node_id_, cross_left_ch_,
                              cross_right_ch_, err);
  if (ok)
    *bytes_moved += 2 * static_cast<int64_t>(n_nodes_ - 1) *
                    ((n + n_nodes_ - 1) / n_nodes_) *
                    static_cast<int64_t>(unit);
  return ok;
}

bool Engine::TwoLevelAllreduce(void* vbuf, int64_t count, uint8_t dtype,
                               uint8_t local_wire, uint8_t cross_wire,
                               bool use_tree, const std::string& name,
                               std::string* err) {
  // The bandwidth-optimal two-level decomposition (docs/performance.md
  // #two-level-topology), replacing the reference's ncclReduce ->
  // MPI_Allreduce -> ncclBcast star (operations.cc:1003-1048):
  //
  //   1. LOCAL_RS    node-local ring reduce-scatter — local rank r ends
  //                  owning the fully node-reduced shard (r+1) % L.
  //   2. CROSS_*     EVERY local rank drives its own cross-node
  //                  exchange (ring or recursive-doubling tree) over its
  //                  shard — local_size parallel DCN streams instead of
  //                  one leader NIC, optionally compressed (bf16/fp8
  //                  with f32 accumulation, the PR-9 wire machinery).
  //   3. LOCAL_AG    node-local ring allgather of the reduced shards.
  //
  // Chunk-level pipelining: the bucket splits into chunks; a helper
  // thread runs phase 2 on the cross fds while the engine thread runs
  // phases 1/3 on the local fds, so the local hops of chunk c overlap
  // the DCN hop of its neighbours instead of waiting behind phase
  // barriers.  Sum semantics throughout; averaging stays the caller's
  // divide-by-global-size.
  if (opts_.size == 1 || count == 0) return true;
  const int L = opts_.local_size;
  const int M = n_nodes_;
  char* data = static_cast<char*>(vbuf);
  const size_t esize = DataTypeSize(dtype);
  if (L > 1 && (local_left_fd_ < 0 || local_right_fd_ < 0)) {
    *err = "node-local ring closed after an earlier failure";
    return false;
  }
  if (M > 1 && (cross_left_fd_ < 0 || cross_right_fd_ < 0)) {
    *err = "cross-node ring closed after an earlier failure";
    return false;
  }
  const int64_t kChunkBytes = 4 << 20;
  int64_t chunk_elems =
      std::max<int64_t>(kChunkBytes / static_cast<int64_t>(esize), L);
  int n_chunks = static_cast<int>((count + chunk_elems - 1) / chunk_elems);

  int64_t local_bytes = 0, cross_bytes = 0;
  int64_t local_rs_us = 0, cross_us_total = 0, local_ag_us = 0;

  // Pipeline handshake: rs_done / cross_done are chunk high-water marks.
  std::mutex pmu;
  std::condition_variable pcv;
  int rs_done = 0, cross_done = 0;
  bool failed = false;
  std::string cross_err;

  const int own = (opts_.local_rank + 1) % L;
  auto own_seg = [&](int c, int64_t* s, int64_t* cn) {
    int64_t off = static_cast<int64_t>(c) * chunk_elems;
    int64_t n = std::min(chunk_elems, count - off);
    SegPart part{n, L};
    *s = off + part.start(own);
    *cn = part.cnt(own);
  };

  // Pipelining pays one thread spawn per bucket; a single-chunk bucket
  // has nothing to overlap, so latency-bound buckets run the cross hop
  // inline on the engine thread instead.
  const bool pipelined = M > 1 && n_chunks > 1;
  std::thread cross_thread;
  if (pipelined) {
    cross_thread = std::thread([&]() {
      for (int c = 0; c < n_chunks; ++c) {
        {
          std::unique_lock<std::mutex> lk(pmu);
          pcv.wait(lk, [&] { return rs_done > c || failed; });
          if (failed) return;
        }
        int64_t s, cn;
        own_seg(c, &s, &cn);
        std::string e;
        auto t0 = std::chrono::steady_clock::now();
        bool ok_c = CrossShardAllreduce(data + s * esize, cn, dtype,
                                        cross_wire, use_tree, &cross_bytes,
                                        &e);
        cross_us_total +=
            std::chrono::duration_cast<std::chrono::microseconds>(
                std::chrono::steady_clock::now() - t0)
                .count();
        std::lock_guard<std::mutex> lk(pmu);
        if (!ok_c) {
          failed = true;
          cross_err = e;
          pcv.notify_all();
          return;
        }
        cross_done = c + 1;
        pcv.notify_all();
      }
    });
  }

  bool ok = true;
  // Phase 1: each reduce-scattered chunk is handed to the cross thread
  // immediately.
  timeline_.ActivityStart(name, "LOCAL_RS");
  {
    auto t0 = std::chrono::steady_clock::now();
    for (int c = 0; c < n_chunks && ok; ++c) {
      int64_t off = static_cast<int64_t>(c) * chunk_elems;
      int64_t n = std::min(chunk_elems, count - off);
      if (!LocalReduceScatter(data + off * esize, n, dtype, local_wire,
                              &local_bytes, err)) {
        ok = false;
        break;
      }
      std::lock_guard<std::mutex> lk(pmu);
      rs_done = c + 1;
      pcv.notify_all();
    }
    local_rs_us = std::chrono::duration_cast<std::chrono::microseconds>(
                      std::chrono::steady_clock::now() - t0)
                      .count();
  }
  timeline_.ActivityEnd(name);
  // Phase 2 as the engine thread sees it: pipelined, the exposed head of
  // the cross pipeline (the DCN hop of later chunks overlaps phase 3
  // below); unpipelined, the whole inline cross exchange.
  if (ok && M > 1) {
    timeline_.ActivityStart(name, use_tree && !cross_tree_fds_.empty()
                                      ? "CROSS_TREE"
                                      : "CROSS_RING");
    if (pipelined) {
      std::unique_lock<std::mutex> lk(pmu);
      pcv.wait(lk, [&] { return cross_done >= 1 || failed; });
    } else {
      for (int c = 0; c < n_chunks && ok; ++c) {
        int64_t s, cn;
        own_seg(c, &s, &cn);
        auto t0 = std::chrono::steady_clock::now();
        if (!CrossShardAllreduce(data + s * esize, cn, dtype, cross_wire,
                                 use_tree, &cross_bytes, err))
          ok = false;
        cross_us_total +=
            std::chrono::duration_cast<std::chrono::microseconds>(
                std::chrono::steady_clock::now() - t0)
                .count();
      }
    }
    timeline_.ActivityEnd(name);
  }
  // Phase 3: allgather each chunk as its cross hop completes.
  if (ok) {
    timeline_.ActivityStart(name, "LOCAL_AG");
    for (int c = 0; c < n_chunks && ok; ++c) {
      if (pipelined) {
        std::unique_lock<std::mutex> lk(pmu);
        pcv.wait(lk, [&] { return cross_done > c || failed; });
        if (failed) {
          ok = false;
          break;
        }
      }
      int64_t off = static_cast<int64_t>(c) * chunk_elems;
      int64_t n = std::min(chunk_elems, count - off);
      if (local_wire != 255) {
        // The reduced shard is forwarded narrowed: quantize the local
        // copy first so every local rank converges to IDENTICAL values
        // (the RingAllreduceWire owner-quantize rule).  Exact when the
        // cross hop already quantized to the same format.
        int64_t s, cn;
        own_seg(c, &s, &cn);
        float* p = reinterpret_cast<float*>(data) + s;
        for (int64_t i = 0; i < cn; ++i)
          p[i] = QuantDequant(p[i], local_wire);
      }
      auto t0 = std::chrono::steady_clock::now();
      if (!LocalAllgather(data + off * esize, n, dtype, local_wire,
                          &local_bytes, err))
        ok = false;
      local_ag_us += std::chrono::duration_cast<std::chrono::microseconds>(
                         std::chrono::steady_clock::now() - t0)
                         .count();
    }
    timeline_.ActivityEnd(name);
  }
  {
    std::lock_guard<std::mutex> lk(pmu);
    if (!ok) failed = true;
    pcv.notify_all();
  }
  // On any failure wake everyone fast: peers blocked on our topology
  // sockets see EOF instead of stalling to the 30s exchange timeout, and
  // the helper thread's in-flight exchange errors out so the join below
  // cannot hang.  Close (and latch the fds at -1) only after the join.
  if (!ok) ShutdownTopologyFds();
  if (cross_thread.joinable()) cross_thread.join();
  if (ok && failed) {
    ok = false;
    ShutdownTopologyFds();
  }
  if (!ok && err->empty())
    *err = cross_err.empty() ? "cross-node exchange failed" : cross_err;
  if (!ok) CloseTopologyFds();
  topo_local_bytes_.fetch_add(local_bytes);
  topo_cross_bytes_.fetch_add(cross_bytes);
  RecordTopologyOp(name, use_tree && M > 1 && !cross_tree_fds_.empty(),
                   local_rs_us, cross_us_total, local_ag_us);
  return ok;
}

void Engine::RecordTopologyOp(const std::string& name, bool tree,
                              int64_t local_rs_us, int64_t cross_us,
                              int64_t local_ag_us) {
  std::lock_guard<std::mutex> lk(topo_mu_);
  std::string entry;
  for (char c : name) entry += (c == ';' || c == '|') ? '_' : c;
  entry += std::string("|") + (tree ? "tree" : "ring") + "|" +
           std::to_string(local_rs_us) + "|" + std::to_string(cross_us) +
           "|" + std::to_string(local_ag_us);
  topo_log_.push_back(std::move(entry));
  while (topo_log_.size() > 256) topo_log_.pop_front();
  ++topo_log_total_;
  // Cumulative phase sums: the anomaly detector's per-phase input (sweep
  // deltas -> mean phase time per interval, no log parsing).
  topo_rs_us_.fetch_add(local_rs_us);
  topo_cross_us_.fetch_add(cross_us);
  topo_ag_us_.fetch_add(local_ag_us);
  topo_timed_ops_.fetch_add(1);
}

std::string Engine::TopologyInfo() {
  int64_t log_total;
  {
    std::lock_guard<std::mutex> lk(topo_mu_);
    log_total = topo_log_total_;
  }
  bool hier = topo_hier_.load() && cur_size_.load() > 1;
  return std::string(hier ? "1" : "0") + "|" +
         std::to_string(topo_nodes_.load()) +
         "|" + std::to_string(cur_local_size_.load()) + "|" +
         std::to_string(cur_cross_algo_.load()) + "|" +
         std::to_string(topo_ops_ring_.load()) + "|" +
         std::to_string(topo_ops_tree_.load()) + "|" +
         std::to_string(topo_local_bytes_.load()) + "|" +
         std::to_string(topo_cross_bytes_.load()) + "|" +
         std::to_string(log_total) + "|" +
         (topo_shm_.load() ? "shm" : "tcp");
}

std::string Engine::TopologyLog() {
  std::lock_guard<std::mutex> lk(topo_mu_);
  std::string out;
  for (const auto& e : topo_log_) {
    if (!out.empty()) out += ';';
    out += e;
  }
  return out;
}

bool Engine::RingAllgather(char* buf, const std::vector<int64_t>& block_bytes,
                           std::string* err) {
  int N = opts_.size;
  if (N == 1) return true;
  std::vector<int64_t> off(N, 0);
  for (int i = 1; i < N; ++i) off[i] = off[i - 1] + block_bytes[i - 1];
  int r = opts_.rank;
  for (int step = 0; step < N - 1; ++step) {
    int ss = ((r - step) % N + N) % N;
    int rs = ((r - step - 1) % N + N) % N;
    if (!ChannelExchange(right_ch_, buf + off[ss],
                         static_cast<size_t>(block_bytes[ss]), left_ch_,
                         buf + off[rs], static_cast<size_t>(block_bytes[rs]))) {
      *err = "neighbour exchange failed (allgather step " +
             std::to_string(step) + ")";
      return false;
    }
  }
  return true;
}

bool Engine::RingBroadcast(void* buf, int64_t nbytes, int root,
                           std::string* err) {
  int N = opts_.size;
  if (N == 1 || nbytes == 0) return true;
  const int64_t kChunk = 1 << 20;  // pipeline at 1 MiB granularity
  int dist = ((opts_.rank - root) % N + N) % N;
  bool recv_from_left = dist != 0;
  bool send_to_right = dist != N - 1;
  char* p = static_cast<char*>(buf);
  for (int64_t o = 0; o < nbytes; o += kChunk) {
    int64_t len = std::min(kChunk, nbytes - o);
    if (recv_from_left &&
        !ChannelRecvAll(left_ch_, p + o, static_cast<size_t>(len))) {
      *err = "broadcast recv failed";
      return false;
    }
    if (send_to_right &&
        !ChannelSendAll(right_ch_, p + o, static_cast<size_t>(len))) {
      *err = "broadcast send failed";
      return false;
    }
  }
  return true;
}

// ---------------------------------------------------------------------------
// Handle API.
// ---------------------------------------------------------------------------

int Engine::Poll(int64_t handle) {
  std::lock_guard<std::mutex> lk(handles_mu_);
  auto it = handles_.find(handle);
  if (it == handles_.end()) return -1;
  return it->second->code.load() == ST_PENDING ? 0 : 1;
}

int32_t Engine::Wait(int64_t handle) {
  std::shared_ptr<HandleStatus> status;
  {
    std::lock_guard<std::mutex> lk(handles_mu_);
    auto it = handles_.find(handle);
    if (it == handles_.end()) return ST_INVALID;
    status = it->second;
  }
  std::unique_lock<std::mutex> lk(status->mu);
  status->cv.wait(lk, [&]() { return status->code.load() != ST_PENDING; });
  return status->code.load();
}

int32_t Engine::StatusOf(int64_t handle, std::string* error) {
  std::lock_guard<std::mutex> lk(handles_mu_);
  auto it = handles_.find(handle);
  if (it == handles_.end()) return ST_INVALID;
  if (error) *error = it->second->error;
  return it->second->code.load();
}

int64_t Engine::CompletionSeq(int64_t handle) {
  std::lock_guard<std::mutex> lk(handles_mu_);
  auto it = handles_.find(handle);
  if (it == handles_.end() || it->second->code.load() == ST_PENDING) return -1;
  return it->second->completion_seq;
}

int64_t Engine::CompletionTick(int64_t handle) {
  std::lock_guard<std::mutex> lk(handles_mu_);
  auto it = handles_.find(handle);
  if (it == handles_.end() || it->second->code.load() == ST_PENDING) return -1;
  return it->second->completion_tick;
}

int64_t Engine::NegotiationUs(int64_t handle) {
  std::lock_guard<std::mutex> lk(handles_mu_);
  auto it = handles_.find(handle);
  if (it == handles_.end() || it->second->code.load() == ST_PENDING) return -1;
  return it->second->negotiation_us;
}

int64_t Engine::ResultBytes(int64_t handle) {
  std::lock_guard<std::mutex> lk(handles_mu_);
  auto it = handles_.find(handle);
  if (it == handles_.end()) return -1;
  return static_cast<int64_t>(it->second->gathered.size());
}

int64_t Engine::ResultDim0(int64_t handle) {
  std::lock_guard<std::mutex> lk(handles_mu_);
  auto it = handles_.find(handle);
  if (it == handles_.end()) return -1;
  return it->second->out_dim0;
}

bool Engine::CopyResult(int64_t handle, void* dst, int64_t nbytes) {
  std::shared_ptr<HandleStatus> status;
  {
    std::lock_guard<std::mutex> lk(handles_mu_);
    auto it = handles_.find(handle);
    if (it == handles_.end()) return false;
    status = it->second;
  }
  if (nbytes != static_cast<int64_t>(status->gathered.size())) return false;
  memcpy(dst, status->gathered.data(), static_cast<size_t>(nbytes));
  return true;
}

void* Engine::ResultPtr(int64_t handle) {
  std::lock_guard<std::mutex> lk(handles_mu_);
  auto it = handles_.find(handle);
  if (it == handles_.end() || it->second->code.load() == ST_PENDING ||
      it->second->gathered.empty())
    return nullptr;
  return it->second->gathered.data();
}

void Engine::Release(int64_t handle) {
  std::lock_guard<std::mutex> lk(handles_mu_);
  handles_.erase(handle);
}

}  // namespace hvdtpu
