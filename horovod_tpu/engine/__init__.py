"""Native collective engine: C++ sources, build, and ctypes bindings."""

from horovod_tpu.engine.build import build, lib_path  # noqa: F401
