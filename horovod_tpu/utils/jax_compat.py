"""jax cross-version compatibility helpers.

The repo supports the CI min-versions leg (jax 0.4.x) through current
releases; API moves are funneled through here (the shard_map kwarg rename
is handled in horovod_tpu/jax/train.py, which predates this module).
"""

from __future__ import annotations


def axis_size(axis) -> int:
    """Participant count of a mapped mesh axis.  ``lax.axis_size`` arrived
    in jax 0.6; earlier versions use the classic psum-of-one idiom, which
    constant-folds to a static int at trace time (so callers may use it in
    shape arithmetic and static modulos)."""
    from jax import lax

    try:
        return lax.axis_size(axis)
    except AttributeError:
        return lax.psum(1, axis)


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` across versions: renamed from
    ``TPUCompilerParams`` in jax 0.6, which also gained new fields
    (``has_side_effects``).  Kwargs the installed class does not accept
    are dropped — on those versions they are compilation hints that do
    not exist, not semantics we can emulate."""
    import inspect

    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
        params = inspect.signature(cls).parameters
        kwargs = {k: v for k, v in kwargs.items() if k in params}
    return cls(**kwargs)


def shape_dtype_struct(shape, dtype, vma=None):
    """``jax.ShapeDtypeStruct`` with the vma annotation where the
    installed jax supports it (0.6+); plain otherwise."""
    import jax

    if vma is not None:
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        except TypeError:
            pass
    return jax.ShapeDtypeStruct(shape, dtype)


def vma(x):
    """The varying-manual-axes set of a value's abstract type, or None
    where the concept does not exist.  ``jax.typeof`` arrived with the
    vma machinery (jax 0.6); earlier versions have neither, and callers
    treat None as "nothing varies" (the pre-vma semantics)."""
    import jax

    try:
        aval = jax.typeof(x)
    except AttributeError:
        aval = getattr(x, "aval", None)
    return getattr(aval, "vma", None)
