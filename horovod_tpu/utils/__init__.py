"""Shared utilities: platform control."""

from horovod_tpu.utils.platform import apply_env_platform  # noqa: F401
