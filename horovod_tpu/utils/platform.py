"""JAX platform selection that survives site-customization hooks.

Some environments pre-register an accelerator platform through `jax.config`
at interpreter startup, which silently overrides the documented
``JAX_PLATFORMS`` environment variable.  :func:`apply_env_platform`
re-asserts the environment variable's choice through `jax.config` so that
``JAX_PLATFORMS=cpu python examples/...`` always means CPU (e.g. for the
virtual ``--xla_force_host_platform_device_count=N`` test mesh).

Must run before any JAX backend initializes (first `jax.devices()` /
computation).
"""

from __future__ import annotations

import os


def apply_env_platform() -> None:
    platforms = os.environ.get("JAX_PLATFORMS")
    if not platforms:
        return
    import jax

    jax.config.update("jax_platforms", platforms)
