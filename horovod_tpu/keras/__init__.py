"""Keras binding: DistributedOptimizer, value collectives, load_model.

Counterpart of /root/reference/horovod/keras/__init__.py, redesigned for
Keras 3: the optimizer wrapper dynamically subclasses the wrapped
optimizer's class — keeping its class name so checkpoints save/load without
horovod installed (reference lines 30-90 keep the same property) — and
averages gradients across workers in `apply_gradients`.  `load_model`
re-wraps any stock or custom optimizer on load (reference lines 150-196).
Training callbacks live in `horovod_tpu.keras.callbacks`.
"""

from __future__ import annotations

from typing import Optional

import keras
import numpy as np

import horovod_tpu.common as _common
from horovod_tpu.common import (  # noqa: F401  (process-control re-exports)
    HorovodInternalError,
    init,
    is_initialized,
    local_rank,
    local_size,
    mpi_threads_supported,
    rank,
    shutdown,
    size,
)


def _tf_backend() -> bool:
    return keras.backend.backend() == "tensorflow"


def _average_gradients(grads):
    if _common.size() == 1:
        return list(grads)
    if _tf_backend():
        # Graph-safe path (model.fit traces train_step into a tf.function):
        # one enqueue-all-then-wait group so the gradients fuse and overlap
        # instead of blocking one engine cycle each.
        import horovod_tpu.tensorflow as hvd_tf

        return hvd_tf._group_average_gradients(
            list(grads), "DistributedOptimizer.grad")
    # Non-TF backends hold eager values: enqueue every gradient, then wait.
    handles = [None if g is None else
               _common.allreduce_async(
                   _common._as_contig(keras.ops.convert_to_numpy(g)),
                   average=True, name=f"DistributedOptimizer.grad.{i}")
               for i, g in enumerate(grads)]
    return [None if h is None else keras.ops.convert_to_tensor(h.wait())
            for h in handles]


class _DistributedKerasOptimizer:
    """Method set grafted onto the wrapped optimizer's class."""

    def apply_gradients(self, grads_and_vars, *args, **kwargs):
        pairs = list(grads_and_vars)
        grads = _average_gradients([g for g, _ in pairs])
        return super(self.__class__, self).apply_gradients(
            [(g, v) for g, (_, v) in zip(grads, pairs)], *args, **kwargs)


def _wrap_optimizer_class(cls):
    methods = {k: v for k, v in _DistributedKerasOptimizer.__dict__.items()
               if k not in ("__dict__", "__weakref__")}
    return type(cls.__name__, (cls,), methods)


def DistributedOptimizer(optimizer: keras.optimizers.Optimizer):
    """Wrap a Keras optimizer so gradients are allreduce-averaged across
    workers before being applied."""
    cls = _wrap_optimizer_class(optimizer.__class__)
    return cls.from_config(optimizer.get_config())


def _stock_optimizer_classes():
    out = []
    for name in dir(keras.optimizers):
        obj = getattr(keras.optimizers, name)
        if isinstance(obj, type) and issubclass(obj, keras.optimizers.Optimizer) \
                and obj is not keras.optimizers.Optimizer:
            out.append(obj)
    return out


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compile: bool = True):
    """Load a saved model with every stock (or listed custom) optimizer
    class re-wrapped in DistributedOptimizer."""
    objects = {cls.__name__: _wrap_optimizer_class(cls)
               for cls in _stock_optimizer_classes()}
    for cls in (custom_optimizers or []):
        objects[cls.__name__] = _wrap_optimizer_class(cls)
    objects.update(custom_objects or {})
    return keras.models.load_model(filepath, custom_objects=objects,
                                   compile=compile)


def _value_collective(fn, value, **kw):
    return fn(_common._as_contig(np.asarray(value)), **kw)


def allreduce(value, average: bool = True, name: Optional[str] = None):
    """Allreduce on eager values/arrays (the reference's session-based
    helper, /root/reference/horovod/keras/__init__.py:104-123)."""
    return _value_collective(_common.allreduce, value, average=average,
                             name=name)


def allgather(value, name: Optional[str] = None):
    return _value_collective(_common.allgather, value, name=name)


def broadcast(value, root_rank: int, name: Optional[str] = None):
    return _value_collective(_common.broadcast, value, root_rank=root_rank,
                             name=name)


def broadcast_global_variables(root_rank: int = 0, model=None) -> None:
    """Broadcast a model's (and its optimizer's) variables from root."""
    if model is None:
        raise ValueError("Keras 3 has no global-variable registry; pass "
                         "model= (or use BroadcastGlobalVariablesCallback)")
    variables = list(model.weights)
    opt = getattr(model, "optimizer", None)
    if opt is not None:
        # Build slot variables BEFORE collecting: on resume-from-
        # checkpoint the root's loaded optimizer (hvd.load_model) already
        # has momentum slots while fresh ranks would lazily build them on
        # the first batch — a divergent variable set deadlocks the
        # broadcast group (caught by tests/test_examples.py's keras
        # resume leg).
        if not getattr(opt, "built", True):
            opt.build(model.trainable_variables)
        variables += list(opt.variables)
    # Enqueue all broadcasts, then wait: the set fuses into few engine
    # cycles instead of paying one negotiation cycle per variable.
    arrays = [np.asarray(keras.ops.convert_to_numpy(var))
              for var in variables]
    handles = [_common.broadcast_async(arr, root_rank,
                                       name=f"broadcast_model.{i}")
               for i, arr in enumerate(arrays)]
    for var, arr, handle in zip(variables, arrays, handles):
        var.assign(np.asarray(handle.wait()).reshape(arr.shape))
