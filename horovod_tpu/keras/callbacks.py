"""Keras training callbacks.

Counterparts of /root/reference/horovod/keras/callbacks.py:
`BroadcastGlobalVariablesCallback` (rank-0 state replication at train
start), `MetricAverageCallback` (epoch-end cross-worker metric averaging),
`LearningRateScheduleCallback` (epoch/batch-granular LR multiplier with
momentum correction), and `LearningRateWarmupCallback` (the Goyal et al.
linear warmup ``lr/size → lr``, reference lines 202-259).
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import keras
import numpy as np

import horovod_tpu.common as _common


def _latest_weights_file(directory: str) -> Optional[str]:
    """Newest ``*.weights.h5`` under ``directory`` by checkpoint number
    (``ckpt-<n>.weights.h5``, falling back to mtime for other names)."""
    import os
    import re

    try:
        names = [n for n in os.listdir(directory)
                 if n.endswith(".weights.h5")]
    except OSError:
        return None
    if not names:
        return None

    def key(name: str):
        m = re.match(r"ckpt-(\d+)", name)
        if m:
            return (1, int(m.group(1)))
        return (0, os.path.getmtime(os.path.join(directory, name)))

    return os.path.join(directory, max(names, key=key))


def _latest_resume_source(directory: str):
    """``(kind, path)`` of the newest resumable checkpoint under
    ``directory``: a keras ``*.weights.h5`` (``"weights_h5"``) or a
    ``jax.train.save_checkpoint`` artifact (``"checkpoint"`` — legacy
    pickle or committed sharded directory; torn sharded directories are
    invisible).  When both formats exist and both carry a ``ckpt-<n>``
    step, the higher step wins (ties go to the keras-native weights
    file); without comparable steps (e.g. a fixed-name
    ``final.weights.h5``), newer mtime wins — a stale jax artifact must
    never outrank the weights file ModelCheckpoint just wrote."""
    import os
    import re

    from horovod_tpu.state import checkpoint as _ckpt

    h5 = _latest_weights_file(directory)
    entries = _ckpt.scan_checkpoints(directory)
    if not entries:
        return ("weights_h5", h5) if h5 else (None, None)
    ck_step, ck_path, _ = entries[-1]
    if h5 is None:
        return "checkpoint", ck_path
    m = re.match(r"ckpt-(\d+)", os.path.basename(h5))
    if m:
        return (("checkpoint", ck_path) if ck_step > int(m.group(1))
                else ("weights_h5", h5))
    try:
        newer_ck = os.path.getmtime(ck_path) > os.path.getmtime(h5)
    except OSError:
        newer_ck = False
    return ("checkpoint", ck_path) if newer_ck else ("weights_h5", h5)


def _weights_list(tree) -> Optional[list]:
    """The flat weight list a checkpoint tree carries, in
    ``model.set_weights`` order — a list/tuple of arrays (the
    ``model.get_weights()`` shape), or a dict holding one under
    ``"weights"``.  None when the tree is some other pytree (full jax
    train state), which weights-only resume cannot consume."""
    if isinstance(tree, dict) and "weights" in tree:
        tree = tree["weights"]
    if isinstance(tree, (list, tuple)) and tree and all(
            hasattr(w, "shape") for w in tree):
        return [np.asarray(w) for w in tree]
    return None


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcast model + optimizer state from ``root_rank`` once, at the
    start of training (reference lines 8-34).

    ``checkpoint_dir`` adds the job-level-restart glue
    (docs/fault-tolerance.md): on a relaunched run (``hvdrun
    --max-restarts``, detected via ``HVD_TPU_RESTART_EPOCH``), the root
    rank reloads the newest checkpoint in that directory before
    broadcasting, so every rank resumes from the last checkpoint instead
    of reinitialized weights.  Pair it with a
    ``keras.callbacks.ModelCheckpoint`` writing into the same directory.
    Besides ``*.weights.h5``, the resume path reads
    ``jax.train.save_checkpoint`` artifacts — the legacy pickle AND the
    sharded ``ckpt-<step>/`` format (docs/fault-tolerance.md
    #state-plane) — when the tree is a flat ``model.get_weights()`` list
    (or a dict with a ``"weights"`` entry), so an elastic job that saved
    sharded checkpoints and fell below ``--min-np`` resumes through
    ``--max-restarts`` too.

    Scope: this resumes **weights only** — the optimizer (iteration
    counter, momentum/slot variables) restarts fresh, so LR schedules
    keyed on ``optimizer.iterations`` begin again at step 0.  For full
    training-state resume, checkpoint whole models (``.keras``) and
    reload via ``hvd.load_model`` before ``fit`` — the
    ``examples/keras_imagenet_resnet50.py`` pattern.
    """

    def __init__(self, root_rank: int = 0,
                 checkpoint_dir: Optional[str] = None):
        super().__init__()
        self.root_rank = root_rank
        self.checkpoint_dir = checkpoint_dir
        self.broadcast_done = False
        self.resumed_from: Optional[str] = None
        self._membership_epoch = 0

    def on_train_begin(self, logs=None):  # noqa: D401
        # Elastic re-entry (docs/fault-tolerance.md#elastic-membership):
        # when fit() is called again after a reshape killed the previous
        # one ("catch MembershipChangedError and call fit again"), the
        # engine's enqueue poison is still armed — ack it BEFORE any
        # broadcast below, and re-broadcast even if an earlier fit already
        # did (the survivors' weights diverged from the cancelled batch).
        epoch = _common.membership_epoch()
        if epoch != self._membership_epoch:
            self._membership_epoch = epoch
            _common.membership_ack()
            self.broadcast_done = False
        if self.broadcast_done:
            return
        from horovod_tpu.keras import broadcast_global_variables

        if (self.checkpoint_dir and _common.restart_epoch() > 0
                and _common.rank() == self.root_rank):
            kind, latest = _latest_resume_source(self.checkpoint_dir)
            if kind == "weights_h5":
                # Root-only load; the broadcast below replicates it, so
                # ranks whose local filesystem lacks the checkpoint (or
                # holds a stale one) still resume consistently.
                self.model.load_weights(latest)
                self.resumed_from = latest
            elif kind == "checkpoint":
                # A jax.train.save_checkpoint artifact — the format an
                # elastic job's sharded saves leave when it falls below
                # --min-np and --max-restarts relaunches.  Root-only, so
                # the sharded read must assemble locally
                # (collective=False), never enqueue broadcasts the other
                # ranks are not making.
                from horovod_tpu.jax.train import load_checkpoint

                weights, problem = None, None
                try:
                    _, tree = load_checkpoint(latest, collective=False)
                    weights = _weights_list(tree)
                    if weights is None:
                        problem = ("does not carry a flat weight list "
                                   "(checkpoint model.get_weights(), or "
                                   "a dict with a 'weights' entry)")
                except Exception as exc:  # torn/corrupt artifact
                    problem = f"is unreadable ({exc})"
                if weights is not None:
                    self.model.set_weights(weights)
                    self.resumed_from = latest
                else:
                    # An unusable artifact must not cost the resume a
                    # usable (if older) .weights.h5 sitting next to it,
                    # nor crash the relaunch whose whole purpose is
                    # crash recovery — the pre-sharded-format behavior.
                    import warnings

                    h5 = _latest_weights_file(self.checkpoint_dir)
                    if h5 is not None:
                        self.model.load_weights(h5)
                        self.resumed_from = h5
                    warnings.warn(
                        f"checkpoint {latest} {problem}; "
                        + (f"resumed from older {h5} instead"
                           if h5 else "weights-only resume skipped"))
            if self.resumed_from is not None:
                print(f"[horovod_tpu] restart epoch "
                      f"{_common.restart_epoch()}: resumed weights from "
                      f"{self.resumed_from}")
        broadcast_global_variables(self.root_rank, model=self.model)
        self.broadcast_done = True

    def on_train_batch_begin(self, batch, logs=None):
        # Elastic membership (docs/fault-tolerance.md#elastic-membership):
        # after a reshape, re-broadcast from the root so every member
        # trains on identical weights.  This fully covers GROW barriers
        # (quiesced ticks, i.e. between batches — the admitted standby
        # gets the live weights) and re-entry after a shrink; a shrink
        # that cancels an in-flight batch still raises the retryable
        # MembershipChangedError out of fit() — catch it and call fit
        # again, or drive the loop with hvd.run_elastic.  One cheap
        # engine call per batch when nothing changed.
        if not _common.is_initialized():
            return
        epoch = _common.membership_epoch()
        if epoch != self._membership_epoch:
            self._membership_epoch = epoch
            from horovod_tpu.keras import broadcast_global_variables

            _common.membership_ack()
            broadcast_global_variables(self.root_rank, model=self.model)


class MetricAverageCallback(keras.callbacks.Callback):
    """Average epoch-end metrics (e.g. validation scores computed on each
    worker's shard) over all workers (reference lines 37-87)."""

    def on_epoch_end(self, epoch, logs=None):
        if logs is None or _common.size() == 1:
            return
        for key in sorted(logs):
            value = logs[key]
            if isinstance(value, (int, float, np.floating, np.integer)):
                out = _common.allreduce(
                    np.asarray(float(value)), average=True,
                    name=f"MetricAverageCallback.{key}")
                logs[key] = float(out)


class MetricsLoggingCallback(keras.callbacks.Callback):
    """Per-epoch collective-layer summary from the metrics registry
    (docs/metrics.md): ops enqueued, bytes moved, fused batches, and stall
    events since the previous epoch, printed on ``root_rank`` only.  A
    no-op unless metrics are enabled (``HVD_TPU_METRICS=1``, a metrics
    file, or a monitor port) — except for stall events, which the registry
    records unconditionally."""

    def __init__(self, root_rank: int = 0,
                 log_fn: Optional[Callable[[str], None]] = None):
        super().__init__()
        self.root_rank = root_rank
        self._log = log_fn or print
        self._last: Optional[dict] = None

    @staticmethod
    def _totals(snap: dict) -> dict:
        return {
            "ops": {p: sum(v.values()) for p, v in snap["ops"].items()},
            "bytes_in": sum(v["in"] for v in snap["bytes"].values()),
            "bytes_out": sum(v["out"] for v in snap["bytes"].values()),
            "batches": snap["batches"]["dispatched"],
            "stalls": snap["stalls"]["count"],
        }

    def on_epoch_end(self, epoch, logs=None):
        snap = _common.metrics_snapshot()
        if not (snap["enabled"] or snap["stalls"]["count"]):
            return
        cur = self._totals(snap)
        prev = self._last or {"ops": {p: 0 for p in cur["ops"]},
                              "bytes_in": 0, "bytes_out": 0,
                              "batches": 0, "stalls": 0}
        self._last = cur
        if _common.is_initialized() and _common.rank() != self.root_rank:
            return
        ops = " ".join(f"{p}={cur['ops'][p] - prev['ops'][p]}"
                       for p in cur["ops"])
        self._log(
            f"[hvd-metrics] epoch {epoch + 1}: ops {ops}, "
            f"bytes in/out {cur['bytes_in'] - prev['bytes_in']}/"
            f"{cur['bytes_out'] - prev['bytes_out']}, "
            f"batches {cur['batches'] - prev['batches']}, "
            f"stalls {cur['stalls'] - prev['stalls']}")


class TimelineCallback(keras.callbacks.Callback):
    """Epoch and (optionally) step spans into this rank's timeline
    (docs/timeline.md): a ``keras.epoch`` trace row with one span per
    epoch and — with ``steps=True`` — a ``keras.step`` row with one span
    per train batch, so collective rows line up against the training loop
    that issued them.  Every hook is a no-op when the timeline is disabled
    (``HOROVOD_TIMELINE`` unset), so the callback can stay wired in
    production configs."""

    def __init__(self, steps: bool = True):
        super().__init__()
        self.steps = steps

    def on_epoch_begin(self, epoch, logs=None):
        _common._trace_begin("keras.epoch", f"EPOCH_{epoch}")

    def on_epoch_end(self, epoch, logs=None):
        _common._trace_end("keras.epoch")

    def on_train_batch_begin(self, batch, logs=None):
        if self.steps:
            _common._trace_begin("keras.step", "STEP")

    def on_train_batch_end(self, batch, logs=None):
        if self.steps:
            _common._trace_end("keras.step")


class LearningRateScheduleCallback(keras.callbacks.Callback):
    """Multiply the initial LR by ``multiplier`` (a constant or a function
    of epoch).  ``staircase=True`` applies at epoch granularity; otherwise
    per batch using fractional epochs (requires ``steps_per_epoch`` or an
    inferable one).  When the LR changes and the optimizer carries momentum
    buffers, they are rescaled by ``old_lr/new_lr`` so the effective update
    velocity ``lr * m`` stays continuous across the change — the momentum
    correction of Goyal et al. the reference applies (lines 90-199)."""

    def __init__(self, multiplier: Union[float, Callable[[float], float]],
                 start_epoch: int = 0, end_epoch: Optional[int] = None,
                 staircase: bool = True, momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None,
                 initial_lr: Optional[float] = None):
        super().__init__()
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.initial_lr = initial_lr
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        if callable(multiplier):
            self.multiplier = multiplier
        else:
            self.multiplier = lambda epoch: multiplier

    # -- helpers ----------------------------------------------------------

    def _lr(self) -> float:
        return float(keras.ops.convert_to_numpy(
            self.model.optimizer.learning_rate))

    def _set_lr(self, lr: float) -> None:
        opt = self.model.optimizer
        old = self._lr()
        if old == lr:
            return
        opt.learning_rate = lr
        if self.momentum_correction and lr != 0:
            momentums = getattr(opt, "momentums", None)
            if momentums:
                scale = old / lr
                for buf in momentums:
                    buf.assign(buf * scale)

    def _in_window(self, epoch: float) -> bool:
        if epoch < self.start_epoch:
            return False
        if self.end_epoch is None:
            return True
        # Continuous schedules include the window's right edge so e.g. a
        # warmup's final batch lands exactly on the full multiplier.
        return epoch < self.end_epoch if self.staircase \
            else epoch <= self.end_epoch

    def _apply(self, epoch: float) -> None:
        if self._in_window(epoch):
            self._set_lr(self.initial_lr * self.multiplier(epoch))

    # -- keras hooks ------------------------------------------------------

    def on_train_begin(self, logs=None):
        if self.initial_lr is None:
            self.initial_lr = self._lr()
        if not self.staircase and self.steps_per_epoch is None:
            self.steps_per_epoch = (self.params or {}).get("steps")
            if self.steps_per_epoch is None:
                raise ValueError(
                    "steps_per_epoch is required for batch-granular "
                    "(staircase=False) LR schedules")

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase:
            self._apply(epoch)

    def on_train_batch_begin(self, batch, logs=None):
        if not self.staircase:
            # batch+1 so the final warmup batch reaches the full multiplier.
            self._apply(self.current_epoch +
                        (batch + 1) / self.steps_per_epoch)

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = self._lr()


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Linear warmup from ``initial_lr / size`` to ``initial_lr`` over the
    first ``warmup_epochs`` epochs, batch-granular (reference lines
    202-259: the large-batch recipe of Goyal et al., arXiv:1706.02677)."""

    def __init__(self, warmup_epochs: int = 5,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None, verbose: int = 0,
                 initial_lr: Optional[float] = None):
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose
        n = max(_common.size(), 1) if _common.is_initialized() else 1

        def multiplier(epoch: float) -> float:
            progress = min(epoch / warmup_epochs, 1.0) if warmup_epochs else 1.0
            return 1.0 / n + progress * (1.0 - 1.0 / n)

        super().__init__(multiplier, start_epoch=0, end_epoch=warmup_epochs,
                         staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch,
                         initial_lr=initial_lr)

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if self.verbose and epoch == self.warmup_epochs - 1 \
                and _common.rank() == 0:
            print(f"Epoch {epoch + 1}: finished gradual learning rate "
                  f"warmup to {self._lr():.6g}.")
