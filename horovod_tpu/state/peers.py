"""Peer-replicated snapshot mirror: each rank pushes its latest shard
snapshot to its ring neighbor (``rank+1 mod size``) over a dedicated
state-plane socket.

The Gemini shape (PAPERS.md): checkpoint redundancy lives in PEER host
memory, not (only) on shared storage, so losing a rank costs one
O(model/size) transfer from the survivor holding the copy instead of an
O(model) root broadcast.  The engine's collectives cannot express a
point-to-point send (allreduce/allgather/broadcast are all-rank), so the
mirror runs its own tiny framed-TCP hop: one listener per rank, one
connect-push-close per snapshot.  Endpoints are exchanged through a named
allgather at arm/re-arm time, so the mirror follows membership reshapes.

Torn pushes cannot poison the store: a frame is length-prefixed and the
receiver installs it only after every byte arrived and unpickled — a rank
crashing mid-push (exactly the case this plane exists for) leaves the
neighbor's previous copy intact.  Scope matches elastic membership:
single-host jobs (the launcher rejects ``--hosts`` + elastic), so the
listener binds ``HVD_TPU_STATE_BIND`` (default 127.0.0.1).

TRUST BOUNDARY: frames are pickled and the listener is unauthenticated
— the same trust model as the engine's own cleartext TCP control/data
planes, which accept raw frames from anyone who can connect.  Unpickling
attacker bytes is code execution, so ``HVD_TPU_STATE_BIND`` must never
expose the port beyond the loopback/cluster network the engine already
trusts (docs/fault-tolerance.md#state-plane).
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
from typing import Optional

from horovod_tpu.common import metrics as _metrics

_MAGIC = b"HVDSTAT1"
# magic + (src_rank, src_size, step, sig, nbytes) little-endian int64s;
# sig is the sender's state-shape signature (plane._state_signature) —
# restore only trusts a copy cut under the receiver's current shape.
_HEADER = struct.Struct("<8sqqqqq")
# A shard frame is bounded by the model size; 16 GiB is far past any
# single-rank shard this plane will ever carry and keeps a corrupt
# header from triggering a giant allocation.
_MAX_FRAME = 16 << 30


def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None  # torn push: sender died mid-frame
        buf.extend(chunk)
    return bytes(buf)


class PeerMirror:
    """Listener + latest-copy store for one rank's incoming peer shard."""

    def __init__(self, bind_host: Optional[str] = None):
        self._host = (bind_host
                      or os.environ.get("HVD_TPU_STATE_BIND")
                      or "127.0.0.1")
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((self._host, 0))
        self._server.listen(4)
        self._lock = threading.Lock()
        self._latest: Optional[dict] = None
        self._closed = False
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True,
                                        name="hvd-tpu-state-peer")
        self._thread.start()

    @property
    def endpoint(self) -> str:
        return f"{self._host}:{self._server.getsockname()[1]}"

    # -- receive ----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return  # listener closed
            try:
                self._receive(conn)
            except Exception:
                pass  # a malformed push must never kill the listener
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def _receive(self, conn: socket.socket) -> None:
        conn.settimeout(30.0)
        raw = _recv_exact(conn, _HEADER.size)
        if raw is None:
            return
        magic, src_rank, src_size, step, sig, nbytes = _HEADER.unpack(raw)
        if magic != _MAGIC or not 0 <= nbytes <= _MAX_FRAME:
            return
        payload = _recv_exact(conn, nbytes)
        if payload is None:
            return  # torn mid-payload: keep the previous intact copy
        leaves = pickle.loads(payload)
        with self._lock:
            self._latest = {"src": int(src_rank), "size": int(src_size),
                            "step": int(step), "sig": int(sig),
                            "leaves": leaves}
        _metrics.registry.record_state_peer(received_step=int(step))

    # -- send -------------------------------------------------------------

    @staticmethod
    def push(endpoint: str, src_rank: int, src_size: int, step: int,
             leaves: dict, sig: int = 0, timeout: float = 30.0) -> bool:
        """Push one shard snapshot to a neighbor's mirror; False (never a
        raise) when the neighbor is unreachable — a dead peer is the
        normal case this plane tolerates."""
        host, _, port = endpoint.rpartition(":")
        try:
            payload = pickle.dumps(leaves, protocol=pickle.HIGHEST_PROTOCOL)
            with socket.create_connection((host, int(port)),
                                          timeout=timeout) as conn:
                conn.sendall(_HEADER.pack(_MAGIC, src_rank, src_size, step,
                                          sig, len(payload)))
                conn.sendall(payload)
            _metrics.registry.record_state_peer(sent_bytes=len(payload))
            return True
        except (OSError, ValueError):
            return False

    # -- reading ----------------------------------------------------------

    def latest(self) -> Optional[dict]:
        """The newest fully-received peer copy:
        ``{"src", "size", "step", "leaves"}`` or None."""
        with self._lock:
            return self._latest

    def clear(self) -> None:
        """Drop the held copy (its partition died with the old
        membership)."""
        with self._lock:
            self._latest = None

    def close(self) -> None:
        self._closed = True
        try:
            self._server.close()
        except OSError:
            pass
