"""State plane: async sharded checkpoints + peer-replicated shards for
instant elastic recovery (docs/fault-tolerance.md#state-plane).

Three pieces over one partition contract (``partition.py``: leaf ``i`` of
the flattened state belongs to rank ``i % size``):

* async shard **snapshots** — a double-buffered device→host capture off
  the step path, serialization/spill/mirror overlapped with compute
  (``snapshot.py``);
* **sharded durable checkpoints** — ``ckpt-<step>/rank-N.pkl`` + an
  atomically committed rank-0 manifest, O(model/size) per rank instead
  of O(model) on rank 0 (``checkpoint.py``; surfaced through
  ``horovod_tpu.jax.train.save_checkpoint(..., sharded=True)``);
* **peer-replicated redundancy** — every committed snapshot mirrors to
  the ring neighbor, so an elastic reshape restores lost shards from
  surviving peer copies instead of a full root broadcast (``peers.py``,
  ``plane.py``; ``hvd.run_elastic`` routes through the armed plane).

Usage::

    hvd.init()
    plane = hvd.state.arm()            # every rank, same program point
    state = hvd.ElasticState(weights=w, step=0)

    def train(state):
        while state.step < TOTAL:
            ...collectives...
            state.step += 1
            plane.snapshot(state)      # async; ~free on the step path
        return state.weights

    hvd.run_elastic(train, state)      # reshapes restore via the plane
"""

from __future__ import annotations

import threading
from typing import Optional

from horovod_tpu.state.partition import (  # noqa: F401
    flatten_state,
    flatten_tree,
    owner,
    shard_indices,
)
from horovod_tpu.state.plane import StatePlane  # noqa: F401

_armed_lock = threading.Lock()
_armed: Optional[StatePlane] = None


def arm(state_dir: Optional[str] = None) -> StatePlane:
    """Arm the process-wide state plane (idempotent: re-arming returns
    the live plane).  Call on EVERY rank at the same program point, after
    ``hvd.init()``; ``hvd.run_elastic`` picks the armed plane up
    automatically.  ``state_dir`` (default ``HVD_TPU_STATE_DIR``) adds
    the on-disk snapshot spill."""
    global _armed
    with _armed_lock:
        if _armed is None:
            _armed = StatePlane(state_dir=state_dir)
        elif state_dir and _armed._state_dir != state_dir:
            import warnings

            # Re-arming cannot move the spill dir mid-lifetime (the live
            # worker holds the old one); a silently ignored request would
            # leave the operator staring at an empty directory.
            warnings.warn(
                f"state plane already armed with state_dir="
                f"{_armed._state_dir!r}; ignoring new state_dir="
                f"{state_dir!r} (disarm first to change it)")
        return _armed


def current() -> Optional[StatePlane]:
    """The armed plane, or None (``run_elastic``'s routing hook)."""
    with _armed_lock:
        return _armed


def disarm() -> None:
    """Close and forget the armed plane (tests; shutdown paths)."""
    global _armed
    with _armed_lock:
        plane, _armed = _armed, None
    if plane is not None:
        plane.close()
