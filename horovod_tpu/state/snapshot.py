"""Async shard snapshots: double-buffered device→host capture with the
serialization + write overlapped with subsequent compute.

The CheckFreq shape (PAPERS.md): the only work on the training-step path
is the **capture** — a host copy of this rank's owned leaves, O(model/size)
— plus, at most, a bounded wait for the PREVIOUS snapshot to clear the
single background slot (the double buffer: one snapshot serializing in the
background while the next captures).  Pickling, the optional disk spill
(``HVD_TPU_STATE_DIR``), and the peer-mirror push all run on the worker
thread, overlapped with compute.

The epoch fence: a snapshot becomes **committed** — visible to
:meth:`committed_steps` / :meth:`get`, eligible for peer restore — only
after the worker finished every byte of it, and the capture is a private
copy, so a torn snapshot is never committable and later training-step
mutation cannot reach captured state.  The last TWO committed snapshots
are retained: the peer copy of step ``s`` may still be in flight to the
neighbor when ``s+1`` commits locally, so restore needs ``s`` available on
both sides to find a common fence step.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from horovod_tpu.common import metrics as _metrics

# Committed snapshots retained per rank.  Two, not one: the neighbor's
# copy of the newest snapshot may lag one push, and restore needs one
# step that EVERY shard (own copies and peer copies alike) can serve.
SNAPSHOT_KEEP = 2


class ShardSnapshotter:
    """Background serializer for one rank's shard snapshots.

    ``submit(step, leaves)`` captures nothing itself — the caller passes
    already-copied host arrays — and blocks only while the single
    background slot is busy (the fence half of the double buffer).
    ``writer`` is invoked on the worker thread with
    ``(step, leaves, payload_nbytes)`` after the snapshot committed
    locally (the plane uses it for the disk spill + peer push).
    """

    def __init__(self, writer: Optional[Callable[[int, dict, int], None]]
                 = None):
        self._writer = writer
        self._queue: "queue.Queue" = queue.Queue(maxsize=1)
        self._committed: List[dict] = []  # [{"step", "leaves", "nbytes"}]
        self._lock = threading.Lock()
        # Outstanding = submitted but not yet committed/abandoned; the
        # exact idle predicate (an emptiness+event pair would race the
        # window between the worker's queue.get() and its first action).
        self._outstanding = 0
        # Bumped by clear(): a snapshot submitted under an older
        # generation must never commit after the clear — it was cut
        # under a partition the membership change just invalidated.
        self._generation = 0
        self._closed = False
        self.blocked_sec = 0.0   # step-path time spent waiting on the slot
        self.async_sec = 0.0     # worker time overlapped with compute
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="hvd-tpu-state-snapshot")
        self._thread.start()

    # -- step path --------------------------------------------------------

    def submit(self, step: int, leaves: Dict[int, np.ndarray]) -> None:
        """Hand one captured shard to the background worker.  Blocks only
        while the previous snapshot still occupies the slot."""
        if self._closed:
            raise RuntimeError("snapshotter is closed")
        t0 = time.perf_counter()
        with self._lock:
            self._outstanding += 1
            gen = self._generation
        self._queue.put({"step": int(step), "leaves": leaves, "gen": gen})
        self.blocked_sec += time.perf_counter() - t0

    def wait(self, timeout: float = 30.0) -> bool:
        """Drain the background slot (tests, shutdown, restore entry):
        True when every submitted snapshot committed (or was abandoned)
        within ``timeout``."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                if self._outstanding == 0:
                    return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.002)

    # -- worker -----------------------------------------------------------

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            t0 = time.perf_counter()
            try:
                nbytes = sum(int(a.nbytes) for a in item["leaves"].values())
                entry = {"step": item["step"], "leaves": item["leaves"],
                         "nbytes": nbytes}
                if self._writer is not None:
                    try:
                        self._writer(entry["step"], entry["leaves"], nbytes)
                    except Exception as exc:  # never kill the worker
                        import warnings

                        warnings.warn(
                            f"state snapshot writer failed at step "
                            f"{entry['step']}: {exc}")
                # Commit LAST: the snapshot becomes visible (and restore-
                # eligible) only after spill + peer push finished — the
                # epoch fence.  A failed writer still commits: the local
                # arrays are whole regardless of mirror reachability.  A
                # snapshot from a PRE-clear() generation is abandoned —
                # its partition died with the old membership, and a late
                # commit here would poison the next restore plan.
                with self._lock:
                    if item["gen"] == self._generation:
                        self._committed = (
                            [e for e in self._committed
                             if e["step"] != entry["step"]] + [entry]
                        )[-SNAPSHOT_KEEP:]
                        committed = True
                    else:
                        committed = False
                dt = time.perf_counter() - t0
                self.async_sec += dt
                if committed:
                    _metrics.registry.record_state_snapshot(
                        entry["step"], nbytes)
                    _metrics.registry.observe("state_snapshot_sec", dt)
            finally:
                with self._lock:
                    self._outstanding -= 1
                self._queue.task_done()

    # -- reading ----------------------------------------------------------

    def committed_steps(self) -> List[int]:
        """Steps of the committed snapshots, oldest first."""
        with self._lock:
            return [e["step"] for e in self._committed]

    def get(self, step: int) -> Optional[Dict[int, np.ndarray]]:
        with self._lock:
            for entry in self._committed:
                if entry["step"] == step:
                    return entry["leaves"]
        return None

    def clear(self) -> None:
        """Drop every committed snapshot AND abandon in-flight ones (a
        reshape invalidates the partition they were all cut under — a
        submit that commits after this call would otherwise resurface a
        stale-partition snapshot in the next restore plan)."""
        with self._lock:
            self._committed = []
            self._generation += 1

    def overlap_ratio(self) -> float:
        total = self.async_sec + self.blocked_sec
        return self.async_sec / total if total > 0 else 1.0

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._queue.put(None)
        self._thread.join(timeout=5.0)
