"""The state plane: async shard snapshots + peer-replicated restore.

One object per rank (``horovod_tpu.state.arm()``), three duties:

* **snapshot** (:meth:`StatePlane.snapshot`): capture this rank's owned
  1/size shard of the flattened training state (partition.py) as a
  private host copy, then serialize / spill / mirror it in the
  background (snapshot.py) — the step path pays the O(model/size) copy
  and, at most, one double-buffer fence.
* **mirror**: the background writer pushes every committed snapshot to
  the ring neighbor ``rank+1 mod size`` (peers.py), so each shard exists
  on two hosts.
* **restore** (:meth:`StatePlane.restore`): after an elastic reshape,
  survivors agree on a fence step every shard can serve (own snapshots
  or peer copies), then each shard's designated holder broadcasts it —
  O(model/size) per NIC instead of PR 6's O(model) root broadcast.  A
  rank lost together with its mirror (neighbor pairs dying at once), a
  membership that never snapshotted, or a state-shape mismatch all fall
  back to the classic root broadcast; ``run_elastic`` handles both ends.

The restore *decision* is collective: every rank allgathers its holdings
(`__state.plan.<epoch>`) and computes the same verdict from the same
table, so no rank can locally shortcut into a deadlock.  Restore rolls
state back to the fence step — the re-enterable ``train_fn`` recomputes
the (at most ``SNAPSHOT_KEEP``) steps since, which is the CheckFreq /
Gemini trade: a bounded recompute instead of an O(model) stop-the-world
transfer.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Dict, List, Optional

import numpy as np

from horovod_tpu.common import metrics as _metrics
from horovod_tpu.state import partition
from horovod_tpu.state.peers import PeerMirror
from horovod_tpu.state.snapshot import ShardSnapshotter

_ENDPOINT_BYTES = 64


def _state_signature(named) -> int:
    """Stable 63-bit digest of the flattened state's SHAPE — leaf names,
    array shapes, dtypes — identical across ranks running the same SPMD
    program (Python ``hash()`` is salt-randomized per process, so it
    cannot cross rank boundaries).  Restore only trusts snapshots and
    peer copies cut under the current signature: a shape change between
    capture and restore must fall back to the root broadcast, never tear
    a fixed-shape shard broadcast mid-resync."""
    import hashlib

    h = hashlib.blake2s(digest_size=8)
    for name, leaf in named:
        arr_like = np.asarray(leaf) if not hasattr(leaf, "shape") else leaf
        h.update(f"{name}|{tuple(arr_like.shape)}|"
                 f"{np.dtype(arr_like.dtype).name};".encode())
    return int.from_bytes(h.digest(), "little") & ((1 << 63) - 1)


def _private_host_copy(leaf) -> np.ndarray:
    """One host copy, guaranteed private: numpy leaves (and any view
    aliasing caller memory) are copied; an ``__array__``-produced buffer
    that already owns its data (the jax device->host materialization) is
    used as-is — the capture pays exactly ONE O(leaf) pass."""
    arr = np.asarray(leaf)
    if arr is leaf or arr.base is not None or not arr.flags["OWNDATA"]:
        arr = arr.copy()
    return arr


class StatePlane:
    """Per-rank driver of the state plane.  Arm on EVERY rank at the same
    program point (the restore collectives are symmetric); one plane per
    engine lifetime."""

    def __init__(self, state_dir: Optional[str] = None):
        from horovod_tpu import common as _common

        if not _common.is_initialized():
            raise ValueError("arm the state plane after hvd.init()")
        self._rank = _common.rank()
        self._size = _common.size()
        self._state_dir = (state_dir
                           or os.environ.get("HVD_TPU_STATE_DIR") or "")
        if self._state_dir:
            os.makedirs(self._state_dir, exist_ok=True)
        self._mirror = PeerMirror()
        self._neighbor: Optional[str] = None  # set by the peer exchange
        self._snapshotter = ShardSnapshotter(writer=self._background_write)
        self._ever_snapshotted = False
        # step -> state-shape signature at capture time (bounded; restore
        # only advertises steps whose signature matches the live state).
        self._sig_by_step: dict = {}
        self._closed = False
        _metrics.registry.set_state_armed(True)

    # -- snapshot (step path) ---------------------------------------------

    def snapshot(self, state, step: Optional[int] = None) -> int:
        """Snapshot this rank's shard of ``state`` (an ``ElasticState``).
        Returns the snapshot step (default: ``state.step``).  The call
        captures a private host copy and hands it to the background
        worker; it blocks only on the double-buffer fence."""
        from horovod_tpu import common as _common

        if step is None:
            step = getattr(state, "step", None)
            if step is None:
                raise ValueError(
                    "snapshot(state) needs a step: pass step= or give the "
                    "ElasticState a 'step' leaf")
        step = int(step)
        _common._trace_begin("state.snapshot", "STATE_SNAPSHOT")
        try:
            named, _ = partition.flatten_state(state)
            own = {}
            for i in partition.shard_indices(self._rank, self._size,
                                             len(named)):
                own[i] = _private_host_copy(named[i][1])
            sig = _state_signature(named)
            self._sig_by_step[step] = sig
            if len(self._sig_by_step) > 8:  # bounded; only recent steps
                for old in sorted(self._sig_by_step)[:-8]:
                    del self._sig_by_step[old]
            self._snapshotter.submit(step, own)
            self._ever_snapshotted = True
        finally:
            _common._trace_end("state.snapshot")
        from horovod_tpu.common import postmortem as _postmortem

        _postmortem.plane_ring.record("state_snapshot", f"step.{step}",
                                      step)
        return step

    def _background_write(self, step: int, leaves: dict,
                          nbytes: int) -> None:
        """Worker-thread half: disk spill (``HVD_TPU_STATE_DIR``) then the
        peer push — both overlapped with compute."""
        if self._state_dir:
            from horovod_tpu.state.checkpoint import _atomic_write

            doc = {"format": "hvd-tpu-snap-v1", "step": step,
                   "rank": self._rank, "size": self._size,
                   "leaves": leaves}
            path = os.path.join(self._state_dir,
                                f"snap-rank{self._rank}.pkl")
            _atomic_write(path, lambda f: pickle.dump(
                doc, f, protocol=pickle.HIGHEST_PROTOCOL))
        if self._neighbor is not None and self._size > 1:
            PeerMirror.push(self._neighbor, self._rank, self._size, step,
                            leaves, sig=self._sig_by_step.get(step, 0))
        # Overlap gauges ride the commit (cumulative totals, idempotent).
        _metrics.registry.set_state_overlap(self._snapshotter.blocked_sec,
                                            self._snapshotter.async_sec)

    # -- peer wiring ------------------------------------------------------

    def exchange_peers(self, key: str = "arm") -> None:
        """Allgather every rank's mirror endpoint and pick this rank's
        ring neighbor.  Collective — call on every rank at the same point
        (restore() does it per epoch; call it once after arm() for
        snapshot-only jobs that never enter ``run_elastic``)."""
        from horovod_tpu import common as _common

        if _common.size() == 1:
            self._neighbor = None
            return
        endpoints = self._allgather_endpoints(key)
        self._neighbor = endpoints[(_common.rank() + 1) % _common.size()]

    def _allgather_endpoints(self, key: str) -> List[str]:
        from horovod_tpu import common as _common

        row = np.zeros((1, _ENDPOINT_BYTES), np.uint8)
        enc = self._mirror.endpoint.encode()
        if len(enc) > _ENDPOINT_BYTES:
            raise ValueError(f"state endpoint too long: "
                             f"{self._mirror.endpoint!r}")
        row[0, :len(enc)] = np.frombuffer(enc, np.uint8)
        rows = _common.allgather(row, name=f"__state.peers.{key}")
        return [bytes(r).rstrip(b"\0").decode() for r in rows]

    # -- restore (reshape path) -------------------------------------------

    def restore(self, state, epoch: int) -> bool:
        """Collective restore attempt for membership ``epoch`` (call after
        ``membership_ack``, on every rank).  True: ``state`` now holds the
        fence-step snapshot, assembled from surviving shard holders —
        skip the root broadcast.  False: no covering fence step — caller
        must root-broadcast (``ElasticState.sync``)."""
        from horovod_tpu import common as _common
        from horovod_tpu.common import postmortem as _postmortem

        t0 = time.perf_counter()
        _common._trace_begin("state.restore", "STATE_RESTORE")
        try:
            ok, peer_used = self._restore_inner(state, epoch)
        finally:
            _common._trace_end("state.restore")
        if ok:
            _metrics.registry.record_state_restore(
                "peer" if peer_used else "local")
            _metrics.registry.observe("state_restore_sec",
                                      time.perf_counter() - t0)
            _postmortem.plane_ring.record(
                "state_restore", "peer" if peer_used else "local", epoch)
        return ok

    def _restore_inner(self, state, epoch: int) -> tuple:
        from horovod_tpu import common as _common

        self._snapshotter.wait(timeout=30.0)  # settle in-flight commits
        new_rank, new_size = _common.rank(), _common.size()
        named, assign = partition.flatten_state(state)
        n = len(named)
        live_sig = _state_signature(named)

        # Advertise only holdings cut under the CURRENT state shape — a
        # mismatched snapshot would tear the fixed-shape shard broadcasts
        # below; the plan's live-signature column catches cross-rank
        # divergence the same way.
        own_steps = [s for s in self._snapshotter.committed_steps()
                     if self._sig_by_step.get(s) == live_sig]
        # Pin the advertised snapshots NOW: a same-generation commit
        # landing after the plan allgather (slow peer push held the
        # worker past the settle above) may evict an advertised step
        # from the keep-2 window; holding the leaf dicts here keeps the
        # promise the plan makes regardless.
        own_map = {s: self._snapshotter.get(s) for s in own_steps}
        own_steps = [s for s in own_steps if own_map[s] is not None]
        peer = self._mirror.latest()
        if peer is not None and peer.get("sig") != live_sig:
            peer = None
        row = np.full((1, 10), -1, np.int64)
        row[0, 0] = self._rank          # rank under the OLD membership
        row[0, 1] = self._size if own_steps else -1
        if own_steps:
            row[0, 2] = own_steps[-1]
            row[0, 3] = own_steps[0] if len(own_steps) > 1 else -1
        if peer is not None:
            row[0, 4] = peer["src"]
            row[0, 5] = peer["size"]
            row[0, 6] = peer["step"]
        row[0, 7] = n
        row[0, 8] = int(self._ever_snapshotted)
        row[0, 9] = live_sig
        table = np.asarray(_common.allgather(
            row, name=f"__state.plan.{epoch}"))
        endpoints = (self._allgather_endpoints(str(epoch))
                     if new_size > 1 else [])

        plan = _plan_restore(table, n)
        anyone_snapshotted = bool(table[:, 8].any())
        if plan is None:
            # No covering fence step: adopt the new membership (stale
            # shards are useless) and let the caller root-broadcast.
            self._refresh(new_rank, new_size, endpoints)
            if anyone_snapshotted:
                _metrics.registry.record_state_restore("root_broadcast")
            return False, False

        fence_step, old_size, holders = plan
        peer_used = any(src == "peer" for _, src in holders.values())
        new_leaves: List[np.ndarray] = []
        for i in range(n):
            shard = i % old_size
            root, source = holders[shard]
            if root == new_rank:
                # `own_map`/`peer` are the copies the plan was built
                # from — re-reading the snapshotter or mirror here could
                # pick up (or lose) a late in-flight commit and tear the
                # fence the plan promised.
                leaves = (own_map[fence_step]
                          if source == "own" else peer["leaves"])
                src_arr = np.ascontiguousarray(leaves[i])
            else:
                # Shape/dtype mirror the local live leaf (the SPMD
                # replicated-state invariant); contents are overwritten,
                # so an EMPTY buffer suffices — materializing the live
                # leaf (np.asarray) would force a device->host transfer
                # of every non-owned leaf and undo the O(model/size)
                # restore cost.
                leaf = named[i][1]
                if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                    src_arr = np.empty(tuple(leaf.shape),
                                       np.dtype(leaf.dtype))
                else:  # python scalar leaf: tiny, materialize directly
                    src_arr = np.ascontiguousarray(np.asarray(leaf))
            new_leaves.append(_common.broadcast(
                src_arr, root, name=f"__state.restore.{epoch}.{i}"))
        assign(new_leaves)
        self._refresh(new_rank, new_size, endpoints)
        return True, peer_used

    def _refresh(self, rank: int, size: int,
                 endpoints: List[str]) -> None:
        """Adopt a new membership: the old partition's snapshots and peer
        copies are meaningless under the new leaf ownership."""
        self._rank, self._size = rank, size
        self._snapshotter.clear()
        self._sig_by_step.clear()
        self._mirror.clear()
        self._neighbor = (endpoints[(rank + 1) % size]
                          if size > 1 and endpoints else None)

    # -- reading / lifecycle ----------------------------------------------

    @property
    def ever_snapshotted(self) -> bool:
        return self._ever_snapshotted

    def wait(self, timeout: float = 30.0) -> bool:
        """Drain the background snapshot slot (benches, tests)."""
        return self._snapshotter.wait(timeout)

    def status(self) -> dict:
        """Compact view for postmortem dumps and tests."""
        steps = self._snapshotter.committed_steps()
        peer = self._mirror.latest()
        return {
            "rank": self._rank, "size": self._size,
            "last_snapshot_step": steps[-1] if steps else -1,
            "committed_steps": steps,
            "peer_src": peer["src"] if peer else -1,
            "peer_step": peer["step"] if peer else -1,
            "overlap_ratio": self._snapshotter.overlap_ratio(),
        }

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._snapshotter.close()
        self._mirror.close()
        _metrics.registry.set_state_armed(False)


def _plan_restore(table: np.ndarray, n_leaves: int):
    """The deterministic restore plan every rank computes from the
    allgathered plan table (rows indexed by NEW rank):
    ``(fence_step, old_size, {shard: (new_root_rank, "own"|"peer")})`` or
    None when no single step covers every old shard."""
    old_sizes = {int(r[1]) for r in table if r[1] > 0}
    peer_sizes = {int(r[5]) for r in table if r[5] > 0}
    if len(old_sizes | peer_sizes) != 1:
        return None  # nobody has state, or mixed-generation holdings
    old_size = (old_sizes | peer_sizes).pop()
    if any(int(r[7]) != n_leaves for r in table):
        return None  # the state tree changed shape across the reshape
    if len({int(r[9]) for r in table}) != 1:
        return None  # per-leaf shape/dtype signatures diverged
    # availability[shard] = {step: [(priority, new_rank, source), ...]}
    avail: Dict[int, Dict[int, list]] = {}
    for new_rank, r in enumerate(table):
        if r[1] > 0 and r[0] >= 0:
            for step in (int(r[2]), int(r[3])):
                if step >= 0:
                    avail.setdefault(int(r[0]), {}).setdefault(
                        step, []).append((0, new_rank, "own"))
        if r[4] >= 0 and r[6] >= 0:
            avail.setdefault(int(r[4]), {}).setdefault(
                int(r[6]), []).append((1, new_rank, "peer"))
    candidate_steps = sorted(
        {s for per in avail.values() for s in per}, reverse=True)
    for step in candidate_steps:
        if all(step in avail.get(shard, {}) for shard in range(old_size)):
            holders = {}
            for shard in range(old_size):
                _, root, source = min(avail[shard][step])
                holders[shard] = (root, source)
            return step, old_size, holders
    return None
