"""The state plane's partition contract: who owns which leaf.

Everything in ``horovod_tpu/state`` — async shard snapshots, sharded
durable checkpoints, peer-replicated redundancy — rests on ONE shared
fact: given a flattened state tree of ``n`` leaves and a job of ``size``
ranks, leaf ``i`` is owned by rank ``i % size``.  Round-robin by leaf
index is deterministic (no byte-size heuristics that could drift between
a writer and a reader), spreads the large trailing leaves of typical
models across ranks, and — critically — is a pure function of ``(i,
size)``, so a reader at a different world size (or none at all) can
reconstruct the exact writer-side layout from the manifest alone.

The flattening itself reuses :func:`horovod_tpu.common.elastic._tree_flatten`
(jax ``tree_util`` when importable, the deterministic pure-python walk
otherwise), so the snapshot/checkpoint leaf order is the SAME order
``ElasticState.sync`` broadcasts in — one named-leaf walk, three
consumers (docs/fault-tolerance.md#state-plane).
"""

from __future__ import annotations

from typing import Any, Callable, List, Tuple


def owner(leaf_index: int, size: int) -> int:
    """The rank owning leaf ``leaf_index`` in a ``size``-rank job."""
    if size <= 0:
        raise ValueError(f"need size >= 1, got {size}")
    return leaf_index % size


def shard_indices(rank: int, size: int, n_leaves: int) -> List[int]:
    """The leaf indices rank ``rank`` owns out of ``n_leaves``."""
    if not 0 <= rank < size:
        raise ValueError(f"need 0 <= rank ({rank}) < size ({size})")
    return list(range(rank, n_leaves, size))


def flatten_tree(tree: Any) -> Tuple[list, Callable[[list], Any]]:
    """``(leaves, rebuild)`` in the canonical state-plane order (the
    ``ElasticState.sync`` walk)."""
    from horovod_tpu.common.elastic import _tree_flatten

    return _tree_flatten(tree)


def flatten_state(state) -> Tuple[List[Tuple[str, Any]],
                                  Callable[[list], None]]:
    """Flatten an :class:`~horovod_tpu.common.elastic.ElasticState` into the
    canonical named leaf list, plus an ``assign(new_leaves)`` writing the
    values back into the state object.

    Names match the ``sync`` broadcast naming (``<key>`` for scalar/array
    leaves, ``<key>.<i>`` for pytree sub-leaves); scalar leaves round-trip
    with their Python types preserved (step counters stay ints), exactly
    like ``sync`` (the re-enterability contract depends on it).
    """
    from horovod_tpu.common.elastic import _coerce_like, _tree_flatten

    named: List[Tuple[str, Any]] = []
    writers: List[Tuple[str, Any, Any]] = []  # (key, rebuild|None, span)
    for key in state.keys():
        value = getattr(state, key)
        if isinstance(value, (dict, list, tuple)):
            flat, rebuild = _tree_flatten(value)
            start = len(named)
            named.extend((f"{key}.{i}", leaf) for i, leaf in enumerate(flat))
            writers.append((key, rebuild, (start, len(named))))
        else:
            start = len(named)
            named.append((key, value))
            writers.append((key, None, (start, start + 1)))

    originals = [value for _, value in named]

    def assign(new_leaves: list) -> None:
        if len(new_leaves) != len(originals):
            raise ValueError(
                f"state shape changed: {len(originals)} leaves flattened, "
                f"{len(new_leaves)} supplied")
        for key, rebuild, (start, stop) in writers:
            if rebuild is not None:
                setattr(state, key, rebuild(list(new_leaves[start:stop])))
            else:
                setattr(state, key,
                        _coerce_like(originals[start], new_leaves[start]))

    return named, assign
