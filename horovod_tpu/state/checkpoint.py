"""Sharded durable checkpoints: ``ckpt-<step>/rank-N.pkl`` + manifest.

The legacy checkpoint (``jax/train.py``, PR 2) is a single rank-0 pickle:
O(model) serialized and written through one rank's disk/NIC while every
other rank idles at the barrier.  The sharded format spreads the same
bytes across ALL ranks — each writes only the leaves it owns under the
state plane's partition contract (``partition.owner``: leaf ``i`` → rank
``i % size``) — so wall time drops to O(model/size) per rank, and a
rank-0 ``manifest.json`` commits the checkpoint atomically AFTER a named
-collective barrier confirmed every shard landed.

Commit protocol (the torn-checkpoint story):

1. every rank writes ``rank-<r>.pkl`` (tmp + rename, like the legacy path);
2. barrier ``__ckpt.<step>.barrier`` — no rank proceeds until all shards
   are durable;
3. rank 0 writes ``manifest.json`` (tmp + rename) — the COMMIT POINT:
   a checkpoint directory without a manifest is torn by definition and
   invisible to ``latest_checkpoint``;
4. barrier ``__ckpt.<step>.commit`` — ``save_checkpoint`` returns on no
   rank before the manifest is durable;
5. rank 0 prunes past ``HVD_TPU_CKPT_KEEP`` (retention never touches the
   checkpoint just written, and only runs after its manifest committed).

Reading: with the engine up at the manifest's world size, each rank reads
ONLY its own shard and the rest arrives by per-leaf broadcast from the
owning rank (O(model/size) disk per rank); any other reader — different
size, no engine, tools — assembles all shards locally.  Non-array leaves
(step counters, rng keys as ints, flags) are replicated verbatim into
every shard so scalar Python types round-trip exactly like the legacy
pickle.
"""

from __future__ import annotations

import json
import os
import pickle
import tempfile
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from horovod_tpu.common import metrics as _metrics
from horovod_tpu.state import partition

MANIFEST = "manifest.json"
SHARD_FORMAT = "hvd-tpu-sharded-v1"
_CKPT_PREFIX = "ckpt-"
_CKPT_SUFFIX = ".pkl"


def shard_file(rank: int) -> str:
    return f"rank-{rank}.pkl"


def _is_array_leaf(leaf: Any) -> bool:
    """Array leaves shard and broadcast; everything else (python scalars,
    strings, rng ints) replicates into every shard verbatim, preserving
    exact types the way the legacy whole-tree pickle did."""
    if isinstance(leaf, np.ndarray):
        return True
    try:
        import jax

        return isinstance(leaf, jax.Array)
    except Exception:
        return False


def _leaf_names(tree: Any, n: int) -> List[str]:
    """Human leaf names for the manifest: jax key paths when available,
    positional ``leaf.<i>`` otherwise.  Best effort — names are for
    ``tools/ckpt_inspect.py`` humans, never for reassembly."""
    try:
        import jax

        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        if len(flat) == n:
            return [jax.tree_util.keystr(path) or f"leaf.{i}"
                    for i, (path, _) in enumerate(flat)]
    except Exception:
        pass
    return [f"leaf.{i}" for i in range(n)]


def _atomic_write(path: str, write_fn) -> None:
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            write_fn(f)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def save_sharded(directory: str, step: int, tree: Any, rank: int,
                 size: int, barrier=None) -> str:
    """Write this rank's shard of ``ckpt-<step>/`` and (rank 0) commit the
    manifest; returns the checkpoint directory path.  ``barrier(name)`` is
    the named-collective barrier (None for single-process writers)."""
    try:  # device arrays materialize as host numpy, like the legacy path
        from jax import device_get as _device_get
    except ImportError:  # pragma: no cover - engine-only environments
        def _device_get(x):
            return x

    ckpt_dir = os.path.join(directory,
                            f"{_CKPT_PREFIX}{int(step):08d}")
    os.makedirs(ckpt_dir, exist_ok=True)
    leaves, rebuild = partition.flatten_tree(tree)
    # Skeleton: the tree with every leaf replaced by its global index —
    # pickles through the same container types the legacy format already
    # required, and rebuilds via the shared _tree_flatten walk.  Stored in
    # EVERY shard so any one surviving shard explains the structure.
    skeleton = rebuild(list(range(len(leaves))))
    own_idx = set(partition.shard_indices(rank, size, len(leaves)))
    array_meta: List[Optional[dict]] = []
    objects: Dict[int, Any] = {}
    own: Dict[int, np.ndarray] = {}
    for i, leaf in enumerate(leaves):
        if _is_array_leaf(leaf):
            # Metadata comes from the (device) leaf's shape/dtype; only
            # OWNED leaves pay the device->host materialization — the
            # per-rank transfer stays O(model/size), the sharding point.
            dtype = np.dtype(leaf.dtype)
            array_meta.append({"shape": list(leaf.shape),
                               "dtype": dtype.name,
                               "nbytes": int(dtype.itemsize
                                             * int(np.prod(leaf.shape)))})
            if i in own_idx:
                own[i] = np.asarray(_device_get(leaf))
        else:
            array_meta.append(None)
            objects[i] = leaf
    shard_doc = {"format": SHARD_FORMAT, "step": int(step), "rank": rank,
                 "size": size, "skeleton": skeleton, "objects": objects,
                 "leaves": own}
    path = os.path.join(ckpt_dir, shard_file(rank))
    _atomic_write(path, lambda f: pickle.dump(
        shard_doc, f, protocol=pickle.HIGHEST_PROTOCOL))
    shard_nbytes = os.path.getsize(path)
    if barrier is not None:
        barrier(f"__ckpt.{int(step)}.barrier")
    if rank == 0:
        names = _leaf_names(tree, len(leaves))
        manifest = {
            "format": SHARD_FORMAT,
            "step": int(step),
            "size": size,
            "leaf_count": len(leaves),
            "leaves": [
                {"index": i, "name": names[i],
                 "shard": partition.owner(i, size),
                 **(array_meta[i] if array_meta[i] is not None
                    else {"object": True})}
                for i in range(len(leaves))],
            "shards": [{"rank": r, "file": shard_file(r)}
                       for r in range(size)],
        }
        mpath = os.path.join(ckpt_dir, MANIFEST)
        _atomic_write(mpath, lambda f: f.write(
            (json.dumps(manifest, indent=2) + "\n").encode()))
    if barrier is not None:
        barrier(f"__ckpt.{int(step)}.commit")
    _metrics.registry.record_state_ckpt("sharded_saves",
                                        nbytes=shard_nbytes)
    return ckpt_dir


def read_manifest(ckpt_dir: str) -> dict:
    """The committed manifest of a sharded checkpoint directory;
    ``ValueError`` when missing (torn) or malformed."""
    path = os.path.join(ckpt_dir, MANIFEST)
    try:
        with open(path) as f:
            manifest = json.load(f)
    except OSError:
        raise ValueError(
            f"torn sharded checkpoint {ckpt_dir}: no committed "
            f"{MANIFEST} (the writer died before the commit point)")
    except ValueError as exc:
        raise ValueError(f"corrupt manifest in {ckpt_dir}: {exc}")
    if manifest.get("format") != SHARD_FORMAT:
        raise ValueError(f"unknown checkpoint format in {ckpt_dir}: "
                         f"{manifest.get('format')!r}")
    return manifest


def _read_shard(ckpt_dir: str, manifest: dict, rank: int) -> dict:
    path = os.path.join(ckpt_dir, shard_file(rank))
    try:
        with open(path, "rb") as f:
            doc = pickle.load(f)
    except OSError:
        raise ValueError(
            f"torn sharded checkpoint {ckpt_dir}: missing shard "
            f"{shard_file(rank)} (manifest expects {manifest['size']} "
            f"shards)")
    except Exception as exc:  # truncated/corrupt pickle is torn, too
        raise ValueError(
            f"torn sharded checkpoint {ckpt_dir}: shard "
            f"{shard_file(rank)} is unreadable "
            f"({type(exc).__name__}: {exc})")
    if not isinstance(doc, dict):
        raise ValueError(
            f"torn sharded checkpoint {ckpt_dir}: shard "
            f"{shard_file(rank)} does not hold a shard document")
    if doc.get("step") != manifest["step"] \
            or doc.get("size") != manifest["size"]:
        raise ValueError(
            f"torn sharded checkpoint {ckpt_dir}: shard "
            f"{shard_file(rank)} is step {doc.get('step')} / size "
            f"{doc.get('size')}, manifest says step {manifest['step']} / "
            f"size {manifest['size']}")
    return doc


def load_sharded(ckpt_dir: str, collective: bool = True
                 ) -> Tuple[int, Any]:
    """``(step, tree)`` from a committed sharded checkpoint.

    With ``collective=True`` and the engine initialized at the manifest's
    world size, each rank reads only its own shard and the remaining
    leaves arrive by broadcast from their owners (shapes/dtypes come from
    the manifest, so non-owners allocate without touching the files).
    Otherwise every shard is read locally — correct at any world size,
    engine or not.
    """
    manifest = read_manifest(ckpt_dir)
    step, size, n = manifest["step"], manifest["size"], manifest["leaf_count"]
    from horovod_tpu import common as _common

    use_collective = (collective and size > 1 and _common.is_initialized()
                      and _common.size() == size)
    if use_collective:
        tree = _load_collective(ckpt_dir, manifest)
    else:
        tree = _load_local(ckpt_dir, manifest)
    _metrics.registry.record_state_ckpt("loads")
    return int(step), tree


def _assemble(skeleton: Any, objects: Dict[int, Any],
              arrays: Dict[int, np.ndarray], n: int) -> Any:
    order, rebuild = partition.flatten_tree(skeleton)
    values: List[Any] = []
    for idx in order:
        idx = int(idx)
        if idx in objects:
            values.append(objects[idx])
        elif idx in arrays:
            values.append(arrays[idx])
        else:
            raise ValueError(f"sharded checkpoint reassembly missing leaf "
                             f"{idx} of {n}")
    return rebuild(values)


def _load_local(ckpt_dir: str, manifest: dict) -> Any:
    arrays: Dict[int, np.ndarray] = {}
    objects: Dict[int, Any] = {}
    skeleton = None
    for r in range(manifest["size"]):
        doc = _read_shard(ckpt_dir, manifest, r)
        skeleton = doc["skeleton"] if skeleton is None else skeleton
        objects.update(doc.get("objects", {}))
        arrays.update(doc.get("leaves", {}))
    return _assemble(skeleton, objects, arrays, manifest["leaf_count"])


def _load_collective(ckpt_dir: str, manifest: dict) -> Any:
    from horovod_tpu import common as _common

    rank = _common.rank()
    doc = _read_shard(ckpt_dir, manifest, rank)
    skeleton, objects = doc["skeleton"], dict(doc.get("objects", {}))
    own = doc.get("leaves", {})
    arrays: Dict[int, np.ndarray] = {}
    step = manifest["step"]
    for meta in manifest["leaves"]:
        i = meta["index"]
        if meta.get("object"):
            continue  # replicated into every shard
        root = meta["shard"]
        if root == rank:
            src = np.ascontiguousarray(own[i])
        else:
            # Receive buffer only — contents are overwritten, so empty
            # beats zeros (no O(model) memset on the resume path).
            src = np.empty(tuple(meta["shape"]), dtype=meta["dtype"])
        arrays[i] = _common.broadcast(src, root,
                                      name=f"__ckpt.load.{step}.{i}")
    return _assemble(skeleton, objects, arrays, manifest["leaf_count"])


# ---------------------------------------------------------------------------
# Directory scanning + retention (shared with jax/train.py).
# ---------------------------------------------------------------------------


def scan_checkpoints(directory: str) -> List[Tuple[int, str, str]]:
    """Every commit-complete checkpoint under ``directory``:
    ``[(step, path, kind)]`` sorted by step, kind ``"legacy"`` (single
    pickle) or ``"sharded"`` (directory with a committed manifest).  Torn
    sharded directories (no manifest yet — mid-write, or a died writer)
    are invisible, exactly like a legacy ``.tmp`` file."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    found = []
    for name in names:
        if not name.startswith(_CKPT_PREFIX):
            continue
        path = os.path.join(directory, name)
        if name.endswith(_CKPT_SUFFIX):
            try:
                step = int(name[len(_CKPT_PREFIX):-len(_CKPT_SUFFIX)])
            except ValueError:
                continue
            found.append((step, path, "legacy"))
        elif os.path.isdir(path):
            try:
                step = int(name[len(_CKPT_PREFIX):])
            except ValueError:
                continue
            if os.path.exists(os.path.join(path, MANIFEST)):
                found.append((step, path, "sharded"))
    return sorted(found)


def retention_keep() -> Optional[int]:
    """``HVD_TPU_CKPT_KEEP``: how many committed checkpoints to retain
    (None / unset / <= 0 = unbounded, the historical behavior)."""
    raw = os.environ.get("HVD_TPU_CKPT_KEEP")
    if not raw:
        return None
    try:
        keep = int(raw)
    except ValueError:
        raise ValueError(f"HVD_TPU_CKPT_KEEP must be an integer, got "
                         f"{raw!r}")
    return keep if keep > 0 else None


def prune_checkpoints(directory: str, keep: Optional[int],
                      protect_step: Optional[int] = None) -> List[str]:
    """Delete the oldest committed checkpoints past ``keep``, newest-first
    retention.  ``protect_step`` (the checkpoint just written) is never
    pruned even if the scan ordered it away; torn directories are never
    touched (they are some writer's in-flight state, not garbage —
    ``tools/ckpt_inspect.py`` flags them for humans).  Returns the pruned
    paths."""
    if keep is None or keep <= 0:
        return []
    import shutil

    entries = scan_checkpoints(directory)
    victims = entries[:-keep] if len(entries) > keep else []
    pruned = []
    for step, path, kind in victims:
        if protect_step is not None and step == int(protect_step):
            continue
        try:
            if kind == "sharded":
                # Manifest first: the directory stops being a committed
                # checkpoint before any shard byte disappears, so a
                # concurrent reader sees "torn" (skipped), never a
                # half-deleted "committed" one.
                os.unlink(os.path.join(path, MANIFEST))
                shutil.rmtree(path, ignore_errors=True)
            else:
                os.unlink(path)
        except OSError:
            continue
        pruned.append(path)
        _metrics.registry.record_state_ckpt("pruned")
    return pruned
