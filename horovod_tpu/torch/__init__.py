"""PyTorch user API: DistributedOptimizer, parameter/optimizer-state broadcast.

Counterpart of /root/reference/horovod/torch/__init__.py: the optimizer
wrapper overlaps gradient allreduce with backprop via per-parameter hooks
(reference lines 64-89), `step()` drains the outstanding handles first, and
`broadcast_parameters` / `broadcast_optimizer_state` replicate rank 0's
state at startup (reference lines 127-228).
"""

from __future__ import annotations

import collections
from typing import Iterator, Optional, Tuple

import numpy as np
import torch

import horovod_tpu.common as _common
from horovod_tpu.common import (  # noqa: F401  (process-control re-exports)
    HorovodInternalError,
    init,
    is_initialized,
    local_rank,
    local_size,
    mpi_threads_supported,
    rank,
    shutdown,
    size,
)
from horovod_tpu.torch.mpi_ops import (  # noqa: F401
    allgather,
    allgather_async,
    allreduce,
    allreduce_,
    allreduce_async,
    allreduce_async_,
    broadcast,
    broadcast_,
    broadcast_async,
    broadcast_async_,
    poll,
    synchronize,
)


class _DistributedOptimizer(torch.optim.Optimizer):
    """Mixin methods grafted onto the wrapped optimizer's class by
    :func:`DistributedOptimizer` (dynamic-subclass pattern, keeping
    `isinstance(opt, OriginalClass)` true, as the reference does at
    /root/reference/horovod/torch/__init__.py:92-124)."""

    _hvd_tpu_distributed = True  # marker for comm-free base-step dispatch

    def __init__(self, params, named_parameters=None,
                 backward_passes_per_step=1):
        super(self.__class__, self).__init__(params)
        if named_parameters is not None:
            named = list(named_parameters)
        else:
            named = []
        self._param_names = {id(p): name for name, p in named}
        self._handles = {}
        self._hook_registrations = []
        if backward_passes_per_step < 1:
            raise ValueError("backward_passes_per_step must be >= 1")
        self._backward_passes_per_step = backward_passes_per_step
        self._passes = collections.Counter()  # id(p) -> hook fires
        self._register_hooks()

    def _grad_name(self, p) -> str:
        name = self._param_names.get(id(p))
        if name is None:
            # Deterministic across ranks: parameter order in param_groups.
            idx = 0
            for group in self.param_groups:
                for q in group["params"]:
                    if q is p:
                        return f"DistributedOptimizer.grad.{idx}"
                    idx += 1
            raise ValueError("parameter not found in optimizer param groups")
        return f"DistributedOptimizer.grad.{name}"

    def _register_hooks(self) -> None:
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    reg = p.register_post_accumulate_grad_hook(
                        self._make_hook())
                    self._hook_registrations.append(reg)

    def _make_hook(self):
        def hook(p):
            if p in self._handles:
                # The previous allreduce still reads p.grad's memory; a
                # second backward would race autograd's accumulation into
                # the same buffer and silently corrupt gradients.  Fail
                # loudly instead (the reference's later
                # backward_passes_per_step semantics, made explicit).
                raise RuntimeError(
                    f"gradient for '{self._grad_name(p)}' was produced "
                    "again while its allreduce is still in flight. For "
                    "gradient accumulation over N micro-batches, construct "
                    "DistributedOptimizer(..., backward_passes_per_step=N); "
                    "otherwise call step()/synchronize() between backward "
                    "passes.")
            self._passes[id(p)] += 1
            if self._passes[id(p)] >= self._backward_passes_per_step:
                # Local accumulation is complete: p.grad now holds the sum
                # over the N micro-batch backwards; average it across ranks.
                self._handles[p] = allreduce_async_(
                    p.grad.data, average=True, name=self._grad_name(p))
        return hook

    def synchronize(self) -> None:
        """Wait for every outstanding gradient allreduce; enqueue any grads
        not yet in flight (hooks that never fired, or mid-accumulation
        grads when step() is called before the Nth backward)."""
        for group in self.param_groups:
            for p in group["params"]:
                if p.grad is not None and p not in self._handles:
                    self._handles[p] = allreduce_async_(
                        p.grad.data, average=True, name=self._grad_name(p))
        for p, handle in list(self._handles.items()):
            handle.synchronize()
        self._handles.clear()
        self._passes.clear()

    def step(self, closure=None):
        self.synchronize()
        return super(self.__class__, self).step(closure)


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters: Optional[Iterator[Tuple[str, torch.nn.Parameter]]] = None,
                         backward_passes_per_step: int = 1):
    """Wrap a torch optimizer: gradients are allreduce-averaged across
    workers as backprop produces them; `step()` waits for them first.

    ``backward_passes_per_step=N`` enables gradient accumulation: the
    allreduce for each parameter is delayed until its Nth backward since
    the last ``step()``, so ``p.grad`` first accumulates the local sum of N
    micro-batches and one averaged allreduce carries it (reference
    counterpart: the hook semantics of
    /root/reference/horovod/torch/__init__.py:64-89, extended so
    micro-batching is race-free)."""
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    return cls(optimizer.param_groups, named_parameters,
               backward_passes_per_step)


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """In-place broadcast of a `state_dict()` or iterable of (name, tensor).

    Reference: /root/reference/horovod/torch/__init__.py:127-158.
    """
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)
    handles = []
    for name, p in items:
        if p is None:
            continue
        if not isinstance(p, torch.Tensor):
            raise ValueError(
                f"broadcast_parameters got non-tensor for '{name}'; use "
                "broadcast_optimizer_state for mixed state")
        handles.append(broadcast_async_(p.data if hasattr(p, "data") else p,
                                        root_rank,
                                        name=f"broadcast_parameters.{name}"))
    for h in handles:
        h.synchronize()


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0) -> None:
    """Replicate rank ``root_rank``'s optimizer state dict on every worker,
    round-tripping scalar hyperparameters through tensors.

    Reference: /root/reference/horovod/torch/__init__.py:161-228 (including
    the empty-state bootstrap via a zero-gradient dummy step and the LBFGS
    rejection — LBFGS keeps non-broadcastable closure state).
    """
    if isinstance(optimizer, torch.optim.LBFGS):
        raise ValueError("cannot broadcast torch.optim.LBFGS state")

    state_dict = optimizer.state_dict()
    if not state_dict["state"]:
        # New optimizers have empty per-param state; materialize it with a
        # zero-grad step so every rank has the same structure to fill.
        # The bootstrap must be LOCAL and PARAM-NEUTRAL: on a
        # resume-from-checkpoint, the root rank has loaded state while the
        # other ranks bootstrap — (a) a DistributedOptimizer.step() here
        # would enqueue gradient allreduces the root never joins
        # (deadlock, caught by tests/test_examples.py's resume leg), so
        # dispatch to the wrapped optimizer's own step; (b) lr/
        # weight_decay are zeroed for the dummy step so it cannot move
        # the already-broadcast parameters (zero grads alone don't make
        # a decoupled-weight-decay step a no-op).
        for group in optimizer.param_groups:
            for p in group["params"]:
                if p.requires_grad and p.grad is None:
                    p.grad = torch.zeros_like(p)
        saved = [{key: group[key] for key in ("lr", "weight_decay")
                  if key in group} for group in optimizer.param_groups]
        for group in optimizer.param_groups:
            for key in ("lr", "weight_decay"):
                if key in group:
                    group[key] = 0.0
        try:
            if getattr(optimizer, "_hvd_tpu_distributed", False):
                super(type(optimizer), optimizer).step()
            else:
                optimizer.step()
        finally:
            for group, vals in zip(optimizer.param_groups, saved):
                group.update(vals)
        state_dict = optimizer.state_dict()

    scalars = {}       # key -> broadcast scalar value
    handles = []

    def visit(prefix: str, container, key) -> None:
        value = container[key]
        name = f"broadcast_opt_state.{prefix}"
        if isinstance(value, torch.Tensor):
            handles.append(broadcast_async_(value, root_rank, name=name))
        elif isinstance(value, (bool, int, float)):
            arr = np.asarray(value)
            out = _common.broadcast(arr, root_rank, name=name)
            container[key] = type(value)(out.item())
            scalars[prefix] = container[key]

    for pid, pstate in sorted(state_dict["state"].items(),
                              key=lambda kv: str(kv[0])):
        for key in sorted(pstate, key=str):
            visit(f"state.{pid}.{key}", pstate, key)
    for gi, group in enumerate(state_dict["param_groups"]):
        for key in sorted(group, key=str):
            if key == "params":
                continue
            visit(f"group.{gi}.{key}", group, key)
    for h in handles:
        h.synchronize()
    optimizer.load_state_dict(state_dict)
