"""PyTorch collective ops: async handles, in-place variants, autograd.

The surface of the reference's torch binding
(/root/reference/horovod/torch/mpi_ops.py: allreduce{,_async}{,_},
allgather{,_async}, broadcast{,_async}{,_}, poll, synchronize) rebuilt on the
shared C++ engine through zero-copy numpy views of CPU tensors — the cffi
per-dtype function table (/root/reference/horovod/torch/interface.h) is
unnecessary because dtype travels as a runtime tag.

TPU note: tensors live on host here; the engine moves them over DCN.  Models
whose compute runs on TPU via the JAX path exchange gradients in compiled
XLA collectives instead — this binding serves torch-CPU training loops and
eager state replication (the role of the reference's CudaOnCPU staging path,
/root/reference/horovod/torch/mpi_ops.cc:72-101).
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import torch

import horovod_tpu.common as _common

try:
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BF16 = None

# Handles still outstanding; pins tensors against GC while the engine may
# write to their memory (the reference's _handle_map,
# /root/reference/horovod/torch/mpi_ops.py:28-31).
_outstanding = {}


def _np_view(tensor: torch.Tensor) -> np.ndarray:
    """A zero-copy numpy view of a contiguous CPU tensor."""
    if tensor.dtype == torch.bfloat16:
        if _BF16 is None:
            raise ValueError("bfloat16 collectives require ml_dtypes")
        return tensor.view(torch.uint16).numpy().view(_BF16)
    return tensor.numpy()


def _check_tensor(tensor: torch.Tensor, inplace: bool) -> torch.Tensor:
    if tensor.device.type != "cpu":
        raise ValueError(
            f"horovod_tpu.torch collectives operate on CPU tensors; got "
            f"device {tensor.device}. TPU-resident compute should use the "
            f"compiled horovod_tpu.jax path.")
    if not tensor.is_contiguous():
        if inplace:
            raise ValueError(
                "in-place collectives require a contiguous tensor")
        tensor = tensor.contiguous()
    return tensor


class TorchHandle:
    """Outstanding torch collective; resolves to a tensor on synchronize."""

    def __init__(self, inner, result_tensor: Optional[torch.Tensor],
                 template: Optional[torch.Tensor] = None):
        self._inner = inner
        self._result = result_tensor     # pre-bound output (allreduce/bcast)
        self._template = template        # dtype/shape donor for allgather
        _outstanding[id(self)] = self

    def poll(self) -> bool:
        return self._inner.done()

    def synchronize(self) -> torch.Tensor:
        try:
            out = self._inner.wait()
        finally:
            _outstanding.pop(id(self), None)
        if self._result is not None:
            return self._result
        # Allgather: engine returned a fresh numpy array.
        t = self._template
        if t is not None and t.dtype == torch.bfloat16:
            return torch.from_numpy(out.view(np.uint16).copy()).view(
                torch.bfloat16)
        return torch.from_numpy(out)


def poll(handle: TorchHandle) -> bool:
    return handle.poll()


def synchronize(handle: TorchHandle) -> torch.Tensor:
    return handle.synchronize()


# --- allreduce ---------------------------------------------------------------


def allreduce_async(tensor: torch.Tensor, average: bool = True,
                    name: Optional[str] = None) -> TorchHandle:
    tensor = _check_tensor(tensor, inplace=False)
    output = torch.empty_like(tensor)
    inner = _common.allreduce_async(_np_view(tensor), average=average,
                                    name=name, out=_np_view(output))
    return TorchHandle(inner, output)


def allreduce_async_(tensor: torch.Tensor, average: bool = True,
                     name: Optional[str] = None) -> TorchHandle:
    tensor = _check_tensor(tensor, inplace=True)
    view = _np_view(tensor)
    inner = _common.allreduce_async(view, average=average, name=name,
                                    out=view)
    return TorchHandle(inner, tensor)


class _AllreduceFunction(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, average, name):
        ctx.average = average
        return allreduce_async(tensor, average, name).synchronize()

    @staticmethod
    def backward(ctx, grad_output):
        return (allreduce_async(grad_output.contiguous(),
                                ctx.average).synchronize(), None, None)


def allreduce(tensor: torch.Tensor, average: bool = True,
              name: Optional[str] = None) -> torch.Tensor:
    """Differentiable allreduce: the gradient is itself allreduced, as in the
    reference (/root/reference/horovod/torch/mpi_ops.py:83-94)."""
    return _AllreduceFunction.apply(tensor, average, name)


def allreduce_(tensor: torch.Tensor, average: bool = True,
               name: Optional[str] = None) -> torch.Tensor:
    return allreduce_async_(tensor, average, name).synchronize()


# --- allgather ---------------------------------------------------------------


def allgather_async(tensor: torch.Tensor,
                    name: Optional[str] = None) -> TorchHandle:
    tensor = _check_tensor(tensor, inplace=False)
    inner = _common.allgather_async(_np_view(tensor), name=name)
    return TorchHandle(inner, None, template=tensor)


class _AllgatherFunction(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, name):
        ctx.dim0 = tensor.shape[0] if tensor.dim() else 0
        return allgather_async(tensor, name).synchronize()

    @staticmethod
    def backward(ctx, grad_output):
        # d(concat_r x_r)/dx_me: sum every rank's grad_output, then take this
        # rank's row block.  Row offsets come from an allgather of per-rank
        # dim0 (ranks may contribute different dim0).
        grad_sum = allreduce_async(grad_output.contiguous(),
                                   average=False).synchronize()
        sizes = allgather_async(
            torch.tensor([ctx.dim0], dtype=torch.int64)).synchronize()
        offset = int(sizes[:_common.rank()].sum())
        return grad_sum.narrow(0, offset, ctx.dim0), None


def allgather(tensor: torch.Tensor,
              name: Optional[str] = None) -> torch.Tensor:
    """Differentiable concatenation of every rank's tensor along dim 0."""
    return _AllgatherFunction.apply(tensor, name)


# --- broadcast ---------------------------------------------------------------


def broadcast_async(tensor: torch.Tensor, root_rank: int,
                    name: Optional[str] = None) -> TorchHandle:
    tensor = _check_tensor(tensor, inplace=False)
    output = torch.empty_like(tensor)
    inner = _common.broadcast_async(_np_view(tensor), root_rank, name=name,
                                    out=_np_view(output))
    return TorchHandle(inner, output)


def broadcast_async_(tensor: torch.Tensor, root_rank: int,
                     name: Optional[str] = None) -> TorchHandle:
    tensor = _check_tensor(tensor, inplace=True)
    view = _np_view(tensor)
    inner = _common.broadcast_async(view, root_rank, name=name, out=view)
    return TorchHandle(inner, tensor)


class _BroadcastFunction(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, root_rank, name):
        ctx.root_rank = root_rank
        return broadcast_async(tensor, root_rank, name).synchronize()

    @staticmethod
    def backward(ctx, grad_output):
        grad = allreduce_async(grad_output.contiguous(),
                               average=False).synchronize()
        if _common.rank() != ctx.root_rank:
            grad = grad * 0
        return grad, None, None


def broadcast(tensor: torch.Tensor, root_rank: int,
              name: Optional[str] = None) -> torch.Tensor:
    """Differentiable broadcast; non-root ranks get zero gradient, as in the
    reference's gradient registration
    (/root/reference/horovod/tensorflow/mpi_ops.py:155-170)."""
    return _BroadcastFunction.apply(tensor, root_rank, name)


def broadcast_(tensor: torch.Tensor, root_rank: int,
               name: Optional[str] = None) -> torch.Tensor:
    return broadcast_async_(tensor, root_rank, name).synchronize()
