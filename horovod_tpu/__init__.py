"""horovod_tpu: TPU-native synchronous data-parallel training framework.

A ground-up, TPU-first rebuild of the capabilities of Horovod v0.13.11
(reference: zhangzhao156/horovod).  A single-device training script becomes a
multi-chip / multi-host one with five changes, exactly as in the reference
(/root/reference/README.md:80-105):

    import horovod_tpu as hvd
    hvd.init()                         # rank/size from pod metadata, not MPI
    ...  # pin device by hvd.local_rank(); scale LR by hvd.size()

Collectives are named, asynchronously enqueued into a C++ background engine
that negotiates readiness across ranks through a rank-0 TCP coordinator,
fuses small tensors, and executes ring collectives over the host network
(DCN), while the compiled JAX path (`horovod_tpu.jax`) lowers the same API to
XLA collectives over ICI inside `jit`.

The top-level module exposes the process-control API plus numpy collectives;
per-framework submodules add `DistributedOptimizer` wrappers and broadcast
helpers on top of this substrate.
"""

from horovod_tpu.common import (  # noqa: F401
    CollectiveTimeoutError,
    HorovodInternalError,
    HorovodNotInitializedError,
    MembershipChangedError,
    RanksDownError,
    StageGroup,
    allgather,
    allgather_async,
    allreduce,
    allreduce_async,
    autotune_report,
    autotune_set,
    broadcast,
    broadcast_async,
    compression_report,
    init,
    is_initialized,
    local_rank,
    local_size,
    membership_ack,
    membership_epoch,
    metrics_reset,
    metrics_snapshot,
    mpi_threads_supported,
    rank,
    recv,
    recv_async,
    restart_epoch,
    send,
    send_async,
    shutdown,
    size,
    stage_group,
    timeline_enabled,
    trace_marker,
    trace_span,
)
from horovod_tpu.common.elastic import (  # noqa: F401
    ElasticState,
    run_elastic,
)
# State plane (docs/fault-tolerance.md#state-plane): hvd.state.arm() /
# hvd.state.current() / hvd.state.disarm(), plus the sharded-checkpoint
# helpers under horovod_tpu.state.checkpoint.
from horovod_tpu import state  # noqa: E402,F401

__version__ = "0.1.0"
