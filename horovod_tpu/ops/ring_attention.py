"""Ring attention: sequence-parallel attention over a mesh axis.

Long-context strategy (Liu et al., Ring Attention with Blockwise
Transformers, arXiv:2310.01889), absent from the reference (SURVEY §5.7)
and added here as a first-class TPU capability: the sequence dimension is
sharded over the mesh axis; each device keeps its query shard and passes
its key/value shard around the ring with `lax.ppermute` (which XLA lowers
to ICI neighbour transfers overlapped with the attention compute), merging
partial results with the same online-softmax statistics the flash kernel
uses.  Peak memory per device is O(seq/N) — context length scales linearly
with the ring size.

Use inside `shard_map` with the sequence dimension sharded along
``axis_name``; differentiable end-to-end (ppermute transposes to the
reverse rotation).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.utils.jax_compat import axis_size as _axis_size

from horovod_tpu.ops.attention import (
    NEG_INF,
    _block_attend,
    _finalize,
)


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   sm_scale: Optional[float] = None,
                   rotate_impl: str = "ppermute"):
    """Attention over a sequence sharded along ``axis_name``.

    Args:
      q, k, v: local shards, ``(batch, heads, seq_local, head_dim)``; the
        global sequence is the concatenation of shards in mesh-axis order.
      axis_name: the mapped mesh axis carrying the sequence shards.
      causal: apply a causal mask over *global* positions.
      sm_scale: softmax scale; default ``head_dim ** -0.5``.
      rotate_impl: how K/V shards travel the ring — ``"ppermute"`` (XLA
        collective permute, default: the compiler schedules it as an async
        start/done pair overlapped with compute), ``"rdma"``
        (:func:`horovod_tpu.ops.rdma.ring_permute`: one raw Pallas remote
        DMA per rotation, for hardware where explicit transfer control
        beats XLA's scheduling), or ``"fused"``
        (:func:`horovod_tpu.ops.ring_flash.fused_ring_attention`: ONE
        Pallas program per ring step that starts the rotation DMA, flash-
        attends the current shard while it flies, and waits at the end —
        overlap by construction).  Differentiable in every mode.

    Returns:
      The local output shard, same shape/dtype as ``q``.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if rotate_impl == "fused":
        from horovod_tpu.ops.ring_flash import fused_ring_attention

        return fused_ring_attention(q, k, v, axis_name, causal=causal,
                                    sm_scale=sm_scale)
    n = _axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    seq_local = q.shape[-2]

    q_pos = my_idx * seq_local + jnp.arange(seq_local)
    m0 = jnp.full(q.shape[:-1], NEG_INF, jnp.float32)
    l0 = jnp.zeros(q.shape[:-1], jnp.float32)
    acc0 = jnp.zeros(q.shape[:-2] + (seq_local, q.shape[-1]), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    if rotate_impl == "ppermute":
        def rotate(t, phase):
            del phase
            return lax.ppermute(t, axis_name, perm)
    elif rotate_impl == "rdma":
        from horovod_tpu.ops.rdma import _ambient_mesh_axes, ring_permute

        if (jax.default_backend() != "tpu"
                and len(_ambient_mesh_axes(axis_name)) > 1):
            # Interpret-mode remote DMA only supports single-axis meshes
            # (upstream dma_start_p limitation); fall back to ppermute on
            # CPU dp x sp meshes, as the fused backend does.
            return ring_attention(q, k, v, axis_name, causal=causal,
                                  sm_scale=sm_scale,
                                  rotate_impl="ppermute")

        def rotate(t, phase):
            # Barrier-namespace discipline (see rdma.py): the K and V
            # rotation chains are independent of each other, so each
            # gets its own namespace PAIR (K: phases 0/1, V: 2/3) and
            # alternates within the pair per step.  Adjacent rotations
            # of one chain — the only orderings data dependence forces —
            # then always differ, forward, backward (the VJP flips
            # within the pair), and across the fwd/bwd seam, regardless
            # of how jax orders the traced transposes or how the
            # scheduler interleaves the two chains at runtime.
            return ring_permute(t, axis_name, phase=phase)
    else:
        raise ValueError(f"unknown rotate_impl {rotate_impl!r}")

    # Unrolled ring loop (n is the static mesh-axis size): each step's
    # ppermute can then be scheduled by XLA as an async collective-permute
    # overlapped with the next step's attention compute, which a
    # lax.fori_loop carry would serialize.  Each step's attention is
    # rematerialized in the backward pass (jax.checkpoint): without it the
    # VJP saves every step's (seq_local, seq_local) probability block —
    # O(seq^2 / n) per device, defeating the ring's memory scaling.  The
    # mask is built *inside* the checkpointed step from the scalar shard
    # index, so it is recomputed too, not stored as a residual.
    def step_attend(q, k_cur, v_cur, m, l, acc, kv_idx):
        mask = None
        if causal:
            k_pos = kv_idx * seq_local + jnp.arange(seq_local)
            mask = q_pos[:, None] >= k_pos[None, :]
        return _block_attend(q, k_cur, v_cur, m, l, acc, mask, sm_scale)

    attend = jax.checkpoint(step_attend)
    k_cur, v_cur, m, l, acc = k, v, m0, l0, acc0
    for t in range(n):
        # After t right-rotations this device holds the shard that
        # originated on device (my_idx - t) mod n.
        m, l, acc = attend(q, k_cur, v_cur, m, l, acc, (my_idx - t) % n)
        if t < n - 1:  # rotate K/V to the right neighbour
            k_cur = rotate(k_cur, t % 2)
            v_cur = rotate(v_cur, 2 + t % 2)
    return _finalize(m, l, acc, q.dtype)
