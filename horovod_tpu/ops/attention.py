"""Fused attention: Pallas TPU kernel + differentiable blockwise fallback.

Layout convention: ``(batch, num_heads, seq, head_dim)`` throughout.

The Pallas kernel tiles queries and keys into MXU-sized blocks and keeps the
online-softmax state (running max, normalizer, accumulator) in VMEM scratch
across the key-block grid dimension, so attention needs O(block) on-chip
memory instead of materializing the (seq, seq) score matrix in HBM.  The
backward pass recomputes through :func:`blockwise_attention` (same math,
pure JAX), trading FLOPs for memory exactly like `jax.checkpoint`.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30  # big-negative instead of -inf: keeps exp() NaN-free when a
# whole row is masked (fully-masked causal blocks)


def mha_reference(q, k, v, causal: bool = False,
                  sm_scale: Optional[float] = None):
    """O(seq^2)-memory reference attention (for tests and tiny shapes)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    # precision="highest": on TPU the default matmul precision truncates f32
    # operands to bf16 passes; the reference must be at least as accurate as
    # the kernels it validates.
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   precision="highest").astype(jnp.float32) * sm_scale
    if causal:
        q_pos = jnp.arange(q.shape[2])[:, None]
        k_pos = jnp.arange(k.shape[2])[None, :]
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                      precision="highest")


# ---------------------------------------------------------------------------
# Blockwise attention: lax.scan online softmax.  Differentiable on any
# backend; the building block ring_attention reuses per ring step.
# ---------------------------------------------------------------------------


def _block_attend(q, k, v, m, l, acc, mask, sm_scale):
    """One online-softmax update of (m, l, acc) with a (q_len, k_len) block.

    ``mask`` is True where attention is allowed (or None for dense).
    Shapes: q (..., q_len, d), k/v (..., k_len, d); m/l (..., q_len);
    acc (..., q_len, d); all statistics in float32.
    """
    # preferred_element_type=f32: half-precision operands ride the MXU's
    # native passes while the accumulation (and, crucially, the backward
    # cotangents) stay float32 — a bf16 result here is both less accurate
    # and produces NaN gradients in the transposed scan on TPU.
    s = jnp.einsum("...qd,...kd->...qk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # Guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1.
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l_new = l * alpha + p.sum(axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "...qk,...kd->...qd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _finalize(m, l, acc, dtype):
    # Fully-masked rows have l == 0; emit zeros, not NaN.
    safe_l = jnp.where(l == 0.0, 1.0, l)
    return (acc / safe_l[..., None]).astype(dtype)


def blockwise_attention(q, k, v, causal: bool = False,
                        sm_scale: Optional[float] = None,
                        block_size: int = 512,
                        q_offset=0, k_offset=0):
    """Memory-efficient attention as a `lax.scan` over key/value blocks.

    ``q_offset``/``k_offset`` give the global sequence positions of the
    first query/key row — this is what lets :func:`ring_attention` apply a
    correct causal mask to rotated K/V shards.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    q_len, k_len = q.shape[-2], k.shape[-2]
    block = min(block_size, k_len)
    n_blocks = (k_len + block - 1) // block
    pad = n_blocks * block - k_len
    if pad:
        kp = jnp.pad(k, [(0, 0)] * (k.ndim - 2) + [(0, pad), (0, 0)])
        vp = jnp.pad(v, [(0, 0)] * (v.ndim - 2) + [(0, pad), (0, 0)])
    else:
        kp, vp = k, v
    kb = kp.reshape(*k.shape[:-2], n_blocks, block, k.shape[-1])
    vb = vp.reshape(*v.shape[:-2], n_blocks, block, v.shape[-1])
    # scan over the block axis: move it to the front.
    kb = jnp.moveaxis(kb, -3, 0)
    vb = jnp.moveaxis(vb, -3, 0)

    q_pos = q_offset + jnp.arange(q_len)
    m0 = jnp.full(q.shape[:-1], NEG_INF, jnp.float32)
    l0 = jnp.zeros(q.shape[:-1], jnp.float32)
    acc0 = jnp.zeros(q.shape[:-2] + (q_len, q.shape[-1]), jnp.float32)

    def step(carry, inputs):
        m, l, acc = carry
        i, kblk, vblk = inputs
        k_pos = k_offset + i * block + jnp.arange(block)
        valid = k_pos < k_offset + k_len  # padding rows
        mask = valid[None, :]
        if causal:
            mask = mask & (q_pos[:, None] >= k_pos[None, :])
        m, l, acc = _block_attend(q, kblk, vblk, m, l, acc, mask, sm_scale)
        return (m, l, acc), None

    (m, l, acc), _ = lax.scan(
        step, (m0, l0, acc0), (jnp.arange(n_blocks), kb, vb))
    return _finalize(m, l, acc, q.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU kernel.
# ---------------------------------------------------------------------------

try:  # Pallas is TPU-oriented; import lazily so CPU-only installs still work
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except ImportError:  # pragma: no cover
    _HAS_PALLAS = False


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scratch, l_scratch,
                  acc_scratch, *, sm_scale, causal, block_q, block_k,
                  num_k_blocks):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        m_scratch[...] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[...] = jnp.zeros_like(l_scratch)
        acc_scratch[...] = jnp.zeros_like(acc_scratch)

    q_start = qi * block_q
    k_start = ki * block_k
    # Causal pruning: skip key blocks entirely above the diagonal.
    run = True if not causal else k_start <= q_start + block_q - 1

    @pl.when(run)
    def _():
        q = q_ref[0]  # (block_q, d)
        k = k_ref[0]  # (block_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_scratch[:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        if causal:
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        l_new = l_scratch[:, 0] * alpha + p.sum(axis=-1)
        acc_scratch[...] = (
            acc_scratch[...] * alpha[:, None]
            + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
        m_scratch[...] = jnp.broadcast_to(m_new[:, None], m_scratch.shape)
        l_scratch[...] = jnp.broadcast_to(l_new[:, None], l_scratch.shape)

    @pl.when(ki == num_k_blocks - 1)
    def _():
        l = l_scratch[:, 0]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scratch[...] / safe_l[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    batch, heads, q_len, d = q.shape
    k_len = k.shape[2]
    block_q = min(block_q, q_len)
    block_k = min(block_k, k_len)
    if (q_len % block_q or k_len % block_k
            or block_q % 8 or block_k % 128):
        # Ragged tails or blocks off the TPU tiling grid (f32 sublane 8,
        # lane 128): the blockwise path handles them without padding
        # gymnastics (the kernel targets the aligned hot path).
        return blockwise_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    bh = batch * heads
    qr = q.reshape(bh, q_len, d)
    kr = k.reshape(bh, k_len, d)
    vr = v.reshape(bh, k_len, d)
    num_q = q_len // block_q
    num_k = k_len // block_k

    kernel = functools.partial(
        _flash_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, num_k_blocks=num_k)
    out = pl.pallas_call(
        kernel,
        grid=(bh, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, q_len, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running normalizer
            pltpu.VMEM((block_q, d), jnp.float32),    # output accumulator
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(batch, heads, q_len, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret)


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                         interpret)
    return out, (q, k, v)


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    # Recompute through the blockwise path (identical math): flash memory
    # savings in forward, lax.scan rematerialization in backward.
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: blockwise_attention(
            q, k, v, causal=causal, sm_scale=sm_scale,
            block_size=max(block_k, 128)), q, k, v)
    return vjp(g)


_flash_attention.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None):
    """Fused multi-head attention, ``(batch, heads, seq, head_dim)``.

    On TPU this is a Pallas kernel (MXU-tiled blocks, VMEM online-softmax
    state); elsewhere (and for ragged block tails) it falls back to the
    mathematically identical :func:`blockwise_attention`.  Differentiable;
    the VJP recomputes blockwise.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if not _HAS_PALLAS:
        return blockwise_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_attention(q, k, v, causal, sm_scale, block_q, block_k,
                            interpret)
