"""Fused attention: Pallas TPU kernel + differentiable blockwise fallback.

Layout convention: ``(batch, num_heads, seq, head_dim)`` throughout.

The Pallas kernel tiles queries and keys into MXU-sized blocks and keeps the
online-softmax state (running max, normalizer, accumulator) in VMEM scratch
across the key-block grid dimension, so attention needs O(block) on-chip
memory instead of materializing the (seq, seq) score matrix in HBM.

Both :func:`flash_attention` and :func:`blockwise_attention` use the
flash-attention backward algorithm (Dao et al., arXiv:2205.14135): the
forward saves only the output and the per-row logsumexp, and the backward
recomputes each key block's probabilities on the fly — O(seq) residual
memory, where differentiating *through* the forward scan would save every
block's probability matrix (O(seq^2 / block)).
"""

from __future__ import annotations

import functools
import os
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.utils.jax_compat import axis_size as _axis_size
from horovod_tpu.utils.jax_compat import tpu_compiler_params as _compiler_params
from horovod_tpu.utils.jax_compat import vma as _vma

NEG_INF = -1e30  # big-negative instead of -inf: keeps exp() NaN-free when a
# whole row is masked (fully-masked causal blocks)
POS_BIG = 1e30   # logsumexp sentinel for fully-masked rows: exp(s - POS_BIG)
# underflows to exactly 0 for any finite s


def mha_reference(q, k, v, causal: bool = False,
                  sm_scale: Optional[float] = None):
    """O(seq^2)-memory reference attention (for tests and tiny shapes)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    # precision="highest": on TPU the default matmul precision truncates f32
    # operands to bf16 passes; the reference must be at least as accurate as
    # the kernels it validates.
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   precision="highest").astype(jnp.float32) * sm_scale
    if causal:
        q_pos = jnp.arange(q.shape[2])[:, None]
        k_pos = jnp.arange(k.shape[2])[None, :]
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                      precision="highest")


# ---------------------------------------------------------------------------
# Blockwise attention: lax.scan online softmax.  Differentiable on any
# backend; the building block ring_attention reuses per ring step.
# ---------------------------------------------------------------------------


def _block_attend(q, k, v, m, l, acc, mask, sm_scale):
    """One online-softmax update of (m, l, acc) with a (q_len, k_len) block.

    ``mask`` is True where attention is allowed (or None for dense).
    Shapes: q (..., q_len, d), k/v (..., k_len, d); m/l (..., q_len);
    acc (..., q_len, d); all statistics in float32.
    """
    # preferred_element_type=f32: half-precision operands ride the MXU's
    # native passes while the accumulation (and, crucially, the backward
    # cotangents) stay float32 — a bf16 result here is both less accurate
    # and produces NaN gradients in the transposed scan on TPU.
    s = jnp.einsum("...qd,...kd->...qk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # Guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1.
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l_new = l * alpha + p.sum(axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "...qk,...kd->...qd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _finalize(m, l, acc, dtype):
    # Fully-masked rows have l == 0; emit zeros, not NaN.
    safe_l = jnp.where(l == 0.0, 1.0, l)
    return (acc / safe_l[..., None]).astype(dtype)


def _lse_of(m, l):
    """Per-row logsumexp; POS_BIG sentinel for fully-masked (l == 0) rows so
    the backward's exp(s - lse) is exactly 0 there."""
    return jnp.where(l == 0.0, POS_BIG, m + jnp.log(jnp.maximum(l, 1e-37)))


def _kv_blocks(k, v, block, n_blocks, pad):
    if pad:
        k = jnp.pad(k, [(0, 0)] * (k.ndim - 2) + [(0, pad), (0, 0)])
        v = jnp.pad(v, [(0, 0)] * (v.ndim - 2) + [(0, pad), (0, 0)])
    kb = k.reshape(*k.shape[:-2], n_blocks, block, k.shape[-1])
    vb = v.reshape(*v.shape[:-2], n_blocks, block, v.shape[-1])
    # scan over the block axis: move it to the front.
    return jnp.moveaxis(kb, -3, 0), jnp.moveaxis(vb, -3, 0)


def _block_mask(i, block, q_pos, k_offset, k_len, causal):
    k_pos = k_offset + i * block + jnp.arange(block)
    mask = (k_pos < k_offset + k_len)[None, :]  # padding rows
    if causal:
        mask = mask & (q_pos[:, None] >= k_pos[None, :])
    return mask


def _blockwise_fwd_impl(q, k, v, causal, sm_scale, block_size, q_offset,
                        k_offset):
    """Forward scan; returns (out, lse) with lse the per-row logsumexp."""
    q_len, k_len = q.shape[-2], k.shape[-2]
    block = min(block_size, k_len)
    n_blocks = (k_len + block - 1) // block
    kb, vb = _kv_blocks(k, v, block, n_blocks, n_blocks * block - k_len)
    q_pos = q_offset + jnp.arange(q_len)
    m0 = jnp.full(q.shape[:-1], NEG_INF, jnp.float32)
    l0 = jnp.zeros(q.shape[:-1], jnp.float32)
    acc0 = jnp.zeros(q.shape[:-2] + (q_len, q.shape[-1]), jnp.float32)

    def step(carry, inputs):
        m, l, acc = carry
        i, kblk, vblk = inputs
        mask = _block_mask(i, block, q_pos, k_offset, k_len, causal)
        m, l, acc = _block_attend(q, kblk, vblk, m, l, acc, mask, sm_scale)
        return (m, l, acc), None

    (m, l, acc), _ = lax.scan(
        step, (m0, l0, acc0), (jnp.arange(n_blocks), kb, vb))
    return _finalize(m, l, acc, q.dtype), _lse_of(m, l)


def _attention_bwd_impl(q, k, v, out, lse, g, causal, sm_scale, block_size,
                        q_offset, k_offset):
    """Flash-attention backward: recompute each key block's probabilities
    from (q, k, lse); residual memory O(seq)."""
    q_len, k_len = q.shape[-2], k.shape[-2]
    d = q.shape[-1]
    block = min(block_size, k_len)
    n_blocks = (k_len + block - 1) // block
    kb, vb = _kv_blocks(k, v, block, n_blocks, n_blocks * block - k_len)
    q_pos = q_offset + jnp.arange(q_len)
    g32 = g.astype(jnp.float32)
    # D_i = sum_j dOut_ij * Out_ij  (the softmax-jacobian diagonal term).
    D = jnp.sum(g32 * out.astype(jnp.float32), axis=-1)

    def step(dq, inputs):
        i, kblk, vblk = inputs
        s = jnp.einsum("...qd,...kd->...qk", q, kblk,
                       preferred_element_type=jnp.float32) * sm_scale
        mask = _block_mask(i, block, q_pos, k_offset, k_len, causal)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse[..., None])
        p = jnp.where(mask, p, 0.0)
        dv_blk = jnp.einsum("...qk,...qd->...kd", p, g32,
                            preferred_element_type=jnp.float32)
        dp = jnp.einsum("...qd,...kd->...qk", g32, vblk,
                        preferred_element_type=jnp.float32)
        ds = p * (dp - D[..., None]) * sm_scale
        dq = dq + jnp.einsum("...qk,...kd->...qd", ds, kblk,
                             preferred_element_type=jnp.float32)
        dk_blk = jnp.einsum("...qk,...qd->...kd", ds, q,
                            preferred_element_type=jnp.float32)
        return dq, (dk_blk, dv_blk)

    dq0 = jnp.zeros(q.shape[:-2] + (q_len, d), jnp.float32)
    dq, (dkb, dvb) = lax.scan(step, dq0, (jnp.arange(n_blocks), kb, vb))
    # (n_blocks, ..., block, d) -> (..., n_blocks*block, d) -> clip padding
    dk = jnp.moveaxis(dkb, 0, -3).reshape(*k.shape[:-2], n_blocks * block, d)
    dv = jnp.moveaxis(dvb, 0, -3).reshape(*v.shape[:-2], n_blocks * block, d)
    return (dq.astype(q.dtype), dk[..., :k_len, :].astype(k.dtype),
            dv[..., :k_len, :].astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _blockwise(q, k, v, causal, sm_scale, block_size, q_offset, k_offset):
    out, _ = _blockwise_fwd_impl(q, k, v, causal, sm_scale, block_size,
                                 q_offset, k_offset)
    return out


def _blockwise_fwd(q, k, v, causal, sm_scale, block_size, q_offset,
                   k_offset):
    out, lse = _blockwise_fwd_impl(q, k, v, causal, sm_scale, block_size,
                                   q_offset, k_offset)
    return out, (q, k, v, out, lse)


def _blockwise_bwd(causal, sm_scale, block_size, q_offset, k_offset, res, g):
    q, k, v, out, lse = res
    return _attention_bwd_impl(q, k, v, out, lse, g, causal, sm_scale,
                               block_size, q_offset, k_offset)


_blockwise.defvjp(_blockwise_fwd, _blockwise_bwd)


def blockwise_attention(q, k, v, causal: bool = False,
                        sm_scale: Optional[float] = None,
                        block_size: int = 512,
                        q_offset: int = 0, k_offset: int = 0):
    """Memory-efficient attention as a `lax.scan` over key/value blocks.

    ``q_offset``/``k_offset`` give the global sequence positions of the
    first query/key row — this is what lets :func:`ring_attention` apply a
    correct causal mask to rotated K/V shards.  O(seq) residual memory in
    both directions (flash backward).  Note: the flash backward is a
    `jax.custom_vjp`, so reverse-mode only; traced (non-static) offsets
    fall back to plain differentiation through the scan.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    try:
        q_offset, k_offset = int(q_offset), int(k_offset)
    except (TypeError, jax.errors.ConcretizationTypeError):
        # Traced offsets can't be custom_vjp static args; keep the plain
        # (through-scan) differentiable path for this corner.
        out, _ = _blockwise_fwd_impl(q, k, v, causal, sm_scale, block_size,
                                     q_offset, k_offset)
        return out
    return _blockwise(q, k, v, causal, sm_scale, block_size, q_offset,
                      k_offset)


# ---------------------------------------------------------------------------
# Pallas TPU kernel.
# ---------------------------------------------------------------------------

try:  # Pallas is TPU-oriented; import lazily so CPU-only installs still work
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except ImportError:  # pragma: no cover
    _HAS_PALLAS = False


def _rd(ref):
    """Read a block ref squeezing unit dims: (1, n, d) and (1, n, 1, d)
    (the bshd layout's head slot) both load as (n, d)."""
    x = ref[...]
    return x.reshape([s for s in x.shape if s != 1])


def _st(ref, val):
    ref[...] = val.reshape(ref.shape).astype(ref.dtype)


def _split_scale(sm_scale: float):
    """Split ``sm_scale`` into an exact power-of-two factor (applied to q
    in the storage dtype — exact even in bf16) and a float32 residual in
    [1, 2) applied to the logits inside the kernel.  For head dims that
    are powers of 4 (64, 256, ...) the residual is exactly 1.0 and the
    kernels skip the extra (block_q, block_k) pass entirely; other scales
    (head_dim 128, 96, ...) keep full f32 accuracy instead of rounding q
    to bf16 under a non-representable scale (ADVICE r4)."""
    import math

    m, e = math.frexp(sm_scale)  # sm_scale = m * 2**e, m in [0.5, 1)
    return 2.0 ** (e - 1), m * 2.0


def _attend_block(q_ref, k_ref, v_ref, m_scratch, l_scratch, acc_scratch,
                  q_start, k_start, causal, block_q, block_k,
                  single_k=False, scale_r=1.0):
    """One online-softmax block update of the VMEM (m, l, acc) state.

    Shared by the single-shard flash kernel and the fused ring-flash step
    (ops/ring_flash.py) — the only difference between them is where
    ``q_start``/``k_start`` come from (grid position vs scalar-prefetched
    absolute shard offsets).

    VPU economy (the kernel is elementwise-bound at head_dim 64 — the MXU
    finishes each block's two dots in ~1/3 of the time the softmax passes
    take): ``q`` arrives PRE-SCALED by the power-of-two part of sm_scale
    (one (seq, d) pass at the wrapper instead of a (seq, seq) pass here;
    ``scale_r`` is the f32 residual, exactly 1.0 for power-of-4 head
    dims — see :func:`_split_scale`); fully-masked rows are
    neutralized by clamping the softmax reference ``m_safe`` per ROW
    (block_q elements) instead of a second (block_q, block_k) ``where``
    on p — masked elements already underflow via exp(NEG_INF - m_safe);
    and ``single_k=True`` (one key block, the tuned whole-k layout) skips
    the online-rescale multiplies entirely."""
    q = _rd(q_ref)  # (block_q, d), pre-scaled by the pow2 part of sm_scale
    k = _rd(k_ref)  # (block_k, d)
    v = _rd(v_ref)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if scale_r != 1.0:
        s *= scale_r
    if causal:
        q_pos = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    if single_k:
        m_new = s.max(axis=-1)
        m_safe = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(s - m_safe[:, None])
        l_new = p.sum(axis=-1)
        acc_scratch[...] = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        m_prev = m_scratch[:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        # m_safe keeps fully-masked rows at zero mass: exp(NEG_INF - 0)
        # underflows to 0 for every element AND for alpha (m_prev is
        # NEG_INF too), so no (block_q, block_k) re-mask of p is needed.
        m_safe = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        alpha = jnp.exp(m_prev - m_safe)
        p = jnp.exp(s - m_safe[:, None])
        l_new = l_scratch[:, 0] * alpha + p.sum(axis=-1)
        acc_scratch[...] = (
            acc_scratch[...] * alpha[:, None]
            + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
    m_scratch[...] = jnp.broadcast_to(m_new[:, None], m_scratch.shape)
    l_scratch[...] = jnp.broadcast_to(l_new[:, None], l_scratch.shape)


def _init_state(m_scratch, l_scratch, acc_scratch):
    m_scratch[...] = jnp.full_like(m_scratch, NEG_INF)
    l_scratch[...] = jnp.zeros_like(l_scratch)
    acc_scratch[...] = jnp.zeros_like(acc_scratch)


def _finalize_flash(o_ref, lse_ref, m_scratch, l_scratch, acc_scratch,
                    block_q):
    l = l_scratch[:, 0]
    safe_l = jnp.where(l == 0.0, 1.0, l)
    _st(o_ref, acc_scratch[...] / safe_l[:, None])
    # 8 identical sublanes: a (1, block_q) block would violate the TPU
    # (8, 128) output tiling.
    lse_ref[...] = jnp.broadcast_to(
        _lse_of(m_scratch[:, 0], l)[None, :], (8, block_q)).reshape(
        lse_ref.shape)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scratch, l_scratch,
                  acc_scratch, *, causal, block_q, block_k, num_k_blocks,
                  scale_r=1.0):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    single_k = num_k_blocks == 1

    if not single_k:
        @pl.when(ki == 0)
        def _():
            _init_state(m_scratch, l_scratch, acc_scratch)

    q_start = qi * block_q
    k_start = ki * block_k
    # Causal pruning: skip key blocks entirely above the diagonal.
    run = True if not causal else k_start <= q_start + block_q - 1

    @pl.when(run)
    def _():
        _attend_block(q_ref, k_ref, v_ref, m_scratch, l_scratch,
                      acc_scratch, q_start, k_start, causal,
                      block_q, block_k, single_k=single_k,
                      scale_r=scale_r)

    @pl.when(ki == num_k_blocks - 1)
    def _():
        _finalize_flash(o_ref, lse_ref, m_scratch, l_scratch, acc_scratch,
                        block_q)


def _bwd_block_math(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                    causal, q_start, k_start, block_q, block_k, scale_r):
    """Shared flash-backward block recompute (Dao et al. alg. 2 inner
    body), used by the combined kernel, both split kernels, and the fused
    ring backward (ops/ring_flash.py).

    ``q`` arrives pre-scaled by the power-of-two part of sm_scale;
    ``scale_r`` is the f32 residual (see :func:`_split_scale`), applied
    once to s (matching the forward's pre-activation) and once to ds —
    ds_r = r * dL/ds — so dk = ds_r^T q' and dq' = ds_r k are exact in
    q' units (the wrapper rescales dq by the pow2 factor once).

    Returns ``(pb, ds, q, do, k)``: the probability block cast to v's
    dtype (for dv += pb^T do), the scaled ds block cast to q's dtype
    (for dk/dq dots), and the loaded q/do/k blocks — returned so callers
    don't re-read the refs (a second ``_rd`` costs extra scoped VMEM)."""
    q = _rd(q_ref)          # (block_q, d), pre-scaled (pow2 part)
    do = _rd(do_ref)        # (block_q, d)
    lse = _rd(lse_ref)[0]   # (block_q,)
    delta = _rd(delta_ref)[0]
    k = _rd(k_ref)          # (block_k, d)
    v = _rd(v_ref)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if scale_r != 1.0:
        s *= scale_r
    if causal:
        q_pos = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    p = jnp.exp(s - lse[:, None])  # POS_BIG lse zeroes masked rows
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None])
    if scale_r != 1.0:
        ds *= scale_r
    return p.astype(v.dtype), ds.astype(q.dtype), q, do, k


def _flash_bwd_dkdv_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                           dk_ref, dv_ref, dk_scratch, dv_scratch, *,
                           causal, block_q, block_k, num_q_blocks, scale_r):
    """Split backward, dk/dv half: O(block) scoped memory — the long-seq
    path where the combined kernel's whole-seq dq scratch exceeds the
    chip's scoped-VMEM ceiling (see _bwd_plan)."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)  # innermost: accumulates over query blocks

    @pl.when(qi == 0)
    def _():
        dk_scratch[...] = jnp.zeros_like(dk_scratch)
        dv_scratch[...] = jnp.zeros_like(dv_scratch)

    q_start = qi * block_q
    k_start = ki * block_k
    run = True if not causal else q_start + block_q - 1 >= k_start

    @pl.when(run)
    def _():
        pb, ds, q, do, _k = _bwd_block_math(
            q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, causal,
            q_start, k_start, block_q, block_k, scale_r)
        dv_scratch[...] += jax.lax.dot_general(
            pb, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scratch[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_q_blocks - 1)
    def _():
        _st(dk_ref, dk_scratch[...])
        _st(dv_ref, dv_scratch[...])


def _flash_bwd_dq_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                         dq_ref, dq_scratch, *, causal, block_q,
                         block_k, num_k_blocks, scale_r, dq_scale=1.0):
    """Split backward, dq half: accumulates one query block over the key
    loop — O(block) scoped memory (long-seq path, see _bwd_plan)."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)  # innermost: accumulates over key blocks

    @pl.when(ki == 0)
    def _():
        dq_scratch[...] = jnp.zeros_like(dq_scratch)

    q_start = qi * block_q
    k_start = ki * block_k
    run = True if not causal else q_start + block_q - 1 >= k_start

    @pl.when(run)
    def _():
        _pb, ds, _q, _do, k = _bwd_block_math(
            q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, causal,
            q_start, k_start, block_q, block_k, scale_r)
        dq_scratch[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_k_blocks - 1)
    def _():
        # pow2 rescale folded into the f32 flush (see the combined
        # kernel's _flush_dq note).
        _st(dq_ref, dq_scratch[...] * dq_scale if dq_scale != 1.0
            else dq_scratch[...])


def _combined_bwd_kernel(*refs, causal, block_q, block_k, num_q_blocks,
                         num_k_blocks, bh, rotate, barrier, axis_name,
                         mesh_axes, scale_r, dq_scale=1.0):
    """Flash backward with dk/dv AND dq from ONE probability recompute.

    Grid: (bh, ki, qi) — queries innermost so dk/dv accumulate in scratch
    and flush per key block; dq accumulates in a whole-sequence VMEM
    scratch and flushes once per bh row.  The split dkdv/dq kernel pair
    pays the s/p/dp/ds recompute twice; sharing it here nearly halves the
    backward's kernel time (measured on v5e, docs/benchmarks.md r4).

    With ``rotate=True`` this is the fused ring-flash backward step
    (ops/ring_flash.py): the K/V rotation DMA to the right neighbour
    starts at the first grid step, flies under the gradient compute, and
    is waited at the last.  ``offsets_ref`` carries the absolute
    [q_offset, k_offset] for causal masking across shards (zeros for the
    single-shard case).  ``q`` arrives pre-scaled by the pow2 part of
    sm_scale; dq is emitted in q' units (callers rescale once).
    """
    if rotate:
        (offsets_ref, q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
         k_full, v_full, dk_ref, dv_ref, dq_ref, k_next, v_next,
         dk_scratch, dv_scratch, dq_scratch, sems) = refs
    else:
        (offsets_ref, q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
         dk_ref, dv_ref, dq_ref,
         dk_scratch, dv_scratch, dq_scratch) = refs
    b = pl.program_id(0)
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    if rotate:
        from horovod_tpu.ops.rdma import _device_id

        my = jax.lax.axis_index(axis_name)
        n = _axis_size(axis_name)
        dst, id_type = _device_id(jax.lax.rem(my + 1, n), axis_name,
                                  mesh_axes)
        src, _ = _device_id(jax.lax.rem(my - 1 + n, n), axis_name,
                            mesh_axes)

        @pl.when((b == 0) & (ki == 0) & (qi == 0))
        def _start_rotation():
            if barrier:
                bar = pltpu.get_barrier_semaphore()
                pltpu.semaphore_signal(
                    bar, inc=1, device_id=src, device_id_type=id_type)
                pltpu.semaphore_wait(bar, 1)
            pltpu.make_async_remote_copy(
                src_ref=k_full, dst_ref=k_next, send_sem=sems.at[0],
                recv_sem=sems.at[1], device_id=dst,
                device_id_type=id_type).start()
            pltpu.make_async_remote_copy(
                src_ref=v_full, dst_ref=v_next, send_sem=sems.at[2],
                recv_sem=sems.at[3], device_id=dst,
                device_id_type=id_type).start()

    @pl.when((ki == 0) & (qi == 0))
    def _zero_dq():
        dq_scratch[...] = jnp.zeros_like(dq_scratch)

    @pl.when(qi == 0)
    def _zero_dkdv():
        dk_scratch[...] = jnp.zeros_like(dk_scratch)
        dv_scratch[...] = jnp.zeros_like(dv_scratch)

    if causal:
        q_start = offsets_ref[0] + qi * block_q  # absolute positions
        k_start = offsets_ref[1] + ki * block_k
        run = q_start + block_q - 1 >= k_start
    else:
        q_start = k_start = 0
        run = True

    @pl.when(run)
    def _():
        pb, ds, q, do, k = _bwd_block_math(
            q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, causal,
            q_start, k_start, block_q, block_k, scale_r)
        dv_scratch[...] += jax.lax.dot_general(
            pb, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scratch[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        row = pl.ds(qi * block_q, block_q)
        dq_scratch[row, :] = dq_scratch[row, :] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_q_blocks - 1)
    def _flush_dkdv():
        # _st casts: the scratch accumulates in f32, the output dtype is
        # the caller's grad_dtype (input dtype for the single-shard path
        # — saving an XLA-side cast+relayout pass over each gradient —
        # f32 for the ring path, whose partials keep accumulating).
        _st(dk_ref, dk_scratch[...])
        _st(dv_ref, dv_scratch[...])

    @pl.when((ki == num_k_blocks - 1) & (qi == num_q_blocks - 1))
    def _flush_dq():
        # dq accumulated in q' units; the pow2 rescale folds into the
        # flush IN F32, before the grad_dtype cast — no extra XLA pass
        # over dq, and no overflow for narrow-exponent dtypes (fp16).
        # Ring callers keep dq_scale=1.0 (partials sum across steps
        # first) and rescale once outside.
        _st(dq_ref, dq_scratch[...] * dq_scale if dq_scale != 1.0
            else dq_scratch[...])

    if rotate:
        @pl.when((b == bh - 1) & (ki == num_k_blocks - 1)
                 & (qi == num_q_blocks - 1))
        def _finish_rotation():
            pltpu.make_async_remote_copy(
                src_ref=k_full, dst_ref=k_next, send_sem=sems.at[0],
                recv_sem=sems.at[1], device_id=dst,
                device_id_type=id_type).wait()
            pltpu.make_async_remote_copy(
                src_ref=v_full, dst_ref=v_next, send_sem=sems.at[2],
                recv_sem=sems.at[3], device_id=dst,
                device_id_type=id_type).wait()


def _combined_bwd_call(q, do, lse8, delta8, k_cur, v_cur, q_offset,
                       k_offset, *, causal, block_q, block_k, rotate,
                       collective_id, axis_name, mesh_axes, interpret,
                       scale_r=1.0, grad_dtype=jnp.float32, dq_scale=1.0):
    """pallas_call wrapper for `_combined_bwd_kernel` over (bh, sl, d)
    operands (q pre-scaled by the pow2 part of sm_scale).  Returns
    (dk, dv, dq[, k_next, v_next]) with the gradients in ``grad_dtype``
    (accumulation is always f32 in scratch; only the flush casts, after
    applying ``dq_scale`` to dq in f32)."""
    bh, sl, d = q.shape
    num_q, num_k = sl // block_q, sl // block_k
    offsets = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                         jnp.asarray(k_offset, jnp.int32)])

    kernel = functools.partial(
        _combined_bwd_kernel, causal=causal, block_q=block_q,
        block_k=block_k, num_q_blocks=num_q, num_k_blocks=num_k, bh=bh,
        rotate=rotate, barrier=rotate and not interpret,
        axis_name=axis_name, mesh_axes=mesh_axes, scale_r=scale_r,
        dq_scale=dq_scale)

    def qspec(row):
        return pl.BlockSpec((1, block_q, d),
                            lambda b, ki, qi, s, _r=row: (b, _r(qi, ki), 0))

    def kspec(row):
        return pl.BlockSpec((1, block_k, d),
                            lambda b, ki, qi, s, _r=row: (b, _r(qi, ki), 0))

    inner_q = lambda qi, ki: qi  # noqa: E731
    outer_k = lambda qi, ki: ki  # noqa: E731
    in_specs = [
        qspec(inner_q),                                    # q
        qspec(inner_q),                                    # do
        pl.BlockSpec((1, 8, block_q), lambda b, ki, qi, s: (b, 0, qi)),
        pl.BlockSpec((1, 8, block_q), lambda b, ki, qi, s: (b, 0, qi)),
        kspec(outer_k),                                    # k (blocked)
        kspec(outer_k),                                    # v (blocked)
    ]
    out_shapes = [
        jax.ShapeDtypeStruct((bh, sl, d), grad_dtype),     # dk
        jax.ShapeDtypeStruct((bh, sl, d), grad_dtype),     # dv
        jax.ShapeDtypeStruct((bh, sl, d), grad_dtype),     # dq
    ]
    out_specs = [
        kspec(outer_k),                                    # dk
        kspec(outer_k),                                    # dv
        pl.BlockSpec((1, sl, d), lambda b, ki, qi, s: (b, 0, 0)),  # dq
    ]
    scratch_shapes = [
        pltpu.VMEM((block_k, d), jnp.float32),             # dk accumulator
        pltpu.VMEM((block_k, d), jnp.float32),             # dv accumulator
        pltpu.VMEM((sl, d), jnp.float32),                  # whole-seq dq
    ]
    args = [offsets, q, do, lse8, delta8, k_cur, v_cur]
    if rotate:
        in_specs += [
            pl.BlockSpec(memory_space=pl.ANY),             # k (DMA src)
            pl.BlockSpec(memory_space=pl.ANY),             # v (DMA src)
        ]
        out_shapes += [
            jax.ShapeDtypeStruct(k_cur.shape, k_cur.dtype),  # k_next
            jax.ShapeDtypeStruct(v_cur.shape, v_cur.dtype),  # v_next
        ]
        out_specs += [
            pl.BlockSpec(memory_space=pl.ANY),             # k_next
            pl.BlockSpec(memory_space=pl.ANY),             # v_next
        ]
        scratch_shapes += [pltpu.SemaphoreType.DMA((4,))]
        args += [k_cur, v_cur]
    vma = _vma(q)
    if vma is not None:
        out_shapes = [jax.ShapeDtypeStruct(s.shape, s.dtype, vma=vma)
                      for s in out_shapes]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, num_k, num_q),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch_shapes,
    )
    compiler_params = _compiler_params(
        collective_id=(collective_id if rotate and not interpret
                       else None),
        has_side_effects=rotate)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shapes,
        compiler_params=compiler_params,
        interpret=interpret,
    )(*args)


def _row_spec(block, d):
    """BlockSpec factory for (batch*heads, seq, d) tensors: ``row`` picks
    which grid dim walks the sequence.

    (A strided (1, block, 1, d) spec reading (b, s, h, d) directly would
    skip the host-side transposes, but Mosaic requires the second-minor
    block dim to be a multiple of 8 or the full array dim — a 1-wide head
    slot is not lowerable, so the bshd layout transposes at the wrapper
    instead; see flash_attention.)"""
    def spec(row):
        return pl.BlockSpec((1, block, d),
                            lambda b, i, j, _r=row: (b, _r(i, j), 0))

    return spec


def _pick_block(seq_len: int, maximum: int = 512) -> int:
    """Largest kernel-grid block <= maximum that divides the sequence:
    keeps common non-512-multiple lengths (640, 768, 1152, ...) on the
    Pallas kernel instead of silently demoting them to the blockwise
    fallback."""
    for b in (1024, 768, 512, 384, 256, 128):
        if b <= maximum and seq_len % b == 0:
            return b
    return min(maximum, seq_len)  # ragged: the fallback path handles it


def _vmem_budget_bytes() -> int:
    """Scoped-VMEM planning budget, bytes.  Default 16 MiB — the v5e
    scoped-allocation ceiling the r5 sweep calibrated against;
    ``HVD_TPU_VMEM_LIMIT_MB`` overrides it for chips with different
    scoped capacity (or to leave headroom under other scoped users)."""
    return int(float(os.environ.get("HVD_TPU_VMEM_LIMIT_MB") or 16.0)
               * (1 << 20))


def _plan_vmem_bytes(mode: str, q_len: int, d: int, block_q: int,
                     block_k: int) -> int:
    """Conservative scoped-VMEM estimate for a backward plan, bytes.

    Mosaic's real allocation is not a closed form (see _bwd_plan), so
    this models the structural upper bound: every revolving block window
    double-buffered at f32 width with head_dim padded to the 128-lane
    tile, the combined kernel's whole-seq dq charged three ways (scratch
    + a double-buffered output window — the term whose growth is exactly
    the BENCH_r04 seq-8192 OOM).  Calibrated against the r5 sweep: every
    measured-pass band lands under 16 MiB here and the measured 23.2 MiB
    seq-8192/1024-block failure lands over, so clamping to this estimate
    can only reject plans the frontier also rejects."""
    lanes = max(d, 128)
    w, db = 4, 2              # f32 worst case; double-buffered windows
    lse = db * w * 8 * 2 * block_q          # lse8 + delta8 windows
    if mode == "combined":
        wins = db * w * lanes * (2 * block_q + 2 * block_k  # q,do,k,v in
                                 + 2 * block_k)             # dk,dv out
        dq = (db + 1) * w * lanes * q_len   # whole-seq out window + scratch
        scratch = w * lanes * 2 * block_k   # dk/dv accumulators
        return wins + lse + dq + scratch
    # Split kernels run back to back; scoped peak is the larger one.
    dkdv = (db * w * lanes * (2 * block_q + 4 * block_k) + lse
            + w * lanes * 2 * block_k)
    dqk = (db * w * lanes * (3 * block_q + 2 * block_k) + lse
           + w * lanes * block_q)
    return max(dkdv, dqk)


def _fwd_vmem_bytes(q_len: int, d: int, block_q: int,
                    block_k: int) -> int:
    """Same structural estimate for the forward kernel (q in + out + k/v
    windows, lse output, online-softmax scratch)."""
    lanes = max(d, 128)
    w, db = 4, 2
    return (db * w * lanes * (2 * block_q + 2 * block_k)
            + db * w * 8 * block_q                       # lse out
            + w * block_q * (2 * 128 + lanes))           # m/l/acc scratch


def _clamp_blocks(mode: str, q_len: int, d: int, block_q: int,
                  block_k: int, estimate=_plan_vmem_bytes):
    """Step a plan's blocks down until ``estimate`` fits the budget.
    Returns the fitted (block_q, block_k), or None when even 128-blocks
    cannot fit (combined's whole-seq dq term: the caller demotes to
    split).  Warns when it changes the requested plan — a clamp means
    the tuned choice would have been the r04 compile-time OOM."""
    budget = _vmem_budget_bytes()
    bq, bk = block_q, block_k
    while estimate(mode, q_len, d, bq, bk) > budget:
        if bq >= bk and bq > 128:
            bq = _pick_block(q_len, bq // 2)
        elif bk > 128:
            bk = _pick_block(q_len, bk // 2)
        elif mode == "combined":
            return None
        else:
            break  # nothing below 128-blocks; the grid is as small as it gets
    if (bq, bk) != (block_q, block_k):
        warnings.warn(
            f"attention {mode} blocks ({block_q}, {block_k}) at "
            f"seq {q_len}/head_dim {d} exceed the scoped-VMEM budget "
            f"({budget >> 20} MiB, HVD_TPU_VMEM_LIMIT_MB); clamped to "
            f"({bq}, {bk})", stacklevel=3)
    return bq, bk


def _bwd_plan(q_len: int, d: int, block_q: int, block_k: int,
              bh: int = 1):
    """Choose the flash-backward execution mode and blocks against the
    chip's 16 MiB scoped-VMEM ceiling.

    Calibrated by on-chip compile sweep, v5e r5 (tools/vmem_sweep.py;
    docs/benchmarks.md).  Mosaic's scoped allocation for the combined
    kernel is NOT a simple closed form — it grows with the whole-seq dq
    scratch (head_dim <= 128 pads to 128 lanes, so sequence length
    enters as ``q_len * max(d, 128)``), with block size, and
    NON-MONOTONICALLY with the batch*heads grid dimension (measured:
    seq 8192 at 1024-blocks is 23.2 MiB at bh=16 but 16.5 MiB at
    bh=32; seq 8192 at 512-blocks fits at bh<=32 and exceeds by 0.17
    MiB at bh=64) — so the bands below come from the measured pass/fail
    frontier with margin, not a model:

    The combined kernel is restricted to head_dim <= 128 outright: wide
    heads fail at shapes whose 128-lane equivalents fit (measured d=256:
    17.9 MiB at seq 1024/bh 64 with 1024-blocks, 18.8 MiB at seq
    2048/bh 64 with (512, 1024) — where d=64 passes both at bh up to
    1024), and the sweep has no wide-head pass region worth the risk.
    For d <= 128 (lane-padded, so seq enters as q_len*max(d,128)/128):

    =====================  ==========  =============================
    q_len*max(d,128)/128   bh          choice
    =====================  ==========  =============================
    <= 2048                any(<=1024)  combined, tuned blocks (1024)
    <= 4096                any(<=512)   combined (512, 1024)
    <= 8192                <= 32        combined (512, 512)
    otherwise              any          split, tuned blocks (1024)
    =====================  ==========  =============================

    ``mode`` is ``"combined"`` (one probability recompute per block,
    whole-seq dq scratch — fastest where it fits: measured ~15% over
    split at seq 8192) or ``"split"`` (dkdv + dq kernel pair, O(block)
    scoped memory: full 1024-blocks compile at every probed extreme —
    seq to 64k, bh to 256, d to 256 — and beat 512-blocks by ~12% at
    seq 16k)."""
    rows128 = q_len * max(d, 128) // 128
    if d <= 128:
        # Each band is gated at its CALIBRATED bh bound (the table
        # above); anything beyond falls through to split, which
        # compiles everywhere — never extrapolate the combined kernel
        # past a probed region (the r4 lesson).  The band choice is then
        # backstopped against the COMPUTED budget (_plan_vmem_bytes):
        # a shrunken HVD_TPU_VMEM_LIMIT_MB, or a band edge the sweep's
        # granularity missed, clamps blocks down (warning) or demotes to
        # split instead of handing Mosaic a plan that cannot compile.
        choice = None
        if rows128 <= 2048 and bh <= 1024:
            choice = (block_q, block_k)
        elif rows128 <= 4096 and bh <= 512:
            choice = (_pick_block(q_len, min(block_q, 512)),
                      _pick_block(q_len, min(block_k, 1024)))
        elif rows128 <= 8192 and bh <= 32:
            choice = (_pick_block(q_len, min(block_q, 512)),
                      _pick_block(q_len, min(block_k, 512)))
        if choice is not None:
            fitted = _clamp_blocks("combined", q_len, d, *choice)
            if fitted is not None:
                return ("combined",) + fitted
            warnings.warn(
                f"combined attention backward at seq {q_len}/head_dim "
                f"{d} cannot fit the scoped-VMEM budget "
                f"({_vmem_budget_bytes() >> 20} MiB) at any block size "
                "(whole-seq dq scratch); demoting to the split kernels",
                stacklevel=2)
    fitted = _clamp_blocks("split", q_len, d, _pick_block(q_len, block_q),
                           _pick_block(q_len, block_k))
    return ("split",) + fitted


def _split_bwd_call(q, do, lse8, delta8, k, v, *, causal, block_q,
                    block_k, interpret, scale_r, grad_dtype=jnp.float32,
                    dq_scale=1.0):
    """Split flash backward over (bh, sl, d) operands (q pre-scaled by
    the pow2 part of sm_scale): two pallas_calls — dk/dv (queries inner)
    and dq (keys inner) — each with O(block) scoped VMEM, so any
    sequence length compiles.  Pays the s/p/dp/ds recompute twice; the
    combined kernel is preferred whenever its whole-seq dq scratch fits
    (see _bwd_plan).  Returns (dk, dv, dq) in ``grad_dtype`` (f32
    accumulation in scratch; the flush casts)."""
    bh, sl, d = q.shape
    num_q, num_k = sl // block_q, sl // block_k
    qspec, kspec = _row_spec(block_q, d), _row_spec(block_k, d)

    def lse_spec(row):
        return pl.BlockSpec((1, 8, block_q), lambda b, i, j, _r=row:
                            (b, 0, _r(i, j)))

    inner = lambda i, j: j  # noqa: E731  (innermost grid dim)
    outer = lambda i, j: i  # noqa: E731
    dkdv = functools.partial(
        _flash_bwd_dkdv_kernel, causal=causal, block_q=block_q,
        block_k=block_k, num_q_blocks=num_q, scale_r=scale_r)
    dk, dv = pl.pallas_call(
        dkdv,
        grid=(bh, num_k, num_q),  # queries innermost
        in_specs=[qspec(inner), qspec(inner), lse_spec(inner),
                  lse_spec(inner), kspec(outer), kspec(outer)],
        out_specs=(kspec(outer), kspec(outer)),
        out_shape=(jax.ShapeDtypeStruct((bh, sl, d), grad_dtype),
                   jax.ShapeDtypeStruct((bh, sl, d), grad_dtype)),
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(q, do, lse8, delta8, k, v)
    dqk = functools.partial(
        _flash_bwd_dq_kernel, causal=causal, block_q=block_q,
        block_k=block_k, num_k_blocks=num_k, scale_r=scale_r,
        dq_scale=dq_scale)
    dq = pl.pallas_call(
        dqk,
        grid=(bh, num_q, num_k),  # keys innermost
        in_specs=[qspec(outer), qspec(outer), lse_spec(outer),
                  lse_spec(outer), kspec(inner), kspec(inner)],
        out_specs=qspec(outer),
        out_shape=jax.ShapeDtypeStruct((bh, sl, d), grad_dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, do, lse8, delta8, k, v)
    return dk, dv, dq


def _flash_backward(q, k, v, out, lse, g, causal, sm_scale, block_q,
                    block_k, interpret):
    """Pallas flash backward.  Two kernel strategies, chosen per shape by
    :func:`_bwd_plan` against the scoped-VMEM ceiling: the combined
    kernel computes dk/dv AND dq from a single probability recompute per
    block (whole-seq dq scratch), the split dkdv/dq pair recomputes twice
    but needs only O(block) scoped memory (long sequences).  Residual
    memory is O(seq) either way (Dao et al. alg. 2)."""
    batch, heads, q_len, d = q.shape
    k_len = k.shape[2]
    block_q = min(block_q, q_len)
    block_k = min(block_k, k_len)
    if (q_len % block_q or k_len % block_k
            or block_q % 128 or block_k % 128 or q_len != k_len):
        return _attention_bwd_impl(q, k, v, out, lse, g, causal, sm_scale,
                                   max(block_k, 128), 0, 0)
    mode, block_q, block_k = _bwd_plan(q_len, d, block_q, block_k,
                                       batch * heads)
    if q_len % block_q or k_len % block_k or block_q % 128 or block_k % 128:
        # Plan stepped blocks down past what divides this length (rare
        # non-power-of-two long seqs): the scan impl handles it.
        return _attention_bwd_impl(q, k, v, out, lse, g, causal, sm_scale,
                                   max(block_k, 128), 0, 0)
    bh = batch * heads
    # Pre-scaled q (see _flash_forward): exact pow2 factor on q, f32
    # residual inside the kernel; dq comes back in q' units and is
    # rescaled once below.
    p2, scale_r = _split_scale(sm_scale)
    qr = (q * p2).astype(q.dtype).reshape(bh, q_len, d)
    kr = k.reshape(bh, k_len, d)
    vr = v.reshape(bh, k_len, d)
    dor = g.reshape(bh, q_len, d)
    # delta_i = sum_d dOut_id * Out_id; 8 broadcast sublanes keep the
    # (8, 128) tiling legal, same trick as the forward's lse output.
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(bh, q_len)
    delta8 = jnp.broadcast_to(delta[:, None, :], (bh, 8, q_len))
    lse8 = jnp.broadcast_to(lse.reshape(bh, q_len)[:, None, :],
                            (bh, 8, q_len))
    # Gradients emitted directly in the input dtype, with the pow2 dq
    # rescale folded into the kernels' f32 flush: the XLA-side
    # cast+relayout and rescale passes over the 3 gradients measured
    # ~100 us/layer of pure copy time in the seq-1024 LM step.  The
    # f32-multiply-then-cast order also keeps narrow-exponent dtypes
    # (fp16) finite where cast-then-scale could overflow in q' units.
    # Mixed input dtypes keep the old f32 emission (dk must not round
    # through q.dtype when k is wider).
    same_dtype = q.dtype == k.dtype == v.dtype
    grad_dtype = q.dtype if same_dtype else jnp.float32
    if mode == "combined":
        dk, dv, dq = _combined_bwd_call(
            qr, dor, lse8, delta8, kr, vr, 0, 0, causal=causal,
            block_q=block_q, block_k=block_k, rotate=False,
            collective_id=None, axis_name=None, mesh_axes=(),
            interpret=interpret, scale_r=scale_r, grad_dtype=grad_dtype,
            dq_scale=p2)
    else:
        dk, dv, dq = _split_bwd_call(
            qr, dor, lse8, delta8, kr, vr, causal=causal,
            block_q=block_q, block_k=block_k, interpret=interpret,
            scale_r=scale_r, grad_dtype=grad_dtype, dq_scale=p2)
    return (dq.astype(q.dtype).reshape(q.shape),
            dk.astype(k.dtype).reshape(k.shape),
            dv.astype(v.dtype).reshape(v.shape))


def _flash_forward(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    """Returns (out, lse); routes off-grid shapes to the blockwise impl."""
    batch, heads, q_len, d = q.shape
    k_len = k.shape[2]
    block_q = min(block_q, q_len)
    block_k = min(block_k, k_len)
    if (q_len % block_q or k_len % block_k
            or block_q % 128 or block_k % 128):
        # Ragged tails or blocks off the TPU tiling grid (the lse output
        # block puts block_q in the 128-lane dimension): the blockwise path
        # handles them without padding gymnastics (the kernel targets the
        # aligned hot path).
        return _blockwise_fwd_impl(q, k, v, causal, sm_scale,
                                   max(block_k, 128), 0, 0)
    # Backstop explicit oversized blocks against the scoped-VMEM budget
    # (the default <=1024 blocks peak ~6 MiB and never clamp).
    block_q, block_k = _clamp_blocks(
        "forward", q_len, d, block_q, block_k,
        estimate=lambda _m, s, dd, bq, bk: _fwd_vmem_bytes(s, dd, bq, bk))
    bh = batch * heads
    # Pre-scale q by the exact power-of-two part of sm_scale: one
    # (seq, d) multiply here replaces a (seq, seq) pass inside the
    # kernel; the f32 residual (1.0 for power-of-4 head dims) is applied
    # to the logits in-kernel, so non-pow2 scales lose no precision.
    p2, scale_r = _split_scale(sm_scale)
    qr = (q * p2).astype(q.dtype).reshape(bh, q_len, d)
    kr = k.reshape(bh, k_len, d)
    vr = v.reshape(bh, k_len, d)
    o_shape = jax.ShapeDtypeStruct((bh, q_len, d), q.dtype)
    num_q = q_len // block_q
    num_k = k_len // block_k
    qspec, kspec = _row_spec(block_q, d), _row_spec(block_k, d)
    qrow = lambda i, j: i  # noqa: E731
    krow = lambda i, j: j  # noqa: E731

    kernel = functools.partial(
        _flash_kernel, causal=causal, block_q=block_q,
        block_k=block_k, num_k_blocks=num_k, scale_r=scale_r)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, num_q, num_k),
        in_specs=[qspec(qrow), kspec(krow), kspec(krow)],
        out_specs=(
            qspec(qrow),
            pl.BlockSpec((1, 8, block_q), lambda b, qi, ki: (b, 0, qi)),
        ),
        out_shape=(
            o_shape,
            jax.ShapeDtypeStruct((bh, 8, q_len), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running normalizer
            pltpu.VMEM((block_q, d), jnp.float32),    # output accumulator
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return (out.reshape(batch, heads, q_len, d),
            lse[:, 0, :].reshape(batch, heads, q_len))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret)[0]


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                              interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, g, causal, sm_scale, block_q,
                           block_k, interpret)


_flash_attention.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    layout: str = "bhsd"):
    """Fused multi-head attention.

    ``layout="bhsd"`` takes ``(batch, heads, seq, head_dim)``;
    ``layout="bshd"`` accepts ``(batch, seq, heads, head_dim)`` — the
    shape QKV projections naturally produce — and returns the same layout.
    (Internally bshd transposes to bhsd: Mosaic's block tiling cannot
    address a 1-wide head slot, so a transpose-free strided read is not
    lowerable; the option exists so callers never have to think about
    head-major conventions.)

    On TPU this is a Pallas kernel (MXU-tiled blocks, VMEM online-softmax
    state); elsewhere (and for ragged block tails) it falls back to the
    mathematically identical :func:`blockwise_attention`.  Differentiable
    with the flash backward (logsumexp residual + per-block recompute,
    O(seq) memory).  Default blocks: up to 1024 each, the largest
    candidate dividing the sequence — measured on v5e at seq 1024,
    1024-row query blocks beat 512 by ~5% fwd+bwd (grid overhead
    amortizes) and whole-k key blocks skip the online-softmax rescale
    (the kernel's single_k path).  The BACKWARD re-plans blocks per
    shape against the 16 MiB scoped-VMEM ceiling and switches to the
    split dkdv/dq kernel pair for long sequences (see :func:`_bwd_plan`
    — the r4 regression was exactly a tuned-block choice that did not
    compile at seq 8192).
    """
    if layout not in ("bhsd", "bshd"):
        raise ValueError(f"unknown layout {layout!r}")
    if layout == "bshd":
        t = lambda a: a.transpose(0, 2, 1, 3)  # noqa: E731
        return t(flash_attention(t(q), t(k), t(v), causal=causal,
                                 sm_scale=sm_scale, block_q=block_q,
                                 block_k=block_k, interpret=interpret))
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if not _HAS_PALLAS:
        return blockwise_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not interpret and jnp.float16 in (q.dtype, k.dtype, v.dtype):
        # float16 is not a native TPU type and Mosaic refuses the kernel
        # outright (verified on v5e: even the forward fails to compile) —
        # route to the mathematically identical scan implementation
        # instead of crashing at compile time.  bf16 is the supported
        # half-precision on TPU.
        return blockwise_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    if block_q is None:
        # 1024-row query blocks: the kernels are grid-overhead-bound at
        # these shapes (~3-5 us of fixed cost per grid step against ~1.4
        # us of MXU work), so halving the grid beats smaller tiles —
        # measured r4 at seq 1024: fwd 965 -> 687 us/call, fwd+bwd -5%
        # vs 512-row blocks.  VMEM peaks ~2 MB at head_dim 64.
        block_q = _pick_block(q.shape[-2], maximum=1024)
    if block_k is None:
        # Whole-k key blocks skip the online-softmax rescale entirely
        # (the kernel's single_k fast path) and the backward's key loop.
        block_k = _pick_block(k.shape[-2], maximum=1024)
    return _flash_attention(q, k, v, causal, sm_scale, block_q, block_k,
                            interpret)
