"""Fused ring-flash attention: rotation DMA overlapped inside the kernel.

The separable ring attention (`ops/ring_attention.py`) alternates
whole-shard rotate (ppermute / rdma) and whole-shard attend steps; XLA can
overlap them across steps, but each rotation is still a standalone
collective the scheduler must place.  This module fuses one ring step into
ONE Pallas program: the kernel *starts* the async remote copy of the
current K/V shard to the right neighbour, computes the shard's flash
attention while the DMA flies, and *waits* for the transfer only at the
final grid step — the start-DMA → attend → wait-DMA pattern of hand-
written TPU collective kernels (cf. the collective-matmul examples in the
Pallas guide).  Communication latency hides behind the attention compute
by construction, not by scheduler luck.

Per ring step the kernel returns the shard-local attention output and its
per-row logsumexp; consecutive steps merge at the JAX level with the
standard flash-merge identity::

    lse = logaddexp(lse_1, lse_2)
    out = out_1 * exp(lse_1 - lse) + out_2 * exp(lse_2 - lse)

The backward is fused the same way (round 4): each ring step is ONE
Pallas program that starts the K/V rotation DMA, recomputes the step's
probability block once from the saved (out, lse) residuals — feeding BOTH
the dk/dv and the dq gradient blocks, where the split single-shard
backward pays that recompute twice — and waits for the DMA at the final
grid step.  The dk/dv partial accumulators travel between step kernels as
float32 ``lax.ppermute`` rotations (following their K/V shard around the
ring, one extra rotation delivering each shard's total to its owner):
a trailing in-kernel DMA could not overlap anything — the accumulator is
only complete at kernel end — while the XLA-level rotation of step t can
hide under the step-t+1 kernel.  Unlike the round-3 composed backward,
nothing re-runs the forward: out/lse are residuals, exactly the flash
backward recompute strategy (Dao et al., arXiv:2205.14135) extended
across the ring.

Correctness of the remote DMA relies on the same ready-handshake barrier
and phase-alternating collective_id scheme as ``ops/rdma.py`` (reserved
ids 15/16 here; 13/14 belong to rdma) — see the invariant discussion
there.  Interpret mode (CPU test meshes) skips the barrier, as rdma does.

No reference counterpart (SURVEY §5.7: the reference has no sequence
parallelism); this is the exceeds-reference flagship.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.utils.jax_compat import axis_size as _axis_size
from horovod_tpu.utils.jax_compat import tpu_compiler_params as _compiler_params
from horovod_tpu.utils.jax_compat import vma as _vma

from horovod_tpu.ops.attention import (NEG_INF, POS_BIG, _attend_block,
                                       _bwd_plan, _combined_bwd_call,
                                       _finalize_flash, _init_state,
                                       _pick_block, _split_scale)

try:
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except ImportError:  # pragma: no cover
    _HAS_PALLAS = False

_COLLECTIVE_IDS = (15, 16)  # phase-alternating barrier namespaces

if _HAS_PALLAS:
    from horovod_tpu.ops.rdma import _ambient_mesh_axes, _device_id


def _step_kernel(*refs, causal, block_q, block_k, num_q_blocks,
                 num_k_blocks, bh, rotate, barrier, phase, axis_name,
                 mesh_axes, scale_r):
    """One ring step: start K/V DMA to the right neighbour, flash-attend
    the current shard, wait the DMA at the end.

    Grid: (bh, num_q, num_k).  ``offsets_ref`` (SMEM, scalar-prefetch):
    [q_offset, k_offset] — the absolute sequence positions of this
    device's q shard and of the k/v shard it currently holds (for causal
    masking across shards).  The last (non-rotating) ring step takes no
    DMA refs/semaphores at all.
    """
    if rotate:
        (offsets_ref, q_ref, k_ref, v_ref, k_full, v_full,
         o_ref, lse_ref, k_next, v_next,
         m_scratch, l_scratch, acc_scratch, sems) = refs
    else:
        (offsets_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
         m_scratch, l_scratch, acc_scratch) = refs
    b = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    if rotate:
        my = lax.axis_index(axis_name)
        n = _axis_size(axis_name)
        dst, id_type = _device_id(lax.rem(my + 1, n), axis_name, mesh_axes)
        src, _ = _device_id(lax.rem(my - 1 + n, n), axis_name, mesh_axes)

        @pl.when((b == 0) & (qi == 0) & (ki == 0))
        def _start_rotation():
            if barrier:
                # Ready handshake (see ops/rdma.py): signal my *source*
                # ("you may write into my k_next/v_next"), wait for the
                # matching signal from my *destination*.
                bar = pltpu.get_barrier_semaphore()
                pltpu.semaphore_signal(
                    bar, inc=1, device_id=src, device_id_type=id_type)
                pltpu.semaphore_wait(bar, 1)
            pltpu.make_async_remote_copy(
                src_ref=k_full, dst_ref=k_next, send_sem=sems.at[0],
                recv_sem=sems.at[1], device_id=dst,
                device_id_type=id_type).start()
            pltpu.make_async_remote_copy(
                src_ref=v_full, dst_ref=v_next, send_sem=sems.at[2],
                recv_sem=sems.at[3], device_id=dst,
                device_id_type=id_type).start()

    @pl.when(ki == 0)
    def _():
        _init_state(m_scratch, l_scratch, acc_scratch)

    if causal:
        q_start = offsets_ref[0] + qi * block_q  # absolute positions
        k_start = offsets_ref[1] + ki * block_k
        run = k_start <= q_start + block_q - 1
    else:
        q_start = k_start = 0
        run = True

    @pl.when(run)
    def _():
        # single_k skips the online rescale; the unconditional init above
        # still covers whole-shard-masked ring steps (run stays False).
        _attend_block(q_ref, k_ref, v_ref, m_scratch, l_scratch,
                      acc_scratch, q_start, k_start, causal,
                      block_q, block_k, single_k=num_k_blocks == 1,
                      scale_r=scale_r)

    @pl.when(ki == num_k_blocks - 1)
    def _():
        _finalize_flash(o_ref, lse_ref, m_scratch, l_scratch, acc_scratch,
                        block_q)

    if rotate:
        @pl.when((b == bh - 1) & (qi == num_q_blocks - 1)
                 & (ki == num_k_blocks - 1))
        def _finish_rotation():
            # Reconstructing the descriptor with the same refs/semaphores
            # waits on the copies started at the first grid step.
            pltpu.make_async_remote_copy(
                src_ref=k_full, dst_ref=k_next, send_sem=sems.at[0],
                recv_sem=sems.at[1], device_id=dst,
                device_id_type=id_type).wait()
            pltpu.make_async_remote_copy(
                src_ref=v_full, dst_ref=v_next, send_sem=sems.at[2],
                recv_sem=sems.at[3], device_id=dst,
                device_id_type=id_type).wait()


def _row_spec(block, d, row):
    # PrefetchScalarGridSpec passes the scalar-prefetch ref as the LAST
    # index_map argument.
    return pl.BlockSpec((1, block, d),
                        lambda b, qi, ki, s: (b, row(qi, ki), 0))


def _bwd_ring_step(q, do, lse8, delta8, k_cur, v_cur, q_offset, k_offset, *,
                   causal, block_q, block_k, rotate, phase,
                   axis_name, interpret, scale_r):
    """One fused backward ring step over (bh, seq_local, d) shards (q
    arrives pre-scaled by the pow2 part of sm_scale).  Returns (dk, dv,
    dq, k_next, v_next) — dk/dv/dq float32 contributions for the
    CURRENTLY HELD shard (dq in q' units); k_next/v_next only when
    rotating.  The kernel is attention.py's combined backward
    (`_combined_bwd_kernel`) invoked with rotate=True: one probability
    recompute feeds dk/dv and dq while the K/V rotation DMA flies."""
    barrier = rotate and not interpret
    results = _combined_bwd_call(
        q, do, lse8, delta8, k_cur, v_cur, q_offset, k_offset,
        causal=causal, block_q=block_q, block_k=block_k, rotate=rotate,
        collective_id=_COLLECTIVE_IDS[phase % 2] if barrier else None,
        axis_name=axis_name, mesh_axes=_ambient_mesh_axes(axis_name),
        interpret=interpret, scale_r=scale_r)
    if rotate:
        dk, dv, dq, k_next, v_next = results
        return dk, dv, dq, k_next, v_next
    dk, dv, dq = results
    return dk, dv, dq, None, None


def _ring_flash_step(q, k_cur, v_cur, q_offset, k_offset, *,
                     causal, block_q, block_k, rotate, phase, axis_name,
                     interpret, scale_r):
    """One fused ring step over (bh, seq_local, d) shards (q arrives
    pre-scaled by the pow2 part of sm_scale).  Returns (out, lse,
    k_next, v_next) — k_next/v_next only when rotating."""
    bh, sl, d = q.shape
    block_q = _pick_block(sl, block_q)
    block_k = _pick_block(sl, block_k)
    assert sl % block_q == 0 and sl % block_k == 0, (
        "fused_ring_attention routes ragged shard lengths to the "
        "separable path before reaching the kernel")
    num_q, num_k = sl // block_q, sl // block_k
    offsets = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                         jnp.asarray(k_offset, jnp.int32)])

    kernel = functools.partial(
        _step_kernel, causal=causal, block_q=block_q,
        block_k=block_k, num_q_blocks=num_q, num_k_blocks=num_k, bh=bh,
        rotate=rotate, barrier=rotate and not interpret, phase=phase,
        axis_name=axis_name, mesh_axes=_ambient_mesh_axes(axis_name),
        scale_r=scale_r)
    out_shapes = [
        jax.ShapeDtypeStruct((bh, sl, d), q.dtype),        # out
        jax.ShapeDtypeStruct((bh, 8, sl), jnp.float32),    # lse (8 sublanes)
    ]
    in_specs = [
        _row_spec(block_q, d, lambda qi, ki: qi),   # q
        _row_spec(block_k, d, lambda qi, ki: ki),   # k (blocked)
        _row_spec(block_k, d, lambda qi, ki: ki),   # v (blocked)
    ]
    out_specs = [
        _row_spec(block_q, d, lambda qi, ki: qi),   # out
        pl.BlockSpec((1, 8, block_q), lambda b, qi, ki, s: (b, 0, qi)),
    ]
    scratch_shapes = [
        pltpu.VMEM((block_q, 128), jnp.float32),    # running max
        pltpu.VMEM((block_q, 128), jnp.float32),    # running normalizer
        pltpu.VMEM((block_q, d), jnp.float32),      # output accumulator
    ]
    args = [offsets, q, k_cur, v_cur]
    if rotate:
        in_specs += [
            pl.BlockSpec(memory_space=pl.ANY),      # k (whole, DMA src)
            pl.BlockSpec(memory_space=pl.ANY),      # v (whole, DMA src)
        ]
        out_shapes += [
            jax.ShapeDtypeStruct(k_cur.shape, k_cur.dtype),  # k_next
            jax.ShapeDtypeStruct(v_cur.shape, v_cur.dtype),  # v_next
        ]
        out_specs += [
            pl.BlockSpec(memory_space=pl.ANY),      # k_next (DMA dst)
            pl.BlockSpec(memory_space=pl.ANY),      # v_next (DMA dst)
        ]
        scratch_shapes += [pltpu.SemaphoreType.DMA((4,))]  # k/v send+recv
        args += [k_cur, v_cur]
    vma = _vma(q)
    if vma is not None:
        out_shapes = [jax.ShapeDtypeStruct(s.shape, s.dtype, vma=vma)
                      for s in out_shapes]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, num_q, num_k),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch_shapes,
    )
    barrier = rotate and not interpret
    compiler_params = _compiler_params(
        # collective_id may only be set when the kernel takes the custom
        # barrier (the non-rotating last step has no barrier).
        collective_id=_COLLECTIVE_IDS[phase % 2] if barrier else None,
        has_side_effects=True)
    results = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shapes,
        compiler_params=compiler_params,
        interpret=interpret,
    )(*args)
    if rotate:
        out, lse, k_next, v_next = results
        return out, lse[:, 0, :], k_next, v_next
    out, lse = results
    return out, lse[:, 0, :], None, None


def _phase_closer_kernel(o_ref, *, axis_name, mesh_axes):
    my = lax.axis_index(axis_name)
    n = _axis_size(axis_name)
    src, id_type = _device_id(lax.rem(my - 1 + n, n), axis_name, mesh_axes)
    bar = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(bar, inc=1, device_id=src,
                           device_id_type=id_type)
    pltpu.semaphore_wait(bar, 1)
    o_ref[...] = jnp.zeros_like(o_ref)


def _phase_closer(axis_name):
    """Barrier-only invocation on phase 1: appended when a fused forward
    used an ODD number of rotating steps (even ring sizes), so every
    fused call's barrier-phase stream starts on 0 and ends on 1 — the
    cyclic alternation invariant (ops/rdma.py) then holds across
    repeated executions of the same compiled program (training loops
    re-run the jitted step; the junction last-phase -> first-phase must
    differ)."""
    pl.pallas_call(
        functools.partial(_phase_closer_kernel, axis_name=axis_name,
                          mesh_axes=_ambient_mesh_axes(axis_name)),
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        compiler_params=_compiler_params(
            collective_id=_COLLECTIVE_IDS[1], has_side_effects=True),
    )()


def _rotation_phases(n: int):
    """Barrier-phase schedule for one fused ring pass over ``n`` devices.

    Returns ``(phases, needs_closer)``: ``phases[t]`` is the barrier
    namespace (0/1 -> collective_ids 15/16) of rotating step ``t`` (the
    last step doesn't rotate), and ``needs_closer`` says whether a
    trailing :func:`_phase_closer` on phase 1 is required so the pass's
    barrier stream has even length — the cyclic-alternation invariant
    (ops/rdma.py): consecutive barrier invocations, INCLUDING the
    junctions forward->backward and end-of-step->next-step of a re-run
    jitted program, must never share a namespace, or a lagging device's
    ready-wait could be satisfied by a neighbour's next-invocation
    signal.  Pure so tests can pin the schedule
    (tests/test_ops.py::test_ring_flash_phase_stream_alternates)."""
    phases = [t % 2 for t in range(n - 1)]
    return phases, len(phases) % 2 == 1


def _merge(o1, lse1, o2, lse2):
    """Flash-merge two partial attention results.  POS_BIG lse rows carry
    zero mass (fully masked).  Returns the merged output in FLOAT32 — the
    running accumulator must stay f32 across the whole ring (an n-device
    ring would otherwise accumulate n-1 bf16 roundings, drifting from the
    separable path's single final cast); callers cast once at the end."""
    e1 = jnp.where(lse1 > POS_BIG / 2, NEG_INF, lse1)
    e2 = jnp.where(lse2 > POS_BIG / 2, NEG_INF, lse2)
    m = jnp.maximum(e1, e2)
    both_empty = m <= NEG_INF / 2
    m_safe = jnp.where(both_empty, 0.0, m)
    w1 = jnp.where(e1 <= NEG_INF / 2, 0.0, jnp.exp(e1 - m_safe))
    w2 = jnp.where(e2 <= NEG_INF / 2, 0.0, jnp.exp(e2 - m_safe))
    total = w1 + w2
    safe_total = jnp.where(total == 0.0, 1.0, total)
    out = (o1.astype(jnp.float32) * (w1 / safe_total)[..., None]
           + o2.astype(jnp.float32) * (w2 / safe_total)[..., None])
    lse = jnp.where(both_empty, POS_BIG, m_safe + jnp.log(safe_total))
    return out, lse


def _fused_forward(q, k, v, axis_name, causal, sm_scale, block_q, block_k,
                   interpret):
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    sl = q.shape[-2]
    batch, heads = q.shape[0], q.shape[1]
    bh = batch * heads
    # Pre-scaled q (ops/attention.py): exact pow2 factor on q, f32
    # residual applied to the logits inside the kernel.
    p2, scale_r = _split_scale(sm_scale)
    qr = (q * p2).astype(q.dtype).reshape(bh, sl, q.shape[-1])
    k_cur = k.reshape(bh, sl, k.shape[-1])
    v_cur = v.reshape(bh, sl, v.shape[-1])
    q_off = my * sl

    phases, needs_closer = _rotation_phases(n)
    out = lse = None
    for t in range(n):
        kv_idx = lax.rem(my - t + n, n)
        k_off = kv_idx * sl
        o_t, lse_t, k_next, v_next = _ring_flash_step(
            qr, k_cur, v_cur, q_off, k_off,
            causal=causal, block_q=block_q, block_k=block_k,
            rotate=t < n - 1, phase=phases[t] if t < n - 1 else 0,
            axis_name=axis_name, interpret=interpret, scale_r=scale_r)
        if t < n - 1:
            k_cur, v_cur = k_next, v_next
        if out is None:
            out, lse = o_t, lse_t
        else:
            out, lse = _merge(out, lse, o_t, lse_t)
    if not interpret and needs_closer:
        # Even ring: odd number of rotating steps [0,1,...,0] — close the
        # barrier-phase stream on 1 so repeated executions alternate.
        _phase_closer(axis_name)
    return (out.reshape(q.shape).astype(q.dtype),
            lse.reshape(q.shape[:-1]))


def _fused_backward(q, k, v, out, lse, g, axis_name, causal, sm_scale,
                    block_q, block_k, interpret):
    """Fused ring backward: per ring step ONE Pallas program rotates K/V
    by in-kernel DMA while computing the shard's dk/dv and dq blocks from
    the saved (out, lse); the float32 dk/dv partials follow their shard
    around the ring as ppermute rotations between kernels."""
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    batch, heads, sl, d = q.shape
    bh = batch * heads
    p2, scale_r = _split_scale(sm_scale)
    qr = (q * p2).astype(q.dtype).reshape(bh, sl, d)  # q' units
    dor = g.reshape(bh, sl, d)
    k_cur = k.reshape(bh, sl, d)
    v_cur = v.reshape(bh, sl, d)
    q_off = my * sl
    # delta_i = sum_d dOut_id * Out_id, broadcast to 8 sublanes alongside
    # lse (the single-shard flash backward's tiling trick).
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(bh, sl)
    delta8 = jnp.broadcast_to(delta[:, None, :], (bh, 8, sl))
    lse8 = jnp.broadcast_to(lse.reshape(bh, sl)[:, None, :], (bh, 8, sl))

    perm = [(i, (i + 1) % n) for i in range(n)]
    phases, needs_closer = _rotation_phases(n)
    dq_total = None
    acc_k = acc_v = None
    for t in range(n):
        kv_idx = lax.rem(my - t + n, n)
        k_off = kv_idx * sl
        dk_t, dv_t, dq_t, k_next, v_next = _bwd_ring_step(
            qr, dor, lse8, delta8, k_cur, v_cur, q_off, k_off,
            causal=causal, block_q=block_q,
            block_k=block_k, rotate=t < n - 1,
            phase=phases[t] if t < n - 1 else 0,
            axis_name=axis_name, interpret=interpret, scale_r=scale_r)
        if t < n - 1:
            k_cur, v_cur = k_next, v_next
        dq_total = dq_t if dq_total is None else dq_total + dq_t
        if acc_k is None:
            acc_k, acc_v = dk_t, dv_t
        else:
            # The accumulators chase their K/V shard: rotate one hop (the
            # shard moved while the kernel ran), then add this device's
            # contribution for the shard it now holds.  XLA schedules the
            # ppermute of step t-1 alongside the step-t kernel.
            acc_k = lax.ppermute(acc_k, axis_name, perm) + dk_t
            acc_v = lax.ppermute(acc_v, axis_name, perm) + dv_t
    if n > 1:
        # After step n-1, shard j's totals sit one hop left of owner j.
        acc_k = lax.ppermute(acc_k, axis_name, perm)
        acc_v = lax.ppermute(acc_v, axis_name, perm)
    if not interpret and needs_closer:
        _phase_closer(axis_name)  # same stream invariant as the forward
    # dq accumulated in q' = p2*q units; rescale once.
    return ((dq_total * p2).reshape(q.shape).astype(q.dtype),
            acc_k.reshape(k.shape).astype(k.dtype),
            acc_v.reshape(v.shape).astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _fused_ring_attention(q, k, v, axis_name, causal, sm_scale, block_q,
                          block_k, interpret):
    return _fused_forward(q, k, v, axis_name, causal, sm_scale, block_q,
                          block_k, interpret)[0]


def _fused_fwd(q, k, v, axis_name, causal, sm_scale, block_q, block_k,
               interpret):
    out, lse = _fused_forward(q, k, v, axis_name, causal, sm_scale,
                              block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _fused_bwd(axis_name, causal, sm_scale, block_q, block_k, interpret,
               res, g):
    q, k, v, out, lse = res
    return _fused_backward(q, k, v, out, lse, g, axis_name, causal,
                           sm_scale, block_q, block_k, interpret)


_fused_ring_attention.defvjp(_fused_fwd, _fused_bwd)


def fused_ring_attention(q, k, v, axis_name: str, causal: bool = False,
                         sm_scale: Optional[float] = None,
                         block_q: int = 512, block_k: int = 512,
                         interpret: Optional[bool] = None):
    """Ring attention with the rotation DMA fused into the flash kernel.

    Same contract as :func:`horovod_tpu.ops.ring_attention` (shards of
    ``(batch, heads, seq_local, head_dim)`` inside ``shard_map`` over
    ``axis_name``).  Shard lengths that don't factor into MXU-tileable
    blocks (see ``_pick_block``) fall back to the separable ppermute ring,
    as :func:`flash_attention` falls back to blockwise.
    """
    if not _HAS_PALLAS:
        raise RuntimeError("fused_ring_attention requires Pallas")
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    sl = q.shape[-2]
    d = q.shape[-1]
    bq, bk = _pick_block(sl, block_q), _pick_block(sl, block_k)
    off_grid = sl % bq or sl % bk or (not interpret
                                      and (bq % 128 or bk % 128))
    # The fused backward step is the combined kernel — whole-shard dq
    # scratch in VMEM.  Long local shards where that cannot compile
    # (attention._bwd_plan, calibrated against the 16 MiB scoped-VMEM
    # ceiling) route to the separable ppermute ring, whose backward
    # composes per-step flash backwards, instead of failing at Mosaic
    # compile time on the backward pass (ADVICE r4).
    mode, bq, bk = _bwd_plan(sl, d, bq, bk, q.shape[0] * q.shape[1])
    off_grid = off_grid or mode != "combined" or sl % bq or sl % bk
    # Interpret-mode (CPU test mesh) remote DMA only supports single-axis
    # meshes (upstream dma_start_p limitation); a dp x sp mesh on CPU
    # falls back to the separable ring.  Real TPUs use MESH device ids
    # and are unaffected.
    multi_axis_interpret = (interpret
                            and len(_ambient_mesh_axes(axis_name)) > 1)
    if off_grid or multi_axis_interpret:
        # Ragged or non-MXU-tileable shard lengths: the separable ring
        # handles them (mirrors _flash_forward's blockwise fallback).
        from horovod_tpu.ops.ring_attention import ring_attention

        return ring_attention(q, k, v, axis_name, causal=causal,
                              sm_scale=sm_scale, rotate_impl="ppermute")
    return _fused_ring_attention(q, k, v, axis_name, causal, sm_scale,
                                 bq, bk, interpret)
