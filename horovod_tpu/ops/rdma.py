"""Raw inter-chip RDMA collectives as Pallas kernels.

``ring_permute`` rotates each device's shard to its ring neighbour with a
single ``pltpu.make_async_remote_copy`` — the hand-rolled equivalent of
``lax.ppermute`` with the shift-by-one permutation, issued as one direct
HBM-to-HBM DMA over ICI instead of going through XLA's collective-permute
machinery.  It is the communication primitive for an RDMA-backed ring
attention (``ring_attention(..., rotate_impl="rdma")``): on hardware where
XLA's collective-permute scheduling is the bottleneck, the explicit DMA
gives the kernel author the overlap control (start early, wait late).

Differentiable: the VJP of a right rotation is a left rotation of the
cotangent, mirroring ``ppermute``'s transpose.

Requirements: must run inside ``shard_map`` over ``axis_name`` on a TPU
mesh (or in interpret mode on any mesh, which is how the unit tests
exercise it without multi-chip hardware).  On real TPUs the kernel takes a
neighbour barrier first (remote DMA writes into the peer's buffer, so both
sides must have entered the kernel); barrier semaphores need a
``collective_id``, reserved here as 13/14/17/18 (15/16 belong to
ops/ring_flash.py).

Barrier-namespace discipline: consecutive invocations in one DEPENDENCY
CHAIN (a sequence of rotations where each consumes the previous's output)
must alternate namespaces, so a lagging device's ready-wait can never be
satisfied by a neighbour's next-invocation signal.  Two namespaces per
chain suffice — program order within a chain is forced by data
dependence.  Chains that are INDEPENDENT of each other (ring_attention's
K and V streams) get disjoint namespace pairs: their runtime interleaving
is scheduler-chosen, so sharing a namespace across chains would let one
chain's signal satisfy the other's wait.  This also divorces correctness
from jax's tracing order: current jax traces custom_vjp transposes
grouped per cotangent chain (not interleaved with program order), which
broke the old global-alternation scheme.

HARDWARE CAVEAT: this module (and ops/ring_flash.py, which shares the
barrier scheme) has NEVER run on a physical multi-chip slice — every
round of this project had one chip.  The barrier/phase invariants are
pinned by interpret-mode tests (tests/test_ops.py
::test_rdma_phase_alternates_through_backward and
::test_ring_flash_phase_stream_alternates), but validate on a real slice
before production use; ``lax.ppermute`` is the default rotation for
exactly this reason.

No reference counterpart (SURVEY §5.7: the reference has no sequence
parallelism at all); this exceeds it.
"""

from __future__ import annotations

import functools

import jax
from jax import lax

from horovod_tpu.utils.jax_compat import axis_size as _axis_size
from horovod_tpu.utils.jax_compat import shape_dtype_struct as _shape_dtype_struct
from horovod_tpu.utils.jax_compat import tpu_compiler_params as _compiler_params
from horovod_tpu.utils.jax_compat import vma as _vma

try:
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except ImportError:  # pragma: no cover
    _HAS_PALLAS = False

# Barrier namespaces: phases 0/1 = chain A (ids 13/14), phases 2/3 =
# chain B (ids 17/18).  ``phase ^ 1`` flips within a chain — the VJP's
# move — while ``phase // 2`` names the chain.
_COLLECTIVE_IDS = (13, 14, 17, 18)


def _device_id(ring_idx, ring_axis, mesh_axes):
    """(device_id, device_id_type) addressing ``ring_idx`` along the ring
    axis.  Single-axis meshes use scalar LOGICAL ids (what interpret mode
    supports); multi-axis meshes use MESH coordinates over every axis —
    a LOGICAL id computed from the ring axis alone would address the
    wrong device on a dp x sp mesh."""
    if len(mesh_axes) == 1:
        return ring_idx, pltpu.DeviceIdType.LOGICAL
    coords = tuple(ring_idx if ax == ring_axis else lax.axis_index(ax)
                   for ax in mesh_axes)
    return coords, pltpu.DeviceIdType.MESH


def _ambient_mesh_axes(axis_name):
    """Axis names of the surrounding shard_map mesh (falls back to the
    ring axis alone outside any mesh context)."""
    try:
        import jax as _jax

        mesh = _jax.sharding.get_abstract_mesh()
        names = tuple(getattr(mesh, "axis_names", ()) or ())
        if axis_name in names:
            return names
    except Exception:  # pragma: no cover - very old jax
        pass
    return (axis_name,)


def _permute_kernel(x_ref, o_ref, send_sem, recv_sem, *, axis_name,
                    shift, barrier, mesh_axes):
    my = lax.axis_index(axis_name)
    n = _axis_size(axis_name)
    dst, id_type = _device_id(lax.rem(my + shift, n), axis_name, mesh_axes)
    if barrier:
        # Ready handshake: I may DMA into `dst` only once `dst` has
        # entered this kernel (its output buffer is live).  Every device
        # signals its *source* ("you may write to me") and waits for the
        # matching signal from its *destination*.  A stale signal from a
        # later invocation cannot satisfy this wait: invocations alternate
        # barrier namespaces (collective_id), and for `dst` to reach the
        # invocation-after-next it would need its own destination — and,
        # chasing the chain the whole way around the ring — *this* device
        # to have advanced too, a contradiction.
        src, _ = _device_id(lax.rem(my - shift + n, n), axis_name,
                            mesh_axes)
        sem = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(sem, inc=1, device_id=src,
                               device_id_type=id_type)
        pltpu.semaphore_wait(sem, 1)
    copy = pltpu.make_async_remote_copy(
        src_ref=x_ref, dst_ref=o_ref, send_sem=send_sem, recv_sem=recv_sem,
        device_id=dst, device_id_type=id_type)
    copy.start()
    copy.wait()


def _ring_permute_raw(x, axis_name, shift, interpret, phase):
    shift = shift % _axis_size(axis_name)  # static: axis sizes are known
    kernel = functools.partial(_permute_kernel, axis_name=axis_name,
                               shift=shift, barrier=not interpret,
                               mesh_axes=_ambient_mesh_axes(axis_name))
    # Propagate the varying-mesh-axes annotation so shard_map's vma check
    # accepts the pallas output (the result varies exactly as the input).
    vma = _vma(x)
    return pl.pallas_call(
        kernel,
        out_shape=_shape_dtype_struct(x.shape, x.dtype, vma=vma),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
        compiler_params=_compiler_params(
            collective_id=_COLLECTIVE_IDS[phase % 4],
            has_side_effects=True),
        interpret=interpret,
    )(x)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _ring_permute(x, axis_name, shift, interpret, phase):
    return _ring_permute_raw(x, axis_name, shift, interpret, phase)


def _ring_permute_fwd(x, axis_name, shift, interpret, phase):
    return _ring_permute_raw(x, axis_name, shift, interpret, phase), None


def _ring_permute_bwd(axis_name, shift, interpret, phase, _res, g):
    # The transpose of "send my shard +shift" is "send the cotangent
    # -shift" — identical to ppermute's transpose rule.  The barrier
    # namespace is FLIPPED within the chain (phase ^ 1 keeps phase // 2,
    # the chain id): the transposed rotations execute in reverse
    # dependency order, so the chain's last forward rotation (phase p) is
    # immediately followed by its own backward rotation — with the flip
    # that backward uses p^1, and since the chain's forward phases
    # alternate ..., p^1, p, the composed fwd+bwd chain stays strictly
    # alternating, seam included.  Without the flip, two adjacent
    # same-chain invocations would share a semaphore namespace and a
    # lagging device's ready-wait could be satisfied by a neighbour's
    # *next*-invocation signal, licensing a DMA into a buffer that is
    # not yet live.
    return (_ring_permute_raw(g, axis_name, -shift, interpret, phase ^ 1),)


_ring_permute.defvjp(_ring_permute_fwd, _ring_permute_bwd)


def ring_permute(x, axis_name: str, shift: int = 1,
                 interpret: bool = None, phase: int = 0):
    """Rotate ``x``'s shards ``shift`` positions up the mesh ring.

    Equivalent to ``lax.ppermute(x, axis_name, [(i, (i+shift) % n)])``,
    executed as one Pallas async remote copy per device.  Differentiable.
    Must be called inside ``shard_map`` over ``axis_name``.  Callers
    issuing a *sequence* of dependent rotations should alternate
    ``phase`` between consecutive calls of that chain (0,1,0,... or
    2,3,2,...) so adjacent invocations use distinct semaphore
    namespaces; an INDEPENDENT concurrent chain must use the other
    namespace pair (``phase // 2`` differs) — see the module docstring.
    """
    if not _HAS_PALLAS:
        raise RuntimeError("ring_permute requires Pallas (TPU jaxlib)")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _ring_permute(x, axis_name, shift, interpret, phase)
