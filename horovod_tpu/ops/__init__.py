"""TPU compute kernels and memory-efficient attention.

No reference counterpart (zhangzhao156/horovod ships no kernels — all its
compute lives in the wrapped frameworks); this package is the TPU-native
compute layer the task's long-context requirement adds on top of the
collective substrate:

* :func:`flash_attention` — fused Pallas attention kernel (MXU-tiled,
  online softmax, O(seq) memory).
* :func:`blockwise_attention` — differentiable pure-JAX blockwise attention
  (the same math as a `lax.scan`, usable on any backend and as the
  recompute path for flash attention's VJP).
* :func:`ring_attention` — sequence-parallel attention over a mesh axis:
  K/V shards rotate around the ICI ring via `lax.ppermute` while each
  device's queries stay put (Liu et al., Ring Attention, arXiv:2310.01889).
* :func:`fused_ring_attention` — ring attention with the rotation DMA
  fused INTO the flash kernel (start DMA -> attend -> wait), one Pallas
  program per ring step (`ring_attention(..., rotate_impl="fused")`).
"""

from horovod_tpu.ops.attention import (  # noqa: F401
    blockwise_attention,
    flash_attention,
    mha_reference,
)
from horovod_tpu.ops.ring_attention import ring_attention  # noqa: F401
from horovod_tpu.ops.ring_flash import fused_ring_attention  # noqa: F401
