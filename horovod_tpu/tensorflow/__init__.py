"""TensorFlow binding: collectives, DistributedOptimizer, broadcast hooks.

Counterpart of /root/reference/horovod/tensorflow/__init__.py (allreduce with
sparse IndexedSlices support, `broadcast_global_variables`,
`BroadcastGlobalVariablesHook`, `DistributedOptimizer`) redesigned for TF2:

* Collectives run through the shared C++ engine.  Eager tensors take a
  direct numpy path; inside `tf.function` the op is a `tf.py_function`
  (host-side, like every engine collective).  Gradients are registered via
  `tf.custom_gradient` with the same algebra the reference registers for its
  graph ops (/root/reference/horovod/tensorflow/mpi_ops.py:81-170):
  allreduce' = allreduce, allgather' = reduce-then-slice, broadcast' =
  reduce, zeroed off-root.
* On TPU, TF training should run via the JAX path; this binding serves
  TF-CPU loops and state replication, the same division of labor as the
  torch binding.
"""

from __future__ import annotations

import collections
import threading
import weakref
from typing import Optional

import numpy as np
import tensorflow as tf

import horovod_tpu.common as _common
from horovod_tpu.common import (  # noqa: F401  (process-control re-exports)
    HorovodInternalError,
    init,
    is_initialized,
    local_rank,
    local_size,
    mpi_threads_supported,
    rank,
    shutdown,
    size,
)

_name_lock = threading.Lock()
_name_counter = [0]


def _auto_name(prefix: str) -> str:
    with _name_lock:
        _name_counter[0] += 1
        return f"{prefix}.HorovodAuto_{_name_counter[0]}"


def _np_collective(kind: str, name: str, **kw):
    def run(x: np.ndarray) -> np.ndarray:
        if kind == "allreduce":
            return _common.allreduce(x, average=False, name=name)
        if kind == "allgather":
            return _common.allgather(x, name=name)
        return _common.broadcast(x, kw["root_rank"], name=name)
    return run


def _through_engine(kind: str, tensor: tf.Tensor, name: str, **kw):
    run = _np_collective(kind, name, **kw)
    if isinstance(tensor, tf.Tensor) and hasattr(tensor, "numpy"):
        return tf.constant(run(tensor.numpy()))
    # Graph (tf.function) mode: host round-trip as a py_function.
    out = tf.py_function(lambda x: run(x.numpy()), [tensor], tensor.dtype,
                         name=name.replace(".", "_"))
    if kind != "allgather":
        out.set_shape(tensor.shape)
    else:
        out.set_shape([None] + list(tensor.shape[1:]))
    return out


# ---------------------------------------------------------------------------
# Async collectives -- the ComputeAsync analogue.
#
# The reference's TF kernels are ComputeAsync: enqueue, return, done() fires
# in the engine callback (/root/reference/horovod/tensorflow/mpi_ops.cc:
# 275-330), which is what lets N gradients negotiate in one engine cycle and
# FUSE (operations.cc:1607-1642 — fusion only works when ops co-arrive).
# Here each collective splits into an *enqueue* py_function (non-blocking:
# submits to the engine/plane, parks the handle in a registry) and a *wait*
# py_function.  synchronize() gives every wait of a group a control
# dependency on ALL the group's enqueues, so no rank blocks before it has
# submitted everything — arbitrary executor order is then deadlock-free (the
# engine coordinator tolerates any per-rank arrival order) and co-arriving
# ops fuse into one negotiation cycle / one plane dispatch.
# ---------------------------------------------------------------------------

# name -> FIFO of enqueued common handles.  A deque, not a slot: the same
# graph ops can run again (next session.run) before earlier waits drained,
# and duplicate-named groups built twice in one graph then pair first
# enqueue with first wait — the engine's duplicate-in-flight-name check
# turns genuinely concurrent reuse into a typed error instead of silent
# cross-pairing.
_async_handles: dict = {}
_async_lock = threading.Lock()
# Handles of the most recent _group_average_gradients group; after the ops
# have executed, their completion_tick spread shows how well the group
# fused (tests assert ≤2 distinct ticks for N small gradients).
_last_group_handles: list = []
_group_counter = [0]


def _next_group_id() -> int:
    """Build-time counter making collective-name prefixes unique per group
    (two optimizers / repeated tape calls in one graph must not share
    in-flight names).  Deterministic across ranks under the standing
    assumption that every rank executes the same user program."""
    with _name_lock:
        _group_counter[0] += 1
        return _group_counter[0]


def _common_enqueue(kind: str, arr: np.ndarray, name: str, root_rank: int,
                    average: bool):
    if kind == "allreduce":
        return _common.allreduce_async(arr, average=average, name=name)
    if kind == "allgather":
        return _common.allgather_async(arr, name=name)
    return _common.broadcast_async(arr, root_rank, name=name)


class TFAsyncHandle:
    """An outstanding TF collective: produce the result via
    :func:`synchronize`.  After synchronization, ``completion_tick`` holds
    the engine negotiation tick the op completed in (fused ops share one) —
    the observability tests and the timeline key off."""

    def __init__(self, kind: str, name: str, eager_handle=None, token=None,
                 dtype=None, shape=None):
        self._kind = kind
        self._name = name
        self._eager = eager_handle
        self._token = token  # graph mode: the enqueue op's output
        self._dtype = dtype
        self._shape = shape
        self._waited = False
        self.completion_tick: Optional[int] = None

    def done(self) -> bool:
        """Non-blocking poll (eager handles only — a graph-mode handle has
        no engine state until its enqueue op runs in a session)."""
        if self._eager is None:
            raise ValueError(
                "done() is only available for eagerly-enqueued handles")
        return self._eager.done()

    def _wait_tensor(self) -> tf.Tensor:
        if self._waited:
            raise ValueError(
                f"handle for '{self._name}' already synchronized")
        self._waited = True
        if self._eager is not None:
            arr = self._eager.wait()
            self.completion_tick = self._eager.completion_tick
            return tf.constant(arr)

        def wait_fn():
            with _async_lock:
                queue = _async_handles[self._name]
                handle = queue.popleft()
                if not queue:
                    del _async_handles[self._name]
            arr = handle.wait()
            self.completion_tick = handle.completion_tick
            return arr

        out = tf.py_function(wait_fn, [], self._dtype,
                             name=(self._name + ".wait").replace(".", "_"))
        if self._kind == "allgather":
            out.set_shape([None] + list(self._shape[1:]))
        else:
            out.set_shape(self._shape)
        return out


def _enqueue_async(kind: str, tensor: tf.Tensor, name: str,
                   root_rank: int = 0, average: bool = True) -> TFAsyncHandle:
    tensor = tf.convert_to_tensor(tensor)
    if hasattr(tensor, "numpy"):  # eager: enqueue NOW, wait later
        eager_handle = _common_enqueue(kind, tensor.numpy(), name,
                                       root_rank, average)
        return TFAsyncHandle(kind, name, eager_handle=eager_handle,
                             dtype=tensor.dtype, shape=tensor.shape)

    def enqueue_fn(x):
        handle = _common_enqueue(kind, x.numpy(), name, root_rank, average)
        with _async_lock:
            _async_handles.setdefault(name, collections.deque()).append(
                handle)
        return np.int64(1)

    token = tf.py_function(enqueue_fn, [tensor], tf.int64,
                           name=(name + ".enq").replace(".", "_"))
    return TFAsyncHandle(kind, name, token=token, dtype=tensor.dtype,
                         shape=tensor.shape)


def allreduce_async(tensor: tf.Tensor, average: bool = True,
                    name: Optional[str] = None) -> TFAsyncHandle:
    """Enqueue a (sum or average) allreduce without blocking."""
    return _enqueue_async("allreduce", tensor,
                          name or _auto_name("allreduce"), average=average)


def allgather_async(tensor: tf.Tensor,
                    name: Optional[str] = None) -> TFAsyncHandle:
    """Enqueue a dim-0 allgather without blocking."""
    return _enqueue_async("allgather", tensor,
                          name or _auto_name("allgather"))


def broadcast_async(tensor: tf.Tensor, root_rank: int,
                    name: Optional[str] = None) -> TFAsyncHandle:
    """Enqueue a broadcast from ``root_rank`` without blocking."""
    return _enqueue_async("broadcast", tensor,
                          name or _auto_name("broadcast"),
                          root_rank=root_rank)


def synchronize(handles):
    """Materialize async collective results.

    Accepts one handle or a sequence; returns the result tensor(s).  When
    given the whole group at once (the internal users always do), every
    graph-mode wait op is given a control dependency on *all* of the
    group's enqueue ops — the property that makes independent-op executor
    scheduling deadlock-free and lets the group fuse."""
    single = isinstance(handles, TFAsyncHandle)
    group = [handles] if single else list(handles)
    tokens = [h._token for h in group if h._token is not None]
    outs = []
    with tf.control_dependencies(tokens or None):
        for h in group:
            outs.append(h._wait_tensor())
    if tokens and len(outs) > 1:
        # Tie every output to every wait: fetching any subset still runs
        # ALL the group's waits, so no enqueued handle is orphaned in the
        # registry by graph pruning (every enqueue ran — the waits must
        # drain them) and no rank leaves collectives half-consumed.
        with tf.control_dependencies(outs):
            outs = [tf.identity(t) for t in outs]
    return outs[0] if single else outs


def _allreduce(tensor: tf.Tensor, name: Optional[str] = None) -> tf.Tensor:
    """Raw sum across ranks (the reference's `_allreduce`,
    /root/reference/horovod/tensorflow/mpi_ops.py:65-78)."""
    name = name or _auto_name("allreduce")

    @tf.custom_gradient
    def op(x):
        y = _through_engine("allreduce", x, name)

        def grad(dy):
            return _allreduce(dy, name=f"{name}.grad")
        return y, grad

    return op(tensor)


def allreduce(tensor, average: bool = True, name: Optional[str] = None,
              device_dense: str = "", device_sparse: str = ""):
    """Average (or sum) across ranks.  `tf.IndexedSlices` are handled as the
    reference does — allgather values and indices instead of densifying
    (/root/reference/horovod/tensorflow/__init__.py:50-86)."""
    if isinstance(tensor, tf.IndexedSlices):
        values = allgather(tensor.values, name=(name or _auto_name("ar")) + ".values")
        indices = allgather(tensor.indices, name=(name or _auto_name("ar")) + ".indices")
        if average:
            values = tf.math.divide(values, float(_common.size()))
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)
    summed = _allreduce(tensor, name=name)
    if average:
        return tf.math.divide(summed, float(_common.size()))
    return summed


def allgather(tensor: tf.Tensor, name: Optional[str] = None) -> tf.Tensor:
    """Concatenation of every rank's tensor along dim 0 (ranks may differ in
    dim 0)."""
    name = name or _auto_name("allgather")

    @tf.custom_gradient
    def op(x):
        y = _through_engine("allgather", x, name)
        dim0 = tf.shape(x)[0]

        def grad(dy):
            summed = _allreduce(dy, name=f"{name}.grad")
            sizes = _through_engine(
                "allgather", tf.reshape(tf.cast(dim0, tf.int64), [1]),
                f"{name}.grad.sizes")
            offset = tf.reduce_sum(sizes[:_common.rank()])
            return tf.slice(summed, [tf.cast(offset, tf.int32)] +
                            [0] * (len(x.shape) - 1),
                            tf.shape(x))
        return y, grad

    return op(tensor)


def broadcast(tensor: tf.Tensor, root_rank: int,
              name: Optional[str] = None) -> tf.Tensor:
    """Every rank receives root_rank's value; gradient is summed to the root
    and zeroed elsewhere (/root/reference/horovod/tensorflow/mpi_ops.py:155-170)."""
    name = name or _auto_name("broadcast")

    @tf.custom_gradient
    def op(x):
        y = _through_engine("broadcast", x, name, root_rank=root_rank)

        def grad(dy):
            summed = _allreduce(dy, name=f"{name}.grad")
            if _common.rank() == root_rank:
                return summed
            return tf.zeros_like(summed)
        return y, grad

    return op(tensor)


def broadcast_global_variables(root_rank: int = 0):
    """Assign rank ``root_rank``'s value to every global variable.  Eager:
    acts immediately on `tf.compat.v1.global_variables()` plus any tracked
    module variables; graph mode: returns the grouped assign op
    (/root/reference/horovod/tensorflow/__init__.py:89-98)."""
    variables = tf.compat.v1.global_variables()
    return broadcast_variables(variables, root_rank)


def broadcast_variables(variables, root_rank: int = 0):
    # Enqueue-all-then-wait: every broadcast is submitted before any wait
    # blocks (synchronize control-deps each wait on all enqueues), so the
    # whole set negotiates in one engine cycle and fuses — the reference's
    # ComputeAsync behavior — instead of paying one cycle per variable.
    variables = list(variables)
    prefix = f"broadcast_var.g{_next_group_id()}"
    handles = [
        broadcast_async(
            tf.convert_to_tensor(var), root_rank,
            name=f"{prefix}.{i}.{var.name.replace(':', '_')}")
        for i, var in enumerate(variables)]
    values = synchronize(handles)
    ops = [var.assign(value) for var, value in zip(variables, values)]
    if ops and isinstance(ops[0], tf.Operation):
        return tf.group(*ops)
    return ops


class BroadcastGlobalVariablesHook(tf.compat.v1.train.SessionRunHook):
    """Session hook broadcasting all global variables from root once after
    session creation (/root/reference/horovod/tensorflow/__init__.py:100-131)."""

    def __init__(self, root_rank: int = 0, device: str = ""):
        super().__init__()
        self.root_rank = root_rank
        self.bcast_op = None
        self.device = device

    def begin(self):
        self.bcast_op = broadcast_global_variables(self.root_rank)

    def after_create_session(self, session, coord):
        if self.bcast_op is not None:
            session.run(self.bcast_op)


# Backward-pass collectives from custom gradients are built independently
# by TF's backprop, with no group to enqueue together — in graph mode,
# unordered blocking collectives deadlock across ranks (different
# executors pick different first ops).  _chained_bwd serializes them by
# BUILD order via control deps, which is deterministic and identical on
# every rank (the same forward graph yields the same backward build
# order).  Slower than a group, but the double-backward path is rare;
# eager mode needs no chain (python program order is already global).
# Per-graph last-built backward collective (weakly keyed: graphs are not
# pinned alive).  A single shared slot would lose the chain whenever two
# graphs' builds interleave (nested FuncGraphs, tf.cond gradients) and
# silently re-expose the deadlock.
_bwd_chain = weakref.WeakKeyDictionary()


def _chained_bwd(build_fn, ref_tensor):
    if hasattr(ref_tensor, "numpy"):  # eager backward: program order
        return build_fn()
    graph = getattr(ref_tensor, "graph", None)
    if graph is None:
        return build_fn()
    with _name_lock:
        prev_ref = _bwd_chain.get(graph)
        prev = prev_ref() if prev_ref is not None else None
    with tf.control_dependencies([prev] if prev is not None else []):
        out = build_fn()
    with _name_lock:
        try:
            # The value is a weakref too: a strong op value would reference
            # its graph (the key) and pin both alive forever.
            _bwd_chain[graph] = weakref.ref(out)
        except TypeError:  # non-weakref-able object: skip chaining
            pass
    return out


def _with_allreduce_grad(x, y, name: str):
    """Attach the allreduce gradient (allreduce' = allreduce, the
    reference's registration, mpi_ops.py:81-92) to a result ``y`` computed
    from ``x`` by the async group machinery, so differentiating through a
    group-averaged gradient (e.g. a gradient penalty) still allreduces the
    cotangent instead of silently disconnecting."""

    @tf.custom_gradient
    def op(x):
        def grad(dy):
            summed = _chained_bwd(
                lambda: _allreduce(dy, name=f"{name}.bwd"), dy)
            return tf.math.divide(summed, float(_common.size()))
        return y, grad

    return op(x)


def _with_allgather_grad(x, y, name: str):
    """Attach the allgather gradient (reduce-then-slice-by-rank-offsets,
    the reference's mpi_ops.py:114-135) to an async-group result."""

    @tf.custom_gradient
    def op(x):
        dim0 = tf.shape(x)[0]

        def grad(dy):
            summed = _chained_bwd(
                lambda: _allreduce(dy, name=f"{name}.bwd"), dy)
            sizes = _chained_bwd(
                lambda: _through_engine(
                    "allgather", tf.reshape(tf.cast(dim0, tf.int64), [1]),
                    f"{name}.bwd.sizes"), dy)
            offset = tf.reduce_sum(sizes[:_common.rank()])
            return tf.slice(summed, [tf.cast(offset, tf.int32)] +
                            [0] * (len(x.shape) - 1),
                            tf.shape(x))
        return y, grad

    return op(x)


def _group_average_gradients(gradients, name_prefix: str):
    """Allreduce-average a list of ``(grad, var)`` (or bare grads) as ONE
    enqueue-all-then-wait group: every gradient negotiates in the same
    engine cycle(s) and fuses, and the collectives overlap instead of
    serializing one cycle each.  ``tf.IndexedSlices`` ride as allgathers of
    values+indices, like the reference's sparse path.  Results stay
    differentiable (allreduce'/allgather' re-attached via custom_gradient).
    """
    global _last_group_handles
    with_vars = gradients and isinstance(gradients[0], tuple)
    pairs = gradients if with_vars else [(g, None) for g in gradients]
    n = float(_common.size())
    prefix = f"{name_prefix}.g{_next_group_id()}"
    handles = []  # flat group, in deterministic program order
    plan = []  # mirrors pairs: (mode, payload)
    for i, (grad, var) in enumerate(pairs):
        if grad is None:
            plan.append(("none", None))
        elif isinstance(grad, tf.IndexedSlices):
            hv = allgather_async(grad.values, name=f"{prefix}.{i}.values")
            hi = allgather_async(grad.indices, name=f"{prefix}.{i}.indices")
            handles += [hv, hi]
            plan.append(("sparse", (hv, hi, grad.dense_shape)))
        else:
            h = allreduce_async(grad, average=True, name=f"{prefix}.{i}")
            handles.append(h)
            plan.append(("dense", h))
    results = dict(zip(map(id, handles), synchronize(handles)))
    _last_group_handles = handles  # observability: completion_tick spread
    out = []
    for (grad, var), (mode, payload) in zip(pairs, plan):
        if mode == "none":
            red = None
        elif mode == "sparse":
            hv, hi, dense_shape = payload
            values = _with_allgather_grad(grad.values, results[id(hv)],
                                          hv._name)
            red = tf.IndexedSlices(tf.math.divide(values, n),
                                   results[id(hi)],
                                   dense_shape=dense_shape)
        else:
            red = _with_allreduce_grad(grad, results[id(payload)],
                                       payload._name)
        out.append((red, var) if with_vars else red)
    return out


class _DistributedOptimizer(tf.compat.v1.train.Optimizer):
    """Wraps a `tf.compat.v1.train.Optimizer`; `compute_gradients` returns
    allreduce-averaged gradients
    (/root/reference/horovod/tensorflow/__init__.py:134-208)."""

    def __init__(self, optimizer, name=None, use_locking=False,
                 device_dense="", device_sparse=""):
        if name is None:
            name = f"Distributed{type(optimizer).__name__}"
        super().__init__(name=name, use_locking=use_locking)
        self._optimizer = optimizer
        # Accepted for reference-API compatibility only: every engine
        # collective is host-staged on TPU (there is no GPU-vs-CPU
        # placement choice), so these have no effect.
        self._device_dense = device_dense
        self._device_sparse = device_sparse

    def compute_gradients(self, *args, **kwargs):
        gradients = self._optimizer.compute_gradients(*args, **kwargs)
        if _common.size() == 1:
            return gradients
        return _group_average_gradients(
            gradients, "DistributedOptimizer.grad")

    def apply_gradients(self, *args, **kwargs):
        return self._optimizer.apply_gradients(*args, **kwargs)

    def get_slot(self, *args, **kwargs):
        return self._optimizer.get_slot(*args, **kwargs)

    def get_slot_names(self, *args, **kwargs):
        return self._optimizer.get_slot_names(*args, **kwargs)

    def variables(self, *args, **kwargs):
        return self._optimizer.variables(*args, **kwargs)


def DistributedOptimizer(optimizer, name=None, use_locking=False,
                         device_dense="", device_sparse=""):
    return _DistributedOptimizer(optimizer, name, use_locking, device_dense,
                                 device_sparse)


class DistributedGradientTape(tf.GradientTape):
    """TF2-native gradient averaging: `tape.gradient` results are
    allreduce-averaged — the eager-mode face of DistributedOptimizer."""

    def __init__(self, persistent=False, watch_accessed_variables=True):
        super().__init__(persistent=persistent,
                         watch_accessed_variables=watch_accessed_variables)

    def gradient(self, target, sources, output_gradients=None):
        grads = super().gradient(target, sources, output_gradients)
        if _common.size() == 1:
            return grads
        # One enqueue-all-then-wait group: the collectives fuse and
        # overlap instead of blocking one engine cycle per gradient.
        return _group_average_gradients(grads, "DistributedTape.grad")
