"""TensorFlow binding: collectives, DistributedOptimizer, broadcast hooks.

Counterpart of /root/reference/horovod/tensorflow/__init__.py (allreduce with
sparse IndexedSlices support, `broadcast_global_variables`,
`BroadcastGlobalVariablesHook`, `DistributedOptimizer`) redesigned for TF2:

* Collectives run through the shared C++ engine.  Eager tensors take a
  direct numpy path; inside `tf.function` the op is a `tf.py_function`
  (host-side, like every engine collective).  Gradients are registered via
  `tf.custom_gradient` with the same algebra the reference registers for its
  graph ops (/root/reference/horovod/tensorflow/mpi_ops.py:81-170):
  allreduce' = allreduce, allgather' = reduce-then-slice, broadcast' =
  reduce, zeroed off-root.
* On TPU, TF training should run via the JAX path; this binding serves
  TF-CPU loops and state replication, the same division of labor as the
  torch binding.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np
import tensorflow as tf

import horovod_tpu.common as _common
from horovod_tpu.common import (  # noqa: F401  (process-control re-exports)
    HorovodInternalError,
    init,
    is_initialized,
    local_rank,
    local_size,
    mpi_threads_supported,
    rank,
    shutdown,
    size,
)

_name_lock = threading.Lock()
_name_counter = [0]


def _auto_name(prefix: str) -> str:
    with _name_lock:
        _name_counter[0] += 1
        return f"{prefix}.HorovodAuto_{_name_counter[0]}"


def _np_collective(kind: str, name: str, **kw):
    def run(x: np.ndarray) -> np.ndarray:
        if kind == "allreduce":
            return _common.allreduce(x, average=False, name=name)
        if kind == "allgather":
            return _common.allgather(x, name=name)
        return _common.broadcast(x, kw["root_rank"], name=name)
    return run


def _through_engine(kind: str, tensor: tf.Tensor, name: str, **kw):
    run = _np_collective(kind, name, **kw)
    if isinstance(tensor, tf.Tensor) and hasattr(tensor, "numpy"):
        return tf.constant(run(tensor.numpy()))
    # Graph (tf.function) mode: host round-trip as a py_function.
    out = tf.py_function(lambda x: run(x.numpy()), [tensor], tensor.dtype,
                         name=name.replace(".", "_"))
    if kind != "allgather":
        out.set_shape(tensor.shape)
    else:
        out.set_shape([None] + list(tensor.shape[1:]))
    return out


def _allreduce(tensor: tf.Tensor, name: Optional[str] = None) -> tf.Tensor:
    """Raw sum across ranks (the reference's `_allreduce`,
    /root/reference/horovod/tensorflow/mpi_ops.py:65-78)."""
    name = name or _auto_name("allreduce")

    @tf.custom_gradient
    def op(x):
        y = _through_engine("allreduce", x, name)

        def grad(dy):
            return _allreduce(dy, name=f"{name}.grad")
        return y, grad

    return op(tensor)


def allreduce(tensor, average: bool = True, name: Optional[str] = None,
              device_dense: str = "", device_sparse: str = ""):
    """Average (or sum) across ranks.  `tf.IndexedSlices` are handled as the
    reference does — allgather values and indices instead of densifying
    (/root/reference/horovod/tensorflow/__init__.py:50-86)."""
    if isinstance(tensor, tf.IndexedSlices):
        values = allgather(tensor.values, name=(name or _auto_name("ar")) + ".values")
        indices = allgather(tensor.indices, name=(name or _auto_name("ar")) + ".indices")
        if average:
            values = tf.math.divide(values, float(_common.size()))
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)
    summed = _allreduce(tensor, name=name)
    if average:
        return tf.math.divide(summed, float(_common.size()))
    return summed


def allgather(tensor: tf.Tensor, name: Optional[str] = None) -> tf.Tensor:
    """Concatenation of every rank's tensor along dim 0 (ranks may differ in
    dim 0)."""
    name = name or _auto_name("allgather")

    @tf.custom_gradient
    def op(x):
        y = _through_engine("allgather", x, name)
        dim0 = tf.shape(x)[0]

        def grad(dy):
            summed = _allreduce(dy, name=f"{name}.grad")
            sizes = _through_engine(
                "allgather", tf.reshape(tf.cast(dim0, tf.int64), [1]),
                f"{name}.grad.sizes")
            offset = tf.reduce_sum(sizes[:_common.rank()])
            return tf.slice(summed, [tf.cast(offset, tf.int32)] +
                            [0] * (len(x.shape) - 1),
                            tf.shape(x))
        return y, grad

    return op(tensor)


def broadcast(tensor: tf.Tensor, root_rank: int,
              name: Optional[str] = None) -> tf.Tensor:
    """Every rank receives root_rank's value; gradient is summed to the root
    and zeroed elsewhere (/root/reference/horovod/tensorflow/mpi_ops.py:155-170)."""
    name = name or _auto_name("broadcast")

    @tf.custom_gradient
    def op(x):
        y = _through_engine("broadcast", x, name, root_rank=root_rank)

        def grad(dy):
            summed = _allreduce(dy, name=f"{name}.grad")
            if _common.rank() == root_rank:
                return summed
            return tf.zeros_like(summed)
        return y, grad

    return op(tensor)


def broadcast_global_variables(root_rank: int = 0):
    """Assign rank ``root_rank``'s value to every global variable.  Eager:
    acts immediately on `tf.compat.v1.global_variables()` plus any tracked
    module variables; graph mode: returns the grouped assign op
    (/root/reference/horovod/tensorflow/__init__.py:89-98)."""
    variables = tf.compat.v1.global_variables()
    return broadcast_variables(variables, root_rank)


def broadcast_variables(variables, root_rank: int = 0):
    ops = []
    prev = []
    for i, var in enumerate(variables):
        # Chain the broadcasts: in graph mode each one is a blocking
        # py_function, and a tf.group of independent ops executes in a
        # process-dependent order (executor readiness / hash order) — two
        # ranks whose single inter-op thread picks different first ops
        # would deadlock the engine's negotiation.  Control dependencies
        # force the same (program) order on every rank; in eager mode the
        # context is a no-op and execution is already sequential.
        with tf.control_dependencies(prev):
            value = broadcast(
                tf.convert_to_tensor(var), root_rank,
                name=f"broadcast_var.{i}.{var.name.replace(':', '_')}")
            assign = var.assign(value)
        ops.append(assign)
        prev = [assign]
    if ops and isinstance(ops[0], tf.Operation):
        return tf.group(*ops)
    return ops


class BroadcastGlobalVariablesHook(tf.compat.v1.train.SessionRunHook):
    """Session hook broadcasting all global variables from root once after
    session creation (/root/reference/horovod/tensorflow/__init__.py:100-131)."""

    def __init__(self, root_rank: int = 0, device: str = ""):
        super().__init__()
        self.root_rank = root_rank
        self.bcast_op = None
        self.device = device

    def begin(self):
        self.bcast_op = broadcast_global_variables(self.root_rank)

    def after_create_session(self, session, coord):
        if self.bcast_op is not None:
            session.run(self.bcast_op)


class _DistributedOptimizer(tf.compat.v1.train.Optimizer):
    """Wraps a `tf.compat.v1.train.Optimizer`; `compute_gradients` returns
    allreduce-averaged gradients
    (/root/reference/horovod/tensorflow/__init__.py:134-208)."""

    def __init__(self, optimizer, name=None, use_locking=False,
                 device_dense="", device_sparse=""):
        if name is None:
            name = f"Distributed{type(optimizer).__name__}"
        super().__init__(name=name, use_locking=use_locking)
        self._optimizer = optimizer
        self._device_dense = device_dense
        self._device_sparse = device_sparse

    def compute_gradients(self, *args, **kwargs):
        gradients = self._optimizer.compute_gradients(*args, **kwargs)
        if _common.size() == 1:
            return gradients
        averaged = []
        prev = []
        for i, (grad, var) in enumerate(gradients):
            if grad is None:
                averaged.append((None, var))
                continue
            # Chain the allreduces (control deps): graph-mode collectives
            # are blocking py_functions and a session executes independent
            # ones in process-dependent order — ranks whose inter-op
            # threads pick different first gradients deadlock the
            # negotiation.  Program order is the same on every rank.
            with tf.control_dependencies(prev):
                avg = allreduce(
                    grad, average=True,
                    name=f"DistributedOptimizer.grad.{i}",
                    device_dense=self._device_dense,
                    device_sparse=self._device_sparse)
            averaged.append((avg, var))
            prev = [avg.values if isinstance(avg, tf.IndexedSlices) else avg]
        return averaged

    def apply_gradients(self, *args, **kwargs):
        return self._optimizer.apply_gradients(*args, **kwargs)

    def get_slot(self, *args, **kwargs):
        return self._optimizer.get_slot(*args, **kwargs)

    def get_slot_names(self, *args, **kwargs):
        return self._optimizer.get_slot_names(*args, **kwargs)

    def variables(self, *args, **kwargs):
        return self._optimizer.variables(*args, **kwargs)


def DistributedOptimizer(optimizer, name=None, use_locking=False,
                         device_dense="", device_sparse=""):
    return _DistributedOptimizer(optimizer, name, use_locking, device_dense,
                                 device_sparse)


class DistributedGradientTape(tf.GradientTape):
    """TF2-native gradient averaging: `tape.gradient` results are
    allreduce-averaged — the eager-mode face of DistributedOptimizer."""

    def __init__(self, persistent=False, watch_accessed_variables=True):
        super().__init__(persistent=persistent,
                         watch_accessed_variables=watch_accessed_variables)

    def gradient(self, target, sources, output_gradients=None):
        grads = super().gradient(target, sources, output_gradients)
        if _common.size() == 1:
            return grads
        return [None if g is None else
                allreduce(g, average=True, name=f"DistributedTape.grad.{i}")
                for i, g in enumerate(grads)]
