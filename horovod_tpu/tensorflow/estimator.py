"""Minimal ``tf.estimator``-compatible shim for TF builds without it.

TensorFlow >= 2.16 removed ``tf.estimator``, but the reference's
acceptance surface includes an estimator-path example
(/root/reference/examples/tensorflow_mnist_estimator.py).  This module
implements just enough of the estimator contract over ``tf.compat.v1``
graphs/sessions for that workflow to run unchanged:

* ``ModeKeys`` / ``EstimatorSpec`` — the ``model_fn`` protocol,
* ``Estimator(model_fn, model_dir).train(input_fn, steps, hooks)`` /
  ``.evaluate(input_fn)`` — a graph-mode train loop honoring
  ``SessionRunHook.begin``/``after_create_session`` (the surface
  :class:`horovod_tpu.tensorflow.BroadcastGlobalVariablesHook` uses) and
  rank-0-only checkpointing via ``model_dir=None`` elsewhere,
* ``inputs.numpy_input_fn`` — the classic in-memory input pipeline.

This is a training-workflow shim, not a full estimator reimplementation:
``train``/``evaluate``/``predict`` cover the reference example's usage;
exporters, distribution strategies, and ``RunConfig`` are out of scope.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional

import tensorflow as tf

v1 = tf.compat.v1


class ModeKeys:
    """Same string values as ``tf.estimator.ModeKeys``."""

    TRAIN = "train"
    EVAL = "eval"
    PREDICT = "infer"


class EstimatorSpec:
    def __init__(self, mode, predictions=None, loss=None, train_op=None,
                 eval_metric_ops=None):
        self.mode = mode
        self.predictions = predictions
        self.loss = loss
        self.train_op = train_op
        self.eval_metric_ops = eval_metric_ops or {}


class _Inputs:
    @staticmethod
    def numpy_input_fn(x: Dict[str, "object"], y=None, batch_size: int = 128,
                       num_epochs: Optional[int] = 1, shuffle: bool = True):
        """In-memory input pipeline: dict of arrays (+ labels) -> batched
        ``tf.data`` iterator tensors, like the removed
        ``tf.compat.v1.estimator.inputs.numpy_input_fn``."""

        def input_fn():
            data = (dict(x), y) if y is not None else dict(x)
            ds = tf.data.Dataset.from_tensor_slices(data)
            if shuffle:
                ds = ds.shuffle(10000, seed=0)
            if num_epochs is None:
                ds = ds.repeat()
            elif num_epochs > 1:
                ds = ds.repeat(num_epochs)
            ds = ds.batch(batch_size)
            it = v1.data.make_one_shot_iterator(ds)
            return it.get_next()

        return input_fn


inputs = _Inputs


def _run_hooks_begin(hooks):
    for h in hooks:
        if hasattr(h, "begin"):
            h.begin()


def _run_hooks_after_create(hooks, sess):
    for h in hooks:
        if hasattr(h, "after_create_session"):
            h.after_create_session(sess, None)


class Estimator:
    """Graph-mode train/evaluate/predict driver around a ``model_fn``.

    ``model_dir=None`` disables checkpointing — the distributed-training
    convention where only rank 0 persists state (SURVEY.md §5.4)."""

    def __init__(self, model_fn: Callable, model_dir: Optional[str] = None):
        self._model_fn = model_fn
        self._model_dir = model_dir
        # Trained variable values kept in memory so evaluate()/predict()
        # warm-start even with model_dir=None (the every-rank-but-0
        # convention) — real tf.estimator warm-starts from its own
        # temp-dir checkpoint in that case; we keep the analogue in RAM
        # instead of inventing temp files on non-checkpointing ranks.
        self._warm_start: Optional[Dict[str, "object"]] = None

    def _ckpt_prefix(self):
        return os.path.join(self._model_dir, "model.ckpt")

    def _maybe_restore(self, sess, saver):
        if self._model_dir is not None and saver is not None:
            latest = v1.train.latest_checkpoint(self._model_dir)
            if latest:
                saver.restore(sess, latest)
                return
        if self._warm_start is not None:
            # Assign cached trained values into same-named variables of
            # the freshly built graph (shape-checked; unmatched names —
            # e.g. new metric locals — keep their initializer values).
            for var in v1.global_variables():
                value = self._warm_start.get(var.op.name)
                if value is not None and tuple(var.shape) == value.shape:
                    var.load(value, sess)

    def train(self, input_fn, steps: int, hooks=()):
        hooks = list(hooks or ())
        with tf.Graph().as_default():
            global_step = v1.train.get_or_create_global_step()
            features, labels = input_fn()
            spec = self._model_fn(features, labels, ModeKeys.TRAIN)
            if spec.train_op is None:
                raise ValueError("model_fn returned no train_op for TRAIN")
            _run_hooks_begin(hooks)
            saver = v1.train.Saver() if self._model_dir else None
            with v1.Session() as sess:
                sess.run(v1.global_variables_initializer())
                sess.run(v1.local_variables_initializer())
                self._maybe_restore(sess, saver)
                _run_hooks_after_create(hooks, sess)
                loss = None
                for _ in range(int(steps)):
                    _, loss = sess.run([spec.train_op, spec.loss])
                if saver is None:
                    # Non-checkpointing rank: keep trained values in RAM
                    # so evaluate()/predict() warm-start (checkpointing
                    # ranks restore from the checkpoint instead).
                    variables = v1.global_variables()
                    self._warm_start = dict(
                        zip((var.op.name for var in variables),
                            sess.run(variables)))
                else:
                    os.makedirs(self._model_dir, exist_ok=True)
                    saver.save(sess, self._ckpt_prefix(),
                               global_step=global_step)
                return loss

    def evaluate(self, input_fn, hooks=()):
        hooks = list(hooks or ())
        with tf.Graph().as_default():
            v1.train.get_or_create_global_step()
            features, labels = input_fn()
            spec = self._model_fn(features, labels, ModeKeys.EVAL)
            _run_hooks_begin(hooks)
            value_ops = {k: m[0] for k, m in spec.eval_metric_ops.items()}
            update_ops = [m[1] for m in spec.eval_metric_ops.values()]
            saver = v1.train.Saver() if self._model_dir else None
            with v1.Session() as sess:
                sess.run(v1.global_variables_initializer())
                sess.run(v1.local_variables_initializer())
                self._maybe_restore(sess, saver)
                _run_hooks_after_create(hooks, sess)
                total_loss, batches = 0.0, 0
                try:
                    while True:
                        out = sess.run({"loss": spec.loss,
                                        "updates": update_ops})
                        total_loss += float(out["loss"])
                        batches += 1
                except tf.errors.OutOfRangeError:
                    pass
                results = sess.run(value_ops)
                results["loss"] = total_loss / max(batches, 1)
                results["global_step"] = int(
                    sess.run(v1.train.get_global_step()))
                return results

    def predict(self, input_fn, hooks=()):
        hooks = list(hooks or ())
        with tf.Graph().as_default():
            v1.train.get_or_create_global_step()
            batch = input_fn()
            features = batch[0] if isinstance(batch, tuple) else batch
            spec = self._model_fn(features, None, ModeKeys.PREDICT)
            _run_hooks_begin(hooks)
            saver = v1.train.Saver() if self._model_dir else None
            with v1.Session() as sess:
                sess.run(v1.global_variables_initializer())
                sess.run(v1.local_variables_initializer())
                self._maybe_restore(sess, saver)
                _run_hooks_after_create(hooks, sess)
                try:
                    while True:
                        out = sess.run(spec.predictions)
                        # unbatch dict-of-arrays into per-example dicts
                        n = len(next(iter(out.values())))
                        for i in range(n):
                            yield {k: val[i] for k, val in out.items()}
                except tf.errors.OutOfRangeError:
                    return
