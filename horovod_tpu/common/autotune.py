"""Online autotuning: Python seam over the engine's ParameterManager.

The engine's two dominant performance knobs — the tensor-fusion threshold
and the negotiation cycle time — defaulted to static env values no single
workload agrees on.  With ``HVD_TPU_AUTOTUNE=1`` the rank-0 coordinator
scores each tuning window from the throughput it already observes (payload
bytes of every negotiated collective / wall time over the window), walks a
coordinate-descent hill-climb over log-spaced candidate grids
(warmup -> climb -> freeze at the best point seen; engine/cc/autotune.cc),
and broadcasts each candidate inside the coordinator response list so
EVERY rank applies it at the same tick boundary — the lockstep-mutation
contract the negotiation response cache established.  See
``docs/performance.md`` ("Autotuning").

This module holds the Python half: the env-spec parsing ``hvd.init()``
feeds the engine, and the report/control helpers behind
``hvd.autotune_report()`` / ``hvd.autotune_set()``.  ``autotune_set`` is
the pluggable-policy seam: a custom policy runs wherever you like (rank
0), reads ``hvd.metrics_snapshot()``, and injects its own candidates —
the engine still does the lockstep broadcast, so every rank stays in
step no matter who proposes.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

# Candidate grids, mirrored from engine/cc/autotune.cc (keep in sync):
# log-spaced, spanning the negotiation-bound 32 B-allreduce regime to
# 100 MB CNN gradient buckets.  The compression axis is the CompressionMode
# codes by wire aggressiveness; it is searchable only when the job opted
# into compression (HVD_TPU_COMPRESSION != off) — the tuner must never
# turn a lossy wire format on for a job that asked for exact fp32.
FUSION_GRID: Tuple[int, ...] = tuple(
    v << 10 for v in (64, 256, 1024, 4096, 16384, 65536, 262144))
CYCLE_GRID_MS: Tuple[float, ...] = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0)
COMPRESSION_GRID: Tuple[str, ...] = ("off", "bf16", "fp8")
# The fourth axis (docs/performance.md#two-level-topology): the byte
# boundary under which a two-level bucket's cross-node hop takes the
# recursive-doubling tree instead of the ring.  Searchable only on the
# hierarchical topology — the flat ring pins it (dead knob).
CROSS_ALGO_GRID: Tuple[int, ...] = (0, 16 << 10, 64 << 10, 256 << 10,
                                    1 << 20)

# Knob names accepted by HVD_TPU_AUTOTUNE_FIX (and their report keys).
KNOBS = ("fusion_threshold", "cycle_time_ms", "compression",
         "cross_algo_threshold")


def parse_fix(spec: str) -> Tuple[int, float, int, int]:
    """Parse ``HVD_TPU_AUTOTUNE_FIX`` ("k=v,..." with knobs from
    :data:`KNOBS`) into the engine's pin values ``(fix_fusion_bytes,
    fix_cycle_ms, fix_compression_code, fix_cross_algo_bytes)``; -1 means
    "tune this knob".  Raises ``ValueError`` on unknown knobs or
    unparsable/negative values — a silently dropped pin would tune a knob
    the user asked to hold."""
    from horovod_tpu.common.config import parse_compression

    fix_fusion, fix_cycle, fix_comp, fix_algo = -1, -1.0, -1, -1
    for clause in (spec or "").split(","):
        clause = clause.strip()
        if not clause:
            continue
        key, sep, value = clause.partition("=")
        key = key.strip()
        if not sep or key not in KNOBS:
            raise ValueError(
                f"HVD_TPU_AUTOTUNE_FIX: bad clause {clause!r} (want "
                f"k=v with k in {KNOBS})")
        if key == "compression":
            try:
                fix_comp = parse_compression(value)
            except ValueError:
                raise ValueError(
                    f"HVD_TPU_AUTOTUNE_FIX: bad value in {clause!r} "
                    f"(want compression=off|bf16|fp8)") from None
            continue
        try:
            num = float(value)
        except ValueError:
            raise ValueError(
                f"HVD_TPU_AUTOTUNE_FIX: bad value in {clause!r}") from None
        if num < 0:
            raise ValueError(
                f"HVD_TPU_AUTOTUNE_FIX: negative value in {clause!r}")
        if key == "fusion_threshold":
            fix_fusion = int(num)
        elif key == "cross_algo_threshold":
            fix_algo = int(num)
        else:
            fix_cycle = num
    return fix_fusion, fix_cycle, fix_comp, fix_algo


@dataclasses.dataclass(frozen=True)
class WindowRecord:
    """One scored tuning window (coordinator side): the parameters it ran
    under and the throughput score it measured (bytes+ops per second)."""
    window: int
    fusion_threshold: int
    cycle_time_ms: float
    score: float


def _parse_log(raw: str, fields) -> List[dict]:
    """Parse an engine "a|b|c|...;..." log into dicts; `fields` pairs each
    position with (name, converter).  Malformed entries are skipped — the
    C side writes them, so a mismatch means version skew, not user input."""
    out = []
    for entry in raw.split(";"):
        if not entry:
            continue
        parts = entry.split("|")
        if len(parts) != len(fields):
            continue
        try:
            out.append({name: conv(part)
                        for part, (name, conv) in zip(parts, fields)})
        except ValueError:
            continue
    return out


def _cycle_ms(us: str) -> float:
    return int(us) / 1000.0


def _comp_name(code: str) -> str:
    from horovod_tpu.common.config import COMPRESSION_NAMES

    return COMPRESSION_NAMES.get(int(code), code)


_HISTORY_FIELDS = (("window", int), ("fusion_threshold", int),
                   ("cycle_time_ms", _cycle_ms),
                   ("compression", _comp_name),
                   ("cross_algo_threshold", int), ("score", float))
_APPLIED_FIELDS = (("tick", int), ("fusion_threshold", int),
                   ("cycle_time_ms", _cycle_ms),
                   ("compression", _comp_name),
                   ("cross_algo_threshold", int),
                   ("frozen", lambda v: v == "1"))


def report(lib) -> dict:
    """The autotuning report read from the (loaded) engine library:
    current applied parameters (lockstep — identical on every rank of a
    healthy job), freeze state, and the coordinator's per-window search
    history.  Workers see an empty ``history`` (the search runs at rank
    0) but a full ``applied`` log."""
    from horovod_tpu.common.config import COMPRESSION_NAMES

    return {
        "enabled": bool(lib.hvd_tpu_autotune_enabled()),
        "frozen": bool(lib.hvd_tpu_autotune_frozen()),
        "windows": int(lib.hvd_tpu_autotune_windows()),
        "fusion_threshold": int(lib.hvd_tpu_autotune_fusion_threshold()),
        "cycle_time_ms": int(lib.hvd_tpu_autotune_cycle_time_us()) / 1000.0,
        "compression": COMPRESSION_NAMES.get(
            int(lib.hvd_tpu_compression_mode()), "off"),
        "cross_algo_threshold": int(
            lib.hvd_tpu_autotune_cross_algo_threshold()),
        "best_score": float(lib.hvd_tpu_autotune_best_score()),
        "history": _parse_log(
            lib.hvd_tpu_autotune_history().decode(), _HISTORY_FIELDS),
        "applied": _parse_log(
            lib.hvd_tpu_autotune_applied().decode(), _APPLIED_FIELDS),
    }


def empty_report() -> dict:
    """The report shape before any engine exists — keeps
    ``metrics_snapshot()["autotune"]`` structurally stable (ungated)."""
    return {"enabled": False, "frozen": False, "windows": 0,
            "fusion_threshold": 0, "cycle_time_ms": 0.0,
            "compression": "off", "cross_algo_threshold": 0,
            "best_score": 0.0, "history": [], "applied": []}


def set_params(lib, fusion_threshold: Optional[int] = None,
               cycle_time_ms: Optional[float] = None,
               compression: Optional[str] = None,
               cross_algo_threshold: Optional[int] = None) -> None:
    """Inject parameters for lockstep broadcast at the next tick (rank 0
    only — the coordinator owns the broadcast).  The engine applies them
    on every rank at the same tick boundary, exactly like search
    candidates; a live search resumes from the nearest grid point."""
    from horovod_tpu.common.config import parse_compression

    if (fusion_threshold is None and cycle_time_ms is None
            and compression is None and cross_algo_threshold is None):
        raise ValueError(
            "autotune_set: provide fusion_threshold, cycle_time_ms, "
            "compression, and/or cross_algo_threshold")
    if fusion_threshold is not None and int(fusion_threshold) < 0:
        raise ValueError("autotune_set: fusion_threshold must be >= 0")
    if cycle_time_ms is not None and float(cycle_time_ms) < 0:
        raise ValueError("autotune_set: cycle_time_ms must be >= 0")
    if cross_algo_threshold is not None and int(cross_algo_threshold) < 0:
        raise ValueError("autotune_set: cross_algo_threshold must be >= 0")
    comp_code = -1
    if compression is not None:
        try:
            comp_code = parse_compression(compression)
        except ValueError:
            raise ValueError(
                f"autotune_set: unknown compression mode {compression!r} "
                f"(want off, bf16, or fp8)") from None
    rc = lib.hvd_tpu_autotune_set(
        -1 if fusion_threshold is None else int(fusion_threshold),
        -1.0 if cycle_time_ms is None else float(cycle_time_ms),
        comp_code,
        -1 if cross_algo_threshold is None else int(cross_algo_threshold))
    if rc == 1:
        raise ValueError(
            "autotune_set: only rank 0 (the coordinator) can inject "
            "parameters; run your tuning policy there.")
    if rc != 0:
        from horovod_tpu.common import HorovodNotInitializedError

        raise HorovodNotInitializedError(
            "Horovod-TPU has not been initialized; use hvd.init().")
