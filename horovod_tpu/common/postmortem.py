"""Postmortem plane: flight-recorder drains and crash/hang dump files.

PRs 2/6 turned failures into *typed* errors, but a typed
``CollectiveTimeoutError`` still tells you *that* the job hung, not *why*.
This module makes every abort, hang, and crash leave a self-explaining
artifact: with ``HVD_TPU_POSTMORTEM_DIR`` set, each rank writes ONE
``rank-<N>.json`` (``rank-<N>.e<E>.json`` on restart epochs) the first
time it dies a typed death — a coordinated abort
(``RanksDownError``/``CollectiveTimeoutError``), a fatal uncaught Python
exception, an injected crash (``common/faults.py``), or an abort latched
by the engine when ``shutdown()`` runs.  The dump carries:

* the drained **flight recorder** rings of both data planes — the engine's
  C++ ring (``engine/cc/flight.{h,cc}``, the last N control-plane events
  with epoch-anchored timestamps) and the Python-side ring the XLA plane
  records into (:data:`plane_ring`);
* the **pending-tensor tables**: which collectives were in flight on this
  rank, and (rank 0) which ranks each stalled negotiation was waiting on;
* the **cross-rank diagnosis** the coordinator folded into the abort
  message on the hang path ("rank 2 stopped announcing after tick 1841");
* current **membership epoch**, applied **autotune** parameters, and a
  full **metrics snapshot**.

``tools/postmortem_dump.py`` renders a dump directory into the human
story; ``hvdrun --postmortem-dir`` sets the env for every rank and points
at the first-failing rank's dump in its failure report.  Dumps are
write-once per process (first death wins — later failure paths are the
kill cascade, not the root cause) and atomically renamed into place so a
mid-write SIGKILL cannot leave a half-parseable file.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
from typing import List, Optional

# Flight-recorder event names, shared with engine/cc/flight.cc
# (FlightEventName) — the engine serializes names, so Python only needs
# this list for tools/docs, not for parsing.
EVENTS = ("enqueue", "announce", "cache_hit", "execute", "error", "tick",
          "stall", "abort", "reshape", "tune", "compress", "topology",
          "steady", "heartbeat_miss", "anomaly", "transport")

DEFAULT_RING_EVENTS = 512

_write_lock = threading.Lock()
_written_path: Optional[str] = None


def postmortem_dir() -> str:
    """The dump directory (``HVD_TPU_POSTMORTEM_DIR``); empty = disabled."""
    return os.environ.get("HVD_TPU_POSTMORTEM_DIR", "")


def ring_capacity() -> int:
    """``HVD_TPU_FLIGHT_EVENTS`` (shared with the C++ ring); 0 disables.
    Read through Config so the Python ring and the documented knob cannot
    drift (the engine's own getenv parse runs only after Config.from_env
    validated the value at init)."""
    from horovod_tpu.common.config import Config

    try:
        cap = Config.from_env().flight_events
    except ValueError:
        cap = DEFAULT_RING_EVENTS
    return max(0, min(cap, 65536))


class FlightRing:
    """Python-side flight recorder (XLA plane / app events): the same
    bounded always-on ring the engine keeps in C++, for the code paths
    that never enter it.  Lock-cheap by the same argument — a handful of
    control-plane events per collective."""

    def __init__(self, capacity: Optional[int] = None):
        cap = ring_capacity() if capacity is None else capacity
        self.enabled = cap > 0
        self._ring = collections.deque(maxlen=max(cap, 1))
        self._lock = threading.Lock()
        self._epoch = time.monotonic()
        self._seq = 0
        self.total = 0  # cumulative, survives drain (metrics contract)

    def record(self, event: str, name: str = "", arg: int = 0) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._ring.append({
                "seq": self._seq,
                "ts_us": int((time.monotonic() - self._epoch) * 1e6),
                "event": event, "name": name, "arg": int(arg),
            })
            self._seq += 1
            self.total += 1

    def drain(self) -> List[dict]:
        """Oldest-first snapshot (non-destructive, like the C++ Dump)."""
        with self._lock:
            return [dict(e) for e in self._ring]


# The XLA plane's ring (jax/eager_mesh.py records into it); created at
# import so recording sites pay one attribute read when disabled.
plane_ring = FlightRing()


def parse_engine_ring(raw: str) -> List[dict]:
    """Decode the engine's ``seq|ts_us|event|name|arg;...`` ring dump."""
    events = []
    for entry in raw.split(";"):
        if not entry:
            continue
        parts = entry.split("|")
        if len(parts) != 5:
            continue
        try:
            events.append({"seq": int(parts[0]), "ts_us": int(parts[1]),
                           "event": parts[2], "name": parts[3],
                           "arg": int(parts[4])})
        except ValueError:
            continue
    return events


def _parse_pending_local(raw: str) -> List[dict]:
    out = []
    for entry in raw.split(";"):
        parts = entry.split("|")
        if len(parts) != 3:
            continue
        try:
            out.append({"name": parts[0], "op": parts[1],
                        "age_sec": int(parts[2]) / 1e6})
        except ValueError:
            continue
    return out


def _parse_pending_coord(raw: str) -> List[dict]:
    out = []
    for entry in raw.split(";"):
        parts = entry.split("|")
        if len(parts) != 3:
            continue
        try:
            out.append({"name": parts[0], "age_sec": int(parts[1]) / 1e6,
                        "missing_ranks": [int(r) for r in parts[2].split()
                                          if r]})
        except ValueError:
            continue
    return out


def written_path() -> Optional[str]:
    """Path of the dump this process wrote, if any (tests, reports).
    None while no dump exists — including mid-write, when the slot is
    claimed but the file is not on disk yet."""
    return _written_path or None


def _resolve_rank() -> int:
    from horovod_tpu import common

    if common._process_set is not None:
        return common._process_set.rank
    lib = common._lib
    if lib is not None and lib.hvd_tpu_initialized():
        return int(lib.hvd_tpu_rank())
    try:
        return int(os.environ.get("HVD_TPU_RANK") or 0)
    except ValueError:
        return 0


def write_postmortem(reason: str,
                     exc: Optional[BaseException] = None) -> Optional[str]:
    """Write this rank's postmortem dump; returns its path, or None when
    the dir is unset or a dump was already written (first death wins).
    Never raises: a failing dump writer must not mask the real error."""
    global _written_path
    directory = postmortem_dir()
    if not directory:
        return None
    with _write_lock:
        if _written_path is not None:
            return None
        _written_path = ""  # claim before the slow work below
    try:
        path = _write(directory, reason, exc)
        with _write_lock:
            _written_path = path
        return path
    except Exception as write_exc:  # pragma: no cover - best effort
        import warnings

        # Release the claim: a transient failure (dir briefly unwritable)
        # must not stop a later death path from leaving the artifact.
        with _write_lock:
            _written_path = None
        warnings.warn(f"could not write postmortem dump: {write_exc}")
        return None


def _write(directory: str, reason: str,
           exc: Optional[BaseException]) -> str:
    from horovod_tpu import common

    lib = common._lib
    rank = _resolve_rank()
    engine_up = lib is not None and bool(lib.hvd_tpu_initialized())
    doc = {
        "schema": 1,
        "rank": rank,
        "size": int(lib.hvd_tpu_size()) if engine_up else 0,
        "restart_epoch": common.restart_epoch(),
        "membership_epoch": common.membership_epoch(),
        "reason": reason,
        "written_unix": time.time(),
    }
    if exc is not None:
        doc["exception"] = {"type": type(exc).__name__,
                            "message": str(exc)[:4000]}
    if lib is not None:
        doc["abort"] = {"code": int(lib.hvd_tpu_abort_code()),
                        "message": lib.hvd_tpu_abort_message().decode()}
        diag = lib.hvd_tpu_diagnosis().decode()
        # Workers receive the diagnosis inside the broadcast abort
        # message; Diagnosis() extracts the paragraph on every rank.
        doc["diagnosis"] = diag or None
        doc["ring"] = {
            "engine": parse_engine_ring(lib.hvd_tpu_flight_dump().decode()),
            "xla": plane_ring.drain(),
        }
        doc["pending"] = {
            "local": _parse_pending_local(
                lib.hvd_tpu_pending_info().decode()),
            "coordinator": _parse_pending_coord(
                lib.hvd_tpu_coord_pending_info().decode()),
        }
    else:
        doc["abort"] = {"code": 0, "message": ""}
        doc["diagnosis"] = None
        doc["ring"] = {"engine": [], "xla": plane_ring.drain()}
        doc["pending"] = {"local": [], "coordinator": []}
    try:
        doc["autotune"] = common.autotune_report()
    except Exception:
        doc["autotune"] = {}
    # State plane (docs/fault-tolerance.md#state-plane): the last
    # committed snapshot step + peer-copy freshness answer the operator's
    # first postmortem question — "how much work did this death cost?".
    try:
        from horovod_tpu import state as _state

        plane = _state.current()
        doc["state"] = plane.status() if plane is not None else None
    except Exception:
        doc["state"] = None
    try:
        doc["metrics"] = common.metrics_snapshot()
    except Exception:
        doc["metrics"] = {}
    # Active data-plane transport, top-level: which path (shm rings vs TCP
    # sockets) the node-local hops ran on, and per peer — so the failure
    # report and renderer answer "was shared memory in play?" without
    # digging through the embedded metrics snapshot.
    metrics = doc["metrics"] if isinstance(doc["metrics"], dict) else {}
    doc["transport"] = {
        "local": str(metrics.get("topology", {})
                     .get("local_transport", "tcp")),
        "peers": {str(r): str(v.get("transport", "tcp"))
                  for r, v in metrics.get("links", {})
                                     .get("peers", {}).items()},
    }
    os.makedirs(directory, exist_ok=True)
    epoch = common.restart_epoch()
    suffix = f".e{epoch}" if epoch else ""
    path = os.path.join(directory, f"rank-{rank}{suffix}.json")
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    os.replace(tmp, path)
    # Crashed ranks must leave their metrics too (the timeline already
    # flushes on these paths; the metrics file rides the same hook).
    common._flush_metrics_file(clear=False)
    print(f"[horovod_tpu] postmortem dump written: {path}",
          file=sys.stderr, flush=True)
    return path


_REASON_BY_CODE = {6: "ranks_down", 7: "timeout"}


def reason_for_code(code: int) -> str:
    return _REASON_BY_CODE.get(int(code), f"abort_{int(code)}")


_excepthook_installed = False


def install_excepthook() -> None:
    """Chain a ``sys.excepthook`` that writes a postmortem for fatal
    uncaught exceptions (KeyboardInterrupt/SystemExit excluded: an
    operator ^C or a deliberate exit is not a postmortem)."""
    global _excepthook_installed
    if _excepthook_installed:
        return
    _excepthook_installed = True
    previous = sys.excepthook

    def hook(exc_type, exc, tb):
        if not issubclass(exc_type, (KeyboardInterrupt, SystemExit)):
            write_postmortem("exception", exc)
        previous(exc_type, exc, tb)

    sys.excepthook = hook


def dump_path_for(directory: str, rank: int) -> Optional[str]:
    """Newest existing dump for `rank` in `directory` (restart-epoch
    suffixed files included), or None."""
    import glob

    candidates = [os.path.join(directory, f"rank-{rank}.json")]
    candidates += sorted(
        glob.glob(os.path.join(directory, f"rank-{rank}.e*.json")))
    existing = [p for p in candidates if os.path.exists(p)]
    if not existing:
        return None
    return max(existing, key=os.path.getmtime)
