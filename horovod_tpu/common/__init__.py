"""Python seam to the native collective engine.

Counterpart of the reference's horovod/common/__init__.py (ctypes CDLL load,
init/shutdown/rank/size/local_rank/... wrappers raising ValueError when
uninitialized) plus the numpy-level async collective API that every framework
binding builds on (the role the torch cffi interface plays in the reference,
/root/reference/horovod/torch/interface.h).
"""

from __future__ import annotations

import atexit
import contextlib
import ctypes
import json
import os
import threading
import time
import weakref
from typing import Any, Optional, Sequence, Union

import numpy as np

from horovod_tpu.common import dtypes, metrics
from horovod_tpu.common.basics import ProcessSet, resolve_process_set
from horovod_tpu.common.config import Config

# Op codes shared with the C++ engine (engine/cc/wire.h OpType).
OP_ALLREDUCE = 0
OP_ALLGATHER = 1
OP_BROADCAST = 2
# Negotiation-only (no data moves): the XLA plane's metadata-cache fast
# path replays a verified cross-rank agreement through this op to keep
# the global dispatch order without the metadata allreduce.
OP_NOOP = 3
# Point-to-point plane (docs/pipeline.md): negotiated pairwise transfers
# for pipeline parallelism.  A send and its matching recv announce under
# ONE wire name (``<name>.p2p.<src>-<dst>.t<tag>``) and execute when BOTH
# sides are ready (paired readiness).
OP_SEND = 4
OP_RECV = 5

# Status codes (engine/cc/wire.h StatusCode).
ST_OK = 0
ST_UNKNOWN = 1
ST_PRECONDITION = 2
ST_ABORTED = 3
ST_INVALID = 4
ST_PENDING = 5
ST_RANKS_DOWN = 6
ST_TIMEOUT = 7
ST_RESHAPE = 8


class HorovodInternalError(RuntimeError):
    """An unrecoverable engine error (transport failure, shutdown race)."""


class RanksDownError(HorovodInternalError):
    """A coordinated abort because one or more ranks died (control-socket
    EOF at the coordinator, or the coordinator itself went away).  The
    message names the missing ranks and the collectives they left pending;
    ``ranks`` carries them parsed (empty when unparsable).  The job cannot
    make progress — restart it (``hvdrun --max-restarts``) and resume from
    the latest checkpoint (docs/fault-tolerance.md)."""

    def __init__(self, message: str, ranks: Sequence[int] = ()):  # noqa: D107
        super().__init__(message)
        self.ranks = list(ranks)


class CollectiveTimeoutError(HorovodInternalError):
    """A coordinated abort because a collective stalled past
    ``HVD_TPU_COLLECTIVE_TIMEOUT_SEC``: a subset of ranks never submitted
    the matching op (rank-divergent control flow, or a wedged — not dead —
    peer).  The message names the stalled tensors and missing ranks."""


class MembershipChangedError(HorovodInternalError):
    """RETRYABLE (docs/fault-tolerance.md#elastic-membership): the elastic
    job reshaped — ranks died and the survivors re-negotiated size/rank at
    a tick boundary (or a standby was admitted) — and this collective was
    cancelled at the barrier.  No process relaunch or checkpoint reload is
    needed: re-enter agreement and resync state by root broadcast
    (``hvd.run_elastic`` does both).  ``lost_ranks`` names the dead ranks
    in the previous membership's numbering (empty on pure grows)."""

    def __init__(self, message: str, lost_ranks: Sequence[int] = ()):  # noqa: D107
        super().__init__(message)
        self.lost_ranks = list(lost_ranks)


class HorovodNotInitializedError(HorovodInternalError, ValueError):
    """An operation that needs a running engine was called before
    ``hvd.init()`` (or after ``hvd.shutdown()``).  Subclasses ValueError
    for compatibility with the reference's pre-init contract."""


_lib = None
_lib_lock = threading.Lock()
_process_set: Optional[ProcessSet] = None
# XLA data plane (compiled collectives over the accelerator fabric) when
# HVD_TPU_XLA_DATA_PLANE=1; None = disabled/unavailable -> TCP engine.
_xla_plane = None
# Dtypes the XLA plane accepts: jax's default (x64-disabled) world plus the
# half types it widens; everything else (f64, bool, ...) stays on the engine.
_XLA_PLANE_DTYPES = ("float32", "float16", "bfloat16", "int32", "int8",
                     "uint8")
# Metrics plumbing: per-rank JSON dump path (HVD_TPU_METRICS_FILE) and the
# count of engine stall/abort events already folded into the Python
# registry.
_metrics_file: Optional[str] = None
_engine_stalls_seen = 0
_engine_aborts_seen = 0
# Announce-order sync state (straggler attribution): events already folded
# into the Python registry, and the last cumulative per-rank
# last-to-announce counts read from the engine.
_engine_announces_seen = 0
_engine_last_announce_seen: list = []
# Response-cache sync state (docs/performance.md): engine-cumulative
# hit/miss/eviction counts already folded into the registry.
_engine_cache_seen = [0, 0, 0]
# Two-level topology sync state: per-bucket phase records already folded
# into the topology phase histograms (the engine log is bounded; the
# cumulative count keeps totals honest past it).
_engine_topo_seen = 0
# Deterministic fault injection (common/faults.py, HVD_TPU_FAULT_SPEC):
# the injector for this (rank, restart epoch), or None; and the per-process
# submission index of user-level collectives it is driven by.
_fault_injector = None
_collective_seq = 0
_fault_lock = threading.Lock()
# Serializes _sync_engine_stalls: the monitor thread and API callers may
# snapshot concurrently, and the ctypes stall-count read releases the GIL.
_stall_sync_lock = threading.Lock()


def _load_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        from horovod_tpu.engine.build import build

        path = build()
        lib = ctypes.CDLL(path, mode=ctypes.RTLD_GLOBAL)
        lib.hvd_tpu_init.restype = ctypes.c_int
        lib.hvd_tpu_init.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_double,
            ctypes.c_longlong, ctypes.c_double, ctypes.c_char_p,
            ctypes.c_int, ctypes.c_double, ctypes.c_longlong,
            ctypes.c_int, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_longlong, ctypes.c_double, ctypes.c_int,
            ctypes.c_longlong, ctypes.c_int, ctypes.c_int,
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_longlong, ctypes.c_int, ctypes.c_longlong,
            ctypes.c_longlong]
        lib.hvd_tpu_init_error.restype = ctypes.c_char_p
        lib.hvd_tpu_init_error.argtypes = []
        # Every export gets an explicit restype/argtypes — including the
        # void and no-arg ones ctypes would default correctly today —
        # so the hvdlint C-API parity checker can hold the seam to the
        # C signatures (docs/contributing.md#c-api-parity).
        lib.hvd_tpu_shutdown.restype = None
        lib.hvd_tpu_shutdown.argtypes = []
        lib.hvd_tpu_initialized.restype = ctypes.c_int
        lib.hvd_tpu_initialized.argtypes = []
        lib.hvd_tpu_rank.restype = ctypes.c_int
        lib.hvd_tpu_rank.argtypes = []
        lib.hvd_tpu_size.restype = ctypes.c_int
        lib.hvd_tpu_size.argtypes = []
        lib.hvd_tpu_local_rank.restype = ctypes.c_int
        lib.hvd_tpu_local_rank.argtypes = []
        lib.hvd_tpu_local_size.restype = ctypes.c_int
        lib.hvd_tpu_local_size.argtypes = []
        lib.hvd_tpu_enqueue.restype = ctypes.c_longlong
        lib.hvd_tpu_enqueue.argtypes = [
            ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int]
        lib.hvd_tpu_enqueue_p2p.restype = ctypes.c_longlong
        lib.hvd_tpu_enqueue_p2p.argtypes = [
            ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int]
        lib.hvd_tpu_enqueue_group.restype = ctypes.c_longlong
        lib.hvd_tpu_enqueue_group.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.POINTER(ctypes.c_longlong), ctypes.c_int]
        lib.hvd_tpu_p2p_info.restype = ctypes.c_char_p
        lib.hvd_tpu_p2p_info.argtypes = []
        lib.hvd_tpu_poll.restype = ctypes.c_int
        lib.hvd_tpu_poll.argtypes = [ctypes.c_longlong]
        lib.hvd_tpu_wait.restype = ctypes.c_int
        lib.hvd_tpu_wait.argtypes = [ctypes.c_longlong]
        lib.hvd_tpu_status.restype = ctypes.c_int
        lib.hvd_tpu_status.argtypes = [ctypes.c_longlong]
        lib.hvd_tpu_error.restype = ctypes.c_char_p
        lib.hvd_tpu_error.argtypes = [ctypes.c_longlong]
        lib.hvd_tpu_completion_seq.restype = ctypes.c_longlong
        lib.hvd_tpu_completion_seq.argtypes = [ctypes.c_longlong]
        lib.hvd_tpu_completion_tick.restype = ctypes.c_longlong
        lib.hvd_tpu_completion_tick.argtypes = [ctypes.c_longlong]
        lib.hvd_tpu_negotiation_us.restype = ctypes.c_longlong
        lib.hvd_tpu_negotiation_us.argtypes = [ctypes.c_longlong]
        lib.hvd_tpu_ticks_done.restype = ctypes.c_longlong
        lib.hvd_tpu_ticks_done.argtypes = []
        lib.hvd_tpu_result_nbytes.restype = ctypes.c_longlong
        lib.hvd_tpu_result_nbytes.argtypes = [ctypes.c_longlong]
        lib.hvd_tpu_result_dim0.restype = ctypes.c_longlong
        lib.hvd_tpu_result_dim0.argtypes = [ctypes.c_longlong]
        lib.hvd_tpu_result_ptr.restype = ctypes.c_void_p
        lib.hvd_tpu_result_ptr.argtypes = [ctypes.c_longlong]
        lib.hvd_tpu_copy_result.restype = ctypes.c_int
        lib.hvd_tpu_copy_result.argtypes = [
            ctypes.c_longlong, ctypes.c_void_p, ctypes.c_longlong]
        lib.hvd_tpu_release.restype = None
        lib.hvd_tpu_release.argtypes = [ctypes.c_longlong]
        lib.hvd_tpu_stall_count.restype = ctypes.c_longlong
        lib.hvd_tpu_stall_count.argtypes = []
        lib.hvd_tpu_stall_info.restype = ctypes.c_char_p
        lib.hvd_tpu_stall_info.argtypes = []
        lib.hvd_tpu_abort_code.restype = ctypes.c_int
        lib.hvd_tpu_abort_code.argtypes = []
        lib.hvd_tpu_abort_message.restype = ctypes.c_char_p
        lib.hvd_tpu_abort_message.argtypes = []
        lib.hvd_tpu_abort_count.restype = ctypes.c_longlong
        lib.hvd_tpu_abort_count.argtypes = []
        lib.hvd_tpu_clock_offset_us.restype = ctypes.c_longlong
        lib.hvd_tpu_clock_offset_us.argtypes = []
        lib.hvd_tpu_clock_rtt_us.restype = ctypes.c_longlong
        lib.hvd_tpu_clock_rtt_us.argtypes = []
        lib.hvd_tpu_liveness_info.restype = ctypes.c_char_p
        lib.hvd_tpu_liveness_info.argtypes = []
        lib.hvd_tpu_link_info.restype = ctypes.c_char_p
        lib.hvd_tpu_link_info.argtypes = []
        lib.hvd_tpu_anomaly_info.restype = ctypes.c_char_p
        lib.hvd_tpu_anomaly_info.argtypes = []
        lib.hvd_tpu_anomaly_log.restype = ctypes.c_char_p
        lib.hvd_tpu_anomaly_log.argtypes = []
        lib.hvd_tpu_announce_count.restype = ctypes.c_longlong
        lib.hvd_tpu_announce_count.argtypes = []
        lib.hvd_tpu_announce_log.restype = ctypes.c_char_p
        lib.hvd_tpu_announce_log.argtypes = []
        lib.hvd_tpu_last_announce_counts.restype = ctypes.c_char_p
        lib.hvd_tpu_last_announce_counts.argtypes = []
        lib.hvd_tpu_cache_hit_count.restype = ctypes.c_longlong
        lib.hvd_tpu_cache_hit_count.argtypes = []
        lib.hvd_tpu_cache_miss_count.restype = ctypes.c_longlong
        lib.hvd_tpu_cache_miss_count.argtypes = []
        lib.hvd_tpu_cache_eviction_count.restype = ctypes.c_longlong
        lib.hvd_tpu_cache_eviction_count.argtypes = []
        lib.hvd_tpu_cache_size.restype = ctypes.c_longlong
        lib.hvd_tpu_cache_size.argtypes = []
        lib.hvd_tpu_control_info.restype = ctypes.c_char_p
        lib.hvd_tpu_control_info.argtypes = []
        lib.hvd_tpu_steady_active.restype = ctypes.c_int
        lib.hvd_tpu_steady_active.argtypes = []
        lib.hvd_tpu_simscale_run.restype = ctypes.c_int
        lib.hvd_tpu_simscale_run.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_longlong, ctypes.c_int, ctypes.c_int,
            ctypes.c_double, ctypes.c_char_p, ctypes.c_longlong]
        lib.hvd_tpu_autotune_enabled.restype = ctypes.c_int
        lib.hvd_tpu_autotune_enabled.argtypes = []
        lib.hvd_tpu_autotune_frozen.restype = ctypes.c_int
        lib.hvd_tpu_autotune_frozen.argtypes = []
        lib.hvd_tpu_autotune_windows.restype = ctypes.c_longlong
        lib.hvd_tpu_autotune_windows.argtypes = []
        lib.hvd_tpu_autotune_fusion_threshold.restype = ctypes.c_longlong
        lib.hvd_tpu_autotune_fusion_threshold.argtypes = []
        lib.hvd_tpu_autotune_cycle_time_us.restype = ctypes.c_longlong
        lib.hvd_tpu_autotune_cycle_time_us.argtypes = []
        lib.hvd_tpu_autotune_best_score.restype = ctypes.c_double
        lib.hvd_tpu_autotune_best_score.argtypes = []
        lib.hvd_tpu_autotune_history.restype = ctypes.c_char_p
        lib.hvd_tpu_autotune_history.argtypes = []
        lib.hvd_tpu_autotune_applied.restype = ctypes.c_char_p
        lib.hvd_tpu_autotune_applied.argtypes = []
        lib.hvd_tpu_autotune_set.restype = ctypes.c_int
        lib.hvd_tpu_autotune_set.argtypes = [ctypes.c_longlong,
                                             ctypes.c_double,
                                             ctypes.c_longlong,
                                             ctypes.c_longlong]
        lib.hvd_tpu_autotune_cross_algo_threshold.restype = \
            ctypes.c_longlong
        lib.hvd_tpu_autotune_cross_algo_threshold.argtypes = []
        lib.hvd_tpu_topology_info.restype = ctypes.c_char_p
        lib.hvd_tpu_topology_info.argtypes = []
        lib.hvd_tpu_topology_log.restype = ctypes.c_char_p
        lib.hvd_tpu_topology_log.argtypes = []
        lib.hvd_tpu_fusion_threshold_at.restype = ctypes.c_longlong
        lib.hvd_tpu_fusion_threshold_at.argtypes = [ctypes.c_longlong]
        lib.hvd_tpu_compression_mode.restype = ctypes.c_int
        lib.hvd_tpu_compression_mode.argtypes = []
        lib.hvd_tpu_compression_mode_at.restype = ctypes.c_longlong
        lib.hvd_tpu_compression_mode_at.argtypes = [ctypes.c_longlong]
        lib.hvd_tpu_compression_info.restype = ctypes.c_char_p
        lib.hvd_tpu_compression_info.argtypes = []
        lib.hvd_tpu_compression_log.restype = ctypes.c_char_p
        lib.hvd_tpu_compression_log.argtypes = []
        lib.hvd_tpu_elastic_enabled.restype = ctypes.c_int
        lib.hvd_tpu_elastic_enabled.argtypes = []
        lib.hvd_tpu_membership_epoch.restype = ctypes.c_longlong
        lib.hvd_tpu_membership_epoch.argtypes = []
        lib.hvd_tpu_membership_reshapes.restype = ctypes.c_longlong
        lib.hvd_tpu_membership_reshapes.argtypes = []
        lib.hvd_tpu_membership_info.restype = ctypes.c_char_p
        lib.hvd_tpu_membership_info.argtypes = []
        lib.hvd_tpu_membership_ack_pending.restype = ctypes.c_int
        lib.hvd_tpu_membership_ack_pending.argtypes = []
        lib.hvd_tpu_membership_ack.restype = None
        lib.hvd_tpu_membership_ack.argtypes = []
        lib.hvd_tpu_timeline_enabled.restype = ctypes.c_int
        lib.hvd_tpu_timeline_enabled.argtypes = []
        lib.hvd_tpu_timeline_op_start.restype = None
        lib.hvd_tpu_timeline_op_start.argtypes = [ctypes.c_char_p,
                                                  ctypes.c_char_p]
        lib.hvd_tpu_timeline_activity_start.restype = None
        lib.hvd_tpu_timeline_activity_start.argtypes = [ctypes.c_char_p,
                                                        ctypes.c_char_p]
        lib.hvd_tpu_timeline_activity_end.restype = None
        lib.hvd_tpu_timeline_activity_end.argtypes = [ctypes.c_char_p]
        lib.hvd_tpu_timeline_op_end.restype = None
        lib.hvd_tpu_timeline_op_end.argtypes = [ctypes.c_char_p,
                                                ctypes.c_longlong]
        lib.hvd_tpu_timeline_instant.restype = None
        lib.hvd_tpu_timeline_instant.argtypes = [ctypes.c_char_p,
                                                 ctypes.c_char_p]
        lib.hvd_tpu_timeline_flush.restype = None
        lib.hvd_tpu_timeline_flush.argtypes = []
        lib.hvd_tpu_flight_count.restype = ctypes.c_longlong
        lib.hvd_tpu_flight_count.argtypes = []
        lib.hvd_tpu_flight_dump.restype = ctypes.c_char_p
        lib.hvd_tpu_flight_dump.argtypes = []
        lib.hvd_tpu_pending_info.restype = ctypes.c_char_p
        lib.hvd_tpu_pending_info.argtypes = []
        lib.hvd_tpu_coord_pending_info.restype = ctypes.c_char_p
        lib.hvd_tpu_coord_pending_info.argtypes = []
        lib.hvd_tpu_diagnosis.restype = ctypes.c_char_p
        lib.hvd_tpu_diagnosis.argtypes = []
        _lib = lib
        return lib


def _resolve_timeline_path(path: str, rank: int, epoch: int = 0) -> str:
    """Resolve ``HOROVOD_TIMELINE``'s forms (docs/timeline.md) to this
    rank's trace path: a ``%d`` template or a directory (existing, or a
    trailing-separator path) yield one Chrome-trace file PER RANK; a plain
    file path keeps the legacy rank-0-only single file.  A non-zero
    restart epoch (``hvdrun --max-restarts``) lands in the filename
    (``rank<N>.e<E>.json``) so a relaunch cannot truncate the crashed
    attempt's post-mortem traces."""
    if not path:
        return ""
    suffix = f".e{epoch}" if epoch else ""
    if "%d" in path:
        resolved = path.replace("%d", str(rank)) + suffix
        parent = os.path.dirname(resolved)
        if parent:
            os.makedirs(parent, exist_ok=True)
        return resolved
    if path.endswith(os.sep) or os.path.isdir(path):
        os.makedirs(path, exist_ok=True)
        return os.path.join(path, f"rank{rank}{suffix}.json")
    return path if rank == 0 else ""


def init(comm: Union[Sequence[int], Any, None] = None) -> None:
    """Initialize the engine.

    ``comm`` optionally restricts the job to a subset of launcher ranks —
    either a rank list or an mpi4py(-style) communicator, mirroring both
    forms the reference accepts
    (/root/reference/horovod/common/__init__.py:51-78; the communicator
    is duck-typed via ``Get_size``/``allgather``, see
    :func:`horovod_tpu.common.basics.comm_ranks` — no MPI dependency).
    """
    global _process_set
    lib = _load_lib()
    if lib.hvd_tpu_initialized():
        return
    if comm is not None and hasattr(comm, "Get_size"):
        from horovod_tpu.common.basics import comm_ranks

        comm = comm_ranks(comm, resolve_process_set(None).rank)
    ps = resolve_process_set(comm)
    cfg = Config.from_env()
    # A rejoining standby's rank is a placeholder until the coordinator
    # admits it, so a rank-keyed timeline path would collide with the live
    # rank that currently owns that number; standbys skip the timeline.
    timeline = ("" if cfg.rejoin else
                _resolve_timeline_path(cfg.timeline_path, ps.rank,
                                       cfg.restart_epoch))
    data = ",".join(ps.data_endpoints) if ps.data_endpoints else ""
    from horovod_tpu.common import autotune as _autotune

    # Pin-spec and compression-spec errors must surface at init, not be
    # silently dropped into a knob the user asked to hold
    # (common/autotune.py, common/config.py).
    fix_fusion, fix_cycle, fix_comp, fix_algo = _autotune.parse_fix(
        cfg.autotune_fix)
    compression_code = cfg.compression_code  # ValueError on a bad mode
    if fix_comp > 0 and compression_code == 0:
        # The engine pins the autotune axis at "none" whenever the job
        # did not opt into compression (a tuner must never make an exact
        # job lossy) — so a lossy pin here would be silently dropped,
        # the exact failure mode parse_fix exists to reject.  (A lossy
        # pin WITH the two-level topology is fine: the negotiated mode
        # narrows the cross-node/DCN hop there.)
        raise ValueError(
            "HVD_TPU_AUTOTUNE_FIX pins a lossy wire-compression mode but "
            "HVD_TPU_COMPRESSION is off; set HVD_TPU_COMPRESSION=bf16|fp8 "
            "(or drop the compression pin).")
    if fix_algo >= 0 and not cfg.hierarchical_allreduce:
        # The cross-algo axis only means anything on the two-level
        # topology; the flat ring pins it silently at the env value, so
        # an explicit pin there would be dropped — the same parse_fix
        # contract the compression pin enforces.
        raise ValueError(
            "HVD_TPU_AUTOTUNE_FIX pins cross_algo_threshold but the flat "
            "ring has no cross-node hop; set "
            "HVD_TPU_HIERARCHICAL_ALLREDUCE=1 (or drop the pin).")
    rc = lib.hvd_tpu_init(
        ps.rank, ps.size, ps.local_rank, ps.local_size,
        (ps.coord_endpoint or "").encode(), data.encode(),
        cfg.cycle_time_ms, cfg.fusion_threshold, cfg.stall_warning_sec,
        timeline.encode(), int(cfg.hierarchical_allreduce),
        cfg.collective_timeout_sec, cfg.effective_cache_capacity,
        int(cfg.autotune), cfg.autotune_warmup, cfg.autotune_window,
        fix_fusion, fix_cycle, int(cfg.elastic or cfg.rejoin),
        cfg.min_np, int(cfg.rejoin), compression_code,
        cfg.compression_min_bytes, fix_comp, cfg.cross_algo_threshold,
        fix_algo, int(cfg.coord_tree), cfg.steady_threshold,
        cfg.steady_max_period)
    if rc != 0:
        raise HorovodInternalError(
            "engine initialization failed: "
            + lib.hvd_tpu_init_error().decode())
    _process_set = ps
    if cfg.restart_epoch:
        # Identify relaunched runs in metrics snapshots/dumps.
        metrics.registry.set_restart_epoch(cfg.restart_epoch)
    # Metrics: enabled by HVD_TPU_METRICS=1 or implied by a dump file /
    # monitor port (docs/metrics.md).  The monitor binds port+local_rank
    # so several ranks on one host coexist; rank 0's local_rank is 0, so
    # the scrape example `curl localhost:$HVD_TPU_MONITOR_PORT/metrics`
    # always hits rank 0.
    global _metrics_file
    if cfg.metrics_enabled:
        metrics.registry.enable()
    _metrics_file = (f"{cfg.metrics_file}.{ps.rank}"
                     if cfg.metrics_file else None)
    # Postmortem plane (docs/troubleshooting.md#reading-a-postmortem):
    # with a dump dir set, fatal uncaught exceptions leave a rank dump
    # too (typed aborts and injected crashes hook their own paths).
    if cfg.postmortem_dir:
        from horovod_tpu.common import postmortem as _postmortem

        _postmortem.install_excepthook()
    if cfg.monitor_port is not None:
        port = cfg.monitor_port + ps.local_rank if cfg.monitor_port else 0
        try:
            metrics.start_monitor(port, snapshot_fn=metrics_snapshot)
            # Job-level aggregation (docs/metrics.md#cluster): rank 0's
            # monitor additionally serves /cluster, one merged health view
            # scraped from every rank's /health.  Needs a fixed base port
            # (port 0 binds randomly — peers become unscrapable).
            if ps.rank == 0 and cfg.monitor_port:
                metrics.configure_cluster(
                    _cluster_targets(ps, cfg.monitor_port))
        except OSError as exc:
            import warnings

            # A busy port must not take down the training job; metrics
            # stay collectable through the API and the shutdown dump.
            warnings.warn(f"metrics monitor could not bind port {port}: "
                          f"{exc}; continuing without the HTTP endpoint.")
    # XLA data plane selection.  Like the reference's NCCL path — which
    # auto-selected whenever NCCL was compiled in, no runtime flag
    # (/root/reference/horovod/common/operations.cc:861-914) — the plane
    # is AUTO-enabled when jax reports TPU devices; HVD_TPU_XLA_DATA_PLANE
    # (or HOROVOD_XLA_DATA_PLANE) forces it on (=1) or off (=0).
    global _xla_plane
    auto = cfg.xla_data_plane is None
    enable = _tpu_visible() if auto else cfg.xla_data_plane
    if cfg.elastic or cfg.rejoin:
        # Elastic membership rides the TCP engine only: the XLA plane's
        # device mesh is fixed at init and cannot survive a reshape, and a
        # standby must not enqueue the init-time plane agreement into a
        # job that is not running one.
        if enable and not auto:
            import warnings

            warnings.warn(
                "elastic membership (HVD_TPU_ELASTIC/--min-np) does not "
                "support the XLA data plane; eager collectives will use "
                "the TCP engine.")
        _xla_plane = None
    elif enable or auto:
        plane = None
        if enable:
            try:
                from horovod_tpu.jax import eager_mesh

                plane = eager_mesh.initialize(ps)
            except ImportError as exc:
                import warnings

                warnings.warn(
                    f"XLA data plane requested but jax is unavailable "
                    f"({exc}); eager collectives will use the TCP engine.")
        if ps.size > 1:
            # Job-wide agreement over the TCP engine (_xla_plane is still
            # None, so this allreduce cannot ride the plane): a rank whose
            # plane init failed — or, in auto mode, that saw no TPU —
            # must not diverge from ranks that enabled the plane, or the
            # job deadlocks across two transports.  Auto mode therefore
            # always votes, even with a local "no".
            total = allreduce(np.asarray(1 if plane else 0, np.int32),
                              average=False, name="__xla_plane_agreement__")
            if int(total) != ps.size:
                if plane is not None:
                    import warnings

                    warnings.warn(
                        "XLA data plane disabled: not every rank could "
                        "initialize it; eager collectives use the TCP "
                        "engine.")
                plane = None
        _xla_plane = plane
    # Deterministic fault injection (docs/fault-tolerance.md), armed LAST:
    # init()'s own internal collectives (the plane agreement above) must
    # not consume fault-spec op indices — op=N counts the caller's
    # collectives from 0.
    global _fault_injector, _collective_seq
    from horovod_tpu.common import faults as _faults

    with _fault_lock:
        _collective_seq = 0  # re-init after shutdown restarts the count
    _fault_injector = _faults.from_env(ps.rank)
    atexit.register(shutdown)


def _cluster_targets(ps: ProcessSet, base_port: int) -> list:
    """(rank, host, port) scrape targets for the /cluster aggregation:
    every rank's monitor binds ``base_port + local_rank``, and hvdrun
    places ranks in contiguous per-host blocks, so a rank's local index is
    the count of same-host ranks before it.  Falls back to localhost when
    the launcher provided no data endpoints (single-process init)."""
    targets = []
    seen: dict = {}
    for r in range(ps.size):
        if ps.data_endpoints and r < len(ps.data_endpoints):
            host = ps.data_endpoints[r].rsplit(":", 1)[0]
        else:
            host = "127.0.0.1"
        local_idx = seen.get(host, 0)
        seen[host] = local_idx + 1
        targets.append((r, host, base_port + local_idx))
    return targets


def _tpu_visible() -> bool:
    """True when jax is importable and reports at least one TPU device —
    the auto-enable predicate for the XLA data plane.  Conservative: any
    failure (no jax, no backend, no devices) means 'no'."""
    try:
        import jax

        return any(d.platform == "tpu" for d in jax.devices())
    except Exception:
        return False


def _flush_metrics_file(clear: bool = True) -> None:
    """Write the per-rank ``HVD_TPU_METRICS_FILE`` dump now.  The clean
    ``shutdown()`` path clears the pending path afterwards; the abort /
    postmortem paths flush WITHOUT clearing (crashed ranks must leave
    metrics too, and a later clean shutdown simply overwrites the dump
    with fresher totals)."""
    global _metrics_file
    if _metrics_file is None:
        return
    path = _metrics_file
    if clear:
        _metrics_file = None
    try:
        with open(path, "w") as f:
            json.dump(metrics_snapshot(), f, indent=2)
            f.write("\n")
    except OSError as exc:
        import warnings

        warnings.warn(f"could not write metrics file {path}: {exc}")


def shutdown() -> None:
    """Shut the engine down.  Idempotent: safe to call twice, or without a
    prior ``init()`` (both are no-ops beyond flushing metrics plumbing)."""
    global _process_set, _xla_plane, _fault_injector
    _fault_injector = None
    # The state plane's lifetime is the engine's: disarm (close the
    # snapshot worker + peer listener) so a later init()+arm() starts
    # clean and a stale plane can never route a new job's resyncs.
    try:
        from horovod_tpu import state as _state_mod

        _state_mod.disarm()
    except Exception:
        pass
    if _lib is not None and int(_lib.hvd_tpu_abort_code()) != 0:
        # A typed abort the process never consumed through a Handle.wait
        # (e.g. the driver was between collectives when the coordinator
        # aborted, and atexit is the first code to look): leave the
        # postmortem artifact before the engine state goes away.
        from horovod_tpu.common import postmortem as _postmortem

        _postmortem.write_postmortem(
            _postmortem.reason_for_code(int(_lib.hvd_tpu_abort_code())))
    _flush_metrics_file(clear=True)
    metrics.stop_monitor()
    if _lib is not None and _lib.hvd_tpu_initialized():
        _lib.hvd_tpu_shutdown()
    _process_set = None
    _xla_plane = None


def _check_initialized(lib) -> None:
    if not lib.hvd_tpu_initialized():
        raise HorovodNotInitializedError(
            "Horovod-TPU has not been initialized; use hvd.init().")


def is_initialized() -> bool:
    """True between a successful ``init()`` and ``shutdown()``.  Never
    loads or builds the native engine as a side effect."""
    return _lib is not None and bool(_lib.hvd_tpu_initialized())


def restart_epoch() -> int:
    """The ``hvdrun --max-restarts`` relaunch counter for this process: 0
    on the first run, +1 per restart (``HVD_TPU_RESTART_EPOCH``).  Usable
    before ``init()`` — checkpoint-resume glue runs early."""
    return int(os.environ.get("HVD_TPU_RESTART_EPOCH") or 0)


def membership_epoch() -> int:
    """The elastic-membership epoch of this engine lifetime: 0 until the
    first reshape, +1 per reshape survived (shrink or grow).  After a
    reshape, ``hvd.rank()``/``hvd.size()`` re-resolve to the new dense
    membership; this counter is how drivers notice the change
    (docs/fault-tolerance.md#elastic-membership).  0 before ``init()``."""
    if _lib is None:
        return 0
    return int(_lib.hvd_tpu_membership_epoch())


def membership_ack() -> None:
    """Acknowledge the latest membership reshape: clears the engine's
    post-reshape enqueue poison so collectives negotiate again in the new
    membership.  Call only once every rank is about to re-enter agreement
    from a synchronized point — ``hvd.run_elastic`` does this (followed by
    the root-broadcast state resync) and is the normal way to consume
    reshapes."""
    if _lib is not None:
        _lib.hvd_tpu_membership_ack()


def rank() -> int:
    lib = _load_lib()
    _check_initialized(lib)
    return lib.hvd_tpu_rank()


def size() -> int:
    lib = _load_lib()
    _check_initialized(lib)
    return lib.hvd_tpu_size()


def local_rank() -> int:
    lib = _load_lib()
    _check_initialized(lib)
    return lib.hvd_tpu_local_rank()


def local_size() -> int:
    lib = _load_lib()
    _check_initialized(lib)
    return lib.hvd_tpu_local_size()


def mpi_threads_supported() -> bool:
    """Compatibility shim: there is no MPI; the engine is always
    thread-safe for concurrent enqueues (the property this reference API,
    /root/reference/horovod/common/__init__.py:142-153, reported)."""
    _check_initialized(_load_lib())
    return True


# ---------------------------------------------------------------------------
# Collective metrics (common/metrics.py; docs/metrics.md).
# ---------------------------------------------------------------------------


def _sync_engine_stalls() -> None:
    """Fold the engine's (C++, rank-0 sweep) stall warnings into the Python
    registry.  The C side reports a cumulative event count plus a bounded
    log of the most recent "name|seconds" records; we consume only the
    events not yet seen, so repeated snapshots never double-count and
    ``metrics_reset()`` keeps its clear-everything semantics."""
    global _engine_stalls_seen
    if _lib is None:
        return
    with _stall_sync_lock:
        count = int(_lib.hvd_tpu_stall_count())
        new = count - _engine_stalls_seen
        if new <= 0:
            return
        _engine_stalls_seen = count
        entries = [e for e in
                   _lib.hvd_tpu_stall_info().decode().split(";") if e]
        taken = entries[-new:]
        for entry in taken:
            name, _, sec = entry.partition("|")
            try:
                duration = float(sec)
            except ValueError:
                duration = 0.0
            metrics.registry.record_stall(name, duration)
        # The engine's log is bounded (64): events beyond it keep the
        # total honest even though their tensor names are gone.
        if new > len(taken):
            metrics.registry.record_stall_count(new - len(taken))


def _sync_engine_aborts() -> None:
    """Fold the engine's coordinated-abort events into the registry (kind
    from the latched status code: ranks_down / timeout).  Consumes only
    unseen events, like the stall sync."""
    global _engine_aborts_seen
    if _lib is None:
        return
    with _stall_sync_lock:
        count = int(_lib.hvd_tpu_abort_count())
        new = count - _engine_aborts_seen
        if new <= 0:
            return
        _engine_aborts_seen = count
        code = int(_lib.hvd_tpu_abort_code())
        kind = "timeout" if code == ST_TIMEOUT else "ranks_down"
        metrics.registry.record_abort(kind, new)


def _sync_engine_announces() -> None:
    """Fold the coordinator's announce-order accounting into the registry
    (straggler attribution, docs/troubleshooting.md).  Per-rank
    last-to-announce counts come from an exact cumulative C-side vector;
    the first->last skew histogram from a bounded event log — events that
    fell off the log keep the per-rank totals honest but contribute no
    skew sample.  Coordinator-side data: non-zero on rank 0 only."""
    global _engine_announces_seen, _engine_last_announce_seen
    if _lib is None:
        return
    with _stall_sync_lock:
        counts_raw = _lib.hvd_tpu_last_announce_counts().decode()
        counts = [int(tok) for tok in counts_raw.split(",") if tok]
        for r, c in enumerate(counts):
            prev = (_engine_last_announce_seen[r]
                    if r < len(_engine_last_announce_seen) else 0)
            if c > prev:
                metrics.registry.record_last_announce(r, c - prev)
        _engine_last_announce_seen = counts
        # One C call carries "cumulative_count:entries", serialized under
        # the engine's announce lock — pairing a separate count call with
        # the log would race concurrent negotiations and mis-window the
        # skew samples.
        head, _, tail = _lib.hvd_tpu_announce_log().decode().partition(":")
        try:
            count = int(head)
        except ValueError:
            return
        new = count - _engine_announces_seen
        if new <= 0:
            return
        _engine_announces_seen = count
        entries = [e for e in tail.split(";") if e]
        for entry in entries[-new:]:
            _, _, us = entry.partition("|")
            try:
                skew_sec = float(us) / 1e6
            except ValueError:
                continue
            metrics.registry.observe("announce_skew_sec", skew_sec)


def _sync_engine_cache() -> None:
    """Fold the engine's response-cache counters (C++, cumulative) into
    the registry's ``"cache"`` section.  Consumes only unseen events, like
    the stall sync, so snapshots never double-count and the cache size
    gauge always reflects the engine's current entry count."""
    if _lib is None:
        return
    with _stall_sync_lock:
        counts = (int(_lib.hvd_tpu_cache_hit_count()),
                  int(_lib.hvd_tpu_cache_miss_count()),
                  int(_lib.hvd_tpu_cache_eviction_count()))
        for kind, total, seen_idx in (("hits", counts[0], 0),
                                      ("misses", counts[1], 1),
                                      ("evictions", counts[2], 2)):
            new = total - _engine_cache_seen[seen_idx]
            if new > 0:
                metrics.registry.record_cache("engine", kind, new)
            _engine_cache_seen[seen_idx] = total
        metrics.registry.set_cache_size("engine",
                                        int(_lib.hvd_tpu_cache_size()))
        meta = getattr(_xla_plane, "_meta_cache", None)
        if meta is not None:
            metrics.registry.set_cache_size("xla", len(meta))


def _sync_engine_membership() -> None:
    """Mirror the engine's elastic-membership state into the registry's
    ungated ``"membership"`` section (epoch, current size, reshapes, ranks
    lost/joined).  A state copy like the autotune sync: overwriting is
    idempotent and ``metrics_reset()`` re-mirrors on the next snapshot."""
    if _lib is None:
        return
    with _stall_sync_lock:
        info = _lib.hvd_tpu_membership_info().decode()
        parts = (info.split("|") + ["", "", "", ""])[:4]
        try:
            epoch, size_now = int(parts[0]), int(parts[1])
        except ValueError:
            return
        metrics.registry.set_membership({
            "epoch": epoch,
            "size": size_now,
            "reshapes": int(_lib.hvd_tpu_membership_reshapes()),
            "ranks_lost": [int(tok) for tok in parts[2].split(",") if tok],
            "ranks_joined": [int(tok) for tok in parts[3].split(",") if tok],
        })


def _sync_engine_flight() -> None:
    """Mirror the flight recorders' cumulative event counts (engine C++
    ring + XLA-plane Python ring) into the registry's ungated ``"flight"``
    section.  A state copy, like the membership sync."""
    from horovod_tpu.common import postmortem as _postmortem

    with _stall_sync_lock:
        engine_events = (int(_lib.hvd_tpu_flight_count())
                         if _lib is not None else 0)
        metrics.registry.set_flight({
            "events": {"engine": engine_events,
                       "xla": _postmortem.plane_ring.total},
            "capacity": _postmortem.ring_capacity(),
        })


def _sync_engine_compression() -> None:
    """Mirror the wire-compression state of both data planes into the
    registry's ungated ``"compression"`` section (docs/performance.md
    #wire-compression): the applied mode and min-bytes floor, per-plane
    wire-vs-payload byte totals and per-mode bucket counts, and the
    error-feedback residual gauges.  A state copy like the membership
    sync — the C counters are cumulative, so overwriting is idempotent."""
    if _lib is None:
        return
    from horovod_tpu.common.config import COMPRESSION_NAMES

    with _stall_sync_lock:
        parts = _lib.hvd_tpu_compression_info().decode().split("|")
        try:
            (wire, payload, n_none, n_bf16, n_fp8, res_bytes, res_tensors,
             min_bytes) = (int(p) for p in parts[:8])
        except ValueError:
            return
        planes = {
            "engine": {"wire_bytes": wire, "payload_bytes": payload,
                       "ops": {"none": n_none, "bf16": n_bf16,
                               "fp8": n_fp8}},
            "xla": {"wire_bytes": 0, "payload_bytes": 0,
                    "ops": {"none": 0, "bf16": 0, "fp8": 0}},
        }
        plane_stats = getattr(_xla_plane, "comp_stats", None)
        plane_res = 0
        if plane_stats is not None:
            planes["xla"] = {
                "wire_bytes": int(plane_stats["wire_bytes"]),
                "payload_bytes": int(plane_stats["payload_bytes"]),
                "ops": dict(plane_stats["ops"]),
            }
            plane_res = sum(r.nbytes for r in
                            getattr(_xla_plane, "_residuals", {}).values())
        metrics.registry.set_compression({
            "mode": COMPRESSION_NAMES.get(
                int(_lib.hvd_tpu_compression_mode()), "off"),
            "min_bytes": min_bytes,
            "planes": planes,
            "residual_bytes": res_bytes + plane_res,
            "residual_tensors": res_tensors + len(
                getattr(_xla_plane, "_residuals", {}) or {}),
        })


def _sync_engine_topology() -> None:
    """Mirror the engine's two-level topology state into the registry's
    ungated ``"topology"`` section (docs/performance.md
    #two-level-topology) and fold the bounded per-bucket phase log into
    the ``topology_*_sec`` phase histograms.  The gauges/counters are a
    state copy like the compression sync; the log is delta-consumed like
    the stall sync so repeated snapshots never double-observe."""
    global _engine_topo_seen
    if _lib is None:
        return
    with _stall_sync_lock:
        parts = _lib.hvd_tpu_topology_info().decode().split("|")
        try:
            (hier, nodes, local_size, threshold, ops_ring, ops_tree,
             local_bytes, cross_bytes, log_total) = (
                int(p) for p in parts[:9])
        except ValueError:
            return
        # 10th field (PR-19): the node-local hop's transport ("shm" once
        # the segment armed, else "tcp"); tolerate 9-field engines.
        local_transport = parts[9] if len(parts) > 9 else "tcp"
        metrics.registry.set_topology({
            "hierarchical": bool(hier),
            "nodes": nodes,
            "local_size": local_size,
            "cross_algo_threshold": threshold,
            "cross_ops": {"ring": ops_ring, "tree": ops_tree},
            "bytes": {"local": local_bytes, "cross": cross_bytes},
            "local_transport": local_transport,
        })
        new = log_total - _engine_topo_seen
        if new <= 0:
            return
        _engine_topo_seen = log_total
        entries = [e for e in
                   _lib.hvd_tpu_topology_log().decode().split(";") if e]
        for entry in entries[-new:]:
            fields = entry.split("|")
            if len(fields) != 5:
                continue
            try:
                rs_us, cross_us, ag_us = (int(f) for f in fields[2:5])
            except ValueError:
                continue
            metrics.registry.observe("topology_local_rs_sec", rs_us / 1e6)
            if cross_us:
                metrics.registry.observe("topology_cross_sec",
                                         cross_us / 1e6)
            metrics.registry.observe("topology_local_ag_sec", ag_us / 1e6)


def _sync_engine_control() -> None:
    """Mirror the engine's control-plane state into the registry's
    ungated ``"control"`` section (docs/performance.md
    #control-plane-scaling): the coordinator-tree shape, the
    decentralized steady-state counters, and the control-frame totals.
    A state copy like the topology sync — the C counters are cumulative,
    so overwriting is idempotent."""
    if _lib is None:
        return
    with _stall_sync_lock:
        parts = _lib.hvd_tpu_control_info().decode().split("|")
        try:
            (tree, children, hosts, active, pattern_len, threshold,
             entries, exits, replays, cycles, negotiated, sent,
             received) = (int(p) for p in parts[:13])
        except ValueError:
            return
        metrics.registry.set_control({
            "tree": bool(tree),
            "depth": 2 if tree else 1,
            "children": children,
            "hosts": hosts,
            "steady": {"active": bool(active), "pattern_len": pattern_len,
                       "threshold": threshold, "entries": entries,
                       "exits": exits, "replays": replays,
                       "cycles": cycles},
            "negotiated_ticks": negotiated,
            "frames": {"sent": sent, "received": received},
        })


def _sync_engine_liveness() -> None:
    """Mirror the engine's data-plane heartbeat detector into the
    registry's ungated ``"liveness"`` section (docs/fault-tolerance.md
    #failure-detection): the configured cadence and miss limit, beacon
    frame totals, miss/eviction events, per-peer last-seen ages, and the
    init clock-sync fan-in.  A state copy like the control sync — the C
    counters are cumulative, so overwriting is idempotent."""
    if _lib is None:
        return
    with _stall_sync_lock:
        info = _lib.hvd_tpu_liveness_info().decode()
        parts = info.split("|")
        if len(parts) < 8:
            return
        try:
            (interval_ms, miss_limit, sent, recv, miss_events, evictions,
             fanin) = (int(p) for p in parts[:7])
        except ValueError:
            return
        peers = {}
        for tok in parts[7].split():
            fields = tok.split(":")
            if len(fields) != 3:
                continue
            try:
                peers[int(fields[0])] = {"age_us": int(fields[1]),
                                         "misses": int(fields[2])}
            except ValueError:
                continue
        metrics.registry.set_liveness({
            "interval_ms": interval_ms,
            "miss_limit": miss_limit,
            "frames": {"sent": sent, "received": recv},
            "miss_events": miss_events,
            "evictions": evictions,
            "clock_fanin": fanin,
            "peers": peers,
        })


def _sync_engine_links() -> None:
    """Mirror the engine's per-peer link telemetry into the registry's
    ungated ``"links"`` section (docs/metrics.md#links): transport byte /
    stall counters, the timed-send latency histogram, and the
    heartbeat-echo RTT estimate for every TCP link this rank holds.  A
    state copy — the net-layer counters are cumulative, so overwriting is
    idempotent."""
    if _lib is None:
        return
    with _stall_sync_lock:
        info = _lib.hvd_tpu_link_info().decode()
        parts = info.split("|")
        if len(parts) < 2:
            return
        peers = {}
        for tok in parts[1].split(";"):
            fields = tok.split(":")
            if len(fields) != 20:
                continue
            try:
                peers[int(fields[0])] = {
                    "bytes_out": int(fields[1]),
                    "bytes_in": int(fields[2]),
                    "sends": int(fields[3]),
                    "recvs": int(fields[4]),
                    "stalls": int(fields[5]),
                    "short_writes": int(fields[6]),
                    "send_us_sum": int(fields[7]),
                    "send_us_count": int(fields[8]),
                    "send_us_buckets": [int(b) for b in
                                        fields[9].split(",") if b],
                    "rtt_last_us": int(fields[10]),
                    "rtt_ewma_us": int(fields[11]),
                    "rtt_samples": int(fields[12]),
                    "shm_bytes_out": int(fields[13]),
                    "shm_bytes_in": int(fields[14]),
                    "shm_handoffs": int(fields[15]),
                    "shm_us_sum": int(fields[16]),
                    "shm_us_count": int(fields[17]),
                    "shm_us_buckets": [int(b) for b in
                                       fields[18].split(",") if b],
                    "transport": fields[19],
                }
            except ValueError:
                continue
        metrics.registry.set_links({"enabled": parts[0] == "1",
                                    "peers": peers})


def _sync_engine_anomalies() -> None:
    """Mirror the engine's online anomaly detector into the registry's
    ungated ``"anomalies"`` section (docs/metrics.md#anomalies): the
    configured sigma/interval, cumulative verdict counts per kind, and
    the bounded typed-verdict log.  A state copy — idempotent."""
    if _lib is None:
        return
    with _stall_sync_lock:
        info = _lib.hvd_tpu_anomaly_info().decode()
        parts = info.split("|")
        if len(parts) < 6:
            return
        try:
            sigma, interval_ms = int(parts[0]), int(parts[1])
            counts = [int(p) for p in parts[2:6]]
        except ValueError:
            return
        log = []
        for tok in _lib.hvd_tpu_anomaly_log().decode().split(";"):
            fields = tok.split("|")
            if len(fields) != 4:
                continue
            try:
                age_us = int(fields[3])
            except ValueError:
                continue
            log.append({"kind": fields[0], "subject": fields[1],
                        "detail": fields[2], "age_us": age_us})
        metrics.registry.set_anomalies({
            "sigma": sigma,
            "interval_ms": interval_ms,
            "verdicts": dict(zip(metrics.ANOMALY_KINDS, counts)),
            "log": log,
        })


def _sync_engine_autotune() -> None:
    """Mirror the engine's autotuning state into the registry's ungated
    ``"autotune"`` section (docs/performance.md#autotuning).  Unlike the
    event syncs above this is a state COPY, not a delta fold: the report
    is current-state plus bounded logs, so overwriting is idempotent and
    a ``metrics_reset()`` simply re-mirrors on the next snapshot."""
    if _lib is None:
        return
    from horovod_tpu.common import autotune as _autotune

    with _stall_sync_lock:
        metrics.registry.set_autotune(_autotune.report(_lib))


def _sync_engine_p2p() -> None:
    """Mirror the engine's point-to-point plane counters into the
    registry's ungated ``"p2p"`` section (docs/pipeline.md
    #observability): transfer/byte totals per direction, the matched
    counter against the unmatched gauge, stage-group ops, and the open
    dedicated-channel gauge.  A state copy — idempotent."""
    if _lib is None:
        return
    with _stall_sync_lock:
        info = _lib.hvd_tpu_p2p_info().decode()
        parts = info.split("|")
        if len(parts) != 8:
            return
        try:
            (sends, recvs, bytes_out, bytes_in, matched, unmatched,
             group_ops, channels) = (int(p) for p in parts)
        except ValueError:
            return
        metrics.registry.set_p2p({
            "sends": sends,
            "recvs": recvs,
            "bytes": {"out": bytes_out, "in": bytes_in},
            "matched": matched,
            "unmatched": unmatched,
            "group_ops": group_ops,
            "channels": channels,
        })


def metrics_snapshot() -> dict:
    """Plain nested dict of the collective metrics registry: op/byte
    counters per data plane, fusion-batch counters, latency/fill
    histograms, stall events (engine sweep + XLA-plane waits), the
    coordinator's announce-order skew accounting (``"skew"``, rank 0),
    and the online-autotuning state (``"autotune"``: applied params,
    freeze state, per-window search history).  Always callable; counters
    and histograms only accumulate while metrics are enabled
    (``HVD_TPU_METRICS=1``, a metrics file, or a monitor port); stall,
    fault, skew, and autotune records always do."""
    _sync_engine_stalls()
    _sync_engine_aborts()
    _sync_engine_announces()
    _sync_engine_cache()
    _sync_engine_autotune()
    _sync_engine_membership()
    _sync_engine_flight()
    _sync_engine_compression()
    _sync_engine_topology()
    _sync_engine_control()
    _sync_engine_liveness()
    _sync_engine_links()
    _sync_engine_anomalies()
    _sync_engine_p2p()
    return metrics.registry.snapshot()


def metrics_reset() -> None:
    """Zero every counter, histogram, and stall record (the enabled flag
    is unaffected).  Outstanding engine stall events are consumed first so
    they cannot resurface in the next snapshot."""
    _sync_engine_stalls()
    _sync_engine_aborts()
    _sync_engine_announces()
    _sync_engine_cache()
    _sync_engine_topology()
    metrics.registry.reset()


# ---------------------------------------------------------------------------
# Online autotuning API (common/autotune.py; docs/performance.md).
# ---------------------------------------------------------------------------


def autotune_report() -> dict:
    """The online-autotuning report: whether the tuner is enabled/frozen,
    the currently applied ``fusion_threshold`` / ``cycle_time_ms`` (set by
    lockstep broadcast — identical on every rank of a healthy job), the
    per-rank ``applied`` parameter log, and — on rank 0 — the per-window
    search ``history`` with scores.  Callable without ``init()`` (returns
    the empty shape) so post-shutdown tooling can read the last state."""
    from horovod_tpu.common import autotune as _autotune

    if _lib is None:
        return _autotune.empty_report()
    return _autotune.report(_lib)


def autotune_set(fusion_threshold: Optional[int] = None,
                 cycle_time_ms: Optional[float] = None,
                 compression: Optional[str] = None,
                 cross_algo_threshold: Optional[int] = None) -> None:
    """Inject engine parameters for lockstep broadcast at the next
    negotiation tick — the pluggable-policy seam: a custom tuning policy
    runs on rank 0, reads ``metrics_snapshot()``, and drives the same
    broadcast machinery the built-in search uses, so every rank applies
    the change at the same tick boundary.  Works with the built-in tuner
    disabled or frozen; while a search is live it resumes from the
    nearest grid point.  ``compression`` takes a wire mode name
    ("off"/"bf16"/"fp8"); ``cross_algo_threshold`` the two-level
    ring-vs-tree byte boundary (docs/performance.md#two-level-topology).
    Rank 0 only (``ValueError`` elsewhere)."""
    lib = _load_lib()
    _check_initialized(lib)
    from horovod_tpu.common import autotune as _autotune

    _autotune.set_params(lib, fusion_threshold, cycle_time_ms, compression,
                         cross_algo_threshold)


def compression_report() -> dict:
    """The wire-compression report (docs/performance.md#wire-compression):
    the applied mode and min-bytes floor (lockstep state — identical on
    every rank of a healthy job), per-plane wire-vs-payload byte totals
    and per-mode bucket counts, the error-feedback residual gauges, and
    the engine's bounded per-bucket decision ``log`` ([{"name", "mode"},
    ...] in execution order — identical across ranks; tests allgather and
    compare it).  Returns the empty shape before ``init()``."""
    from horovod_tpu.common.config import COMPRESSION_NAMES

    empty_ops = {"none": 0, "bf16": 0, "fp8": 0}
    rep = {
        "mode": "off", "min_bytes": 0,
        "engine": {"wire_bytes": 0, "payload_bytes": 0,
                   "ops": dict(empty_ops)},
        "xla": {"wire_bytes": 0, "payload_bytes": 0, "ops": dict(empty_ops)},
        "residual_bytes": 0, "residual_tensors": 0,
        "log": [],
    }
    if _lib is None:
        return rep
    parts = _lib.hvd_tpu_compression_info().decode().split("|")
    try:
        (wire, payload, n_none, n_bf16, n_fp8, res_bytes, res_tensors,
         min_bytes) = (int(p) for p in parts[:8])
    except ValueError:
        return rep
    rep.update({
        "mode": COMPRESSION_NAMES.get(
            int(_lib.hvd_tpu_compression_mode()), "off"),
        "min_bytes": min_bytes,
        "engine": {"wire_bytes": wire, "payload_bytes": payload,
                   "ops": {"none": n_none, "bf16": n_bf16, "fp8": n_fp8}},
        "residual_bytes": res_bytes,
        "residual_tensors": res_tensors,
    })
    plane_stats = getattr(_xla_plane, "comp_stats", None)
    if plane_stats is not None:
        rep["xla"] = {"wire_bytes": int(plane_stats["wire_bytes"]),
                      "payload_bytes": int(plane_stats["payload_bytes"]),
                      "ops": dict(plane_stats["ops"])}
        # Residual gauges cover BOTH planes, exactly like
        # metrics_snapshot()["compression"] — the two public surfaces
        # must agree on the same field names.
        plane_res = getattr(_xla_plane, "_residuals", {}) or {}
        rep["residual_bytes"] += sum(r.nbytes for r in plane_res.values())
        rep["residual_tensors"] += len(plane_res)
    for entry in _lib.hvd_tpu_compression_log().decode().split(";"):
        if not entry:
            continue
        name, _, mode = entry.rpartition("|")
        rep["log"].append({"name": name, "mode": mode})
    return rep


# ---------------------------------------------------------------------------
# Application span API (docs/timeline.md): land app events in this rank's
# trace next to the engine's collective rows.
# ---------------------------------------------------------------------------


def timeline_enabled() -> bool:
    """True when this rank is writing a timeline (``HOROVOD_TIMELINE`` /
    ``hvdrun --timeline``); span and marker calls are no-ops otherwise."""
    return _lib is not None and bool(_lib.hvd_tpu_timeline_enabled())


def _trace_begin(row: str, label: str) -> None:
    """Open a span labelled `label` on trace row `row` (internal: the
    framework hooks — keras callbacks, jax train steps — share it with
    :func:`trace_span`)."""
    if _lib is not None and _lib.hvd_tpu_timeline_enabled():
        _lib.hvd_tpu_timeline_op_start(row.encode(), label.encode())


def _trace_end(row: str) -> None:
    if _lib is not None and _lib.hvd_tpu_timeline_enabled():
        _lib.hvd_tpu_timeline_op_end(row.encode(), 0)


@contextlib.contextmanager
def trace_span(name: str, label: Optional[str] = None):
    """Context manager landing an application span in this rank's timeline::

        with hvd.trace_span("data_loading"):
            batch = next(loader)

    The span occupies the trace row ``name`` (same-row spans nest);
    ``label`` overrides the event label (default: the row name).  A no-op
    when the timeline is disabled — safe to leave in production code."""
    if _lib is None or not _lib.hvd_tpu_timeline_enabled():
        yield
        return
    _lib.hvd_tpu_timeline_op_start(name.encode(), (label or name).encode())
    try:
        yield
    finally:
        _lib.hvd_tpu_timeline_op_end(name.encode(), 0)


def trace_marker(name: str, row: str = "app.markers") -> None:
    """Drop an instant event named `name` on the ``app.markers`` trace row
    (or `row`).  A no-op when the timeline is disabled."""
    if _lib is not None and _lib.hvd_tpu_timeline_enabled():
        _lib.hvd_tpu_timeline_instant(row.encode(), name.encode())


# ---------------------------------------------------------------------------
# Async numpy collectives -- the substrate for all framework bindings.
# ---------------------------------------------------------------------------


class Handle:
    """An outstanding collective.  Poll with :meth:`done`, finish with
    :meth:`wait`.  Keeps input/output arrays alive while the engine may
    still touch their memory (the reference pins tensors in _handle_map,
    /root/reference/horovod/torch/mpi_ops.py:28-31)."""

    def __init__(self, raw: int, op: int, inp: np.ndarray,
                 out: Optional[np.ndarray], name: str):
        self._raw = raw
        self._op = op
        self._in = inp
        self._out = out
        self._name = name
        self._finished = False
        self._finish_lock = threading.Lock()
        # Metrics: end-to-end wait latency measured from enqueue.  One
        # enabled check; 0.0 doubles as the "metrics off" sentinel.
        self._t0 = time.perf_counter() if metrics.registry.enabled else 0.0
        # Engine (tick, seq) completion stamp, set by wait(): ops fused in
        # one negotiation cycle share a tick — observability for tests and
        # the timeline (the reference's cycle accounting).
        self.completion_tick: Optional[int] = None
        self.completion_seq: Optional[int] = None

    def done(self) -> bool:
        if self._finished:
            return True
        return _lib.hvd_tpu_poll(self._raw) != 0

    def wait(self) -> np.ndarray:
        # Atomic test-and-set: with the zero-copy allgather result a
        # double-wait would register two finalizers releasing the same
        # engine buffer (use-after-free), not just waste a copy.
        with self._finish_lock:
            if self._finished:
                raise ValueError(
                    f"handle for '{self._name}' already waited on")
            self._finished = True
        release = True
        code = _lib.hvd_tpu_wait(self._raw)
        try:
            if code != ST_OK:
                msg = _lib.hvd_tpu_error(self._raw).decode()
                code, msg = _promote_transport_failure(code, msg)
                raise _status_error(code, msg, self._name)
            self.completion_tick = int(
                _lib.hvd_tpu_completion_tick(self._raw))
            self.completion_seq = int(
                _lib.hvd_tpu_completion_seq(self._raw))
            if self._t0:
                # Engine-plane negotiation latency (enqueue -> agreed
                # response), stamped by the engine thread — the number the
                # response cache exists to shrink (docs/performance.md).
                neg_us = int(_lib.hvd_tpu_negotiation_us(self._raw))
                if neg_us >= 0:
                    metrics.registry.observe("negotiation_sec",
                                             neg_us / 1e6)
            if self._op == OP_ALLGATHER:
                nbytes = int(_lib.hvd_tpu_result_nbytes(self._raw))
                dim0 = _lib.hvd_tpu_result_dim0(self._raw)
                shape = (int(dim0),) + self._in.shape[1:]
                if self._t0:
                    metrics.registry.record_bytes_out("engine", nbytes)
                    metrics.registry.observe(
                        "wait_sec", time.perf_counter() - self._t0)
                if not nbytes:
                    return np.empty(shape, dtype=self._in.dtype)
                # Zero-copy: view the engine-owned result buffer directly
                # (the second full copy of the gathered payload the
                # round-3 host path paid).  The handle — and with it the
                # buffer — is released when the array is dropped; the
                # engine never touches a completed handle's buffer again,
                # and the (leaked) engine keeps released-less handles
                # valid across shutdown, so the view cannot dangle.
                itemsize = np.dtype(self._in.dtype).itemsize
                assert int(np.prod(shape)) * itemsize == nbytes, \
                    (shape, self._in.dtype, nbytes)
                ptr = _lib.hvd_tpu_result_ptr(self._raw)
                view = (ctypes.c_char * nbytes).from_address(ptr)
                # The finalizer hangs off the ctypes view — the bottom of
                # every derived ndarray's base chain (numpy collapses
                # view-of-view bases, so an intermediate array could be
                # collected while slices of it live on).
                weakref.finalize(view, _lib.hvd_tpu_release, self._raw)
                release = False
                return np.frombuffer(view,
                                     dtype=self._in.dtype).reshape(shape)
            if self._t0:
                metrics.registry.record_bytes_out("engine", self._out.nbytes)
                metrics.registry.observe(
                    "wait_sec", time.perf_counter() - self._t0)
            return self._out
        finally:
            if release:
                _lib.hvd_tpu_release(self._raw)


def _parse_down_ranks(msg: str) -> list:
    """Extract the rank list from an engine abort message of the form
    'ranks down: 0, 2 (...)'; empty when the shape is unexpected."""
    import re

    m = re.search(r"ranks down: ([0-9, ]+)", msg)
    if not m:
        return []
    return [int(tok) for tok in m.group(1).split(",") if tok.strip()]


def _promote_transport_failure(code: int, msg: str):
    """A mid-collective transport failure racing a coordinated abort:
    prefer the typed verdict.  Under the decentralized steady state
    (docs/performance.md#control-plane-scaling) survivors enter the data
    plane WITHOUT a negotiation round, so a peer's crash surfaces as a
    broken ring (ST_UNKNOWN) on them a beat before the coordinator's
    RanksDown broadcast lands — wait briefly for the control plane's
    verdict so the caller still gets the typed error naming the dead
    rank (the star had the same race with a much narrower window).
    ST_ABORTED drains check the latch once, without waiting: a clean
    shutdown also drains with that status and must not stall."""
    if _lib is None:
        return code, msg
    transport = code == ST_UNKNOWN and "failed" in msg
    deadline = time.monotonic() + (2.0 if transport else 0.0)
    while True:
        ac = int(_lib.hvd_tpu_abort_code())
        if ac in (ST_RANKS_DOWN, ST_TIMEOUT):
            return ac, _lib.hvd_tpu_abort_message().decode()
        if not transport or time.monotonic() >= deadline:
            return code, msg
        time.sleep(0.01)


def _status_error(code: int, msg: str, name: str) -> Exception:
    prefix = f"collective '{name}' failed: "
    if code == ST_PRECONDITION:
        return ValueError(prefix + msg)
    if code in (ST_RANKS_DOWN, ST_TIMEOUT):
        # Typed abort: leave the postmortem artifact NOW, while the
        # engine's flight ring and pending tables still describe the
        # moment of death (both planes route their abort statuses through
        # here).  Write-once and best-effort inside.
        from horovod_tpu.common import postmortem as _postmortem

        _postmortem.write_postmortem(_postmortem.reason_for_code(code))
    if code == ST_RANKS_DOWN:
        return RanksDownError(prefix + msg, ranks=_parse_down_ranks(msg))
    if code == ST_TIMEOUT:
        return CollectiveTimeoutError(prefix + msg)
    if code == ST_RESHAPE:
        return MembershipChangedError(prefix + msg,
                                      lost_ranks=_parse_down_ranks(msg))
    if code == ST_ABORTED:
        return HorovodInternalError(prefix + msg)
    return HorovodInternalError(prefix + (msg or f"status {code}"))


def _as_c_dims(shape) -> tuple:
    arr = (ctypes.c_longlong * len(shape))(*shape)
    return arr, len(shape)


_name_counter = [0]
_name_lock = threading.Lock()


def _auto_name(prefix: str) -> str:
    with _name_lock:
        _name_counter[0] += 1
        return f"{prefix}.noname.{_name_counter[0]}"


def _as_contig(array) -> np.ndarray:
    """C-contiguous ndarray view/copy that preserves 0-d shapes
    (`np.ascontiguousarray` would promote scalars to shape (1,))."""
    array = np.asarray(array)
    if not array.flags["C_CONTIGUOUS"]:
        array = np.ascontiguousarray(array)
    return array


def _check_out(out: np.ndarray, array: np.ndarray) -> None:
    if out.shape != array.shape or out.dtype != array.dtype:
        raise ValueError(
            f"output buffer mismatch: expected shape {array.shape} dtype "
            f"{array.dtype}, got shape {out.shape} dtype {out.dtype}")
    if not out.flags["C_CONTIGUOUS"] or not out.flags["WRITEABLE"]:
        raise ValueError("output buffer must be C-contiguous and writeable")


def _plane_eligible(array: np.ndarray) -> bool:
    return _xla_plane is not None and array.dtype.name in _XLA_PLANE_DTYPES


def _fault_hook(name: str) -> None:
    """Collective-boundary fault injection (common/faults.py).  Sits in
    the shared entry points, so it covers BOTH data planes — the XLA plane
    is dispatched from these same functions.  One None check when no spec
    is active; the submission index only advances while an injector is
    armed (it is the injector's coordinate system, nobody else's)."""
    if _fault_injector is None:
        return
    global _collective_seq
    with _fault_lock:
        idx = _collective_seq
        _collective_seq += 1
    _fault_injector.on_collective(idx, name)


def allreduce_async(array: np.ndarray, average: bool = True,
                    name: Optional[str] = None,
                    out: Optional[np.ndarray] = None,
                    group: Optional["StageGroup"] = None) -> Handle:
    lib = _load_lib()
    _check_initialized(lib)
    array = _as_contig(array)
    if out is None:
        out = np.empty_like(array)
    else:
        _check_out(out, array)
    if group is not None:
        # Scoped collective (docs/pipeline.md#stage-groups): reduces only
        # over the group's ranks — the data-parallel dimension inside one
        # pipeline stage.  Always the engine path: the XLA plane compiles
        # full-world collectives and knows nothing of membership subsets.
        name = name or _auto_name("group_allreduce")
        _fault_hook(name)
        dims, ndim = _as_c_dims(array.shape)
        members = (ctypes.c_longlong * len(group.ranks))(*group.ranks)
        raw = lib.hvd_tpu_enqueue_group(
            name.encode(),
            array.ctypes.data_as(ctypes.c_void_p),
            out.ctypes.data_as(ctypes.c_void_p),
            dims, ndim, dtypes.numpy_to_code(array.dtype), int(average),
            members, len(group.ranks))
        if raw < 0:
            raise HorovodInternalError("engine is shut down")
        if metrics.registry.enabled:
            metrics.registry.record_enqueue("engine", "allreduce",
                                            array.nbytes)
        return Handle(raw, OP_ALLREDUCE, array, out, name)
    name = name or _auto_name("allreduce")
    _fault_hook(name)
    if _plane_eligible(array):
        # Compiled XLA collective over the fabric; dispatch order and
        # shape/dtype consistency are negotiated over the control plane.
        return _xla_plane.allreduce_async(array, average, out, name)
    dims, ndim = _as_c_dims(array.shape)
    raw = lib.hvd_tpu_enqueue(
        OP_ALLREDUCE, name.encode(),
        array.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        dims, ndim, dtypes.numpy_to_code(array.dtype), -1, int(average))
    if raw < 0:
        raise HorovodInternalError("engine is shut down")
    if metrics.registry.enabled:
        metrics.registry.record_enqueue("engine", "allreduce", array.nbytes)
    return Handle(raw, OP_ALLREDUCE, array, out, name)


def allgather_async(array: np.ndarray, name: Optional[str] = None) -> Handle:
    lib = _load_lib()
    _check_initialized(lib)
    array = _as_contig(array)
    if array.ndim == 0:
        raise ValueError("allgather requires tensors of rank >= 1")
    name = name or _auto_name("allgather")
    _fault_hook(name)
    if _plane_eligible(array):
        # Compiled XLA all-gather over the fabric; ragged dim-0 geometry is
        # exchanged by the plane's metadata negotiation.
        return _xla_plane.allgather_async(array, name)
    dims, ndim = _as_c_dims(array.shape)
    raw = lib.hvd_tpu_enqueue(
        OP_ALLGATHER, name.encode(),
        array.ctypes.data_as(ctypes.c_void_p), None,
        dims, ndim, dtypes.numpy_to_code(array.dtype), -1, 0)
    if raw < 0:
        raise HorovodInternalError("engine is shut down")
    if metrics.registry.enabled:
        metrics.registry.record_enqueue("engine", "allgather", array.nbytes)
    return Handle(raw, OP_ALLGATHER, array, None, name)


def broadcast_async(array: np.ndarray, root_rank: int,
                    name: Optional[str] = None,
                    out: Optional[np.ndarray] = None) -> Handle:
    lib = _load_lib()
    _check_initialized(lib)
    array = _as_contig(array)
    if out is None:
        out = np.empty_like(array)
    else:
        _check_out(out, array)
    name = name or _auto_name("broadcast")
    _fault_hook(name)
    if _plane_eligible(array):
        if not (0 <= root_rank < (_process_set.size if _process_set else 1)):
            raise ValueError(f"broadcast root rank {root_rank} out of range")
        return _xla_plane.broadcast_async(array, root_rank, out, name)
    dims, ndim = _as_c_dims(array.shape)
    raw = lib.hvd_tpu_enqueue(
        OP_BROADCAST, name.encode(),
        array.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        dims, ndim, dtypes.numpy_to_code(array.dtype), root_rank, 0)
    if raw < 0:
        raise HorovodInternalError("engine is shut down")
    if metrics.registry.enabled:
        metrics.registry.record_enqueue("engine", "broadcast", array.nbytes)
    return Handle(raw, OP_BROADCAST, array, out, name)


class StageGroup:
    """Immutable membership subset for scoped collectives
    (docs/pipeline.md#stage-groups).  A pipeline job arranges its world
    as a stages x data-parallel grid: collectives scoped to one group
    reduce along the DP axis inside a stage, while the p2p plane
    (``send``/``recv``) crosses groups along the PP axis.  Membership is
    validated by the coordinator at negotiation time — every announcing
    rank must list an identical group, and every listed rank must
    announce — so a mismatched grid fails with a typed precondition
    error instead of a hang."""

    def __init__(self, ranks):
        members = sorted({int(r) for r in ranks})
        if not members:
            raise ValueError("stage group must contain at least one rank")
        if members[0] < 0:
            raise ValueError(f"stage group rank {members[0]} is negative")
        self.ranks = tuple(members)

    @property
    def size(self) -> int:
        return len(self.ranks)

    def __contains__(self, r) -> bool:
        return int(r) in self.ranks

    def __eq__(self, other) -> bool:
        return isinstance(other, StageGroup) and self.ranks == other.ranks

    def __hash__(self) -> int:
        return hash(self.ranks)

    def __repr__(self) -> str:
        return f"StageGroup(ranks={list(self.ranks)})"


def stage_group(ranks) -> StageGroup:
    """Build a :class:`StageGroup` from an iterable of global ranks."""
    return StageGroup(ranks)


def _p2p_wire_name(name: Optional[str], src: int, dst: int,
                   tag: int) -> str:
    """Canonical p2p wire name — the paired-readiness contract
    (docs/pipeline.md#wire-protocol) keys a send and its matching recv
    on ONE name, so both ends must construct it identically: the sender
    stamps (rank -> peer), the receiver (peer -> rank), and both arrive
    at the same ``<base>.p2p.<src>-<dst>.t<tag>``."""
    base = name or "p2p"
    return f"{base}.p2p.{src}-{dst}.t{tag}"


def _enqueue_p2p(op: int, kind: str, array: np.ndarray,
                 out: Optional[np.ndarray], peer: int, tag: int,
                 wire_name: str) -> Handle:
    lib = _load_lib()
    if not (0 <= peer < size()):
        raise ValueError(f"p2p peer rank {peer} out of range for world "
                         f"size {size()}")
    if peer == rank():
        raise ValueError("p2p peer must be a different rank")
    if tag < 0:
        raise ValueError(f"p2p tag {tag} must be non-negative")
    _fault_hook(wire_name)
    # Always the engine path: p2p rides the Channel transport seam
    # directly — there is no compiled-collective equivalent.
    dims, ndim = _as_c_dims(array.shape)
    raw = lib.hvd_tpu_enqueue_p2p(
        op, wire_name.encode(),
        array.ctypes.data_as(ctypes.c_void_p) if op == OP_SEND else None,
        out.ctypes.data_as(ctypes.c_void_p) if out is not None else None,
        dims, ndim, dtypes.numpy_to_code(array.dtype), peer, tag)
    if raw < 0:
        raise HorovodInternalError("engine is shut down")
    # No record_enqueue here: snap["ops"] is collectives-only (pinned by
    # test_snapshot_shape); the engine mirrors the canonical p2p counters
    # into snap["p2p"] via set_p2p, bytes included.
    # A send has no output buffer; hand the Handle the input so wait()'s
    # byte accounting and return value stay uniform.
    return Handle(raw, op, array, out if out is not None else array,
                  wire_name)


def send_async(array: np.ndarray, dest: int, tag: int = 0,
               name: Optional[str] = None) -> Handle:
    """Asynchronously send ``array`` to global rank ``dest``.  Completes
    only once the matching :func:`recv` has announced — an unmatched
    send surfaces as a collective-timeout naming this tensor and peer,
    never a silent hang (docs/pipeline.md#faults)."""
    lib = _load_lib()
    _check_initialized(lib)
    array = _as_contig(array)
    wire_name = _p2p_wire_name(name, rank(), dest, tag)
    return _enqueue_p2p(OP_SEND, "send", array, None, dest, tag, wire_name)


def recv_async(out: np.ndarray, source: int, tag: int = 0,
               name: Optional[str] = None) -> Handle:
    """Asynchronously receive into caller-allocated ``out`` from global
    rank ``source``.  The buffer is the shape/dtype contract: the
    coordinator cross-checks it against the sender's announcement and
    fails a mismatch with a typed precondition error.  Fixed-shape
    buffers keep repeated micro-batch cycles cacheable
    (docs/pipeline.md#steady-state)."""
    lib = _load_lib()
    _check_initialized(lib)
    out = np.asarray(out)
    if not out.flags["C_CONTIGUOUS"] or not out.flags["WRITEABLE"]:
        raise ValueError("recv buffer must be C-contiguous and writeable")
    wire_name = _p2p_wire_name(name, source, rank(), tag)
    return _enqueue_p2p(OP_RECV, "recv", out, out, source, tag, wire_name)


def send(array: np.ndarray, dest: int, tag: int = 0,
         name: Optional[str] = None) -> None:
    send_async(array, dest, tag, name).wait()


def recv(out: np.ndarray, source: int, tag: int = 0,
         name: Optional[str] = None) -> np.ndarray:
    return recv_async(out, source, tag, name).wait()


def allreduce(array: np.ndarray, average: bool = True,
              name: Optional[str] = None,
              group: Optional[StageGroup] = None) -> np.ndarray:
    return allreduce_async(array, average, name, group=group).wait()


def allgather(array: np.ndarray, name: Optional[str] = None) -> np.ndarray:
    return allgather_async(array, name).wait()


def broadcast(array: np.ndarray, root_rank: int,
              name: Optional[str] = None) -> np.ndarray:
    return broadcast_async(array, root_rank, name).wait()
