"""Elastic training driver: shrink-and-continue without checkpoint reload.

PR 2's fault tolerance recovers from a dead rank by relaunching *every*
rank and reloading a checkpoint — minutes of lost work per preemption.
With elastic membership (``hvdrun --min-np/--max-np``,
docs/fault-tolerance.md#elastic-membership) the engine instead reshapes
the job in place: survivors re-negotiate ``size()``/``rank()`` at a tick
boundary, in-flight collectives fail with the RETRYABLE
:class:`~horovod_tpu.common.MembershipChangedError`, and training state
is resynced by a root broadcast from the lowest surviving rank (always
the coordinator, rank 0).  This module is the loop that drives that
contract::

    state = hvd.ElasticState(weights=w, step=0)

    def train(state):
        for s in range(state.step, TOTAL):
            state.weights += hvd.allreduce(grad(state.weights),
                                           name=f"grad.{s}")
            state.step = s + 1
        return state.weights

    result = hvd.run_elastic(train, state)

``train_fn`` must be RE-ENTERABLE from ``state``: after a reshape the
driver resyncs every state leaf from the root and calls it again, so any
progress marker (the step counter above) has to live in the state.  The
checkpoint path stays the fallback — when survivors drop below
``--min-np`` the engine aborts fatally and the launcher's
``--max-restarts`` relaunch takes over.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional

import numpy as np


class ElasticState:
    """Named training state synchronized across membership changes.

    Keyword arguments become attributes; each is a numpy array, an
    array-convertible value (scalars round-trip through 0-d arrays and
    come back as Python numbers), or a pytree of arrays — nested
    dicts/lists/tuples, e.g. jax ``params``/``opt_state`` — whose array
    leaves are broadcast one by one in a deterministic order.
    :meth:`sync` replaces every leaf with the root rank's value via
    broadcast, using names keyed by the membership epoch so a resync in
    the new membership can never be confused with a stale pre-reshape
    negotiation.
    """

    def __init__(self, **leaves: Any):
        if not leaves:
            raise ValueError("ElasticState needs at least one named leaf")
        self._keys = sorted(leaves)
        for key, value in leaves.items():
            setattr(self, key, value)

    def keys(self):
        return list(self._keys)

    def sync(self, root: int = 0, key: int = 0) -> None:
        """Replace every leaf with the root's value (root broadcast)."""
        from horovod_tpu import common as _common

        for name in self._keys:
            value = getattr(self, name)
            if isinstance(value, (dict, list, tuple)):
                # A pytree leaf (jax params/opt_state): broadcast every
                # array leaf under an index-suffixed name.  Flattening
                # order is deterministic across ranks (same structure on
                # every member — the SPMD contract).
                flat, rebuild = _tree_flatten(value)
                synced = [
                    _common.broadcast(
                        np.asarray(x), root,
                        name=f"__elastic.sync.{key}.{name}.{i}")
                    for i, x in enumerate(flat)
                ]
                setattr(self, name, rebuild(synced))
                continue
            arr = np.asarray(value)
            out = _common.broadcast(arr, root,
                                    name=f"__elastic.sync.{key}.{name}")
            setattr(self, name, _coerce_like(value, out))


def _coerce_like(old: Any, new) -> Any:
    """A synced/restored leaf value with the ORIGINAL leaf's Python type
    preserved (step counters stay ints, flags stay bools) — shared by the
    root-broadcast sync and the state plane's sharded restore
    (horovod_tpu/state/partition.py), so the two resync paths cannot
    drift on scalar round-tripping."""
    if isinstance(old, np.ndarray):
        return new
    if isinstance(old, (bool, np.bool_)):
        return bool(new)
    if isinstance(old, (int, np.integer)):
        return int(new)
    if isinstance(old, (float, np.floating)):
        return float(new)
    return new


def _tree_flatten(tree: Any):
    """``(leaves, rebuild)`` for a pytree of arrays.  Uses
    ``jax.tree_util`` when importable (handles registered custom nodes —
    optax states and the like); otherwise a deterministic pure-python
    walk over dicts (sorted keys), lists, tuples, and namedtuples."""
    try:
        from jax import tree_util

        leaves, treedef = tree_util.tree_flatten(tree)
        return leaves, lambda new: tree_util.tree_unflatten(treedef, new)
    except ImportError:
        pass

    leaves: list = []

    def flatten(node):
        if isinstance(node, dict):
            keys = sorted(node)
            subs = [flatten(node[k]) for k in keys]
            return lambda it: {k: s(it) for k, s in zip(keys, subs)}
        if isinstance(node, (list, tuple)):
            subs = [flatten(v) for v in node]
            if isinstance(node, tuple) and hasattr(node, "_fields"):
                return lambda it: type(node)(*(s(it) for s in subs))
            if isinstance(node, tuple):
                return lambda it: tuple(s(it) for s in subs)
            return lambda it: [s(it) for s in subs]
        idx = len(leaves)
        leaves.append(node)
        return lambda it: it[idx]

    rebuild = flatten(tree)
    return leaves, rebuild


def run_elastic(train_fn: Callable[[ElasticState], Any],
                state: ElasticState,
                reshape_timeout: Optional[float] = None,
                state_plane=None) -> Any:
    """Run ``train_fn(state)`` under elastic membership, returning its
    result.

    On entry (and again after every reshape) the driver acknowledges the
    current membership and resyncs ``state`` from rank 0 by root
    broadcast — the entry-time sync doubles as the classic initial-state
    broadcast.  When a collective fails with a retryable engine error
    (:class:`MembershipChangedError`, or the transport errors that precede
    the reshape broadcast when a rank dies mid-ring), the driver waits for
    the membership epoch to advance and re-enters ``train_fn``.

    Fatal errors re-raise unchanged: :class:`RanksDownError` (the
    coordinator died, or survivors fell below ``--min-np`` — the
    checkpoint-restart fallback), :class:`CollectiveTimeoutError`
    (rank-divergent code, which shrinking cannot fix), and any
    non-engine exception from ``train_fn`` itself.

    ``reshape_timeout`` bounds the wait for the reshape broadcast after a
    retryable failure (default: twice ``HVD_TPU_COLLECTIVE_TIMEOUT_SEC``
    plus slack, min 30s); if no reshape lands in time the original error
    re-raises.

    With the state plane armed (``hvd.state.arm()``, or an explicit
    ``state_plane=``; docs/fault-tolerance.md#state-plane) the resync
    routes through it first: survivors restore from shard snapshots and
    peer copies in O(model/size) per rank, and only a membership no
    snapshot generation covers (nothing snapshotted yet, a neighbor pair
    lost together, a state-shape change) falls back to the root
    broadcast above — ``metrics_snapshot()["state"]`` counts both paths.
    """
    from horovod_tpu import common as _common
    from horovod_tpu.common import (CollectiveTimeoutError,
                                    HorovodInternalError,
                                    HorovodNotInitializedError,
                                    RanksDownError)
    from horovod_tpu.common.config import Config

    lib = _common._load_lib()
    _common._check_initialized(lib)
    if reshape_timeout is None:
        deadline_sec = Config.from_env().collective_timeout_sec
        reshape_timeout = max(2.0 * deadline_sec + 10.0, 30.0)
    synced = -1
    while True:
        try:
            epoch = int(lib.hvd_tpu_membership_epoch())
            if epoch != synced:
                # Ack BEFORE the resync collectives: they are the first
                # of the new membership and must not hit the engine's
                # post-reshape enqueue poison.
                lib.hvd_tpu_membership_ack()
                plane = state_plane
                if plane is None:
                    from horovod_tpu import state as _state_mod

                    plane = _state_mod.current()
                # The plane's restore is COLLECTIVE (plan allgather +
                # shard broadcasts), so the armed/None decision must be
                # rank-symmetric — arming is documented as every-rank.
                if plane is None or not plane.restore(state, epoch):
                    state.sync(root=0, key=epoch)
                synced = epoch
            return train_fn(state)
        except (RanksDownError, CollectiveTimeoutError,
                HorovodNotInitializedError):
            raise
        except HorovodInternalError as exc:
            # Retryable iff a reshape (re)shapes the job around the
            # failure.  The epoch may already have advanced (the reshape
            # broadcast often lands before the failed handle is waited
            # on); otherwise wait for the coordinator's barrier.
            deadline = time.monotonic() + reshape_timeout
            while int(lib.hvd_tpu_membership_epoch()) == synced:
                if (time.monotonic() >= deadline
                        or not lib.hvd_tpu_initialized()):
                    raise
                time.sleep(0.02)
            del exc  # consumed: the reshape explains the failure
