"""Runtime configuration knobs, read from the environment.

TPU-native counterpart of the env config block read in the reference's
background thread (/root/reference/horovod/common/operations.cc:1393-1420).
Both the reference's historical names (``HOROVOD_*``) and the new
``HVD_TPU_*`` names are honoured, new names winning, so reference scripts and
docs carry over unchanged.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

DEFAULT_FUSION_THRESHOLD = 64 * 1024 * 1024  # bytes, same default as reference
DEFAULT_CYCLE_TIME_MS = 5.0
DEFAULT_STALL_WARNING_SEC = 60.0


def _get(new: str, old: str) -> Optional[str]:
    return os.environ.get(new, os.environ.get(old))


_FALSY = ("", "0", "false", "no", "off")


def _flag(value: Optional[str]) -> bool:
    return value is not None and value.strip().lower() not in _FALSY


# Wire-compression mode spellings -> engine CompressionMode codes
# (engine/cc/wire.h; mirrored by the XLA plane's jnp casts).
COMPRESSION_CODES = {"off": 0, "none": 0, "0": 0, "": 0,
                     "bf16": 1, "bfloat16": 1,
                     "fp8": 2, "fp8_e4m3": 2, "float8_e4m3fn": 2}
COMPRESSION_NAMES = {0: "off", 1: "bf16", 2: "fp8"}


def parse_compression(value: Optional[str]) -> int:
    """``HVD_TPU_COMPRESSION`` spelling -> CompressionMode code; raises
    ``ValueError`` on an unknown mode."""
    key = (value or "off").strip().lower()
    if key not in COMPRESSION_CODES:
        raise ValueError(
            f"HVD_TPU_COMPRESSION: unknown wire-compression mode {value!r} "
            f"(want off, bf16, or fp8)")
    return COMPRESSION_CODES[key]


@dataclasses.dataclass(frozen=True)
class Config:
    fusion_threshold: int = DEFAULT_FUSION_THRESHOLD
    cycle_time_ms: float = DEFAULT_CYCLE_TIME_MS
    stall_warning_sec: float = DEFAULT_STALL_WARNING_SEC
    timeline_path: str = ""          # Chrome-tracing JSON output, rank 0
    # Two-level allreduce (docs/performance.md#two-level-topology):
    # node-local reduce-scatter, one cross-node (DCN) exchange per local
    # rank over its 1/local_size shard, node-local allgather — requires
    # the hvdrun contiguous-block rank layout.  The bandwidth-optimal
    # successor of the reference's HOROVOD_HIERARCHICAL_ALLREDUCE star
    # (operations.cc:1003-1048).
    hierarchical_allreduce: bool = False
    # Ring-vs-tree boundary for the two-level cross-node hop: buckets
    # under this many bytes take the recursive-doubling (tree) exchange
    # (log2(nodes) latency steps), the rest the bandwidth-optimal ring.
    # Autotuned as the fourth ParameterManager axis; 0 = ring always.
    cross_algo_threshold: int = 64 * 1024
    # Execute eager collectives as compiled XLA collectives over the
    # accelerator fabric (jax.distributed across the job) instead of the TCP
    # ring — the TPU mapping of the reference's NCCL data plane
    # (operations.cc:861-1100).  Tri-state, like the reference's NCCL path
    # which needed no runtime flag once compiled in (operations.cc:861-914):
    # None (env unset) = AUTO — enable when jax reports TPU devices;
    # True = forced on; False ("0"/"false"/"off") = explicit opt-out.
    # Unsupported dtypes stay on the TCP engine either way.
    xla_data_plane: Optional[bool] = None
    # Collective metrics registry (common/metrics.py, docs/metrics.md).
    # `metrics` force-enables collection; setting a metrics file or a
    # monitor port implies it (an empty registry serves nobody).
    metrics: bool = False
    metrics_file: str = ""           # JSON dump at shutdown, per rank
    monitor_port: Optional[int] = None  # HTTP /metrics server (+local_rank)
    # Fault tolerance (docs/fault-tolerance.md).  collective_timeout_sec:
    # hard deadline for a collective stuck in negotiation — past it the
    # coordinator escalates the stall warning to a coordinated abort
    # (CollectiveTimeoutError on every rank); <= 0 disables.  Applies to
    # both data planes (the engine's negotiation sweep and the XLA plane's
    # dispatch wait).
    collective_timeout_sec: float = 0.0
    # Deterministic fault injection spec (common/faults.py), e.g.
    # "rank=1:crash@op=12; rank=2:hang@op=5; rank=1:delay=3.0@op=7".
    fault_spec: str = ""
    # Restart counter exported by `hvdrun --max-restarts` (0 on the first
    # run, +1 per relaunch).  Read by checkpoint-resume glue and gates
    # fault clauses without an explicit epoch=N to the first run.
    restart_epoch: int = 0
    # Negotiation response cache (docs/performance.md): once a collective
    # has been fully negotiated, every rank replays the agreement from a
    # compact slot index instead of re-serializing string requests (and
    # the XLA plane skips its `__xp.*` metadata allreduce entirely).
    # HVD_TPU_RESPONSE_CACHE=0 is the kill switch; HVD_TPU_CACHE_CAPACITY
    # bounds the per-rank entry count (LRU eviction past it).
    response_cache: bool = True
    cache_capacity: int = 1024
    # Online autotuning (common/autotune.py, docs/performance.md
    # #autotuning): HVD_TPU_AUTOTUNE=1 lets the coordinator tune
    # fusion_threshold and cycle_time_ms online, broadcasting candidates
    # in the response list so every rank applies them in lockstep.  The
    # first `autotune_warmup` windows (of `autotune_window` negotiated
    # collectives each) are discarded; `autotune_fix` pins knobs
    # ("fusion_threshold=67108864,cycle_time_ms=5").
    autotune: bool = False
    autotune_warmup: int = 2
    autotune_window: int = 32
    autotune_fix: str = ""
    # Elastic membership (docs/fault-tolerance.md#elastic-membership).
    # HVD_TPU_ELASTIC=1 (set by `hvdrun --min-np/--max-np`): when a rank
    # dies, survivors re-negotiate size/rank at the next tick and keep
    # training (shrink-and-continue) instead of aborting, as long as at
    # least `min_np` ranks remain; `HVD_TPU_REJOIN=1` marks a standby
    # process that registers with a live coordinator and is admitted at
    # the next reshape barrier.
    elastic: bool = False
    min_np: int = 1
    rejoin: bool = False
    # Wire-level gradient compression (docs/performance.md
    # #wire-compression).  HVD_TPU_COMPRESSION=off|bf16|fp8: fp32
    # allreduce buckets of at least `compression_min_bytes` transfer as
    # bf16 (2x fewer wire bytes) or fp8-e4m3 (4x) with fp32 master copies
    # and per-tensor error-feedback residuals; reduction still
    # accumulates in f32 at every ring hop.  Agreed job-wide at init (a
    # mixed-env launch is a typed error), kill-switched by "off" (the
    # default — the fp32 wire stays bit-identical), and exposed to the
    # autotuner as a third axis (HVD_TPU_AUTOTUNE_FIX=compression=...
    # pins it).  f16/bf16 payloads ship at native width regardless.
    compression: str = "off"
    compression_min_bytes: int = 1024
    # Postmortem plane (docs/troubleshooting.md#reading-a-postmortem).
    # HVD_TPU_POSTMORTEM_DIR: directory each rank writes its
    # rank-<N>.json crash/hang dump into on typed aborts, injected
    # crashes, and fatal uncaught exceptions (hvdrun --postmortem-dir
    # sets it job-wide); empty disables.  HVD_TPU_FLIGHT_EVENTS sizes the
    # always-on flight-recorder rings (engine C++ ring and the XLA
    # plane's Python ring alike); 0 disables recording.
    postmortem_dir: str = ""
    flight_events: int = 512
    # Control-plane scaling (docs/performance.md#control-plane-scaling).
    # coord_tree (HVD_TPU_COORD_TREE, default on): multi-host jobs
    # restructure the rank-0 coordinator star into a two-level tree —
    # each host's local-rank-0 aggregates its node's announces into one
    # frame per tick and relays broadcasts back down, so rank 0 holds
    # O(hosts) sockets instead of O(ranks).  Single-host layouts keep the
    # degenerate one-level star either way.  steady_threshold
    # (HVD_TPU_STEADY_THRESHOLD): once a negotiation cycle's cache-hit
    # pattern repeats identically this many times, the coordinator
    # broadcasts a STEADY verdict and every rank self-clocks on an epoch
    # counter, replaying the cached responses with ZERO control-plane
    # messages per cycle (any miss falls back to full negotiation); 0
    # disables.  steady_max_period (HVD_TPU_STEADY_MAX_PERIOD) bounds the
    # detectable cycle length in collectives.
    coord_tree: bool = True
    steady_threshold: int = 32
    steady_max_period: int = 256
    # Data-plane heartbeat failure detector (docs/fault-tolerance.md
    # #failure-detection).  heartbeat_ms (HVD_TPU_HEARTBEAT_MS, default
    # 100): every rank's monitor thread beacons tiny typed frames to its
    # ring neighbours over dedicated data-plane sockets on this cadence,
    # entirely off the engine tick; 0 disables the detector.
    # heartbeat_miss (HVD_TPU_HEARTBEAT_MISS, default 10): consecutive
    # silent intervals before a neighbour is flagged frozen — elastic
    # jobs evict it through the reshape barrier, non-elastic jobs reach
    # a coordinated RanksDownError naming it, in O(heartbeat window)
    # instead of O(collective timeout).  net_fault_spec
    # (HVD_TPU_NET_FAULT_SPEC, common chaos grammar): deterministic
    # link-fault injection, e.g. "link=0-1:drop@after=2" or
    # "partition=0,1/2,3@after=1" or "link=1-2:delay=5|jitter=3" or
    # "link=0-3:flaky=0.05"; parsed by the engine at init (a bad spec is
    # a typed init error) and composable with HVD_TPU_FAULT_SPEC.
    heartbeat_ms: int = 100
    heartbeat_miss: int = 10
    net_fault_spec: str = ""
    # Perf-introspection plane (docs/metrics.md#links, #anomalies).
    # link_stats (HVD_TPU_LINK_STATS, default on): per-peer transport
    # telemetry — bytes, write stalls, timed-send latency histograms,
    # heartbeat-echo RTT — accounted at the net layer and exposed via
    # metrics_snapshot()["links"] / hvd_tpu_link_* families; 0 disables
    # the accounting (one relaxed atomic per transport call remains).
    # anomaly_sigma (HVD_TPU_ANOMALY_SIGMA, default 5): robust-excursion
    # threshold (median + sigma * MAD) of the online anomaly detector
    # that turns those baselines into typed verdicts — slow_link(A-B),
    # straggler(rank), cache_degraded, slow_phase(phase); 0 disables the
    # detector thread.  anomaly_interval_ms
    # (HVD_TPU_ANOMALY_INTERVAL_MS, default 500): detector sweep cadence,
    # floored at 10ms.
    link_stats: bool = True
    anomaly_sigma: int = 5
    anomaly_interval_ms: int = 500
    # Pluggable data-plane transport (docs/performance.md#transport).
    # shm (HVD_TPU_SHM=auto|off|force, default auto): the node-local
    # hops of the two-level allreduce hand fused-bucket segments through
    # mmap'd per-node shared-memory rings (no serialization, no syscall
    # per segment) when every rank of a node shares a host; "off" pins
    # every hop to TCP (kill switch — the data path is bit-identical
    # either way), "force" fails init with a typed error when shm
    # cannot arm.  Agreed job-wide at init like compression (a mixed-env
    # launch is a typed error).  shm_ring_bytes
    # (HVD_TPU_SHM_RING_BYTES, default 1 MiB, floor 64 KiB): payload
    # capacity of each direction's ring, rounded up to a power of two.
    shm: str = "auto"
    shm_ring_bytes: int = 1 << 20

    @property
    def compression_code(self) -> int:
        """The engine's CompressionMode code for ``compression``
        (engine/cc/wire.h).  Raises ``ValueError`` on an unknown
        spelling — a typo must not silently run uncompressed."""
        return parse_compression(self.compression)

    @property
    def effective_cache_capacity(self) -> int:
        """Slots the engine is told to keep: 0 (disabled) when the kill
        switch is thrown, else the configured capacity."""
        return self.cache_capacity if self.response_cache else 0

    @property
    def metrics_enabled(self) -> bool:
        return bool(self.metrics or self.metrics_file
                    or self.monitor_port is not None)

    @staticmethod
    def from_env() -> "Config":
        fusion = _get("HVD_TPU_FUSION_THRESHOLD", "HOROVOD_FUSION_THRESHOLD")
        # HVD_TPU_CYCLE_TIME_MS is the documented spelling (the idle-tick
        # floor of the adaptive engine loop, docs/performance.md); the
        # older unsuffixed names still work.
        cycle = os.environ.get(
            "HVD_TPU_CYCLE_TIME_MS",
            _get("HVD_TPU_CYCLE_TIME", "HOROVOD_CYCLE_TIME"))
        stall = _get("HVD_TPU_STALL_WARNING_SEC", "HOROVOD_STALL_WARNING_SEC")
        timeline = _get("HVD_TPU_TIMELINE", "HOROVOD_TIMELINE")
        return Config(
            fusion_threshold=int(fusion) if fusion else DEFAULT_FUSION_THRESHOLD,
            cycle_time_ms=float(cycle) if cycle else DEFAULT_CYCLE_TIME_MS,
            stall_warning_sec=float(stall) if stall else DEFAULT_STALL_WARNING_SEC,
            timeline_path=timeline or "",
            hierarchical_allreduce=_flag(
                _get("HVD_TPU_HIERARCHICAL_ALLREDUCE",
                     "HOROVOD_HIERARCHICAL_ALLREDUCE")),
            cross_algo_threshold=int(os.environ.get(
                "HVD_TPU_CROSS_ALGO_THRESHOLD") or 64 * 1024),
            xla_data_plane=(None if (plane := _get(
                "HVD_TPU_XLA_DATA_PLANE", "HOROVOD_XLA_DATA_PLANE")) is None
                else _flag(plane)),
            metrics=_flag(os.environ.get("HVD_TPU_METRICS")),
            metrics_file=os.environ.get("HVD_TPU_METRICS_FILE", ""),
            monitor_port=(int(port) if (port := os.environ.get(
                "HVD_TPU_MONITOR_PORT")) else None),
            collective_timeout_sec=float(os.environ.get(
                "HVD_TPU_COLLECTIVE_TIMEOUT_SEC") or 0.0),
            fault_spec=os.environ.get("HVD_TPU_FAULT_SPEC", ""),
            restart_epoch=int(os.environ.get(
                "HVD_TPU_RESTART_EPOCH") or 0),
            response_cache=_flag(os.environ.get(
                "HVD_TPU_RESPONSE_CACHE", "1")),
            cache_capacity=int(os.environ.get(
                "HVD_TPU_CACHE_CAPACITY") or 1024),
            autotune=_flag(os.environ.get("HVD_TPU_AUTOTUNE")),
            autotune_warmup=int(os.environ.get(
                "HVD_TPU_AUTOTUNE_WARMUP") or 2),
            autotune_window=int(os.environ.get(
                "HVD_TPU_AUTOTUNE_WINDOW") or 32),
            autotune_fix=os.environ.get("HVD_TPU_AUTOTUNE_FIX", ""),
            compression=os.environ.get("HVD_TPU_COMPRESSION", "off"),
            compression_min_bytes=int(os.environ.get(
                "HVD_TPU_COMPRESSION_MIN_BYTES") or 1024),
            elastic=_flag(os.environ.get("HVD_TPU_ELASTIC")),
            min_np=int(os.environ.get("HVD_TPU_MIN_NP") or 1),
            rejoin=_flag(os.environ.get("HVD_TPU_REJOIN")),
            postmortem_dir=os.environ.get("HVD_TPU_POSTMORTEM_DIR", ""),
            flight_events=int(os.environ.get(
                "HVD_TPU_FLIGHT_EVENTS") or 512),
            coord_tree=_flag(os.environ.get("HVD_TPU_COORD_TREE", "1")),
            steady_threshold=int(os.environ.get(
                "HVD_TPU_STEADY_THRESHOLD") or 32),
            steady_max_period=int(os.environ.get(
                "HVD_TPU_STEADY_MAX_PERIOD") or 256),
            heartbeat_ms=int(os.environ.get("HVD_TPU_HEARTBEAT_MS")
                             if os.environ.get("HVD_TPU_HEARTBEAT_MS")
                             not in (None, "") else 100),
            heartbeat_miss=int(os.environ.get(
                "HVD_TPU_HEARTBEAT_MISS") or 10),
            net_fault_spec=os.environ.get("HVD_TPU_NET_FAULT_SPEC", ""),
            link_stats=_flag(os.environ.get("HVD_TPU_LINK_STATS", "1")),
            anomaly_sigma=int(os.environ.get("HVD_TPU_ANOMALY_SIGMA")
                              if os.environ.get("HVD_TPU_ANOMALY_SIGMA")
                              not in (None, "") else 5),
            anomaly_interval_ms=int(os.environ.get(
                "HVD_TPU_ANOMALY_INTERVAL_MS") or 500),
            shm=os.environ.get("HVD_TPU_SHM", "auto") or "auto",
            shm_ring_bytes=int(os.environ.get(
                "HVD_TPU_SHM_RING_BYTES") or (1 << 20)),
        )
