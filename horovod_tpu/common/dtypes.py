"""Dtype codes shared with the C++ engine (engine/cc/wire.h DataType)."""

from __future__ import annotations

import numpy as np

UINT8 = 0
INT8 = 1
INT32 = 2
INT64 = 3
FLOAT16 = 4
FLOAT32 = 5
FLOAT64 = 6
BFLOAT16 = 7
BOOL = 8
UINT16 = 9

_NUMPY_TO_CODE = {
    np.dtype(np.uint8): UINT8,
    np.dtype(np.int8): INT8,
    np.dtype(np.int32): INT32,
    np.dtype(np.int64): INT64,
    np.dtype(np.float16): FLOAT16,
    np.dtype(np.float32): FLOAT32,
    np.dtype(np.float64): FLOAT64,
    np.dtype(np.bool_): BOOL,
    np.dtype(np.uint16): UINT16,
}

_CODE_TO_NUMPY = {v: k for k, v in _NUMPY_TO_CODE.items()}

try:  # ml_dtypes ships with jax; gives us a numpy bfloat16
    import ml_dtypes

    _NUMPY_TO_CODE[np.dtype(ml_dtypes.bfloat16)] = BFLOAT16
    _CODE_TO_NUMPY[BFLOAT16] = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    pass


def numpy_to_code(dtype) -> int:
    dtype = np.dtype(dtype)
    code = _NUMPY_TO_CODE.get(dtype)
    if code is None:
        raise ValueError(f"unsupported dtype for collective: {dtype}")
    return code


def code_to_numpy(code: int):
    return _CODE_TO_NUMPY[code]
