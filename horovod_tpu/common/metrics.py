"""Collective metrics registry: live aggregates for both data planes.

The timeline (docs/timeline.md) answers "what did tensor X do at time T";
this registry answers the operator questions a trace file cannot: how many
collectives ran, how many bytes moved, how full the fusion buckets are,
where wall-clock time goes (negotiation vs dispatch vs execute vs wait),
and which tensors are stalling — live, while the job runs.

Three consumers sit on top of one process-local registry:

* ``hvd.metrics_snapshot()`` / ``hvd.metrics_reset()`` — plain nested dict
  for programmatic access (tests, notebooks, schedulers).
* ``HVD_TPU_METRICS_FILE=<path>`` — JSON dump at ``shutdown()``, one file
  per rank (``<path>.<rank>``), for offline diffing (tools/metrics_dump.py).
* ``HVD_TPU_MONITOR_PORT=<port>`` — a daemon-thread HTTP server exposing
  Prometheus text at ``/metrics`` and the raw snapshot at ``/metrics.json``
  so a pod-slice job can be scraped mid-training.

Hot-path discipline: every instrumentation site is guarded by a single
``registry.enabled`` check (a plain attribute read); when disabled — the
default — collectives pay one branch.  Counter/histogram updates are a few
dict/int ops under one lock, safe against the engine's waiter threads and
the XLA plane's flush-from-any-thread pattern.  Stall records are NOT
gated on ``enabled``: they are rare by construction and tests must be able
to assert on them without opting into full metrics.
"""

from __future__ import annotations

import bisect
import json
import threading
from typing import Callable, Dict, List, Optional, Tuple

PLANES = ("engine", "xla")
OPS = ("allreduce", "allgather", "broadcast")

# Fixed bucket upper bounds.  Latencies: pseudo-log seconds covering 100us
# (one engine cycle is 5ms) out to the 60s stall horizon; fills: linear
# tenths of the fusion threshold.
LATENCY_BUCKETS: Tuple[float, ...] = (
    1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
FILL_BUCKETS: Tuple[float, ...] = tuple((i + 1) / 10 for i in range(10))

# name -> (bucket bounds, what it measures).  All durations in seconds.
HISTOGRAMS = {
    "negotiation_sec": (LATENCY_BUCKETS,
                        "control-plane negotiation wait "
                        "(enqueue -> agreed response), both planes"),
    "residency_sec": (LATENCY_BUCKETS,
                      "XLA-plane queue/bucket residency "
                      "(negotiated -> dispatched)"),
    "dispatch_sec": (LATENCY_BUCKETS,
                     "XLA-plane dispatch+execute "
                     "(program launch -> host result)"),
    "wait_sec": (LATENCY_BUCKETS,
                 "end-to-end Handle.wait() latency, both planes"),
    "bucket_fill": (FILL_BUCKETS,
                    "fusion-bucket fill fraction of the fusion threshold"),
    "step_sec": (LATENCY_BUCKETS,
                 "jax build_train_step per-call dispatch time"),
    "announce_skew_sec": (LATENCY_BUCKETS,
                          "first-to-last announce skew per negotiated "
                          "collective (rank-0 coordinator view)"),
    "serving_ttft_sec": (LATENCY_BUCKETS,
                         "serving plane: submit to first generated token "
                         "(rank-0 scheduler view)"),
    "serving_token_sec": (LATENCY_BUCKETS,
                          "serving plane: mean per-token latency of "
                          "retired requests (end-to-end / tokens)"),
    "topology_local_rs_sec": (LATENCY_BUCKETS,
                              "two-level allreduce: node-local "
                              "reduce-scatter phase per bucket"),
    "topology_cross_sec": (LATENCY_BUCKETS,
                           "two-level allreduce: cross-node (DCN) "
                           "exchange per bucket, ring or tree"),
    "topology_local_ag_sec": (LATENCY_BUCKETS,
                              "two-level allreduce: node-local "
                              "allgather phase per bucket"),
    "state_snapshot_sec": (LATENCY_BUCKETS,
                           "state plane: background serialize + spill + "
                           "peer push per committed shard snapshot"),
    "state_restore_sec": (LATENCY_BUCKETS,
                          "state plane: sharded restore after an elastic "
                          "reshape (plan + per-shard broadcasts)"),
}

# State-plane restore outcomes — the `source` label values of
# hvd_tpu_state_restores_total and the keys the elastic acceptance tests
# assert on (docs/fault-tolerance.md#state-plane).
STATE_RESTORE_SOURCES = ("peer", "local", "root_broadcast")
# Checkpoint lifecycle events — the `event` label values of
# hvd_tpu_state_checkpoint_events_total.
STATE_CKPT_EVENTS = ("sharded_saves", "legacy_saves", "loads", "pruned")

# Cap on distinct stalled-tensor entries kept by name; beyond it new names
# fold into a single overflow key so a pathological job (auto-named tensors
# stalling forever) cannot grow the registry unboundedly.
_MAX_STALL_TENSORS = 256
_STALL_OVERFLOW_KEY = "<other>"
# Same cap for per-tenant serving counters: tenant names arrive from the
# network, so an adversarial client must not be able to grow the registry
# (or the Prometheus exposition) without bound.
_MAX_TENANTS = 256

# Serving-plane event counters (requests lifecycle) — the keys of the
# "serving" snapshot section and the `event` label values of
# hvd_tpu_serving_requests_total.
SERVING_EVENTS = ("requests", "admitted", "rejected", "retired", "failed",
                  "preempted", "reformed")

# Anomaly-verdict kinds — the keys of the "anomalies" snapshot section's
# verdict counts and the `kind` label values of
# hvd_tpu_anomaly_verdicts_total.  Order matches the engine's verdict-kind
# indices (engine/cc/flight.h FL_ANOMALY).
ANOMALY_KINDS = ("slow_link", "straggler", "cache_degraded", "slow_phase")

# Per-link timed-send latency bucket upper bounds (µs) — must match
# kNetLinkBucketUs in engine/cc/net.cc; the engine serializes one extra
# +Inf overflow bucket after these.
LINK_SEND_BUCKETS_US = (50, 100, 250, 500, 1000, 2500, 5000, 10000, 50000)


class Histogram:
    """Fixed-bucket histogram; Prometheus-compatible (le upper bounds plus
    an implicit +Inf overflow bucket, sum, count).  Not self-locking: the
    registry's lock covers every mutation."""

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds: Tuple[float, ...]):
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.counts[bisect.bisect_left(self.bounds, value)] += 1
        self.sum += value
        self.count += 1

    def to_dict(self) -> dict:
        return {"buckets": list(self.bounds), "counts": list(self.counts),
                "sum": self.sum, "count": self.count}


class MetricsRegistry:
    """Process-local counters + histograms for the collective layer.

    ``enabled`` is the single hot-path gate: instrumentation sites read it
    once and skip everything when False.  All mutation happens under one
    lock; both data planes touch the registry from background/waiter
    threads (the engine's per-handle waits, the plane's flush-from-wait).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = False
        self._init_state()

    def _init_state(self) -> None:
        self._ops = {p: {o: 0 for o in OPS} for p in PLANES}
        self._bytes = {p: {"in": 0, "out": 0} for p in PLANES}
        self._batches = {"dispatched": 0, "fused_tensors": 0}
        self._stall_count = 0
        self._stall_tensors: Dict[str, dict] = {}
        # Fault tolerance (docs/fault-tolerance.md): injected faults by
        # action (crash/hang/delay), coordinated aborts by kind
        # (ranks_down/timeout), and the hvdrun restart epoch.  Recorded
        # ungated, like stalls: rare by construction, and fault tests must
        # assert on them without opting into full metrics.
        self._faults = {"injected": {}, "aborts": {}, "restart_epoch": 0}
        # Straggler attribution (rank-0 coordinator view): how often each
        # rank announced a collective LAST.  Ungated, like stalls: the
        # acceptance path asserts on it without enabling full metrics; the
        # matching skew distribution is the announce_skew_sec histogram.
        self._skew = {"count": 0, "last_to_announce": {}}
        # Negotiation response cache (docs/performance.md): hit/miss/
        # eviction events per plane ("engine" = the TCP engine's response
        # cache, "xla" = the plane's metadata cache) plus the current
        # entry-count gauge.  Ungated, like stalls: the acceptance path
        # asserts a hit rate without enabling full metrics.
        self._cache = {p: {"hits": 0, "misses": 0, "evictions": 0,
                           "size": 0} for p in PLANES}
        # Online autotuning (docs/performance.md#autotuning): a mirror of
        # the engine's state (applied params, freeze verdict, per-window
        # search history), refreshed on every snapshot by
        # hvd.metrics_snapshot().  Ungated, like stalls: the acceptance
        # path asserts frozen params without enabling full metrics.
        # Local import: this module loads from common/__init__.py, so a
        # module-level sibling import would run during the package's
        # partial initialization.
        from horovod_tpu.common.autotune import empty_report

        self._autotune = empty_report()
        # Elastic membership (docs/fault-tolerance.md#elastic-membership):
        # a mirror of the engine's membership state (epoch, current size,
        # reshape count, ranks lost/joined), refreshed on every snapshot.
        # Ungated, like stalls: reshape tests assert on it without
        # enabling full metrics.
        self._membership = {"epoch": 0, "size": 0, "reshapes": 0,
                            "ranks_lost": [], "ranks_joined": []}
        # Serving plane (docs/inference.md): request-lifecycle counters,
        # decode-step occupancy accounting, KV-pool gauges, and per-tenant
        # request/token counters.  Ungated, like stalls: the serve smoke
        # and acceptance tests assert on them without enabling full
        # metrics.  Meaningful on rank 0 (the scheduler) only.
        self._serving = {
            **{e: 0 for e in SERVING_EVENTS},
            "steps": 0, "slot_steps": 0,
            "queue_depth": 0, "active": 0, "batch_slots": 0,
            "kv_blocks_in_use": 0, "kv_blocks_total": 0,
            "tenants": {},
        }
        # Flight recorder (docs/troubleshooting.md#reading-a-postmortem):
        # cumulative event counts per plane plus the configured ring
        # capacity, mirrored from the recorders on every snapshot.
        # Ungated, like stalls: postmortem tests assert on it without
        # enabling full metrics.
        self._flight = {"events": {p: 0 for p in PLANES}, "capacity": 0}
        # Wire compression (docs/performance.md#wire-compression): the
        # applied mode, per-plane wire-vs-payload byte totals with
        # per-mode bucket counts, and the error-feedback residual gauges,
        # mirrored from both data planes on every snapshot.  Ungated,
        # like stalls: compression tests assert bytes ratios without
        # enabling full metrics.  Wire bytes count each allreduce bucket
        # at its on-wire width, payload bytes at the caller dtype's
        # width — the pair is what "2x fewer bytes" claims are made of.
        self._compression = {
            "mode": "off", "min_bytes": 0,
            "planes": {p: {"wire_bytes": 0, "payload_bytes": 0,
                           "ops": {"none": 0, "bf16": 0, "fp8": 0}}
                       for p in PLANES},
            "residual_bytes": 0, "residual_tensors": 0,
        }
        # Two-level topology (docs/performance.md#two-level-topology):
        # the engine's topology shape, ring/tree bucket counts, and
        # per-hop byte totals, mirrored on every snapshot; the matching
        # per-bucket phase timings land in the topology_*_sec
        # histograms.  Ungated, like stalls: topology tests assert byte
        # splits without enabling full metrics.
        self._topology = {
            "hierarchical": False, "nodes": 1, "local_size": 1,
            "cross_algo_threshold": 0,
            "cross_ops": {"ring": 0, "tree": 0},
            "bytes": {"local": 0, "cross": 0},
            "local_transport": "tcp",
        }
        # Control plane (docs/performance.md#control-plane-scaling): the
        # coordinator-tree shape this rank sees, the decentralized
        # steady-state counters, and the control-frame totals the
        # zero-frames-per-steady-cycle contract is asserted against.
        # Ungated, like stalls: the scale harness and control tests
        # assert frame deltas without enabling full metrics.
        self._control = {
            "tree": False, "depth": 1, "children": 0, "hosts": 1,
            "steady": {"active": False, "pattern_len": 0, "threshold": 0,
                       "entries": 0, "exits": 0, "replays": 0,
                       "cycles": 0},
            "negotiated_ticks": 0,
            "frames": {"sent": 0, "received": 0},
        }
        # Data-plane liveness (docs/fault-tolerance.md#failure-detection):
        # the heartbeat detector's configuration, beacon frame totals,
        # miss/eviction events, per-peer last-seen ages for the directly
        # monitored beacon neighbours, and the init clock-sync fan-in
        # (rank 0: peers probed directly — O(hosts) under the tree
        # relay).  Ungated, like stalls: fault tests assert eviction
        # counts without enabling full metrics.
        self._liveness = {
            "interval_ms": 0, "miss_limit": 0,
            "frames": {"sent": 0, "received": 0},
            "miss_events": 0, "evictions": 0, "clock_fanin": 0,
            "peers": {},
        }
        # Per-peer link telemetry (docs/metrics.md#links): transport
        # counters and latency/RTT estimates for every TCP link this rank
        # holds, mirrored from the engine's net-layer accounting.
        # Ungated, like stalls: the chaos-localization test asserts
        # per-link latency without enabling full metrics.
        self._links = {"enabled": False, "peers": {}}
        # Point-to-point plane (docs/metrics.md#p2p): send/recv transfer
        # and byte totals, the matched counter against the unmatched
        # gauge (enqueued transfers still waiting for their counterpart
        # to announce), stage-group ops, and the open dedicated-channel
        # gauge.  Ungated, like stalls: the pipeline fault tests assert
        # unmatched counts without enabling full metrics.
        self._p2p = {
            "sends": 0, "recvs": 0,
            "bytes": {"out": 0, "in": 0},
            "matched": 0, "unmatched": 0,
            "group_ops": 0, "channels": 0,
        }
        # Anomaly detector (docs/metrics.md#anomalies): configuration,
        # cumulative typed-verdict counts, and the bounded verdict log.
        # Ungated — verdicts exist to be seen.
        self._anomalies = {
            "sigma": 0, "interval_ms": 0,
            "verdicts": {k: 0 for k in ANOMALY_KINDS},
            "log": [],
        }
        # State plane (docs/fault-tolerance.md#state-plane): snapshot /
        # peer-copy / restore counters and the checkpoint lifecycle.
        # Ungated, like stalls: the elastic acceptance path asserts
        # peer_restores (and ZERO root-broadcast fallbacks) without
        # enabling full metrics, and the bench reads the overlap gauges.
        self._state = {
            "armed": False,
            "snapshots": 0, "snapshot_bytes": 0,
            "last_snapshot_step": -1,
            "blocked_sec": 0.0, "async_sec": 0.0,
            "peer_copies_sent": 0, "peer_bytes_sent": 0,
            "peer_copies_received": 0, "peer_last_step": -1,
            "restores": 0, "peer_restores": 0,
            "root_broadcast_fallbacks": 0,
            "ckpt": {**{e: 0 for e in STATE_CKPT_EVENTS},
                     "shard_bytes": 0},
        }
        self._hists = {name: Histogram(bounds)
                       for name, (bounds, _) in HISTOGRAMS.items()}

    # -- lifecycle --------------------------------------------------------

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            epoch = self._faults["restart_epoch"]
            self._init_state()
            # The restart epoch is job identity, not a counter; a mid-run
            # metrics_reset() must not make the job look like a first run.
            self._faults["restart_epoch"] = epoch

    # -- recording (call sites guard on `enabled`; stalls are ungated) ----

    def record_enqueue(self, plane: str, op: str, nbytes: int) -> None:
        with self._lock:
            self._ops[plane][op] += 1
            self._bytes[plane]["in"] += int(nbytes)

    def record_bytes_out(self, plane: str, nbytes: int) -> None:
        with self._lock:
            self._bytes[plane]["out"] += int(nbytes)

    def record_batch(self, n_ops: int) -> None:
        with self._lock:
            self._batches["dispatched"] += 1
            self._batches["fused_tensors"] += int(n_ops)

    def observe(self, hist: str, value: float) -> None:
        with self._lock:
            self._hists[hist].observe(float(value))

    def record_stall_count(self, n: int) -> None:
        """Bump the stall-event total without per-tensor detail (engine
        events whose names fell off the bounded C-side log)."""
        with self._lock:
            self._stall_count += int(n)

    def record_fault(self, action: str) -> None:
        """One injected fault fired (common/faults.py)."""
        with self._lock:
            self._faults["injected"][action] = (
                self._faults["injected"].get(action, 0) + 1)

    def record_abort(self, kind: str, n: int = 1) -> None:
        """Coordinated abort events: ``ranks_down`` (peer EOF) or
        ``timeout`` (collective deadline), folded in from the engine."""
        with self._lock:
            self._faults["aborts"][kind] = (
                self._faults["aborts"].get(kind, 0) + int(n))

    def set_restart_epoch(self, epoch: int) -> None:
        with self._lock:
            self._faults["restart_epoch"] = int(epoch)

    def record_cache(self, plane: str, kind: str, n: int = 1) -> None:
        """`n` response-cache events of `kind` ("hits" / "misses" /
        "evictions") on `plane`.  Ungated."""
        with self._lock:
            self._cache[plane][kind] += int(n)

    def set_cache_size(self, plane: str, size: int) -> None:
        """Current entry count of `plane`'s response cache (a gauge)."""
        with self._lock:
            self._cache[plane]["size"] = int(size)

    def set_membership(self, state: dict) -> None:
        """Mirror the engine's elastic-membership state (a state copy —
        idempotent overwrite, like the autotune mirror).  Ungated."""
        with self._lock:
            self._membership = dict(state)

    def set_flight(self, state: dict) -> None:
        """Mirror the flight recorders' state (a state copy — idempotent
        overwrite, like the membership mirror).  Ungated."""
        with self._lock:
            self._flight = {"events": dict(state.get("events", {})),
                            "capacity": int(state.get("capacity", 0))}

    def set_compression(self, state: dict) -> None:
        """Mirror the wire-compression state of both data planes (a state
        copy — the underlying counters are cumulative, so overwriting is
        idempotent, like the membership mirror).  Ungated."""
        with self._lock:
            planes = {}
            for plane in PLANES:
                entry = dict(state.get("planes", {}).get(plane, {}))
                planes[plane] = {
                    "wire_bytes": int(entry.get("wire_bytes", 0)),
                    "payload_bytes": int(entry.get("payload_bytes", 0)),
                    "ops": {m: int(entry.get("ops", {}).get(m, 0))
                            for m in ("none", "bf16", "fp8")},
                }
            self._compression = {
                "mode": str(state.get("mode", "off")),
                "min_bytes": int(state.get("min_bytes", 0)),
                "planes": planes,
                "residual_bytes": int(state.get("residual_bytes", 0)),
                "residual_tensors": int(state.get("residual_tensors", 0)),
            }

    def set_topology(self, state: dict) -> None:
        """Mirror the engine's two-level topology state (a state copy —
        the underlying counters are cumulative, so overwriting is
        idempotent, like the compression mirror).  Ungated."""
        with self._lock:
            self._topology = {
                "hierarchical": bool(state.get("hierarchical", False)),
                "nodes": int(state.get("nodes", 1)),
                "local_size": int(state.get("local_size", 1)),
                "cross_algo_threshold": int(
                    state.get("cross_algo_threshold", 0)),
                "cross_ops": {a: int(state.get("cross_ops", {}).get(a, 0))
                              for a in ("ring", "tree")},
                "bytes": {h: int(state.get("bytes", {}).get(h, 0))
                          for h in ("local", "cross")},
                "local_transport": str(
                    state.get("local_transport", "tcp")),
            }

    def set_control(self, state: dict) -> None:
        """Mirror the engine's control-plane state (a state copy — the
        underlying counters are cumulative, so overwriting is idempotent,
        like the topology mirror).  Ungated."""
        with self._lock:
            steady = state.get("steady", {})
            self._control = {
                "tree": bool(state.get("tree", False)),
                "depth": int(state.get("depth", 1)),
                "children": int(state.get("children", 0)),
                "hosts": int(state.get("hosts", 1)),
                "steady": {
                    "active": bool(steady.get("active", False)),
                    "pattern_len": int(steady.get("pattern_len", 0)),
                    "threshold": int(steady.get("threshold", 0)),
                    "entries": int(steady.get("entries", 0)),
                    "exits": int(steady.get("exits", 0)),
                    "replays": int(steady.get("replays", 0)),
                    "cycles": int(steady.get("cycles", 0)),
                },
                "negotiated_ticks": int(state.get("negotiated_ticks", 0)),
                "frames": {d: int(state.get("frames", {}).get(d, 0))
                           for d in ("sent", "received")},
            }

    def set_liveness(self, state: dict) -> None:
        """Mirror the engine's heartbeat-detector state (a state copy —
        the underlying counters are cumulative, so overwriting is
        idempotent, like the control mirror).  Ungated."""
        with self._lock:
            self._liveness = {
                "interval_ms": int(state.get("interval_ms", 0)),
                "miss_limit": int(state.get("miss_limit", 0)),
                "frames": {d: int(state.get("frames", {}).get(d, 0))
                           for d in ("sent", "received")},
                "miss_events": int(state.get("miss_events", 0)),
                "evictions": int(state.get("evictions", 0)),
                "clock_fanin": int(state.get("clock_fanin", 0)),
                "peers": {int(r): {"age_us": int(v.get("age_us", 0)),
                                   "misses": int(v.get("misses", 0))}
                          for r, v in state.get("peers", {}).items()},
            }

    def set_links(self, state: dict) -> None:
        """Mirror the engine's per-peer link telemetry (a state copy —
        the net-layer counters are cumulative, so overwriting is
        idempotent, like the liveness mirror).  Ungated."""
        with self._lock:
            self._links = {
                "enabled": bool(state.get("enabled", False)),
                "peers": {
                    str(r): {
                        "bytes_out": int(v.get("bytes_out", 0)),
                        "bytes_in": int(v.get("bytes_in", 0)),
                        "sends": int(v.get("sends", 0)),
                        "recvs": int(v.get("recvs", 0)),
                        "stalls": int(v.get("stalls", 0)),
                        "short_writes": int(v.get("short_writes", 0)),
                        "send_us_sum": int(v.get("send_us_sum", 0)),
                        "send_us_count": int(v.get("send_us_count", 0)),
                        "send_us_buckets": [
                            int(b) for b in v.get("send_us_buckets", [])],
                        "rtt_last_us": int(v.get("rtt_last_us", -1)),
                        "rtt_ewma_us": int(v.get("rtt_ewma_us", 0)),
                        "rtt_samples": int(v.get("rtt_samples", 0)),
                        "shm_bytes_out": int(v.get("shm_bytes_out", 0)),
                        "shm_bytes_in": int(v.get("shm_bytes_in", 0)),
                        "shm_handoffs": int(v.get("shm_handoffs", 0)),
                        "shm_us_sum": int(v.get("shm_us_sum", 0)),
                        "shm_us_count": int(v.get("shm_us_count", 0)),
                        "shm_us_buckets": [
                            int(b) for b in v.get("shm_us_buckets", [])],
                        "transport": str(v.get("transport", "tcp")),
                    }
                    for r, v in state.get("peers", {}).items()
                },
            }

    def set_p2p(self, state: dict) -> None:
        """Mirror the engine's point-to-point plane state (a state copy —
        the engine counters are cumulative, so overwriting is idempotent,
        like the links mirror).  Ungated."""
        with self._lock:
            self._p2p = {
                "sends": int(state.get("sends", 0)),
                "recvs": int(state.get("recvs", 0)),
                "bytes": {d: int(state.get("bytes", {}).get(d, 0))
                          for d in ("out", "in")},
                "matched": int(state.get("matched", 0)),
                "unmatched": int(state.get("unmatched", 0)),
                "group_ops": int(state.get("group_ops", 0)),
                "channels": int(state.get("channels", 0)),
            }

    def set_anomalies(self, state: dict) -> None:
        """Mirror the engine's anomaly-detector state: config, cumulative
        verdict counts, bounded verdict log (a state copy — idempotent).
        Ungated."""
        with self._lock:
            self._anomalies = {
                "sigma": int(state.get("sigma", 0)),
                "interval_ms": int(state.get("interval_ms", 0)),
                "verdicts": {k: int(state.get("verdicts", {}).get(k, 0))
                             for k in ANOMALY_KINDS},
                "log": [{"kind": str(e.get("kind", "")),
                         "subject": str(e.get("subject", "")),
                         "detail": str(e.get("detail", "")),
                         "age_us": int(e.get("age_us", 0))}
                        for e in state.get("log", [])][-64:],
            }

    def set_autotune(self, report: dict) -> None:
        """Mirror the engine's autotuning report (a state copy — the
        report carries current values plus bounded logs, so overwriting
        is idempotent).  Ungated."""
        with self._lock:
            self._autotune = dict(report)

    def record_last_announce(self, rank: int, n: int = 1) -> None:
        """`rank` announced a negotiated collective last, `n` times
        (coordinator view, folded in from the engine).  Ungated."""
        with self._lock:
            self._skew["count"] += int(n)
            key = str(rank)
            self._skew["last_to_announce"][key] = (
                self._skew["last_to_announce"].get(key, 0) + int(n))

    def _tenant_locked(self, tenant: str) -> dict:
        tenants = self._serving["tenants"]
        if tenant not in tenants and len(tenants) >= _MAX_TENANTS:
            tenant = _STALL_OVERFLOW_KEY
        return tenants.setdefault(tenant, {
            **{e: 0 for e in SERVING_EVENTS},
            "prompt_tokens": 0, "generated_tokens": 0,
        })

    def record_serving(self, event: str, tenant: Optional[str] = None,
                       n: int = 1) -> None:
        """`n` serving request-lifecycle events (one of
        :data:`SERVING_EVENTS`), optionally attributed to a tenant.
        Ungated."""
        with self._lock:
            self._serving[event] += int(n)
            if tenant is not None:
                self._tenant_locked(tenant)[event] += int(n)

    def record_serving_tokens(self, tenant: str, kind: str,
                              n: int) -> None:
        """`n` `kind` ("prompt" / "generated") tokens for a tenant."""
        with self._lock:
            self._tenant_locked(tenant)[f"{kind}_tokens"] += int(n)

    def record_serving_step(self, active_slots: int,
                            batch_slots: int) -> None:
        """One decode step carrying `active_slots` live requests: the
        running occupancy numerator/denominator."""
        with self._lock:
            self._serving["steps"] += 1
            self._serving["slot_steps"] += int(active_slots)
            self._serving["batch_slots"] = int(batch_slots)

    def set_serving_gauges(self, **gauges) -> None:
        """Overwrite serving gauges (queue_depth / active / batch_slots /
        kv_blocks_in_use / kv_blocks_total)."""
        with self._lock:
            for key, value in gauges.items():
                if key not in self._serving or key == "tenants":
                    raise KeyError(f"unknown serving gauge {key!r}")
                self._serving[key] = int(value)

    def set_state_armed(self, armed: bool) -> None:
        """The state plane armed/closed on this rank.  Ungated."""
        with self._lock:
            self._state["armed"] = bool(armed)

    def record_state_snapshot(self, step: int, nbytes: int) -> None:
        """One shard snapshot committed (background worker).  Ungated."""
        with self._lock:
            self._state["snapshots"] += 1
            self._state["snapshot_bytes"] += int(nbytes)
            self._state["last_snapshot_step"] = int(step)

    def set_state_overlap(self, blocked_sec: float,
                          async_sec: float) -> None:
        """Cumulative step-path blocked vs background overlapped seconds
        (gauges — the snapshotter owns the totals).  Ungated."""
        with self._lock:
            self._state["blocked_sec"] = float(blocked_sec)
            self._state["async_sec"] = float(async_sec)

    def record_state_peer(self, sent_bytes: Optional[int] = None,
                          received_step: Optional[int] = None) -> None:
        """A peer-mirror push sent (``sent_bytes``) or a full copy
        received (``received_step`` — the freshness gauge).  Ungated."""
        with self._lock:
            if sent_bytes is not None:
                self._state["peer_copies_sent"] += 1
                self._state["peer_bytes_sent"] += int(sent_bytes)
            if received_step is not None:
                self._state["peer_copies_received"] += 1
                self._state["peer_last_step"] = int(received_step)

    def record_state_restore(self, source: str) -> None:
        """One elastic resync routed by its source: ``"peer"`` (at least
        one shard came from a peer copy), ``"local"`` (own/survivor
        snapshots covered everything), or ``"root_broadcast"`` (the plane
        fell back to the classic O(model) sync).  Ungated."""
        if source not in STATE_RESTORE_SOURCES:
            raise ValueError(f"unknown state restore source {source!r}")
        with self._lock:
            if source == "root_broadcast":
                self._state["root_broadcast_fallbacks"] += 1
            else:
                self._state["restores"] += 1
                if source == "peer":
                    self._state["peer_restores"] += 1

    def record_state_ckpt(self, event: str, n: int = 1,
                          nbytes: int = 0) -> None:
        """Checkpoint lifecycle events (:data:`STATE_CKPT_EVENTS`).
        Ungated."""
        if event not in STATE_CKPT_EVENTS:
            raise ValueError(f"unknown state checkpoint event {event!r}")
        with self._lock:
            self._state["ckpt"][event] += int(n)
            self._state["ckpt"]["shard_bytes"] += int(nbytes)

    def record_stall(self, name: str, duration_sec: float) -> None:
        with self._lock:
            self._stall_count += 1
            if (name not in self._stall_tensors
                    and len(self._stall_tensors) >= _MAX_STALL_TENSORS):
                name = _STALL_OVERFLOW_KEY
            entry = self._stall_tensors.setdefault(
                name, {"count": 0, "last_duration_sec": 0.0})
            entry["count"] += 1
            entry["last_duration_sec"] = float(duration_sec)

    # -- reading ----------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "ops": {p: dict(v) for p, v in self._ops.items()},
                "bytes": {p: dict(v) for p, v in self._bytes.items()},
                "batches": dict(self._batches),
                "stalls": {
                    "count": self._stall_count,
                    "tensors": {k: dict(v)
                                for k, v in self._stall_tensors.items()},
                },
                "faults": {
                    "injected": dict(self._faults["injected"]),
                    "aborts": dict(self._faults["aborts"]),
                    "restart_epoch": self._faults["restart_epoch"],
                },
                "skew": {
                    "count": self._skew["count"],
                    "last_to_announce": dict(self._skew["last_to_announce"]),
                },
                "cache": {p: dict(v) for p, v in self._cache.items()},
                "membership": {
                    **self._membership,
                    "ranks_lost": list(
                        self._membership.get("ranks_lost", [])),
                    "ranks_joined": list(
                        self._membership.get("ranks_joined", [])),
                },
                "autotune": {
                    **self._autotune,
                    "history": [dict(h) for h in
                                self._autotune.get("history", [])],
                    "applied": [dict(a) for a in
                                self._autotune.get("applied", [])],
                },
                "serving": {
                    **{k: v for k, v in self._serving.items()
                       if k != "tenants"},
                    "occupancy": (
                        self._serving["slot_steps"]
                        / (self._serving["steps"]
                           * self._serving["batch_slots"])
                        if self._serving["steps"]
                        and self._serving["batch_slots"] else 0.0),
                    "tenants": {t: dict(v) for t, v in
                                self._serving["tenants"].items()},
                },
                "flight": {
                    "events": dict(self._flight["events"]),
                    "capacity": self._flight["capacity"],
                },
                "compression": {
                    "mode": self._compression["mode"],
                    "min_bytes": self._compression["min_bytes"],
                    "planes": {p: {"wire_bytes": v["wire_bytes"],
                                   "payload_bytes": v["payload_bytes"],
                                   "ops": dict(v["ops"])}
                               for p, v in
                               self._compression["planes"].items()},
                    "residual_bytes": self._compression["residual_bytes"],
                    "residual_tensors":
                        self._compression["residual_tensors"],
                },
                "topology": {
                    **{k: v for k, v in self._topology.items()
                       if k not in ("cross_ops", "bytes")},
                    "cross_ops": dict(self._topology["cross_ops"]),
                    "bytes": dict(self._topology["bytes"]),
                },
                "control": {
                    **{k: v for k, v in self._control.items()
                       if k not in ("steady", "frames")},
                    "steady": dict(self._control["steady"]),
                    "frames": dict(self._control["frames"]),
                },
                "liveness": {
                    **{k: v for k, v in self._liveness.items()
                       if k not in ("frames", "peers")},
                    "frames": dict(self._liveness["frames"]),
                    "peers": {r: dict(v) for r, v in
                              self._liveness["peers"].items()},
                },
                "links": {
                    "enabled": self._links["enabled"],
                    "peers": {r: {**v,
                                  "send_us_buckets":
                                  list(v["send_us_buckets"]),
                                  "shm_us_buckets":
                                  list(v.get("shm_us_buckets", []))}
                              for r, v in self._links["peers"].items()},
                },
                "p2p": {
                    **{k: v for k, v in self._p2p.items() if k != "bytes"},
                    "bytes": dict(self._p2p["bytes"]),
                },
                "anomalies": {
                    "sigma": self._anomalies["sigma"],
                    "interval_ms": self._anomalies["interval_ms"],
                    "verdicts": dict(self._anomalies["verdicts"]),
                    "log": [dict(e) for e in self._anomalies["log"]],
                },
                "state": {
                    **{k: v for k, v in self._state.items()
                       if k != "ckpt"},
                    "overlap_ratio": (
                        self._state["async_sec"]
                        / (self._state["async_sec"]
                           + self._state["blocked_sec"])
                        if self._state["async_sec"]
                        + self._state["blocked_sec"] > 0 else 1.0),
                    "ckpt": dict(self._state["ckpt"]),
                },
                "histograms": {name: h.to_dict()
                               for name, h in self._hists.items()},
            }


registry = MetricsRegistry()


# ---------------------------------------------------------------------------
# Prometheus text exposition (format v0.0.4).
# ---------------------------------------------------------------------------


def _label_escape(value: str) -> str:
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(value: float) -> str:
    return repr(int(value)) if float(value).is_integer() else repr(value)


def _prom_hist_name(name: str) -> str:
    if name.endswith("_sec"):
        return f"hvd_tpu_{name[:-4]}_seconds"
    return f"hvd_tpu_{name}_ratio"


def prometheus_text(snapshot: dict) -> str:
    """Render a metrics snapshot as Prometheus text exposition."""
    out: List[str] = []

    out.append("# HELP hvd_tpu_ops_total collective operations enqueued")
    out.append("# TYPE hvd_tpu_ops_total counter")
    for plane, per_op in snapshot["ops"].items():
        for op, n in per_op.items():
            out.append(f'hvd_tpu_ops_total{{plane="{plane}",op="{op}"}} {n}')

    out.append("# HELP hvd_tpu_bytes_total collective payload bytes")
    out.append("# TYPE hvd_tpu_bytes_total counter")
    for plane, per_dir in snapshot["bytes"].items():
        for direction, n in per_dir.items():
            out.append(f'hvd_tpu_bytes_total{{plane="{plane}",'
                       f'direction="{direction}"}} {n}')

    out.append("# HELP hvd_tpu_batches_dispatched_total "
               "fused batches dispatched (XLA plane)")
    out.append("# TYPE hvd_tpu_batches_dispatched_total counter")
    out.append("hvd_tpu_batches_dispatched_total "
               f"{snapshot['batches']['dispatched']}")
    out.append("# HELP hvd_tpu_fused_tensors_total "
               "tensors carried by dispatched batches")
    out.append("# TYPE hvd_tpu_fused_tensors_total counter")
    out.append("hvd_tpu_fused_tensors_total "
               f"{snapshot['batches']['fused_tensors']}")

    out.append("# HELP hvd_tpu_stall_events_total "
               "stall warnings (engine sweep + XLA-plane wait)")
    out.append("# TYPE hvd_tpu_stall_events_total counter")
    out.append(f"hvd_tpu_stall_events_total {snapshot['stalls']['count']}")
    out.append("# HELP hvd_tpu_stalled_tensor_total "
               "stall warnings per tensor name")
    out.append("# TYPE hvd_tpu_stalled_tensor_total counter")
    for name, entry in snapshot["stalls"]["tensors"].items():
        out.append(f'hvd_tpu_stalled_tensor_total{{tensor='
                   f'"{_label_escape(name)}"}} {entry["count"]}')

    faults = snapshot.get("faults", {})
    out.append("# HELP hvd_tpu_faults_injected_total "
               "injected faults fired (HVD_TPU_FAULT_SPEC)")
    out.append("# TYPE hvd_tpu_faults_injected_total counter")
    for action, n in faults.get("injected", {}).items():
        out.append(f'hvd_tpu_faults_injected_total{{action='
                   f'"{_label_escape(action)}"}} {n}')
    out.append("# HELP hvd_tpu_aborts_total "
               "coordinated aborts (ranks_down / timeout)")
    out.append("# TYPE hvd_tpu_aborts_total counter")
    for kind, n in faults.get("aborts", {}).items():
        out.append(f'hvd_tpu_aborts_total{{kind='
                   f'"{_label_escape(kind)}"}} {n}')
    out.append("# HELP hvd_tpu_restart_epoch "
               "hvdrun restart counter (0 = first run)")
    out.append("# TYPE hvd_tpu_restart_epoch gauge")
    out.append(f"hvd_tpu_restart_epoch {faults.get('restart_epoch', 0)}")

    cache = snapshot.get("cache", {})
    out.append("# HELP hvd_tpu_response_cache_events_total "
               "negotiation response cache events (docs/performance.md)")
    out.append("# TYPE hvd_tpu_response_cache_events_total counter")
    for plane, per_kind in cache.items():
        for kind in ("hits", "misses", "evictions"):
            out.append(f'hvd_tpu_response_cache_events_total{{plane='
                       f'"{plane}",event="{kind}"}} '
                       f'{per_kind.get(kind, 0)}')
    out.append("# HELP hvd_tpu_response_cache_size "
               "current response-cache entry count")
    out.append("# TYPE hvd_tpu_response_cache_size gauge")
    for plane, per_kind in cache.items():
        out.append(f'hvd_tpu_response_cache_size{{plane="{plane}"}} '
                   f'{per_kind.get("size", 0)}')

    tune = snapshot.get("autotune", {})
    out.append("# HELP hvd_tpu_autotune_enabled "
               "online autotuning opted in (HVD_TPU_AUTOTUNE)")
    out.append("# TYPE hvd_tpu_autotune_enabled gauge")
    out.append(f"hvd_tpu_autotune_enabled {int(tune.get('enabled', False))}")
    out.append("# HELP hvd_tpu_autotune_frozen "
               "autotuning search converged and froze")
    out.append("# TYPE hvd_tpu_autotune_frozen gauge")
    out.append(f"hvd_tpu_autotune_frozen {int(tune.get('frozen', False))}")
    out.append("# HELP hvd_tpu_autotune_windows_total "
               "tuning windows scored (coordinator view)")
    out.append("# TYPE hvd_tpu_autotune_windows_total counter")
    out.append(f"hvd_tpu_autotune_windows_total {tune.get('windows', 0)}")
    out.append("# HELP hvd_tpu_autotune_fusion_threshold_bytes "
               "currently applied tensor-fusion threshold")
    out.append("# TYPE hvd_tpu_autotune_fusion_threshold_bytes gauge")
    out.append("hvd_tpu_autotune_fusion_threshold_bytes "
               f"{tune.get('fusion_threshold', 0)}")
    out.append("# HELP hvd_tpu_autotune_cycle_time_seconds "
               "currently applied negotiation cycle time")
    out.append("# TYPE hvd_tpu_autotune_cycle_time_seconds gauge")
    out.append("hvd_tpu_autotune_cycle_time_seconds "
               f"{repr(float(tune.get('cycle_time_ms', 0.0)) / 1000.0)}")
    out.append("# HELP hvd_tpu_autotune_best_score "
               "best window score seen (payload bytes+ops per second)")
    out.append("# TYPE hvd_tpu_autotune_best_score gauge")
    out.append(f"hvd_tpu_autotune_best_score "
               f"{repr(float(tune.get('best_score', 0.0)))}")

    member = snapshot.get("membership", {})
    out.append("# HELP hvd_tpu_membership_epoch "
               "elastic membership epoch (reshapes survived this job)")
    out.append("# TYPE hvd_tpu_membership_epoch gauge")
    out.append(f"hvd_tpu_membership_epoch {member.get('epoch', 0)}")
    out.append("# HELP hvd_tpu_membership_size "
               "current job size after elastic reshapes")
    out.append("# TYPE hvd_tpu_membership_size gauge")
    out.append(f"hvd_tpu_membership_size {member.get('size', 0)}")
    out.append("# HELP hvd_tpu_membership_reshapes_total "
               "elastic membership reshape barriers applied")
    out.append("# TYPE hvd_tpu_membership_reshapes_total counter")
    out.append("hvd_tpu_membership_reshapes_total "
               f"{member.get('reshapes', 0)}")
    out.append("# HELP hvd_tpu_membership_ranks_lost_total "
               "ranks lost to elastic shrinks")
    out.append("# TYPE hvd_tpu_membership_ranks_lost_total counter")
    out.append("hvd_tpu_membership_ranks_lost_total "
               f"{len(member.get('ranks_lost', []))}")
    out.append("# HELP hvd_tpu_membership_ranks_joined_total "
               "standby ranks admitted by elastic grows")
    out.append("# TYPE hvd_tpu_membership_ranks_joined_total counter")
    out.append("hvd_tpu_membership_ranks_joined_total "
               f"{len(member.get('ranks_joined', []))}")

    serving = snapshot.get("serving", {})
    out.append("# HELP hvd_tpu_serving_requests_total "
               "serving request lifecycle events (docs/inference.md)")
    out.append("# TYPE hvd_tpu_serving_requests_total counter")
    for event in SERVING_EVENTS:
        out.append(f'hvd_tpu_serving_requests_total{{event="{event}"}} '
                   f'{serving.get(event, 0)}')
    out.append("# HELP hvd_tpu_serving_steps_total "
               "decode steps executed (rank-0 scheduler view)")
    out.append("# TYPE hvd_tpu_serving_steps_total counter")
    out.append(f"hvd_tpu_serving_steps_total {serving.get('steps', 0)}")
    out.append("# HELP hvd_tpu_serving_queue_depth "
               "requests waiting for a batch slot")
    out.append("# TYPE hvd_tpu_serving_queue_depth gauge")
    out.append("hvd_tpu_serving_queue_depth "
               f"{serving.get('queue_depth', 0)}")
    out.append("# HELP hvd_tpu_serving_active_requests "
               "requests currently holding a decode-batch slot")
    out.append("# TYPE hvd_tpu_serving_active_requests gauge")
    out.append(f"hvd_tpu_serving_active_requests {serving.get('active', 0)}")
    out.append("# HELP hvd_tpu_serving_batch_occupancy "
               "mean fraction of decode-batch slots carrying a request")
    out.append("# TYPE hvd_tpu_serving_batch_occupancy gauge")
    out.append("hvd_tpu_serving_batch_occupancy "
               f"{repr(float(serving.get('occupancy', 0.0)))}")
    out.append("# HELP hvd_tpu_serving_kv_blocks_in_use "
               "KV cache blocks currently allocated")
    out.append("# TYPE hvd_tpu_serving_kv_blocks_in_use gauge")
    out.append("hvd_tpu_serving_kv_blocks_in_use "
               f"{serving.get('kv_blocks_in_use', 0)}")
    out.append("# HELP hvd_tpu_serving_kv_blocks_total "
               "KV cache block pool size")
    out.append("# TYPE hvd_tpu_serving_kv_blocks_total gauge")
    out.append("hvd_tpu_serving_kv_blocks_total "
               f"{serving.get('kv_blocks_total', 0)}")
    out.append("# HELP hvd_tpu_serving_tenant_requests_total "
               "serving request events per tenant")
    out.append("# TYPE hvd_tpu_serving_tenant_requests_total counter")
    out.append("# HELP hvd_tpu_serving_tenant_tokens_total "
               "prompt/generated tokens per tenant")
    out.append("# TYPE hvd_tpu_serving_tenant_tokens_total counter")
    for tenant, entry in serving.get("tenants", {}).items():
        label = _label_escape(tenant)
        for event in SERVING_EVENTS:
            if entry.get(event):
                out.append(
                    f'hvd_tpu_serving_tenant_requests_total{{tenant='
                    f'"{label}",event="{event}"}} {entry[event]}')
        for kind in ("prompt", "generated"):
            out.append(f'hvd_tpu_serving_tenant_tokens_total{{tenant='
                       f'"{label}",kind="{kind}"}} '
                       f'{entry.get(f"{kind}_tokens", 0)}')

    flight = snapshot.get("flight", {})
    out.append("# HELP hvd_tpu_flight_events_total "
               "flight-recorder events recorded "
               "(docs/troubleshooting.md#reading-a-postmortem)")
    out.append("# TYPE hvd_tpu_flight_events_total counter")
    for plane in PLANES:
        out.append(f'hvd_tpu_flight_events_total{{plane="{plane}"}} '
                   f'{flight.get("events", {}).get(plane, 0)}')
    out.append("# HELP hvd_tpu_flight_ring_capacity "
               "configured flight-recorder ring size "
               "(HVD_TPU_FLIGHT_EVENTS; 0 = disabled)")
    out.append("# TYPE hvd_tpu_flight_ring_capacity gauge")
    out.append(f"hvd_tpu_flight_ring_capacity {flight.get('capacity', 0)}")

    comp = snapshot.get("compression", {})
    out.append("# HELP hvd_tpu_compression_mode "
               "applied wire-compression mode (0=off 1=bf16 2=fp8; "
               "docs/performance.md#wire-compression)")
    out.append("# TYPE hvd_tpu_compression_mode gauge")
    out.append("hvd_tpu_compression_mode "
               f"{ {'off': 0, 'bf16': 1, 'fp8': 2}.get(comp.get('mode'), 0) }")
    out.append("# HELP hvd_tpu_compression_wire_bytes_total "
               "allreduce bucket bytes at on-wire width")
    out.append("# TYPE hvd_tpu_compression_wire_bytes_total counter")
    for plane, entry in comp.get("planes", {}).items():
        out.append(f'hvd_tpu_compression_wire_bytes_total{{plane='
                   f'"{plane}"}} {entry.get("wire_bytes", 0)}')
    out.append("# HELP hvd_tpu_compression_payload_bytes_total "
               "allreduce bucket bytes at caller-dtype width")
    out.append("# TYPE hvd_tpu_compression_payload_bytes_total counter")
    for plane, entry in comp.get("planes", {}).items():
        out.append(f'hvd_tpu_compression_payload_bytes_total{{plane='
                   f'"{plane}"}} {entry.get("payload_bytes", 0)}')
    out.append("# HELP hvd_tpu_compression_ops_total "
               "allreduce buckets executed per wire mode")
    out.append("# TYPE hvd_tpu_compression_ops_total counter")
    for plane, entry in comp.get("planes", {}).items():
        for mode, n in entry.get("ops", {}).items():
            out.append(f'hvd_tpu_compression_ops_total{{plane="{plane}",'
                       f'mode="{mode}"}} {n}')
    out.append("# HELP hvd_tpu_compression_residual_bytes "
               "error-feedback residual buffer bytes held")
    out.append("# TYPE hvd_tpu_compression_residual_bytes gauge")
    out.append("hvd_tpu_compression_residual_bytes "
               f"{comp.get('residual_bytes', 0)}")

    topo = snapshot.get("topology", {})
    out.append("# HELP hvd_tpu_topology_hierarchical "
               "two-level allreduce topology active "
               "(docs/performance.md#two-level-topology)")
    out.append("# TYPE hvd_tpu_topology_hierarchical gauge")
    out.append("hvd_tpu_topology_hierarchical "
               f"{int(topo.get('hierarchical', False))}")
    out.append("# HELP hvd_tpu_topology_nodes "
               "node count of the two-level topology (1 = flat)")
    out.append("# TYPE hvd_tpu_topology_nodes gauge")
    out.append(f"hvd_tpu_topology_nodes {topo.get('nodes', 1)}")
    out.append("# HELP hvd_tpu_topology_local_size "
               "ranks per node in the two-level topology")
    out.append("# TYPE hvd_tpu_topology_local_size gauge")
    out.append(f"hvd_tpu_topology_local_size {topo.get('local_size', 1)}")
    out.append("# HELP hvd_tpu_topology_local_transport transport carrying "
               "the node-local hops (docs/performance.md#transport)")
    out.append("# TYPE hvd_tpu_topology_local_transport gauge")
    out.append("hvd_tpu_topology_local_transport{transport="
               f"\"{topo.get('local_transport', 'tcp')}\"}} 1")
    out.append("# HELP hvd_tpu_topology_cross_algo_threshold_bytes "
               "ring-vs-tree boundary for the cross-node hop "
               "(buckets under it take the tree)")
    out.append("# TYPE hvd_tpu_topology_cross_algo_threshold_bytes gauge")
    out.append("hvd_tpu_topology_cross_algo_threshold_bytes "
               f"{topo.get('cross_algo_threshold', 0)}")
    out.append("# HELP hvd_tpu_topology_cross_ops_total "
               "two-level buckets executed per cross-node algorithm")
    out.append("# TYPE hvd_tpu_topology_cross_ops_total counter")
    for algo, n in topo.get("cross_ops", {}).items():
        out.append(f'hvd_tpu_topology_cross_ops_total{{algo="{algo}"}} {n}')
    out.append("# HELP hvd_tpu_topology_bytes_total "
               "two-level allreduce wire bytes sent per hop "
               "(local = intra-node ring, cross = DCN)")
    out.append("# TYPE hvd_tpu_topology_bytes_total counter")
    for hop, n in topo.get("bytes", {}).items():
        out.append(f'hvd_tpu_topology_bytes_total{{hop="{hop}"}} {n}')

    ctrl = snapshot.get("control", {})
    steady = ctrl.get("steady", {})
    out.append("# HELP hvd_tpu_control_tree_depth coordinator levels in "
               "the control plane (1 = star, 2 = per-host "
               "sub-coordinator tree; docs/performance.md"
               "#control-plane-scaling)")
    out.append("# TYPE hvd_tpu_control_tree_depth gauge")
    out.append(f"hvd_tpu_control_tree_depth {ctrl.get('depth', 1)}")
    out.append("# HELP hvd_tpu_control_children control sockets this "
               "rank reads each negotiation tick (fan-in at its tree "
               "level)")
    out.append("# TYPE hvd_tpu_control_children gauge")
    out.append(f"hvd_tpu_control_children {ctrl.get('children', 0)}")
    out.append("# HELP hvd_tpu_control_steady_active this rank is "
               "self-clocking in the decentralized steady state (zero "
               "control-plane frames per cycle)")
    out.append("# TYPE hvd_tpu_control_steady_active gauge")
    out.append("hvd_tpu_control_steady_active "
               f"{int(steady.get('active', False))}")
    out.append("# HELP hvd_tpu_control_steady_cycles_total negotiation "
               "cycles replayed self-clocked (no coordinator traffic)")
    out.append("# TYPE hvd_tpu_control_steady_cycles_total counter")
    out.append("hvd_tpu_control_steady_cycles_total "
               f"{steady.get('cycles', 0)}")
    out.append("# HELP hvd_tpu_control_steady_transitions_total steady-"
               "state entries and exits on this rank")
    out.append("# TYPE hvd_tpu_control_steady_transitions_total counter")
    for kind in ("entries", "exits"):
        out.append("hvd_tpu_control_steady_transitions_total"
                   f'{{kind="{kind}"}} {steady.get(kind, 0)}')
    out.append("# HELP hvd_tpu_control_negotiated_ticks_total broadcast "
               "response lists processed that carried negotiated work")
    out.append("# TYPE hvd_tpu_control_negotiated_ticks_total counter")
    out.append("hvd_tpu_control_negotiated_ticks_total "
               f"{ctrl.get('negotiated_ticks', 0)}")
    out.append("# HELP hvd_tpu_control_frames_total control-plane frames "
               "this rank sent/received (flat during steady-state "
               "cycles)")
    out.append("# TYPE hvd_tpu_control_frames_total counter")
    for d, n in ctrl.get("frames", {}).items():
        out.append(f'hvd_tpu_control_frames_total{{dir="{d}"}} {n}')

    live = snapshot.get("liveness", {})
    out.append("# HELP hvd_tpu_liveness_interval_ms data-plane heartbeat "
               "interval (0 = detector disabled; docs/fault-tolerance.md"
               "#failure-detection)")
    out.append("# TYPE hvd_tpu_liveness_interval_ms gauge")
    out.append(f"hvd_tpu_liveness_interval_ms {live.get('interval_ms', 0)}")
    out.append("# HELP hvd_tpu_liveness_miss_limit consecutive missed "
               "beacon intervals before a peer is flagged")
    out.append("# TYPE hvd_tpu_liveness_miss_limit gauge")
    out.append(f"hvd_tpu_liveness_miss_limit {live.get('miss_limit', 0)}")
    out.append("# HELP hvd_tpu_liveness_frames_total heartbeat beacons "
               "this rank sent/received on the data plane")
    out.append("# TYPE hvd_tpu_liveness_frames_total counter")
    for d, n in live.get("frames", {}).items():
        out.append(f'hvd_tpu_liveness_frames_total{{dir="{d}"}} {n}')
    out.append("# HELP hvd_tpu_liveness_miss_events_total peers flagged "
               "silent past the miss window by this rank's detector")
    out.append("# TYPE hvd_tpu_liveness_miss_events_total counter")
    out.append("hvd_tpu_liveness_miss_events_total "
               f"{live.get('miss_events', 0)}")
    out.append("# HELP hvd_tpu_liveness_evictions_total ranks the "
               "coordinator marked down from heartbeat evidence")
    out.append("# TYPE hvd_tpu_liveness_evictions_total counter")
    out.append(f"hvd_tpu_liveness_evictions_total {live.get('evictions', 0)}")
    out.append("# HELP hvd_tpu_liveness_clock_fanin peers this rank "
               "probed directly during init clock sync (rank 0 under the "
               "sub-coordinator tree: O(hosts), not O(ranks))")
    out.append("# TYPE hvd_tpu_liveness_clock_fanin gauge")
    out.append(f"hvd_tpu_liveness_clock_fanin {live.get('clock_fanin', 0)}")
    out.append("# HELP hvd_tpu_liveness_peer_age_us microseconds since "
               "the last beacon from a directly monitored neighbour")
    out.append("# TYPE hvd_tpu_liveness_peer_age_us gauge")
    for r, v in live.get("peers", {}).items():
        out.append(f'hvd_tpu_liveness_peer_age_us{{peer="{r}"}} '
                   f'{v.get("age_us", 0)}')

    links = snapshot.get("links", {})
    link_peers = links.get("peers", {})
    out.append("# HELP hvd_tpu_link_stats_enabled per-peer link telemetry "
               "armed on this rank (HVD_TPU_LINK_STATS)")
    out.append("# TYPE hvd_tpu_link_stats_enabled gauge")
    out.append("hvd_tpu_link_stats_enabled "
               f"{int(links.get('enabled', False))}")
    out.append("# HELP hvd_tpu_link_bytes_total bytes moved over each "
               "peer link by direction (docs/metrics.md#links)")
    out.append("# TYPE hvd_tpu_link_bytes_total counter")
    for r, v in link_peers.items():
        out.append(f'hvd_tpu_link_bytes_total{{peer="{r}",dir="out"}} '
                   f'{v.get("bytes_out", 0)}')
        out.append(f'hvd_tpu_link_bytes_total{{peer="{r}",dir="in"}} '
                   f'{v.get("bytes_in", 0)}')
    out.append("# HELP hvd_tpu_link_sends_total timed whole-frame sends "
               "completed on each peer link")
    out.append("# TYPE hvd_tpu_link_sends_total counter")
    for r, v in link_peers.items():
        out.append(f'hvd_tpu_link_sends_total{{peer="{r}"}} '
                   f'{v.get("sends", 0)}')
    out.append("# HELP hvd_tpu_link_stall_events_total transport "
               "backpressure on each peer link (write stalls, short "
               "writes)")
    out.append("# TYPE hvd_tpu_link_stall_events_total counter")
    for r, v in link_peers.items():
        out.append(f'hvd_tpu_link_stall_events_total{{peer="{r}",'
                   f'kind="stall"}} {v.get("stalls", 0)}')
        out.append(f'hvd_tpu_link_stall_events_total{{peer="{r}",'
                   f'kind="short_write"}} {v.get("short_writes", 0)}')
    out.append("# HELP hvd_tpu_link_send_latency_us whole-frame send "
               "latency per peer link (includes any injected chaos "
               "delay)")
    out.append("# TYPE hvd_tpu_link_send_latency_us histogram")
    for r, v in link_peers.items():
        buckets = v.get("send_us_buckets", [])
        cumulative = 0
        for bound, n in zip(LINK_SEND_BUCKETS_US, buckets):
            cumulative += n
            out.append(f'hvd_tpu_link_send_latency_us_bucket{{peer="{r}",'
                       f'le="{_fmt(bound)}"}} {cumulative}')
        out.append(f'hvd_tpu_link_send_latency_us_bucket{{peer="{r}",'
                   f'le="+Inf"}} {v.get("send_us_count", 0)}')
        out.append(f'hvd_tpu_link_send_latency_us_sum{{peer="{r}"}} '
                   f'{v.get("send_us_sum", 0)}')
        out.append(f'hvd_tpu_link_send_latency_us_count{{peer="{r}"}} '
                   f'{v.get("send_us_count", 0)}')
    out.append("# HELP hvd_tpu_link_rtt_us heartbeat-echo round-trip "
               "estimate per peer link (last sample and EWMA)")
    out.append("# TYPE hvd_tpu_link_rtt_us gauge")
    for r, v in link_peers.items():
        if v.get("rtt_samples", 0) > 0:
            out.append(f'hvd_tpu_link_rtt_us{{peer="{r}",stat="last"}} '
                       f'{v.get("rtt_last_us", -1)}')
            out.append(f'hvd_tpu_link_rtt_us{{peer="{r}",stat="ewma"}} '
                       f'{v.get("rtt_ewma_us", 0)}')
    out.append("# HELP hvd_tpu_link_rtt_samples_total heartbeat-echo "
               "round trips measured per peer link")
    out.append("# TYPE hvd_tpu_link_rtt_samples_total counter")
    for r, v in link_peers.items():
        out.append(f'hvd_tpu_link_rtt_samples_total{{peer="{r}"}} '
                   f'{v.get("rtt_samples", 0)}')
    out.append("# HELP hvd_tpu_link_transport data-plane transport "
               "carrying each peer link (1 for the labeled transport; "
               "docs/performance.md#transport)")
    out.append("# TYPE hvd_tpu_link_transport gauge")
    for r, v in link_peers.items():
        out.append(f'hvd_tpu_link_transport{{peer="{r}",'
                   f'transport="{v.get("transport", "tcp")}"}} 1')
    out.append("# HELP hvd_tpu_link_shm_bytes_total bytes handed off "
               "through the shared-memory rings per peer by direction")
    out.append("# TYPE hvd_tpu_link_shm_bytes_total counter")
    for r, v in link_peers.items():
        out.append(f'hvd_tpu_link_shm_bytes_total{{peer="{r}",dir="out"}} '
                   f'{v.get("shm_bytes_out", 0)}')
        out.append(f'hvd_tpu_link_shm_bytes_total{{peer="{r}",dir="in"}} '
                   f'{v.get("shm_bytes_in", 0)}')
    out.append("# HELP hvd_tpu_link_shm_handoffs_total segment handoffs "
               "completed through the shared-memory rings per peer")
    out.append("# TYPE hvd_tpu_link_shm_handoffs_total counter")
    for r, v in link_peers.items():
        out.append(f'hvd_tpu_link_shm_handoffs_total{{peer="{r}"}} '
                   f'{v.get("shm_handoffs", 0)}')
    out.append("# HELP hvd_tpu_link_shm_handoff_latency_us time for one "
               "send leg to fully enter the peer's ring (includes any "
               "injected chaos delay)")
    out.append("# TYPE hvd_tpu_link_shm_handoff_latency_us histogram")
    for r, v in link_peers.items():
        buckets = v.get("shm_us_buckets", [])
        cumulative = 0
        for bound, n in zip(LINK_SEND_BUCKETS_US, buckets):
            cumulative += n
            out.append(
                f'hvd_tpu_link_shm_handoff_latency_us_bucket{{peer="{r}",'
                f'le="{_fmt(bound)}"}} {cumulative}')
        out.append(
            f'hvd_tpu_link_shm_handoff_latency_us_bucket{{peer="{r}",'
            f'le="+Inf"}} {v.get("shm_us_count", 0)}')
        out.append(f'hvd_tpu_link_shm_handoff_latency_us_sum{{peer="{r}"}} '
                   f'{v.get("shm_us_sum", 0)}')
        out.append(
            f'hvd_tpu_link_shm_handoff_latency_us_count{{peer="{r}"}} '
            f'{v.get("shm_us_count", 0)}')

    p2p = snapshot.get("p2p", {})
    out.append("# HELP hvd_tpu_p2p_transfers_total point-to-point "
               "transfers executed by direction (docs/pipeline.md)")
    out.append("# TYPE hvd_tpu_p2p_transfers_total counter")
    out.append(f'hvd_tpu_p2p_transfers_total{{dir="send"}} '
               f'{p2p.get("sends", 0)}')
    out.append(f'hvd_tpu_p2p_transfers_total{{dir="recv"}} '
               f'{p2p.get("recvs", 0)}')
    out.append("# HELP hvd_tpu_p2p_bytes_total point-to-point wire bytes "
               "moved by direction (inter-stage activation/grad traffic)")
    out.append("# TYPE hvd_tpu_p2p_bytes_total counter")
    for d, n in p2p.get("bytes", {}).items():
        out.append(f'hvd_tpu_p2p_bytes_total{{dir="{d}"}} {n}')
    out.append("# HELP hvd_tpu_p2p_matched_total send/recv pairs this "
               "rank completed after paired-readiness negotiation")
    out.append("# TYPE hvd_tpu_p2p_matched_total counter")
    out.append(f"hvd_tpu_p2p_matched_total {p2p.get('matched', 0)}")
    out.append("# HELP hvd_tpu_p2p_unmatched enqueued transfers still "
               "waiting for the counterpart rank to announce (a stuck "
               "nonzero value names a pipeline schedule bug)")
    out.append("# TYPE hvd_tpu_p2p_unmatched gauge")
    out.append(f"hvd_tpu_p2p_unmatched {p2p.get('unmatched', 0)}")
    out.append("# HELP hvd_tpu_p2p_group_ops_total stage-scoped "
               "allreduce operations executed (DP inside one stage)")
    out.append("# TYPE hvd_tpu_p2p_group_ops_total counter")
    out.append(f"hvd_tpu_p2p_group_ops_total {p2p.get('group_ops', 0)}")
    out.append("# HELP hvd_tpu_p2p_channels dedicated non-neighbour "
               "p2p connections currently open")
    out.append("# TYPE hvd_tpu_p2p_channels gauge")
    out.append(f"hvd_tpu_p2p_channels {p2p.get('channels', 0)}")

    anomalies = snapshot.get("anomalies", {})
    out.append("# HELP hvd_tpu_anomaly_sigma robust-excursion threshold "
               "of the online anomaly detector (0 = disabled)")
    out.append("# TYPE hvd_tpu_anomaly_sigma gauge")
    out.append(f"hvd_tpu_anomaly_sigma {anomalies.get('sigma', 0)}")
    out.append("# HELP hvd_tpu_anomaly_verdicts_total typed anomaly "
               "verdicts emitted by the online detector "
               "(docs/metrics.md#anomalies)")
    out.append("# TYPE hvd_tpu_anomaly_verdicts_total counter")
    for kind in ANOMALY_KINDS:
        out.append(f'hvd_tpu_anomaly_verdicts_total{{kind="{kind}"}} '
                   f'{anomalies.get("verdicts", {}).get(kind, 0)}')

    state = snapshot.get("state", {})
    out.append("# HELP hvd_tpu_state_armed state plane armed on this "
               "rank (docs/fault-tolerance.md#state-plane)")
    out.append("# TYPE hvd_tpu_state_armed gauge")
    out.append(f"hvd_tpu_state_armed {int(state.get('armed', False))}")
    out.append("# HELP hvd_tpu_state_snapshots_total shard snapshots "
               "committed by the state plane")
    out.append("# TYPE hvd_tpu_state_snapshots_total counter")
    out.append(f"hvd_tpu_state_snapshots_total {state.get('snapshots', 0)}")
    out.append("# HELP hvd_tpu_state_snapshot_bytes_total bytes captured "
               "into committed shard snapshots")
    out.append("# TYPE hvd_tpu_state_snapshot_bytes_total counter")
    out.append("hvd_tpu_state_snapshot_bytes_total "
               f"{state.get('snapshot_bytes', 0)}")
    out.append("# HELP hvd_tpu_state_last_snapshot_step step of the "
               "newest committed shard snapshot (-1 = none)")
    out.append("# TYPE hvd_tpu_state_last_snapshot_step gauge")
    out.append("hvd_tpu_state_last_snapshot_step "
               f"{state.get('last_snapshot_step', -1)}")
    out.append("# HELP hvd_tpu_state_overlap_ratio fraction of snapshot "
               "work overlapped with compute (1.0 = fully async)")
    out.append("# TYPE hvd_tpu_state_overlap_ratio gauge")
    out.append("hvd_tpu_state_overlap_ratio "
               f"{repr(float(state.get('overlap_ratio', 1.0)))}")
    out.append("# HELP hvd_tpu_state_peer_copies_total peer-mirror shard "
               "copies moved over the state plane")
    out.append("# TYPE hvd_tpu_state_peer_copies_total counter")
    out.append('hvd_tpu_state_peer_copies_total{direction="sent"} '
               f"{state.get('peer_copies_sent', 0)}")
    out.append('hvd_tpu_state_peer_copies_total{direction="received"} '
               f"{state.get('peer_copies_received', 0)}")
    out.append("# HELP hvd_tpu_state_peer_last_step step of the newest "
               "fully received peer copy (freshness; -1 = none)")
    out.append("# TYPE hvd_tpu_state_peer_last_step gauge")
    out.append("hvd_tpu_state_peer_last_step "
               f"{state.get('peer_last_step', -1)}")
    out.append("# HELP hvd_tpu_state_restores_total elastic resyncs by "
               "source (peer / local snapshots / root-broadcast fallback)")
    out.append("# TYPE hvd_tpu_state_restores_total counter")
    out.append('hvd_tpu_state_restores_total{source="peer"} '
               f"{state.get('peer_restores', 0)}")
    local_restores = max(state.get("restores", 0)
                         - state.get("peer_restores", 0), 0)
    out.append('hvd_tpu_state_restores_total{source="local"} '
               f"{local_restores}")
    out.append('hvd_tpu_state_restores_total{source="root_broadcast"} '
               f"{state.get('root_broadcast_fallbacks', 0)}")
    out.append("# HELP hvd_tpu_state_checkpoint_events_total durable "
               "checkpoint lifecycle (sharded/legacy saves, loads, prunes)")
    out.append("# TYPE hvd_tpu_state_checkpoint_events_total counter")
    for event in STATE_CKPT_EVENTS:
        out.append(f'hvd_tpu_state_checkpoint_events_total{{event='
                   f'"{event}"}} {state.get("ckpt", {}).get(event, 0)}')
    out.append("# HELP hvd_tpu_state_checkpoint_shard_bytes_total bytes "
               "this rank wrote into checkpoint shards")
    out.append("# TYPE hvd_tpu_state_checkpoint_shard_bytes_total counter")
    out.append("hvd_tpu_state_checkpoint_shard_bytes_total "
               f"{state.get('ckpt', {}).get('shard_bytes', 0)}")

    skew = snapshot.get("skew", {})
    out.append("# HELP hvd_tpu_announce_total "
               "negotiations reaching full count (coordinator view)")
    out.append("# TYPE hvd_tpu_announce_total counter")
    out.append(f"hvd_tpu_announce_total {skew.get('count', 0)}")
    out.append("# HELP hvd_tpu_last_to_announce_total "
               "negotiations this rank announced last (straggler "
               "attribution, coordinator view)")
    out.append("# TYPE hvd_tpu_last_to_announce_total counter")
    for rank, n in skew.get("last_to_announce", {}).items():
        out.append(f'hvd_tpu_last_to_announce_total{{rank='
                   f'"{_label_escape(rank)}"}} {n}')

    for name, hist in snapshot["histograms"].items():
        prom = _prom_hist_name(name)
        out.append(f"# HELP {prom} {HISTOGRAMS[name][1]}")
        out.append(f"# TYPE {prom} histogram")
        cumulative = 0
        for bound, n in zip(hist["buckets"], hist["counts"]):
            cumulative += n
            out.append(f'{prom}_bucket{{le="{_fmt(bound)}"}} {cumulative}')
        out.append(f'{prom}_bucket{{le="+Inf"}} {hist["count"]}')
        out.append(f"{prom}_sum {repr(float(hist['sum']))}")
        out.append(f"{prom}_count {hist['count']}")

    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# Job-level aggregation (docs/metrics.md#cluster): rank 0's monitor serves
# /cluster — one merged health view of every live rank — so a single scrape
# target covers the fleet.  Each rank's monitor serves the compact /health
# summary the aggregation is built from.
# ---------------------------------------------------------------------------

_monitor_lock = threading.Lock()
_monitor = None  # (server, bound_port)
# /cluster scrape targets [(rank, host, port)], set on rank 0 by
# configure_cluster at init.  Torn down by stop_monitor (and thus re-init
# and hvdrun relaunches) so elastic reshapes / --max-restarts cannot serve
# stale per-rank entries (the PR-6 cache-clear discipline).
_cluster_cfg = None


def configure_cluster(targets) -> None:
    """Arm rank 0's /cluster aggregation with the per-rank monitor
    endpoints ([(rank, host, port)]; rank 0's own entry included)."""
    global _cluster_cfg
    with _monitor_lock:
        _cluster_cfg = list(targets)


def cluster_configured() -> bool:
    with _monitor_lock:
        return _cluster_cfg is not None


def health_summary(snap: dict) -> dict:
    """The compact per-rank health record /cluster merges: liveness,
    membership epoch, cache hit rate, stall/abort counts, serving
    occupancy, flight-recorder activity."""
    member = snap.get("membership", {})
    # Both planes' negotiation caches count (an XLA-plane job records its
    # hits under "xla"; engine-only would read 0.0 there).
    hits = sum(c.get("hits", 0) for c in snap.get("cache", {}).values())
    misses = sum(c.get("misses", 0)
                 for c in snap.get("cache", {}).values())
    serving = snap.get("serving", {})
    links = snap.get("links", {})
    anomalies = snap.get("anomalies", {})
    return {
        "live": True,
        "membership_epoch": member.get("epoch", 0),
        "size": member.get("size", 0),
        "restart_epoch": snap.get("faults", {}).get("restart_epoch", 0),
        "stalls": snap.get("stalls", {}).get("count", 0),
        "aborts": sum(snap.get("faults", {}).get("aborts", {}).values()),
        "cache_hit_rate": (hits / (hits + misses)
                           if hits + misses else 0.0),
        "serving_occupancy": serving.get("occupancy", 0.0),
        "serving_active": serving.get("active", 0),
        "flight_events": sum(
            snap.get("flight", {}).get("events", {}).values()),
        # Compact per-link heat record (one row per peer this rank talks
        # to) — what hvdtop's link table renders.  send_mean_us covers
        # timed whole-frame sends; rtt_ewma_us is -1 until the first
        # heartbeat echo lands.
        "links": {
            str(r): {
                "send_mean_us": (v.get("send_us_sum", 0)
                                 // max(v.get("send_us_count", 0), 1)
                                 if v.get("send_us_count", 0) else -1),
                "rtt_ewma_us": (v.get("rtt_ewma_us", 0)
                                if v.get("rtt_samples", 0) else -1),
                "stalls": (v.get("stalls", 0)
                           + v.get("short_writes", 0)),
                "bytes": (v.get("bytes_out", 0) + v.get("bytes_in", 0)
                          + v.get("shm_bytes_out", 0)
                          + v.get("shm_bytes_in", 0)),
                "transport": v.get("transport", "tcp"),
                "shm_handoff_mean_us": (
                    v.get("shm_us_sum", 0)
                    // max(v.get("shm_us_count", 0), 1)
                    if v.get("shm_us_count", 0) else -1),
            }
            for r, v in links.get("peers", {}).items()
        },
        # Typed anomaly verdicts (docs/metrics.md#anomalies): cumulative
        # counts plus the tail of the verdict log, so /cluster can merge
        # a job-wide anomaly feed.
        "anomalies": {
            "verdicts": dict(anomalies.get("verdicts", {})),
            "log": [dict(e) for e in anomalies.get("log", [])[-8:]],
        },
    }


def _scrape_health(host: str, port: int, timeout: float = 1.0) -> dict:
    import urllib.request

    try:
        with urllib.request.urlopen(
                f"http://{host}:{port}/health", timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except Exception as exc:
        return {"live": False, "error": f"{type(exc).__name__}: {exc}"}


def cluster_document(snapshot_fn: Callable[[], dict]) -> dict:
    """Scrape every rank's /health (rank 0's own summary is computed
    locally — no loopback HTTP round trip) and merge one job view."""
    with _monitor_lock:
        targets = list(_cluster_cfg or [])
    ranks: Dict[str, dict] = {}
    threads = []

    def scrape(rank, host, port):
        ranks[str(rank)] = _scrape_health(host, port)

    own_rank = targets[0][0] if targets else 0
    for rank, host, port in targets:
        if rank == own_rank:
            ranks[str(rank)] = health_summary(snapshot_fn())
            continue
        # Pre-claim the entry as dead: a scrape thread that outlives the
        # join below (e.g. DNS resolution blocking past urllib's timeout)
        # must leave the rank visible as live:false, not silently missing
        # — liveness is the point, a dead rank must not hide.
        ranks[str(rank)] = {"live": False,
                            "error": "scrape did not respond in time"}
        t = threading.Thread(target=scrape, args=(rank, host, port),
                             daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=2.0)
    live = [r for r in ranks.values() if r.get("live")]
    epochs = {r.get("membership_epoch") for r in live}
    # Job-wide anomaly rollup: total verdicts per kind plus a merged,
    # rank-attributed tail of every rank's verdict log (newest-by-age
    # first) — the scrolling feed hvdtop renders.
    verdict_totals: Dict[str, int] = {}
    feed = []
    for rank, entry in ranks.items():
        anomalies = entry.get("anomalies", {}) or {}
        for kind, n in anomalies.get("verdicts", {}).items():
            verdict_totals[kind] = verdict_totals.get(kind, 0) + int(n)
        for e in anomalies.get("log", []):
            feed.append({"rank": rank, **e})
    feed.sort(key=lambda e: e.get("age_us", 0))
    return {
        "ranks": ranks,
        "launched": len(targets),
        "live": len(live),
        "membership_epochs_agree": len(epochs) <= 1,
        "anomalies": {
            "total": sum(verdict_totals.values()),
            "verdicts": verdict_totals,
            "recent": feed[:32],
        },
    }


def cluster_prometheus_text(doc: dict) -> str:
    """Prometheus form of the merged /cluster document, so one scrape
    target covers the fleet's liveness and epoch agreement."""
    out: List[str] = []
    out.append("# HELP hvd_tpu_cluster_rank_up rank responded to the "
               "cluster health scrape")
    out.append("# TYPE hvd_tpu_cluster_rank_up gauge")
    for rank, entry in sorted(doc["ranks"].items(), key=lambda kv: kv[0]):
        out.append(f'hvd_tpu_cluster_rank_up{{rank="{rank}"}} '
                   f'{1 if entry.get("live") else 0}')
    out.append("# HELP hvd_tpu_cluster_rank_membership_epoch per-rank "
               "elastic membership epoch")
    out.append("# TYPE hvd_tpu_cluster_rank_membership_epoch gauge")
    for rank, entry in sorted(doc["ranks"].items(), key=lambda kv: kv[0]):
        if entry.get("live"):
            out.append(
                f'hvd_tpu_cluster_rank_membership_epoch{{rank="{rank}"}} '
                f'{entry.get("membership_epoch", 0)}')
    out.append("# HELP hvd_tpu_cluster_ranks_live ranks responding to the "
               "cluster health scrape")
    out.append("# TYPE hvd_tpu_cluster_ranks_live gauge")
    out.append(f"hvd_tpu_cluster_ranks_live {doc['live']}")
    return "\n".join(out) + "\n"


def start_monitor(port: int,
                  snapshot_fn: Optional[Callable[[], dict]] = None,
                  host: str = "") -> int:
    """Serve ``/metrics`` (Prometheus text) and ``/metrics.json`` from a
    daemon thread; returns the bound port (useful with ``port=0``).
    Idempotent: a second call returns the running monitor's port.
    Starting the monitor enables the registry — a scrape target with all
    counters frozen at zero would be worse than no target."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    global _monitor
    with _monitor_lock:
        if _monitor is not None:
            return _monitor[1]
        fn = snapshot_fn or registry.snapshot

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                path = self.path.split("?")[0]
                if path == "/metrics":
                    body = prometheus_text(fn()).encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/metrics.json":
                    body = json.dumps(fn()).encode()
                    ctype = "application/json"
                elif path == "/health":
                    # Compact per-rank summary, the /cluster scrape unit.
                    body = json.dumps(health_summary(fn())).encode()
                    ctype = "application/json"
                elif path in ("/cluster", "/cluster.prom") \
                        and cluster_configured():
                    doc = cluster_document(fn)
                    if path == "/cluster":
                        body = json.dumps(doc).encode()
                        ctype = "application/json"
                    else:
                        body = cluster_prometheus_text(doc).encode()
                        ctype = "text/plain; version=0.0.4; charset=utf-8"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # keep scrapes off stderr
                pass

        server = ThreadingHTTPServer((host, port), Handler)
        server.daemon_threads = True
        thread = threading.Thread(target=server.serve_forever,
                                  name="hvd-tpu-monitor", daemon=True)
        thread.start()
        registry.enable()
        _monitor = (server, server.server_address[1])
        return _monitor[1]


def stop_monitor() -> None:
    global _monitor, _cluster_cfg
    with _monitor_lock:
        # The /cluster aggregation dies with the monitor: a re-init (or
        # an hvdrun --max-restarts relaunch) reconfigures fresh targets,
        # so stale per-rank entries from a previous membership cannot be
        # served (the PR-6 cache-clear discipline).
        _cluster_cfg = None
        if _monitor is None:
            return
        server, _ = _monitor
        _monitor = None
    server.shutdown()
    server.server_close()


def monitor_port() -> Optional[int]:
    with _monitor_lock:
        return _monitor[1] if _monitor else None
