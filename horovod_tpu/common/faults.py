"""Deterministic fault injection at collective boundaries.

Synchronous data parallelism means every fault-tolerance path — liveness
detection, coordinated abort, structured error propagation, job restart —
only triggers when a rank actually dies or wedges mid-job.  Real crashes
are not reproducible on demand, so this module makes them so:
``HVD_TPU_FAULT_SPEC`` describes exactly which rank misbehaves, how, and
at which collective, and the injector fires at the moment that collective
would be submitted, on both data planes (the hook lives in the shared
``common.*_async`` entry points the XLA plane is dispatched from).

Spec grammar (clauses separated by ``;`` or ``,``)::

    rank=<r>:<action>@op=<n>[@epoch=<e>]

    rank=1:crash@op=12          # rank 1 exits hard (no shutdown handshake)
                                # instead of submitting its 12th collective
    rank=2:hang@op=5            # rank 2's Python wedges forever at its 5th
                                # (engine thread keeps ticking)
    rank=1:delay=3.0@op=7       # rank 1 sleeps 3s, then proceeds
    rank=2:freeze@op=5          # SIGSTOPs the whole process: engine thread
                                # included, so sockets stay open but go
                                # silent (the liveness-probe case)

``op`` counts the rank's submitted collectives from 0, in program order
(allreduce/allgather/broadcast alike; the XLA plane's internal ``__xp.*``
metadata ops are not counted).  A clause without an explicit ``epoch=``
fires only on the FIRST run (``HVD_TPU_RESTART_EPOCH`` 0), so a job under
``hvdrun --max-restarts`` crashes once, restarts, and trains through —
the end-to-end restart contract tier-1 tests exercise on CPU.

Every firing is recorded in the metrics registry
(``hvd.metrics_snapshot()["faults"]["injected"]``), ungated like stall
records: fault runs are tests by construction and must be assertable
without opting into full metrics.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time
from typing import List, Optional

from horovod_tpu.common import metrics

_ACTIONS = ("crash", "hang", "delay", "freeze")

# Exit code for an injected crash: distinctive in launcher reports, and
# outside the shell's 126/127/128+sig conventions.
CRASH_EXIT_CODE = 43


@dataclasses.dataclass(frozen=True)
class Fault:
    rank: int
    action: str  # "crash" | "hang" | "delay"
    op: int      # 0-based index of the rank's submitted collectives
    delay_sec: float = 0.0
    epoch: int = 0  # HVD_TPU_RESTART_EPOCH this clause fires on


def parse_spec(spec: str) -> List[Fault]:
    """Parse ``HVD_TPU_FAULT_SPEC``; raises ValueError with the offending
    clause on any syntax error (a silently ignored fault spec would make a
    red test green)."""
    faults: List[Fault] = []
    for raw in spec.replace(",", ";").split(";"):
        clause = raw.strip()
        if not clause:
            continue
        try:
            head, _, tail = clause.partition(":")
            key, _, rank_s = head.partition("=")
            if key.strip() != "rank":
                raise ValueError("expected 'rank=<r>:'")
            rank = int(rank_s)
            parts = tail.split("@")
            action_part = parts[0].strip()
            action, _, delay_s = action_part.partition("=")
            action = action.strip()
            if action not in _ACTIONS:
                raise ValueError(f"unknown action '{action}'")
            delay = float(delay_s) if delay_s else 0.0
            if action == "delay" and not delay_s:
                raise ValueError("delay needs a duration: delay=<sec>")
            op: Optional[int] = None
            epoch = 0
            for term in parts[1:]:
                tkey, _, tval = term.partition("=")
                tkey = tkey.strip()
                if tkey == "op":
                    op = int(tval)
                elif tkey == "epoch":
                    epoch = int(tval)
                else:
                    raise ValueError(f"unknown term '@{tkey}'")
            if op is None:
                raise ValueError("missing '@op=<n>'")
            faults.append(Fault(rank=rank, action=action, op=op,
                                delay_sec=delay, epoch=epoch))
        except ValueError as exc:
            raise ValueError(
                f"bad HVD_TPU_FAULT_SPEC clause '{clause}': {exc}") from None
    return faults


class FaultInjector:
    """The active faults for ONE (rank, restart epoch), keyed by op index.

    ``on_collective`` is called from the shared collective entry points
    with the submission index; it either returns immediately (no fault, a
    plain dict lookup) or fires.  Not thread-safe by design: the op
    counter it is driven by is already serialized by the caller.
    """

    def __init__(self, faults: List[Fault], rank: int, epoch: int):
        self._by_op = {f.op: f for f in faults
                       if f.rank == rank and f.epoch == epoch}
        self._rank = rank

    def __bool__(self) -> bool:
        return bool(self._by_op)

    def on_collective(self, op_index: int, name: str) -> None:
        fault = self._by_op.get(op_index)
        if fault is None:
            return
        metrics.registry.record_fault(fault.action)
        print(f"[horovod_tpu] FAULT INJECTION: rank {self._rank} "
              f"{fault.action} at op {op_index} ('{name}')",
              file=sys.stderr, flush=True)
        if fault.action == "crash":
            # Flush this rank's timeline before dying: an injected crash
            # is a reproducible test crash, and the post-mortem trace
            # contract (docs/timeline.md) says the file must still parse.
            # Real SIGKILLs rely on the engine's abort-path flush instead.
            try:
                from horovod_tpu import common as _common

                if _common._lib is not None:
                    _common._lib.hvd_tpu_timeline_flush()
            except Exception:
                pass
            # And the postmortem dump (docs/troubleshooting.md#reading-a-
            # postmortem): the crashing rank leaves its own flight ring
            # and pending table, not just the survivors' view of it.
            try:
                from horovod_tpu.common import postmortem as _postmortem

                _postmortem.write_postmortem("fault_crash")
            except Exception:
                pass
            # os._exit skips atexit, so the HVD_TPU_METRICS_FILE dump
            # must flush here too (write_postmortem only covers it when a
            # postmortem dir is set) — crashed ranks leave metrics.
            try:
                from horovod_tpu import common as _common

                _common._flush_metrics_file(clear=False)
            except Exception:
                pass
            # Hard death: no shutdown handshake, sockets drop — the
            # coordinator sees EOF, exactly like a SIGKILL'd rank.
            os._exit(CRASH_EXIT_CODE)
        elif fault.action == "freeze":
            # Whole-process stop (engine thread too): sockets stay open
            # but fall silent — detectable only by the coordinator's
            # control-plane liveness probe, never by EOF.
            import signal

            os.kill(os.getpid(), signal.SIGSTOP)
        elif fault.action == "hang":
            # Wedge this thread forever (the engine's background thread
            # keeps ticking, so liveness looks healthy — only the stall /
            # collective-timeout path can catch this, by design).
            while True:
                time.sleep(3600.0)
        else:  # delay
            time.sleep(fault.delay_sec)


def from_env(rank: int) -> Optional[FaultInjector]:
    """Build the injector for this rank from the environment; None when no
    clause applies (the hot path then pays a single `is not None`)."""
    from horovod_tpu.common.config import Config

    cfg = Config.from_env()
    if not cfg.fault_spec:
        return None
    injector = FaultInjector(parse_spec(cfg.fault_spec), rank,
                             cfg.restart_epoch)
    return injector if injector else None
