"""Process-model bootstrap: rank/size/local_rank resolution for TPU pod slices.

TPU-native counterpart of the reference's MPI process model
(/root/reference/horovod/common/operations.cc:1299-1428, where rank/size come
from MPI_COMM_WORLD).  Here they resolve, in priority order, from:

  1. Explicit arguments to :func:`resolve_process_set`.
  2. ``HVD_TPU_RANK`` / ``HVD_TPU_SIZE`` / ``HVD_TPU_LOCAL_RANK`` /
     ``HVD_TPU_LOCAL_SIZE`` — set by the ``hvdrun`` launcher
     (the mpirun replacement, see ``horovod_tpu/runner``).
  3. libtpu multi-process pinning env (``CLOUD_TPU_TASK_ID`` +
     ``TPU_PROCESS_ADDRESSES``) — one process per chip, local geometry
     from grouping the address list by host.
  4. TPU pod-slice metadata environment (``TPU_WORKER_ID`` +
     ``TPU_WORKER_HOSTNAMES``, or Cloud TPU ``CLOUD_TPU_TASK_ID``);
     one process per host by default, N per host when the process manager
     also exports ``HVD_TPU_LOCAL_RANK``/``HVD_TPU_LOCAL_SIZE``.
  5. An already-initialised JAX distributed runtime
     (``jax.process_index()`` / ``jax.process_count()``).
  6. Single-process defaults (rank 0 of 1).

No MPI anywhere.  The launcher also provides the control/data-plane endpoints
(``HVD_TPU_COORD``, ``HVD_TPU_DATA``) consumed by the C++ engine.
"""

from __future__ import annotations

import dataclasses
import os
import socket
from typing import Optional, Sequence


@dataclasses.dataclass(frozen=True)
class ProcessSet:
    """Resolved identity of this process within the job."""

    rank: int
    size: int
    local_rank: int
    local_size: int
    # Control-plane (rank-0 coordinator) endpoint, "host:port".
    coord_endpoint: Optional[str] = None
    # Data-plane endpoints for every rank, ["host:port", ...] (len == size).
    data_endpoints: Optional[Sequence[str]] = None

    def validate(self) -> "ProcessSet":
        if not (0 <= self.rank < self.size):
            raise ValueError(
                f"rank {self.rank} out of range for size {self.size}")
        if not (0 <= self.local_rank < self.local_size):
            raise ValueError(
                f"local_rank {self.local_rank} out of range for "
                f"local_size {self.local_size}")
        if self.size > 1:
            if not self.coord_endpoint:
                raise ValueError(
                    "size > 1 requires a coordinator endpoint "
                    "(set HVD_TPU_COORD or launch via hvdrun)")
            if not self.data_endpoints or len(self.data_endpoints) != self.size:
                raise ValueError(
                    "size > 1 requires one data endpoint per rank "
                    "(set HVD_TPU_DATA or launch via hvdrun)")
        return self


def _env_int(name: str, default: Optional[int] = None) -> Optional[int]:
    val = os.environ.get(name)
    if val is None or val == "":
        return default
    try:
        return int(val)
    except ValueError as exc:
        raise ValueError(f"environment variable {name}={val!r} is not an int") from exc


def _from_launcher_env() -> Optional[ProcessSet]:
    rank = _env_int("HVD_TPU_RANK")
    size = _env_int("HVD_TPU_SIZE")
    if rank is None or size is None:
        return None
    local_rank = _env_int("HVD_TPU_LOCAL_RANK", rank)
    local_size = _env_int("HVD_TPU_LOCAL_SIZE", size)
    coord = os.environ.get("HVD_TPU_COORD")
    data = os.environ.get("HVD_TPU_DATA")
    endpoints = data.split(",") if data else None
    return ProcessSet(rank, size, local_rank, local_size, coord, endpoints)


def _from_tpu_pinned_metadata() -> Optional[ProcessSet]:
    """Resolve from the libtpu multi-process pinning env (one process per
    chip: ``CLOUD_TPU_TASK_ID`` + ``TPU_PROCESS_ADDRESSES``, as set by the
    ``hvdrun --tpu-pin`` planner or a GKE-style process manager).  Local
    geometry comes from grouping the address list by host."""
    task_id = _env_int("CLOUD_TPU_TASK_ID")
    addresses = os.environ.get("TPU_PROCESS_ADDRESSES")
    if task_id is None or not addresses:
        return None
    addrs = [a.strip() for a in addresses.split(",") if a.strip()]
    size = len(addrs)
    if size <= 1:
        return ProcessSet(0, 1, 0, 1)
    hosts = [a.rsplit(":", 1)[0] for a in addrs]
    peers = [i for i, h in enumerate(hosts) if h == hosts[task_id]]
    coord_port = _env_int("HVD_TPU_COORD_PORT", 58930)
    data_port = _env_int("HVD_TPU_DATA_PORT", 58931)
    coord = f"{hosts[0]}:{coord_port}"
    # Per-rank data ports offset by local rank so co-hosted ranks don't
    # collide (the hvdrun planner uses the same layout, runner/hosts.py).
    local_ranks = {}
    seen: dict = {}
    for i, h in enumerate(hosts):
        local_ranks[i] = seen.get(h, 0)
        seen[h] = local_ranks[i] + 1
    endpoints = [f"{h}:{data_port + local_ranks[i]}"
                 for i, h in enumerate(hosts)]
    return ProcessSet(task_id, size, peers.index(task_id), len(peers),
                      coord, endpoints)


def _from_tpu_metadata() -> Optional[ProcessSet]:
    """Resolve from Cloud TPU pod-slice metadata env.  Default: one process
    per host (the classic Cloud TPU layout).  With N processes per host
    (chip pinning), the process manager additionally exports
    ``HVD_TPU_LOCAL_RANK``/``HVD_TPU_LOCAL_SIZE`` and the global identity
    is host-major: rank = worker_id * local_size + local_rank."""
    worker_id = _env_int("TPU_WORKER_ID", _env_int("CLOUD_TPU_TASK_ID"))
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES")
    if worker_id is None or not hostnames:
        return None
    hosts = [h.strip() for h in hostnames.split(",") if h.strip()]
    local_rank = _env_int("HVD_TPU_LOCAL_RANK", 0)
    local_size = _env_int("HVD_TPU_LOCAL_SIZE", 1)
    size = len(hosts) * local_size
    if size <= 1:
        return ProcessSet(0, 1, 0, 1)
    coord_port = _env_int("HVD_TPU_COORD_PORT", 58930)
    data_port = _env_int("HVD_TPU_DATA_PORT", 58931)
    coord = f"{hosts[0]}:{coord_port}"
    endpoints = [f"{h}:{data_port + lr}"
                 for h in hosts for lr in range(local_size)]
    return ProcessSet(worker_id * local_size + local_rank, size,
                      local_rank, local_size, coord, endpoints)


def _from_jax_distributed() -> Optional[ProcessSet]:
    try:
        import jax  # local import: keep basics importable without jax

        # Only meaningful when the distributed runtime was initialised.
        from jax._src import distributed  # type: ignore

        if distributed.global_state.client is None:
            return None
        return ProcessSet(
            jax.process_index(), jax.process_count(),
            _env_int("HVD_TPU_LOCAL_RANK", 0),
            _env_int("HVD_TPU_LOCAL_SIZE", 1))
    except Exception:  # pragma: no cover - jax absent or internal change
        return None


def comm_ranks(comm, launcher_rank: int) -> list:
    """Map an mpi4py-style communicator to the launcher-rank subset the
    rank-list init path consumes.

    The reference accepts either a rank list or an mpi4py communicator in
    ``hvd.init(comm=...)`` (/root/reference/horovod/common/__init__.py:
    51-78, where the C side marshals the raw ``MPI_Comm``).  There is no
    MPI anywhere in this framework, so the shim is duck-typed instead of
    importing mpi4py: any object with ``Get_size`` and a pickle-based
    ``allgather`` works — each member contributes its own launcher rank
    and the gathered list IS the subset, with no world-group rank
    translation needed.  The list keeps the communicator's own rank
    order (allgather returns in comm-rank order), and
    :func:`resolve_process_set` numbers the subset by list position —
    so ``hvd.rank() == comm.Get_rank()`` even for reordered
    subcommunicators (root-only logic stays on the comm's root).
    """
    ranks = list(comm.allgather(launcher_rank))
    if len(ranks) != comm.Get_size():
        raise ValueError(
            f"communicator allgather returned {len(ranks)} ranks but "
            f"Get_size() says {comm.Get_size()}")
    return ranks


def resolve_process_set(ranks: Optional[Sequence[int]] = None) -> ProcessSet:
    """Resolve this process's identity.

    ``ranks`` mirrors the reference's ``hvd.init(comm=[...])`` rank-subset
    argument (/root/reference/horovod/common/__init__.py:51-78): when given,
    it must contain this process's launcher rank, and rank/size are re-mapped
    to the subset.
    """
    ps = (_from_launcher_env() or _from_tpu_pinned_metadata()
          or _from_tpu_metadata() or _from_jax_distributed()
          or ProcessSet(0, 1, 0, 1))
    if ranks is not None:
        ranks = list(ranks)
        if len(set(ranks)) != len(ranks):
            raise ValueError(f"duplicate ranks in subset {ranks}")
        if ps.rank not in ranks:
            raise ValueError(
                f"process rank {ps.rank} not in requested subset {ranks}")
        # LIST ORDER defines the new numbering — matching MPI Group.Incl
        # semantics, which is what the reference's comm forms resolve to:
        # subset rank i is launcher rank ranks[i], so a reordered
        # mpi4py subcommunicator keeps hvd.rank() == comm.Get_rank()
        # (root-only logic stays on the comm's root).
        new_rank = ranks.index(ps.rank)
        endpoints = None
        if ps.data_endpoints:
            endpoints = [ps.data_endpoints[r] for r in ranks]
        coord = None
        if endpoints:
            host = endpoints[0].rsplit(":", 1)[0]
            # Derive a subset coordinator endpoint from rank-0-of-subset's
            # data host with the configured coordinator port.
            port = _env_int("HVD_TPU_COORD_PORT")
            if port is None and ps.coord_endpoint:
                port = int(ps.coord_endpoint.rsplit(":", 1)[1])
            coord = f"{host}:{port}" if port else ps.coord_endpoint
        # Node-locality must be re-derived for the subset.  The data
        # endpoints carry each subset rank's host, so group by host and index
        # within the group; without endpoints (single-host jobs) the subset
        # rank itself is the local rank.
        if endpoints:
            hosts = [e.rsplit(":", 1)[0] for e in endpoints]
            my_host = hosts[new_rank]
            peers = [i for i, h in enumerate(hosts) if h == my_host]
            local_rank = peers.index(new_rank)
            local_size = len(peers)
        else:
            local_rank, local_size = new_rank, len(ranks)
        ps = ProcessSet(new_rank, len(ranks), local_rank, local_size,
                        coord, endpoints)
    return ps.validate()


def pick_free_port(host: str = "127.0.0.1") -> int:
    """Ask the OS for a currently-free TCP port (used by tests/launcher)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def pick_free_ports(n: int, host: str = "127.0.0.1") -> list:
    """``n`` distinct currently-free TCP ports, all sockets held open
    until every port is picked.  Sequential :func:`pick_free_port` calls
    release each socket before the next bind, so the OS may hand the
    same port out twice within one launch — a rank then dies with
    EADDRINUSE (the bind/listen flake the suite used to see under
    port-churn load)."""
    socks = []
    try:
        for _ in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.bind((host, 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()
