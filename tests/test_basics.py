"""Process-model and single-process API tests.

Mirrors the reference's test_common.py (env-truth rank/size checks,
uninitialized errors; /root/reference/test/test_common.py:26-58)."""

import os

import numpy as np
import pytest

from horovod_tpu.common.basics import ProcessSet, resolve_process_set


def test_single_process_defaults(monkeypatch):
    for var in ("HVD_TPU_RANK", "HVD_TPU_SIZE", "TPU_WORKER_ID",
                "TPU_WORKER_HOSTNAMES", "CLOUD_TPU_TASK_ID"):
        monkeypatch.delenv(var, raising=False)
    ps = resolve_process_set()
    assert (ps.rank, ps.size, ps.local_rank, ps.local_size) == (0, 1, 0, 1)


def test_launcher_env_resolution(monkeypatch):
    monkeypatch.setenv("HVD_TPU_RANK", "2")
    monkeypatch.setenv("HVD_TPU_SIZE", "4")
    monkeypatch.setenv("HVD_TPU_LOCAL_RANK", "0")
    monkeypatch.setenv("HVD_TPU_LOCAL_SIZE", "1")
    monkeypatch.setenv("HVD_TPU_COORD", "10.0.0.1:1234")
    monkeypatch.setenv("HVD_TPU_DATA",
                       "10.0.0.1:70,10.0.0.2:70,10.0.0.3:70,10.0.0.4:70")
    ps = resolve_process_set()
    assert ps.rank == 2 and ps.size == 4
    assert ps.local_rank == 0 and ps.local_size == 1
    assert ps.coord_endpoint == "10.0.0.1:1234"
    assert len(ps.data_endpoints) == 4


def test_tpu_pod_metadata_resolution(monkeypatch):
    monkeypatch.delenv("HVD_TPU_RANK", raising=False)
    monkeypatch.delenv("HVD_TPU_SIZE", raising=False)
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host0,host1,host2")
    ps = resolve_process_set()
    assert ps.rank == 1 and ps.size == 3
    assert ps.local_rank == 0 and ps.local_size == 1
    assert ps.coord_endpoint.startswith("host0:")
    assert [e.rsplit(":", 1)[0] for e in ps.data_endpoints] == [
        "host0", "host1", "host2"]


def test_rank_subset(monkeypatch):
    monkeypatch.setenv("HVD_TPU_RANK", "3")
    monkeypatch.setenv("HVD_TPU_SIZE", "4")
    monkeypatch.setenv("HVD_TPU_COORD", "h0:1")
    monkeypatch.setenv("HVD_TPU_DATA", "h0:2,h1:2,h2:2,h3:2")
    ps = resolve_process_set(ranks=[1, 3])
    assert ps.rank == 1 and ps.size == 2
    assert list(ps.data_endpoints) == ["h1:2", "h3:2"]
    # List order defines the numbering (MPI Group.Incl semantics): a
    # reordered subset makes this launcher rank the subset ROOT.
    ps = resolve_process_set(ranks=[3, 1])
    assert ps.rank == 0 and ps.size == 2
    assert list(ps.data_endpoints) == ["h3:2", "h1:2"]
    assert ps.coord_endpoint.startswith("h3:")
    with pytest.raises(ValueError):
        resolve_process_set(ranks=[0, 2])  # our rank not in subset


def test_invalid_process_set():
    with pytest.raises(ValueError):
        ProcessSet(rank=2, size=2, local_rank=0, local_size=1).validate()
    with pytest.raises(ValueError):
        ProcessSet(rank=0, size=2, local_rank=0, local_size=1).validate()


def test_uninitialized_raises():
    import horovod_tpu as hvd

    if hvd.is_initialized():
        pytest.skip("engine already initialized in this process")
    with pytest.raises(ValueError):
        hvd.rank()
    with pytest.raises(ValueError):
        hvd.size()


def test_single_process_collectives(single_process_hvd):
    hvd = single_process_hvd
    assert hvd.rank() == 0
    assert hvd.size() == 1
    assert hvd.local_rank() == 0
    assert hvd.local_size() == 1
    assert hvd.mpi_threads_supported()

    x = np.random.randn(4, 5).astype(np.float32)
    assert np.array_equal(hvd.allreduce(x, average=False, name="t0"), x)
    assert np.array_equal(hvd.allreduce(x, average=True, name="t1"), x)
    assert np.array_equal(hvd.allgather(x, name="t2"), x)
    assert np.array_equal(hvd.broadcast(x, root_rank=0, name="t3"), x)


def test_duplicate_name_rejected(monkeypatch):
    import horovod_tpu as hvd

    for var in ("HVD_TPU_RANK", "HVD_TPU_SIZE", "HVD_TPU_COORD",
                "HVD_TPU_DATA"):
        monkeypatch.delenv(var, raising=False)
    # Slow the engine cycle so both enqueues land in the same tick window.
    monkeypatch.setenv("HVD_TPU_CYCLE_TIME", "100")
    hvd.init()
    try:
        x = np.zeros(1000, np.float32)
        h1 = hvd.allreduce_async(x, name="dup")
        h2 = hvd.allreduce_async(x, name="dup")
        outcomes = []
        for h in (h1, h2):
            try:
                h.wait()
                outcomes.append("ok")
            except ValueError:
                outcomes.append("dup")
        # The second enqueue must be rejected while the first is pending.
        assert outcomes == ["ok", "dup"], outcomes
    finally:
        hvd.shutdown()


def test_config_env(monkeypatch):
    from horovod_tpu.common.config import Config

    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "1024")
    monkeypatch.setenv("HVD_TPU_CYCLE_TIME", "2.5")
    monkeypatch.setenv("HOROVOD_TIMELINE", "/tmp/tl.json")
    cfg = Config.from_env()
    assert cfg.fusion_threshold == 1024
    assert cfg.cycle_time_ms == 2.5
    assert cfg.timeline_path == "/tmp/tl.json"


def test_comm_ranks_shim():
    """comm_ranks maps an mpi4py-style communicator to the rank-subset
    form (duck-typed allgather of launcher ranks; reference
    /root/reference/horovod/common/__init__.py:51-78)."""
    import pytest

    from horovod_tpu.common.basics import comm_ranks

    class Comm:
        def __init__(self, members, size=None):
            self._members, self._size = members, size or len(members)

        def Get_size(self):
            return self._size

        def allgather(self, value):
            assert value in self._members
            return list(self._members)

    assert comm_ranks(Comm([0, 2]), 2) == [0, 2]
    assert comm_ranks(Comm([3, 1, 5]), 1) == [3, 1, 5]
    with pytest.raises(ValueError):
        comm_ranks(Comm([0, 2], size=3), 0)  # gather/size mismatch
