"""Timeline tests (docs/timeline.md): Chrome-trace structural validation
for both data planes, per-rank trace files with clock-sync metadata, the
span API (``hvd.trace_span`` / ``hvd.trace_marker``), the
``tools/timeline_merge.py`` merge + straggler report, and post-mortem
trace survival across a coordinated abort."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_timeline_merge():
    spec = importlib.util.spec_from_file_location(
        "timeline_merge", os.path.join(REPO, "tools", "timeline_merge.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod

_CHILD = """\
import numpy as np
import horovod_tpu as hvd

hvd.init()
for i in range(3):
    hvd.allreduce(np.ones(64, np.float32), name=f"t{i}")
hvd.allgather(np.ones((2, 2), np.float32), name="g0")
hvd.broadcast(np.arange(5, dtype=np.float32), 0, name="b0")
hvd.shutdown()
"""


def _load_trace(path):
    # The writer streams events with trailing commas and no closing "]"
    # (Chrome's parser tolerates it); normalize before json.loads.
    raw = open(path).read().rstrip().rstrip(",")
    return json.loads(raw + "]")


def _child_env(extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for var in ("HVD_TPU_RANK", "HVD_TPU_SIZE", "HVD_TPU_COORD",
                "HVD_TPU_DATA", "HVD_TPU_TIMELINE", "HOROVOD_TIMELINE",
                "HVD_TPU_FAULT_SPEC", "HVD_TPU_XLA_DATA_PLANE"):
        env.pop(var, None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra or {})
    return env


def _run_child(code, env, timeout=180):
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, env=env,
                          timeout=timeout)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc


def validate_chrome_events(events):
    """Satellite: structural Chrome-trace validation — the required keys
    (``ph``, ``ts``, ``pid``, ``name``) on every row and non-decreasing
    ``ts`` per row (one writer, one clock per file)."""
    assert events, "empty timeline"
    last_ts = {}
    for e in events:
        for key in ("ph", "ts", "pid", "name"):
            assert key in e, (key, e)
        pid = e["pid"]
        assert e["ts"] >= last_ts.get(pid, 0), (e, last_ts.get(pid, 0))
        last_ts[pid] = e["ts"]


def _pid_names(events):
    return {e["pid"]: e["args"]["name"] for e in events
            if e.get("ph") == "M" and e.get("name") == "process_name"}


def test_timeline_negotiate_op_nesting_and_timestamps(tmp_path):
    path = str(tmp_path / "timeline.json")
    env = _child_env({"HVD_TPU_TIMELINE": path})
    _run_child(_CHILD, env, timeout=120)
    events = _load_trace(path)
    validate_chrome_events(events)

    # pid metadata maps each trace row to its tensor name, and the file
    # carries its rank + clock-sync metadata for the merge tool.
    pid_names = _pid_names(events)
    assert set(pid_names.values()) >= {"t0", "t1", "t2", "g0", "b0"}
    metas = {e["name"] for e in events if e.get("ph") == "M"}
    assert "hvd_rank" in metas and "hvd_clock_sync" in metas

    # Timestamps never decrease in file order (one writer, one clock).
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts)

    by_name = {}
    for e in events:
        if e.get("ph") in ("B", "E"):
            by_name.setdefault(pid_names[e["pid"]], []).append(e)

    expect_op = {"t0": "ALLREDUCE", "t1": "ALLREDUCE", "t2": "ALLREDUCE",
                 "g0": "ALLGATHER", "b0": "BROADCAST"}
    for name, op in expect_op.items():
        evs = by_name[name]
        cats = [e.get("name") for e in evs]
        # NEGOTIATE opens first and closes before the op row opens:
        # NEGOTIATE(B) ... E ... OP(B) ... E — per-tensor state machine.
        assert cats[0] == "NEGOTIATE", (name, cats)
        assert op in cats, (name, cats)
        assert cats.index("NEGOTIATE") < cats.index(op), (name, cats)
        neg_end = next(i for i, e in enumerate(evs)
                       if e["ph"] == "E" and i > 0)
        assert neg_end < cats.index(op), (name, cats)
        # Begin/End events balance, and never go negative (no E before B).
        depth = 0
        for e in evs:
            depth += 1 if e["ph"] == "B" else -1
            assert depth >= 0, (name, cats)
        assert depth == 0, (name, cats)
        # The op's closing E carries the payload byte count.
        closing = evs[-1]
        assert closing["ph"] == "E", (name, evs[-1])
        assert closing.get("args", {}).get("bytes", 0) > 0, (name, closing)


def test_timeline_structural_validation_xla_plane(tmp_path):
    """Satellite: the same structural contract holds with the XLA data
    plane active (``HVD_TPU_XLA_DATA_PLANE=1``) — plane execution rows
    and engine ``__xp.*`` negotiation rows share one valid file."""
    pytest.importorskip("jax")
    path = str(tmp_path / "timeline_plane.json")
    env = _child_env({"HVD_TPU_TIMELINE": path,
                      "HVD_TPU_XLA_DATA_PLANE": "1"})
    _run_child(_CHILD, env, timeout=240)
    events = _load_trace(path)
    validate_chrome_events(events)
    names = {e.get("name") for e in events}
    assert "XLA_ALLREDUCE" in names, names
    for phase in ("BUCKET_BUILD", "XLA_DISPATCH", "DEVICE_WAIT"):
        assert phase in names, names
    rows = set(_pid_names(events).values())
    assert "t0" in rows, rows


def test_timeline_disabled_writes_nothing(tmp_path):
    """Without HVD_TPU_TIMELINE the engine must not create a file (the
    default path: timeline disabled, zero overhead)."""
    path = tmp_path / "no_timeline.json"
    env = _child_env()
    _run_child(_CHILD, env, timeout=120)
    assert not path.exists()


# ---------------------------------------------------------------------------
# Span API (hvd.trace_span / hvd.trace_marker).
# ---------------------------------------------------------------------------

_CHILD_SPANS = """\
import numpy as np
import horovod_tpu as hvd

hvd.init()
assert hvd.timeline_enabled()
with hvd.trace_span("data_loading"):
    hvd.allreduce(np.ones(8, np.float32), name="s0")
hvd.trace_marker("epoch_boundary")
hvd.shutdown()
"""


def test_trace_span_and_marker_land_in_trace(tmp_path):
    path = str(tmp_path / "spans.json")
    env = _child_env({"HVD_TPU_TIMELINE": path})
    _run_child(_CHILD_SPANS, env, timeout=120)
    events = _load_trace(path)
    validate_chrome_events(events)
    rows = set(_pid_names(events).values())
    assert "data_loading" in rows and "app.markers" in rows, rows
    spans = [e for e in events if e.get("name") == "data_loading"
             and e["ph"] in ("B", "E")]
    assert [e["ph"] for e in spans] == ["B", "E"], spans
    # The collective issued inside the span sits between its B and E.
    s0_ts = [e["ts"] for e in events
             if e.get("ph") in ("B", "E")
             and _pid_names(events).get(e["pid"]) == "s0"]
    assert s0_ts and spans[0]["ts"] <= s0_ts[0] <= spans[1]["ts"]
    markers = [e for e in events
               if e["ph"] == "i" and e["name"] == "epoch_boundary"]
    assert markers, events


def test_trace_span_noop_without_timeline():
    """Spans/markers must be safe to leave in production code: no-ops (no
    crash, no file) when no timeline is configured."""
    import horovod_tpu as hvd

    assert hvd.timeline_enabled() is False
    with hvd.trace_span("x"):
        pass
    hvd.trace_marker("y")


def test_keras_timeline_callback_noop_smoke():
    """TimelineCallback hooks are callable (and no-ops) without an active
    timeline — safe in production configs."""
    pytest.importorskip("keras")
    from horovod_tpu.keras.callbacks import TimelineCallback

    cb = TimelineCallback(steps=True)
    cb.on_epoch_begin(0)
    cb.on_train_batch_begin(0)
    cb.on_train_batch_end(0)
    cb.on_epoch_end(0)


# ---------------------------------------------------------------------------
# Per-rank files + clock alignment + merge toolchain (tentpole acceptance).
# ---------------------------------------------------------------------------


def test_per_rank_timelines_merge_and_straggler_attribution(tmp_path):
    """Acceptance: a 4-rank CPU job with a timeline directory and an
    injected delay on rank 2 produces per-rank trace files that
    tools/timeline_merge.py fuses into one valid Chrome/Perfetto JSON,
    and BOTH the merge tool's straggler report and rank 0's
    metrics_snapshot()["skew"] name rank 2 as the dominant
    last-announcer."""
    from horovod_tpu.runner import run_command

    tl = str(tmp_path / "tl")
    os.makedirs(tl)
    code = (
        "import numpy as np, horovod_tpu as hvd\n"
        "hvd.init()\n"
        "for i in range(6):\n"
        "    hvd.allreduce(np.ones(32, np.float32), name=f'acc.{i}')\n"
        "if hvd.rank() == 0:\n"
        "    snap = hvd.metrics_snapshot()\n"
        "    last = snap['skew']['last_to_announce']\n"
        "    assert last, snap['skew']\n"
        "    assert max(last, key=last.get) == '2', last\n"
        "    assert snap['histograms']['announce_skew_sec']['count'] > 0\n"
        "hvd.shutdown()\n"
    )
    # Delays on 4 of 6 collectives: rank 2 is deterministically last on
    # those, which no other rank can match on the remaining negotiations.
    spec = ";".join(f"rank=2:delay=0.2@op={i}" for i in (1, 2, 3, 4))
    env = _child_env({"HVD_TPU_TIMELINE": tl, "HVD_TPU_FAULT_SPEC": spec})
    results = run_command([sys.executable, "-c", code], 4, env=env,
                          timeout=120.0, capture=True)
    for r in results:
        assert r.returncode == 0, (r.rank, r.stderr[-2000:])
    files = sorted(n for n in os.listdir(tl) if n.startswith("rank"))
    assert files == [f"rank{r}.json" for r in range(4)], files
    # Every rank's file is independently valid, with clock metadata.
    for name in files:
        events = _load_trace(os.path.join(tl, name))
        validate_chrome_events(events)
        metas = {e["name"] for e in events if e.get("ph") == "M"}
        assert "hvd_rank" in metas and "hvd_clock_sync" in metas, name

    merged_path = str(tmp_path / "merged.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "timeline_merge.py"),
         tl, "-o", merged_path],
        capture_output=True, text=True, env=_child_env(), timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dominant straggler: rank 2" in proc.stdout, proc.stdout
    assert "announce skew:" in proc.stdout, proc.stdout
    merged = json.load(open(merged_path))  # complete, valid JSON
    events = merged["traceEvents"]
    procs = {e["args"]["name"] for e in events
             if e.get("name") == "process_name"}
    assert procs >= {f"rank {r}" for r in range(4)}, procs
    # Offsets applied: timestamps were rebased onto one clock, and every
    # rank contributed events.
    contributing = {e["pid"] for e in events if e.get("ph") != "M"}
    assert contributing >= set(range(4)), contributing


def test_resolve_timeline_path_forms(tmp_path):
    """HOROVOD_TIMELINE resolution: %d template, directory (existing or
    trailing-sep), legacy plain file, and the restart-epoch suffix that
    keeps a relaunch from truncating the crashed attempt's traces."""
    from horovod_tpu.common import _resolve_timeline_path as resolve

    d = str(tmp_path / "tl")
    assert resolve("", 1) == ""
    assert resolve(str(tmp_path / "t-%d.json"), 2) == \
        str(tmp_path / "t-2.json")
    assert resolve(d + os.sep, 1) == os.path.join(d, "rank1.json")
    assert os.path.isdir(d)  # trailing-sep form creates the directory
    assert resolve(d, 0) == os.path.join(d, "rank0.json")  # now existing
    plain = str(tmp_path / "single.json")
    assert resolve(plain, 0) == plain
    assert resolve(plain, 1) == ""  # legacy: rank 0 only
    # Restart epochs land in the filename for the per-rank forms.
    assert resolve(d, 3, epoch=2) == os.path.join(d, "rank3.e2.json")
    assert resolve(str(tmp_path / "t-%d.json"), 1, epoch=1) == \
        str(tmp_path / "t-1.json.e1")


def test_timeline_merge_prefers_latest_epoch(tmp_path):
    """The merge tool's directory form keeps only the latest restart
    epoch per rank, so two attempts never interleave in one trace."""
    tm = _load_timeline_merge()
    for name, rank in (("rank0.json", 0), ("rank0.e1.json", 0),
                       ("rank1.e1.json", 1)):
        (tmp_path / name).write_text(
            '[\n{"name":"hvd_rank","ph":"M","ts":0,"pid":0,'
            f'"args":{{"rank":{rank}}}}},\n')
    files = tm.resolve_inputs([str(tmp_path)])
    assert [os.path.basename(f) for f in files] == \
        ["rank0.e1.json", "rank1.e1.json"], files


def test_hvdrun_timeline_flag_writes_per_rank_files(tmp_path):
    """`hvdrun --timeline DIR` wires HVD_TPU_TIMELINE per rank: one trace
    file per rank appears under DIR."""
    tl = str(tmp_path / "tl")
    code = ("import numpy as np, horovod_tpu as hvd; hvd.init(); "
            "hvd.allreduce(np.ones(4, np.float32), name='cli.0'); "
            "hvd.shutdown()")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         "--timeline", tl, "--", sys.executable, "-c", code],
        capture_output=True, text=True, env=_child_env(), timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    files = sorted(n for n in os.listdir(tl) if n.endswith(".json"))
    assert files == ["rank0.json", "rank1.json"], files
    for name in files:
        validate_chrome_events(_load_trace(os.path.join(tl, name)))


def test_timeline_survives_crash_abort(tmp_path):
    """Satellite: a coordinated abort (``rank=1:crash``) leaves parseable
    per-rank traces — the crashed rank flushes before dying, the
    survivors flush on the abort path and close at shutdown."""
    from horovod_tpu.common.faults import CRASH_EXIT_CODE
    from horovod_tpu.runner import run_command

    tl = str(tmp_path / "tl")
    os.makedirs(tl)
    code = (
        "import numpy as np, horovod_tpu as hvd\n"
        "from horovod_tpu.common import RanksDownError\n"
        "hvd.init()\n"
        "try:\n"
        "    for i in range(4):\n"
        "        hvd.allreduce(np.ones(8, np.float32), name=f'c.{i}')\n"
        "    raise SystemExit(9)\n"  # survivors must NOT complete
        "except RanksDownError:\n"
        "    raise SystemExit(0)\n"
    )
    env = _child_env({"HVD_TPU_TIMELINE": tl,
                      "HVD_TPU_FAULT_SPEC": "rank=1:crash@op=2",
                      "HVD_TPU_KILL_GRACE_SEC": "3"})
    results = run_command([sys.executable, "-c", code], 2, env=env,
                          timeout=90.0, capture=True)
    by_rank = {r.rank: r for r in results}
    assert by_rank[1].returncode == CRASH_EXIT_CODE, by_rank[1]
    assert by_rank[0].returncode == 0, by_rank[0].stderr[-2000:]
    # The survivor shut down cleanly: its file must parse strictly.  The
    # crashed rank's file goes through the merge tool's salvaging loader
    # (an ofstream auto-flush can tear its final line), and must still
    # yield a valid, non-empty event stream.
    validate_chrome_events(_load_trace(os.path.join(tl, "rank0.json")))
    salvage = _load_timeline_merge().load_events
    validate_chrome_events(salvage(os.path.join(tl, "rank1.json")))
    # The survivor traced the collectives that completed before the abort.
    rows = set(_pid_names(
        _load_trace(os.path.join(tl, "rank0.json"))).values())
    assert "c.0" in rows, rows
