"""Direct test of the engine's Chrome-tracing timeline (docs/timeline.md):
run eager collectives in a fresh process with ``HVD_TPU_TIMELINE`` set,
parse the output as JSON, and assert the NEGOTIATE -> op event nesting and
non-decreasing timestamps.  (The XLA plane's timeline integration is
covered by tests/test_xla_plane.py::test_xla_plane_timeline_activities;
this covers the engine path itself, which previously had no direct test.)
"""

import json
import os
import subprocess
import sys

_CHILD = """\
import numpy as np
import horovod_tpu as hvd

hvd.init()
for i in range(3):
    hvd.allreduce(np.ones(64, np.float32), name=f"t{i}")
hvd.allgather(np.ones((2, 2), np.float32), name="g0")
hvd.broadcast(np.arange(5, dtype=np.float32), 0, name="b0")
hvd.shutdown()
"""


def _run_with_timeline(tmp_path):
    path = str(tmp_path / "timeline.json")
    env = dict(os.environ, HVD_TPU_TIMELINE=path, JAX_PLATFORMS="cpu")
    for var in ("HVD_TPU_RANK", "HVD_TPU_SIZE", "HVD_TPU_COORD",
                "HVD_TPU_DATA"):
        env.pop(var, None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", _CHILD],
                          capture_output=True, text=True, env=env,
                          timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    # The writer streams events with trailing commas and no closing "]"
    # (Chrome's parser tolerates it); normalize before json.loads.
    raw = open(path).read().rstrip().rstrip(",")
    return json.loads(raw + "]")


def test_timeline_negotiate_op_nesting_and_timestamps(tmp_path):
    events = _run_with_timeline(tmp_path)
    assert events, "empty timeline"

    # pid metadata maps each trace row to its tensor name.
    pid_names = {e["pid"]: e["args"]["name"]
                 for e in events if e.get("ph") == "M"}
    assert set(pid_names.values()) >= {"t0", "t1", "t2", "g0", "b0"}

    # Timestamps never decrease in file order (one writer, one clock).
    ts = [e["ts"] for e in events if "ts" in e]
    assert ts == sorted(ts)

    by_name = {}
    for e in events:
        if e.get("ph") in ("B", "E"):
            by_name.setdefault(pid_names[e["pid"]], []).append(e)

    expect_op = {"t0": "ALLREDUCE", "t1": "ALLREDUCE", "t2": "ALLREDUCE",
                 "g0": "ALLGATHER", "b0": "BROADCAST"}
    for name, op in expect_op.items():
        evs = by_name[name]
        cats = [e.get("name") for e in evs]
        # NEGOTIATE opens first and closes before the op row opens:
        # NEGOTIATE(B) ... E ... OP(B) ... E — per-tensor state machine.
        assert cats[0] == "NEGOTIATE", (name, cats)
        assert op in cats, (name, cats)
        assert cats.index("NEGOTIATE") < cats.index(op), (name, cats)
        neg_end = next(i for i, e in enumerate(evs)
                       if e["ph"] == "E" and i > 0)
        assert neg_end < cats.index(op), (name, cats)
        # Begin/End events balance, and never go negative (no E before B).
        depth = 0
        for e in evs:
            depth += 1 if e["ph"] == "B" else -1
            assert depth >= 0, (name, cats)
        assert depth == 0, (name, cats)
        # The op's closing E carries the payload byte count.
        closing = evs[-1]
        assert closing["ph"] == "E", (name, evs[-1])
        assert closing.get("args", {}).get("bytes", 0) > 0, (name, closing)


def test_timeline_disabled_writes_nothing(tmp_path):
    """Without HVD_TPU_TIMELINE the engine must not create a file (the
    default path: timeline disabled, zero overhead)."""
    path = tmp_path / "no_timeline.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("HVD_TPU_TIMELINE", None)
    env.pop("HOROVOD_TIMELINE", None)
    for var in ("HVD_TPU_RANK", "HVD_TPU_SIZE", "HVD_TPU_COORD",
                "HVD_TPU_DATA"):
        env.pop(var, None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", _CHILD],
                          capture_output=True, text=True, env=env,
                          timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert not path.exists()
