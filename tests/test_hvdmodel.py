"""hvdmodel: the control-plane model checker (tools/hvdmodel).

Three layers:

* the tier-1 CLI contract: ``python -m tools.hvdmodel --quick`` explores
  the four quick configs exhaustively (>= 50k states, < 60s), covers
  every required protocol event, and exits 0 — so a protocol change that
  deadlocks, diverges membership, or accepts a stale-epoch frame fails
  the suite at the PR that introduces it;
* the seeded historical bugs: each ``--bug`` variant re-introduces a
  real protocol mistake (skipping the steady revocation before a
  reshape, accepting stale-epoch frames, dropping the exit requeue) and
  MUST be caught with a readable shortest-path trace — a checker that
  passes everything would let the protocol drift silently;
* in-process spot checks of the explorer API the CLI wraps.
"""

import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.hvdmodel import configs, explorer  # noqa: E402


def _run_cli(*args, timeout=120):
    env = dict(os.environ,
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    return subprocess.run(
        [sys.executable, "-m", "tools.hvdmodel", *args],
        capture_output=True, text=True, env=env, cwd=REPO,
        timeout=timeout)


def test_quick_is_clean_and_exhaustive():
    proc = _run_cli("--quick")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert proc.stdout.rstrip().endswith("OK"), proc.stdout
    m = re.search(r"total: (\d+) states", proc.stdout)
    assert m, proc.stdout
    # The acceptance floor: the quick tier must stay a real exploration,
    # not shrink into a smoke test as configs are tuned.
    assert int(m.group(1)) >= 50000, proc.stdout
    # No config may hit the state cap — quick is EXHAUSTIVE by contract.
    assert "truncated" not in proc.stdout, proc.stdout
    for event in ("steady_enter", "steady_exit", "reshape_shrink",
                  "reshape_grow", "crash", "freeze", "stale_drop",
                  "hb_detect", "abort:ST_TIMEOUT",
                  # The p2p plane (docs/pipeline.md#fault-semantics):
                  # paired-readiness negotiation end to end, plus the
                  # blocked-forever and timeout terminals.
                  "p2p_announce", "p2p_match", "p2p_execute",
                  "p2p_blocked", "p2p_timeout"):
        assert event in proc.stdout, (event, proc.stdout)


@pytest.mark.parametrize("bug", ["skip-revoke", "stale-epoch",
                                 "no-requeue",
                                 "drop-heartbeat-revoke",
                                 "p2p-unmatched-send"])
def test_seeded_bug_is_caught_with_trace(bug):
    proc = _run_cli("--bug", bug)
    assert proc.returncode == 1, (bug, proc.stdout, proc.stderr)
    assert "VIOLATION" in proc.stdout, (bug, proc.stdout)
    # Counterexamples render as file:line steps into the model source.
    assert re.search(r"tools/hvdmodel/model\.py:\d+", proc.stdout), \
        proc.stdout


def test_unknown_bug_is_rejected():
    proc = _run_cli("--bug", "made-up")
    assert proc.returncode != 0
    assert "made-up" in (proc.stdout + proc.stderr)


def test_explorer_finds_shortest_deadlock_in_process():
    """The skip-revoke seed runs under ``group_timeout=False`` (no
    data-plane backstop): survivors stay self-clocked forever once the
    revocation is skipped, and the BFS must report that as a deadlock
    whose trace starts from the initial state."""
    res = explorer.explore(configs.seeded("skip-revoke"),
                           max_states=100000)
    assert not res.ok
    codes = {code for code, _, _ in res.violations}
    assert "deadlock" in codes, res.violations
    code, detail, trace = res.violations[0]
    assert trace, "counterexample trace must be non-empty"
    assert all(isinstance(line, int) and line > 0
               for _, line in trace), trace


def test_quick_configs_declare_distinct_regimes():
    """quick() pins six regimes: the coordinator tree, the elastic
    star, the revoke-only liveness config (group_timeout disabled —
    the revocation broadcast alone must keep survivors live), the
    heartbeat-off config (HVD_TPU_HEARTBEAT_MS=0 — the legacy
    exchange-silence ST_TIMEOUT contract), and the two p2p configs
    (paired readiness under faults, and the lost-recv timeout path —
    docs/pipeline.md#fault-semantics)."""
    cfgs = {c.name: c for c in configs.quick()}
    assert set(cfgs) == {"quick-tree", "quick-elastic",
                         "quick-revoke-only", "quick-hb-off",
                         "quick-p2p", "quick-p2p-lost"}
    assert not cfgs["quick-tree"].elastic
    assert cfgs["quick-elastic"].elastic
    assert cfgs["quick-revoke-only"].elastic
    assert cfgs["quick-revoke-only"].group_timeout is False
    assert cfgs["quick-tree"].group_timeout is True
    assert cfgs["quick-tree"].heartbeat is True
    assert cfgs["quick-hb-off"].heartbeat is False
    assert "freeze:1" in cfgs["quick-hb-off"].faults
    assert cfgs["quick-p2p"].p2p == (1, 2)
    assert not cfgs["quick-p2p"].p2p_lost_recv
    assert cfgs["quick-p2p-lost"].p2p_lost_recv
    assert cfgs["quick-p2p-lost"].fault_budget == 0  # pure liveness
