"""Negotiation response cache tests (docs/performance.md): steady-state
hit rates on the engine path, the OP_NOOP negotiation-only op the XLA
plane's metadata cache rides, and — the part that must never regress —
the fallbacks: a signature change after a warm cache still raises the
typed cross-rank mismatch error, ragged allgather geometry changes still
negotiate, a crash mid-cached-steady-state still aborts with
RanksDownError, a stalled cached negotiation still hits the
HVD_TPU_COLLECTIVE_TIMEOUT_SEC deadline, and cache state resets across
re-init.  The cache is a pure fast path: every behavior contract from
PR 1-3 holds with it on.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tests.distributed import distributed_test

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env(**overrides):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.setdefault("HVD_TPU_KILL_GRACE_SEC", "3")
    env.update({k: str(v) for k, v in overrides.items()})
    for var in ("HVD_TPU_RANK", "HVD_TPU_SIZE", "HVD_TPU_COORD",
                "HVD_TPU_DATA", "HVD_TPU_FAULT_SPEC"):
        env.setdefault(var, "")
        if not env[var]:
            env.pop(var, None)
    return env


# ---------------------------------------------------------------------------
# Steady state: bit-vector negotiation, correctness, hit rate, latency.
# ---------------------------------------------------------------------------


@distributed_test(np_=4)
def test_steady_state_hit_rate_and_correctness():
    """The acceptance shape: a 4-rank job repeating the same named
    allreduce sequence is ≥90% cache hits after the first step, results
    stay exact every step, and negotiation latency is recorded for the
    engine plane."""
    import horovod_tpu as hvd

    hvd.init()
    r, n = hvd.rank(), hvd.size()

    def step(s):
        for k in range(3):
            out = hvd.allreduce(np.full(16, float(r + k + s), np.float32),
                                average=False, name=f"steady.{k}")
            want = sum(float(i + k + s) for i in range(n))
            assert np.allclose(out, want), (r, s, k, out[0], want)

    step(0)  # warm: full string negotiation populates every rank's cache
    warm = hvd.metrics_snapshot()["cache"]["engine"]
    for s in range(1, 11):
        step(s)
    snap = hvd.metrics_snapshot()
    c = snap["cache"]["engine"]
    hits = c["hits"] - warm["hits"]
    misses = c["misses"] - warm["misses"]
    assert hits == 30, (r, warm, c)  # 3 names x 10 post-warm steps
    assert hits / max(hits + misses, 1) >= 0.9, (r, warm, c)
    assert c["size"] >= 3, c
    assert c["evictions"] == 0, c


@distributed_test(np_=3)
def test_fused_steady_state_stays_fused():
    """Replayed cache hits re-fuse: many small same-dtype allreduces in
    flight at once stay correct across repeat steps (the replay path
    merges consecutive hits under the threshold like fresh responses)."""
    import horovod_tpu as hvd

    hvd.init()
    r, n = hvd.rank(), hvd.size()
    for s in range(4):
        handles = [
            hvd.allreduce_async(np.full(17, float(i + r + s), np.float32),
                                average=False, name=f"fc.{i}")
            for i in range(32)
        ]
        for i, h in enumerate(handles):
            out = h.wait()
            want = sum(float(i + j + s) for j in range(n))
            assert np.allclose(out, want), (r, s, i)


@distributed_test(np_=3)
def test_mixed_ops_and_average_replay():
    """Broadcast and averaged allreduce replay correctly from the cache
    (root and average semantics live in the stored signature / the local
    entry, not re-negotiated)."""
    import horovod_tpu as hvd

    hvd.init()
    r, n = hvd.rank(), hvd.size()
    for s in range(5):
        avg = hvd.allreduce(np.full((4, 2), float(r + s), np.float32),
                            average=True, name="mix.avg")
        assert np.allclose(avg, sum(float(i + s) for i in range(n)) / n), \
            (r, s)
        b = hvd.broadcast(np.full(6, float(r * 10 + s), np.float32), 1,
                          name="mix.bc")
        assert np.allclose(b, 10.0 + s), (r, s, b[0])


# ---------------------------------------------------------------------------
# Fallbacks: the cache must never weaken a PR 1-3 contract.
# ---------------------------------------------------------------------------


@distributed_test(np_=3)
def test_shape_change_after_warm_cache_raises_mismatch():
    """Rank-divergent shape change after the cache is warm: the rank with
    the new shape misses and sends a full request, the coordinator folds
    the other ranks' cache bits back into full requests, and the PR-2
    typed mismatch error fires on every rank (never a hang, never a
    silent replay of the stale agreement)."""
    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    for s in range(3):  # warm
        hvd.allreduce(np.ones(16, np.float32), average=False, name="chg")
    with pytest.raises(ValueError, match="Mismatched"):
        shape = 8 if r == 1 else 16
        hvd.allreduce(np.ones(shape, np.float32), average=False, name="chg")


@distributed_test(np_=3)
def test_coherent_shape_change_renegotiates_and_recaches():
    """All ranks changing a cached tensor's shape together is NOT an
    error: every rank misses, the name renegotiates in full, the cache
    entry refreshes, and the new shape hits again on its repeats."""
    import horovod_tpu as hvd

    hvd.init()
    r, n = hvd.rank(), hvd.size()
    for s in range(3):
        out = hvd.allreduce(np.full(16, float(r), np.float32),
                            average=False, name="grow")
        assert np.allclose(out, sum(range(n))), (r, s)
    before = hvd.metrics_snapshot()["cache"]["engine"]
    for s in range(3):  # coherent change: everyone moves to the new shape
        out = hvd.allreduce(np.full(32, float(r), np.float32),
                            average=False, name="grow")
        assert out.shape == (32,) and np.allclose(out, sum(range(n))), (r, s)
    after = hvd.metrics_snapshot()["cache"]["engine"]
    assert after["misses"] == before["misses"] + 1, (r, before, after)
    assert after["hits"] >= before["hits"] + 2, (r, before, after)


@distributed_test(np_=3)
def test_ragged_allgather_dim0_change_still_negotiates():
    """Allgather signatures include dim0, so one rank growing its shard
    is a local miss — and must renegotiate cleanly (the coordinator
    synthesizes the other ranks' requests with their per-rank dim0 from
    the stored geometry), not error: ragged allgather stays ragged."""
    import horovod_tpu as hvd

    hvd.init()
    r, n = hvd.rank(), hvd.size()
    base = sum(i + 1 for i in range(n))
    for s in range(3):  # warm with per-rank dim0 = r + 1
        g = hvd.allgather(np.full((r + 1, 2), float(r), np.float32),
                          name="rag")
        assert g.shape == (base, 2), (r, s, g.shape)
    d0 = (r + 1) + (2 if r == 1 else 0)  # rank 1 grows its shard
    g = hvd.allgather(np.full((d0, 2), float(r), np.float32), name="rag")
    assert g.shape == (base + 2, 2), (r, g.shape)
    # and the refreshed geometry is cached again
    g = hvd.allgather(np.full((d0, 2), float(r), np.float32), name="rag")
    assert g.shape == (base + 2, 2), (r, g.shape)


@distributed_test(np_=3)
def test_kill_switch_disables_cache():
    """HVD_TPU_RESPONSE_CACHE=0: identical results, zero cache traffic."""
    os.environ["HVD_TPU_RESPONSE_CACHE"] = "0"
    import horovod_tpu as hvd

    hvd.init()
    r, n = hvd.rank(), hvd.size()
    for s in range(5):
        out = hvd.allreduce(np.full(8, float(r), np.float32),
                            average=False, name="off.x")
        assert np.allclose(out, sum(range(n))), (r, s)
    c = hvd.metrics_snapshot()["cache"]["engine"]
    assert c == {"hits": 0, "misses": 0, "evictions": 0, "size": 0}, c


@distributed_test(np_=3)
def test_tiny_capacity_evicts_and_stays_correct():
    """HVD_TPU_CACHE_CAPACITY=4 with 8 names in rotation: constant LRU
    eviction, every result still exact, size pinned at the cap."""
    os.environ["HVD_TPU_CACHE_CAPACITY"] = "4"
    import horovod_tpu as hvd

    hvd.init()
    r, n = hvd.rank(), hvd.size()
    for s in range(3):
        for k in range(8):
            out = hvd.allreduce(np.full(8, float(r + k), np.float32),
                                average=False, name=f"evict.{k}")
            assert np.allclose(out, sum(i + k for i in range(n))), (r, s, k)
    c = hvd.metrics_snapshot()["cache"]["engine"]
    assert c["evictions"] > 0, c
    assert c["size"] <= 4, c


@distributed_test(np_=3)
def test_noop_negotiation_only_op():
    """OP_NOOP — the engine half of the XLA plane's metadata-cache fast
    path — negotiates, stamps completion order, moves no data, and its
    repeats ride the cache-bit vector like any other collective."""
    import ctypes

    import horovod_tpu as hvd
    from horovod_tpu import common

    hvd.init()
    n = hvd.size()
    lib = common._lib
    before = hvd.metrics_snapshot()["cache"]["engine"]
    seqs = []
    for s in range(4):
        dims = (ctypes.c_longlong * 1)(2 * n)
        raw = lib.hvd_tpu_enqueue(common.OP_NOOP, b"__xp.meta", None, None,
                                  dims, 1, 3, -1, 0)  # dtype 3 = int64
        assert raw >= 0
        assert lib.hvd_tpu_wait(raw) == common.ST_OK
        seqs.append(int(lib.hvd_tpu_completion_seq(raw)))
        lib.hvd_tpu_release(raw)
    assert seqs == sorted(seqs) and len(set(seqs)) == 4, seqs
    after = hvd.metrics_snapshot()["cache"]["engine"]
    assert after["hits"] - before["hits"] >= 3, (before, after)


@distributed_test(np_=4, timeout=240)
def test_plane_metadata_cache_skips_xp_allreduce():
    """The XLA-plane acceptance clause, minus the XLA execution this CPU
    environment cannot run (multiprocess CPU computations are the known
    jax-drift limitation): over 4 real ranks, step one negotiates the
    real `__xp.` metadata allreduce, and every later step of the same op
    rides the cached agreement — op.cached on every rank, zero further
    metadata allreduces — with negotiation driven through the real
    engine.  Dispatch is stubbed; everything up to it is live."""
    import time

    os.environ["HVD_TPU_XLA_DATA_PLANE"] = "1"
    import horovod_tpu as hvd

    hvd.init()
    from horovod_tpu import common

    plane = common._xla_plane
    assert plane is not None, "XLA plane failed to initialize"
    dispatched = []
    plane._dispatch = lambda bucket: dispatched.append(
        [op.name for op in bucket])
    cached_flags = []
    for s in range(5):
        plane.allreduce_async(np.full(8, 1.0, np.float32), False, None,
                              "pm.x")
        op = plane._pending[-1]
        deadline = time.monotonic() + 30
        while op.seq is None and time.monotonic() < deadline:
            with plane._mu:
                plane._poll_negotiations()
            time.sleep(0.002)
        assert op.seq is not None and op.seq >= 0, (s, op.seq)
        cached_flags.append(op.cached)
        plane.flush()  # (stubbed) dispatch order drives the cache store
    assert cached_flags[0] is False, cached_flags
    assert all(cached_flags[1:]), cached_flags  # zero __xp. after step one
    assert len(dispatched) == 5, dispatched
    c = hvd.metrics_snapshot()["cache"]["xla"]
    assert c["hits"] == 4 and c["misses"] == 1, c  # 100% after step one


@distributed_test(np_=2)
def test_timeline_marks_cached_negotiations():
    """Rank 0's NEGOTIATE rows carry a NEGOTIATE_CACHED instant for
    bit-vector agreements, so a merged trace shows which negotiations the
    cache absorbed."""
    import json
    import tempfile

    tl_dir = os.path.join(tempfile.gettempdir(), "hvd_cache_tl") + os.sep
    os.environ["HOROVOD_TIMELINE"] = tl_dir
    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    for s in range(4):
        hvd.allreduce(np.ones(8, np.float32), average=False, name="tl.x")
    hvd.shutdown()
    if r == 0:
        # Trailing comma, no closing bracket (Chrome tolerates it);
        # normalize like tests/test_timeline.py does.
        raw = open(os.path.join(tl_dir, "rank0.json")).read()
        events = json.loads(raw.rstrip().rstrip(",") + "]")
        names = [e.get("name") for e in events if isinstance(e, dict)]
        assert "NEGOTIATE" in names, sorted(set(names))
        assert "NEGOTIATE_CACHED" in names, sorted(set(names))


def test_cache_resets_across_reinit(single_process_hvd):
    """Cache CONTENTS die with the engine (re-init, and with it restart
    epochs, starts cold — the peers' caches are gone), while the
    hit/miss counters stay process-cumulative like stalls."""
    hvd = single_process_hvd
    hvd.allreduce(np.ones(4, np.float32), name="re.x")
    hvd.allreduce(np.ones(4, np.float32), name="re.x")
    c1 = hvd.metrics_snapshot()["cache"]["engine"]
    assert c1["hits"] >= 1 and c1["size"] >= 1, c1
    hvd.shutdown()
    hvd.init()
    hvd.allreduce(np.ones(4, np.float32), name="re.x")
    c2 = hvd.metrics_snapshot()["cache"]["engine"]
    assert c2["misses"] == c1["misses"] + 1, (c1, c2)  # cold again
    assert c2["hits"] == c1["hits"], (c1, c2)  # cumulative, no false hit


# ---------------------------------------------------------------------------
# Faults mid-cached-steady-state (satellite): crash -> RanksDownError,
# stall -> CollectiveTimeoutError, with the cache warm on every rank.
# ---------------------------------------------------------------------------


def test_crash_mid_cached_steady_state_aborts():
    """rank=1:crash at op 30 — deep in cached steady state — still
    surfaces RanksDownError naming rank 1 on every survivor: liveness and
    the coordinated abort poison the bit-vector path exactly like the
    string path."""
    from horovod_tpu.runner import run_command

    code = (
        "import numpy as np, horovod_tpu as hvd\n"
        "from horovod_tpu.common import RanksDownError\n"
        "hvd.init()\n"
        "try:\n"
        "    for s in range(20):\n"
        "        for k in range(3):\n"
        "            hvd.allreduce(np.ones(8, np.float32), average=False,\n"
        "                          name=f'cs.{k}')\n"
        "    raise SystemExit(9)  # survivors must NOT complete\n"
        "except RanksDownError as e:\n"
        "    assert 1 in e.ranks, (e.ranks, str(e))\n"
        "    c = hvd.metrics_snapshot()['cache']['engine']\n"
        "    assert c['hits'] > 10, c  # the crash hit a WARM cache\n"
        "    raise SystemExit(0)\n"
    )
    results = run_command(
        [sys.executable, "-c", code], 4,
        env=_env(HVD_TPU_FAULT_SPEC="rank=1:crash@op=30",
                 HVD_TPU_COLLECTIVE_TIMEOUT_SEC="20"),
        timeout=90.0, capture=True)
    by_rank = {r.rank: r for r in results}
    from horovod_tpu.common.faults import CRASH_EXIT_CODE

    assert by_rank[1].returncode == CRASH_EXIT_CODE, by_rank[1]
    for r in (0, 2, 3):
        assert by_rank[r].returncode == 0, \
            (r, by_rank[r].returncode, by_rank[r].stderr[-800:])


@pytest.mark.slow  # ~13s (sleeps through the deadline sweep); the sweep
# itself stays tier-1 via the message_table timeout tests
def test_cached_negotiation_hits_collective_timeout():
    """A cache-bit announcement that never reaches full count (one rank
    stops submitting) trips the HVD_TPU_COLLECTIVE_TIMEOUT_SEC sweep with
    the tensor's NAME in the error — the deadline sweep covers the
    integer-keyed pending table too, not just message_table."""
    from horovod_tpu.runner import run_command

    code = (
        "import numpy as np, sys, time, horovod_tpu as hvd\n"
        "from horovod_tpu.common import (CollectiveTimeoutError,\n"
        "                                HorovodInternalError)\n"
        "hvd.init()\n"
        "for s in range(3):  # warm the cache on every rank\n"
        "    hvd.allreduce(np.ones(8, np.float32), average=False,\n"
        "                  name='half')\n"
        "if hvd.rank() == 0:\n"
        "    try:\n"
        "        hvd.allreduce(np.ones(8, np.float32), average=False,\n"
        "                      name='half')\n"
        "        sys.exit(9)\n"
        "    except CollectiveTimeoutError as e:\n"
        "        assert 'half' in str(e), str(e)\n"
        "        sys.exit(0)\n"
        "else:\n"
        "    time.sleep(12)  # stay alive so liveness stays green\n"
    )
    results = run_command(
        [sys.executable, "-c", code], 2,
        env=_env(HVD_TPU_COLLECTIVE_TIMEOUT_SEC="3"),
        timeout=60.0, capture=True)
    by_rank = {r.rank: r for r in results}
    assert by_rank[0].returncode == 0, \
        (by_rank[0].returncode, by_rank[0].stderr[-800:])


# ---------------------------------------------------------------------------
# In-process units: config knobs and the plane-side LRU bounds.
# ---------------------------------------------------------------------------


def test_config_cache_knobs(monkeypatch):
    from horovod_tpu.common.config import Config

    monkeypatch.delenv("HVD_TPU_RESPONSE_CACHE", raising=False)
    monkeypatch.delenv("HVD_TPU_CACHE_CAPACITY", raising=False)
    cfg = Config.from_env()
    assert cfg.response_cache is True
    assert cfg.cache_capacity == 1024
    assert cfg.effective_cache_capacity == 1024
    monkeypatch.setenv("HVD_TPU_RESPONSE_CACHE", "0")
    monkeypatch.setenv("HVD_TPU_CACHE_CAPACITY", "64")
    cfg = Config.from_env()
    assert cfg.response_cache is False
    assert cfg.cache_capacity == 64
    assert cfg.effective_cache_capacity == 0  # kill switch wins
    # HVD_TPU_CYCLE_TIME_MS is the documented spelling and wins.
    monkeypatch.setenv("HVD_TPU_CYCLE_TIME", "7.0")
    monkeypatch.setenv("HVD_TPU_CYCLE_TIME_MS", "2.5")
    assert Config.from_env().cycle_time_ms == 2.5


def test_jit_cache_lru_bound(monkeypatch):
    """_jit_for keeps at most _JIT_CACHE_CAPACITY compiled entries,
    evicting least-recently-used (the compile cache used to grow without
    bound under a ragged shape stream)."""
    pytest.importorskip("jax")
    from horovod_tpu.jax import eager_mesh

    monkeypatch.setattr(eager_mesh, "_JIT_CACHE_CAPACITY", 4)
    plane = eager_mesh.XlaDataPlane.__new__(eager_mesh.XlaDataPlane)
    plane._fns = __import__("collections").OrderedDict()
    plane._out_sharding = None  # jax.jit is lazy: never traced here
    for length in range(10):
        plane._jit_for("ar", length, np.float32)
    assert len(plane._fns) == 4
    assert [k[1] for k in plane._fns] == [6, 7, 8, 9]
    plane._jit_for("ar", 7, np.float32)  # LRU touch
    plane._jit_for("ar", 99, np.float32)  # evicts 6 (oldest), not 7
    assert ("ar", 7, np.dtype(np.float32).str, 0) in plane._fns
    assert ("ar", 6, np.dtype(np.float32).str, 0) not in plane._fns


def test_plane_meta_cache_update_semantics():
    """_meta_update: insert-only and immutable — entries fill in dispatch
    order up to capacity, are never churn-evicted or re-hashed in place
    (rank-local eviction/refresh timing could split a consistent job into
    cached/uncached camps), and allgathers never cache (ragged dim0 must
    keep negotiating)."""
    pytest.importorskip("jax")
    import types

    from horovod_tpu.jax import eager_mesh

    plane = eager_mesh.XlaDataPlane.__new__(eager_mesh.XlaDataPlane)
    plane._meta_cache = {}
    plane._meta_capacity = 2
    plane._size = 2

    def op(name, kind="ar", h=7):
        return types.SimpleNamespace(name=name, kind=kind, my_hash=h)

    plane._meta_update(op("a"))
    plane._meta_update(op("g", kind="ag"))  # never cached
    plane._meta_update(op("b"))
    assert plane._meta_cache == {"a": 7, "b": 7}
    plane._meta_update(op("c"))  # at capacity: no insert, no eviction
    assert plane._meta_cache == {"a": 7, "b": 7}
    plane._meta_update(op("a", h=9))  # immutable: no in-place re-hash
    assert plane._meta_cache["a"] == 7
    plane._meta_cache.pop("a")  # per-name error eviction re-opens the slot
    plane._meta_update(op("c", h=5))
    assert plane._meta_cache == {"b": 7, "c": 5}


# ---------------------------------------------------------------------------
# Elastic membership interplay (docs/fault-tolerance.md#elastic-membership):
# the reshape barrier clears the cache and autotune search on every rank so
# slot numbering and tuned params stay lockstep in the new membership.
# ---------------------------------------------------------------------------


_RESHAPE_CACHE_SCRIPT = """\
import numpy as np
import horovod_tpu as hvd

hvd.init()
state = hvd.ElasticState(step=0)
marks = {}

def train(state):
    if hvd.membership_epoch() > 0 and "at_reshape" not in marks:
        marks["at_reshape"] = hvd.metrics_snapshot()["cache"]["engine"]
    while state.step < 30:
        for k in range(3):
            out = hvd.allreduce(np.full(16, 1.0, np.float32),
                                average=False, name=f"steady.{k}")
            assert np.allclose(out, float(hvd.size())), (out[0], hvd.size())
        state.step += 1
    return True

hvd.run_elastic(train, state)
m = hvd.metrics_snapshot()["membership"]
assert m["epoch"] == 1 and m["ranks_lost"] == [2], m
at = marks["at_reshape"]
end = hvd.metrics_snapshot()["cache"]["engine"]
# Counters are process-cumulative; contents were cleared at the barrier,
# so the new membership re-negotiates the 3 names once (misses) and then
# rides slot-bit hits again -- the cache re-warms instead of staying
# poisoned with pre-reshape slot numbering.
hits = end["hits"] - at["hits"]
misses = end["misses"] - at["misses"]
assert misses >= 3, (at, end)
assert hits >= 30, (at, end)
assert hits / max(hits + misses, 1) >= 0.7, (at, end)
assert end["size"] >= 3, end
print("CACHEOK", hvd.rank(), hits, misses, flush=True)
"""


def test_cache_rewarms_after_reshape(tmp_path):
    """PR-4 interplay: a crash mid-cached-steady-state on an elastic job
    reshapes instead of aborting; the response cache is cleared at the
    barrier on every survivor and re-warms in the new membership (fresh
    misses once, then steady hits)."""
    from horovod_tpu.runner import membership_succeeded, run_membership

    script = tmp_path / "train.py"
    script.write_text(_RESHAPE_CACHE_SCRIPT)
    # rank 2's ops: 1 entry-sync broadcast, then 3 per step -> op 31 is
    # mid-steady-state (step 10 of 30), well after the cache warmed.
    results = run_membership(
        [sys.executable, str(script)], 3, min_np=2, max_np=3,
        max_rejoins=0,
        env=_env(HVD_TPU_FAULT_SPEC="rank=2:crash@op=31",
                 HVD_TPU_COLLECTIVE_TIMEOUT_SEC="20"),
        timeout=90.0, capture=True, report=lambda msg: None)
    assert membership_succeeded(results, 2), \
        [(r.rank, r.returncode, r.stderr[-400:]) for r in results]
    oks = [line for r in results if r.returncode == 0
           for line in r.stdout.splitlines() if line.startswith("CACHEOK")]
    assert len(oks) == 2, results


_RESHAPE_AUTOTUNE_SCRIPT = """\
import time
import numpy as np
import horovod_tpu as hvd

hvd.init()
assert hvd.autotune_report()["enabled"]
state = hvd.ElasticState(step=0)
marks = {}

def train(state):
    if hvd.membership_epoch() > 0 and "applied" not in marks:
        marks["applied"] = len(hvd.autotune_report()["applied"])
    while state.step < 80:
        for k in range(3):
            hvd.allreduce(np.full(256, 1.0, np.float32),
                          average=False, name=f"tune.{k}")
        state.step += 1
        time.sleep(0.005)
    return True

hvd.run_elastic(train, state)
rep = hvd.autotune_report()
# The tuner restarted at the barrier and re-broadcast parameters in the
# new membership...
assert len(rep["applied"]) > marks["applied"], (marks, rep["applied"])
# ...and every survivor applied them in lockstep.
mine = np.asarray([rep["fusion_threshold"],
                   int(rep["cycle_time_ms"] * 1000)], np.int64)
rows = hvd.allgather(mine.reshape(1, -1), name="tune.check")
assert (rows == rows[0]).all(), rows
print("TUNEOK", hvd.rank(), len(rep["applied"]), flush=True)
"""


def test_autotune_rebroadcasts_after_reshape(tmp_path):
    """PR-5 interplay: after a reshape the autotune search resets and its
    parameter broadcasts resume in the new membership -- autotune_report()
    shows fresh applied entries, lockstep-identical across survivors."""
    from horovod_tpu.runner import membership_succeeded, run_membership

    script = tmp_path / "train.py"
    script.write_text(_RESHAPE_AUTOTUNE_SCRIPT)
    results = run_membership(
        [sys.executable, str(script)], 3, min_np=2, max_np=3,
        max_rejoins=0,
        env=_env(HVD_TPU_FAULT_SPEC="rank=1:crash@op=13",
                 HVD_TPU_COLLECTIVE_TIMEOUT_SEC="20",
                 HVD_TPU_AUTOTUNE="1", HVD_TPU_AUTOTUNE_WARMUP="1",
                 HVD_TPU_AUTOTUNE_WINDOW="8"),
        timeout=90.0, capture=True, report=lambda msg: None)
    assert membership_succeeded(results, 2), \
        [(r.rank, r.returncode, r.stderr[-400:]) for r in results]
    oks = [line for r in results if r.returncode == 0
           for line in r.stdout.splitlines() if line.startswith("TUNEOK")]
    assert len(oks) == 2, results
