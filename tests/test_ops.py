"""Attention kernel tests: flash/blockwise vs the dense reference, and
ring attention (sequence parallel over the virtual 8-device mesh) vs the
full-sequence result — values and gradients."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.ops import (blockwise_attention, flash_attention,
                             mha_reference, ring_attention)

# The train.py wrapper translates the check_vma/check_rep kwarg rename
# across jax versions (CI min-versions leg).
from horovod_tpu.jax.train import shard_map


def _qkv(batch=2, heads=2, seq=256, d=64, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (batch, heads, seq, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_reference(causal):
    q, k, v = _qkv(seq=192, d=32)
    want = mha_reference(q, k, v, causal=causal)
    got = blockwise_attention(q, k, v, causal=causal, block_size=64)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _qkv(seq=256, d=64)
    want = mha_reference(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_flash_ragged_tail_falls_back():
    q, k, v = _qkv(seq=100, d=32)  # not a multiple of the block size
    want = mha_reference(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_flash_gradients_match_reference():
    q, k, v = _qkv(seq=128, d=32)

    def loss_ref(q, k, v):
        return (mha_reference(q, k, v, causal=True) ** 2).sum()

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=True) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bshd_layout_matches(causal):
    """layout='bshd' ((b, s, h, d), the transpose-free model path) matches
    the reference in values and gradients, including the multi-block
    grid."""
    q, k, v = _qkv(seq=256, d=64, seed=5)

    def loss_ref(q, k, v):
        return (mha_reference(q, k, v, causal=causal) ** 2).sum()

    def loss_bshd(q, k, v):
        t = lambda a: a.transpose(0, 2, 1, 3)  # noqa: E731
        out = flash_attention(t(q), t(k), t(v), causal=causal,
                              block_q=128, block_k=128, layout="bshd")
        return (t(out) ** 2).sum()

    np.testing.assert_allclose(
        jax.jit(loss_bshd)(q, k, v), loss_ref(q, k, v), rtol=1e-5)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_bshd = jax.grad(loss_bshd, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_bshd, g_ref):
        np.testing.assert_allclose(a, b, atol=1e-3, rtol=1e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_multiblock(causal):
    """Multi-block grid (seq 384 / block 128): exercises the Pallas
    backward's scratch accumulation across grid steps and, for causal, the
    above-diagonal block pruning."""
    q, k, v = _qkv(seq=384, d=64, seed=3)

    def loss_ref(q, k, v):
        return (mha_reference(q, k, v, causal=causal) ** 2).sum()

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=causal,
                                block_q=128, block_k=128) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b, atol=1e-3, rtol=1e-3)


def test_bwd_plan_matches_vmem_calibration():
    """The backward block plan must reproduce the v5e scoped-VMEM compile
    sweep (r5 calibration, tools/vmem_sweep.py, docs/benchmarks.md): the
    combined kernel's viability depends on sequence rows, head width AND
    the batch*heads grid dim (measured non-monotonic), so the plan bands
    are pinned exactly.  The r4 regression — tuned 1024-blocks that
    failed TPU compilation at seq 8192 — is the class of change this
    catches."""
    from horovod_tpu.ops.attention import _bwd_plan

    # bench-protocol shapes (token-constant seq:batch sweep)
    assert _bwd_plan(1024, 64, 1024, 1024, 128) == ("combined", 1024, 1024)
    assert _bwd_plan(2048, 64, 1024, 1024, 64) == ("combined", 1024, 1024)
    assert _bwd_plan(4096, 64, 1024, 1024, 32) == ("combined", 512, 1024)
    assert _bwd_plan(8192, 64, 1024, 1024, 16) == ("combined", 512, 512)
    assert _bwd_plan(16384, 64, 1024, 1024, 8)[0] == "split"
    # the bh frontier at seq 8192 (bh=64 measured 0.17 MiB over limit)
    assert _bwd_plan(8192, 64, 1024, 1024, 32)[0] == "combined"
    assert _bwd_plan(8192, 64, 1024, 1024, 64)[0] == "split"
    # bands never extrapolate past their calibrated bh bound
    assert _bwd_plan(1024, 64, 1024, 1024, 2048)[0] == "split"
    assert _bwd_plan(4096, 64, 1024, 1024, 1024)[0] == "split"
    # wide heads never take the combined kernel (d=256 measured failing
    # at seq 1024/bh 64 where the d=64 lane-equivalent passes)
    assert _bwd_plan(2048, 128, 1024, 1024, 16)[0] == "combined"
    assert _bwd_plan(8192, 128, 1024, 1024, 16) == ("combined", 512, 512)
    assert _bwd_plan(1024, 256, 1024, 1024, 64)[0] == "split"
    assert _bwd_plan(4096, 256, 1024, 1024, 16)[0] == "split"
    assert _bwd_plan(32768, 128, 1024, 1024, 8)[0] == "split"
    # plan blocks must divide the sequence even for non-pow2 lengths
    mode, bq, bk = _bwd_plan(11520, 64, 1024, 1024, 8)
    assert 11520 % bq == 0 and 11520 % bk == 0


def test_bwd_plan_fits_vmem_budget(monkeypatch):
    """Every plan the block selection emits must fit the COMPUTED
    scoped-VMEM estimate — the backstop behind the calibrated bands
    (the BENCH_r04 seq-8192 OOM was a tuned block choice whose scoped
    footprint nobody computed).  Long-context shapes 8192/16384 are the
    regression region."""
    import horovod_tpu.ops.attention as attn

    for seq in (8192, 16384):
        for d in (64, 128, 256):
            for bh in (8, 16, 32, 64, 256):
                mode, bq, bk = attn._bwd_plan(seq, d, 1024, 1024, bh)
                assert seq % bq == 0 and seq % bk == 0
                assert (attn._plan_vmem_bytes(mode, seq, d, bq, bk)
                        <= attn._vmem_budget_bytes()), (seq, d, bh, mode)
    # The measured r04 failure (combined 1024-blocks at seq 8192:
    # 23.2 MiB) must score over the default 16 MiB budget — the estimate
    # is only a guard if it rejects the shape that actually OOMed.
    assert (attn._plan_vmem_bytes("combined", 8192, 64, 1024, 1024)
            > attn._vmem_budget_bytes())
    # A shrunken budget clamps (with a warning) instead of handing
    # Mosaic a plan that cannot compile; 8 MiB cannot hold seq-8192
    # combined's whole-seq dq at ANY block size, so it demotes to split.
    monkeypatch.setenv("HVD_TPU_VMEM_LIMIT_MB", "8")
    with pytest.warns(UserWarning, match="scoped-VMEM"):
        mode, bq, bk = attn._bwd_plan(8192, 64, 1024, 1024, 16)
    assert mode == "split"
    assert (attn._plan_vmem_bytes(mode, 8192, 64, bq, bk)
            <= attn._vmem_budget_bytes())
    monkeypatch.delenv("HVD_TPU_VMEM_LIMIT_MB")
    # The forward guard: explicit oversized blocks clamp to fitting ones
    # instead of compiling a >budget kernel.
    assert (attn._fwd_vmem_bytes(8192, 64, 8192, 1024)
            > attn._vmem_budget_bytes())
    with pytest.warns(UserWarning, match="clamped"):
        fitted = attn._clamp_blocks(
            "forward", 8192, 64, 8192, 1024,
            estimate=lambda _m, s, dd, a, b:
                attn._fwd_vmem_bytes(s, dd, a, b))
    assert fitted is not None
    assert attn._fwd_vmem_bytes(8192, 64, *fitted) <= attn._vmem_budget_bytes()


@pytest.mark.parametrize("d", [64, 128])
@pytest.mark.parametrize("seq", [1024, 4096, 8192, 16384])
def test_flash_bwd_seq_sweep_compiles(seq, d):
    """The documented long-context sweep {1k, 4k, 8k, 16k} x head_dim
    {64, 128} must COMPILE for fwd+bwd at the bench-protocol batch
    (token-constant seq:batch pairs — batch*heads feeds _bwd_plan's bh
    frontier) — AOT on a real TPU (catches scoped-VMEM OOM, the r4
    failure), abstract trace elsewhere (catches block/shape mismatches
    in the plan routing)."""
    batch = {1024: 16, 4096: 4, 8192: 2, 16384: 1}[seq]
    q = jnp.zeros((batch, 8, seq, d), jnp.bfloat16)

    def loss(q, k, v):
        return flash_attention(q, k, v, causal=True,
                               interpret=jax.default_backend() != "tpu"
                               ).astype(jnp.float32).sum()

    g = jax.grad(loss, argnums=(0, 1, 2))
    if jax.default_backend() == "tpu":
        jax.jit(g).lower(q, q, q).compile()  # real Mosaic compile
    else:
        jax.eval_shape(g, q, q, q)  # trace-only: plan/blocks consistency


def test_flash_split_backward_matches(monkeypatch):
    """The split dkdv/dq kernel pair (long-seq path) must match the
    blockwise gradients — forced via the plan so it runs at test sizes."""
    import horovod_tpu.ops.attention as attn

    monkeypatch.setattr(attn, "_bwd_plan",
                        lambda q_len, d, bq, bk, bh=1: ("split", 128, 128))
    q, k, v = _qkv(seq=384, d=64, seed=5)

    def loss_ref(q, k, v):
        return (blockwise_attention(q, k, v, causal=True) ** 2).sum()

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=True,
                                block_q=128, block_k=128) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b, atol=1e-3, rtol=1e-3)


def test_flash_nonpow2_scale_matches_reference():
    """head_dim 96: sm_scale is not a power of two — the pow2/residual
    scale split must keep full f32 logit accuracy (ADVICE r4: the old
    single pre-scale rounded q to bf16 under a non-representable
    scale)."""
    q, k, v = _qkv(seq=256, d=96, seed=7)
    want = mha_reference(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)

    def loss_ref(q, k, v):
        return (mha_reference(q, k, v, causal=True) ** 2).sum()

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=True,
                                block_q=128, block_k=128) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b, atol=1e-3, rtol=1e-3)


def _ring_apply(fn, q, k, v, mesh, axis):
    spec = P(None, None, axis, None)  # shard the sequence dimension
    return jax.jit(shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec))(q, k, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    devices = jax.devices()
    assert len(devices) >= 8, "conftest forces an 8-device CPU platform"
    mesh = Mesh(np.array(devices[:8]), ("sp",))
    q, k, v = _qkv(batch=1, heads=2, seq=8 * 32, d=16)
    want = mha_reference(q, k, v, causal=causal)
    got = _ring_apply(
        functools.partial(ring_attention, axis_name="sp", causal=causal),
        q, k, v, mesh, "sp")
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_ring_attention_gradients():
    devices = jax.devices()
    mesh = Mesh(np.array(devices[:4]), ("sp",))
    q, k, v = _qkv(batch=1, heads=1, seq=4 * 16, d=8)
    spec = P(None, None, "sp", None)

    def ring_loss(q, k, v):
        out = shard_map(
            functools.partial(ring_attention, axis_name="sp", causal=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)(q, k, v)
        return (out ** 2).sum()

    def ref_loss(q, k, v):
        return (mha_reference(q, k, v, causal=True) ** 2).sum()

    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_rdma_ring_permute_values_and_grad():
    """ops.rdma.ring_permute (Pallas async remote copy) matches
    lax.ppermute's shift rotation in value and VJP on the virtual mesh
    (interpret-mode remote DMA)."""
    from horovod_tpu.ops.rdma import ring_permute

    devices = jax.devices()
    mesh = Mesh(np.array(devices[:4]), ("r",))
    x = jnp.arange(4 * 8 * 128, dtype=jnp.float32).reshape(4, 8, 128)
    spec = P("r", None, None)

    def rotated(x, shift):
        return jax.jit(shard_map(
            lambda t: ring_permute(t, "r", shift=shift),
            mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False))(x)

    np.testing.assert_array_equal(rotated(x, 1), np.roll(x, 1, axis=0))
    np.testing.assert_array_equal(rotated(x, -1), np.roll(x, -1, axis=0))

    # VJP: d/dx sum(w * rotate(x)) == rotate_back(w).
    w = jnp.asarray(np.random.RandomState(0).rand(4, 8, 128), jnp.float32)

    def loss(x):
        rotated = jax.jit(shard_map(
            lambda t: ring_permute(t, "r"), mesh=mesh, in_specs=spec,
            out_specs=spec, check_vma=False))(x)
        return (rotated * w).sum()

    g = jax.grad(loss)(x)
    np.testing.assert_allclose(g, np.roll(w, -1, axis=0), rtol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_rdma_rotate_matches(causal):
    """ring_attention(rotate_impl='rdma') — K/V rotation as raw Pallas
    remote DMAs — matches the dense reference in value and gradient.
    (check_vma=False: interpret-mode pallas does not propagate the
    varying-manual-axes annotation through its internals.)"""
    devices = jax.devices()
    mesh = Mesh(np.array(devices[:4]), ("sp",))
    q, k, v = _qkv(batch=1, heads=2, seq=4 * 32, d=16)
    want = mha_reference(q, k, v, causal=causal)
    spec = P(None, None, "sp", None)
    fn = functools.partial(ring_attention, axis_name="sp", causal=causal,
                           rotate_impl="rdma")
    got = jax.jit(shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec, check_vma=False))(q, k, v)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)

    def ring_loss(q, k, v):
        out = shard_map(
            fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)(q, k, v)
        return (out ** 2).sum()

    def ref_loss(q, k, v):
        return (mha_reference(q, k, v, causal=causal) ** 2).sum()

    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_rdma_phase_alternates_through_backward(monkeypatch):
    """The barrier-namespace discipline of ring_permute (rdma.py): within
    each DEPENDENCY CHAIN of rotations (ring_attention's K stream, and
    its V stream) the phase sequence must strictly alternate across the
    whole autodiff-composed program — forward, backward (the VJP flips
    within the chain pair), and the fwd/bwd seam — while the two
    independent chains use DISJOINT namespace pairs, so a lagging
    device's ready-wait can never be satisfied by a signal from either
    its chain's next invocation or the concurrently-scheduled other
    chain.  (The old single-pair global-alternation scheme asserted on
    jax's tracing order, which current jax no longer interleaves: custom
    VJP transposes now trace grouped per cotangent chain.)"""
    import horovod_tpu.ops.rdma as rdma

    phases = []
    real_raw = rdma._ring_permute_raw

    def recording_raw(x, axis_name, shift, interpret, phase):
        phases.append(phase % 4)
        return real_raw(x, axis_name, shift, interpret, phase)

    monkeypatch.setattr(rdma, "_ring_permute_raw", recording_raw)

    devices = jax.devices()
    mesh = Mesh(np.array(devices[:4]), ("sp",))
    q, k, v = _qkv(batch=1, heads=1, seq=4 * 16, d=8)
    spec = P(None, None, "sp", None)
    fn = functools.partial(ring_attention, axis_name="sp", causal=False,
                           rotate_impl="rdma")

    def ring_loss(q, k, v):
        out = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec, check_vma=False)(q, k, v)
        return (out ** 2).sum()

    jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    # Two chains (phase // 2), each recorded over forward AND backward
    # (3 fwd + 3 bwd rotations per chain on a 4-device ring).
    chains = {0: [], 1: []}
    for p in phases:
        chains[p // 2].append(p % 2)
    assert len(chains[0]) >= 4 and len(chains[1]) >= 4, phases
    # Within a chain, trace order follows the dependency chain (each
    # rotation consumes the previous one's output — forward — and each
    # transpose the next one's cotangent — backward), so the recorded
    # per-chain stream is the execution-order stream: it must strictly
    # alternate, seam included.
    for chain, stream in chains.items():
        for a, b in zip(stream, stream[1:]):
            assert a != b, (
                f"chain {chain}: adjacent invocations share a namespace: "
                f"{phases}")
    # Distinct chains map to disjoint collective_id namespaces.
    ids = {c: {rdma._COLLECTIVE_IDS[2 * c + p] for p in stream}
           for c, stream in chains.items()}
    assert not (ids[0] & ids[1]), ids


def test_blockwise_offsets_compose():
    """Shifted-window blockwise calls (the ring building block) agree with
    one global causal call."""
    q, k, v = _qkv(batch=1, heads=1, seq=64, d=16)
    full = blockwise_attention(q, k, v, causal=True, block_size=16)
    # Second half of queries attending over both halves of keys, via two
    # offset calls merged by hand is exactly what ring_attention does; here
    # just check the offset mask itself.
    got = blockwise_attention(q[:, :, 32:], k, v, causal=True,
                              block_size=16, q_offset=32, k_offset=0)
    np.testing.assert_allclose(got, full[:, :, 32:], atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_fused_ring_flash_matches_dense(causal):
    """VERDICT r2 #3: the fused ring-flash kernel (rotation DMA inside the
    Pallas program, per-step flash + lse merge) matches the dense
    reference in value AND gradient on the virtual mesh (interpret-mode
    remote DMA), for both causal and dense masks."""
    devices = jax.devices()
    mesh = Mesh(np.array(devices[:4]), ("sp",))
    q, k, v = _qkv(batch=1, heads=2, seq=4 * 32, d=16)
    want = mha_reference(q, k, v, causal=causal)
    spec = P(None, None, "sp", None)
    fn = functools.partial(ring_attention, axis_name="sp", causal=causal,
                           rotate_impl="fused")
    got = jax.jit(shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec, check_vma=False))(q, k, v)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)

    def ring_loss(q, k, v):
        out = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec, check_vma=False)(q, k, v)
        return (out ** 2).sum()

    def ref_loss(q, k, v):
        return (mha_reference(q, k, v, causal=causal) ** 2).sum()

    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_fused_ring_flash_oversized_shard_falls_back(monkeypatch):
    """Local shards whose combined-backward VMEM plan cannot compile must
    route to the separable ppermute ring INSTEAD of failing at Mosaic
    compile time on the backward pass (ADVICE r4: the old predicate only
    checked block divisibility).  Forced via the plan so it runs at test
    sizes; the fallback must still match the dense reference."""
    import importlib

    import horovod_tpu.ops.ring_flash as rf

    # The package re-exports the function under the same name as the
    # module, so fetch the module itself for monkeypatching.
    ra_mod = importlib.import_module("horovod_tpu.ops.ring_attention")

    # ring_flash binds _bwd_plan by value at import; patch its binding.
    monkeypatch.setattr(rf, "_bwd_plan", lambda *a: ("split", 128, 128))
    calls = []
    real_ring = ra_mod.ring_attention

    def recording_ring(*args, **kw):
        calls.append(kw.get("rotate_impl"))
        return real_ring(*args, **kw)

    monkeypatch.setattr(ra_mod, "ring_attention", recording_ring)

    devices = jax.devices()
    mesh = Mesh(np.array(devices[:4]), ("sp",))
    q, k, v = _qkv(batch=1, heads=2, seq=4 * 32, d=16)
    spec = P(None, None, "sp", None)
    fn = functools.partial(rf.fused_ring_attention, axis_name="sp",
                           causal=True)
    got = jax.jit(shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec, check_vma=False))(q, k, v)
    assert calls == ["ppermute"], calls  # fused path declined, separable ran
    want = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


@pytest.mark.slow  # ~15s; ring-flash numerics stay tier-1 in
# test_fused_ring_flash_matches_dense
def test_ring_flash_phase_stream_alternates(monkeypatch):
    """The fused ring kernels' barrier-namespace stream (collective_ids
    15/16, ops/ring_flash.py) must strictly alternate across the WHOLE
    fwd+bwd program AND across re-executions of the same jitted step —
    the rdma.py invariant (mirror of
    test_rdma_phase_alternates_through_backward).  Checks both the pure
    schedule (_rotation_phases: closer appended whenever a pass's
    rotating count is odd) and the wiring (the phases the step functions
    actually receive during an autodiff-composed run)."""
    import horovod_tpu.ops.ring_flash as rf

    # Pure schedule: for every ring size, one pass's barrier stream
    # (rotating steps + optional closer on 1) has even length and
    # alternates, so any concatenation of passes alternates cyclically.
    for n in range(2, 9):
        phases, needs_closer = rf._rotation_phases(n)
        stream = phases + ([1] if needs_closer else [])
        assert len(stream) % 2 == 0, (n, stream)
        for a, b in zip(stream, stream[1:]):
            assert a != b, (n, stream)
        assert not stream or stream[0] == 0, (n, stream)

    # Wiring: record the phases the rotating step kernels are invoked
    # with through a full forward+backward on a 4-device ring.
    events = []
    real_fwd, real_bwd = rf._ring_flash_step, rf._bwd_ring_step

    def rec_fwd(*args, **kw):
        if kw["rotate"]:
            events.append(("fwd", kw["phase"]))
        return real_fwd(*args, **kw)

    def rec_bwd(*args, **kw):
        if kw["rotate"]:
            events.append(("bwd", kw["phase"]))
        return real_bwd(*args, **kw)

    monkeypatch.setattr(rf, "_ring_flash_step", rec_fwd)
    monkeypatch.setattr(rf, "_bwd_ring_step", rec_bwd)

    devices = jax.devices()
    mesh = Mesh(np.array(devices[:4]), ("sp",))
    q, k, v = _qkv(batch=1, heads=2, seq=4 * 32, d=16)
    spec = P(None, None, "sp", None)
    fn = functools.partial(rf.fused_ring_attention, axis_name="sp",
                           causal=True)

    def loss(q, k, v):
        out = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec, check_vma=False)(q, k, v)
        return (out ** 2).sum()

    jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    want = rf._rotation_phases(4)[0]
    got_fwd = [p for kind, p in events if kind == "fwd"]
    got_bwd = [p for kind, p in events if kind == "bwd"]
    assert got_fwd == want, events
    assert got_bwd == want, events


def test_fused_ring_flash_bf16_and_uneven_heads():
    """Fused ring flash in bf16 with several heads stays close to the f32
    dense reference (bf16 tolerance), exercising the merge in the
    kernel's production dtype."""
    devices = jax.devices()
    mesh = Mesh(np.array(devices[:4]), ("sp",))
    q, k, v = _qkv(batch=2, heads=3, seq=4 * 16, d=32)
    qb, kb, vb = (t.astype(jnp.bfloat16) for t in (q, k, v))
    want = mha_reference(q, k, v, causal=True)
    spec = P(None, None, "sp", None)
    fn = functools.partial(ring_attention, axis_name="sp", causal=True,
                           rotate_impl="fused")
    got = jax.jit(shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec, check_vma=False))(qb, kb, vb)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32), want,
                               atol=3e-2, rtol=3e-2)
