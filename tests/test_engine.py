"""Multi-process engine tests: the negotiation protocol, ring data plane,
tensor fusion, and the negative paths (cross-rank shape/dtype/op mismatch
must surface as typed Python errors, not hangs).

Mirrors the reference's TF/torch collective test matrix
(/root/reference/test/test_tensorflow.py:40-300,
 /root/reference/test/test_torch.py:60-260), rewritten against the engine's
numpy substrate and run over N real processes via the hvdrun launcher.
"""

import numpy as np
import pytest

from tests.distributed import distributed_test


def _init():
    import horovod_tpu as hvd

    hvd.init()
    return hvd


@distributed_test()
def test_allreduce_sum():
    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    for dtype in (np.float32, np.float64, np.int32, np.int64):
        x = (np.arange(101) + r).astype(dtype)
        out = hvd.allreduce(x, average=False, name=f"sum.{np.dtype(dtype)}")
        want = sum((np.arange(101) + i).astype(dtype) for i in range(n))
        assert np.array_equal(out, want), (r, dtype)


@distributed_test()
def test_allreduce_average():
    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    x = np.full((7, 3), float(r), np.float32)
    out = hvd.allreduce(x, average=True, name="avg")
    want = sum(range(n)) / n
    assert np.allclose(out, want), (r, out[0, 0], want)


@distributed_test()
def test_allreduce_half_precision():
    import ml_dtypes

    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    for dtype, tag in ((np.float16, "f16"), (ml_dtypes.bfloat16, "bf16")):
        x = np.full(64, 0.5 + r, dtype)
        out = hvd.allreduce(x, average=False, name=f"half.{tag}")
        want = sum(0.5 + i for i in range(n))
        assert np.allclose(np.asarray(out, np.float32), want, rtol=1e-2), \
            (r, tag, out[0], want)


@distributed_test()
def test_allreduce_fusion_many_small():
    """100 outstanding named tensors in flight at once -- exercises the
    coordinator's fusion path and the async handle table (the reference's
    test_horovod_allreduce_async_fused, test_torch.py:132)."""
    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    handles = [
        hvd.allreduce_async(np.full(17, float(i + r), np.float32),
                            average=False, name=f"fused.{i}")
        for i in range(100)
    ]
    assert all(isinstance(h.done(), bool) for h in handles)
    for i, h in enumerate(handles):
        out = h.wait()
        want = sum(float(i + j) for j in range(n))
        assert np.allclose(out, want), (r, i)


@distributed_test()
def test_allreduce_large_tensor():
    """Payload whose ring segments exceed kernel socket buffering: all ranks
    send simultaneously, so the data plane must keep draining its recv leg
    while its send leg backs up (full-duplex Exchange), or the ring
    deadlocks."""
    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    x = np.random.RandomState(r).randn(1 << 23).astype(np.float32)  # 32 MiB
    out = hvd.allreduce(x, average=False, name="big")
    want = sum(np.random.RandomState(i).randn(1 << 23).astype(np.float32)
               for i in range(n))
    assert np.allclose(out, want, atol=1e-4), r


@distributed_test()
def test_allgather_variable_dim0():
    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    x = np.full((r + 1, 4), r, np.int32)
    out = hvd.allgather(x, name="gather.var")
    assert out.shape == (sum(i + 1 for i in range(n)), 4)
    off = 0
    for i in range(n):
        assert np.all(out[off:off + i + 1] == i), (r, i)
        off += i + 1


@distributed_test()
def test_broadcast_from_each_root():
    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    for root in range(n):
        x = np.full((5, 2), float(r * 10 + 7), np.float64)
        out = hvd.broadcast(x, root_rank=root, name=f"bcast.{root}")
        assert np.all(out == root * 10 + 7), (r, root)
        # Input of non-root ranks must be left untouched.
        assert np.all(x == r * 10 + 7)


@distributed_test()
def test_allreduce_shape_mismatch_error():
    hvd = _init()
    r = hvd.rank()
    shape = (17, 3) if r == 0 else (17, 2)
    with pytest.raises(ValueError, match="[Mm]ismatched"):
        hvd.allreduce(np.zeros(shape, np.float32), name="badshape")


@distributed_test()
def test_allreduce_dtype_mismatch_error():
    hvd = _init()
    dtype = np.float32 if hvd.rank() == 0 else np.float64
    with pytest.raises(ValueError, match="[Mm]ismatched data types"):
        hvd.allreduce(np.zeros(8, dtype), name="baddtype")


@distributed_test()
def test_mismatched_op_error():
    hvd = _init()
    x = np.zeros((4, 4), np.float32)
    with pytest.raises(ValueError, match="[Mm]ismatched collective"):
        if hvd.rank() == 0:
            hvd.allreduce(x, name="mixedop")
        else:
            hvd.allgather(x, name="mixedop")


@distributed_test()
def test_broadcast_root_mismatch_error():
    hvd = _init()
    with pytest.raises(ValueError, match="root rank"):
        hvd.broadcast(np.zeros(4, np.float32), root_rank=hvd.rank(),
                      name="badroot")


@distributed_test()
def test_allgather_trailing_dim_mismatch_error():
    hvd = _init()
    shape = (2, 3) if hvd.rank() == 0 else (2, 4)
    with pytest.raises(ValueError, match="[Mm]ismatched allgather"):
        hvd.allgather(np.zeros(shape, np.float32), name="badgather")


@distributed_test(np_=2)
def test_two_rank_ring():
    """Smallest nontrivial ring (left and right neighbour are the same
    process, distinct sockets)."""
    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    assert n == 2
    out = hvd.allreduce(np.ones(10, np.float32) * (r + 1), average=False,
                        name="2rank")
    assert np.allclose(out, 3.0)


@distributed_test()
def test_interleaved_order_independent():
    """Ranks enqueue the same tensors in different orders; negotiation must
    still match them up by name without deadlock."""
    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    names = [f"ooo.{i}" for i in range(10)]
    order = names if r % 2 == 0 else list(reversed(names))
    handles = {nm: hvd.allreduce_async(
        np.full(5, float(int(nm.split(".")[1])), np.float32),
        average=False, name=nm) for nm in order}
    for nm in names:
        out = handles[nm].wait()
        assert np.allclose(out, float(int(nm.split(".")[1])) * n), (r, nm)


def _hier_env(local_size):
    """Re-shape this rank's env into `local_size`-sized nodes and enable the
    two-level allreduce, before hvd.init() reads it."""
    import os

    rank = int(os.environ["HVD_TPU_RANK"])
    os.environ["HVD_TPU_LOCAL_SIZE"] = str(local_size)
    os.environ["HVD_TPU_LOCAL_RANK"] = str(rank % local_size)
    os.environ["HOROVOD_HIERARCHICAL_ALLREDUCE"] = "1"


@distributed_test(np_=4)
def test_hierarchical_allreduce_two_nodes():
    """4 ranks as 2 nodes x 2 local: local reduce-scatter -> per-shard
    cross-node exchange -> local allgather must equal the flat ring
    result (the two-level successor of the reference's
    HOROVOD_HIERARCHICAL_ALLREDUCE, operations.cc:1003-1048)."""
    _hier_env(local_size=2)
    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    for i, count in enumerate((1, 7, 1000, 100003)):
        x = (np.arange(count) * 0.01 + r).astype(np.float32)
        out = hvd.allreduce(x, average=False, name=f"hier.{i}")
        want = sum((np.arange(count) * 0.01 + j).astype(np.float32)
                   for j in range(n))
        assert np.allclose(out, want, rtol=1e-5), (r, count)
    # Average + fusion path.
    handles = [hvd.allreduce_async(np.full(11, float(r), np.float32),
                                   average=True, name=f"hier.avg.{i}")
               for i in range(20)]
    for h in handles:
        assert np.allclose(h.wait(), sum(range(n)) / n)
    # Other collectives still ride the flat ring alongside.
    g = hvd.allgather(np.full((1, 2), float(r), np.float32), name="hier.g")
    assert g.shape == (n, 2)


@distributed_test(np_=4)
def test_hierarchical_bad_layout_falls_back():
    """An interleaved (non-contiguous) rank layout must not deadlock: the
    topology agreement makes every rank fall back to the flat ring."""
    import os

    rank = int(os.environ["HVD_TPU_RANK"])
    os.environ["HVD_TPU_LOCAL_SIZE"] = "2"
    # Wrong layout: local_rank = rank // 2 passes the modular check on some
    # ranks only -- exactly the divergence case.
    os.environ["HVD_TPU_LOCAL_RANK"] = str(rank // 2)
    os.environ["HOROVOD_HIERARCHICAL_ALLREDUCE"] = "1"
    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    out = hvd.allreduce(np.full(33, float(r + 1), np.float32),
                        average=False, name="fallback")
    assert np.allclose(out, sum(range(1, n + 1)))


@distributed_test(np_=3)
def test_hierarchical_single_node():
    """All ranks on one node: the cross phase degenerates to nothing and
    the result is a pure local reduce-scatter + allgather."""
    _hier_env(local_size=3)
    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    out = hvd.allreduce(np.full(257, 1.5 * (r + 1), np.float64),
                        average=False, name="hier1")
    assert np.allclose(out, 1.5 * sum(range(1, n + 1)))


def test_stall_warning_printed():
    """Coordinator stall sweep: when a subset of ranks never announces a
    tensor, rank 0 warns with the tensor name and the missing ranks
    (operations.cc:1231-1276 behavior; untested in the reference)."""
    import sys

    from horovod_tpu.runner import run_command

    code = (
        "import os, time, numpy as np, horovod_tpu as hvd\n"
        "hvd.init()\n"
        "if hvd.rank() == 0:\n"
        "    h = hvd.allreduce_async(np.ones(4, np.float32), name='lonely')\n"
        "    time.sleep(3.0)\n"  # > 2x the 1s stall window
        "else:\n"
        "    time.sleep(3.0)\n"
        "    h = hvd.allreduce_async(np.ones(4, np.float32), name='lonely')\n"
        "h.wait()\n"
    )
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, HVD_TPU_STALL_WARNING_SEC="1",
               JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    results = run_command([sys.executable, "-c", code], 2, env=env,
                          timeout=120.0, capture=True)
    assert all(r.returncode == 0 for r in results), \
        [(r.rank, r.stderr[-300:]) for r in results]
    rank0_err = results[0].stderr
    assert "Stalled ops" in rank0_err, rank0_err[-500:]
    assert "lonely" in rank0_err and "missing ranks: 1" in rank0_err


@distributed_test(np_=3)
def test_init_comm_subset():
    """hvd.init(comm=[...]) restricts the job to a rank subset with dense
    renumbering (the reference's init(comm=...) rank-list mode,
    /root/reference/horovod/common/__init__.py:51-62)."""
    import os

    import horovod_tpu as hvd

    launcher_rank = int(os.environ["HVD_TPU_RANK"])
    if launcher_rank == 1:
        return  # not in the subset; must not join
    hvd.init(comm=[0, 2])
    assert hvd.size() == 2
    assert hvd.rank() == (0 if launcher_rank == 0 else 1)
    out = hvd.allreduce(np.full(5, float(launcher_rank), np.float32),
                        average=False, name="subset")
    assert np.allclose(out, 2.0), out  # 0 + 2


@distributed_test(np_=3)
def test_init_comm_mpi4py_style():
    """hvd.init(comm=<communicator>) accepts an mpi4py-style object (the
    reference's second init form, /root/reference/horovod/common/
    __init__.py:51-78): duck-typed Get_size/allgather, each member
    contributing its launcher rank.  The stub stands in for a REORDERED
    subcommunicator (comm rank 0 = launcher rank 2, as
    MPI.Group.Incl([2, 0]) would build): hvd.rank() must equal the
    comm's own rank, so root-only logic stays on the comm's root."""
    import os

    import horovod_tpu as hvd

    launcher_rank = int(os.environ["HVD_TPU_RANK"])
    if launcher_rank == 1:
        return  # not a member of the communicator; must not join
    comm_rank = 0 if launcher_rank == 2 else 1

    class SubComm:  # mpi4py allgather returns values in comm-rank order
        def Get_size(self):
            return 2

        def Get_rank(self):
            return comm_rank

        def allgather(self, value):
            assert value == launcher_rank
            return [2, 0]

    hvd.init(comm=SubComm())
    assert hvd.size() == 2
    assert hvd.rank() == comm_rank
    out = hvd.allreduce(np.full(4, float(launcher_rank), np.float32),
                        average=False, name="mpi4py_subset")
    assert np.allclose(out, 2.0), out  # 0 + 2


def test_timeline_written(tmp_path):
    """Timeline (Chrome tracing) is written on rank 0 when enabled --
    reference aux subsystem /root/reference/horovod/common/timeline.{h,cc}."""
    import json
    import os
    import subprocess
    import sys

    tl = tmp_path / "timeline.json"
    code = (
        "import numpy as np, horovod_tpu as hvd\n"
        "hvd.init()\n"
        "for i in range(3):\n"
        "    hvd.allreduce(np.ones(100, np.float32), name=f'tl.{i}')\n"
        "hvd.allgather(np.ones((2, 2), np.float32), name='tl.g')\n"
        "hvd.shutdown()\n"
    )
    # Pin the TCP engine transport: on a TPU-attached host the site hook
    # re-registers the TPU platform inside the child (overriding
    # JAX_PLATFORMS), and the auto-enabled XLA data plane would record
    # XLA_ALLREDUCE instead of the engine activities asserted below.
    env = dict(os.environ, HOROVOD_TIMELINE=str(tl), JAX_PLATFORMS="cpu",
               HVD_TPU_XLA_DATA_PLANE="0")
    for var in ("HVD_TPU_RANK", "HVD_TPU_SIZE"):
        env.pop(var, None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    subprocess.run([sys.executable, "-c", code], check=True, env=env,
                   timeout=120)
    text = tl.read_text()
    # Chrome-tracing array with trailing comma tolerated by the viewer;
    # complete it for json.loads.
    events = json.loads(text.rstrip().rstrip(",") + "]")
    names = {e.get("name") for e in events}
    assert "ALLREDUCE" in names
    assert "ALLGATHER" in names
    assert "RING_ALLREDUCE" in names or "MEMCPY_IN_FUSION_BUFFER" in names
    pids = {e.get("pid") for e in events}
    assert len(pids) >= 4  # one per tensor name


# ---------------------------------------------------------------------------
# Fault injection: rank death must surface as HorovodInternalError on the
# survivors, never a hang.  The reference's weakest area (SURVEY.md 5.3) --
# its coordinated-shutdown path (operations.cc:1446-1461) was never tested.
# ---------------------------------------------------------------------------


@distributed_test(np_=3, timeout=120.0)
def test_rank_death_before_collective_aborts_survivors():
    """A rank that exits without joining a collective tears the job down:
    the coordinator notices the dead control socket (engine.cc worker-death
    path) and survivors' pending collectives complete with
    HorovodInternalError well inside the stall window."""
    import os

    from horovod_tpu.common import HorovodInternalError

    hvd = _init()
    r = hvd.rank()
    if r == 1:
        os._exit(0)  # simulated crash: no shutdown handshake, sockets drop
    h = hvd.allreduce_async(np.full(64, float(r), np.float32),
                            average=False, name="orphaned")
    with pytest.raises(HorovodInternalError):
        h.wait()


@distributed_test(np_=3, timeout=120.0)
def test_rank_death_mid_allreduce_aborts_survivors():
    """A rank that dies while the ring is moving a large payload breaks the
    neighbour exchange mid-stream; survivors get HorovodInternalError (from
    the failed exchange or the coordinated shutdown, whichever trips
    first), and every LATER collective fails uniformly too instead of
    leaving a half-functional job."""
    import os
    import time

    from horovod_tpu.common import HorovodInternalError

    hvd = _init()
    r = hvd.rank()
    # 64 MB keeps the ring busy for hundreds of ms on loopback, so the
    # killed rank typically dies mid-exchange.
    payload = np.full(16 << 20, float(r), np.float32)
    h = hvd.allreduce_async(payload, average=False, name="doomed")
    if r == 1:
        time.sleep(0.3)  # negotiation (~5ms cycle) done; transfer underway
        os._exit(0)
    # On a fast host the 64 MB ring can outrun the 0.3 s fuse and this
    # first wait legitimately succeeds; the contract under test is that a
    # survivor ERRORS (on this op or the next) and never hangs.
    with pytest.raises(HorovodInternalError):
        h.wait()
        hvd.allreduce(np.zeros(4, np.float32), name="death_sweep")
    # Uniform failure: every subsequent collective must also raise, not
    # hang and not succeed (the job is dead, not degraded).
    with pytest.raises(HorovodInternalError):
        hvd.broadcast(np.zeros(4, np.float32), 0, name="after_death")


@distributed_test(np_=4, timeout=120.0)
def test_leader_death_mid_hierarchical_aborts_all():
    """Killing a rank mid-two-level-allreduce: its node peer's local-ring
    exchange and its cross-ring peers' exchanges fail, the failure
    cascades through the closed topology fds, and every survivor raises
    HorovodInternalError (never hangs); later collectives fail
    uniformly."""
    import os
    import time

    from horovod_tpu.common import HorovodInternalError

    _hier_env(local_size=2)
    hvd = _init()
    r = hvd.rank()
    payload = np.full(16 << 20, float(r), np.float32)
    h = hvd.allreduce_async(payload, average=False, name="hier_doomed")
    if r == 2:  # leader of node 1
        time.sleep(0.3)
        os._exit(0)
    # As above: if the collective outran the fuse, the next one must fail.
    with pytest.raises(HorovodInternalError):
        h.wait()
        hvd.allreduce(np.zeros(4, np.float32), name="hier_sweep")
    with pytest.raises(HorovodInternalError):
        hvd.allgather(np.zeros((1, 2), np.float32), name="hier_after")


@distributed_test(np_=2)
def test_reinit_races_previous_teardown():
    """Back-to-back shutdown -> init cycles with NO pause: a worker's
    reconnect can land in the PREVIOUS engine's listen backlog on rank 0
    (a running non-elastic coordinator never accepts on its control
    listener), where the hello buffers fine and dies with an RST only at
    teardown — while the new init on rank 0 waits for a hello that will
    never arrive.  The init handshake must retry whole (reconnect +
    hello + agreement) instead of failing the job; before that fix this
    loop deadlocked roughly every other run."""
    hvd = _init()
    for cycle in range(4):
        r, n = hvd.rank(), hvd.size()
        out = hvd.allreduce(np.full(64, float(r + 1), np.float32),
                            average=False, name=f"reinit.{cycle}")
        assert abs(out[0] - n * (n + 1) / 2.0) < 1e-5, out[0]
        hvd.shutdown()
        hvd.init()
    hvd.shutdown()
