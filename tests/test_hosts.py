"""Multi-host launcher: placement planning and a live -H run (all slots on
127.0.0.1, which exercises the fixed-port plan without ssh)."""

import subprocess
import sys

import numpy as np
import pytest

from horovod_tpu.common.basics import pick_free_port
from horovod_tpu.runner.hosts import parse_hosts, plan, ssh_command


def test_parse_hosts():
    assert parse_hosts("a:2,b:4") == [("a", 2), ("b", 4)]
    assert parse_hosts("single") == [("single", 1)]
    with pytest.raises(ValueError):
        parse_hosts("a:0")
    with pytest.raises(ValueError):
        parse_hosts("")


def test_plan_contiguous_blocks():
    ps = plan(6, "hostA:2,hostB:4", port_base=50000)
    assert [p.host for p in ps] == ["hostA"] * 2 + ["hostB"] * 4
    assert [p.local_rank for p in ps] == [0, 1, 0, 1, 2, 3]
    assert all(p.local_size == (2 if p.host == "hostA" else 4) for p in ps)
    # Coordinator on the first host; data ports laid out by local rank.
    assert all(p.env["HVD_TPU_COORD"] == "hostA:50000" for p in ps)
    data = ps[0].env["HVD_TPU_DATA"].split(",")
    assert data[0] == "hostA:50001" and data[2] == "hostB:50001"
    assert data[5] == "hostB:50004"
    # Hierarchical layout contract: rank blocks match local ranks.
    for p in ps:
        assert int(p.env["HVD_TPU_RANK"]) == p.rank


def test_plan_partial_last_host():
    ps = plan(3, "a:2,b:4")
    assert [p.host for p in ps] == ["a", "a", "b"]
    assert ps[2].local_size == 1  # only one rank actually landed on b


def test_plan_overcommit_rejected():
    with pytest.raises(ValueError, match="exceeds"):
        plan(5, "a:2,b:2")


def test_plan_merges_duplicate_hosts():
    """Repeated hosts merge their slots (mpirun behavior) instead of
    producing colliding local ranks / data ports."""
    ps = plan(4, "a:2,a:2", port_base=52000)
    assert [p.local_rank for p in ps] == [0, 1, 2, 3]
    assert all(p.local_size == 4 for p in ps)
    data = ps[0].env["HVD_TPU_DATA"].split(",")
    assert len(set(data)) == 4  # all endpoints distinct


def test_ssh_command_quotes_env_and_cds():
    p = plan(2, "remotehost:2", port_base=51000)[1]
    argv = ssh_command(p, ["python", "train.py", "--lr", "0.1"],
                       extra_env={"PYTHONPATH": "/x y"}, cwd="/work dir")
    assert argv[0] == "ssh" and argv[1] == "remotehost"
    assert "HVD_TPU_RANK=1" in argv[2]
    assert "PYTHONPATH='/x y'" in argv[2]
    assert argv[2].startswith("cd '/work dir' 2>/dev/null; ")
    assert "python train.py --lr 0.1" in argv[2]


def _free_port_range(span: int = 501):
    """A port base whose +1..+span offsets (data + XLA-coord layout of
    hosts.plan) are also currently free."""
    import socket

    for _ in range(20):
        base = pick_free_port()
        if base + span > 65535:
            continue
        ok = True
        for off in (1, 500):
            with socket.socket() as s:
                try:
                    s.bind(("127.0.0.1", base + off))
                except OSError:
                    ok = False
                    break
        if ok:
            return base
    raise RuntimeError("no free port range found")


def test_run_hosts_ssh_path_with_fake_ssh(tmp_path, monkeypatch):
    """The remote branch end-to-end: a PATH-shimmed `ssh` executes the
    remote command locally, proving the cd + env-inlining argv actually
    runs a rank.  One rank only: two fake 'hosts' on one machine would
    collide on the per-host data ports, which plan() legitimately reuses
    across distinct hosts."""
    import os
    import stat

    from horovod_tpu.runner import run_hosts

    shim = tmp_path / "ssh"
    # argv: ssh 127.0.0.2 '<remote command>' -> run it like a real ssh
    # would: from $HOME-ish (cd /) with a scrubbed environment, so the
    # assertions can only pass via ssh_command's inlined cd + env exports.
    shim.write_text(
        "#!/bin/sh\nshift\ncd /\nexec env -i PATH=\"$PATH\" sh -c \"$1\"\n")
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{tmp_path}{os.pathsep}" + os.environ["PATH"])

    code = (
        "import os, numpy as np, horovod_tpu as hvd\n"
        "hvd.init()\n"
        "out = hvd.allreduce(np.ones(4, np.float32), average=False,\n"
        "                    name='s')\n"
        "assert np.allclose(out, 1.0), out\n"
        "print('SSH_RANK_OK', hvd.rank(), os.environ['MARKER'],\n"
        "      os.getcwd())\n"
    )
    env = dict(os.environ, MARKER="made-it-through-ssh")
    # 127.0.0.2 is resolvable loopback but not in the is_local set -> ssh.
    results = run_hosts([sys.executable, "-c", code], 1, "127.0.0.2:1",
                        port_base=_free_port_range(), timeout=120.0,
                        capture=True, env=env)
    assert results[0].returncode == 0, results[0].stderr[-400:]
    # The ssh rank got the MARKER env override inlined and cd'd to cwd.
    assert "SSH_RANK_OK 0 made-it-through-ssh" in results[0].stdout
    assert os.path.realpath(os.getcwd()) in results[0].stdout


def test_run_hosts_local_live():
    """-H with every slot on 127.0.0.1: the full fixed-port multi-host path
    minus ssh.  Ranks do one engine allreduce to prove the plan's endpoints
    are mutually consistent."""
    from horovod_tpu.runner import run_hosts

    code = (
        "import numpy as np, horovod_tpu as hvd\n"
        "hvd.init()\n"
        "out = hvd.allreduce(np.ones(8, np.float32) * (hvd.rank() + 1),\n"
        "                    average=False, name='h')\n"
        "assert np.allclose(out, sum(range(1, hvd.size() + 1))), out\n"
        "print('RANK_OK', hvd.rank(), hvd.local_rank(), hvd.local_size())\n"
    )
    port_base = pick_free_port()
    results = run_hosts([sys.executable, "-c", code], 3, "127.0.0.1:3",
                        port_base=port_base, timeout=120.0, capture=True)
    assert all(r.returncode == 0 for r in results), \
        [(r.rank, r.returncode, r.stderr[-500:]) for r in results]
    lines = sorted(r.stdout.strip() for r in results)
    assert lines == ["RANK_OK 0 0 3", "RANK_OK 1 1 3", "RANK_OK 2 2 3"]


def test_plan_tpu_pinning_env():
    """--tpu-pin: every rank's env confines libtpu to the chip matching
    its local_rank (TPU_VISIBLE_CHIPS), with a process grid spanning the
    slice and task-id-ordered process addresses (VERDICT r2 #4 — the TPU
    analogue of visible_device_list = local_rank)."""
    from horovod_tpu.runner.hosts import plan

    placements = plan(8, "hostA:4,hostB:4", port_base=60000, tpu_pin=True)
    for p in placements:
        env = p.env
        assert env["TPU_VISIBLE_CHIPS"] == str(p.local_rank)
        assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,1,1"
        assert env["TPU_PROCESS_BOUNDS"] == "2,4,1"  # 2x2 host grid x 2 hosts
        assert env["CLOUD_TPU_TASK_ID"] == str(p.rank)
        addrs = env["TPU_PROCESS_ADDRESSES"].split(",")
        assert len(addrs) == 8
        # Port clear of engine data ports (60001..60004) and xla (60500).
        host, port = addrs[p.rank].rsplit(":", 1)
        assert host == p.host and int(port) == 60600 + p.local_rank
    # Uneven rank placement cannot be pinned (chip grids are uniform).
    import pytest

    with pytest.raises(ValueError, match="same number of ranks"):
        plan(6, "hostA:4,hostB:4", port_base=60000, tpu_pin=True)
    # Topology override for exotic hosts.
    placements = plan(2, "hostA:1,hostB:1", port_base=60000, tpu_pin=True,
                      tpu_topology="1,1")
    assert placements[0].env["TPU_PROCESS_BOUNDS"] == "1,2,1"


def test_tpu_metadata_multi_rank_per_host(monkeypatch):
    """The pod-slice metadata path supports N ranks per TPU host: global
    rank is host-major (worker_id * local_size + local_rank) and data
    ports offset by local rank (VERDICT r2 #4)."""
    from horovod_tpu.common.basics import resolve_process_set

    for var in ("HVD_TPU_RANK", "HVD_TPU_SIZE", "CLOUD_TPU_TASK_ID",
                "TPU_PROCESS_ADDRESSES"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "tpu-0,tpu-1")
    monkeypatch.setenv("HVD_TPU_LOCAL_RANK", "2")
    monkeypatch.setenv("HVD_TPU_LOCAL_SIZE", "4")
    ps = resolve_process_set()
    assert (ps.rank, ps.size, ps.local_rank, ps.local_size) == (6, 8, 2, 4)
    assert ps.coord_endpoint == "tpu-0:58930"
    assert ps.data_endpoints[6] == "tpu-1:58933"
    assert len(ps.data_endpoints) == 8


def test_tpu_pinned_metadata_path(monkeypatch):
    """CLOUD_TPU_TASK_ID + TPU_PROCESS_ADDRESSES (the env hvdrun --tpu-pin
    exports) resolve rank/size/local geometry without any HVD_TPU_* vars —
    a pinned process manager needs nothing else."""
    from horovod_tpu.common.basics import resolve_process_set

    for var in ("HVD_TPU_RANK", "HVD_TPU_SIZE", "HVD_TPU_LOCAL_RANK",
                "HVD_TPU_LOCAL_SIZE", "TPU_WORKER_ID",
                "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("CLOUD_TPU_TASK_ID", "5")
    monkeypatch.setenv(
        "TPU_PROCESS_ADDRESSES",
        "a:60600,a:60601,a:60602,a:60603,b:60600,b:60601,b:60602,b:60603")
    ps = resolve_process_set()
    assert (ps.rank, ps.size, ps.local_rank, ps.local_size) == (5, 8, 1, 4)
    assert ps.data_endpoints[5] == "b:58932"
