"""Elastic membership tests (docs/fault-tolerance.md#elastic-membership):
shrink-and-continue without process relaunch or checkpoint reload.

The ISSUE acceptance path: a 4-rank CPU job with an injected crash@op
keeps training on the 3 survivors — they agree on the new ``size()==3``
with dense ranks, parameters are allgather-identical after the
root-broadcast resync, and ``metrics_snapshot()["membership"]`` reports
epoch 1 naming the dead rank.  Plus the fast 2-rank shrink-to-1 tier-1
smoke, the standby rejoin (grow) path, the ``hvdrun --min-np`` CLI, and
the in-process units for ``ElasticState``/``run_elastic``/launcher
accounting.  The below-``--min-np`` checkpoint fallback lives in
test_faults.py next to the rest of the restart machinery; the PR-4
cache / PR-5 autotune reshape interplay lives in test_cache.py.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env(**overrides):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.setdefault("HVD_TPU_KILL_GRACE_SEC", "3")
    env.update({k: str(v) for k, v in overrides.items()})
    for var in ("HVD_TPU_RANK", "HVD_TPU_SIZE", "HVD_TPU_COORD",
                "HVD_TPU_DATA", "HVD_TPU_FAULT_SPEC",
                "HVD_TPU_RESTART_EPOCH", "HVD_TPU_ELASTIC",
                "HVD_TPU_MIN_NP", "HVD_TPU_REJOIN",
                "HVD_TPU_NET_FAULT_SPEC", "HVD_TPU_HEARTBEAT_MS",
                "HVD_TPU_HEARTBEAT_MISS"):
        env.setdefault(var, "")
        if not env[var]:
            env.pop(var, None)
    return env


# One re-enterable training script for every elastic test: averaged
# allreduce of ones adds exactly 1.0 per step REGARDLESS of the current
# membership size, so the final weights prove the step count survived the
# reshape; the trailing allgather proves the resync left every member
# (admitted standbys included) bit-identical.
_TRAIN = """\
import os, sys, time
import numpy as np
import horovod_tpu as hvd

TOTAL = int(sys.argv[1])
PAUSE = float(os.environ.get("TEST_STEP_PAUSE") or 0)
hvd.init()
state = hvd.ElasticState(weights=np.zeros(8, np.float32), step=0)

def train(state):
    while state.step < TOTAL:
        s = state.step
        g = np.ones(8, np.float32)
        state.weights = state.weights + hvd.allreduce(
            g, average=True, name=f"grad.{s}")
        state.step = s + 1
        if PAUSE:
            time.sleep(PAUSE)
    return state.weights

w = hvd.run_elastic(train, state)
assert np.allclose(w, float(TOTAL)), (hvd.rank(), w)
# Elastic is single-host: the local identity must track the global one
# through reshapes (a survivor and an admitted standby must never
# collide on local_rank for per-host resources).
assert hvd.local_rank() == hvd.rank(), (hvd.local_rank(), hvd.rank())
assert hvd.local_size() == hvd.size(), (hvd.local_size(), hvd.size())
flat = hvd.allgather(w.reshape(1, -1), name="final.identity")
assert np.allclose(flat, flat[0]), flat
m = hvd.metrics_snapshot()["membership"]
print("MEMBER", hvd.rank(), hvd.size(), m["epoch"], m["size"],
      ",".join(map(str, m["ranks_lost"])) or "-",
      ",".join(map(str, m["ranks_joined"])) or "-", int(w[0]), flush=True)
"""


def _members(results):
    """Parse the MEMBER lines of every clean rank: [(rank, size, epoch,
    size_in_snapshot, lost, joined, w0), ...]."""
    out = []
    for r in results:
        if r.returncode != 0:
            continue
        for line in r.stdout.splitlines():
            if line.startswith("MEMBER "):
                tok = line.split()
                lost = [] if tok[5] == "-" else [int(x) for x in
                                                 tok[5].split(",")]
                joined = [] if tok[6] == "-" else [int(x) for x in
                                                   tok[6].split(",")]
                out.append((int(tok[1]), int(tok[2]), int(tok[3]),
                            int(tok[4]), lost, joined, int(tok[7])))
    return out


# ---------------------------------------------------------------------------
# The acceptance path: 4 ranks shrink to 3 and train to completion.
# ---------------------------------------------------------------------------


def test_shrink_to_three_trains_to_completion(tmp_path):
    """rank=2:crash@op=12 on a 4-rank job: the survivors re-negotiate
    size()==3 with dense ranks at the reshape barrier, resync from rank 0
    by root broadcast (no relaunch, no checkpoint), finish all 30 steps,
    and report membership epoch 1 naming rank 2."""
    from horovod_tpu.common.faults import CRASH_EXIT_CODE
    from horovod_tpu.runner import membership_succeeded, run_membership

    script = tmp_path / "train.py"
    script.write_text(_TRAIN)
    results = run_membership(
        [sys.executable, str(script), "30"], 4, min_np=2, max_np=4,
        max_rejoins=0,
        env=_env(HVD_TPU_FAULT_SPEC="rank=2:crash@op=12",
                 HVD_TPU_COLLECTIVE_TIMEOUT_SEC="20"),
        timeout=90.0, capture=True, report=lambda msg: None)
    by_slot = {r.rank: r for r in results}
    assert by_slot[2].returncode == CRASH_EXIT_CODE, by_slot[2]
    for slot in (0, 1, 3):
        assert by_slot[slot].returncode == 0, \
            (slot, by_slot[slot].returncode, by_slot[slot].stderr[-800:])
    assert membership_succeeded(results, 2)
    members = _members(results)
    assert len(members) == 3, members
    # Dense re-assigned ranks in the new membership: {0, 1, 2}.
    assert sorted(m[0] for m in members) == [0, 1, 2], members
    for rank_now, size_now, epoch, msize, lost, joined, w0 in members:
        assert size_now == 3 and msize == 3, members
        assert epoch == 1, members
        assert lost == [2] and joined == [], members
        assert w0 == 30, members


def test_shrink_to_one_smoke(tmp_path):
    """The fast tier-1 smoke: a 2-rank job loses rank 1 and the
    coordinator finishes the run alone (size()==1)."""
    from horovod_tpu.runner import membership_succeeded, run_membership

    script = tmp_path / "train.py"
    script.write_text(_TRAIN)
    t0 = time.monotonic()
    results = run_membership(
        [sys.executable, str(script), "12"], 2, min_np=1, max_np=2,
        max_rejoins=0,
        env=_env(HVD_TPU_FAULT_SPEC="rank=1:crash@op=6",
                 HVD_TPU_COLLECTIVE_TIMEOUT_SEC="20"),
        timeout=60.0, capture=True, report=lambda msg: None)
    assert time.monotonic() - t0 < 45.0
    assert membership_succeeded(results, 1), \
        [(r.rank, r.returncode, r.stderr[-400:]) for r in results]
    members = _members(results)
    assert len(members) == 1, members
    rank_now, size_now, epoch, msize, lost, joined, w0 = members[0]
    assert (rank_now, size_now, epoch, msize) == (0, 1, 1, 1), members
    assert lost == [1] and w0 == 12, members


# The mid-steady variant of _TRAIN: a FIXED tensor name every step, so
# the response cache repeats one identical negotiation cycle and the
# engine enters the PR-7 steady state (threshold lowered via env below).
# The freeze then lands while ZERO control frames are flowing — only the
# data-plane heartbeat detector can see it (ISSUE 17 tentpole; the
# hvdmodel invariant formerly xfailed as xfail_freeze_eviction).
_STEADY_TRAIN = """\
import os, sys
import numpy as np
import horovod_tpu as hvd

TOTAL = int(sys.argv[1])
hvd.init()
state = hvd.ElasticState(weights=np.zeros(8, np.float32), step=0)

def train(state):
    while state.step < TOTAL:
        g = np.ones(8, np.float32)
        state.weights = state.weights + hvd.allreduce(
            g, average=True, name="grad")
        state.step = state.step + 1
    return state.weights

w = hvd.run_elastic(train, state)
assert np.allclose(w, float(TOTAL)), (hvd.rank(), w)
# Prove the run actually reached steady state before (and after) the
# eviction — otherwise this test degenerates to the plain freeze case.
steady = hvd.metrics_snapshot()["control"]["steady"]
assert steady["entries"] >= 1, steady
flat = hvd.allgather(w.reshape(1, -1), name="final.identity")
assert np.allclose(flat, flat[0]), flat
m = hvd.metrics_snapshot()["membership"]
print("MEMBER", hvd.rank(), hvd.size(), m["epoch"], m["size"],
      ",".join(map(str, m["ranks_lost"])) or "-",
      ",".join(map(str, m["ranks_joined"])) or "-", int(w[0]), flush=True)
"""


@pytest.mark.slow  # ~22s; heartbeat freeze detection stays tier-1 in
# test_nonelastic_freeze_detected_in_heartbeat_time, the steady-state
# revoke+reshape path in test_control_plane's crash-mid-steady test,
# and the freeze-eviction transition is model-checked (hvdmodel quick)
def test_freeze_mid_steady_evicts_and_survivors_match(tmp_path):
    """ISSUE 17 acceptance: 4 ranks deep in steady state (no control
    frames at all), rank 2 SIGSTOPs.  The heartbeat monitors on its beat
    neighbours flag the silence, the coordinator revokes steady and arms
    the reshape barrier, and the 3 survivors finish all steps with
    allgather-identical weights and membership naming rank 2 lost."""
    from horovod_tpu.runner import membership_succeeded, run_membership

    script = tmp_path / "train.py"
    script.write_text(_STEADY_TRAIN)
    t0 = time.monotonic()
    results = run_membership(
        [sys.executable, str(script), "60"], 4, min_np=2, max_np=4,
        max_rejoins=0,
        env=_env(HVD_TPU_FAULT_SPEC="rank=2:freeze@op=30",
                 HVD_TPU_STEADY_THRESHOLD="5",
                 HVD_TPU_HEARTBEAT_MS="100", HVD_TPU_HEARTBEAT_MISS="10",
                 HVD_TPU_COLLECTIVE_TIMEOUT_SEC="20"),
        timeout=90.0, capture=True, report=lambda msg: None)
    assert time.monotonic() - t0 < 75.0
    assert membership_succeeded(results, 3), \
        [(r.rank, r.returncode, r.stderr[-600:]) for r in results]
    by_slot = {r.rank: r for r in results}
    assert by_slot[2].returncode != 0  # frozen, grace-killed
    members = _members(results)
    assert len(members) == 3, members
    assert sorted(m[0] for m in members) == [0, 1, 2], members
    for rank_now, size_now, epoch, msize, lost, joined, w0 in members:
        assert size_now == 3 and msize == 3, members
        assert epoch == 1, members
        assert lost == [2] and joined == [], members
        assert w0 == 60, members


@pytest.mark.slow  # ~19s SIGSTOP liveness path; the shrink contract
# itself stays tier-1 (test_shrink_to_one_smoke / shrink_to_three)
def test_frozen_rank_shrinks_instead_of_fatal_timeout(tmp_path):
    """A SIGSTOP'd rank is caught by the liveness probe AFTER the pending
    collectives have aged past HVD_TPU_COLLECTIVE_TIMEOUT_SEC (the probe
    itself blocked that long) — the armed reshape must win over the
    fatal ST_TIMEOUT sweep in the same tick, or a frozen rank kills an
    elastic job a crashed rank would not."""
    from horovod_tpu.runner import membership_succeeded, run_membership

    script = tmp_path / "train.py"
    script.write_text(_TRAIN)
    t0 = time.monotonic()
    results = run_membership(
        [sys.executable, str(script), "12"], 2, min_np=1, max_np=2,
        max_rejoins=0,
        env=_env(HVD_TPU_FAULT_SPEC="rank=1:freeze@op=6",
                 HVD_TPU_COLLECTIVE_TIMEOUT_SEC="2"),
        timeout=60.0, capture=True, report=lambda msg: None)
    assert time.monotonic() - t0 < 45.0
    assert membership_succeeded(results, 1), \
        [(r.rank, r.returncode, r.stderr[-400:]) for r in results]
    members = _members(results)
    assert len(members) == 1, members
    rank_now, size_now, epoch, msize, lost, joined, w0 = members[0]
    assert (rank_now, size_now, epoch, msize) == (0, 1, 1, 1), members
    assert lost == [1] and w0 == 12, members


# ---------------------------------------------------------------------------
# Grow: a standby registers with the live coordinator and is admitted.
# ---------------------------------------------------------------------------


def test_standby_rejoins_and_grows_back(tmp_path):
    """2-rank job with --max-np 2: rank 1 crashes (shrink to 1), the
    launcher spawns a standby (HVD_TPU_REJOIN=1) that registers with the
    live coordinator and is admitted at the next reshape barrier; both
    the survivor and the admitted standby finish with identical weights
    and the survivor's membership shows the join."""
    from horovod_tpu.runner import membership_succeeded, run_membership

    script = tmp_path / "train.py"
    script.write_text(_TRAIN)
    results = run_membership(
        [sys.executable, str(script), "60"], 2, min_np=1, max_np=2,
        rejoin_delay=0.3,
        env=_env(HVD_TPU_FAULT_SPEC="rank=1:crash@op=10",
                 HVD_TPU_COLLECTIVE_TIMEOUT_SEC="20",
                 TEST_STEP_PAUSE="0.05"),
        timeout=90.0, capture=True, report=lambda msg: None)
    assert membership_succeeded(results, 1), \
        [(r.rank, r.returncode, r.stderr[-400:]) for r in results]
    # Slot 2 is the standby: it must have been admitted and finished.
    by_slot = {r.rank: r for r in results}
    assert 2 in by_slot, results
    assert by_slot[2].returncode == 0, by_slot[2].stderr[-800:]
    members = _members(results)
    # Survivor + standby, dense ranks {0, 1} in the final membership.
    assert sorted(m[0] for m in members) == [0, 1], members
    survivor = next(m for m in members if m[0] == 0)
    _, size_now, epoch, msize, lost, joined, w0 = survivor
    assert size_now == 2 and msize == 2, members
    assert epoch == 2, members          # shrink, then grow
    assert lost == [1] and joined == [1], members
    for m in members:
        assert m[6] == 60, members      # every member trained to the end


# ---------------------------------------------------------------------------
# CLI: hvdrun --min-np/--max-np end to end.
# ---------------------------------------------------------------------------


def test_hvdrun_cli_min_np(tmp_path):
    """`hvdrun -np 2 --min-np 1`: a crashed rank is reshaped around, the
    job exits 0, and the elastic completion notice lands on stderr."""
    script = tmp_path / "train.py"
    script.write_text(_TRAIN)
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         "--min-np", "1", "--timeout", "60", "--",
         sys.executable, str(script), "12"],
        env=_env(HVD_TPU_FAULT_SPEC="rank=1:crash@op=6",
                 HVD_TPU_COLLECTIVE_TIMEOUT_SEC="20"),
        capture_output=True, text=True, timeout=90)
    assert proc.returncode == 0, proc.stderr[-1200:]
    assert "completed elastically" in proc.stderr, proc.stderr[-800:]
    assert "1 member(s) lost" in proc.stderr, proc.stderr[-800:]


def test_hvdrun_cli_rejects_bad_bounds():
    from horovod_tpu.runner import run_membership

    with pytest.raises(ValueError, match="min-np"):
        run_membership(["true"], 2, min_np=3, max_np=4)
    with pytest.raises(ValueError, match="min-np"):
        run_membership(["true"], 2, min_np=1, max_np=1)
    # An explicit 0 is invalid, not "unset": silently disabling elastic
    # for the user who asked for maximal elasticity is the worst outcome.
    with pytest.raises(ValueError, match="min-np"):
        run_membership(["true"], 2, min_np=0)


# ---------------------------------------------------------------------------
# In-process units: state sync, driver error contract, accounting.
# ---------------------------------------------------------------------------


def test_elastic_state_validation_and_keys():
    import horovod_tpu as hvd

    with pytest.raises(ValueError, match="at least one"):
        hvd.ElasticState()
    st = hvd.ElasticState(weights=np.ones(3), step=7, lr=0.1)
    assert st.keys() == ["lr", "step", "weights"]
    assert st.step == 7


def test_elastic_state_sync_roundtrips_leaf_types(single_process_hvd):
    """sync() replaces every leaf with the root's value and preserves the
    Python type of scalar leaves (step counters stay ints)."""
    hvd = single_process_hvd
    st = hvd.ElasticState(weights=np.arange(4, dtype=np.float32),
                          step=3, lr=0.5, done=False)
    st.sync(root=0, key=0)
    assert isinstance(st.step, int) and st.step == 3
    assert isinstance(st.lr, float) and st.lr == 0.5
    assert isinstance(st.done, bool) and st.done is False
    assert isinstance(st.weights, np.ndarray)
    assert np.allclose(st.weights, np.arange(4)), st.weights


def test_run_elastic_returns_result_and_reraises_fatal(single_process_hvd):
    """The driver returns train_fn's result; fatal engine errors
    (RanksDownError — the below-min-np / dead-coordinator path) and
    non-engine exceptions re-raise unchanged."""
    hvd = single_process_hvd
    from horovod_tpu.common import RanksDownError

    st = hvd.ElasticState(step=0)
    assert hvd.run_elastic(lambda s: "done", st) == "done"
    assert st.step == 0

    def fatal(_):
        raise RanksDownError("ranks down: 1", ranks=[1])

    with pytest.raises(RanksDownError):
        hvd.run_elastic(fatal, st)

    def user_bug(_):
        raise KeyError("not an engine error")

    with pytest.raises(KeyError):
        hvd.run_elastic(user_bug, st)


def test_membership_changed_error_is_retryable_internal_error():
    from horovod_tpu.common import (HorovodInternalError,
                                    MembershipChangedError, RanksDownError)

    err = MembershipChangedError("membership changed", lost_ranks=[2, 3])
    assert isinstance(err, HorovodInternalError)
    assert not isinstance(err, RanksDownError)
    assert err.lost_ranks == [2, 3]


def test_membership_epoch_zero_before_init():
    from horovod_tpu.common import membership_epoch

    assert membership_epoch() == 0


def test_membership_succeeded_accounting():
    from horovod_tpu.runner import RankResult, membership_succeeded

    ok = RankResult(0, 0, "", "")
    dead = RankResult(1, 43, "", "")
    assert membership_succeeded([ok, dead], 1)
    assert not membership_succeeded([ok, dead], 2)          # too few clean
    assert not membership_succeeded([dead, ok], 1)          # coordinator died
    assert not membership_succeeded([], 1)
    assert membership_succeeded([ok, dead, RankResult(2, 0, "", "")], 2)


def test_membership_metrics_and_prometheus():
    """The registry's ungated membership mirror and its Prometheus
    families (hvd_tpu_membership_*)."""
    from horovod_tpu.common.metrics import MetricsRegistry, prometheus_text

    reg = MetricsRegistry()
    snap = reg.snapshot()
    assert snap["membership"] == {"epoch": 0, "size": 0, "reshapes": 0,
                                  "ranks_lost": [], "ranks_joined": []}
    reg.set_membership({"epoch": 2, "size": 3, "reshapes": 2,
                        "ranks_lost": [1], "ranks_joined": [3]})
    snap = reg.snapshot()
    assert snap["membership"]["epoch"] == 2
    assert snap["membership"]["ranks_lost"] == [1]
    text = prometheus_text(snap)
    assert "hvd_tpu_membership_epoch 2" in text
    assert "hvd_tpu_membership_size 3" in text
    assert "hvd_tpu_membership_reshapes_total 2" in text
    assert "hvd_tpu_membership_ranks_lost_total 1" in text
    assert "hvd_tpu_membership_ranks_joined_total 1" in text


def test_elastic_state_sync_pytree_leaves(single_process_hvd):
    """Nested dict/namedtuple state (the jax params/opt_state shape)
    syncs leaf-by-leaf and rebuilds the structure."""
    import collections

    hvd = single_process_hvd
    Opt = collections.namedtuple("Opt", ["mu", "nu"])
    params = {"dense": {"w": np.ones((2, 2), np.float32),
                        "b": np.zeros(2, np.float32)}}
    opt = Opt(mu=[np.full(2, 3.0)], nu=[np.full(2, 4.0)])
    st = hvd.ElasticState(params=params, opt=opt, step=5)
    st.sync(root=0, key=1)
    assert isinstance(st.params, dict)
    assert np.allclose(st.params["dense"]["w"], 1.0)
    assert np.allclose(st.params["dense"]["b"], 0.0)
    assert isinstance(st.opt, Opt)
    assert np.allclose(st.opt.mu[0], 3.0) and np.allclose(st.opt.nu[0], 4.0)
    assert st.step == 5


def test_tree_flatten_pure_python_fallback(monkeypatch):
    """Without jax, _tree_flatten still walks dicts (sorted keys), lists,
    tuples, and namedtuples deterministically."""
    import collections
    import sys as _sys

    from horovod_tpu.common import elastic

    monkeypatch.setitem(_sys.modules, "jax", None)  # force ImportError
    Pt = collections.namedtuple("Pt", ["x", "y"])
    tree = {"b": [1, 2], "a": (Pt(x=3, y=4), 5)}
    leaves, rebuild = elastic._tree_flatten(tree)
    # Sorted dict keys -> "a" first.
    assert leaves == [3, 4, 5, 1, 2]
    out = rebuild([v * 10 for v in leaves])
    assert out == {"a": (Pt(x=30, y=40), 50), "b": [10, 20]}
    assert isinstance(out["a"][0], Pt)


def test_run_elastic_rejects_unsupported_combos():
    """Elastic + --hosts / --tpu-pin fail loudly instead of silently
    dropping the feature (chip pinning has no stable local_rank for
    standbys; multi-host elastic is not built yet)."""
    from horovod_tpu.runner import run_elastic

    with pytest.raises(ValueError, match="single-host"):
        run_elastic(["true"], 2, min_np=1, hosts_spec="h1:1,h2:1")
    with pytest.raises(ValueError, match="pinning"):
        run_elastic(["true"], 2, min_np=1, tpu_pin=True)


@pytest.mark.slow  # ~8s; probe/join machinery stays tier-1 in
# test_standby_rejoins_and_grows_back
def test_trickled_probe_cannot_stall_the_job(tmp_path, monkeypatch):
    """A connect to the elastic control port that sends a PARTIAL join
    hello and then goes idle (slow trickle, health check, port scanner
    writing a banner byte) must park in the coordinator's handshake
    buffer and be dropped at its deadline — never block the engine tick
    in a full-message read, which would stall every worker's negotiation
    until the collective timeout killed a healthy job."""
    import socket
    import threading

    from horovod_tpu.runner import launch, membership_succeeded

    captured = {}
    real = launch.allocate_endpoints

    def spy(size, host="127.0.0.1", **kw):
        out = real(size, host, **kw)
        captured["coord"] = out[0]
        return out

    monkeypatch.setattr(launch, "allocate_endpoints", spy)

    script = tmp_path / "train.py"
    script.write_text(_TRAIN)
    box = {}

    def run():
        box["results"] = launch.run_membership(
            [sys.executable, str(script), "30"], 2, min_np=1, max_np=2,
            max_rejoins=0,
            env=_env(HVD_TPU_COLLECTIVE_TIMEOUT_SEC="20",
                     TEST_STEP_PAUSE="0.2"),
            timeout=60.0, capture=True, report=lambda msg: None)

    t = threading.Thread(target=run)
    t.start()
    try:
        deadline = time.monotonic() + 10.0
        while "coord" not in captured and time.monotonic() < deadline:
            time.sleep(0.05)
        host, port = captured["coord"].rsplit(":", 1)
        # Let init finish so the probe hits the elastic accept loop, not
        # the init rendezvous; the job itself runs ~6s of paused steps.
        time.sleep(2.5)
        probe = socket.create_connection((host, int(port)), timeout=5.0)
        probe.sendall(b"\xfe\xff")  # 2 of the 4 hello bytes, then silence
        t.join(timeout=55.0)
        probe.close()
    finally:
        t.join(timeout=60.0)
    assert not t.is_alive()
    results = box["results"]
    assert membership_succeeded(results, 2), \
        [(r.rank, r.returncode, r.stderr[-400:]) for r in results]
    members = _members(results)
    assert len(members) == 2 and all(m[6] == 30 for m in members), members
