"""Collective metrics registry (horovod_tpu/common/metrics.py): snapshot
shape, counter monotonicity, histogram accounting, reset semantics,
thread-safety under concurrent collectives, stall surfacing, and the
Prometheus/JSON monitor endpoints.  Tier-1, CPU-only, in-process (size-1
engine); the multi-rank stall path is covered by the distributed test at
the bottom."""

import json
import os
import re
import threading
import urllib.request

import numpy as np
import pytest

from tests.distributed import distributed_test


@pytest.fixture
def hvd_metrics():
    """hvd.init() at size 1 with metrics collection enabled, registry
    cleared before and after (it is process-global)."""
    for var in ("HVD_TPU_RANK", "HVD_TPU_SIZE", "HVD_TPU_COORD",
                "HVD_TPU_DATA", "HVD_TPU_METRICS_FILE",
                "HVD_TPU_MONITOR_PORT"):
        os.environ.pop(var, None)
    os.environ["HVD_TPU_METRICS"] = "1"
    import horovod_tpu as hvd

    hvd.init()
    hvd.metrics_reset()
    yield hvd
    hvd.metrics_reset()
    hvd.shutdown()
    os.environ.pop("HVD_TPU_METRICS", None)
    from horovod_tpu.common import metrics

    metrics.registry.disable()


def test_snapshot_shape(hvd_metrics):
    hvd = hvd_metrics
    hvd.allreduce(np.ones(100, np.float32), name="m.ar")
    snap = hvd.metrics_snapshot()
    assert snap["enabled"] is True
    for plane in ("engine", "xla"):
        assert set(snap["ops"][plane]) == {"allreduce", "allgather",
                                           "broadcast"}
        assert set(snap["bytes"][plane]) == {"in", "out"}
    assert set(snap["batches"]) == {"dispatched", "fused_tensors"}
    assert set(snap["stalls"]) == {"count", "tensors"}
    for hist in snap["histograms"].values():
        assert set(hist) == {"buckets", "counts", "sum", "count"}
        assert len(hist["counts"]) == len(hist["buckets"]) + 1
    # The whole snapshot is plain data: JSON round-trips.
    assert json.loads(json.dumps(snap)) == snap


def test_counters_and_monotonicity(hvd_metrics):
    hvd = hvd_metrics
    x = np.ones(256, np.float32)
    hvd.allreduce(x, name="m.a")
    hvd.broadcast(x, 0, name="m.b")
    s1 = hvd.metrics_snapshot()
    assert s1["ops"]["engine"]["allreduce"] == 1
    assert s1["ops"]["engine"]["broadcast"] == 1
    assert s1["bytes"]["engine"]["in"] == 2 * x.nbytes
    assert s1["bytes"]["engine"]["out"] == 2 * x.nbytes
    hvd.allgather(np.ones((4, 8), np.float32), name="m.g")
    s2 = hvd.metrics_snapshot()
    for plane in ("engine", "xla"):
        for op in ("allreduce", "allgather", "broadcast"):
            assert s2["ops"][plane][op] >= s1["ops"][plane][op]
    assert s2["ops"]["engine"]["allgather"] == 1
    assert s2["bytes"]["engine"]["in"] == s1["bytes"]["engine"]["in"] + 128


def test_histogram_bucket_sums(hvd_metrics):
    hvd = hvd_metrics
    n = 7
    for i in range(n):
        hvd.allreduce(np.ones(32, np.float32), name=f"m.h{i}")
    hist = hvd.metrics_snapshot()["histograms"]["wait_sec"]
    assert hist["count"] == n
    assert sum(hist["counts"]) == n  # bucket counts account for every obs
    assert hist["sum"] > 0.0
    # Buckets are sorted upper bounds.
    assert hist["buckets"] == sorted(hist["buckets"])


def test_reset_semantics(hvd_metrics):
    hvd = hvd_metrics
    hvd.allreduce(np.ones(8, np.float32), name="m.r")
    assert hvd.metrics_snapshot()["ops"]["engine"]["allreduce"] == 1
    hvd.metrics_reset()
    snap = hvd.metrics_snapshot()
    assert snap["ops"]["engine"]["allreduce"] == 0
    assert snap["bytes"]["engine"]["in"] == 0
    assert snap["stalls"] == {"count": 0, "tensors": {}}
    assert all(h["count"] == 0 for h in snap["histograms"].values())
    assert snap["enabled"] is True  # reset clears data, not the gate
    # The registry keeps recording after a reset.
    hvd.allreduce(np.ones(8, np.float32), name="m.r2")
    assert hvd.metrics_snapshot()["ops"]["engine"]["allreduce"] == 1


def test_thread_safety_smoke(hvd_metrics):
    """Concurrent allreduces from several threads: every op and byte is
    accounted exactly once (the engine supports concurrent enqueues; the
    registry must too)."""
    hvd = hvd_metrics
    threads, per_thread, nbytes = 4, 8, 64 * 4
    errors = []

    def work(t):
        try:
            for i in range(per_thread):
                out = hvd.allreduce(np.full(64, float(t), np.float32),
                                    name=f"m.t{t}.{i}")
                assert np.allclose(out, float(t))
        except Exception as exc:  # pragma: no cover - failure path
            errors.append(exc)

    ts = [threading.Thread(target=work, args=(t,)) for t in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors, errors
    snap = hvd.metrics_snapshot()
    total = threads * per_thread
    assert snap["ops"]["engine"]["allreduce"] == total
    assert snap["bytes"]["engine"]["in"] == total * nbytes
    assert snap["bytes"]["engine"]["out"] == total * nbytes
    assert snap["histograms"]["wait_sec"]["count"] == total


def test_prometheus_endpoint_and_json(hvd_metrics):
    from horovod_tpu.common import metrics

    hvd = hvd_metrics
    hvd.allreduce(np.ones(128, np.float32), name="m.p")
    port = metrics.start_monitor(0, snapshot_fn=hvd.metrics_snapshot)
    try:
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        # Every non-comment line is "name{labels} value" or "name value".
        sample = re.compile(
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [0-9.+\-einfa]+$")
        lines = [l for l in text.splitlines() if l]
        assert lines, text
        for line in lines:
            if line.startswith("#"):
                assert line.startswith(("# HELP ", "# TYPE ")), line
            else:
                assert sample.match(line), line
        assert 'hvd_tpu_ops_total{plane="engine",op="allreduce"} 1' in lines
        # Histogram families expose cumulative buckets + +Inf + sum/count.
        assert any(l.startswith('hvd_tpu_wait_seconds_bucket{le="+Inf"}')
                   for l in lines)
        assert "hvd_tpu_wait_seconds_count 1" in lines
        raw = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics.json",
            timeout=10).read().decode()
        snap = json.loads(raw)
        # The JSON endpoint serves the same registry the API reads.
        assert snap["ops"] == hvd.metrics_snapshot()["ops"]
        assert urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).status == 200
    finally:
        metrics.stop_monitor()
    from horovod_tpu.common.metrics import monitor_port

    assert monitor_port() is None


def test_monitor_env_and_metrics_file(tmp_path):
    """HVD_TPU_MONITOR_PORT starts the monitor at init();
    HVD_TPU_METRICS_FILE writes a per-rank JSON dump at shutdown()."""
    for var in ("HVD_TPU_RANK", "HVD_TPU_SIZE", "HVD_TPU_COORD",
                "HVD_TPU_DATA"):
        os.environ.pop(var, None)
    path = str(tmp_path / "metrics.json")
    os.environ["HVD_TPU_METRICS_FILE"] = path
    os.environ["HVD_TPU_MONITOR_PORT"] = "0"  # ephemeral: avoids collisions
    import horovod_tpu as hvd
    from horovod_tpu.common import metrics

    hvd.init()
    try:
        hvd.metrics_reset()
        assert metrics.registry.enabled  # implied by file/port
        port = metrics.monitor_port()
        assert port
        hvd.allreduce(np.ones(16, np.float32), name="mf.a")
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        assert 'hvd_tpu_ops_total{plane="engine",op="allreduce"} 1' in text
    finally:
        hvd.shutdown()
        os.environ.pop("HVD_TPU_METRICS_FILE", None)
        os.environ.pop("HVD_TPU_MONITOR_PORT", None)
        metrics.registry.disable()
        metrics.registry.reset()
    dump = json.load(open(path + ".0"))  # rank-suffixed
    assert dump["ops"]["engine"]["allreduce"] == 1
    assert metrics.monitor_port() is None  # shutdown stops the monitor


def test_plane_stall_recorded_in_registry(monkeypatch):
    """Satellite: stall warnings are programmatic, not just stderr — the
    XLA plane's wait loop records (tensor, duration) into the registry
    even with metrics collection disabled."""
    import time as _time

    import horovod_tpu.common as common
    from horovod_tpu.common import metrics
    from horovod_tpu.jax.eager_mesh import XlaDataPlane, XlaHandle, _PlaneOp

    metrics.registry.disable()
    metrics.registry.reset()
    monkeypatch.setenv("HVD_TPU_STALL_WARNING_SEC", "0.05")
    plane = XlaDataPlane(mesh=None, spec_sharded=None, spec_replicated=None,
                         rank=0, size=2, fusion_threshold=1 << 20)
    handle = XlaHandle(plane, "ar", "stalled_metric", None, True, 2,
                       np.float32, (2,))
    op = _PlaneOp("stalled_metric", "ar", np.zeros(2, np.float32), 0, handle)
    plane._pending.append(op)
    monkeypatch.setattr(plane, "flush", lambda: None)

    def unblock():
        _time.sleep(0.3)
        handle._error = RuntimeError("unblocked")

    t = threading.Thread(target=unblock)
    t.start()
    plane._wait_dispatch(handle)
    t.join()
    snap = common.metrics_snapshot()
    assert snap["stalls"]["count"] >= 1
    assert "stalled_metric" in snap["stalls"]["tensors"]
    entry = snap["stalls"]["tensors"]["stalled_metric"]
    assert entry["count"] >= 1 and entry["last_duration_sec"] > 0
    metrics.registry.reset()


@distributed_test(np_=2, timeout=300.0)
def test_engine_stall_surfaced_to_snapshot():
    """Satellite (engine side): when a rank submits a collective its peer
    does not, the coordinator's stall sweep is visible on rank 0 through
    metrics_snapshot()["stalls"] — tensor name included — instead of only
    a stderr line.  Metrics collection stays at its default (disabled):
    stall records are ungated."""
    import time

    import horovod_tpu as hvd
    import horovod_tpu.common as common

    os.environ["HVD_TPU_STALL_WARNING_SEC"] = "0.3"
    hvd.init()
    if hvd.rank() == 0:
        h = common.allreduce_async(np.ones(4, np.float32), average=False,
                                   name="lonely")
        snap = {}
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            snap = hvd.metrics_snapshot()
            if snap["stalls"]["count"] >= 1:
                break
            time.sleep(0.1)
        assert snap["stalls"]["count"] >= 1, snap["stalls"]
        assert "lonely" in snap["stalls"]["tensors"], snap["stalls"]
        assert snap["stalls"]["tensors"]["lonely"]["last_duration_sec"] > 0
    else:
        time.sleep(2.0)  # let rank 0's sweep fire before unblocking it
        h = common.allreduce_async(np.ones(4, np.float32), average=False,
                                   name="lonely")
    out = h.wait()
    assert np.allclose(out, 2.0), out


@distributed_test(np_=2, timeout=300.0)
def test_monitor_scrape_during_two_process_job():
    """Acceptance: with HVD_TPU_MONITOR_PORT set, scraping /metrics during
    a 2-process hvdrun CPU job returns Prometheus text whose allreduce op
    count and byte totals match metrics_snapshot() on that rank."""
    os.environ["HVD_TPU_MONITOR_PORT"] = "0"  # ephemeral: collision-proof
    import horovod_tpu as hvd
    from horovod_tpu.common import metrics

    hvd.init()
    hvd.metrics_reset()
    r, n = hvd.rank(), hvd.size()
    x = np.full(500, float(r), np.float32)
    for i in range(3):
        out = hvd.allreduce(x, average=False, name=f"scrape.{i}")
        assert np.allclose(out, sum(range(n)))
    port = metrics.monitor_port()
    assert port, "monitor did not start from HVD_TPU_MONITOR_PORT"
    text = urllib.request.urlopen(
        f"http://localhost:{port}/metrics", timeout=10).read().decode()
    snap = hvd.metrics_snapshot()
    ar = snap["ops"]["engine"]["allreduce"]
    bin_ = snap["bytes"]["engine"]["in"]
    assert ar == 3 and bin_ == 3 * x.nbytes, snap
    assert f'hvd_tpu_ops_total{{plane="engine",op="allreduce"}} {ar}' \
        in text, text[:800]
    assert f'hvd_tpu_bytes_total{{plane="engine",direction="in"}} {bin_}' \
        in text, text[:800]
    hvd.shutdown()


def test_monitor_port_offsets_by_local_rank(monkeypatch):
    """A non-zero HVD_TPU_MONITOR_PORT binds port+local_rank so several
    ranks on one host coexist (rank 0 stays at the base port)."""
    for var in ("HVD_TPU_RANK", "HVD_TPU_SIZE", "HVD_TPU_COORD",
                "HVD_TPU_DATA"):
        os.environ.pop(var, None)
    import horovod_tpu as hvd
    from horovod_tpu.common import metrics

    calls = []
    monkeypatch.setenv("HVD_TPU_MONITOR_PORT", "19123")
    monkeypatch.setattr(metrics, "start_monitor",
                        lambda port, **kw: calls.append(port) or port)
    hvd.init()
    try:
        assert calls == [19123]  # size-1: local_rank 0 -> base port
    finally:
        hvd.shutdown()
        metrics.registry.disable()
        metrics.registry.reset()


def test_keras_metrics_logging_callback(hvd_metrics):
    """MetricsLoggingCallback logs per-epoch deltas of the registry."""
    keras = pytest.importorskip("keras")  # noqa: F841
    from horovod_tpu.keras.callbacks import MetricsLoggingCallback

    hvd = hvd_metrics
    lines = []
    cb = MetricsLoggingCallback(log_fn=lines.append)
    hvd.allreduce(np.ones(64, np.float32), name="cb.0")
    cb.on_epoch_end(0)
    hvd.allreduce(np.ones(64, np.float32), name="cb.1")
    hvd.allreduce(np.ones(64, np.float32), name="cb.2")
    cb.on_epoch_end(1)
    assert len(lines) == 2, lines
    assert "ops engine=1" in lines[0], lines[0]
    assert "ops engine=2" in lines[1], lines[1]  # delta, not cumulative
    assert "stalls 0" in lines[1]


def test_jax_train_step_feeds_step_histogram(hvd_metrics):
    """Steps built by build_train_step record into the step_sec histogram
    when metrics are enabled (and stay zero-overhead pass-throughs when
    not — the proxy consults the gate per call)."""
    jax = pytest.importorskip("jax")
    optax = pytest.importorskip("optax")
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P  # noqa: F401

    from horovod_tpu.jax.train import build_train_step

    hvd = hvd_metrics
    mesh = Mesh(np.array(jax.devices()[:2]), ("hvd",))

    def loss_fn(params, batch):
        return jnp.mean((batch @ params) ** 2)

    tx = optax.sgd(0.1)
    step = build_train_step(loss_fn, tx, mesh, axis_name="hvd")
    params = jnp.ones((4,))
    opt_state = tx.init(params)
    batch = jnp.ones((2, 4))
    before = hvd.metrics_snapshot()["histograms"]["step_sec"]["count"]
    params, opt_state, loss = step(params, opt_state, batch)
    float(loss)
    after = hvd.metrics_snapshot()["histograms"]["step_sec"]["count"]
    assert after == before + 1


def test_skew_section_and_prometheus_families():
    """Straggler attribution plumbing: record_last_announce feeds the
    snapshot's ungated "skew" section and the last_to_announce /
    announce_total Prometheus families; reset clears it."""
    from horovod_tpu.common.metrics import MetricsRegistry, prometheus_text

    reg = MetricsRegistry()
    reg.record_last_announce(2, 3)
    reg.record_last_announce(0)
    reg.observe("announce_skew_sec", 0.2)
    snap = reg.snapshot()
    assert snap["skew"] == {"count": 4,
                            "last_to_announce": {"2": 3, "0": 1}}
    assert snap["histograms"]["announce_skew_sec"]["count"] == 1
    text = prometheus_text(snap)
    assert 'hvd_tpu_last_to_announce_total{rank="2"} 3' in text
    assert "hvd_tpu_announce_total 4" in text
    assert "hvd_tpu_announce_skew_seconds_count 1" in text
    reg.reset()
    assert reg.snapshot()["skew"] == {"count": 0, "last_to_announce": {}}


def _fully_populated_registry():
    """One of everything, so every exposition family renders (shared by
    the conformance test and mirroring tools/check_metric_names.py)."""
    from horovod_tpu.common import metrics

    reg = metrics.MetricsRegistry()
    reg.record_enqueue("engine", "allreduce", 1024)
    reg.record_enqueue("xla", "broadcast", 64)
    reg.record_bytes_out("engine", 1024)
    reg.record_batch(3)
    reg.record_stall("conf.tensor", 1.0)
    reg.record_fault("crash")
    reg.record_abort("ranks_down")
    reg.record_last_announce(1, 2)
    reg.set_restart_epoch(1)
    for name in metrics.HISTOGRAMS:
        reg.observe(name, 0.001)
    reg.set_links({"enabled": True, "peers": {1: {
        "bytes_out": 4096, "bytes_in": 2048, "sends": 7, "recvs": 5,
        "stalls": 1, "short_writes": 2, "send_us_sum": 900,
        "send_us_count": 7,
        "send_us_buckets": [3, 2, 1, 1, 0, 0, 0, 0, 0, 0],
        "rtt_last_us": 180, "rtt_ewma_us": 150, "rtt_samples": 4}}})
    reg.set_anomalies({"sigma": 5, "interval_ms": 500,
                       "verdicts": {"slow_link": 1},
                       "log": [{"kind": "slow_link", "subject": "0-1",
                                "detail": "timed-send level 9000us",
                                "age_us": 1000}]})
    return reg


def test_prometheus_exposition_conformance():
    """Satellite: scrape /metrics and check exposition-format conformance
    — # HELP/# TYPE pairing per family, metric-name charset, samples only
    under declared families — and that every registry section
    (ops/bytes/batches/stalls/faults/skew + every histogram) is
    exposed."""
    from horovod_tpu.common import metrics

    reg = _fully_populated_registry()
    port = metrics.start_monitor(0, snapshot_fn=reg.snapshot)
    try:
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
    finally:
        metrics.stop_monitor()
        metrics.registry.disable()  # start_monitor enables the global one

    name_re = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
    helps, types, order = {}, {}, []
    for i, line in enumerate(text.splitlines()):
        if line.startswith("# HELP "):
            name = line.split()[2]
            assert name not in helps, f"duplicate HELP for {name}"
            helps[name] = i
        elif line.startswith("# TYPE "):
            parts = line.split()
            name, kind = parts[2], parts[3]
            assert name not in types, f"duplicate TYPE for {name}"
            types[name] = i
            order.append(name)
            assert kind in ("counter", "gauge", "histogram"), line
    # Pairing: every TYPE has a HELP immediately before it, and vice versa.
    assert set(helps) == set(types), (set(helps) ^ set(types))
    for name in order:
        assert types[name] == helps[name] + 1, f"HELP/TYPE split for {name}"
        assert name_re.match(name), name
    # Samples belong to a declared family (histograms via their suffixes).
    declared = set(types)
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        sample = line.split("{")[0].split(" ")[0]
        assert name_re.match(sample), line
        base = sample
        for suffix in ("_bucket", "_sum", "_count"):
            if sample.endswith(suffix) and sample[:-len(suffix)] in declared:
                base = sample[:-len(suffix)]
                break
        assert base in declared, f"undeclared sample {sample}"
    # Every registry section is exposed, PR-2 faults and skew included.
    expected = {"hvd_tpu_ops_total", "hvd_tpu_bytes_total",
                "hvd_tpu_batches_dispatched_total",
                "hvd_tpu_fused_tensors_total",
                "hvd_tpu_stall_events_total",
                "hvd_tpu_stalled_tensor_total",
                "hvd_tpu_faults_injected_total", "hvd_tpu_aborts_total",
                "hvd_tpu_restart_epoch", "hvd_tpu_announce_total",
                "hvd_tpu_last_to_announce_total"}
    expected |= {metrics._prom_hist_name(h) for h in metrics.HISTOGRAMS}
    # ISSUE 18: the per-link and anomaly families must pass the same
    # exposition conformance as every older section.
    expected |= {"hvd_tpu_link_stats_enabled", "hvd_tpu_link_bytes_total",
                 "hvd_tpu_link_sends_total",
                 "hvd_tpu_link_stall_events_total",
                 "hvd_tpu_link_send_latency_us", "hvd_tpu_link_rtt_us",
                 "hvd_tpu_link_rtt_samples_total", "hvd_tpu_anomaly_sigma",
                 "hvd_tpu_anomaly_verdicts_total"}
    assert expected <= declared, expected - declared
    assert 'hvd_tpu_last_to_announce_total{rank="1"} 2' in text


def test_check_metric_names_lint():
    """Satellite: the metric-name lint (snake_case, hvd_tpu_ prefix, no
    duplicate families) passes — run from tier-1 so drift fails CI."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ,
               PYTHONPATH=repo + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools",
                                      "check_metric_names.py")],
        capture_output=True, text=True, env=env, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "OK" in proc.stdout, proc.stdout


def test_check_metric_names_lint_detects_violations():
    """The lint rejects camelCase, missing prefixes, and duplicates (a
    lint that passes everything would let names drift silently)."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_metric_names",
        os.path.join(repo, "tools", "check_metric_names.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    bad = ("# HELP hvd_tpu_camelCase_total x\n"
           "# TYPE hvd_tpu_camelCase_total counter\n"
           "hvd_tpu_camelCase_total 1\n"
           "# HELP wrong_prefix_total x\n"
           "# TYPE wrong_prefix_total counter\n"
           "wrong_prefix_total 1\n"
           "# HELP hvd_tpu_dup_total x\n"
           "# TYPE hvd_tpu_dup_total counter\n"
           "# HELP hvd_tpu_dup_total x\n"
           "# TYPE hvd_tpu_dup_total counter\n"
           "hvd_tpu_orphan_total 1\n")
    errors = "\n".join(mod.lint(bad))
    assert "camelCase" in errors
    assert "wrong_prefix_total" in errors
    assert "duplicate metric family 'hvd_tpu_dup_total'" in errors
    assert "orphan" in errors


def test_metrics_dump_stragglers_view(tmp_path):
    """Satellite: `metrics_dump.py --stragglers` ranks ranks by
    last_to_announce share and prints the skew histogram's p50/p99."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "metrics_dump", os.path.join(repo, "tools", "metrics_dump.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    reg = _fully_populated_registry()
    reg.record_last_announce(3, 7)
    for _ in range(5):
        reg.observe("announce_skew_sec", 0.2)
    path = tmp_path / "dump.json.0"
    path.write_text(json.dumps(reg.snapshot()))
    out = mod.render_stragglers(json.loads(path.read_text()))
    assert "dominant straggler: rank 3" in out, out
    assert "p50=" in out and "p99=" in out, out
    # And via the CLI flag.
    import subprocess
    import sys as _sys

    proc = subprocess.run(
        [_sys.executable, os.path.join(repo, "tools", "metrics_dump.py"),
         "--stragglers", str(path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "dominant straggler: rank 3" in proc.stdout, proc.stdout


def test_links_and_anomalies_sections():
    """ISSUE 18 tentpole plumbing, engine-free: set_links/set_anomalies
    mirror into the ungated snapshot sections, the Prometheus families
    render with CUMULATIVE histogram buckets, and health_summary /
    cluster_document carry the per-rank link rows and the merged,
    rank-attributed anomaly feed."""
    from horovod_tpu.common import metrics

    reg = _fully_populated_registry()
    snap = reg.snapshot()
    # Snapshot shape: str-keyed peers (JSON round-trip safe), full
    # counter set, verdict log.
    assert snap["links"]["enabled"] is True
    peer = snap["links"]["peers"]["1"]
    assert peer["sends"] == 7 and peer["send_us_sum"] == 900
    assert len(peer["send_us_buckets"]) == \
        len(metrics.LINK_SEND_BUCKETS_US) + 1  # +Inf overflow bucket
    assert snap["anomalies"]["verdicts"]["slow_link"] == 1
    assert snap["anomalies"]["verdicts"]["straggler"] == 0  # zero-filled
    assert snap["anomalies"]["log"][0]["subject"] == "0-1"

    text = metrics.prometheus_text(snap)
    assert 'hvd_tpu_link_bytes_total{peer="1",dir="out"} 4096' in text
    assert 'hvd_tpu_link_sends_total{peer="1"} 7' in text
    assert ('hvd_tpu_link_stall_events_total{peer="1",kind="short_write"} 2'
            in text)
    # Buckets 3,2,1,1 at bounds 50,100,250,500 render cumulatively.
    assert 'hvd_tpu_link_send_latency_us_bucket{peer="1",le="50"} 3' in text
    assert 'hvd_tpu_link_send_latency_us_bucket{peer="1",le="100"} 5' in text
    assert 'hvd_tpu_link_send_latency_us_bucket{peer="1",le="500"} 7' in text
    assert ('hvd_tpu_link_send_latency_us_bucket{peer="1",le="+Inf"} 7'
            in text)
    assert 'hvd_tpu_link_rtt_us{peer="1",stat="ewma"} 150' in text
    assert "hvd_tpu_anomaly_sigma 5" in text
    assert 'hvd_tpu_anomaly_verdicts_total{kind="slow_link"} 1' in text

    # RTT gauges are omitted (not zero-valued) before the first echo.
    reg2 = metrics.MetricsRegistry()
    reg2.set_links({"enabled": True, "peers": {2: {
        "sends": 1, "send_us_sum": 10, "send_us_count": 1,
        "send_us_buckets": [1] + [0] * 9, "rtt_samples": 0}}})
    assert "hvd_tpu_link_rtt_us{" not in \
        metrics.prometheus_text(reg2.snapshot())

    # /health rows: merged stalls, summed bytes, -1 RTT sentinel handling.
    hs = metrics.health_summary(snap)
    row = hs["links"]["1"]
    assert row["bytes"] == 4096 + 2048
    assert row["stalls"] == 1 + 2
    assert row["send_mean_us"] == 900 // 7
    assert row["rtt_ewma_us"] == 150
    assert hs["anomalies"]["verdicts"]["slow_link"] == 1
    assert hs["anomalies"]["log"][-1]["kind"] == "slow_link"

    # /cluster rollup, through the real scrape path: rank 0 computed
    # locally, "rank 2" scraped from a live monitor serving a registry
    # with a fresher (smaller age_us) verdict.  Totals sum across ranks;
    # the merged feed is rank-attributed and age-sorted freshest-first.
    remote = metrics.MetricsRegistry()
    remote.set_anomalies({"sigma": 5, "interval_ms": 500,
                          "verdicts": {"slow_link": 2},
                          "log": [{"kind": "slow_link", "subject": "0-2",
                                   "detail": "x", "age_us": 50}]})
    port = metrics.start_monitor(0, snapshot_fn=remote.snapshot)
    try:
        metrics.configure_cluster([(0, "127.0.0.1", 0),
                                   (2, "127.0.0.1", port)])
        doc = metrics.cluster_document(reg.snapshot)
    finally:
        metrics.stop_monitor()
        metrics.registry.disable()  # start_monitor enables the global one
    assert doc["anomalies"]["total"] == 3, doc["anomalies"]
    assert doc["anomalies"]["verdicts"]["slow_link"] == 3
    feed = doc["anomalies"]["recent"]
    assert feed[0]["rank"] == "2" and feed[0]["age_us"] == 50, feed


def test_metrics_dump_links_view(tmp_path):
    """Satellite: `metrics_dump.py --links` renders the per-peer link
    table (mean/p99 send latency, RTT, backpressure) and the default
    render grows an anomalies section when verdicts exist."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "metrics_dump", os.path.join(repo, "tools", "metrics_dump.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    reg = _fully_populated_registry()
    snap = reg.snapshot()
    out = mod.render_links(snap)
    assert "peer" in out and "p99" in out, out
    assert "129us" in out, out  # peer 1's mean, round(900/7)
    out_default = mod.render(snap)
    assert "anomalies" in out_default, out_default
    assert "slow_link" in out_default, out_default
    # And via the CLI flag.
    import subprocess
    import sys as _sys

    path = tmp_path / "dump.json.0"
    path.write_text(json.dumps(snap))
    proc = subprocess.run(
        [_sys.executable, os.path.join(repo, "tools", "metrics_dump.py"),
         "--links", str(path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "p99" in proc.stdout, proc.stdout


def test_prometheus_text_pure():
    """prometheus_text renders a synthetic snapshot without an engine."""
    from horovod_tpu.common.metrics import (MetricsRegistry,
                                            prometheus_text)

    reg = MetricsRegistry()
    reg.record_enqueue("xla", "allreduce", 1024)
    reg.record_bytes_out("xla", 1024)
    reg.record_batch(3)
    reg.observe("bucket_fill", 0.42)
    reg.observe("negotiation_sec", 0.003)
    reg.record_stall('we"ird\nname', 1.5)
    text = prometheus_text(reg.snapshot())
    assert 'hvd_tpu_ops_total{plane="xla",op="allreduce"} 1' in text
    assert 'hvd_tpu_bytes_total{plane="xla",direction="out"} 1024' in text
    assert "hvd_tpu_fused_tensors_total 3" in text
    assert "hvd_tpu_stall_events_total 1" in text
    assert '\\"' in text and "\\n" in text  # label escaping
    assert "hvd_tpu_bucket_fill_ratio_count 1" in text
    assert "hvd_tpu_negotiation_seconds_count 1" in text
