"""PyTorch binding tests over N real rank processes.

Mirrors the reference's torch suite (/root/reference/test/test_torch.py):
value tests for allreduce/allgather/broadcast incl. in-place and async
variants, gradient tests, DistributedOptimizer equivalence with full-batch
SGD, and optimizer-state broadcast restoring hyperparameters.
"""

import numpy as np
import pytest

from tests.distributed import distributed_test


def _init():
    import horovod_tpu.torch as hvd

    hvd.init()
    return hvd


@pytest.mark.slow  # ~32s; eager torch allreduce values stay tier-1 in
# test_torch_allreduce_inplace_and_average
@distributed_test()
def test_torch_allreduce_values():
    import torch

    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    for dtype in (torch.float32, torch.float64, torch.int32, torch.int64,
                  torch.float16, torch.bfloat16):
        x = torch.arange(17).to(dtype) + r
        out = hvd.allreduce(x, average=False, name=f"t.{dtype}")
        want = sum(torch.arange(17).to(dtype) + i for i in range(n))
        assert torch.allclose(out.float(), want.float(), rtol=1e-2), dtype
        # Input untouched by the out-of-place variant.
        assert torch.equal(x, torch.arange(17).to(dtype) + r)


@distributed_test()
def test_torch_allreduce_inplace_and_average():
    import torch

    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    x = torch.full((5, 3), float(r))
    out = hvd.allreduce_(x, average=True, name="t.inplace")
    want = sum(range(n)) / n
    assert out is x  # in-place returns the same tensor
    assert torch.allclose(x, torch.full((5, 3), want))


@distributed_test()
def test_torch_async_poll_synchronize():
    import torch

    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    handles = [hvd.allreduce_async(torch.full((11,), float(i + r)),
                                   average=False, name=f"t.async.{i}")
               for i in range(50)]
    assert all(isinstance(hvd.poll(h), bool) for h in handles)
    for i, h in enumerate(handles):
        out = hvd.synchronize(h)
        assert torch.allclose(out, torch.full((11,), float(
            sum(i + j for j in range(n)))))


@pytest.mark.slow  # ~11s; the torch allgather binding stays tier-1 in
# test_torch_allgather_grad, and ragged-dim0 gather semantics in the
# engine suite (test_ops/test_basics allgather cases)
@distributed_test()
def test_torch_allgather_variable_dim0():
    import torch

    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    x = torch.full((r + 1, 2), float(r))
    out = hvd.allgather(x, name="t.gather")
    assert out.shape == (sum(i + 1 for i in range(n)), 2)
    off = 0
    for i in range(n):
        assert torch.all(out[off:off + i + 1] == i), (r, i)
        off += i + 1


@distributed_test()
def test_torch_broadcast():
    import torch

    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    for root in range(n):
        x = torch.full((4,), float(r))
        out = hvd.broadcast(x, root_rank=root, name=f"t.bc.{root}")
        assert torch.all(out == root)
        y = torch.full((4,), float(r))
        hvd.broadcast_(y, root_rank=root, name=f"t.bci.{root}")
        assert torch.all(y == root)


@distributed_test()
def test_torch_allreduce_grad():
    import torch

    hvd = _init()
    n = hvd.size()
    x = torch.ones(6, requires_grad=True)
    y = hvd.allreduce(x, average=False, name="t.grad")
    y.sum().backward()
    # d(sum over ranks)/dx = allreduce-sum of ones = n on every rank.
    assert torch.allclose(x.grad, torch.full((6,), float(n)))


@distributed_test()
def test_torch_allgather_grad():
    import torch

    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    x = torch.full((r + 1, 2), 1.0, requires_grad=True)
    out = hvd.allgather(x, name="t.ggrad")
    (out.sum() * (hvd.rank() + 1.0)).backward()
    # Every rank's grad_output for my block is (rank_s + 1); summed = n(n+1)/2.
    want = float(sum(s + 1 for s in range(n)))
    assert torch.allclose(x.grad, torch.full((r + 1, 2), want)), x.grad


@pytest.mark.slow  # ~12s; the same contract stays tier-1 in
# test_torch_backward_passes_per_step_matches_fused_batch
@distributed_test()
def test_torch_distributed_optimizer_matches_full_batch():
    import torch

    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    torch.manual_seed(7)  # same init on every rank
    model = torch.nn.Linear(4, 1)
    w0 = model.weight.detach().clone()

    # Per-rank disjoint data; full batch is the concatenation.
    all_x = torch.tensor(np.random.RandomState(0).randn(n * 2, 4),
                         dtype=torch.float32)
    all_y = torch.tensor(np.random.RandomState(1).randn(n * 2, 1),
                         dtype=torch.float32)
    x, y = all_x[2 * r:2 * r + 2], all_y[2 * r:2 * r + 2]

    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters())
    assert isinstance(opt, torch.optim.SGD)

    loss = torch.nn.functional.mse_loss(model(x), y)
    opt.zero_grad()
    loss.backward()
    opt.step()

    # Reference: single-process SGD on the full batch (mean of per-rank
    # mean losses == full-batch mean with equal shard sizes).
    torch.manual_seed(7)
    ref = torch.nn.Linear(4, 1)
    assert torch.equal(ref.weight.detach(), w0)
    ref_opt = torch.optim.SGD(ref.parameters(), lr=0.1)
    ref_loss = torch.nn.functional.mse_loss(ref(all_x), all_y)
    ref_opt.zero_grad()
    ref_loss.backward()
    ref_opt.step()
    assert torch.allclose(model.weight.detach(), ref.weight.detach(),
                          atol=1e-6), (r, model.weight, ref.weight)


@distributed_test()
def test_torch_backward_passes_per_step_matches_fused_batch():
    """Two micro-batch backwards + one step() under
    backward_passes_per_step=2 produce exactly the gradient (and weights)
    of one fused-batch backward — the race-free gradient-accumulation
    contract."""
    import torch

    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    torch.manual_seed(7)  # same init on every rank
    model = torch.nn.Linear(4, 1)

    # Per-rank data, two micro-batches of 2 each.
    all_x = torch.tensor(np.random.RandomState(0).randn(n * 4, 4),
                         dtype=torch.float32)
    all_y = torch.tensor(np.random.RandomState(1).randn(n * 4, 1),
                         dtype=torch.float32)
    x, y = all_x[4 * r:4 * r + 4], all_y[4 * r:4 * r + 4]

    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters(),
        backward_passes_per_step=2)
    opt.zero_grad()
    # Sum-of-micro-batch losses == fused loss when each micro loss sums.
    torch.nn.functional.mse_loss(model(x[:2]), y[:2],
                                 reduction="sum").backward()
    torch.nn.functional.mse_loss(model(x[2:]), y[2:],
                                 reduction="sum").backward()
    opt.step()

    torch.manual_seed(7)
    ref = torch.nn.Linear(4, 1)
    ref_opt = torch.optim.SGD(ref.parameters(), lr=0.1)
    # Fused batch over ALL ranks' data: mean over ranks of per-rank sums.
    ref_loss = sum(
        torch.nn.functional.mse_loss(ref(all_x[4 * s:4 * s + 4]),
                                     all_y[4 * s:4 * s + 4],
                                     reduction="sum")
        for s in range(n)) / n
    ref_opt.zero_grad()
    ref_loss.backward()
    ref_opt.step()
    assert torch.allclose(model.weight.detach(), ref.weight.detach(),
                          atol=1e-5), (r, model.weight, ref.weight)
    assert torch.allclose(model.bias.detach(), ref.bias.detach(), atol=1e-5)


@distributed_test(np_=1)
def test_torch_reentrant_backward_without_accumulation_raises():
    """A second backward while a gradient allreduce is outstanding is a
    silent-corruption hazard; it must raise, pointing at
    backward_passes_per_step (round-1 behavior silently skipped the
    re-enqueue and raced the in-flight reduce)."""
    import pytest
    import torch

    hvd = _init()
    model = torch.nn.Linear(3, 1)
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters())
    x = torch.ones(2, 3)
    model(x).sum().backward()
    with pytest.raises(RuntimeError, match="backward_passes_per_step"):
        model(x).sum().backward()


@pytest.mark.slow  # ~12s; broadcast sync stays tier-1 in
# test_torch_broadcast + the optimizer-state resume-asymmetry test
@distributed_test()
def test_torch_broadcast_parameters_and_optimizer_state():
    import torch

    hvd = _init()
    r = hvd.rank()
    torch.manual_seed(100 + r)  # deliberately different init per rank
    model = torch.nn.Linear(3, 2)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    gathered = hvd.allgather(model.weight.detach().reshape(1, -1),
                             name="t.bp.check")
    for i in range(hvd.size()):
        assert torch.allclose(gathered[i], gathered[0])

    # Optimizer with per-rank different hyperparams; rank 0's must win.
    lr = 0.123 if r == 0 else 0.999
    opt = torch.optim.SGD(model.parameters(), lr=lr, momentum=0.5 + 0.1 * r)
    loss = model(torch.ones(1, 3)).sum()
    loss.backward()
    opt.step()
    hvd.broadcast_optimizer_state(opt, root_rank=0)
    assert opt.param_groups[0]["lr"] == pytest.approx(0.123)
    assert opt.param_groups[0]["momentum"] == pytest.approx(0.5)
    bufs = [opt.state[p].get("momentum_buffer") for g in opt.param_groups
            for p in g["params"]]
    gathered = hvd.allgather(bufs[0].reshape(1, -1), name="t.bos.check")
    for i in range(hvd.size()):
        assert torch.allclose(gathered[i], gathered[0])


@pytest.mark.slow  # ~9s; optimizer-state sync stays tier-1 in
# test_torch_broadcast_optimizer_state_resume_asymmetry
@distributed_test(np_=2)
def test_torch_optimizer_state_bootstrap_empty():
    """broadcast_optimizer_state on a never-stepped optimizer initializes
    state via a zero-grad dummy step (reference behavior,
    /root/reference/horovod/torch/__init__.py:193-212)."""
    import torch

    hvd = _init()
    model = torch.nn.Linear(2, 2)
    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
    hvd.broadcast_optimizer_state(opt, root_rank=0)
    assert opt.param_groups[0]["lr"] == pytest.approx(0.1)


def test_torch_lbfgs_rejected(single_process_hvd):
    import torch

    import horovod_tpu.torch as hvd

    model = torch.nn.Linear(2, 2)
    opt = torch.optim.LBFGS(model.parameters())
    with pytest.raises(ValueError, match="LBFGS"):
        hvd.broadcast_optimizer_state(opt, root_rank=0)


def test_torch_noncontiguous_inplace_rejected(single_process_hvd):
    import torch

    import horovod_tpu.torch as hvd

    x = torch.ones(4, 4).t()
    with pytest.raises(ValueError, match="contiguous"):
        hvd.allreduce_(x, name="t.nc")


@distributed_test(np_=2, timeout=300)
def test_torch_broadcast_optimizer_state_resume_asymmetry():
    """Resume-from-checkpoint shape: the ROOT rank has loaded optimizer
    state (momentum buffers), the other ranks are fresh.  The fresh
    ranks' empty-state bootstrap must be comm-free (a wrapped step() here
    used to enqueue gradient allreduces the root never joins — deadlock)
    and param-neutral (lr/weight_decay zeroed for the dummy step, or the
    already-broadcast params drift).  Everyone must end with the root's
    state and identical params."""
    import torch

    import horovod_tpu.torch as hvd

    hvd = _init()
    torch.manual_seed(7)  # same init everywhere; focus on state/step
    model = torch.nn.Linear(4, 3)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9,
                        weight_decay=0.1),
        named_parameters=model.named_parameters())
    if hvd.rank() == 0:
        # Stand-in for torch.load of an epoch-1 checkpoint: state with
        # distinctive momentum buffers.
        sd = opt.state_dict()
        sd["state"] = {i: {"momentum_buffer": torch.full_like(p, 2.5)}
                       for i, p in enumerate(
                           sd["param_groups"][0]["params"]
                           and [p for g in opt.param_groups
                                for p in g["params"]])}
        opt.load_state_dict(sd)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    params_before = [p.detach().clone() for p in model.parameters()]
    hvd.broadcast_optimizer_state(opt, root_rank=0)
    # Params untouched by the bootstrap dummy step (weight_decay != 0).
    for p, want in zip(model.parameters(), params_before):
        assert torch.equal(p, want), "bootstrap moved parameters"
    # Every rank now carries the root's buffers.
    for g in opt.param_groups:
        for p in g["params"]:
            buf = opt.state[p]["momentum_buffer"]
            assert torch.allclose(buf, torch.full_like(buf, 2.5)), buf
    # And hyperparameters were restored after the zeroed dummy step.
    assert opt.param_groups[0]["lr"] == 0.1
    assert opt.param_groups[0]["weight_decay"] == 0.1
    # The job still trains (no stranded handles from the bootstrap).
    out = model(torch.ones(2, 4)).sum()
    out.backward()
    opt.step()
