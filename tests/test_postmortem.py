"""Postmortem-plane tests (docs/troubleshooting.md#reading-a-postmortem):
the always-on flight recorder, crash/hang dump files, the coordinator's
cross-rank stall diagnosis, the rank-0 /cluster aggregation, serving
request traces, and the rendering/lint tooling — the ISSUE-8 acceptance
paths, CPU-only with tight timeouts so the tier-1 budget holds.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env(**overrides):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.setdefault("HVD_TPU_KILL_GRACE_SEC", "3")
    env.update({k: str(v) for k, v in overrides.items()})
    for var in ("HVD_TPU_RANK", "HVD_TPU_SIZE", "HVD_TPU_COORD",
                "HVD_TPU_DATA", "HVD_TPU_FAULT_SPEC",
                "HVD_TPU_RESTART_EPOCH", "HVD_TPU_POSTMORTEM_DIR",
                "HVD_TPU_MONITOR_PORT"):
        env.setdefault(var, "")
        if not env[var]:
            env.pop(var, None)
    return env


# ---------------------------------------------------------------------------
# Flight recorder (in-process units + single-process engine ring).
# ---------------------------------------------------------------------------


def test_flight_ring_bounded_and_ordered():
    from horovod_tpu.common.postmortem import FlightRing

    ring = FlightRing(capacity=4)
    for i in range(10):
        ring.record("enqueue", f"t{i}", i)
    events = ring.drain()
    assert len(events) == 4          # bounded
    assert ring.total == 10          # cumulative survives the wrap
    assert [e["name"] for e in events] == ["t6", "t7", "t8", "t9"]
    assert [e["seq"] for e in events] == [6, 7, 8, 9]  # oldest first
    # ts_us is epoch-anchored and monotone.
    ts = [e["ts_us"] for e in events]
    assert ts == sorted(ts)
    disabled = FlightRing(capacity=0)
    disabled.record("enqueue", "x")
    assert not disabled.enabled and disabled.drain() == []


def test_parse_engine_ring():
    from horovod_tpu.common.postmortem import parse_engine_ring

    raw = "0|100|enqueue|grad_37|5;1|200|execute|grad_37|2;bad;x|y"
    events = parse_engine_ring(raw)
    assert events == [
        {"seq": 0, "ts_us": 100, "event": "enqueue", "name": "grad_37",
         "arg": 5},
        {"seq": 1, "ts_us": 200, "event": "execute", "name": "grad_37",
         "arg": 2},
    ]
    assert parse_engine_ring("") == []


def test_engine_flight_recorder_records(single_process_hvd):
    """The C++ ring records the control-plane story of a collective
    (enqueue -> announce -> execute -> tick) and the metrics snapshot's
    `flight` section mirrors the cumulative counts."""
    hvd = single_process_hvd
    from horovod_tpu import common
    from horovod_tpu.common import postmortem

    for i in range(3):
        hvd.allreduce(np.ones(4, np.float32), name=f"fl.{i}")
    events = postmortem.parse_engine_ring(
        common._lib.hvd_tpu_flight_dump().decode())
    kinds = [e["event"] for e in events]
    for expected in ("enqueue", "announce", "execute", "tick"):
        assert expected in kinds, kinds
    names = {e["name"] for e in events if e["event"] == "enqueue"}
    assert {"fl.0", "fl.1", "fl.2"} <= names, names
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs)
    snap = hvd.metrics_snapshot()
    assert snap["flight"]["events"]["engine"] >= len(events)
    assert snap["flight"]["capacity"] == 512


# ---------------------------------------------------------------------------
# Crash postmortems: every rank (crasher included) leaves a parseable dump
# whose ring / pending table / membership epoch agree across survivors.
# ---------------------------------------------------------------------------


def test_crash_postmortem_dumps(tmp_path):
    from horovod_tpu.runner import run_command

    pm = str(tmp_path / "pm")
    code = (
        "import numpy as np, horovod_tpu as hvd\n"
        "from horovod_tpu.common import RanksDownError\n"
        "hvd.init()\n"
        "try:\n"
        "    for i in range(6):\n"
        "        hvd.allreduce(np.ones(8, np.float32), name=f'step.{i}')\n"
        "    raise SystemExit(9)\n"
        "except RanksDownError:\n"
        "    raise SystemExit(0)\n"
    )
    metrics_file = str(tmp_path / "m.json")
    results = run_command(
        [sys.executable, "-c", code], 4,
        env=_env(HVD_TPU_FAULT_SPEC="rank=1:crash@op=3",
                 HVD_TPU_COLLECTIVE_TIMEOUT_SEC="20",
                 HVD_TPU_POSTMORTEM_DIR=pm,
                 HVD_TPU_METRICS_FILE=metrics_file),
        timeout=90.0, capture=True)
    by_rank = {r.rank: r for r in results}
    for r in (0, 2, 3):
        assert by_rank[r].returncode == 0, \
            (r, by_rank[r].returncode, by_rank[r].stderr[-800:])
    dumps = {}
    for r in range(4):
        path = os.path.join(pm, f"rank-{r}.json")
        assert os.path.exists(path), (r, os.listdir(pm))
        with open(path) as f:
            dumps[r] = json.load(f)  # must parse
    # The crasher dumped through the fault hook, before its hard exit.
    assert dumps[1]["reason"] == "fault_crash"
    crasher_ring = [e["name"] for e in dumps[1]["ring"]["engine"]]
    assert "step.2" in crasher_ring, crasher_ring[-10:]
    for r in (0, 2, 3):
        d = dumps[r]
        assert d["reason"] == "ranks_down", d["reason"]
        assert d["rank"] == r and d["size"] == 4
        assert d["membership_epoch"] == dumps[0]["membership_epoch"]
        # The pending table names the collective the dead rank stranded.
        pending = [p["name"] for p in d["pending"]["local"]]
        assert "step.3" in pending, (r, d["pending"])
        ring_names = [e["name"] for e in d["ring"]["engine"]]
        assert "step.3" in ring_names
        assert d["abort"]["code"] == 6  # ST_RANKS_DOWN
        # The diagnosis (broadcast in the abort message) names rank 1.
        assert d["diagnosis"] and "rank 1" in d["diagnosis"], d["diagnosis"]
    # Rank 0's dump carries the coordinator's waiting-on view.
    coord = dumps[0]["pending"]["coordinator"]
    assert any(p["name"] == "step.3" and 1 in p["missing_ranks"]
               for p in coord), coord
    # Satellite: crashed ranks leave their HVD_TPU_METRICS_FILE dump too
    # (os._exit skips atexit — the fault hook flushes it explicitly).
    for r in range(4):
        path = f"{metrics_file}.{r}"
        assert os.path.exists(path), (r, os.listdir(str(tmp_path)))
        with open(path) as f:
            snap = json.load(f)
        assert "flight" in snap and "ops" in snap


# ---------------------------------------------------------------------------
# Hang postmortems: the coordinator's cross-rank diagnosis names the
# stalled tensor and the wedged rank (the ISSUE acceptance path:
# rank=2:hang@op=12 on a 4-rank job).
# ---------------------------------------------------------------------------


@pytest.mark.slow  # ~20s; postmortem dump + diagnosis coverage stays
# tier-1 in test_crash_postmortem_dumps and
# test_postmortem_dump_tool_renders_story
def test_hang_postmortem_cross_rank_diagnosis(tmp_path):
    from horovod_tpu.runner import run_command

    pm = str(tmp_path / "pm")
    code = (
        "import numpy as np, os, horovod_tpu as hvd\n"
        "from horovod_tpu.common import CollectiveTimeoutError\n"
        "hvd.init()\n"
        "try:\n"
        "    for i in range(13):\n"
        "        hvd.allreduce(np.ones(8, np.float32), name=f'step.{i}')\n"
        "    os._exit(9)\n"
        "except CollectiveTimeoutError as e:\n"
        "    assert 'step.12' in str(e), str(e)\n"
        "    assert 'missing ranks: 2' in str(e), str(e)\n"
        "    os._exit(7)  # nonzero: arm the grace-kill of the wedged rank\n"
    )
    results = run_command(
        [sys.executable, "-c", code], 4,
        env=_env(HVD_TPU_FAULT_SPEC="rank=2:hang@op=12",
                 HVD_TPU_COLLECTIVE_TIMEOUT_SEC="2",
                 HVD_TPU_POSTMORTEM_DIR=pm),
        timeout=90.0, capture=True)
    by_rank = {r.rank: r for r in results}
    for r in (0, 1, 3):
        assert by_rank[r].returncode == 7, \
            (r, by_rank[r].returncode, by_rank[r].stderr[-800:])
    assert by_rank[2].returncode == -9  # grace-killed wedged rank
    # The coordinator printed the one-paragraph diagnosis on stderr.
    assert "cross-rank diagnosis" in by_rank[0].stderr, \
        by_rank[0].stderr[-1500:]
    # Survivors' dumps: timeout reason, the diagnosis naming tensor+rank.
    for r in (0, 1, 3):
        path = os.path.join(pm, f"rank-{r}.json")
        assert os.path.exists(path), (r, os.listdir(pm))
        with open(path) as f:
            d = json.load(f)
        assert d["reason"] == "timeout"
        assert d["abort"]["code"] == 7  # ST_TIMEOUT
        diag = d["diagnosis"]
        assert diag and "rank 2" in diag, diag
        # The wedged rank DID announce earlier steps; the diagnosis says
        # where it stopped.
        assert "last announced" in diag, diag
        assert "step.12" in d["abort"]["message"], d["abort"]["message"]
    with open(os.path.join(pm, "rank-0.json")) as f:
        coord = json.load(f)["pending"]["coordinator"]
    assert any(p["name"] == "step.12" and p["missing_ranks"] == [2]
               for p in coord), coord
    # The failure report points at the dump and repeats the diagnosis.
    from horovod_tpu.runner.launch import failure_report

    report = failure_report(results, postmortem_dir=pm)
    assert "rank-2.json" not in report  # the wedged rank never dumped
    assert "postmortem: " in report and "rank-" in report, report
    assert "cross-rank diagnosis: " in report, report


# ---------------------------------------------------------------------------
# /cluster aggregation: one merged job document from rank 0's monitor.
# ---------------------------------------------------------------------------


def test_cluster_endpoint_merges_all_ranks():
    from horovod_tpu.common.basics import pick_free_port
    from horovod_tpu.runner import run_command

    base_port = pick_free_port("127.0.0.1")
    code = (
        "import json, urllib.request, numpy as np, horovod_tpu as hvd\n"
        "hvd.init()\n"
        "for i in range(3):\n"
        "    hvd.allreduce(np.ones(8, np.float32), name=f'step.{i}')\n"
        "if hvd.rank() == 0:\n"
        f"    url = 'http://127.0.0.1:{base_port}/cluster'\n"
        "    doc = json.load(urllib.request.urlopen(url, timeout=10))\n"
        "    assert doc['launched'] == 4 and doc['live'] == 4, doc\n"
        "    assert sorted(doc['ranks']) == ['0', '1', '2', '3'], doc\n"
        "    epochs = {r['membership_epoch']\n"
        "              for r in doc['ranks'].values()}\n"
        "    assert doc['membership_epochs_agree'] and epochs == {0}, doc\n"
        "    assert all(r['live'] for r in doc['ranks'].values()), doc\n"
        "    prom = urllib.request.urlopen(\n"
        f"        'http://127.0.0.1:{base_port}/cluster.prom',\n"
        "        timeout=10).read().decode()\n"
        "    assert 'hvd_tpu_cluster_ranks_live 4' in prom, prom\n"
        "# Barrier: workers keep their monitors up until rank 0 scraped.\n"
        "hvd.allreduce(np.ones(1, np.float32), name='cluster.barrier')\n"
        "hvd.shutdown()\n"
    )
    results = run_command(
        [sys.executable, "-c", code], 4,
        env=_env(HVD_TPU_MONITOR_PORT=str(base_port)),
        timeout=90.0, capture=True)
    for r in results:
        assert r.returncode == 0, (r.rank, r.stderr[-1200:])


# ---------------------------------------------------------------------------
# Serving request traces: ordered spans via the scheduler and the HTTP
# /v1/trace route.
# ---------------------------------------------------------------------------


def _drive_to_done(sch, req, max_batch, sampled_token=7, max_steps=64):
    steps = 0
    while req.state not in ("done", "failed") and steps < max_steps:
        plan = sch.step_plan()
        assert plan is not None, req.state
        sch.complete_step(plan, [sampled_token] * max_batch)
        steps += 1
    assert req.state == "done", req.state


def test_serving_trace_ordered_spans():
    from horovod_tpu.serving.scheduler import Scheduler, ServeConfig

    cfg = ServeConfig(max_batch=2, prefill_chunk=4, block_tokens=4,
                      num_blocks=16, max_blocks_per_seq=4, eos_id=-1)
    sch = Scheduler(cfg)
    req = sch.submit("acme", [1, 2, 3, 4, 5, 6], max_new_tokens=3)
    _drive_to_done(sch, req, cfg.max_batch)
    trace = sch.trace(req.id)
    assert trace is not None and trace["state"] == "done"
    events = [s["event"] for s in trace["spans"]]
    assert events[0] == "submitted" and events[-1] == "retired"
    # Lifecycle order: admitted before activated before the first
    # prefill chunk before the first decode step.
    for earlier, later in (("submitted", "admitted"),
                           ("admitted", "activated"),
                           ("activated", "prefill_chunk"),
                           ("prefill_chunk", "decode_step"),
                           ("decode_step", "retired")):
        assert events.index(earlier) < events.index(later), events
    t_ms = [s["t_ms"] for s in trace["spans"]]
    assert t_ms == sorted(t_ms)
    assert trace["spans"][-1]["generated"] == 3
    # Unknown ids are None (the route 404s).
    assert sch.trace(99999) is None


def test_serving_trace_http_route():
    from horovod_tpu.serving import server as _server
    from horovod_tpu.serving.scheduler import Scheduler, ServeConfig
    import urllib.error
    import urllib.request

    cfg = ServeConfig(max_batch=2, prefill_chunk=4, block_tokens=4,
                      num_blocks=16, max_blocks_per_seq=4, eos_id=-1,
                      port=0)
    sch = Scheduler(cfg)
    _server.stop_server()  # isolate from any earlier test's singleton
    port = _server.start_server(sch, cfg)
    try:
        req = sch.submit("acme", [1, 2, 3], max_new_tokens=2)
        _drive_to_done(sch, req, cfg.max_batch)
        url = f"http://127.0.0.1:{port}/v1/trace?id={req.id}"
        doc = json.load(urllib.request.urlopen(url, timeout=10))
        assert doc["id"] == req.id
        events = [s["event"] for s in doc["spans"]]
        assert events[0] == "submitted" and events[-1] == "retired"
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/trace?id=424242", timeout=10)
        assert err.value.code == 404
    finally:
        _server.stop_server()


def test_failed_requests_keep_their_trace():
    from horovod_tpu.serving.scheduler import Scheduler, ServeConfig

    cfg = ServeConfig(max_batch=2, prefill_chunk=4, block_tokens=4,
                      num_blocks=16, max_blocks_per_seq=4)
    sch = Scheduler(cfg)
    req = sch.submit("acme", [1, 2, 3], max_new_tokens=2)
    sch.fail_all(RuntimeError("boom"))
    trace = sch.trace(req.id)
    assert trace is not None and trace["state"] == "failed"
    assert trace["spans"][-1]["event"] == "failed"
    assert "boom" in trace["spans"][-1]["error"]


# ---------------------------------------------------------------------------
# Tooling: postmortem_dump.py rendering, failure_report pointers, and the
# extended check_metric_names section lint.
# ---------------------------------------------------------------------------


def _fake_dump(rank, reason, diagnosis=None, epoch=0, size=3):
    return {
        "schema": 1, "rank": rank, "size": size, "restart_epoch": 0,
        "membership_epoch": epoch, "reason": reason,
        "abort": {"code": 7, "message": "collective timeout ..."},
        "diagnosis": diagnosis,
        "ring": {"engine": [
            {"seq": 0, "ts_us": 1000, "event": "enqueue",
             "name": "grad_37", "arg": 0},
            {"seq": 1, "ts_us": 2000, "event": "announce",
             "name": "grad_37", "arg": 0},
        ], "xla": []},
        "pending": {
            "local": [{"name": "grad_37", "op": "allreduce",
                       "age_sec": 2.5}],
            "coordinator": ([{"name": "grad_37", "age_sec": 2.5,
                              "missing_ranks": [2]}] if rank == 0 else []),
        },
        "autotune": {}, "metrics": {}, "written_unix": time.time(),
    }


def test_postmortem_dump_tool_renders_story(tmp_path, capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import postmortem_dump

    d = str(tmp_path)
    diag = ("the coordinator is at tick 1841; rank 2 last announced "
            "'step.11' at tick 1803 and stopped announcing after that")
    for rank in (0, 1):
        with open(os.path.join(d, f"rank-{rank}.json"), "w") as f:
            json.dump(_fake_dump(rank, "timeout", diagnosis=diag), f)
    assert postmortem_dump.main([d]) == 0
    out = capsys.readouterr().out
    assert "2 dump(s)" in out
    assert "cross-rank diagnosis:" in out and "rank 2" in out
    assert "'grad_37' stalled 2.5s, waiting on ranks [2]" in out
    assert "no dump from rank(s) [2]" in out
    assert "grad_37" in out and "enqueue" in out
    # Empty dir: distinct failure.
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    assert postmortem_dump.main([empty]) == 1


def test_failure_report_postmortem_pointers(tmp_path):
    from horovod_tpu.runner.launch import RankResult, failure_report

    d = str(tmp_path)
    diag = "rank 1 never announced any collective"
    with open(os.path.join(d, "rank-0.json"), "w") as f:
        json.dump(_fake_dump(0, "ranks_down", diagnosis=diag), f)
    results = [RankResult(0, 1, "", "boom", first_failure=True),
               RankResult(1, -9, "", "")]
    report = failure_report(results, postmortem_dir=d)
    assert os.path.join(d, "rank-0.json") in report, report
    assert f"cross-rank diagnosis: {diag}" in report, report
    # Without a dir (and no env), no postmortem lines appear.
    plain = failure_report(results, postmortem_dir="")
    if "HVD_TPU_POSTMORTEM_DIR" not in os.environ:
        assert "postmortem" not in plain


def test_check_metric_names_section_lint():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import check_metric_names as lint_tool
    from horovod_tpu.common import metrics

    snapshot = lint_tool.populated_registry().snapshot()
    text = metrics.prometheus_text(snapshot)
    doc = lint_tool._metrics_doc_text()
    assert lint_tool.lint(text) == []
    assert lint_tool.lint_sections(snapshot, text, doc) == []
    # A new snapshot section with no declared family is caught ...
    bad = dict(snapshot, mystery={"x": 1})
    errors = lint_tool.lint_sections(bad, text, doc)
    assert any("mystery" in e for e in errors), errors
    # ... and so is a declared family missing from the exposition.
    pruned = "\n".join(l for l in text.splitlines()
                       if "hvd_tpu_flight" not in l)
    errors = lint_tool.lint_sections(snapshot, pruned, doc)
    assert any("hvd_tpu_flight_events_total" in e for e in errors), errors


def test_postmortem_written_on_fatal_exception(tmp_path):
    """The excepthook path: a fatal uncaught exception on an initialized
    rank leaves a dump with reason 'exception'."""
    import subprocess

    pm = str(tmp_path / "pm")
    code = (
        "import horovod_tpu as hvd\n"
        "hvd.init()\n"
        "raise RuntimeError('driver blew up')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=_env(HVD_TPU_POSTMORTEM_DIR=pm), capture_output=True,
        text=True, timeout=60)
    assert proc.returncode != 0
    path = os.path.join(pm, "rank-0.json")
    assert os.path.exists(path), (proc.stderr[-800:], os.listdir(pm)
                                  if os.path.isdir(pm) else "no dir")
    with open(path) as f:
        d = json.load(f)
    assert d["reason"] == "exception"
    assert d["exception"]["type"] == "RuntimeError"
    assert "driver blew up" in d["exception"]["message"]
