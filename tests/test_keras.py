"""Keras binding tests.

Mirrors the reference Keras suite (/root/reference/test/test_keras.py):
load_model round-trips with stock and custom optimizers, plus distributed
training equivalence and the callback set.
"""

import os

import numpy as np
import pytest

from tests.distributed import distributed_test


def _init():
    import horovod_tpu.keras as hvd

    hvd.init()
    return hvd


@pytest.mark.slow  # ~16s; the distributed-keras seam stays tier-1 in
# test_keras_callbacks_broadcast_and_metric_average, the optimizer math
# in test_keras_momentum_correction
@distributed_test(np_=2, timeout=400)
def test_keras_distributed_optimizer_sync():
    import keras

    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    keras.utils.set_random_seed(42)  # identical init on all ranks

    model = keras.Sequential([keras.layers.Input((4,)),
                              keras.layers.Dense(1)])
    opt = hvd.DistributedOptimizer(keras.optimizers.SGD(learning_rate=0.1))
    assert isinstance(opt, keras.optimizers.SGD)
    assert opt.__class__.__name__ == "SGD"
    model.compile(optimizer=opt, loss="mse")

    x = np.random.RandomState(r).randn(8, 4).astype(np.float32)
    y = np.random.RandomState(100 + r).randn(8, 1).astype(np.float32)
    model.fit(x, y, batch_size=8, epochs=2, verbose=0)

    # Averaged gradients => identical weights on every rank despite
    # different local data.
    w = model.get_weights()[0].reshape(1, -1)
    gathered = hvd.allgather(w, name="k.sync")
    for i in range(n):
        assert np.allclose(gathered[i], gathered[0], atol=1e-6), r


@distributed_test(np_=2, timeout=400)
def test_keras_callbacks_broadcast_and_metric_average():
    import keras

    hvd = _init()
    r, n = hvd.rank(), hvd.size()
    keras.utils.set_random_seed(1000 + r)  # different init per rank

    model = keras.Sequential([keras.layers.Input((3,)),
                              keras.layers.Dense(2)])
    model.compile(optimizer=hvd.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.01)), loss="mse")

    from horovod_tpu.keras.callbacks import (BroadcastGlobalVariablesCallback,
                                             MetricAverageCallback)

    x = np.random.RandomState(r).randn(4, 3).astype(np.float32)
    y = np.random.RandomState(r).randn(4, 2).astype(np.float32)
    history = model.fit(
        x, y, batch_size=4, epochs=1, verbose=0,
        callbacks=[BroadcastGlobalVariablesCallback(0),
                   MetricAverageCallback()])

    # Metric averaging: every rank reports the same (averaged) loss.
    loss = np.asarray(history.history["loss"][-1]).reshape(1)
    gathered = hvd.allgather(loss, name="k.metric")
    assert np.allclose(gathered, gathered[0], atol=1e-6), r


@pytest.mark.slow  # ~16s; the keras callback machinery stays tier-1 in
# test_keras_callbacks_broadcast_and_metric_average
@distributed_test(np_=2, timeout=400)
def test_keras_lr_warmup():
    import keras

    hvd = _init()
    n = hvd.size()
    keras.utils.set_random_seed(0)
    model = keras.Sequential([keras.layers.Input((2,)),
                              keras.layers.Dense(1)])
    base_lr = 0.1 * n  # the reference recipe: scale LR by size
    model.compile(optimizer=hvd.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=base_lr)), loss="mse")

    from horovod_tpu.keras.callbacks import LearningRateWarmupCallback

    warmup = LearningRateWarmupCallback(warmup_epochs=2, steps_per_epoch=2)
    x = np.random.RandomState(0).randn(8, 2).astype(np.float32)
    y = np.random.RandomState(1).randn(8, 1).astype(np.float32)
    history = model.fit(x, y, batch_size=4, epochs=3, verbose=0,
                        callbacks=[warmup])
    lrs = history.history["lr"]
    # During warmup the LR is below base; by the end it reaches base.
    assert lrs[0] < base_lr
    assert np.isclose(lrs[-1], base_lr, rtol=1e-5), lrs


def test_keras_load_model_roundtrip(tmp_path, single_process_hvd):
    import keras

    import horovod_tpu.keras as hvd_keras

    keras.utils.set_random_seed(3)
    model = keras.Sequential([keras.layers.Input((4,)),
                              keras.layers.Dense(2)])
    opt = hvd_keras.DistributedOptimizer(
        keras.optimizers.Adam(learning_rate=0.003))
    model.compile(optimizer=opt, loss="mse")
    x = np.random.randn(8, 4).astype(np.float32)
    y = np.random.randn(8, 2).astype(np.float32)
    model.fit(x, y, epochs=1, verbose=0)

    path = str(tmp_path / "model.keras")
    model.save(path)

    loaded = hvd_keras.load_model(path)
    assert loaded.optimizer.__class__.__name__ == "Adam"
    assert float(keras.ops.convert_to_numpy(
        loaded.optimizer.learning_rate)) == pytest.approx(0.003)
    for a, b in zip(model.get_weights(), loaded.get_weights()):
        assert np.allclose(a, b)
    # Wrapped optimizer still trains after reload.
    loaded.fit(x, y, epochs=1, verbose=0)


def test_keras_resume_recognizes_sharded_checkpoints(tmp_path,
                                                     monkeypatch,
                                                     single_process_hvd):
    """BroadcastGlobalVariablesCallback(checkpoint_dir=) resumes from a
    jax.train sharded checkpoint carrying a model.get_weights() list —
    the format an elastic job leaves when it falls below --min-np and
    --max-restarts relaunches (docs/fault-tolerance.md#state-plane)."""
    import keras

    from horovod_tpu.jax.train import save_checkpoint
    from horovod_tpu.keras.callbacks import (BroadcastGlobalVariablesCallback,
                                             _latest_resume_source)

    keras.utils.set_random_seed(7)
    model = keras.Sequential([keras.layers.Input((3,)),
                              keras.layers.Dense(2)])
    model.compile(optimizer=keras.optimizers.SGD(0.01), loss="mse")
    saved = [np.asarray(w) + 1.5 for w in model.get_weights()]
    save_checkpoint(str(tmp_path), 6, {"weights": saved}, sharded=True)
    # An OLDER .weights.h5 must lose to the newer sharded checkpoint.
    model.save_weights(str(tmp_path / "ckpt-2.weights.h5"))
    kind, path = _latest_resume_source(str(tmp_path))
    assert kind == "checkpoint" and path.endswith("ckpt-00000006"), \
        (kind, path)

    monkeypatch.setenv("HVD_TPU_RESTART_EPOCH", "1")
    cb = BroadcastGlobalVariablesCallback(0, checkpoint_dir=str(tmp_path))
    cb.set_model(model)
    x = np.random.randn(4, 3).astype(np.float32)
    y = np.random.randn(4, 2).astype(np.float32)
    cb.on_train_begin()
    assert cb.resumed_from is not None and "ckpt-00000006" in cb.resumed_from
    for got, want in zip(model.get_weights(), saved):
        assert np.allclose(got, want)
    model.fit(x, y, epochs=1, verbose=0)  # still trainable after resume


def test_keras_momentum_correction(single_process_hvd):
    import keras

    from horovod_tpu.keras.callbacks import LearningRateScheduleCallback

    keras.utils.set_random_seed(0)
    model = keras.Sequential([keras.layers.Input((2,)),
                              keras.layers.Dense(1)])
    opt = keras.optimizers.SGD(learning_rate=0.1, momentum=0.9)
    model.compile(optimizer=opt, loss="mse")
    x = np.random.randn(4, 2).astype(np.float32)
    y = np.random.randn(4, 1).astype(np.float32)
    model.fit(x, y, epochs=1, verbose=0)  # build momentum buffers

    before = [np.asarray(keras.ops.convert_to_numpy(m)).copy()
              for m in opt.momentums]
    cb = LearningRateScheduleCallback(multiplier=0.5, momentum_correction=True)
    cb.set_model(model)
    cb.on_train_begin()
    cb.on_epoch_begin(0)
    after = [np.asarray(keras.ops.convert_to_numpy(m)) for m in opt.momentums]
    assert float(keras.ops.convert_to_numpy(opt.learning_rate)) == \
        pytest.approx(0.05)
    for b, a in zip(before, after):
        if np.abs(b).max() > 0:
            # lr halved => buffers doubled (old_lr/new_lr = 2).
            assert np.allclose(a, b * 2.0, rtol=1e-5)
